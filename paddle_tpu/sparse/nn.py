"""sparse.nn — layers + functionals over SparseCooTensor/SparseCsrTensor.

TPU-native equivalent of the reference's sparse nn (reference:
python/paddle/sparse/nn/ — layer/conv.py Conv3D:239 SubmConv3D:509,
layer/activation.py ReLU/ReLU6/LeakyReLU/Softmax, layer/norm.py
BatchNorm, layer/pooling.py MaxPool3D, functional/transformer.py
attention:22; CUDA kernels paddle/phi/kernels/sparse/).

Design: sparse convolution uses the gather-GEMM-scatter formulation
(the same plan the reference's GPU hash-table kernels build): the
kernel-offset -> (input point, output point) pair lists are planned on
host from the COO coordinates (eager sparse tensors carry concrete
indices), then each offset contributes one [pairs, Cin] x [Cin, Cout]
matmul + scatter-add on device — MXU-shaped work, no dense
materialization. Sparse attention keeps the masked-softmax math but
evaluates it dense-masked: on TPU the MXU makes the dense masked form
the fast path; the CSR mask supplies the sparsity pattern and the
result is returned at full precision parity with the reference's
formula softmax(QK^T/sqrt(d) + masks) V over the mask's nnz.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..nn import initializer as I
from . import (SparseCooTensor, SparseCsrTensor, sparse_coo_tensor)

__all__ = [
    "conv3d", "subm_conv3d", "max_pool3d", "attention", "relu", "relu6",
    "leaky_relu", "softmax", "Conv3D", "SubmConv3D", "MaxPool3D",
    "BatchNorm", "ReLU", "ReLU6", "LeakyReLU", "Softmax",
]


def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v, v)


def _conv_plan(coords: np.ndarray, spatial, kernel, stride, padding,
               subm: bool):
    """Host-side gather/scatter plan (the hash-table step of the
    reference's conv3d kernels, phi/kernels/sparse/gpu/conv.cu).

    coords: [nnz, 4] int (batch, d, h, w). Returns (out_coords [m, 4],
    per-offset (gather_idx, scatter_idx) lists)."""
    kd, kh, kw = kernel
    sd, sh, sw = stride
    pd, ph, pw = padding
    D, H, W = spatial

    in_map = {tuple(c): i for i, c in enumerate(coords.tolist())}
    if subm:
        out_map = in_map
        out_coords = coords.copy()
    else:
        out_map = {}
        out_coords_list = []
        # enumerate every output position each input contributes to
        for (b, d, h, w) in coords.tolist():
            for ki in range(kd):
                od, rd = divmod(d + pd - ki, sd)
                if rd or od < 0 or od > (D + 2 * pd - kd) // sd:
                    continue
                for kj in range(kh):
                    oh, rh = divmod(h + ph - kj, sh)
                    if rh or oh < 0 or oh > (H + 2 * ph - kh) // sh:
                        continue
                    for kk in range(kw):
                        ow, rw = divmod(w + pw - kk, sw)
                        if rw or ow < 0 or ow > (W + 2 * pw - kw) // sw:
                            continue
                        key = (b, od, oh, ow)
                        if key not in out_map:
                            out_map[key] = len(out_coords_list)
                            out_coords_list.append(key)
        out_coords = np.array(out_coords_list, np.int64).reshape(-1, 4)

    pairs = []  # per kernel offset: (in_idx list, out_idx list)
    for ki in range(kd):
        for kj in range(kh):
            for kk in range(kw):
                gi, si = [], []
                for idx, (b, d, h, w) in enumerate(coords.tolist()):
                    od, rd = divmod(d + pd - ki, sd)
                    oh, rh = divmod(h + ph - kj, sh)
                    ow, rw = divmod(w + pw - kk, sw)
                    if rd or rh or rw:
                        continue
                    key = (b, od, oh, ow)
                    o = out_map.get(key)
                    if o is not None:
                        gi.append(idx)
                        si.append(o)
                pairs.append((np.array(gi, np.int32),
                              np.array(si, np.int32)))
    return out_coords, pairs


def _sparse_conv(x: SparseCooTensor, weight, bias, stride, padding,
                 subm: bool):
    """x: SparseCooTensor [N, D, H, W, C]; weight [kd, kh, kw, Cin, Cout]."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse conv expects a SparseCooTensor input")
    w = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    kd, kh, kw, cin, cout = w.shape
    N, D, H, W, C = x.shape
    assert C == cin, f"channel mismatch {C} vs {cin}"
    coords = np.asarray(x._bcoo.indices)[:, :4]
    values = x._bcoo.data
    stride, padding = _triple(stride), _triple(padding)
    out_coords, pairs = _conv_plan(coords, (D, H, W), (kd, kh, kw),
                                   stride, padding, subm)
    m = len(out_coords)
    out_vals = jnp.zeros((m, cout), values.dtype)
    w_flat = w.reshape(kd * kh * kw, cin, cout)
    for off, (gi, si) in enumerate(pairs):
        if len(gi) == 0:
            continue
        contrib = values[jnp.asarray(gi)] @ w_flat[off]
        out_vals = out_vals.at[jnp.asarray(si)].add(contrib)
    if bias is not None:
        b = bias._data if isinstance(bias, Tensor) else jnp.asarray(bias)
        out_vals = out_vals + b
    od = (D + 2 * padding[0] - kd) // stride[0] + 1
    oh = (H + 2 * padding[1] - kh) // stride[1] + 1
    ow = (W + 2 * padding[2] - kw) // stride[2] + 1
    if subm:
        od, oh, ow = D, H, W
    return sparse_coo_tensor(out_coords.T, out_vals,
                             shape=[N, od, oh, ow, cout])


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """(reference functional/conv.py:199) Sparse 3-D convolution over a
    SparseCooTensor [N, D, H, W, C]."""
    if _triple(dilation) != (1, 1, 1) or groups != 1:
        raise NotImplementedError("sparse conv3d: dilation/groups > 1")
    return _sparse_conv(x, weight, bias, stride, padding, subm=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """(reference functional/conv.py:305) Submanifold conv: output
    sparsity pattern == input pattern (no dilation of the active set)."""
    if _triple(dilation) != (1, 1, 1) or groups != 1:
        raise NotImplementedError("sparse subm_conv3d: dilation/groups")
    return _sparse_conv(x, weight, bias, stride, padding, subm=True)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """(reference functional/pooling.py:22) Max pool over active sites."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse max_pool3d expects SparseCooTensor")
    kernel = _triple(kernel_size)
    stride = _triple(stride if stride is not None else kernel_size)
    padding = _triple(padding)
    N, D, H, W, C = x.shape
    coords = np.asarray(x._bcoo.indices)[:, :4]
    values = x._bcoo.data
    out_coords, pairs = _conv_plan(coords, (D, H, W), kernel, stride,
                                   padding, subm=False)
    m = len(out_coords)
    out_vals = jnp.full((m, C), -jnp.inf, values.dtype)
    for gi, si in pairs:
        if len(gi) == 0:
            continue
        out_vals = out_vals.at[jnp.asarray(si)].max(
            values[jnp.asarray(gi)])
    od = (D + 2 * padding[0] - kernel[0]) // stride[0] + 1
    oh = (H + 2 * padding[1] - kernel[1]) // stride[1] + 1
    ow = (W + 2 * padding[2] - kernel[2]) // stride[2] + 1
    return sparse_coo_tensor(out_coords.T, out_vals,
                             shape=[N, od, oh, ow, C])


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """(reference functional/transformer.py:22) softmax(QK^T/sqrt(d))V
    restricted to the CSR ``sparse_mask`` pattern. q/k/v:
    [batch, heads, seq, head_dim]; sparse_mask dense shape
    [batch*heads, seq, seq]."""
    q = query._data if isinstance(query, Tensor) else jnp.asarray(query)
    k = key._data if isinstance(key, Tensor) else jnp.asarray(key)
    v = value._data if isinstance(value, Tensor) else jnp.asarray(value)
    b, h, s, d = q.shape
    if not isinstance(sparse_mask, SparseCsrTensor):
        raise TypeError("sparse_mask must be a SparseCsrTensor")
    # batched CSR [b*h, s, s]: per-batch crows segments of length s+1,
    # per-batch column indices, values concatenated (phi batched-CSR
    # layout)
    crows = np.asarray(sparse_mask._crows)
    cols = np.asarray(sparse_mask._cols)
    nb = b * h
    mask_np = np.zeros((nb, s, s), bool)
    val_base = 0
    for bi in range(nb):
        cr = crows[bi * (s + 1):(bi + 1) * (s + 1)] if crows.size \
            >= nb * (s + 1) else crows
        for r in range(s):
            lo, hi = int(cr[r]), int(cr[r + 1])
            mask_np[bi, r, cols[val_base + lo: val_base + hi]] = True
        val_base += int(cr[-1])
    mask = jnp.asarray(mask_np).reshape(b, h, s, s)
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k) / jnp.sqrt(
        jnp.asarray(d, q.dtype))
    neg = jnp.asarray(-1e30, scores.dtype)
    scores = jnp.where(mask, scores, neg)
    if attn_mask is not None:
        am = attn_mask._data if isinstance(attn_mask, Tensor) \
            else jnp.asarray(attn_mask)
        scores = scores + am[None, None]
    if key_padding_mask is not None:
        kp = key_padding_mask._data if isinstance(key_padding_mask,
                                                  Tensor) \
            else jnp.asarray(key_padding_mask)
        scores = scores + kp[:, None, None, :]
    w = jax.nn.softmax(scores, axis=-1)
    w = jnp.where(mask, w, 0.0)  # rows fully masked stay zero
    return Tensor(jnp.einsum("bhst,bhtd->bhsd", w, v))


# ---------------- value-wise activations ----------------

def _valuewise(x, fn):
    from . import SparseCooTensor as Coo, SparseCsrTensor as Csr
    import jax.experimental.sparse as jsparse

    if isinstance(x, Coo):
        return Coo(jsparse.BCOO((fn(x._bcoo.data), x._bcoo.indices),
                                shape=x._bcoo.shape))
    if isinstance(x, Csr):
        return Csr(x._crows, x._cols, fn(x._values), x._shape)
    return Tensor(fn(x._data if isinstance(x, Tensor) else jnp.asarray(x)))


def relu(x):
    return _valuewise(x, jax.nn.relu)


def relu6(x):
    return _valuewise(x, lambda a: jnp.clip(a, 0, 6))


def leaky_relu(x, negative_slope=0.01):
    return _valuewise(x, lambda a: jnp.where(a >= 0, a,
                                             negative_slope * a))


def softmax(x, axis=-1):
    """CSR softmax per row over stored values (reference
    layer/activation.py Softmax:66 — axis=-1 only)."""
    if isinstance(x, SparseCsrTensor):
        if axis != -1:
            raise ValueError("sparse softmax only supports axis=-1")
        crows = np.asarray(x._crows)
        vals = x._values
        out = []
        # batched CSR: crows may be [batch*(rows+1)]; normalize to rows
        n_rows = x._shape[-2]
        n_batch = int(np.prod(x._shape[:-2])) if len(x._shape) > 2 else 1
        vals_out = jnp.zeros_like(vals)
        base = 0
        for bi in range(n_batch):
            cr = crows[bi * (n_rows + 1):(bi + 1) * (n_rows + 1)]
            for r in range(n_rows):
                lo, hi = int(cr[r]) + base, int(cr[r + 1]) + base
                if hi > lo:
                    seg = vals[lo:hi]
                    seg = jax.nn.softmax(seg)
                    vals_out = vals_out.at[lo:hi].set(seg)
            base += int(cr[-1])
        return SparseCsrTensor(x._crows, x._cols, vals_out, x._shape)
    raise TypeError("sparse softmax expects a SparseCsrTensor")


# ---------------- Layer classes ----------------

class _ConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__()
        k = _triple(kernel_size)
        self._subm = subm
        self._stride = stride
        self._padding = padding
        self.weight = self.create_parameter(
            shape=[*k, in_channels, out_channels], attr=weight_attr,
            default_initializer=I.XavierUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return _sparse_conv(x, self.weight, self.bias, self._stride,
                            self._padding, self._subm)


class Conv3D(_ConvBase):
    """(reference layer/conv.py:239)"""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, False, padding_mode,
                         weight_attr, bias_attr, data_format)


class SubmConv3D(_ConvBase):
    """(reference layer/conv.py:509)"""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, True, padding_mode,
                         weight_attr, bias_attr, data_format)


class MaxPool3D(Layer):
    """(reference layer/pooling.py:20)"""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._k = kernel_size
        self._s = stride
        self._p = padding

    def forward(self, x):
        return max_pool3d(x, self._k, self._s, self._p)


class BatchNorm(Layer):
    """(reference layer/norm.py:24) BatchNorm over the channel dim of
    the active-site values."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ..nn.layers.norm import BatchNorm1D

        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr)

    def forward(self, x):
        if not isinstance(x, SparseCooTensor):
            raise TypeError("sparse BatchNorm expects SparseCooTensor")
        import jax.experimental.sparse as jsparse

        vals = self._bn(Tensor(x._bcoo.data))
        return SparseCooTensor(jsparse.BCOO(
            (vals._data, x._bcoo.indices), shape=x._bcoo.shape))


class ReLU(Layer):
    def forward(self, x):
        return relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._ns = negative_slope

    def forward(self, x):
        return leaky_relu(x, self._ns)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return softmax(x, self._axis)


class functional:
    """sparse.nn.functional namespace (reference sparse/nn/functional)."""

    conv3d = staticmethod(conv3d)
    subm_conv3d = staticmethod(subm_conv3d)
    max_pool3d = staticmethod(max_pool3d)
    attention = staticmethod(attention)
    relu = staticmethod(relu)
    relu6 = staticmethod(relu6)
    leaky_relu = staticmethod(leaky_relu)
    softmax = staticmethod(softmax)
