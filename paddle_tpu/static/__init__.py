"""paddle_tpu.static — static-graph compat surface.

The reference's static graph (Program/Executor) maps onto traced+compiled
XLA programs here (SURVEY.md §7.0); InputSpec is the shared signature type.
Static-graph user APIs are provided for compat where they have a natural
traced equivalent.
"""
from .input_spec import InputSpec  # noqa: F401

__all__ = ["InputSpec"]
