"""paddle_tpu.static — static-graph compat surface.

The reference's static graph (Program/Executor) maps onto traced+compiled
XLA programs here (SURVEY.md §7.0); InputSpec is the shared signature type.
Static-graph user APIs are provided for compat where they have a natural
traced equivalent.
"""
from . import nn  # noqa: F401
from . import quantization  # noqa: F401
from .input_spec import InputSpec  # noqa: F401
from .program import (  # noqa: F401
    CompiledProgram, Executor, Program, data, default_main_program,
    default_startup_program, load_inference_model, program_guard,
    save_inference_model, scope_guard,
)

__all__ = [
    "nn", "quantization",
    "InputSpec", "Program", "program_guard", "default_main_program",
    "default_startup_program", "data", "Executor", "scope_guard",
    "save_inference_model", "load_inference_model", "CompiledProgram",
]
