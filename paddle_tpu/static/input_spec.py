"""InputSpec (reference: python/paddle/static/input_spec.py)."""
from __future__ import annotations

import numpy as np

from ..core.dtype import convert_dtype

__all__ = ["InputSpec"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size):
        self.shape = (batch_size,) + self.shape
        return self

    def unbatch(self):
        self.shape = self.shape[1:]
        return self

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype.name}, "
                f"name={self.name})")
