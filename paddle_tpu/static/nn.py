"""Data-dependent control flow: ``paddle.static.nn.cond`` /
``while_loop`` / ``case`` / ``switch_case``.

TPU-native equivalent of the reference's static control-flow ops
(reference: python/paddle/static/nn/control_flow.py — while_loop:629,
cond:1126, case/switch_case below them; backed by the
conditional_block/while C++ ops). Here the two execution modes map
naturally:

- **eager**: the predicate is a concrete array — evaluate it and run the
  chosen branch as ordinary eager ops. The autograd tape records the
  executed branch (and each executed loop iteration), so gradients flow
  with no special casing — the same property the reference gets from
  dygraph's Python `if`.
- **traced** (inside ``to_static`` / ``TrainStep`` / ``jit.save``): the
  predicate is a tracer — lower to ``jax.lax.cond`` /
  ``jax.lax.while_loop``, the compiler-friendly forms XLA requires
  (SURVEY §7.0: no data-dependent Python control flow under jit).
  Reverse-mode through a traced ``while_loop`` is undefined in XLA;
  differentiate a bounded loop via ``lax.scan``-style rewrites or run
  the loop eagerly (documented limitation; the reference's while op has
  the analogous grad-block restriction).
"""
from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _is_tracing(*tensors) -> bool:
    return any(isinstance(t._data, jax.core.Tracer) for t in tensors
               if isinstance(t, Tensor))


def _flatten(out):
    """Flatten a branch output pytree into (template, [arrays])."""
    from ..jit.static_function import _flatten_tensors

    tensors: List[Tensor] = []
    tmpl = _flatten_tensors(out, tensors)
    return tmpl, [t._data for t in tensors]


def _unflatten(tmpl, arrays):
    from ..jit.static_function import _unflatten_tensors

    return _unflatten_tensors(tmpl, [Tensor(a) for a in arrays])


def cond(pred, true_fn: Callable, false_fn: Callable, name=None,
         return_names=None):
    """Run ``true_fn()`` when pred else ``false_fn()`` (reference
    control_flow.py:1126). Both branches must return the same
    structure/shapes/dtypes (checked when traced, as the reference's
    static cond requires)."""
    pred = pred if isinstance(pred, Tensor) else Tensor(jnp.asarray(pred))
    if not _is_tracing(pred):
        return true_fn() if bool(pred._data) else false_fn()

    tmpl_box = {}

    def _branch(fn, key):
        def wrapped(_):
            out = fn()
            tmpl, arrays = _flatten(out)
            tmpl_box[key] = (tmpl, [(a.shape, a.dtype) for a in arrays])
            return tuple(arrays)
        return wrapped

    true_w, false_w = _branch(true_fn, "t"), _branch(false_fn, "f")
    # trace both eagerly first so structure mismatches raise a
    # framework error (not a raw jax one)
    out_t = jax.eval_shape(true_w, ())
    out_f = jax.eval_shape(false_w, ())
    sig_t = [(o.shape, o.dtype) for o in out_t]
    sig_f = [(o.shape, o.dtype) for o in out_f]
    if sig_t != sig_f or repr(tmpl_box["t"][0]) != repr(tmpl_box["f"][0]):
        raise ValueError(
            "paddle.static.nn.cond: true_fn and false_fn must return "
            f"the same structure/shapes/dtypes; got {sig_t} vs {sig_f} "
            "(reference control_flow.py:1126 check_output_structure)")
    arrays = jax.lax.cond(pred._data.astype(bool).reshape(()),
                          true_w, false_w, ())
    return _unflatten(tmpl_box["t"][0], list(arrays))


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars,
               is_test=False, name=None):
    """``while cond_fn(*vars): vars = body_fn(*vars)`` (reference
    control_flow.py:629). loop_vars is a list/tuple; body must return
    matching shapes/dtypes. Eager mode supports gradients through the
    unrolled tape; traced mode lowers to ``jax.lax.while_loop``."""
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise TypeError("loop_vars must be a non-empty list/tuple")
    loop_vars = list(loop_vars)
    tensors = [v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))
               for v in loop_vars]

    def _pred(vars_now):
        out = cond_fn(*vars_now)
        return bool(out._data if isinstance(out, Tensor) else out)

    if not _is_tracing(*tensors):
        # eager: Python loop; the tape sees every executed op
        vars_now = tensors
        while _pred(vars_now):
            out = body_fn(*vars_now)
            out = out if isinstance(out, (list, tuple)) else (out,)
            if len(out) != len(vars_now):
                raise ValueError(
                    "body_fn must return as many values as loop_vars "
                    f"({len(vars_now)}), got {len(out)}")
            vars_now = [v if isinstance(v, Tensor)
                        else Tensor(jnp.asarray(v)) for v in out]
        return vars_now

    def cond_w(arrays):
        out = cond_fn(*[Tensor(a) for a in arrays])
        arr = out._data if isinstance(out, Tensor) else jnp.asarray(out)
        return arr.astype(bool).reshape(())

    def body_w(arrays):
        out = body_fn(*[Tensor(a) for a in arrays])
        out = out if isinstance(out, (list, tuple)) else (out,)
        new = [o._data if isinstance(o, Tensor) else jnp.asarray(o)
               for o in out]
        if len(new) != len(arrays):
            raise ValueError(
                "body_fn must return as many values as loop_vars "
                f"({len(arrays)}), got {len(new)}")
        return tuple(a.astype(old.dtype) if a.dtype != old.dtype else a
                     for a, old in zip(new, arrays))

    arrays = jax.lax.while_loop(cond_w, body_w,
                                tuple(t._data for t in tensors))
    return [Tensor(a) for a in arrays]


def case(pred_fn_pairs: Sequence[Tuple], default: Callable = None,
         name=None):
    """First-match-wins branch chain (reference control_flow.py case):
    nested ``cond`` over (pred, fn) pairs."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")

    def build(pairs):
        (pred, fn), rest = pairs[0], pairs[1:]
        if not rest:
            if default is None:
                return fn()
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: build(rest))

    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default: Callable = None,
                name=None):
    """Integer-indexed dispatch (reference control_flow.py
    switch_case). branch_fns: dict {int: fn} or list of (int, fn)."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = sorted((int(i), f) for i, f in branch_fns)
    idx = branch_index if isinstance(branch_index, Tensor) \
        else Tensor(jnp.asarray(branch_index))

    def build(pairs):
        (k, fn), rest = pairs[0], pairs[1:]
        pred = Tensor((idx._data == k).reshape(()))
        if not rest:
            if default is None:
                return fn()
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: build(rest))

    return build(items)
