"""static.Program / Executor — static-graph compat over op recording.

TPU-native equivalent of the reference's static graph stack (reference:
python/paddle/base/framework.py Program/Block; executor.py Executor:1152
+ _ExecutorCache:854 over the C++ StandaloneExecutor,
new_executor/standalone_executor.h:34). The reference builds a
ProgramDesc of op protos and runs it through an instruction interpreter;
here ``program_guard`` records every dispatched op (op name, functional
impl, operand slots) into a Program — the ProgramDesc equivalent — and
``Executor.run`` replays the op list as ONE jitted XLA program per feed
signature (the _ExecutorCache role), with placeholder feeds and fetches.

The op list IS the IR: XLA does the pass pipeline the reference's
interpreter + IR passes do.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dtype import convert_dtype

__all__ = [
    "Program", "program_guard", "default_main_program",
    "default_startup_program", "data", "Executor", "scope_guard",
    "save_inference_model", "load_inference_model", "CompiledProgram",
]


class _OpRecord:
    __slots__ = ("op_name", "raw_fn", "static_kwargs", "in_keys",
                 "out_keys")

    def __init__(self, op_name, raw_fn, static_kwargs, in_keys, out_keys):
        self.op_name = op_name
        self.raw_fn = raw_fn
        self.static_kwargs = static_kwargs
        self.in_keys = in_keys
        self.out_keys = out_keys


class Program:
    """Recorded op-list program (reference: base/framework.py Program;
    C++ ProgramDesc). Variables are slot keys; feeds bind placeholder
    slots, every other external operand is captured by reference at
    record time (parameters update in place between runs, like the
    reference's scope variables)."""

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self._id = Program._counter
        self.ops: List[_OpRecord] = []
        # name -> slot key for placeholders created by static.data
        self.feed_slots: Dict[str, int] = {}
        self.feed_specs: Dict[str, tuple] = {}
        # slot key -> producing Tensor (keeps arrays alive + identity)
        self._slot_of_tensor: Dict[int, int] = {}   # id(Tensor) -> slot
        self._tensor_refs: List[Tensor] = []        # strong refs
        self._captured: Dict[int, Tensor] = {}      # slot -> external in
        self._next_slot = 0
        self._exec_cache: Dict[tuple, Any] = {}

    # ---- recording ----
    def _slot_for(self, t: Tensor, create_external: bool) -> Optional[int]:
        key = self._slot_of_tensor.get(id(t))
        if key is not None:
            return key
        if not create_external:
            return None
        key = self._new_slot()
        self._slot_of_tensor[id(t)] = key
        self._tensor_refs.append(t)
        self._captured[key] = t  # late-bound: read t._data at run time
        return key

    def _new_slot(self) -> int:
        self._next_slot += 1
        return self._next_slot

    def _register_output(self, t: Tensor) -> int:
        key = self._new_slot()
        self._slot_of_tensor[id(t)] = key
        self._tensor_refs.append(t)
        t._static_program = self  # back-pointer for fetch-var resolution
        return key

    def record(self, op_name, raw_fn, static_kwargs, inputs, outputs):
        in_keys = [self._slot_for(t, create_external=True) for t in inputs]
        out_keys = [self._register_output(t) for t in outputs]
        self.ops.append(_OpRecord(op_name, raw_fn, dict(static_kwargs or {}),
                                  in_keys, out_keys))

    def add_placeholder(self, name, shape, dtype) -> Tensor:
        np_dtype = convert_dtype(dtype).np_dtype
        orig_shape = tuple(None if (s is None or (isinstance(s, int)
                                                  and s < 0)) else int(s)
                           for s in shape)
        shape = tuple(1 if s is None else s for s in orig_shape)
        self.feed_orig_shapes = getattr(self, "feed_orig_shapes", {})
        self.feed_orig_shapes[name] = orig_shape
        t = Tensor(jnp.zeros(shape, np_dtype), name=name)
        key = self._new_slot()
        self._slot_of_tensor[id(t)] = key
        self._tensor_refs.append(t)
        t._static_program = self
        self.feed_slots[name] = key
        self.feed_specs[name] = (shape, np_dtype)
        return t

    # ---- execution ----
    def _fetch_key(self, var) -> int:
        if isinstance(var, Tensor):
            key = self._slot_of_tensor.get(id(var))
            if key is None:
                raise ValueError("fetch target was not produced inside "
                                 "this Program")
            return key
        if isinstance(var, str):
            for t in self._tensor_refs:
                if t.name == var:
                    return self._slot_of_tensor[id(t)]
            raise ValueError(f"no variable named {var!r} in Program")
        raise TypeError(f"bad fetch target {type(var)}")

    def _replay(self, env: Dict[int, Any], fetch_keys):
        env = dict(env)
        for op in self.ops:
            out = op.raw_fn(*[env[k] for k in op.in_keys],
                            **op.static_kwargs)
            outs = out if isinstance(out, tuple) else (out,)
            for k, o in zip(op.out_keys, outs):
                env[k] = o
        return tuple(env[k] for k in fetch_keys)

    def run(self, feed: Dict[str, Any], fetch_list: Sequence) -> List:
        fetch_keys = tuple(self._fetch_key(v) for v in fetch_list)
        feed = feed or {}
        feed_arrays = {}
        for name, val in feed.items():
            if name not in self.feed_slots:
                raise KeyError(f"feed {name!r} is not a placeholder of "
                               f"this Program")
            feed_arrays[self.feed_slots[name]] = jnp.asarray(
                val._data if isinstance(val, Tensor) else val)
        # captured externals (parameters etc.) travel as jit ARGUMENTS so
        # mutations between runs are visible (reference scope semantics),
        # not baked-in constants; cache key covers the op list and
        # capture set so mutating the Program invalidates stale programs
        cap_keys = tuple(sorted(self._captured))
        sig = (tuple(sorted((k, a.shape, str(a.dtype))
                            for k, a in feed_arrays.items())), fetch_keys,
               len(self.ops), cap_keys)
        if sig not in self._exec_cache:
            feed_keys = tuple(sorted(feed_arrays))

            def compiled(feed_vals, cap_vals):
                env = dict(zip(feed_keys, feed_vals))
                env.update(zip(cap_keys, cap_vals))
                return self._replay(env, fetch_keys)

            self._exec_cache[sig] = (feed_keys, jax.jit(compiled))
        feed_keys, fn = self._exec_cache[sig]
        outs = fn([feed_arrays[k] for k in feed_keys],
                  [self._captured[k]._data for k in cap_keys])
        return [np.asarray(o) for o in outs]

    def clone(self, for_test: bool = False) -> "Program":
        return self  # recorded program has no train/test divergence

    def global_block(self):
        return self

    @property
    def num_ops(self):
        return len(self.ops)

    def __repr__(self):
        return f"Program(id={self._id}, ops={len(self.ops)})"


class _State(threading.local):
    def __init__(self):
        self.main: Optional[Program] = None
        self.startup: Optional[Program] = None
        self.default_main = Program()
        self.default_startup = Program()


_STATE = _State()


def current_program() -> Optional[Program]:
    return _STATE.main


def default_main_program() -> Program:
    return _STATE.main if _STATE.main is not None else _STATE.default_main


def default_startup_program() -> Program:
    return (_STATE.startup if _STATE.startup is not None
            else _STATE.default_startup)


class program_guard:
    """Records dispatched ops into ``main_program`` (reference:
    base/framework.py program_guard)."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self._main = main_program
        self._startup = startup_program

    def __enter__(self):
        self._prev = (_STATE.main, _STATE.startup)
        _STATE.main = self._main
        _STATE.startup = self._startup
        return self

    def __exit__(self, *exc):
        _STATE.main, _STATE.startup = self._prev
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Declare a feed placeholder (reference: static/input.py data).

    Must run under ``program_guard`` — op recording is guard-scoped
    (paddle_tpu is dygraph-first; the guard is the enable_static
    equivalent), so a placeholder outside it would silently record
    nothing."""
    prog = current_program()
    if prog is None:
        raise RuntimeError(
            "static.data() outside program_guard: wrap graph "
            "construction in `with static.program_guard(prog):` — ops "
            "are only recorded inside the guard")
    return prog.add_placeholder(name, shape, dtype)


class scope_guard:
    def __init__(self, scope=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class Executor:
    """Program runner (reference: base/executor.py Executor:1152). The
    per-(program, feed-signature, fetch) jit cache plays the
    _ExecutorCache:854 role; place is accepted for API parity (XLA owns
    placement)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, return_numpy: bool = True):
        program = program or default_main_program()
        outs = program.run(feed or {}, fetch_list or [])
        if return_numpy:
            return outs
        return [Tensor(jnp.asarray(o)) for o in outs]

    def close(self):
        pass


CompiledProgram = Program  # reference CompiledProgram: already compiled


def save_inference_model(path_prefix: str, feed_vars, fetch_vars,
                         executor=None, program: Optional[Program] = None):
    """Export a pruned inference program (reference: static/io.py
    save_inference_model:510). The artifact is the same StableHLO +
    params format jit.save produces, so paddle_tpu.inference.Predictor
    loads it — mirroring the reference's static-save → AnalysisPredictor
    pipeline."""
    import os
    import pickle

    from jax import export as jexport

    feed_vars = list(feed_vars)
    if program is None:
        # resolve the owning Program from the fetch vars (the guard may
        # have exited by now — reference passes program explicitly)
        program = getattr(list(fetch_vars)[0], "_static_program", None) \
            or default_main_program()
    fetch_keys = tuple(program._fetch_key(v) for v in fetch_vars)
    feed_keys = []
    for v in feed_vars:
        key = program._slot_of_tensor.get(id(v))
        if key is None:
            raise ValueError("feed var not part of the program")
        feed_keys.append(key)

    # signature matches TranslatedLayer's (params, buffers, *args)
    # convention so jit.load / inference.Predictor can call it
    def fwd(params, buffers, *arrays):
        env = dict(zip(feed_keys, arrays))
        # deployment artifact: captured params ARE baked in as constants
        env.update({k: t._data for k, t in program._captured.items()})
        return program._replay(env, fetch_keys)

    # dynamic dims (declared None/-1 in static.data) export as symbolic
    # dimensions so the artifact accepts any batch size (reference
    # save_inference_model preserves dynamic batch)
    orig = getattr(program, "feed_orig_shapes", {})
    avals = []
    n_sym = 0
    for v in feed_vars:
        oshape = orig.get(v.name, tuple(v.shape))
        if any(s is None for s in oshape):
            dims = []
            for s in oshape:
                if s is None:
                    dims.append(f"_b{n_sym}")
                    n_sym += 1
                else:
                    dims.append(str(s))
            sym = jexport.symbolic_shape("(" + ", ".join(dims) + ")")
            avals.append(jax.ShapeDtypeStruct(sym, v._data.dtype))
        else:
            avals.append(jax.ShapeDtypeStruct(tuple(v.shape),
                                              v._data.dtype))
    exp = jexport.export(jax.jit(fwd))([], [], *avals)

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({}, f, protocol=4)  # params are baked into the export
    meta = {"class_name": "StaticProgram", "exported": [exp.serialize()],
            "param_names": [], "n_params": 0}
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(meta, f, protocol=4)


def load_inference_model(path_prefix: str, executor=None):
    """reference: static/io.py load_inference_model:820 — returns
    (program-like callable, feed_names, fetch_names)."""
    from ..jit.api import load as jit_load

    layer = jit_load(path_prefix)
    n_in = len(layer._exported.in_avals)
    return layer, [f"input_{i}" for i in range(n_in)], ["output_0"]
