"""Post-training quantization of SAVED inference artifacts.

TPU-native counterpart of the reference's static PTQ toolkit (reference:
python/paddle/static/quantization/post_training_quantization.py —
PostTrainingQuantization loads a saved inference program, calibrates on a
reader, and writes a quantized program the serving stack deploys).

Design divergence, by design: the reference emits activation-int8
programs for int8 GEMM hardware. On TPU the serving bottleneck is HBM
weight bandwidth (SURVEY §6 decode roofline), so this toolkit emits
WEIGHT-ONLY int8 artifacts — int8 weights + per-channel scales stored in
the params file, dequantized inside the re-exported StableHLO program
where XLA fuses the scale multiply into the consuming matmul. This is
the same scheme the live serving path uses
(inference/engine.py quantize_weight_only_int8). The calibration reader
plays the validation role: the fp and int8 programs are run side by side
on its batches and the output deviation is reported, so a serving team
can gate deployment on a numeric budget.
"""
from __future__ import annotations

import os
import pickle
from typing import Dict, Iterable, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["post_training_quantize", "PTQResult"]


class PTQResult:
    """What happened + how close the int8 artifact tracks the original."""

    def __init__(self, output_prefix, quantized, skipped, calib_stats):
        self.output_prefix = output_prefix
        self.quantized = list(quantized)
        self.skipped = list(skipped)
        #: {"batches": N, "max_abs_err": x, "mean_abs_err": y,
        #:  "out_scale": typical |output|} — empty without a reader
        self.calib_stats = dict(calib_stats)

    def __repr__(self):
        return (f"PTQResult(prefix={self.output_prefix!r}, "
                f"quantized={len(self.quantized)}, "
                f"skipped={len(self.skipped)}, "
                f"calib={self.calib_stats})")


def _channel_axes(shape) -> tuple:
    """Reduction axes for per-channel scales: 2-D weights keep the last
    (output) axis, conv-style >=3-D weights keep axis 0 (out channels)."""
    nd = len(shape)
    if nd == 2:
        return (0,)
    return tuple(range(1, nd))


def post_training_quantize(model, calib_reader: Optional[Iterable] = None,
                           output_prefix: Optional[str] = None,
                           weight_bits: int = 8, per_channel: bool = True,
                           skip_params: Sequence[str] = (),
                           min_numel: int = 1024,
                           max_calib_batches: int = 8) -> PTQResult:
    """Quantize a saved jit.save/static.save_inference_model artifact.

    ``model`` is a path prefix, an ``inference.Config``, or a
    ``Predictor``. Writes ``output_prefix{.pdmodel,.pdiparams}``
    (default: ``<prefix>_int8``) loadable by ``jit.load`` and
    ``inference.Predictor``. Returns a :class:`PTQResult`.
    """
    from jax import export as jexport

    from ..jit.api import load as jit_load

    if weight_bits != 8:
        raise NotImplementedError("only weight_bits=8 is supported")
    prefix = model
    if hasattr(prefix, "_config"):           # Predictor
        prefix = prefix._config
    if hasattr(prefix, "model_path"):        # Config
        prefix = prefix.model_path()
    layer = jit_load(prefix)
    if layer._exported is None:
        raise ValueError(
            "artifact was saved without input_spec (no compiled program) "
            "— re-save with input_spec, then quantize")
    meta = layer._meta
    names = list(meta["param_names"])
    n_params = layer._n_params
    param_names, buffer_names = names[:n_params], names[n_params:]
    state = layer._state

    qmax = (1 << (weight_bits - 1)) - 1      # 127
    quantized, skipped = [], []
    new_state: Dict[str, jnp.ndarray] = {}
    scales: Dict[str, jnp.ndarray] = {}
    for n in param_names:
        w = state[n]
        if (not jnp.issubdtype(w.dtype, jnp.floating) or w.ndim < 2
                or w.size < min_numel or n in skip_params):
            skipped.append(n)
            new_state[n] = w
            continue
        axes = _channel_axes(w.shape) if per_channel \
            else tuple(range(w.ndim))
        s = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes,
                    keepdims=True)
        s = jnp.maximum(s, 1e-8) / qmax
        q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -qmax - 1,
                     qmax).astype(jnp.int8)
        quantized.append(n)
        new_state[n] = q
        scales[n] = s.astype(jnp.float32)

    exp = layer._exported

    # the wrapper keeps TranslatedLayer's 2-way (params, buffers) call
    # convention: its "params" list is the q weights followed by scales
    n_w = len(param_names)

    def fwd(qs_arrays, buffer_arrays, *arg_arrays):
        it = iter(qs_arrays[n_w:])           # the scales tail
        deq = []
        for n, a in zip(param_names, qs_arrays[:n_w]):
            if n in scales:
                s = next(it)
                orig_dt = state[n].dtype
                deq.append((a.astype(jnp.float32) * s).astype(orig_dt))
            else:
                deq.append(a)
        return exp.call(deq, list(buffer_arrays), *arg_arrays)

    qs_avals = [jax.ShapeDtypeStruct(new_state[n].shape,
                                     new_state[n].dtype)
                for n in param_names] + \
               [jax.ShapeDtypeStruct(scales[n].shape, scales[n].dtype)
                for n in param_names if n in scales]
    b_avals = [jax.ShapeDtypeStruct(state[n].shape, state[n].dtype)
               for n in buffer_names]
    # original program input avals past (params, buffers) are the data
    # args — reuse them (symbolic batch dims survive the re-export)
    n_state_leaves = len(param_names) + len(buffer_names)
    arg_avals = list(exp.in_avals)[n_state_leaves:]
    new_exp = jexport.export(jax.jit(fwd))(qs_avals, b_avals, *arg_avals)

    # ---- artifact: params = q weights + scales, buffers unchanged ----
    out_prefix = output_prefix or (prefix + "_int8")
    scale_names = [f"{n}@scale" for n in param_names if n in scales]
    all_names = param_names + scale_names + buffer_names
    out_state = {}
    out_state.update({n: np.asarray(new_state[n]) for n in param_names})
    out_state.update({f"{n}@scale": np.asarray(scales[n])
                      for n in param_names if n in scales})
    out_state.update({n: np.asarray(state[n]) for n in buffer_names})
    os.makedirs(os.path.dirname(out_prefix) or ".", exist_ok=True)
    with open(out_prefix + ".pdiparams", "wb") as f:
        pickle.dump(out_state, f, protocol=4)
    new_meta = {
        "class_name": meta.get("class_name", "Layer") + "Int8",
        "n_outputs": meta.get("n_outputs"),
        "exported": [new_exp.serialize()],
        "param_names": all_names,
        # TranslatedLayer splits state as (params, buffers) by n_params:
        # the (q weights + scales) block is the "params" pytree leaves…
        "n_params": len(param_names) + len(scale_names),
        "ptq": {"weight_bits": weight_bits, "per_channel": per_channel,
                "quantized": quantized},
    }
    with open(out_prefix + ".pdmodel", "wb") as f:
        pickle.dump(new_meta, f, protocol=4)

    calib_stats = {}
    if calib_reader is not None:
        q_layer = jit_load(out_prefix)
        max_err, mean_err, out_mag, batches = 0.0, 0.0, 0.0, 0
        for batch in calib_reader:
            if batches >= max_calib_batches:
                break
            args = batch if isinstance(batch, (list, tuple)) else (batch,)
            ref_out = layer(*args)
            q_out = q_layer(*args)
            refs = ref_out if isinstance(ref_out, tuple) else (ref_out,)
            qs = q_out if isinstance(q_out, tuple) else (q_out,)
            for r, q in zip(refs, qs):
                d = np.abs(np.asarray(r.numpy(), np.float32)
                           - np.asarray(q.numpy(), np.float32))
                max_err = max(max_err, float(d.max()))
                mean_err += float(d.mean())
                out_mag = max(out_mag, float(
                    np.abs(np.asarray(r.numpy(), np.float32)).max()))
            batches += 1
        if batches:
            calib_stats = {"batches": batches,
                           "max_abs_err": max_err,
                           "mean_abs_err": mean_err / batches,
                           "out_scale": out_mag}
    return PTQResult(out_prefix, quantized, skipped, calib_stats)
