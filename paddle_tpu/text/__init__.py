"""paddle_tpu.text — text datasets + Viterbi decoding.

TPU-native equivalent of the reference's text package (reference:
python/paddle/text/__init__.py — datasets Conll05st/Imdb/Imikolov/
Movielens/UCIHousing/WMT14/WMT16 + viterbi_decode/ViterbiDecoder).
"""
from .datasets import (Conll05st, Imdb, Imikolov, Movielens, UCIHousing,
                       WMT14, WMT16)
from .viterbi_decode import ViterbiDecoder, viterbi_decode

__all__ = [
    "Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
    "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode",
]
