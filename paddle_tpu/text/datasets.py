"""Text datasets (reference: python/paddle/text/datasets — conll05.py,
imdb.py, imikolov.py, movielens.py, uci_housing.py, wmt14.py, wmt16.py).

Zero-egress environment: each dataset loads from a local ``data_file``
when given, else generates a deterministic synthetic corpus with the
real record structure (ids/fields/shapes match the reference's __getitem__
contract), the same pattern as paddle_tpu.vision.datasets.
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]


def _rng(mode, seed=0):
    return np.random.RandomState(seed if mode == "train" else seed + 1)


class Imdb(Dataset):
    """Sentiment classification: (word-id sequence, 0/1 label)
    (reference imdb.py — __getitem__ returns (doc, label))."""

    VOCAB = 5147

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True, synthetic_size=512):
        self.mode = mode
        rng = _rng(mode, 10)
        n = synthetic_size
        self.labels = rng.randint(0, 2, n).astype(np.int64)
        # label-dependent token distribution so classifiers can learn
        self.docs = []
        for i in range(n):
            ln = rng.randint(8, 64)
            lo = 0 if self.labels[i] == 0 else self.VOCAB // 2
            self.docs.append(rng.randint(
                lo, lo + self.VOCAB // 2, ln).astype(np.int64))

    def word_idx(self):
        return {f"w{i}": i for i in range(self.VOCAB)}

    def __getitem__(self, idx):
        return self.docs[idx], np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset (reference imikolov.py — returns an
    n-gram tuple of word ids)."""

    VOCAB = 2074

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True,
                 synthetic_size=2048):
        self.window_size = window_size
        self.data_type = data_type
        rng = _rng(mode, 20)
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError("data_type must be NGRAM or SEQ")
        self.samples = []
        for _ in range(synthetic_size):
            if data_type == "NGRAM":
                self.samples.append(
                    rng.randint(0, self.VOCAB, window_size)
                    .astype(np.int64))
            else:
                ln = rng.randint(4, 32)
                seq = rng.randint(0, self.VOCAB, ln).astype(np.int64)
                self.samples.append((seq[:-1], seq[1:]))

    def word_idx(self):
        return {f"w{i}": i for i in range(self.VOCAB)}

    def __getitem__(self, idx):
        s = self.samples[idx]
        if self.data_type == "NGRAM":
            return tuple(np.asarray([w], np.int64) for w in s)
        return s

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """Rating prediction records (reference movielens.py — user/movie
    features + score)."""

    NUM_USERS, NUM_MOVIES, NUM_CATS = 6040, 3952, 18

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True, synthetic_size=1024):
        rng = _rng(mode, 30 + rand_seed)
        n = synthetic_size
        self.user_id = rng.randint(1, self.NUM_USERS, n).astype(np.int64)
        self.gender = rng.randint(0, 2, n).astype(np.int64)
        self.age = rng.randint(0, 7, n).astype(np.int64)
        self.job = rng.randint(0, 21, n).astype(np.int64)
        self.movie_id = rng.randint(1, self.NUM_MOVIES, n).astype(np.int64)
        self.category = [rng.randint(0, self.NUM_CATS,
                                     rng.randint(1, 4)).astype(np.int64)
                         for _ in range(n)]
        self.title = [rng.randint(0, 5175, rng.randint(1, 6))
                      .astype(np.int64) for _ in range(n)]
        self.score = (rng.randint(1, 6, n)).astype(np.float32)

    def __getitem__(self, idx):
        return (np.asarray([self.user_id[idx]]),
                np.asarray([self.gender[idx]]),
                np.asarray([self.age[idx]]),
                np.asarray([self.job[idx]]),
                np.asarray([self.movie_id[idx]]),
                self.category[idx], self.title[idx],
                np.asarray([self.score[idx]], np.float32))

    def __len__(self):
        return len(self.score)


class UCIHousing(Dataset):
    """Boston housing regression (reference uci_housing.py — 13 features,
    1 target, feature-normalized)."""

    FEATURE_DIM = 13

    def __init__(self, data_file=None, mode="train", download=True,
                 synthetic_size=404):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
            self.features, self.targets = raw[:, :-1], raw[:, -1:]
        else:
            rng = _rng(mode, 40)
            n = synthetic_size
            self.features = rng.randn(n, self.FEATURE_DIM) \
                .astype(np.float32)
            w = _rng("train", 41).randn(self.FEATURE_DIM, 1)
            self.targets = (self.features @ w
                            + 0.1 * rng.randn(n, 1)).astype(np.float32)

    def __getitem__(self, idx):
        return self.features[idx], self.targets[idx]

    def __len__(self):
        return len(self.features)


class _WMTBase(Dataset):
    """Parallel-corpus pair dataset: (src ids, trg ids, trg_next ids)
    (reference wmt14.py/wmt16.py — BOS/EOS-framed id sequences)."""

    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, dict_size, mode, seed, synthetic_size=512):
        self.dict_size = dict_size
        rng = _rng(mode, seed)
        self.pairs = []
        for _ in range(synthetic_size):
            ls = rng.randint(3, 24)
            lt = max(2, int(ls + rng.randint(-3, 4)))
            src = rng.randint(3, dict_size, ls).astype(np.int64)
            trg = rng.randint(3, dict_size, lt).astype(np.int64)
            self.pairs.append((src, trg))

    def __getitem__(self, idx):
        src, trg = self.pairs[idx]
        src_ids = np.concatenate([[self.BOS], src, [self.EOS]])
        trg_in = np.concatenate([[self.BOS], trg])
        trg_next = np.concatenate([trg, [self.EOS]])
        return src_ids, trg_in, trg_next

    def __len__(self):
        return len(self.pairs)


class WMT14(_WMTBase):
    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True, synthetic_size=512):
        super().__init__(dict_size, mode, 50, synthetic_size)


class WMT16(_WMTBase):
    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True,
                 synthetic_size=512):
        super().__init__(src_dict_size, mode, 60, synthetic_size)


class Conll05st(Dataset):
    """SRL dataset: word/predicate/ctx/mark id sequences + labels
    (reference conll05.py — 9-tuple of aligned sequences)."""

    WORD_DICT, LABEL_DICT, PRED_DICT = 44068, 106, 3162

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=True, synthetic_size=256):
        rng = _rng(mode, 70)
        self.samples = []
        for _ in range(synthetic_size):
            ln = rng.randint(4, 40)
            words = rng.randint(0, self.WORD_DICT, ln).astype(np.int64)
            pred = np.full(ln, rng.randint(0, self.PRED_DICT),
                           np.int64)
            ctx = [rng.randint(0, self.WORD_DICT, ln).astype(np.int64)
                   for _ in range(5)]
            mark = (rng.rand(ln) < 0.2).astype(np.int64)
            label = rng.randint(0, self.LABEL_DICT, ln).astype(np.int64)
            self.samples.append((words, *ctx, pred, mark, label))

    def get_dict(self):
        return ({f"w{i}": i for i in range(100)},
                {f"v{i}": i for i in range(100)},
                {f"l{i}": i for i in range(self.LABEL_DICT)})

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)
