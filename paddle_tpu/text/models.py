"""BERT model family — the BASELINE configs[2] anchor.

TPU-native BERT (reference exemplar: the DP-pretraining anchor in
test/legacy_test/test_dist_base.py:962 and the fleet BERT configs;
architecture per the canonical bert-base: 12-layer post-LN encoder,
GELU FFN, tied MLM decoder + NSP head). Built from this framework's
``nn.TransformerEncoder`` so the whole model runs as one compiled
XLA program under ``jit.TrainStep``.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle

from ..nn import (Dropout, Embedding, GELU, Layer, LayerNorm, Linear,
                  Tanh, TransformerEncoder, TransformerEncoderLayer)
from ..nn import functional as F

__all__ = ["BertModel", "BertForPretraining", "BertPretrainingCriterion",
           "bert_base", "bert_tiny"]


class BertEmbeddings(Layer):
    def __init__(self, vocab_size, hidden_size, max_position,
                 type_vocab_size, dropout):
        super().__init__()
        self.word_embeddings = Embedding(vocab_size, hidden_size)
        self.position_embeddings = Embedding(max_position, hidden_size)
        self.token_type_embeddings = Embedding(type_vocab_size,
                                               hidden_size)
        self.layer_norm = LayerNorm(hidden_size)
        self.dropout = Dropout(dropout)

    def forward(self, input_ids, token_type_ids=None):
        b, s = input_ids.shape
        pos = paddle.arange(s).reshape([1, s]).expand([b, s])
        emb = self.word_embeddings(input_ids) \
            + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertModel(Layer):
    """Encoder trunk + tanh pooler (CLS)."""

    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, max_position_embeddings=512,
                 type_vocab_size=2, hidden_dropout_prob=0.1,
                 attention_probs_dropout_prob=None):
        # attention_probs_dropout_prob=0.0 keeps attention on the flash
        # path (dropout INSIDE attention forces the materialized
        # [b,h,s,s] softmax — the usual flash-era trade, e.g.
        # MosaicBERT); None follows hidden_dropout_prob (canonical BERT)
        super().__init__()
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.embeddings = BertEmbeddings(
            vocab_size, hidden_size, max_position_embeddings,
            type_vocab_size, hidden_dropout_prob)
        layer = TransformerEncoderLayer(
            hidden_size, num_attention_heads, intermediate_size,
            dropout=hidden_dropout_prob, activation="gelu",
            attn_dropout=attention_probs_dropout_prob,
            normalize_before=False)
        self.encoder = TransformerEncoder(layer, num_hidden_layers)
        self.pooler_dense = Linear(hidden_size, hidden_size)
        self.pooler_act = Tanh()

    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None):
        h = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None:
            # [b, s] 1/0 mask -> additive [b, 1, 1, s]
            am = (1.0 - attention_mask.astype("float32")) * -1e4
            attention_mask = am.reshape(
                [am.shape[0], 1, 1, am.shape[1]])
        seq = self.encoder(h, attention_mask)
        pooled = self.pooler_act(self.pooler_dense(seq[:, 0]))
        return seq, pooled


class BertForPretraining(Layer):
    """MLM (transform + TIED decoder) + NSP heads."""

    def __init__(self, bert: BertModel, vocab_size=None):
        super().__init__()
        self.bert = bert
        d = bert.hidden_size
        vocab_size = vocab_size or \
            bert.embeddings.word_embeddings.weight.shape[0]
        self.transform = Linear(d, d)
        self.transform_act = GELU()
        self.transform_norm = LayerNorm(d)
        from ..core.tensor import Parameter
        import jax.numpy as jnp

        self.decoder_bias = Parameter(
            jnp.zeros((vocab_size,), jnp.float32))
        self.nsp = Linear(d, 2)

    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask)
        h = self.transform_norm(self.transform_act(self.transform(seq)))
        # tied decoder: h @ word_embeddings.T + bias
        w = self.bert.embeddings.word_embeddings.weight
        # bias must be the Parameter itself so BOTH the eager tape and
        # the traced path see a trainable leaf (ADVICE r4: wrapping in a
        # fresh Tensor made it stop_gradient on the tape)
        mlm_logits = paddle.matmul(h, w, transpose_y=True) \
            + self.decoder_bias
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


class BertPretrainingCriterion(Layer):
    """MLM CE over masked positions (-100 = unmasked, ignored) + NSP CE
    — the standard pretraining objective."""

    def forward(self, mlm_logits, nsp_logits, mlm_labels, nsp_labels):
        vocab = mlm_logits.shape[-1]
        flat_logits = mlm_logits.reshape([-1, vocab])
        flat_labels = mlm_labels.reshape([-1])
        mask = (flat_labels != -100).astype("float32")
        safe = paddle.where(flat_labels == -100,
                            paddle.zeros_like(flat_labels), flat_labels)
        per_tok = F.cross_entropy(flat_logits, safe, reduction="none") \
            .reshape([-1])
        denom = mask.sum().clip(min=1.0)
        mlm_loss = (per_tok * mask).sum() / denom
        nsp_loss = F.cross_entropy(nsp_logits, nsp_labels.reshape([-1]))
        return mlm_loss + nsp_loss


def bert_base(**kw):
    """bert-base-uncased geometry (110M params)."""
    return BertModel(vocab_size=30522, hidden_size=768,
                     num_hidden_layers=12, num_attention_heads=12,
                     intermediate_size=3072, **kw)


def bert_tiny(**kw):
    """Test-sized geometry (fast CI)."""
    cfg = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
               num_attention_heads=2, intermediate_size=64,
               max_position_embeddings=64)
    cfg.update(kw)
    return BertModel(**cfg)
