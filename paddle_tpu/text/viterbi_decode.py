"""Viterbi decoding (reference: python/paddle/text/viterbi_decode.py —
viterbi_decode:25 over the phi viterbi_decode kernel, ViterbiDecoder:100).

TPU-native design: the whole decode (forward maxes + backtrace) is a pair
of ``lax.scan``s over the time axis, vectorized across the batch, with
per-sequence length masking — one compiled program, no host loop.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..ops.dispatch import as_tensor_args, eager_apply

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Highest-scoring tag sequence per batch row.

    potentials [b, T, n]: unary emissions; transition_params [n, n];
    lengths [b] int. With ``include_bos_eos_tag`` the last row/col of the
    transition matrix is the BOS tag and the second-to-last the EOS tag
    (reference viterbi_decode:37). Returns (scores [b], paths
    [b, max(lengths)]) — positions past a row's length hold 0.
    """
    (pot_t, trans_t, len_t) = as_tensor_args(potentials, transition_params,
                                             lengths)
    max_len = int(np.max(np.asarray(len_t._data)))

    def raw(pot, trans, lens):
        b, T, n = pot.shape
        lens = lens.astype(jnp.int32)

        if include_bos_eos_tag:
            alpha = pot[:, 0] + trans[n - 1, :][None, :]
        else:
            alpha = pot[:, 0]

        def fwd(carry, t):
            alpha = carry
            # scores[j, k] = alpha[j] + trans[j, k]
            scores = alpha[:, :, None] + trans[None, :, :]
            best_prev = jnp.argmax(scores, axis=1)          # [b, n]
            new_alpha = jnp.max(scores, axis=1) + pot[:, t]
            live = (t < lens)[:, None]
            alpha = jnp.where(live, new_alpha, alpha)
            return alpha, best_prev

        alpha, bps = jax.lax.scan(fwd, alpha, jnp.arange(1, T))
        # bps[t-1] maps tag-at-t -> best tag-at-(t-1)

        if include_bos_eos_tag:
            alpha = alpha + trans[:, n - 2][None, :]

        scores = jnp.max(alpha, axis=1)
        last_tag = jnp.argmax(alpha, axis=1).astype(jnp.int32)

        def back(carry, t):
            cur = carry
            at_end = t == lens - 1
            cur = jnp.where(at_end, last_tag, cur)
            out_t = jnp.where(t < lens, cur, 0)
            prev = jnp.take_along_axis(
                bps[jnp.maximum(t - 1, 0)], cur[:, None], axis=1)[:, 0]
            live = (t > 0) & (t < lens)
            cur = jnp.where(live, prev.astype(jnp.int32), cur)
            return cur, out_t

        init = jnp.zeros((b,), jnp.int32)
        _, path_rev = jax.lax.scan(back, init,
                                   jnp.arange(T - 1, -1, -1))
        paths = jnp.flip(jnp.swapaxes(path_rev, 0, 1), axis=1)
        return scores, paths.astype(jnp.int64)

    scores, paths = eager_apply("viterbi_decode", raw,
                                [pot_t, trans_t, len_t], n_outputs=2)
    return scores, Tensor(paths._data[:, :max_len])


class ViterbiDecoder(Layer):
    """(reference viterbi_decode.py:100) Layer wrapper holding the
    transition matrix."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
