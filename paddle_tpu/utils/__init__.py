"""paddle_tpu.utils — misc utilities.

TPU-native equivalent of the reference's utils package (reference:
python/paddle/utils — unique_name.py, deprecated.py, download.py,
dlpack.py, try_import, require_version, cpp_extension/). Zero-egress:
download resolves local paths/caches only; cpp_extension points at the
ctypes/cffi extension path this framework uses for native code.
"""
from __future__ import annotations

import functools
import importlib
import os
import warnings

from . import cpp_extension  # noqa: F401
from . import crypto  # noqa: F401
from . import unique_name  # noqa: F401

__all__ = ["deprecated", "try_import", "require_version", "run_check",
           "unique_name", "download", "dlpack", "cpp_extension",
           "crypto"]


def deprecated(update_to="", since="", reason="", level=0):
    """(reference utils/deprecated.py) decorator emitting a
    DeprecationWarning on call."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API '{fn.__module__}.{fn.__name__}' is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use '{update_to}' instead"
            if reason:
                msg += f" ({reason})"
            if level > 1:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def try_import(module_name, err_msg=None):
    """(reference utils/lazy_import.py try_import)"""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed")


def require_version(min_version, max_version=None):
    """(reference utils/install_check-style version gate) against this
    framework's version string."""
    from .. import __version__

    def _key(v):
        import re

        parts = []
        for x in str(v).split(".")[:3]:
            m = re.match(r"\d+", x)
            parts.append(int(m.group()) if m else 0)
        while len(parts) < 3:  # '0.1' must equal '0.1.0'
            parts.append(0)
        return tuple(parts)

    cur = _key(__version__)
    if _key(min_version) > cur:
        raise RuntimeError(
            f"paddle_tpu>={min_version} required, found {__version__}")
    if max_version is not None and _key(max_version) < cur:
        raise RuntimeError(
            f"paddle_tpu<={max_version} required, found {__version__}")
    return True


def run_check():
    """(reference utils/install_check.py run_check) Sanity-check the
    install: one matmul on the default device."""
    import numpy as np

    import paddle_tpu as paddle

    x = paddle.to_tensor(np.eye(4, dtype=np.float32))
    y = (x @ x).numpy()
    assert np.allclose(y, np.eye(4)), "matmul check failed"
    dev = paddle.device.get_device()
    print(f"paddle_tpu is installed successfully! device: {dev}")


class download:
    """(reference utils/download.py) Zero-egress: resolves local files
    and the local cache dir; remote URLs raise with guidance."""

    @staticmethod
    def get_weights_path_from_url(url, md5sum=None):
        if os.path.exists(url):  # an explicit local path always wins
            return url
        path = os.path.expanduser(
            os.path.join("~", ".cache", "paddle_tpu", "weights",
                         os.path.basename(url)))
        if os.path.exists(path):
            return path
        raise RuntimeError(
            f"zero-egress environment: place the file at {path} "
            f"(requested {url})")


class dlpack:
    """(reference utils/dlpack.py) to/from DLPack via jax's support."""

    @staticmethod
    def to_dlpack(tensor):
        """Returns a DLPack-protocol object (has __dlpack__ /
        __dlpack_device__ — the modern exchange form consumers like
        np/torch/jax from_dlpack expect). Falls back through host
        memory on PJRT transports without external buffer references
        (e.g. tunneled chips)."""
        from ..core.tensor import Tensor

        arr = tensor._data if isinstance(tensor, Tensor) else tensor
        try:
            arr.__dlpack__()  # probe device support
            return arr
        except Exception:
            import numpy as np

            # writable copy: DLPack cannot export readonly views
            return np.array(arr)

    @staticmethod
    def from_dlpack(ext_array):
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        return Tensor(jnp.from_dlpack(ext_array))
