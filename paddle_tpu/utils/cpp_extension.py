"""Custom-op registration + native C++ extension build/load.

TPU-native equivalent of the reference's custom-operator path:
  - reference paddle/fluid/framework/custom_operator.cc:958
    ``RegisterOperatorWithMetaInfo`` — registers a user op (forward +
    grad kernels) into the framework so it works in dygraph, static
    graph, and inference;
  - reference python/paddle/utils/cpp_extension/cpp_extension.py:797
    ``load()`` — JIT-compiles C++/CUDA sources and imports the resulting
    ops.

The TPU-first split: **device kernels are JAX/Pallas callables** (CUDA
sources make no sense on TPU — XLA/Mosaic is the device compiler), and
**host kernels are C++ compiled to a shared library** bridged with
ctypes + ``jax.pure_callback``. A registered op composes with the whole
framework exactly like a built-in op:

  - eager dispatch + autograd tape (``register_custom_op`` routes
    through ``ops.dispatch.eager_apply``; a user ``backward`` becomes a
    ``jax.custom_vjp`` rule, so the tape, ``to_static`` tracing, AND
    whole-step ``jit.TrainStep`` all see the custom gradient);
  - ``to_static`` / ``jit.save`` — the forward is jax-traceable, so it
    serializes into the StableHLO artifact and reloads in the Predictor.
    Host C++ ops execute via callback and are eager/jit-executable but
    NOT serializable; ``jit.save`` detects the host custom-call in the
    export and raises with guidance instead of emitting a broken
    artifact.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
from typing import Callable, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "register_custom_op", "load", "setup", "get_build_directory",
    "CppExtension", "CUDAExtension", "CustomOpModule",
]


# ---------------------------------------------------------------------------
# device-path custom ops (jax / Pallas callables)
# ---------------------------------------------------------------------------

def _float0_zeros(arr):
    return np.zeros(np.shape(arr), jax.dtypes.float0)


def register_custom_op(name: str, forward: Callable,
                       backward: Optional[Callable] = None, *,
                       methods: Sequence[str] = (),
                       save_outputs: bool = False,
                       n_outputs: Optional[int] = None):
    """Register a custom op backed by a jax/Pallas callable.

    Equivalent of the reference's ``PD_BUILD_OP(...)`` + MetaInfo
    registration (custom_operator.cc:958), with JAX supplying what the
    reference generates: shape/dtype inference comes from tracing the
    forward, and the grad node comes from the tape running ``jax.vjp``
    over the (optionally custom-VJP-wrapped) forward.

    Args:
      name: op name; becomes ``paddle_tpu.ops`` registry entry (tagged
        ``custom``) and optionally Tensor methods.
      forward: ``fn(*arrays) -> array | tuple`` over raw jax arrays.
        Positional array inputs only — close over static attributes
        (python scalars) with ``functools.partial`` before registering.
      backward: optional custom gradient. Signature
        ``backward(*inputs, *grad_outs) -> tuple_of_input_grads`` (or
        ``backward(*inputs, *outputs, *grad_outs)`` when
        ``save_outputs=True``). Return ``None`` for a no-grad input.
        When omitted, JAX differentiates the forward automatically.
      methods: Tensor method names to attach (like built-in ops).
      n_outputs: fixed output arity (None = infer per call).

    Returns the eager op callable (also importable via
    ``ops.registry.get_op(name).fn``).
    """
    from ..ops.dispatch import as_tensor_args, eager_apply
    from ..ops.registry import register_op

    def fwd_tuple(*arrays):
        out = forward(*arrays)
        return out if isinstance(out, tuple) else (out,)

    if backward is not None:
        core = jax.custom_vjp(fwd_tuple)

        def fwd_rule(*arrays):
            outs = fwd_tuple(*arrays)
            res = arrays + outs if save_outputs else arrays
            return outs, res

        def bwd_rule(res, gs):
            # res = inputs (+ outputs when save_outputs); recover the
            # input count from the residual length minus the output count
            n_in = len(res) - len(gs) if save_outputs else len(res)
            grads = backward(*res, *gs)
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            grads = list(grads)
            if len(grads) != n_in:
                raise ValueError(
                    f"custom op `{name}` backward returned {len(grads)} "
                    f"grads for {n_in} inputs")
            ins = res[:n_in]
            fixed = []
            for g, x in zip(grads, ins):
                if g is None:
                    fixed.append(
                        jnp.zeros_like(x)
                        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
                        else _float0_zeros(x))
                else:
                    fixed.append(g)
            return tuple(fixed)

        core.defvjp(fwd_rule, bwd_rule)
        raw_fn = core
    else:
        raw_fn = fwd_tuple

    def unwrap(*arrays):
        out = raw_fn(*arrays)
        return out if len(out) != 1 else out[0]

    unwrap.__name__ = name

    def op(*args):
        tensors = as_tensor_args(*args)
        return eager_apply(name, unwrap, tensors, {}, n_outputs)

    op.__name__ = name
    register_op(name, op, methods=methods, tags=("custom",))
    return op


# ---------------------------------------------------------------------------
# host-path native extensions (C++ → shared lib → ctypes + pure_callback)
# ---------------------------------------------------------------------------

def get_build_directory() -> str:
    """(reference cpp_extension.py ``get_build_directory``) Where JIT-
    compiled extensions land; override with PADDLE_EXTENSION_DIR."""
    d = os.environ.get(
        "PADDLE_EXTENSION_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """Build spec for a host C++ extension (reference CppExtension —
    minus setuptools; we drive g++ directly, see Environment notes)."""

    def __init__(self, sources: Sequence[str], name: Optional[str] = None,
                 extra_compile_args: Sequence[str] = (),
                 extra_link_args: Sequence[str] = ()):
        self.sources = list(sources)
        self.name = name
        self.extra_compile_args = list(extra_compile_args)
        self.extra_link_args = list(extra_link_args)


def CUDAExtension(*args, **kwargs):
    raise RuntimeError(
        "CUDAExtension is not supported on the TPU backend: device "
        "kernels are JAX/Pallas callables (see "
        "paddle_tpu.utils.cpp_extension.register_custom_op). Use "
        "CppExtension for host-side C++ code.")


class CustomOpModule:
    """A loaded extension library. Exposes the raw ctypes lib plus
    helpers that lift exported C functions into framework ops."""

    def __init__(self, name: str, lib_path: str):
        import ctypes

        self.name = name
        self.lib_path = lib_path
        self.lib = ctypes.CDLL(lib_path)

    def elementwise_op(self, symbol: str, op_name: Optional[str] = None,
                       backward: Optional[Callable] = None,
                       dtype=np.float32):
        """Lift an exported C function with the elementwise ABI

            extern "C" void symbol(const T* x, T* out, int64_t n);

        into a registered eager op. Executes on HOST via
        ``jax.pure_callback`` (TPU arrays round-trip through host
        memory — the documented cost of host custom ops; device-speed
        custom ops belong in Pallas via ``register_custom_op``).
        """
        import ctypes

        cfn = getattr(self.lib, symbol)
        ct = np.ctypeslib.ndpointer(dtype=dtype, flags="C_CONTIGUOUS")
        cfn.argtypes = [ct, ct, ctypes.c_int64]
        cfn.restype = None

        def host_call(x):
            x = np.ascontiguousarray(np.asarray(x, dtype))
            out = np.empty_like(x)
            cfn(x.reshape(-1), out.reshape(-1), x.size)
            return out

        def forward(x):
            return jax.pure_callback(
                host_call, jax.ShapeDtypeStruct(x.shape, dtype), x,
                vmap_method="sequential")

        return register_custom_op(op_name or symbol, forward, backward)


def _hash_build(sources, cflags, ldflags) -> str:
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(list(cflags) + list(ldflags)).encode())
    return h.hexdigest()[:16]


def load(name: str, sources: Sequence[str],
         extra_cflags: Sequence[str] = (),
         extra_ldflags: Sequence[str] = (),
         build_directory: Optional[str] = None,
         verbose: bool = False) -> CustomOpModule:
    """JIT-compile C++ sources into a shared library and load it
    (reference cpp_extension.py:797 ``load()``; same contract — content-
    hashed rebuild cache, returns a module exposing the ops).

    The library should export plain ``extern "C"`` functions; lift them
    into framework ops with :meth:`CustomOpModule.elementwise_op` (or
    call them via ctypes directly for bespoke ABIs).
    """
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    tag = _hash_build(sources, extra_cflags, extra_ldflags)
    so_path = os.path.join(build_dir, f"{name}_{tag}.so")
    if not os.path.exists(so_path):
        # build to a private temp path, then atomically publish: an
        # interrupted/concurrent build must never leave a half-written
        # .so at the cache-hit path
        tmp_path = f"{so_path}.build.{os.getpid()}"
        cmd = (["g++", "-O3", "-fPIC", "-shared", "-std=c++17"]
               + list(extra_cflags) + list(sources)
               + ["-o", tmp_path] + list(extra_ldflags))
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build of `{name}` failed:\n{proc.stderr}")
        os.replace(tmp_path, so_path)
    return CustomOpModule(name, so_path)


def setup(name: str, ext_modules: Sequence[CppExtension], **kwargs):
    """Ahead-of-time build entry (reference cpp_extension ``setup``):
    builds every extension into the build directory and returns the
    loaded modules instead of driving setuptools."""
    mods = []
    for ext in ext_modules:
        mods.append(load(ext.name or name, ext.sources,
                         extra_cflags=ext.extra_compile_args,
                         extra_ldflags=ext.extra_link_args))
    return mods[0] if len(mods) == 1 else mods
