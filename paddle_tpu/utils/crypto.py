"""Model encryption: cipher factory + encrypted artifact I/O.

TPU-native equivalent of the reference's model-crypto layer (reference:
paddle/fluid/framework/io/crypto/{cipher.cc,aes_cipher.cc,
cipher_utils.cc} — CipherFactory/CipherUtils used to encrypt inference
artifacts at rest). The container has no AES primitive in the stdlib,
so the cipher is an authenticated stream construction from hashlib/hmac
(HMAC-SHA256 keystream in counter mode + HMAC-SHA256 tag,
encrypt-then-MAC) — the same at-rest-protection contract with
stdlib-only dependencies; the file format is versioned so an AES
backend can slot in where one is available.
"""
from __future__ import annotations

import hashlib
import hmac
import os
import struct

__all__ = ["Cipher", "CipherFactory", "CipherUtils"]

_MAGIC_V1 = b"PDTPU\x01"
_MAGIC = b"PDTPU\x02"
_BLOCK = 32  # sha256 digest size


class Cipher:
    """Authenticated stream cipher (reference cipher.h Cipher API:
    encrypt/decrypt + *_to_file/*_from_file)."""

    def __init__(self, key: bytes):
        if not isinstance(key, (bytes, bytearray)) or len(key) < 16:
            raise ValueError("key must be bytes, >= 16 bytes")
        #: the raw key — persist it (e.g. CipherUtils.gen_key_to_file);
        #: without it encrypted artifacts are unrecoverable
        self.key = bytes(key)
        self._enc_key = hashlib.sha256(b"enc" + self.key).digest()
        self._mac_key = hashlib.sha256(b"mac" + self.key).digest()

    def _keystream(self, nonce: bytes, n: int) -> bytes:
        # v2: SHAKE-256 XOF keyed by (enc_key || nonce) — the whole
        # stream in ONE C call (~GB/s), vs v1's per-32-byte hmac.new
        # Python loop (~tens of MB/s on multi-hundred-MB artifacts)
        return hashlib.shake_256(self._enc_key + nonce).digest(n)

    def _keystream_v1(self, nonce: bytes, n: int) -> bytes:
        out = bytearray()
        for ctr in range((n + _BLOCK - 1) // _BLOCK):
            out += hmac.new(self._enc_key,
                            nonce + struct.pack("<Q", ctr),
                            hashlib.sha256).digest()
        return bytes(out[:n])

    @staticmethod
    def _xor(a: bytes, b: bytes) -> bytes:
        # bigint XOR: hundreds of MB/s vs a per-byte Python loop
        n = len(a)
        return (int.from_bytes(a, "little")
                ^ int.from_bytes(b, "little")).to_bytes(n, "little")

    def encrypt(self, plaintext: bytes) -> bytes:
        nonce = os.urandom(16)
        ks = self._keystream(nonce, len(plaintext))
        ct = self._xor(plaintext, ks)
        tag = hmac.new(self._mac_key, _MAGIC + nonce + ct,
                       hashlib.sha256).digest()
        return _MAGIC + nonce + tag + ct

    def decrypt(self, blob: bytes) -> bytes:
        magic = blob[:len(_MAGIC)]
        if magic not in (_MAGIC, _MAGIC_V1):
            raise ValueError("not a paddle_tpu encrypted blob")
        nonce = blob[len(magic):len(magic) + 16]
        tag = blob[len(magic) + 16:len(magic) + 16 + _BLOCK]
        ct = blob[len(magic) + 16 + _BLOCK:]
        want = hmac.new(self._mac_key, magic + nonce + ct,
                        hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise ValueError(
                "decryption failed: wrong key or corrupted file "
                "(authentication tag mismatch)")
        ks = (self._keystream if magic == _MAGIC
              else self._keystream_v1)(nonce, len(ct))
        return self._xor(ct, ks)

    def encrypt_to_file(self, plaintext: bytes, path: str) -> None:
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext))

    def decrypt_from_file(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return self.decrypt(f.read())


class CipherFactory:
    """(reference cipher.cc CipherFactory::CreateCipher)"""

    @staticmethod
    def create_cipher(config_fp: str = None) -> Cipher:
        """With ``config_fp``, load the key from that file; without,
        generate a fresh one — PERSIST ``cipher.key`` yourself (e.g.
        CipherUtils.gen_key_to_file) or the artifacts are
        unrecoverable once the object is gone."""
        key = CipherUtils.read_key_from_file(config_fp) \
            if config_fp else CipherUtils.gen_key(32)
        return Cipher(key)


class CipherUtils:
    """(reference cipher_utils.cc) key generation/persistence."""

    @staticmethod
    def gen_key(length: int = 32) -> bytes:
        return os.urandom(length)

    @staticmethod
    def gen_key_to_file(length: int, path: str) -> bytes:
        key = CipherUtils.gen_key(length)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()
