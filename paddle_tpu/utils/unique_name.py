"""Unique-name generator (reference: python/paddle/utils/unique_name.py
— generate/guard/switch over a per-context counter map)."""
from __future__ import annotations

import contextlib
from typing import Dict

__all__ = ["generate", "guard", "switch"]


class _Generator:
    def __init__(self):
        self.ids: Dict[str, int] = {}

    def __call__(self, key: str) -> str:
        n = self.ids.get(key, 0)
        self.ids[key] = n + 1
        return f"{key}_{n}"


_generator = _Generator()


def generate(key: str) -> str:
    return _generator(key)


def switch(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator or _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
