"""Vision datasets (reference: python/paddle/vision/datasets — MNIST,
FashionMNIST, Cifar10/100, Flowers). Zero-egress environment: datasets
load from a local path when given, else generate a deterministic synthetic
sample set with the real shapes/classes (enough for the e2e anchors and
tests; real data drops in via ``image_path``/``data_file``)."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io import Dataset

from .folder import DatasetFolder, ImageFolder  # noqa: F401

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100",
           "DatasetFolder", "ImageFolder"]


class MNIST(Dataset):
    NUM_CLASSES = 10
    IMAGE_SHAPE = (28, 28)

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None,
                 synthetic_size=1024):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images, self.labels = self._load_idx(image_path, label_path)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = synthetic_size
            self.labels = rng.randint(0, self.NUM_CLASSES, n).astype(np.int64)
            # class-dependent blobs so models can actually fit the data
            base = rng.rand(self.NUM_CLASSES, *self.IMAGE_SHAPE)
            noise = rng.rand(n, *self.IMAGE_SHAPE) * 0.3
            self.images = (base[self.labels] * 255 * 0.7
                           + noise * 255).astype(np.uint8)

    @staticmethod
    def _load_idx(image_path, label_path):
        opener = gzip.open if image_path.endswith(".gz") else open
        with opener(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with opener(label_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0  # CHW
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10
    IMAGE_SHAPE = (3, 32, 32)

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=1024):
        self.mode = mode
        self.transform = transform
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = synthetic_size
        self.labels = rng.randint(0, self.NUM_CLASSES, n).astype(np.int64)
        base = rng.rand(self.NUM_CLASSES, *self.IMAGE_SHAPE)
        noise = rng.rand(n, *self.IMAGE_SHAPE) * 0.3
        self.images = (base[self.labels] * 0.7 + noise).astype(np.float32)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100
