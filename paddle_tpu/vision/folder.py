"""Directory-tree image datasets: DatasetFolder / ImageFolder.

TPU-native equivalent of the reference's folder datasets (reference:
python/paddle/vision/datasets/folder.py — DatasetFolder:66 walks
``root/class_x/xxx.ext`` into (path, class) samples; ImageFolder:310
walks a flat/nested tree into unlabeled samples). Loader default is PIL
(cv2 optional in the reference; absent here), and ``.npy`` arrays load
without PIL — the synthetic-data path used throughout the zero-egress
test suite.
"""
from __future__ import annotations

import os
from typing import Callable, List, Optional

import numpy as np

from ..io import Dataset

__all__ = ["DatasetFolder", "ImageFolder", "has_valid_extension",
           "make_dataset", "default_loader", "pil_loader", "IMG_EXTENSIONS"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                  ".tif", ".tiff", ".webp", ".npy")


def has_valid_extension(filename: str, extensions) -> bool:
    """(reference folder.py:26) case-insensitive suffix check."""
    return filename.lower().endswith(tuple(extensions))


def pil_loader(path: str):
    from PIL import Image

    with open(path, "rb") as f:
        img = Image.open(f)
        return img.convert("RGB")


def npy_loader(path: str):
    return np.load(path)


def default_loader(path: str):
    """(reference folder.py:301) .npy → numpy; images → PIL RGB."""
    if path.lower().endswith(".npy"):
        return npy_loader(path)
    return pil_loader(path)


def make_dataset(directory: str, class_to_idx, extensions,
                 is_valid_file: Optional[Callable] = None):
    """(reference folder.py:43) expand ``root/class_x/**/*.ext`` into
    [(path, class_idx)] — nested subdirectories included."""
    samples = []
    directory = os.path.expanduser(directory)
    if (extensions is None) == (is_valid_file is None):
        raise ValueError(
            "exactly one of extensions / is_valid_file must be given")
    if is_valid_file is None:
        def is_valid_file(p):
            return has_valid_extension(p, extensions)
    for target in sorted(class_to_idx):
        d = os.path.join(directory, target)
        if not os.path.isdir(d):
            continue
        for root, _, fnames in sorted(os.walk(d, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root, fname)
                if is_valid_file(path):
                    samples.append((path, class_to_idx[target]))
    return samples


class DatasetFolder(Dataset):
    """Labeled tree dataset: ``root/class_name/*.ext`` → (sample,
    class_idx) (reference folder.py:66).

    Attributes match the reference: ``classes`` (sorted class names),
    ``class_to_idx``, ``samples`` [(path, idx)], ``targets``.
    """

    def __init__(self, root: str, loader: Optional[Callable] = None,
                 extensions=None, transform=None, target_transform=None,
                 is_valid_file: Optional[Callable] = None):
        super().__init__()
        self.root = root
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        classes, class_to_idx = self._find_classes(root)
        samples = make_dataset(root, class_to_idx, extensions,
                               is_valid_file)
        if not samples:
            raise RuntimeError(
                f"Found 0 files in subfolders of: {root}\n"
                f"Supported extensions are: {extensions}")
        self.loader = loader or default_loader
        self.extensions = extensions
        self.classes = classes
        self.class_to_idx = class_to_idx
        self.samples = samples
        self.targets = [s[1] for s in samples]
        self.transform = transform
        self.target_transform = target_transform

    def _find_classes(self, dir: str):
        """(reference folder.py:241) immediate subdirs = classes."""
        classes = sorted(e.name for e in os.scandir(dir) if e.is_dir())
        if not classes:
            raise RuntimeError(f"no class folders found in {dir}")
        return classes, {c: i for i, c in enumerate(classes)}

    def __getitem__(self, index):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        if self.target_transform is not None:
            target = self.target_transform(target)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Unlabeled tree dataset: every valid file under ``root``
    (reference folder.py:310). ``__getitem__`` returns ``[sample]``."""

    def __init__(self, root: str, loader: Optional[Callable] = None,
                 extensions=None, transform=None,
                 is_valid_file: Optional[Callable] = None):
        super().__init__()
        self.root = root
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if is_valid_file is None:
            exts = extensions

            def is_valid_file(p):
                return has_valid_extension(p, exts)
        samples: List[str] = []
        for r, _, fnames in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(r, fname)
                if is_valid_file(path):
                    samples.append(path)
        if not samples:
            raise RuntimeError(
                f"Found 0 files in subfolders of: {root}\n"
                f"Supported extensions are: {extensions}")
        self.loader = loader or default_loader
        self.samples = samples
        self.transform = transform

    def __getitem__(self, index):
        sample = self.loader(self.samples[index])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
