from .lenet import LeNet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    wide_resnet50_2, wide_resnet101_2,
)
