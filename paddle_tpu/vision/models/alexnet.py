"""AlexNet (reference: python/paddle/vision/models/alexnet.py)."""
from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["AlexNet", "alexnet"]


class AlexNet(nn.Layer):
    def __init__(self, num_classes: int = 1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, kernel_size=11, stride=4, padding=2),
            nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2),
            nn.Conv2D(64, 192, kernel_size=5, padding=2),
            nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2),
            nn.Conv2D(192, 384, kernel_size=3, padding=1),
            nn.ReLU(),
            nn.Conv2D(384, 256, kernel_size=3, padding=1),
            nn.ReLU(),
            nn.Conv2D(256, 256, kernel_size=3, padding=1),
            nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2),
        )
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5),
            nn.Linear(256 * 6 * 6, 4096),
            nn.ReLU(),
            nn.Dropout(0.5),
            nn.Linear(4096, 4096),
            nn.ReLU(),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        x = self.avgpool(self.features(x))
        x = x.reshape([x.shape[0], -1])
        return self.classifier(x)


def alexnet(pretrained: bool = False, **kwargs) -> AlexNet:
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return AlexNet(**kwargs)
