"""DenseNet (reference: python/paddle/vision/models/densenet.py —
dense blocks with concatenated features + transition downsampling)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, inp, growth, bn_size, dropout):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(inp)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(inp, bn_size * growth, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        y = self.conv1(self.relu(self.norm1(x)))
        y = self.conv2(self.relu(self.norm2(y)))
        if self.dropout is not None:
            y = self.dropout(y)
        return paddle.concat([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, inp, out):
        super().__init__()
        self.norm = nn.BatchNorm2D(inp)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(inp, out, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        if layers not in _CFG:
            raise ValueError(f"layers must be one of {list(_CFG)}")
        num_init, growth, blocks = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        ch = num_init
        feats = []
        for i, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if i != len(blocks) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch = ch // 2
        self.features = nn.Sequential(*feats)
        self.norm_final = nn.BatchNorm2D(ch)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.relu(self.norm_final(self.features(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(paddle.flatten(x, 1))
        return x


def _make(layers):
    def f(pretrained=False, **kwargs):
        return DenseNet(layers=layers, **kwargs)

    f.__name__ = f"densenet{layers}"
    return f


densenet121 = _make(121)
densenet161 = _make(161)
densenet169 = _make(169)
densenet201 = _make(201)
densenet264 = _make(264)
