"""GoogLeNet / Inception v1 (reference:
python/paddle/vision/models/googlenet.py — inception modules with four
parallel branches, two auxiliary classifiers)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["GoogLeNet", "googlenet"]


class _ConvBN(nn.Layer):
    def __init__(self, inp, out, k, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(inp, out, k, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(out)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class Inception(nn.Layer):
    def __init__(self, inp, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _ConvBN(inp, c1, 1)
        self.b2 = nn.Sequential(_ConvBN(inp, c3r, 1),
                                _ConvBN(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_ConvBN(inp, c5r, 1),
                                _ConvBN(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _ConvBN(inp, proj, 1))

    def forward(self, x):
        return paddle.concat(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, padding=1),
            _ConvBN(64, 64, 1), _ConvBN(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.i3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)
            # two aux heads (train-time deep supervision)
            self.aux1 = self._aux(512, num_classes)
            self.aux2 = self._aux(528, num_classes)

    @staticmethod
    def _aux(inp, num_classes):
        return nn.Sequential(
            nn.AdaptiveAvgPool2D(4), _ConvBN(inp, 128, 1),
            nn.Flatten(), nn.Linear(128 * 16, 1024), nn.ReLU(),
            nn.Dropout(0.7), nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        aux1 = self.aux1(x) if self.num_classes > 0 and self.training \
            else None
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 and self.training \
            else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(paddle.flatten(x, 1)))
            return x, aux1, aux2
        return x


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
