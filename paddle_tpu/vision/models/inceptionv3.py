"""Inception v3 (reference: python/paddle/vision/models/inceptionv3.py —
factorized convolutions: InceptionA-E blocks, 299x299 input)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["InceptionV3", "inception_v3"]


class _ConvBN(nn.Layer):
    def __init__(self, inp, out, k, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(inp, out, k, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(out)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _IncA(nn.Layer):
    def __init__(self, inp, pool_feat):
        super().__init__()
        self.b1 = _ConvBN(inp, 64, 1)
        self.b5 = nn.Sequential(_ConvBN(inp, 48, 1),
                                _ConvBN(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_ConvBN(inp, 64, 1),
                                _ConvBN(64, 96, 3, padding=1),
                                _ConvBN(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(inp, pool_feat, 1))

    def forward(self, x):
        return paddle.concat([self.b1(x), self.b5(x), self.b3(x),
                              self.bp(x)], axis=1)


class _IncB(nn.Layer):  # grid reduction 35->17
    def __init__(self, inp):
        super().__init__()
        self.b3 = _ConvBN(inp, 384, 3, stride=2)
        self.b3d = nn.Sequential(_ConvBN(inp, 64, 1),
                                 _ConvBN(64, 96, 3, padding=1),
                                 _ConvBN(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b3d(x), self.pool(x)],
                             axis=1)


class _IncC(nn.Layer):  # 7x1/1x7 factorized
    def __init__(self, inp, ch7):
        super().__init__()
        self.b1 = _ConvBN(inp, 192, 1)
        self.b7 = nn.Sequential(
            _ConvBN(inp, ch7, 1),
            _ConvBN(ch7, ch7, (1, 7), padding=(0, 3)),
            _ConvBN(ch7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _ConvBN(inp, ch7, 1),
            _ConvBN(ch7, ch7, (7, 1), padding=(3, 0)),
            _ConvBN(ch7, ch7, (1, 7), padding=(0, 3)),
            _ConvBN(ch7, ch7, (7, 1), padding=(3, 0)),
            _ConvBN(ch7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(inp, 192, 1))

    def forward(self, x):
        return paddle.concat([self.b1(x), self.b7(x), self.b7d(x),
                              self.bp(x)], axis=1)


class _IncD(nn.Layer):  # grid reduction 17->8
    def __init__(self, inp):
        super().__init__()
        self.b3 = nn.Sequential(_ConvBN(inp, 192, 1),
                                _ConvBN(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _ConvBN(inp, 192, 1),
            _ConvBN(192, 192, (1, 7), padding=(0, 3)),
            _ConvBN(192, 192, (7, 1), padding=(3, 0)),
            _ConvBN(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b7(x), self.pool(x)],
                             axis=1)


class _IncE(nn.Layer):  # expanded filter bank
    def __init__(self, inp):
        super().__init__()
        self.b1 = _ConvBN(inp, 320, 1)
        self.b3_1 = _ConvBN(inp, 384, 1)
        self.b3_2a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bd_1 = nn.Sequential(_ConvBN(inp, 448, 1),
                                  _ConvBN(448, 384, 3, padding=1))
        self.bd_2a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.bd_2b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(inp, 192, 1))

    def forward(self, x):
        a = self.b3_1(x)
        b = self.bd_1(x)
        return paddle.concat(
            [self.b1(x),
             paddle.concat([self.b3_2a(a), self.b3_2b(a)], axis=1),
             paddle.concat([self.bd_2a(b), self.bd_2b(b)], axis=1),
             self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncB(288),
            _IncC(768, 128), _IncC(768, 160), _IncC(768, 160),
            _IncC(768, 192),
            _IncD(768),
            _IncE(1280), _IncE(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(paddle.flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
