"""MobileNetV1 + V3 (reference: python/paddle/vision/models/
mobilenetv1.py depthwise-separable stacks; mobilenetv3.py inverted
residuals with squeeze-excitation + hardswish)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

__all__ = ["MobileNetV1", "mobilenet_v1", "MobileNetV3Small",
           "MobileNetV3Large", "mobilenet_v3_small", "mobilenet_v3_large"]


class _DWSep(nn.Layer):
    def __init__(self, inp, out, stride):
        super().__init__()
        self.dw = nn.Sequential(
            nn.Conv2D(inp, inp, 3, stride=stride, padding=1, groups=inp,
                      bias_attr=False),
            nn.BatchNorm2D(inp), nn.ReLU())
        self.pw = nn.Sequential(
            nn.Conv2D(inp, out, 1, bias_attr=False),
            nn.BatchNorm2D(out), nn.ReLU())

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [(c(32), c(64), 1), (c(64), c(128), 2),
               (c(128), c(128), 1), (c(128), c(256), 2),
               (c(256), c(256), 1), (c(256), c(512), 2)] + \
            [(c(512), c(512), 1)] * 5 + \
            [(c(512), c(1024), 2), (c(1024), c(1024), 1)]
        self.stem = nn.Sequential(
            nn.Conv2D(3, c(32), 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(c(32)), nn.ReLU())
        self.blocks = nn.Sequential(
            *[_DWSep(i, o, s) for i, o, s in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(paddle.flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


class _SE(nn.Layer):
    def __init__(self, ch, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, ch // r, 1)
        self.fc2 = nn.Conv2D(ch // r, ch, 1)

    def forward(self, x):
        s = self.pool(x)
        s = F.relu(self.fc1(s))
        s = F.hardsigmoid(self.fc2(s))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, inp, hidden, out, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == out
        act_l = nn.Hardswish if act == "hs" else nn.ReLU
        layers = []
        if hidden != inp:
            layers += [nn.Conv2D(inp, hidden, 1, bias_attr=False),
                       nn.BatchNorm2D(hidden), act_l()]
        layers += [nn.Conv2D(hidden, hidden, k, stride=stride,
                             padding=k // 2, groups=hidden,
                             bias_attr=False),
                   nn.BatchNorm2D(hidden), act_l()]
        if use_se:
            layers.append(_SE(hidden))
        layers += [nn.Conv2D(hidden, out, 1, bias_attr=False),
                   nn.BatchNorm2D(out)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


_V3_SMALL = [
    # k, hidden, out, se, act, stride
    (3, 16, 16, True, "re", 2), (3, 72, 24, False, "re", 2),
    (3, 88, 24, False, "re", 1), (5, 96, 40, True, "hs", 2),
    (5, 240, 40, True, "hs", 1), (5, 240, 40, True, "hs", 1),
    (5, 120, 48, True, "hs", 1), (5, 144, 48, True, "hs", 1),
    (5, 288, 96, True, "hs", 2), (5, 576, 96, True, "hs", 1),
    (5, 576, 96, True, "hs", 1),
]
_V3_LARGE = [
    (3, 16, 16, False, "re", 1), (3, 64, 24, False, "re", 2),
    (3, 72, 24, False, "re", 1), (5, 72, 40, True, "re", 2),
    (5, 120, 40, True, "re", 1), (5, 120, 40, True, "re", 1),
    (3, 240, 80, False, "hs", 2), (3, 200, 80, False, "hs", 1),
    (3, 184, 80, False, "hs", 1), (3, 184, 80, False, "hs", 1),
    (3, 480, 112, True, "hs", 1), (3, 672, 112, True, "hs", 1),
    (5, 672, 160, True, "hs", 2), (5, 960, 160, True, "hs", 1),
    (5, 960, 160, True, "hs", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_ch, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 16, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(16), nn.Hardswish())
        blocks = []
        inp = 16
        for k, hidden, out, se, act, s in cfg:
            blocks.append(_V3Block(inp, hidden, out, k, s, se, act))
            inp = out
        self.blocks = nn.Sequential(*blocks)
        mid = cfg[-1][1]
        self.head_conv = nn.Sequential(
            nn.Conv2D(inp, mid, 1, bias_attr=False),
            nn.BatchNorm2D(mid), nn.Hardswish())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(mid, last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.head_conv(self.blocks(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(paddle.flatten(x, 1))
        return x


def _check_v3_scale(scale):
    # width multipliers below 1.0 need per-stage _make_divisible channel
    # plumbing; fail loudly instead of silently building the full net
    if scale != 1.0:
        raise NotImplementedError(
            f"MobileNetV3 scale={scale} is not supported (only 1.0); "
            "width multipliers would silently change every channel "
            "count")


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, num_classes=1000, with_pool=True, scale=1.0):
        _check_v3_scale(scale)
        super().__init__(_V3_SMALL, 1024, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, num_classes=1000, with_pool=True, scale=1.0):
        _check_v3_scale(scale)
        super().__init__(_V3_LARGE, 1280, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, **kwargs):
    return MobileNetV3Small(**kwargs)


def mobilenet_v3_large(pretrained=False, **kwargs):
    return MobileNetV3Large(**kwargs)
