"""MobileNetV2 (reference: python/paddle/vision/models/mobilenetv2.py —
inverted residual blocks with depthwise separable convs)."""
from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["MobileNetV2", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(nn.Sequential):
    def __init__(self, in_ch, out_ch, kernel_size=3, stride=1, groups=1):
        pad = (kernel_size - 1) // 2
        super().__init__(
            nn.Conv2D(in_ch, out_ch, kernel_size, stride, pad,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_ch),
            nn.ReLU6(),
        )


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(inp, hidden, kernel_size=1))
        layers.extend([
            # depthwise
            ConvBNReLU(hidden, hidden, stride=stride, groups=hidden),
            # linear pointwise
            nn.Conv2D(hidden, oup, 1, 1, 0, bias_attr=False),
            nn.BatchNorm2D(oup),
        ])
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000):
        super().__init__()
        cfg = [
            # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_ch = _make_divisible(32 * scale)
        last_ch = _make_divisible(1280 * max(1.0, scale))
        features = [ConvBNReLU(3, in_ch, stride=2)]
        for t, c, n, s in cfg:
            out_ch = _make_divisible(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    in_ch, out_ch, s if i == 0 else 1, t))
                in_ch = out_ch
        features.append(ConvBNReLU(in_ch, last_ch, kernel_size=1))
        self.features = nn.Sequential(*features)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Sequential(
            nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        x = x.reshape([x.shape[0], -1])
        return self.classifier(x)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV2(scale=scale, **kwargs)
