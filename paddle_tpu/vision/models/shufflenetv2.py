"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py —
channel-split units + channel shuffle)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0"]

_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512), 0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024), 1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024), 2.0: (24, 244, 488, 976, 2048),
}


def _shuffle(x, groups=2):
    return F.channel_shuffle(x, groups=groups)


def _act_layer(act):
    if act == "relu":
        return nn.ReLU()
    if act in ("swish", "silu"):
        return nn.Silu()
    raise ValueError(f"unsupported activation {act!r} (relu|swish)")


class _Unit(nn.Layer):
    def __init__(self, inp, out, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = out // 2
        if stride == 1:
            main_in = inp // 2
        else:
            main_in = inp
            self.short = nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride=2, padding=1, groups=inp,
                          bias_attr=False),
                nn.BatchNorm2D(inp),
                nn.Conv2D(inp, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act_layer(act))
        self.main = nn.Sequential(
            nn.Conv2D(main_in, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act_layer(act),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                      groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act_layer(act))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.main(x2)], axis=1)
        else:
            out = paddle.concat([self.short(x), self.main(x)], axis=1)
        return _shuffle(out)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"scale must be one of {list(_STAGE_OUT)}")
        c0, c1, c2, c3, c_last = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, c0, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(c0), _act_layer(act),
            nn.MaxPool2D(3, stride=2, padding=1))
        stages = []
        inp = c0
        for out, repeat in ((c1, 4), (c2, 8), (c3, 4)):
            stages.append(_Unit(inp, out, stride=2, act=act))
            for _ in range(repeat - 1):
                stages.append(_Unit(out, out, stride=1, act=act))
            inp = out
        self.stages = nn.Sequential(*stages)
        self.final = nn.Sequential(
            nn.Conv2D(inp, c_last, 1, bias_attr=False),
            nn.BatchNorm2D(c_last), _act_layer(act))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c_last, num_classes)

    def forward(self, x):
        x = self.final(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(paddle.flatten(x, 1))
        return x


def _make(scale):
    def f(pretrained=False, **kwargs):
        return ShuffleNetV2(scale=scale, **kwargs)

    return f


shufflenet_v2_x0_25 = _make(0.25)
shufflenet_v2_x0_33 = _make(0.33)
shufflenet_v2_x0_5 = _make(0.5)
shufflenet_v2_x1_0 = _make(1.0)
shufflenet_v2_x1_5 = _make(1.5)
shufflenet_v2_x2_0 = _make(2.0)
