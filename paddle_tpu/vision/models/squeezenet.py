"""SqueezeNet (reference: python/paddle/vision/models/squeezenet.py —
fire modules: squeeze 1x1 then expand 1x1/3x3)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class Fire(nn.Layer):
    def __init__(self, inp, squeeze, e1x1, e3x3):
        super().__init__()
        self.squeeze = nn.Sequential(
            nn.Conv2D(inp, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(
            nn.Conv2D(squeeze, e1x1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(
            nn.Conv2D(squeeze, e3x3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        s = self.squeeze(x)
        return paddle.concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2),
                Fire(512, 64, 256, 256),
            )
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256),
            )
        else:
            raise ValueError("version must be '1.0' or '1.1'")
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5),
                nn.Conv2D(512, num_classes, 1), nn.ReLU(),
                nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
            x = paddle.flatten(x, 1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet(version="1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet(version="1.1", **kwargs)
