"""VGG family (reference: python/paddle/vision/models/vgg.py)."""
from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19"]

_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


def _make_layers(cfg, batch_norm: bool):
    layers, in_ch = [], 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(kernel_size=2, stride=2))
            continue
        layers.append(nn.Conv2D(in_ch, v, kernel_size=3, padding=1))
        if batch_norm:
            layers.append(nn.BatchNorm2D(v))
        layers.append(nn.ReLU())
        in_ch = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    def __init__(self, features, num_classes: int = 1000):
        super().__init__()
        self.features = features
        self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        self.classifier = nn.Sequential(
            nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(0.5),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(0.5),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        x = self.avgpool(self.features(x))
        x = x.reshape([x.shape[0], -1])
        return self.classifier(x)


def _vgg(cfg, batch_norm, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return VGG(_make_layers(_CFGS[cfg], batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("A", batch_norm, pretrained, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("B", batch_norm, pretrained, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("D", batch_norm, pretrained, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("E", batch_norm, pretrained, **kwargs)
