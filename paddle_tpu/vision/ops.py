"""Vision ops (reference: python/paddle/vision/ops.py — roi_align, nms,
deform_conv2d, box utilities). Core subset implemented with jnp."""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import eager_apply, as_tensor_args

__all__ = ["nms", "box_coder", "roi_align", "box_area", "box_iou"]


def box_area(boxes):
    def raw(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])

    return eager_apply("box_area", raw, as_tensor_args(boxes))


def _iou_matrix(a, b):
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / (area_a[:, None] + area_b[None] - inter + 1e-10)


def box_iou(boxes1, boxes2):
    return eager_apply("box_iou", _iou_matrix, as_tensor_args(boxes1, boxes2))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (host-side loop; detection post-processing is not a TPU
    hot path — the reference also runs it as a standalone op)."""
    b = np.asarray(boxes.numpy() if isinstance(boxes, Tensor) else boxes)
    s = np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores) \
        if scores is not None else np.arange(len(b))[::-1].astype(np.float32)
    order = np.argsort(-s)
    iou = np.asarray(_iou_matrix(jnp.asarray(b), jnp.asarray(b)))
    keep = []
    suppressed = np.zeros(len(b), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = False
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = np.asarray(boxes_num.numpy() if isinstance(boxes_num, Tensor)
                    else boxes_num)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def raw(feat, bxs):
        n_roi = bxs.shape[0]
        c = feat.shape[1]
        off = 0.5 if aligned else 0.0
        outs = []
        for r in range(n_roi):
            bi = int(batch_idx[r])
            x1, y1, x2, y2 = [bxs[r, k] * spatial_scale - off for k in range(4)]
            ys = y1 + (jnp.arange(oh) + 0.5) * (y2 - y1) / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * (x2 - x1) / ow
            y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, feat.shape[2] - 1)
            x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, feat.shape[3] - 1)
            y1i = jnp.clip(y0 + 1, 0, feat.shape[2] - 1)
            x1i = jnp.clip(x0 + 1, 0, feat.shape[3] - 1)
            wy = jnp.clip(ys - y0, 0, 1)
            wx = jnp.clip(xs - x0, 0, 1)
            f = feat[bi]
            v00 = f[:, y0][:, :, x0]
            v01 = f[:, y0][:, :, x1i]
            v10 = f[:, y1i][:, :, x0]
            v11 = f[:, y1i][:, :, x1i]
            top = v00 * (1 - wx)[None, None] + v01 * wx[None, None]
            bot = v10 * (1 - wx)[None, None] + v11 * wx[None, None]
            outs.append(top * (1 - wy)[None, :, None] + bot * wy[None, :, None])
        return jnp.stack(outs) if outs else jnp.zeros((0, c, oh, ow),
                                                      feat.dtype)

    return eager_apply("roi_align", raw, as_tensor_args(x, boxes))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    raise NotImplementedError("box_coder: detection-specific; not yet built")
