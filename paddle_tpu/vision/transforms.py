"""Image transforms (reference: python/paddle/vision/transforms).

numpy-based HWC transforms; Compose chains them. Only the commonly used
subset for the anchor configs; functional forms under ``F``-style names.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "to_tensor", "normalize",
           "resize", "hflip", "vflip", "center_crop"]


def to_tensor(pic, data_format="CHW"):
    arr = np.asarray(pic, np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.max() > 1.5:  # uint8 range
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return arr


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    img = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def resize(img, size, interpolation="bilinear"):
    import jax
    import jax.numpy as jnp

    arr = np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[-1] not in (1, 3)
    if isinstance(size, int):
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    if chw:
        target = (arr.shape[0], size[0], size[1])
    elif arr.ndim == 3:
        target = (size[0], size[1], arr.shape[2])
    else:
        target = tuple(size)
    method = {"bilinear": "linear", "nearest": "nearest",
              "bicubic": "cubic"}[interpolation]
    return np.asarray(jax.image.resize(jnp.asarray(arr, jnp.float32), target,
                                       method=method))


def hflip(img):
    arr = np.asarray(img)
    return arr[..., ::-1] if arr.ndim == 3 and arr.shape[0] in (1, 3) \
        else arr[:, ::-1]


def vflip(img):
    arr = np.asarray(img)
    return arr[..., ::-1, :] if arr.ndim == 3 and arr.shape[0] in (1, 3) \
        else arr[::-1]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[-1] not in (1, 3)
    h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
    th, tw = output_size
    i = (h - th) // 2
    j = (w - tw) // 2
    if chw:
        return arr[:, i:i + th, j:j + tw]
    return arr[i:i + th, j:j + tw]


class _Transform:
    def __call__(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(_Transform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Normalize(_Transform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(_Transform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(_Transform):
    def __init__(self, size, keys=None):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop(_Transform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and \
            arr.shape[-1] not in (1, 3)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(_Transform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(_Transform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if random.random() < self.prob else img


class Transpose(_Transform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class BrightnessTransform(_Transform):
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        factor = 1 + random.uniform(-self.value, self.value)
        return np.clip(np.asarray(img, np.float32) * factor, 0,
                       255 if np.asarray(img).max() > 1.5 else 1.0)
