"""Test harness: run everything on a virtual 8-device CPU mesh.

Port of the reference's "distributed tests without a real cluster" trick
(reference: test/legacy_test/test_dist_base.py:962 — localhost multi-proc;
and the fake-device precedent paddle/phi/backends/custom/fake_cpu_device.h):
here a single process gets 8 virtual XLA host devices, which exercises the
full sharding/collective path without TPU hardware.

Must run before jax initializes a backend. The container pins
JAX_PLATFORMS=axon via sitecustomize, so we override programmatically too.

Shared mesh fixtures (session-scoped — the mesh objects are immutable
value types): ``virtual_devices`` (the 8 CPU devices), ``mesh8`` /
``mesh2x4`` (plain ProcessMeshes) and ``fleet_mesh`` (the dp4 x mp2
hybrid mesh via fleet.init — the setup test_distributed/test_moe_ep and
the SPMD-pass tests all need).
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    # registered markers (no pytest.ini in this repo): ``slow`` is
    # excluded from tier-1 (`-m 'not slow'`); ``chaos`` tags the
    # deterministic fault-injection serving tests
    # (tests/test_serving_faults.py) — tier-1 RUNS them (they are not
    # slow), the marker exists so a chip run can select them alone
    # (`-m chaos`) before trusting a serving deploy
    config.addinivalue_line(
        "markers", "slow: long-running composition smoke, excluded "
                   "from tier-1 (-m 'not slow')")
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection serving "
                   "tests (ISSUE 11) — in tier-1, selectable alone "
                   "via -m chaos")


@pytest.fixture(autouse=True)
def _reseed():
    import paddle_tpu as paddle

    paddle.seed(2024)
    yield


@pytest.fixture(scope="session")
def virtual_devices():
    """The 8 virtual CPU devices (the SPMD-pass mesh substrate)."""
    devs = jax.devices("cpu")
    assert len(devs) >= 8, "xla_force_host_platform_device_count not set"
    return devs[:8]


@pytest.fixture(scope="session")
def mesh8(virtual_devices):
    import paddle_tpu.distributed as dist

    return dist.ProcessMesh(list(range(8)), dim_names=["x"])


@pytest.fixture(scope="session")
def mesh2x4(virtual_devices):
    import paddle_tpu.distributed as dist

    return dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                            dim_names=["dp", "mp"])


@pytest.fixture(scope="session")
def fleet_mesh(virtual_devices):
    """The dp4 x mp2 hybrid mesh, fleet-initialized once per session
    (drops the per-test fleet.init boilerplate the distributed/MoE
    tests used to carry)."""
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        **strategy.hybrid_configs,
        "dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
        "sharding_degree": 1, "sep_degree": 1,
    }
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group().mesh
