"""Test harness: run everything on a virtual 8-device CPU mesh.

Port of the reference's "distributed tests without a real cluster" trick
(reference: test/legacy_test/test_dist_base.py:962 — localhost multi-proc;
and the fake-device precedent paddle/phi/backends/custom/fake_cpu_device.h):
here a single process gets 8 virtual XLA host devices, which exercises the
full sharding/collective path without TPU hardware.

Must run before jax initializes a backend. The container pins
JAX_PLATFORMS=axon via sitecustomize, so we override programmatically too.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reseed():
    import paddle_tpu as paddle

    paddle.seed(2024)
    yield
