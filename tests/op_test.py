"""OpTest-style fixture.

Port of the reference's op unit-test pattern (reference:
test/legacy_test/op_test.py:420 class OpTest): numpy-reference forward
comparison in both eager and jit modes, and analytic-vs-numeric gradient
checks via central finite differences (reference op_test.py:2963
check_grad).
"""
from __future__ import annotations

import numpy as np


def check_output(op_fn, np_fn, inputs, atol=1e-5, rtol=1e-5, kwargs=None):
    """Run op eagerly and (via jit trace) compare against numpy reference."""
    import paddle_tpu as paddle

    kwargs = kwargs or {}
    tensors = [paddle.to_tensor(a) for a in inputs]
    out = op_fn(*tensors, **kwargs)
    ref = np_fn(*inputs, **kwargs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    refs = ref if isinstance(ref, (tuple, list)) else [ref]
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(o.numpy(), r, atol=atol, rtol=rtol)


def check_grad(op_fn, inputs, atol=5e-3, rtol=5e-3, eps=1e-3, kwargs=None,
               grad_idx=None):
    """Analytic grads (tape backward) vs central finite differences."""
    import paddle_tpu as paddle

    kwargs = kwargs or {}
    inputs = [np.asarray(a, np.float64) for a in inputs]
    n = len(inputs)
    grad_idx = range(n) if grad_idx is None else grad_idx

    def loss_np(arrs):
        tensors = [paddle.to_tensor(a) for a in arrs]
        out = op_fn(*tensors, **kwargs)
        outs = out if isinstance(out, (tuple, list)) else [out]
        # deterministic scalarization: sum of all float outputs
        total = 0.0
        for o in outs:
            if o.dtype.is_floating_point:
                total = total + float(np.sum(o.numpy()))
        return total

    tensors = [paddle.to_tensor(a, stop_gradient=False) for a in inputs]
    out = op_fn(*tensors, **kwargs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    loss = None
    for o in outs:
        if o.dtype.is_floating_point:
            s = o.sum()
            loss = s if loss is None else loss + s
    loss.backward()

    for i in grad_idx:
        analytic = tensors[i].grad.numpy() if tensors[i].grad is not None \
            else np.zeros_like(inputs[i])
        numeric = np.zeros_like(inputs[i])
        flat = inputs[i].reshape(-1)
        num_flat = numeric.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            up = loss_np(inputs)
            flat[j] = orig - eps
            down = loss_np(inputs)
            flat[j] = orig
            num_flat[j] = (up - down) / (2 * eps)
        np.testing.assert_allclose(
            analytic, numeric, atol=atol, rtol=rtol,
            err_msg=f"grad mismatch for input {i}")
