"""Regression tests for round-1 advisor findings (ADVICE.md)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_structured_param_names():
    """Params registered in Layers get stable structured names, not the
    process-global generated_tensor_N counter (ADVICE item 4)."""
    lin = nn.Linear(3, 4)
    names = {n: p.name for n, p in lin.named_parameters()}
    assert all(not v.startswith("generated_tensor_") for v in names.values()), names
    assert names["weight"].endswith(".weight")
    # creating unrelated tensors must not shift layer param names
    _ = [paddle.to_tensor(np.zeros(2, np.float32)) for _ in range(5)]
    lin2 = nn.Linear(3, 4)
    # same class → same prefix family, deterministic numbering
    assert lin.parameters()[0].name != lin2.parameters()[0].name


def test_optimizer_state_roundtrip_fresh_process_names():
    """Optimizer state keyed by structured names survives a reload into a
    freshly constructed model (simulating a new process)."""
    from paddle_tpu.framework import unique_name

    def build():
        # simulate a fresh process: unique_name.guard resets construction
        # counters (reference: base/unique_name.py guard())
        with unique_name.guard():
            paddle.seed(7)
            m = nn.Linear(4, 2)
            o = paddle.optimizer.Adam(0.01, parameters=m.parameters())
        return m, o

    m1, o1 = build()
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4).astype("float32"))
    loss = m1(x).mean()
    loss.backward()
    o1.step()
    sd = o1.state_dict()

    m2, o2 = build()
    o2.set_state_dict(sd)
    for p in o2._parameter_list:
        st = o2._accumulators.get(id(p))
        assert st is not None, f"no state restored for {p.name}"
        assert "moment1" in st or "moment" in st or len(st) > 0


def test_multi_precision_master_weights_roundtrip():
    """fp32 master weights survive save/restore (ADVICE item 3)."""
    paddle.seed(0)
    m = nn.Linear(4, 2)
    # cast params to bf16 (O2-style)
    import jax.numpy as jnp
    for p in m.parameters():
        p._rebind(p._data.astype(jnp.bfloat16))
    o = paddle.optimizer.AdamW(0.01, parameters=m.parameters(),
                               multi_precision=True)
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4).astype("float32"))
    loss = m(x.astype("bfloat16")).astype("float32").mean()
    loss.backward()
    o.step()
    masters = {p.name: np.asarray(o._accumulators[id(p)]["_master"],
                                  dtype=np.float32)
               for p in m.parameters()}
    sd = o.state_dict()
    assert any(k.endswith("__master") for k in sd), list(sd)

    o2 = paddle.optimizer.AdamW(0.01, parameters=m.parameters(),
                                multi_precision=True)
    o2.set_state_dict(sd)
    for p in m.parameters():
        st = o2._accumulators[id(p)]
        assert "_master" in st, f"master dropped for {p.name}"
        np.testing.assert_allclose(
            np.asarray(st["_master"], dtype=np.float32), masters[p.name])


def test_linear_warmup_get_lr_idempotent():
    """Extra get_lr() calls must not advance the wrapped scheduler
    (ADVICE item 5)."""
    from paddle_tpu.optimizer.lr import LinearWarmup, ExponentialDecay

    inner = ExponentialDecay(learning_rate=1.0, gamma=0.5)
    sched = LinearWarmup(inner, warmup_steps=2, start_lr=0.0, end_lr=1.0)
    for _ in range(3):
        sched.step()  # past warmup
    v1 = sched.get_lr()
    v2 = sched.get_lr()
    v3 = sched.get_lr()
    assert v1 == v2 == v3
    # stepping advances deterministically: epoch offset drives the child
    sched.step()
    assert sched.get_lr() == pytest.approx(v1 * 0.5)


def test_recompute_swaps_buffers_batchnorm():
    """A buffer-mutating layer (BatchNorm, training mode) inside a
    recompute region must not leak tracers into live buffers, and running
    stats must still update (ADVICE item 2)."""
    from paddle_tpu.distributed.fleet.recompute import recompute

    paddle.seed(0)

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)
            self.bn = nn.BatchNorm1D(4)

        def forward(self, x):
            return self.bn(self.lin(x))

    blk = Block()
    blk.train()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(16, 4).astype("float32"))
    x.stop_gradient = False

    mean_before = np.asarray(blk.bn._mean._data).copy()
    out = recompute(blk, x)
    loss = out.mean()
    loss.backward()
    # buffers hold concrete arrays (no leaked tracers)
    import jax

    for name, b in blk.named_buffers():
        assert not isinstance(b._data, jax.core.Tracer), name
        np.asarray(b._data)  # must be materializable
    # running stats actually updated
    mean_after = np.asarray(blk.bn._mean._data)
    assert not np.allclose(mean_before, mean_after)
    # grads flowed
    assert blk.lin.weight.grad is not None

    # parity with non-recomputed execution
    paddle.seed(0)
    blk2 = Block()
    blk2.train()
    for (n1, p1), (_, p2) in zip(blk.named_parameters(),
                                 blk2.named_parameters()):
        p2._rebind(p1._data)
    x2 = paddle.to_tensor(
        np.random.RandomState(0).randn(16, 4).astype("float32"))
    x2.stop_gradient = False
    out2 = blk2(x2)
    np.testing.assert_allclose(np.asarray(out._data), np.asarray(out2._data),
                               rtol=1e-5, atol=1e-5)


def test_amp_o2_autocast_keeps_bf16_through_promotion():
    """O2 must not silently run fp32 (r5 review): fp32 activations
    promote bf16-decorated params back to f32 at every op unless the O2
    autocast casts non-blacklist op inputs to bf16."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    class Toy(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 8)
            self.out = nn.Linear(8, 2)
            self.seen = []

        def forward(self, x):
            h = F.relu(self.fc(x))  # relu is in NO amp list
            self.seen.append(str(h.dtype))
            return self.out(h)

    net = Toy()
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    net, opt = paddle.amp.decorate(net, opt, level="O2", dtype="bfloat16")
    step = paddle.jit.TrainStep(net, nn.CrossEntropyLoss(), opt,
                                amp_level="O2")
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 8).astype("float32"))
    y = paddle.to_tensor(np.array([0, 1, 0, 1]))
    loss = step([x], [y])
    assert np.isfinite(float(loss.numpy()))
    # the post-relu activation stayed bf16 (not promoted to f32)
    assert any("bfloat16" in d for d in net.seen), net.seen


def test_reduce_scatter_single_host_semantics():
    """reduce_scatter degenerate path still binds the right slice."""
    import paddle_tpu.distributed as dist

    t = paddle.to_tensor(np.zeros(4, np.float32))
    src = [paddle.to_tensor(np.arange(4, dtype=np.float32))]
    dist.reduce_scatter(t, src)
    np.testing.assert_allclose(np.asarray(t._data),
                               np.arange(4, dtype=np.float32))
