"""Fleet alerting (ISSUE 16): rule engine semantics on scripted
telemetry trajectories, journal integration, and the serve_top
history rendering.

Tier-1 acceptance pins:

- the burn-rate rule FIRES after 3 sustained breach ticks and
  RESOLVES on the first clear tick of a scripted SLO trajectory,
  with both transitions journaled as ``alert`` lifecycle events and
  counted under ``alert.{fired,resolved}``
  (``TestBurnRateTrajectory``);
- metric-name thresholds (``hbm.bytes_in_use > 0.9 *
  hbm.bytes_limit``, ``fleet.replicas_alive < fleet.replicas``) and
  the preemption rate-spike rule (``TestRuleKinds``);
- ``serve_top --history`` renders sparklines + alert markers from a
  series dump, and ``serve_top`` folds journal alert events into the
  dashboard (``TestServeTopHistory``).
"""
import importlib.util
import os
import sys

import pytest

from paddle_tpu.profiler import (AlertEngine, Rule, TimeSeriesSampler,
                                 default_rules, stats)
from paddle_tpu.serving import ManualClock
from paddle_tpu.serving.journal import FlightRecorder

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import serve_top  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_registry():
    stats.enable()
    stats.reset()
    yield
    stats.reset()


class _Scripted:
    """A tick source driven by a scripted value sequence."""

    def __init__(self, **series):
        self.series = series
        self.i = -1

    def __call__(self):
        self.i = min(self.i + 1, max(len(v) for v in
                                     self.series.values()) - 1)
        return ({}, {k: v[min(self.i, len(v) - 1)]
                     for k, v in self.series.items()}, {})


def _drive(source, rules, journal=None, n=None):
    clk = ManualClock()
    eng = AlertEngine(rules, journal=journal)
    s = TimeSeriesSampler(interval_ms=100, window=64, clock=clk,
                          source=source, enabled=True)
    s.attach_alerts(eng)
    n = n if n is not None else max(len(v) for v
                                    in source.series.values())
    for _ in range(n):
        s.tick()
        clk.advance(0.1)
    return s, eng


class TestRuleValidation:
    def test_bad_op_kind_for_ticks(self):
        with pytest.raises(ValueError):
            Rule("r", "m", op=">=")
        with pytest.raises(ValueError):
            Rule("r", "m", kind="derivative")
        with pytest.raises(ValueError):
            Rule("r", "m", for_ticks=0)

    def test_default_rules_cover_the_issue_set(self):
        names = {r.name for r in default_rules()}
        assert {"slo-burn", "hbm-pressure", "preemption-spike",
                "fleet-replica-down"} <= names
        lit = next(r for r in default_rules(3)
                   if r.name == "fleet-replica-down")
        assert lit.threshold == 3.0


class TestBurnRateTrajectory:
    def test_fires_after_sustained_window_and_resolves(self):
        """The scripted SLO trajectory: healthy -> 4 breach ticks ->
        recovery. for_ticks=3 means tick index 4 (the 3rd consecutive
        breach) fires; the first clear tick resolves."""
        jr = FlightRecorder()
        src = _Scripted(**{"slo.burn_rate":
                           [0.5, 1.0, 3.0, 3.5, 4.0, 3.0, 0.5, 0.5]})
        rules = [Rule("slo-burn", "slo.burn_rate", ">", 2.0,
                      for_ticks=3)]
        s, eng = _drive(src, rules, journal=jr)
        assert [h["state"] for h in eng.history] \
            == ["firing", "resolved"]
        assert eng.active == {}
        # the fire tick is the 3rd consecutive breach (index 4), the
        # resolve tick the first clear one (index 6)
        marks = [bool(t.get("alerts")) for t in s.ticks()]
        assert marks == [False, False, False, False,
                         True, True, False, False]
        assert stats.counter("alert.fired").value == 1
        assert stats.counter("alert.resolved").value == 1
        assert stats.gauge("alert.active").value == 0
        evs = [e for e in jr.events() if e["ev"] == "alert"]
        assert [e["state"] for e in evs] == ["firing", "resolved"]
        assert evs[0]["name"] == "slo-burn"
        assert evs[0]["value"] == pytest.approx(4.0)
        assert evs[0]["threshold"] == pytest.approx(2.0)
        assert evs[0]["rid"] == -1

    def test_streak_resets_on_clear_tick(self):
        src = _Scripted(**{"slo.burn_rate":
                           [3.0, 3.0, 0.5, 3.0, 3.0, 0.5] * 2})
        rules = [Rule("slo-burn", "slo.burn_rate", ">", 2.0,
                      for_ticks=3)]
        _s, eng = _drive(src, rules)
        assert eng.history == []  # never 3 consecutive breaches

    def test_absent_metric_never_breaches(self):
        src = _Scripted(**{"other.gauge": [1.0, 1.0, 1.0]})
        _s, eng = _drive(src, [Rule("r", "slo.burn_rate", ">", 0.0)])
        assert eng.history == [] and eng.active == {}


class TestRuleKinds:
    def test_metric_name_threshold_hbm(self):
        src = _Scripted(**{"hbm.bytes_in_use":
                           [100.0, 800.0, 950.0, 500.0],
                           "hbm.bytes_limit": [1000.0] * 4})
        rules = [Rule("hbm", "hbm.bytes_in_use", ">",
                      "hbm.bytes_limit", scale=0.9)]
        _s, eng = _drive(src, rules)
        assert [h["state"] for h in eng.history] \
            == ["firing", "resolved"]
        assert eng.history[0]["threshold"] == pytest.approx(900.0)

    def test_replica_down_vs_registered_fleet_size(self):
        src = _Scripted(**{"fleet.replicas_alive":
                           [2.0, 2.0, 1.0, 1.0, 2.0],
                           "fleet.replicas": [2.0] * 5})
        rules = [r for r in default_rules()
                 if r.name == "fleet-replica-down"]
        s, eng = _drive(src, rules)
        assert [h["state"] for h in eng.history] \
            == ["firing", "resolved"]
        # active while a replica is down
        assert [bool(t.get("alerts")) for t in s.ticks()] \
            == [False, False, True, True, False]

    def test_rate_spike_rule(self):
        clk = ManualClock()
        eng = AlertEngine([Rule("spike", "serving.preemptions", ">",
                                kind="spike", scale=3.0)])
        s = TimeSeriesSampler(interval_ms=100, window=64, clock=clk,
                              enabled=True).attach_alerts(eng)
        # steady 1 preemption/s for 5 ticks, then a 20x burst
        for _ in range(5):
            stats.inc("serving.preemptions", 1)
            s.tick()
            clk.advance(1.0)
        assert eng.active == {}
        stats.inc("serving.preemptions", 20)
        s.tick()
        assert "spike" in eng.active
        clk.advance(1.0)
        stats.inc("serving.preemptions", 1)
        s.tick()
        assert eng.active == {}
        assert [h["state"] for h in eng.history] \
            == ["firing", "resolved"]

    def test_less_than_op(self):
        src = _Scripted(**{"slo.goodput": [0.99, 0.5, 0.99]})
        _s, eng = _drive(src, [Rule("low", "slo.goodput", "<", 0.9)])
        assert [h["state"] for h in eng.history] \
            == ["firing", "resolved"]


class TestServeTopHistory:
    def _dump(self, tmp_path):
        clk = ManualClock()
        jr = FlightRecorder()
        eng = AlertEngine([Rule("slo-burn", "slo.burn_rate", ">",
                                2.0, for_ticks=2)], journal=jr)
        src = _Scripted(**{
            "slo.burn_rate": [1.0, 3.0, 3.0, 3.0, 1.0, 1.0],
            "slo.goodput": [0.99, 0.7, 0.6, 0.6, 0.95, 0.99],
            "slo.queue_depth": [0, 4, 6, 5, 1, 0]})
        s = TimeSeriesSampler(interval_ms=100, window=64, clock=clk,
                              source=src, enabled=True)
        s.attach_alerts(eng)
        for _ in range(6):
            s.tick()
            clk.advance(0.1)
        p = str(tmp_path / "series.jsonl")
        s.dump_jsonl(p)
        jp = str(tmp_path / "journal.jsonl")
        jr.dump_jsonl(jp)
        return p, jp

    def test_render_history_sparklines_and_alert_marks(self, tmp_path):
        p, _ = self._dump(tmp_path)
        ticks = serve_top._ts_mod().load_jsonl(p)
        out = serve_top.render_history(ticks)
        assert "goodput" in out and "burn_rate" in out
        assert "queue" in out
        assert "slo-burn" in out  # fired-in-window listing
        alert_row = next(ln for ln in out.splitlines()
                         if "alerts" in ln)
        assert "!" in alert_row

    def test_history_cli(self, tmp_path, capsys):
        p, _ = self._dump(tmp_path)
        assert serve_top.main(["--history", p]) == 0
        out = capsys.readouterr().out
        assert "serve_top --history" in out and "goodput" in out

    def test_journal_alerts_in_dashboard(self, tmp_path):
        _, jp = self._dump(tmp_path)
        jm = serve_top._journal_mod()
        events, _extras = jm.load_jsonl(jp)
        s = serve_top.summarize(events)
        assert s["alerts_fired"] == 1 and s["alerts_resolved"] == 1
        assert s["alerts_active"] == []
        out = serve_top.render(s)
        assert "alerts: fired 1  resolved 1" in out

    def test_sparkline_scaling(self):
        assert serve_top.sparkline([0.0, 1.0], lo=0.0, hi=1.0) \
            == "▁█"
        assert serve_top.sparkline([None, 0.5], lo=0.0, hi=1.0)[0] \
            == " "
        assert serve_top.sparkline([]) == ""

    def test_watch_loop_manual_clock_no_sleep(self):
        import io

        clk = ManualClock()
        frames = []

        def render_once():
            frames.append(clk.now())
            return f"frame@{clk.now()}"

        buf = io.StringIO()
        rc = serve_top._watch_loop(render_once, 2.0, clk=clk,
                                   max_iters=3, out=buf)
        assert rc == 0
        assert frames == [0.0, 2.0, 4.0]  # cadence via the seam
        # clear-THEN-draw per frame, stable layout
        assert buf.getvalue().count("\033[2J\033[H") == 3

    def test_watch_loop_renders_once_without_interval(self):
        import io

        buf = io.StringIO()
        rc = serve_top._watch_loop(lambda: "once", 0.0,
                                   clk=ManualClock(), out=buf)
        assert rc == 0
        assert buf.getvalue() == "once\n"  # no clear codes one-shot
