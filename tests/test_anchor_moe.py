"""BASELINE config anchor #5: ERNIE-style MoE + sharding stage-3 +
expert all_to_all, end-to-end on the 8-device CPU mesh.

(reference anchors: BASELINE.md configs[4]; mechanism parity:
incubate/distributed/models/moe/moe_layer.py:263 MoE dispatch,
group_sharded_stage3.py:85 ZeRO-3, global_scatter/global_gather expert
all-to-all. Here EP = expert-dim sharding over the mesh so XLA inserts
the all-to-all; ZeRO-3 = GroupShardedStage3 param sharding over dp.)
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet


def test_moe_sharding3_trains():
    paddle.seed(0)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        **strategy.hybrid_configs,
        "dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
        "sharding_degree": 1, "sep_degree": 1,
    }
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    mesh = hcg.mesh

    from paddle_tpu.incubate.moe import MoELayer

    d_model, vocab, seq = 16, 64, 8

    class ErnieMoEBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(vocab, d_model)
            self.norm = nn.LayerNorm(d_model)
            self.moe = MoELayer(d_model=d_model, num_experts=4,
                                gate="gshard", d_hidden=32)
            self.head = nn.Linear(d_model, vocab)

        def forward(self, ids):
            h = self.embed(ids)
            h = h + self.moe(self.norm(h))
            return self.head(h)

    model = ErnieMoEBlock()
    # EP: shard the stacked expert dim over the mp axis → XLA inserts
    # the expert all-to-all (global_scatter/global_gather equivalent)
    st = model.moe.stacked
    for pname in ("w1", "b1", "w2", "b2"):
        pls = [dist.Replicate()] * mesh.ndim
        pls[mesh.dim_names.index("mp")] = dist.Shard(0)
        st._parameters[pname] = dist.shard_tensor(
            getattr(st, pname), mesh, pls)

    opt = paddle.optimizer.AdamW(5e-2, parameters=model.parameters())
    from paddle_tpu.distributed.fleet.meta_parallel.sharding \
        .sharding_optimizer import GroupShardedStage3

    wrapped = GroupShardedStage3(model, optimizer=opt, hcg=hcg)

    def loss_fn(logits, labels):
        return F.cross_entropy(logits.reshape([-1, vocab]),
                               labels.reshape([-1]))

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (8, seq))
    labels = rng.randint(0, vocab, (8, seq))

    def dp_shard(t):
        pls = [dist.Replicate()] * mesh.ndim
        pls[mesh.dim_names.index("dp")] = dist.Shard(0)
        return dist.shard_tensor(t, mesh, pls)

    losses = []
    for _ in range(6):
        loss = step([dp_shard(paddle.to_tensor(ids))],
                    [dp_shard(paddle.to_tensor(labels))])
        losses.append(float(loss.numpy()))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # learns the fixed batch
    # wrapped forward works too (stage-3 wrapper delegates)
    out = wrapped(paddle.to_tensor(ids))
    assert out.shape == [8, seq, vocab]
