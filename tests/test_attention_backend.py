"""Attention backend gating (VERDICT round 1: the head_dim % 128 gate
meant the Pallas flash kernel was never exercised — head_dim 64/96 are
valid; verified numerically on v5e)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.nn.functional import attention as attn_mod


def test_pallas_gate_accepts_common_head_dims(monkeypatch):
    monkeypatch.setattr(attn_mod, "_on_tpu", lambda: True)
    for hd in (64, 96, 128, 256):
        assert attn_mod._use_pallas(hd, 512, 512, False), hd
    # misaligned head dim, short/unaligned seqs, bias → XLA fallback
    assert not attn_mod._use_pallas(60, 512, 512, False)
    assert not attn_mod._use_pallas(64, 100, 512, False)
    assert not attn_mod._use_pallas(64, 512, 512, True)


def test_gate_off_tpu(monkeypatch):
    monkeypatch.setattr(attn_mod, "_on_tpu", lambda: False)
    assert not attn_mod._use_pallas(128, 512, 512, False)


def test_backend_recorded():
    q = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 16, 2, 8).astype("float32"))
    out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
    assert attn_mod.last_attention_backend() == "xla"  # CPU test host
    assert out.shape == [2, 16, 2, 8]
