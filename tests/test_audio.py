"""paddle.audio feature tests (reference: test/legacy_test audio feature
tests — librosa-convention checks)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.audio import functional as AF
from paddle_tpu.audio.features import (MFCC, LogMelSpectrogram,
                                       MelSpectrogram, Spectrogram)


def _sine(sr=8000, f=440.0, dur=0.5):
    t = np.arange(int(sr * dur)) / sr
    return np.sin(2 * np.pi * f * t).astype(np.float32)


class TestFunctional:
    def test_mel_hz_roundtrip(self):
        freqs = np.array([100.0, 440.0, 1000.0, 4000.0])
        np.testing.assert_allclose(
            AF.mel_to_hz(AF.hz_to_mel(freqs)), freqs, rtol=1e-6)
        np.testing.assert_allclose(
            AF.mel_to_hz(AF.hz_to_mel(freqs, htk=True), htk=True),
            freqs, rtol=1e-6)

    def test_fbank_shape_and_partition(self):
        fb = AF.compute_fbank_matrix(sr=8000, n_fft=256, n_mels=20)
        assert fb.shape == [20, 129]
        assert float(fb.numpy().min()) >= 0.0

    def test_window_shapes(self):
        for w in ("hann", "hamming", "blackman"):
            assert AF.get_window(w, 64).shape == [64]

    def test_power_to_db(self):
        x = paddle.to_tensor(np.array([1.0, 10.0, 100.0], np.float32))
        db = AF.power_to_db(x, top_db=None)
        np.testing.assert_allclose(db.numpy(), [0.0, 10.0, 20.0],
                                   atol=1e-4)


class TestFeatures:
    def test_spectrogram_peak_at_tone(self):
        sr, f = 8000, 1000.0
        spec = Spectrogram(n_fft=256, hop_length=128)(
            paddle.to_tensor(_sine(sr, f)))
        assert spec.shape[0] == 129
        mean_spec = spec.numpy().mean(axis=-1)
        peak_bin = int(mean_spec.argmax())
        expect_bin = round(f / (sr / 256))
        assert abs(peak_bin - expect_bin) <= 1

    def test_mel_logmel_mfcc_shapes(self):
        x = paddle.to_tensor(_sine())
        mel = MelSpectrogram(sr=8000, n_fft=256, n_mels=32)(x)
        assert mel.shape[0] == 32
        logmel = LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32)(x)
        assert logmel.shape == mel.shape
        mfcc = MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32)(x)
        assert mfcc.shape[0] == 13

    def test_batched_input(self):
        x = paddle.to_tensor(np.stack([_sine(), _sine(f=880.0)]))
        spec = Spectrogram(n_fft=256)(x)
        assert spec.shape[0] == 2 and spec.shape[1] == 129


class TestBackends:
    """reference: python/paddle/audio/backends/wave_backend.py save/load/
    info round-trip (PCM16 WAV over the stdlib wave module)."""

    def test_save_load_info_roundtrip(self, tmp_path):
        from paddle_tpu import audio

        sr = 8000
        wav = _sine(sr=sr, dur=0.25)[None, :]  # [1, time]
        p = str(tmp_path / "t.wav")
        audio.save(p, wav, sr)
        meta = audio.info(p)
        assert (meta.sample_rate, meta.num_channels,
                meta.bits_per_sample) == (sr, 1, 16)
        assert meta.num_samples == wav.shape[1]
        loaded, sr2 = audio.load(p)
        assert sr2 == sr
        np.testing.assert_allclose(loaded.numpy(), wav, atol=2e-4)

    def test_load_slice_and_channels_last(self, tmp_path):
        from paddle_tpu import audio

        wav = np.stack([_sine(f=440.0), _sine(f=220.0)])  # [2, time]
        p = str(tmp_path / "st.wav")
        audio.save(p, wav, 8000)
        part, _ = audio.load(p, frame_offset=100, num_frames=50,
                             channels_first=False)
        assert part.shape == [50, 2]
        np.testing.assert_allclose(part.numpy().T, wav[:, 100:150],
                                   atol=2e-4)

    def test_unnormalized_int16(self, tmp_path):
        from paddle_tpu import audio

        p = str(tmp_path / "i.wav")
        audio.save(p, _sine()[None, :], 8000)
        raw, _ = audio.load(p, normalize=False)
        assert raw.numpy().dtype == np.int16

    def test_backend_registry(self):
        from paddle_tpu.audio import backends
        import pytest

        assert backends.list_available_backends() == ["wave"]
        assert backends.get_current_backend() == "wave"
        backends.set_backend("wave")
        with pytest.raises(NotImplementedError, match="zero-egress"):
            backends.set_backend("soundfile")


class TestAudioDatasets:
    """reference: python/paddle/audio/datasets/{esc50,tess}.py protocol
    (synthetic-backed here — zero-egress)."""

    def test_esc50_folds_disjoint(self):
        from paddle_tpu.audio.datasets import ESC50

        train = ESC50(mode="train", split=1)
        dev = ESC50(mode="dev", split=1)
        # 50 classes x 5 clips x (4 train folds / 1 dev fold)
        assert len(train) == 50 * 5 * 4
        assert len(dev) == 50 * 5
        x, y = dev[0]
        assert x.dtype == np.float32 and x.ndim == 1
        assert 0 <= y < 50
        assert len(set(d[1] for d in [dev[i] for i in range(0, 250, 5)])) > 1

    def test_esc50_mfcc_feature(self):
        from paddle_tpu.audio.datasets import ESC50

        ds = ESC50(mode="dev", split=2, feat_type="mfcc", n_mfcc=13)
        x, _ = ds[0]
        assert x.shape[0] == 13  # [n_mfcc, frames]

    def test_tess_protocol(self):
        from paddle_tpu.audio.datasets import TESS

        train = TESS(mode="train", n_folds=5, split=1)
        dev = TESS(mode="dev", n_folds=5, split=1)
        assert len(train) + len(dev) == 7 * 10
        assert sorted({y for _, y in dev}) == list(range(7))

    def test_dataset_classifiable(self):
        """Class-conditioned waveforms must be separable: nearest-
        class-centroid on raw waveforms beats chance by a wide margin."""
        from paddle_tpu.audio.datasets import TESS

        train = TESS(mode="train", n_folds=5, split=1)
        dev = TESS(mode="dev", n_folds=5, split=1)
        import collections

        feats = collections.defaultdict(list)
        for x, y in train:
            feats[y].append(np.abs(np.fft.rfft(x)))
        cents = {y: np.mean(v, 0) for y, v in feats.items()}
        hit = 0
        for x, y in dev:
            f = np.abs(np.fft.rfft(x))
            pred = min(cents, key=lambda c: np.sum((cents[c] - f) ** 2))
            hit += pred == y
        assert hit / len(dev) > 0.6, f"acc {hit / len(dev)}"
