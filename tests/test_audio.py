"""paddle.audio feature tests (reference: test/legacy_test audio feature
tests — librosa-convention checks)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.audio import functional as AF
from paddle_tpu.audio.features import (MFCC, LogMelSpectrogram,
                                       MelSpectrogram, Spectrogram)


def _sine(sr=8000, f=440.0, dur=0.5):
    t = np.arange(int(sr * dur)) / sr
    return np.sin(2 * np.pi * f * t).astype(np.float32)


class TestFunctional:
    def test_mel_hz_roundtrip(self):
        freqs = np.array([100.0, 440.0, 1000.0, 4000.0])
        np.testing.assert_allclose(
            AF.mel_to_hz(AF.hz_to_mel(freqs)), freqs, rtol=1e-6)
        np.testing.assert_allclose(
            AF.mel_to_hz(AF.hz_to_mel(freqs, htk=True), htk=True),
            freqs, rtol=1e-6)

    def test_fbank_shape_and_partition(self):
        fb = AF.compute_fbank_matrix(sr=8000, n_fft=256, n_mels=20)
        assert fb.shape == [20, 129]
        assert float(fb.numpy().min()) >= 0.0

    def test_window_shapes(self):
        for w in ("hann", "hamming", "blackman"):
            assert AF.get_window(w, 64).shape == [64]

    def test_power_to_db(self):
        x = paddle.to_tensor(np.array([1.0, 10.0, 100.0], np.float32))
        db = AF.power_to_db(x, top_db=None)
        np.testing.assert_allclose(db.numpy(), [0.0, 10.0, 20.0],
                                   atol=1e-4)


class TestFeatures:
    def test_spectrogram_peak_at_tone(self):
        sr, f = 8000, 1000.0
        spec = Spectrogram(n_fft=256, hop_length=128)(
            paddle.to_tensor(_sine(sr, f)))
        assert spec.shape[0] == 129
        mean_spec = spec.numpy().mean(axis=-1)
        peak_bin = int(mean_spec.argmax())
        expect_bin = round(f / (sr / 256))
        assert abs(peak_bin - expect_bin) <= 1

    def test_mel_logmel_mfcc_shapes(self):
        x = paddle.to_tensor(_sine())
        mel = MelSpectrogram(sr=8000, n_fft=256, n_mels=32)(x)
        assert mel.shape[0] == 32
        logmel = LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32)(x)
        assert logmel.shape == mel.shape
        mfcc = MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32)(x)
        assert mfcc.shape[0] == 13

    def test_batched_input(self):
        x = paddle.to_tensor(np.stack([_sine(), _sine(f=880.0)]))
        spec = Spectrogram(n_fft=256)(x)
        assert spec.shape[0] == 2 and spec.shape[1] == 129
