"""Autograd engine tests (reference analogue: test/legacy_test backward
tests + paddle/fluid/eager/backward.cc semantics)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32),
                         stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0, 6.0], rtol=1e-6)


def test_grad_accumulation_two_backwards():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0] * 3, rtol=1e-6)


def test_retain_graph():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0] * 3, rtol=1e-6)


def test_backward_twice_without_retain_raises():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_stop_gradient_cuts_graph():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = paddle.to_tensor(np.ones(3, np.float32))  # stop_gradient default True
    z = (x * y).sum()
    z.backward()
    assert x.grad is not None
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = x * 2
    d = y.detach()
    assert d.stop_gradient
    z = (d * x).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0] * 3, rtol=1e-6)


def test_shared_subexpression_fanout():
    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = x * 2
    z = y * y + y  # y consumed twice+once
    z.backward()
    # z = 4x^2 + 2x -> dz/dx = 8x + 2 = 26
    np.testing.assert_allclose(x.grad.numpy(), [26.0], rtol=1e-6)


def test_diamond_graph():
    x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    a = x * 3
    b = x * 5
    c = a * b  # 15x^2 -> 60 at x=2
    c.backward()
    np.testing.assert_allclose(x.grad.numpy(), 60.0, rtol=1e-6)


def test_no_grad_context():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._grad_node is None


def test_no_grad_decorator():
    @paddle.no_grad()
    def f(t):
        return t * 2

    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    assert f(x).stop_gradient


def test_paddle_grad_api():
    x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    y = x * x * x
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), 12.0, rtol=1e-6)
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_paddle_grad_intermediate_input():
    x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    y = x * 3
    z = y * y
    (gy,) = paddle.grad(z, y, retain_graph=True)
    np.testing.assert_allclose(gy.numpy(), 12.0, rtol=1e-6)


def test_grad_allow_unused():
    x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    u = paddle.to_tensor(np.array(1.0, np.float32), stop_gradient=False)
    y = x * 2
    g = paddle.grad(y, [x, u], allow_unused=True)
    assert g[1] is None
    with pytest.raises(RuntimeError):
        paddle.grad(y, [u], allow_unused=False)


def test_non_scalar_backward_uses_ones():
    x = paddle.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
    y = x * 3
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((2, 2), 3.0), rtol=1e-6)


def test_backward_with_grad_tensor():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    y = x * 2
    y.backward(paddle.to_tensor(np.array([1.0, 10.0], np.float32)))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 20.0], rtol=1e-6)


def test_register_hook_scales_grad():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    handle = x.register_hook(lambda g: g * 10)
    (x * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [20.0] * 2, rtol=1e-6)
    x.clear_grad()
    handle.remove()
    (x * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0] * 2, rtol=1e-6)


def test_retain_grads_for_intermediate():
    x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    y = x * 3
    y.retain_grads()
    z = y * y
    z.backward()
    np.testing.assert_allclose(y.grad.numpy(), 12.0, rtol=1e-6)


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32), stop_gradient=False)
    parts = paddle.split(x, 3)
    loss = (parts[0] * 1 + parts[1] * 2 + parts[2] * 3).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [1, 1, 2, 2, 3, 3], rtol=1e-6)


def test_pylayer():
    class Cube(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, a):
            ctx.save_for_backward(a)
            return a * a * a

        @staticmethod
        def backward(ctx, grad):
            (a,) = ctx.saved_tensor()
            return grad * 3 * a * a

    x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    y = Cube.apply(x)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 12.0, rtol=1e-6)


def test_clear_gradient():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    (x * 2).sum().backward()
    x.clear_gradient(set_to_zero=True)
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 0.0])
    x.clear_gradient()
    assert x.grad is None


def test_clone_participates_in_autograd():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    y = x.clone()
    (y * 5).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0], rtol=1e-6)


def test_inplace_setitem_grad():
    x = paddle.to_tensor(np.ones(4, np.float32), stop_gradient=False)
    y = x * 2
    y[1] = 0.0
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 0.0, 2.0, 2.0], rtol=1e-6)


def test_pylayer_none_grad_does_not_stall_graph():
    # regression: a None grad must still release the producer dependency
    class P(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, a, b):
            return a * b

        @staticmethod
        def backward(ctx, g):
            return None, g

    u = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    v = u * 5
    w = paddle.to_tensor(np.array(3.0, np.float32), stop_gradient=False)
    out = P.apply(v, w) + v
    out.backward()
    # u's grad flows only through the direct `+ v` path (P returns None
    # for its first input), and w's grad is the raw cotangent by P's
    # custom backward definition
    np.testing.assert_allclose(u.grad.numpy(), 5.0, rtol=1e-6)
    np.testing.assert_allclose(w.grad.numpy(), 1.0, rtol=1e-6)


def test_retain_grads_survives_paddle_grad():
    # regression: paddle.grad on a retained intermediate must not consume
    # or double-fire the retain registration
    x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    y = x * 3
    y.retain_grads()
    z = y * y
    (gy,) = paddle.grad(z, y, retain_graph=True)
    z.backward()
    np.testing.assert_allclose(gy.numpy(), 12.0, rtol=1e-6)
    np.testing.assert_allclose(y.grad.numpy(), 12.0, rtol=1e-6)


class TestDoubleGrad:
    """create_graph double-grad (reference: eager double-grad via
    generated higher-order GradNodes; engine._apply_node here)."""

    def test_second_derivative_cubic(self):
        import numpy as np

        x = paddle.to_tensor(np.array([2.0, 3.0], np.float32),
                             stop_gradient=False)
        y = x * x * x
        (g1,) = paddle.grad(y, x, create_graph=True)
        assert g1._grad_node is not None  # differentiable grad
        np.testing.assert_allclose(g1.numpy(), 3 * np.array([4.0, 9.0]),
                                   rtol=1e-5)
        ones = paddle.to_tensor(np.ones(2, np.float32))
        (g2,) = paddle.grad(g1, x, grad_outputs=ones)
        np.testing.assert_allclose(g2.numpy(), 6 * np.array([2.0, 3.0]),
                                   rtol=1e-5)

    def test_third_derivative(self):
        import numpy as np

        x = paddle.to_tensor(np.array([1.5], np.float32),
                             stop_gradient=False)
        y = x * x * x * x  # y = x^4
        (g1,) = paddle.grad(y, x, create_graph=True)   # 4x^3
        (g2,) = paddle.grad(g1, x, create_graph=True)  # 12x^2
        (g3,) = paddle.grad(g2, x)                     # 24x
        np.testing.assert_allclose(g3.numpy(), [24 * 1.5], rtol=1e-4)

    def test_gradient_penalty_vs_numeric(self):
        """WGAN-GP pattern: d/dW ||dL/dx||^2 against finite differences."""
        import numpy as np

        W0 = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        X0 = np.array([[0.5, -1.0]], np.float32)
        w = paddle.to_tensor(W0, stop_gradient=False)
        x = paddle.to_tensor(X0, stop_gradient=False)
        out = paddle.matmul(x, w)
        loss = (out * out).sum()
        (gx,) = paddle.grad(loss, x, create_graph=True)
        penalty = (gx * gx).sum()
        (gw,) = paddle.grad(penalty, w)

        def penalty_np(Wm):
            g = 2 * X0 @ Wm @ Wm.T
            return float((g * g).sum())

        eps, num = 1e-3, np.zeros_like(W0)
        for i in range(2):
            for j in range(2):
                Wp, Wm_ = W0.copy(), W0.copy()
                Wp[i, j] += eps
                Wm_[i, j] -= eps
                num[i, j] = (penalty_np(Wp) - penalty_np(Wm_)) / (2 * eps)
        np.testing.assert_allclose(gw.numpy(), num, rtol=1e-2)

    def test_first_order_unaffected(self):
        import numpy as np

        x = paddle.to_tensor(np.array([3.0], np.float32),
                             stop_gradient=False)
        (g,) = paddle.grad(x * x, x)  # default create_graph=False
        assert g._grad_node is None   # plain grad carries no graph
        np.testing.assert_allclose(g.numpy(), [6.0])

    def test_grad_outputs_differentiable(self):
        """d(grad)/d(grad_outputs): the seeded cotangent keeps its graph
        under create_graph."""
        import numpy as np

        x = paddle.to_tensor(np.array([3.0], np.float32),
                             stop_gradient=False)
        v = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        (g,) = paddle.grad(x * x, x, grad_outputs=v, create_graph=True)
        (dv,) = paddle.grad(g, v)
        np.testing.assert_allclose(dv.numpy(), [6.0])  # d(2xv)/dv = 2x
