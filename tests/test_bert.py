"""BERT-base DP anchor (BASELINE configs[2]; VERDICT r3 missing #3).

Reference exemplar: test/legacy_test/test_dist_base.py:962 — a DP
pretraining run whose 2-proc gradients/params match the single-proc
run over the same global batch.
"""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.text.models import (BertForPretraining,
                                    BertPretrainingCriterion, bert_tiny)


def _batch(rng, b=4, s=16, vocab=128):
    ids = rng.randint(0, vocab, (b, s))
    types = rng.randint(0, 2, (b, s))
    mask = np.ones((b, s), np.int64)
    mlm_labels = np.where(rng.rand(b, s) < 0.15,
                          rng.randint(0, vocab, (b, s)), -100)
    nsp = rng.randint(0, 2, (b,))
    return ids, types, mask, mlm_labels, nsp


class TestBertModel:
    def test_shapes_and_pooler(self):
        paddle.seed(0)
        model = bert_tiny()
        rng = np.random.RandomState(0)
        ids, types, mask, _, _ = _batch(rng)
        seq, pooled = model(paddle.to_tensor(ids),
                            paddle.to_tensor(types),
                            paddle.to_tensor(mask))
        assert list(seq.shape) == [4, 16, 32]
        assert list(pooled.shape) == [4, 32]

    def test_attention_mask_zeroes_pad_influence(self):
        paddle.seed(0)
        model = bert_tiny()
        model.eval()
        rng = np.random.RandomState(1)
        ids, types, _, _, _ = _batch(rng)
        full = np.ones((4, 16), np.int64)
        half = full.copy()
        half[:, 8:] = 0
        ids2 = ids.copy()
        ids2[:, 8:] = rng.randint(0, 128, (4, 8))  # junk in masked tail
        s1, _ = model(paddle.to_tensor(ids), paddle.to_tensor(types),
                      paddle.to_tensor(half))
        s2, _ = model(paddle.to_tensor(ids2), paddle.to_tensor(types),
                      paddle.to_tensor(half))
        np.testing.assert_allclose(s1.numpy()[:, :8],
                                   s2.numpy()[:, :8], atol=1e-5)

    def test_mlm_head_tied_and_criterion_masking(self):
        paddle.seed(0)
        model = BertForPretraining(bert_tiny())
        crit = BertPretrainingCriterion()
        rng = np.random.RandomState(2)
        ids, types, mask, mlm, nsp = _batch(rng)
        mlm_logits, nsp_logits = model(
            paddle.to_tensor(ids), paddle.to_tensor(types),
            paddle.to_tensor(mask))
        assert list(mlm_logits.shape) == [4, 16, 128]
        assert list(nsp_logits.shape) == [4, 2]
        loss = crit(mlm_logits, nsp_logits, paddle.to_tensor(mlm),
                    paddle.to_tensor(nsp))
        assert np.isfinite(float(loss.numpy()))
        # all-unmasked labels: loss reduces to NSP CE alone
        no_mlm = np.full_like(mlm, -100)
        loss2 = crit(mlm_logits, nsp_logits, paddle.to_tensor(no_mlm),
                     paddle.to_tensor(nsp))
        ref_nsp = F.cross_entropy(nsp_logits,
                                  paddle.to_tensor(nsp.reshape(-1)))
        np.testing.assert_allclose(float(loss2.numpy()),
                                   float(ref_nsp.numpy()), rtol=1e-5)

    def test_decoder_bias_gets_eager_tape_grad(self):
        """ADVICE r4 regression: the MLM decoder bias must be a
        trainable leaf on the eager autograd tape (the DataParallel /
        hapi path), not just under jit.TrainStep."""
        paddle.seed(0)
        model = BertForPretraining(bert_tiny())
        crit = BertPretrainingCriterion()
        rng = np.random.RandomState(4)
        ids, types, mask, mlm, nsp = _batch(rng)
        mlm_logits, nsp_logits = model(
            paddle.to_tensor(ids), paddle.to_tensor(types),
            paddle.to_tensor(mask))
        loss = crit(mlm_logits, nsp_logits, paddle.to_tensor(mlm),
                    paddle.to_tensor(nsp))
        loss.backward()
        g = model.decoder_bias.grad
        assert g is not None
        assert float(np.abs(g.numpy()).sum()) > 0

    def test_pretraining_converges_in_train_step(self):
        paddle.seed(0)
        model = BertForPretraining(bert_tiny())
        crit = BertPretrainingCriterion()
        opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
        # TrainStep calls loss_fn(*outs, *labels) == crit's signature
        step = paddle.jit.TrainStep(model, crit, opt)
        rng = np.random.RandomState(3)
        ids, types, mask, mlm, nsp = _batch(rng)
        args = [paddle.to_tensor(ids), paddle.to_tensor(types),
                paddle.to_tensor(mask)]
        labels = [paddle.to_tensor(mlm), paddle.to_tensor(nsp)]
        l0 = float(step(args, labels).numpy())
        for _ in range(30):
            loss = step(args, labels)
        assert float(loss.numpy()) < l0 * 0.7, \
            (l0, float(loss.numpy()))


WORKER = textwrap.dedent("""
    import os
    for var in list(os.environ):
        if var.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            os.environ.pop(var)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.text.models import (BertForPretraining,
                                        BertPretrainingCriterion,
                                        bert_tiny)

    dist.init_parallel_env()
    rank = dist.get_rank()

    paddle.seed(0)
    # dropout off: parity compares exact trajectories across RNG streams
    model = BertForPretraining(bert_tiny(hidden_dropout_prob=0.0))
    model = dist.DataParallel(model)
    crit = BertPretrainingCriterion()
    opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())

    rng = np.random.RandomState(10)
    for step in range(4):
        # global batch 8: rank r takes rows [4r:4r+4]
        ids = rng.randint(0, 128, (8, 16))
        types = rng.randint(0, 2, (8, 16))
        mask = np.ones((8, 16), np.int64)
        mlm = np.where(rng.rand(8, 16) < 0.15,
                       rng.randint(0, 128, (8, 16)), -100)
        nsp = rng.randint(0, 2, (8,))
        sl = slice(4 * rank, 4 * rank + 4)
        ml, nl = model(paddle.to_tensor(ids[sl]),
                       paddle.to_tensor(types[sl]),
                       paddle.to_tensor(mask[sl]))
        loss = crit(ml, nl, paddle.to_tensor(mlm[sl]),
                    paddle.to_tensor(nsp[sl]))
        loss.backward()          # DataParallel hook averages grads
        opt.step()
        opt.clear_grad()

    w = np.asarray(model._layers.bert.pooler_dense.weight._data)
    np.save(os.environ["BERT_OUT"] + f".{rank}.npy", w)
    print(f"RANK{rank}_OK")
""")

SINGLE = textwrap.dedent("""
    import os
    for var in list(os.environ):
        if var.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            os.environ.pop(var)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.text.models import (BertForPretraining,
                                        BertPretrainingCriterion,
                                        bert_tiny)

    paddle.seed(0)
    model = BertForPretraining(bert_tiny(hidden_dropout_prob=0.0))
    crit = BertPretrainingCriterion()
    opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())

    rng = np.random.RandomState(10)
    for step in range(4):
        ids = rng.randint(0, 128, (8, 16))
        types = rng.randint(0, 2, (8, 16))
        mask = np.ones((8, 16), np.int64)
        mlm = np.where(rng.rand(8, 16) < 0.15,
                       rng.randint(0, 128, (8, 16)), -100)
        nsp = rng.randint(0, 2, (8,))
        # average of the two half-batch losses == DP-averaged gradient
        total = None
        for sl in (slice(0, 4), slice(4, 8)):
            ml, nl = model(paddle.to_tensor(ids[sl]),
                           paddle.to_tensor(types[sl]),
                           paddle.to_tensor(mask[sl]))
            part = crit(ml, nl, paddle.to_tensor(mlm[sl]),
                        paddle.to_tensor(nsp[sl])) * 0.5
            total = part if total is None else total + part
        total.backward()
        opt.step()
        opt.clear_grad()

    np.save(os.environ["BERT_OUT"] + ".single.npy",
            np.asarray(model.bert.pooler_dense.weight._data))
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_bert_dp_two_proc_parity(tmp_path):
    """BASELINE configs[2]: BERT pretraining, data parallel, end-to-end
    — 2-proc DP trajectory matches the equivalent single-proc run."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_base = str(tmp_path / "w")
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
            "BERT_OUT": out_base,
            "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    for rank, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"rank {rank} failed:\n{err[-3000:]}"
        assert f"RANK{rank}_OK" in out

    single = tmp_path / "single.py"
    single.write_text(SINGLE)
    env = dict(os.environ)
    env.update({"BERT_OUT": out_base,
                "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", "")})
    r = subprocess.run([sys.executable, str(single)], env=env, cwd=repo,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-3000:]

    w0 = np.load(out_base + ".0.npy")
    w1 = np.load(out_base + ".1.npy")
    ws = np.load(out_base + ".single.npy")
    np.testing.assert_allclose(w0, w1, rtol=0, atol=0)  # ranks agree
    np.testing.assert_allclose(w0, ws, rtol=1e-4, atol=1e-6)
