"""True multi-process collective tests on localhost.

Port of the reference's collective test harness (reference:
test/legacy_test/test_collective_api_base.py:113 — spawn per-rank
subprocesses with crafted PADDLE_* envs, compare collective results
against numpy semantics). Two CPU processes rendezvous through the JAX
coordinator (the TCPStore equivalent) and run the eager collective API;
the compiled data plane is exercised because both processes participate
in each jitted collective program.
"""
import os
import socket
import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent("""
    import os, sys
    # force CPU before any jax import (strip the axon TPU plugin)
    for var in list(os.environ):
        if var.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            os.environ.pop(var)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")  # sitecustomize pins axon

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.communication.collectives import (
        all_reduce, all_gather, broadcast, reduce, reduce_scatter,
        all_to_all, send, recv, ReduceOp)

    dist.init_parallel_env()
    import jax
    rank = jax.process_index()
    world = jax.process_count()
    assert world == 2, world

    # all_reduce(SUM): ranks contribute [rank+1]*4
    t = paddle.to_tensor(np.full(4, rank + 1.0, np.float32))
    all_reduce(t)
    np.testing.assert_allclose(t.numpy(), np.full(4, 3.0))

    # all_reduce(MAX)
    t = paddle.to_tensor(np.full(3, float(rank), np.float32))
    all_reduce(t, op=ReduceOp.MAX)
    np.testing.assert_allclose(t.numpy(), np.full(3, 1.0))

    # all_gather
    outs = []
    t = paddle.to_tensor(np.full(2, float(rank), np.float32))
    all_gather(outs, t)
    got = np.stack([o.numpy() for o in outs])
    np.testing.assert_allclose(got, [[0, 0], [1, 1]])

    # broadcast from rank 1
    t = paddle.to_tensor(np.full(2, float(rank * 7), np.float32))
    broadcast(t, src=1)
    np.testing.assert_allclose(t.numpy(), [7.0, 7.0])

    # reduce to dst=0: only rank 0 sees the sum
    t = paddle.to_tensor(np.full(2, rank + 1.0, np.float32))
    reduce(t, dst=0)
    want = [3.0, 3.0] if rank == 0 else [rank + 1.0] * 2
    np.testing.assert_allclose(t.numpy(), want)

    # reduce_scatter: rank r keeps sum of everyone's r-th chunk
    chunks = [paddle.to_tensor(np.full(2, rank * 10 + i, np.float32))
              for i in range(2)]
    out = paddle.to_tensor(np.zeros(2, np.float32))
    reduce_scatter(out, chunks)
    # rank0 chunk0 + rank1 chunk0 = 0 + 10 ; rank: r -> 2r+10... compute:
    want = np.full(2, (0 * 10 + rank) + (1 * 10 + rank), np.float32)
    np.testing.assert_allclose(out.numpy(), want)

    # all_to_all
    ins = [paddle.to_tensor(np.full(2, rank * 2 + j, np.float32))
           for j in range(2)]
    outs = []
    all_to_all(outs, ins)
    got = np.stack([o.numpy() for o in outs])
    want = np.stack([np.full(2, p * 2 + rank, np.float32)
                     for p in range(2)])
    np.testing.assert_allclose(got, want)

    # flag-gated cross-rank dynamic check (nccl_dynamic_check parity):
    # matching metadata passes and the collective still reduces right
    paddle.set_flags({"check_collective": True})
    t = paddle.to_tensor(np.full(2, rank + 1.0, np.float32))
    all_reduce(t)
    np.testing.assert_allclose(t.numpy(), np.full(2, 3.0))
    paddle.set_flags({"check_collective": False})

    # uneven all_to_all_single (reference: communication/all_to_all.py
    # alltoall_single with in/out_split_sizes): rank0 sends [1,3] rows,
    # rank1 sends [2,2] rows -> rank0 receives [1,2], rank1 [3,2]
    from paddle_tpu.distributed.communication.collectives import (
        all_to_all_single, gather)
    in_sp = [[1, 3], [2, 2]][rank]
    out_sp = [[1, 2], [3, 2]][rank]
    data = np.arange(sum(in_sp) * 2, dtype=np.float32).reshape(-1, 2) \
        + 100 * rank
    out = paddle.to_tensor(np.zeros((sum(out_sp), 2), np.float32))
    all_to_all_single(out, paddle.to_tensor(data),
                      out_split_sizes=out_sp, in_split_sizes=in_sp)
    # expected: my inbox = [rank0's piece for me; rank1's piece for me]
    r0 = np.arange(8, dtype=np.float32).reshape(4, 2)
    r1 = np.arange(8, dtype=np.float32).reshape(4, 2) + 100
    if rank == 0:
        want = np.concatenate([r0[:1], r1[:2]])
    else:
        want = np.concatenate([r0[1:], r1[2:]])
    np.testing.assert_allclose(out.numpy(), want)

    # bad split sizes must raise, not silently even-split
    try:
        all_to_all_single(out, paddle.to_tensor(data),
                          in_split_sizes=[1, 1, 1])
    except ValueError:
        pass
    else:
        raise AssertionError("expected ValueError for bad split count")

    # gather honors dst: only rank 1 receives
    gl = []
    gather(paddle.to_tensor(np.full(2, rank + 5.0, np.float32)),
           gl, dst=1)
    if rank == 1:
        got = np.stack([t.numpy() for t in gl])
        np.testing.assert_allclose(got, [[5, 5], [6, 6]])
    else:
        assert gl == [], "gather filled gather_list on a non-dst rank"

    # cross-process send/recv through the coordination-service store
    if rank == 0:
        send(paddle.to_tensor(np.arange(6, dtype=np.float32)), dst=1)
        send(paddle.to_tensor(np.full(3, 9.0, np.float32)), dst=1)
    else:
        buf = paddle.to_tensor(np.zeros(6, np.float32))
        recv(buf, src=0)
        np.testing.assert_allclose(buf.numpy(), np.arange(6))
        buf2 = paddle.to_tensor(np.zeros(3, np.float32))
        recv(buf2, src=0)
        np.testing.assert_allclose(buf2.numpy(), np.full(3, 9.0))

    # fleet observability: every rank runs one profiled collective and
    # dumps its trace + stats snapshot into the SHARED run dir; the
    # parent test merges them with tools/trace_merge.py
    from paddle_tpu.profiler import Profiler, dump_rank
    with Profiler(on_trace_ready=lambda p: None) as prof:
        t = paddle.to_tensor(np.full(4, rank + 1.0, np.float32))
        all_reduce(t)
        prof.step()
    written = dump_rank(os.environ["PADDLE_RUN_DIR"], profiler=prof)
    assert written["stats"].endswith(f"stats_rank{rank}.json")

    print(f"RANK{rank}_OK")
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# Fleet-observability worker: initializes the 2-process distributed
# context (the coordinator rendezvous works on CPU; only COMPILED
# cross-process collectives don't — see the note in the main worker),
# runs rank-local profiled work, and dumps this rank's trace + stats
# snapshot into the shared run dir for tools/trace_merge.py.
FLEET_WORKER = textwrap.dedent("""
    import os, sys
    for var in list(os.environ):
        if var.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            os.environ.pop(var)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.profiler import Profiler, dump_rank, stats

    dist.init_parallel_env()
    rank = jax.process_index()
    assert jax.process_count() == 2

    with Profiler(on_trace_ready=lambda p: None) as prof:
        a = paddle.to_tensor(np.ones((8, 8), np.float32))
        for _ in range(rank + 1):    # rank1 does MORE matmuls than rank0
            _ = a @ a
        prof.step()
    written = dump_rank(os.environ["PADDLE_RUN_DIR"], profiler=prof)
    assert written["stats"].endswith(f"stats_rank{rank}.json")
    assert written["trace"].endswith(f"trace_rank{rank}.json")
    print(f"RANK{rank}_OK")
""")


def test_two_process_fleet_dump_and_merge(tmp_path):
    """≥2-rank multiproc run → per-rank dumps → ONE merged chrome trace
    (pid = rank) + ONE fleet stats snapshot (counters summed, gauges
    maxed). Rank-local work only: compiled cross-process collectives
    are unimplemented on the CPU backend, but the coordinator
    rendezvous — and therefore real distinct process_index stamps —
    works, which is exactly what the aggregation layer needs."""
    import json

    script = tmp_path / "fleet_worker.py"
    script.write_text(FLEET_WORKER)
    run_dir = tmp_path / "run"
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
            "PADDLE_RUN_DIR": str(run_dir),
            "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    for rank, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"rank {rank} failed:\n{err[-3000:]}"
        assert f"RANK{rank}_OK" in out

    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        import trace_merge
    finally:
        sys.path.pop(0)
    assert trace_merge.main([str(run_dir)]) == 0

    merged = json.load(open(run_dir / "merged_trace.json"))
    assert merged["metadata"]["ranks"] == [0, 1]
    # one timeline: every event re-pid'd to its rank, both ranks named
    assert {e["pid"] for e in merged["traceEvents"]} == {0, 1}
    spans = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in spans} == {0, 1}
    names = {e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert any("rank 0" in n for n in names)
    assert any("rank 1" in n for n in names)

    fleet = json.load(open(run_dir / "fleet_stats.json"))
    per_rank = [json.load(open(run_dir / f"stats_rank{r}.json"))
                for r in (0, 1)]
    # rank stamps are REAL process indices, not env echoes
    assert sorted(s["meta"]["process_index"] for s in per_rank) == [0, 1]
    # counters summed: rank0 ran 1 matmul, rank1 ran 2 -> fleet 3
    assert fleet["counters"]["op.matmul"] == sum(
        s["counters"]["op.matmul"] for s in per_rank) == 3
    # gauges maxed: the fleet view keeps the high-water rank coords
    assert fleet["gauges"]["dist.process_index"] == 1
    assert fleet["gauges"]["dist.process_count"] == 2


def _cpu_jaxlib() -> bool:
    import jax

    try:
        return jax.default_backend() == "cpu"
    except Exception:
        return True


@pytest.mark.skipif(
    _cpu_jaxlib(),
    reason="compiled cross-process collectives are unimplemented on CPU "
           "jaxlib (the multi-process CPU runtime has no data-plane "
           "transport for jitted psum/all_gather programs — workers die "
           "in the first compiled collective); run on a real multi-host "
           "TPU slice. The eager/store-based collective paths are "
           "covered by test_subset_group_multiproc and "
           "test_two_process_fleet_dump_and_merge.")
def test_two_process_collectives(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    run_dir = tmp_path / "run"
    port = _free_port()
    procs = []
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
            "PADDLE_RUN_DIR": str(run_dir),
            "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    for rank, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"rank {rank} failed:\n{err[-3000:]}"
        assert f"RANK{rank}_OK" in out

    # ---- fleet aggregation over the real 2-rank artifacts ----
    import json

    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        import trace_merge
    finally:
        sys.path.pop(0)
    rc = trace_merge.main([str(run_dir)])
    assert rc == 0
    merged = json.load(open(run_dir / "merged_trace.json"))
    # one timeline, pid = rank, both ranks present and named
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert pids == {0, 1}
    names = {e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert any("rank 0" in n for n in names)
    assert any("rank 1" in n for n in names)
    fleet = json.load(open(run_dir / "fleet_stats.json"))
    assert sorted(fleet["meta"]["ranks"]) == [0, 1]
    # counters summed across ranks: the fleet total is the SUM of the
    # per-rank counts (each rank ran the same >= 4 all_reduces), not
    # either rank's own count
    per_rank = [json.load(open(run_dir / f"stats_rank{r}.json"))
                for r in (0, 1)]
    want = sum(s["counters"]["dist.all_reduce.calls"] for s in per_rank)
    assert want >= 8
    assert fleet["counters"]["dist.all_reduce.calls"] == want
    # gauges maxed: the fleet view shows the highest rank index/world
    assert fleet["gauges"]["dist.process_index"] == 1
    assert fleet["gauges"]["dist.process_count"] == 2
