"""Persistent XLA compilation cache wiring + cold-vs-warm compile
telemetry (ISSUE r6 satellite, first step toward the 25-min s2048
compile).

- ``FLAGS_compile_cache_dir`` (env ``PADDLE_TPU_COMPILE_CACHE_DIR``)
  -> ``device.setup_compile_cache()`` -> jax_compilation_cache_dir,
  with the ``compile.persistent_cache`` gauge recording the regime.
- ``TrainStep`` records its first call's wall seconds (trace + XLA
  compile + run) in the ``compile.train_step_first_call_s`` histogram,
  which bench.py embeds in its telemetry block — so a cache-warm
  round's compile-second drop is visible across BENCH_r*.json files.
"""
import numpy as np

import jax

import paddle_tpu as paddle
from paddle_tpu.profiler import stats


class TestCompileCacheFlag:
    def test_setup_applies_flag_dir_and_gauge(self, tmp_path):
        old = paddle.get_flags("compile_cache_dir")["compile_cache_dir"]
        try:
            paddle.set_flags({"FLAGS_compile_cache_dir":
                              str(tmp_path)})
            applied = paddle.device.setup_compile_cache()
            assert applied == str(tmp_path)
            assert jax.config.jax_compilation_cache_dir == str(tmp_path)
            assert stats.gauge("compile.persistent_cache").value == 1
        finally:
            jax.config.update("jax_compilation_cache_dir", None)
            paddle.set_flags({"FLAGS_compile_cache_dir": old})
            stats.set_gauge("compile.persistent_cache",
                            1 if old else 0)

    def test_no_dir_is_a_noop(self):
        old = paddle.get_flags("compile_cache_dir")["compile_cache_dir"]
        prev = jax.config.jax_compilation_cache_dir
        try:
            paddle.set_flags({"FLAGS_compile_cache_dir": ""})
            assert paddle.device.setup_compile_cache() is None
            assert jax.config.jax_compilation_cache_dir == prev
            assert stats.gauge("compile.persistent_cache").value == 0
        finally:
            paddle.set_flags({"FLAGS_compile_cache_dir": old})

    def test_explicit_path_wins_over_flag(self, tmp_path):
        try:
            applied = paddle.device.setup_compile_cache(
                str(tmp_path / "explicit"))
            assert applied == str(tmp_path / "explicit")
        finally:
            jax.config.update("jax_compilation_cache_dir", None)
            stats.set_gauge("compile.persistent_cache", 0)


class TestTrainStepCompileSeconds:
    def test_first_call_observed_once(self):
        import paddle_tpu.nn as nn

        paddle.seed(0)
        model = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        step = paddle.jit.TrainStep(
            model, lambda o, y: ((o - y) ** 2).mean(), opt)
        h = stats.histogram("compile.train_step_first_call_s")
        before = h.count
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        y = paddle.to_tensor(np.zeros((2, 4), np.float32))
        step([x], [y])
        assert h.count == before + 1
        assert step.first_call_seconds > 0
        first = step.first_call_seconds
        step([x], [y])  # warm call: no second observation
        assert h.count == before + 1
        assert step.first_call_seconds == first
