"""static.nn control flow + graph-break diagnostics (VERDICT r3 #7).

Reference: python/paddle/static/nn/control_flow.py (while_loop:629,
cond:1126); the SOT graph-break layer (eval_frame.c:411) maps here to
framework-level GraphBreakError diagnostics from trace failures.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static.nn import case, cond, switch_case, while_loop


class TestCondEager:
    def test_branch_selection(self):
        x = paddle.to_tensor(np.array([3.0], np.float32))
        out = cond(x.sum() > 0, lambda: x * 2, lambda: x - 1)
        np.testing.assert_allclose(out.numpy(), [6.0])
        out = cond(x.sum() < 0, lambda: x * 2, lambda: x - 1)
        np.testing.assert_allclose(out.numpy(), [2.0])

    def test_grad_through_taken_branch(self):
        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        out = cond(x.sum() > 0, lambda: x * 3, lambda: x * 5)
        out.backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0])

    def test_nested_structure_output(self):
        x = paddle.to_tensor(np.ones((2,), np.float32))
        out = cond(x.sum() > 0,
                   lambda: {"a": x * 2, "b": (x + 1, x - 1)},
                   lambda: {"a": x, "b": (x, x)})
        np.testing.assert_allclose(out["a"].numpy(), [2.0, 2.0])
        np.testing.assert_allclose(out["b"][0].numpy(), [2.0, 2.0])


class TestCondTraced:
    def test_cond_in_to_static(self):
        x = paddle.to_tensor(np.array([4.0], np.float32))

        @paddle.jit.to_static
        def f(x):
            return cond(x.sum() > 3, lambda: x * 10, lambda: x)

        np.testing.assert_allclose(f(x).numpy(), [40.0])
        # same compiled program, other branch at runtime
        y = paddle.to_tensor(np.array([1.0], np.float32))
        np.testing.assert_allclose(f(y).numpy(), [1.0])

    def test_cond_grad_in_train_step(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                h = self.fc(x)
                return cond(h.sum() > 0, lambda: h * 2, lambda: h * 0.5)

        model = Net()
        opt = paddle.optimizer.SGD(0.05, parameters=model.parameters())
        step = paddle.jit.TrainStep(
            model, lambda o, y: ((o - y) ** 2).mean(), opt)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 4).astype(np.float32))
        y = paddle.to_tensor(np.zeros((2, 4), np.float32))
        l0 = float(step([x], [y]).numpy())
        for _ in range(5):
            loss = step([x], [y])
        assert float(loss.numpy()) < l0

    def test_branch_structure_mismatch_raises(self):
        x = paddle.to_tensor(np.ones((2,), np.float32))

        @paddle.jit.to_static
        def f(x):
            return cond(x.sum() > 0, lambda: (x, x), lambda: x)

        with pytest.raises(ValueError, match="same structure"):
            f(x)


class TestWhileLoop:
    def test_eager_loop_and_gradient(self):
        """Gradient flows through the unrolled eager tape: y = x*2^3."""
        x = paddle.to_tensor(np.array([1.5], np.float32),
                             stop_gradient=False)
        i = paddle.to_tensor(np.array(0, np.int64))
        iv, yv = while_loop(lambda i, y: i < 3,
                            lambda i, y: [i + 1, y * 2.0], [i, x])
        np.testing.assert_allclose(yv.numpy(), [12.0])
        yv.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])

    def test_traced_while_loop(self):
        @paddle.jit.to_static
        def f(x):
            i = paddle.to_tensor(np.array(0, np.int64))
            _, out = while_loop(lambda i, y: i < 4,
                                lambda i, y: [i + 1, y + y], [i, x])
            return out

        x = paddle.to_tensor(np.array([1.0], np.float32))
        np.testing.assert_allclose(f(x).numpy(), [16.0])

    def test_data_dependent_trip_count_traced(self):
        """The loop bound is a runtime VALUE — one compiled program
        serves different trip counts (the reason while_loop exists)."""
        @paddle.jit.to_static
        def countdown(n):
            i = paddle.to_tensor(np.array(0, np.int64))
            _, c = while_loop(
                lambda i, c: i < n.astype("int64").sum(),
                lambda i, c: [i + 1, c + 2.0],
                [i, paddle.to_tensor(np.array(0.0, np.float32))])
            return c

        a = float(countdown(paddle.to_tensor(
            np.array(3, np.int64))).numpy())
        b = float(countdown(paddle.to_tensor(
            np.array(5, np.int64))).numpy())
        assert (a, b) == (6.0, 10.0)

    def test_bad_body_arity_raises(self):
        x = paddle.to_tensor(np.array([1.0], np.float32))
        i = paddle.to_tensor(np.array(0, np.int64))
        with pytest.raises(ValueError, match="as many values"):
            while_loop(lambda i, y: i < 2, lambda i, y: [i + 1], [i, x])


class TestCaseSwitch:
    def test_case_first_match(self):
        x = paddle.to_tensor(np.array(2.0, np.float32))
        out = case([(x > 3, lambda: x * 10), (x > 1, lambda: x * 100)],
                   default=lambda: x)
        np.testing.assert_allclose(out.numpy(), 200.0)

    def test_switch_case(self):
        idx = paddle.to_tensor(np.array(1, np.int64))
        out = switch_case(idx, {0: lambda: paddle.to_tensor(0.0),
                                1: lambda: paddle.to_tensor(10.0)},
                          default=lambda: paddle.to_tensor(-1.0))
        np.testing.assert_allclose(out.numpy(), 10.0)


class TestGraphBreakDiagnostics:
    def test_python_if_on_tensor_in_to_static(self):
        from paddle_tpu.jit.graph_break import GraphBreakError

        @paddle.jit.to_static
        def f(x):
            if (x.sum() > 0):  # data-dependent Python branch
                return x * 2
            return x

        x = paddle.to_tensor(np.ones((2,), np.float32))
        with pytest.raises(GraphBreakError,
                           match="static.nn.cond") as ei:
            f(x)
        assert "graph break while tracing `f`" in str(ei.value)

    def test_train_step_graph_break_names_model(self):
        from paddle_tpu.jit.graph_break import GraphBreakError

        class BadNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(2, 2)

            def forward(self, x):
                h = self.fc(x)
                while h.sum() > 100:  # Python while on a tracer
                    h = h * 0.5
                return h

        model = BadNet()
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        step = paddle.jit.TrainStep(
            model, lambda o, y: ((o - y) ** 2).mean(), opt)
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        y = paddle.to_tensor(np.ones((2, 2), np.float32))
        with pytest.raises(GraphBreakError, match="BadNet"):
            step([x], [y])

    def test_eager_only_op_named_in_diagnostic(self):
        from paddle_tpu.jit.graph_break import GraphBreakError

        @paddle.jit.to_static
        def f(x):
            nz = paddle.nonzero(x)  # data-dependent shape: eager-only
            return nz.sum()

        x = paddle.to_tensor(np.array([1.0, 0.0, 2.0], np.float32))
        with pytest.raises((GraphBreakError, RuntimeError)):
            f(x)

    def test_unrelated_errors_pass_through(self):
        @paddle.jit.to_static
        def f(x):
            return x.reshape([7, 7])  # genuine shape error

        x = paddle.to_tensor(np.ones((4,), np.float32))
        from paddle_tpu.jit.graph_break import GraphBreakError

        with pytest.raises(Exception) as ei:
            f(x)
        assert not isinstance(ei.value, GraphBreakError)
