"""Custom-op registration path (VERDICT r3 missing #1).

Covers the reference custom-op contract (reference:
paddle/fluid/framework/custom_operator.cc:958 RegisterOperatorWithMetaInfo;
python/paddle/utils/cpp_extension/cpp_extension.py:797 load();
test/custom_op/ exercises): a user-registered op must work in eager
dispatch, the autograd tape (with a CUSTOM backward actually used),
to_static tracing, jit.save → Predictor reload, and the host-C++ build
path.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.utils import cpp_extension

from op_test import check_grad, check_output


def _swiglu_np(x, y):
    sig = 1.0 / (1.0 + np.exp(-x))
    return (x * sig) * y


class TestRegisterCustomOp:
    def test_forward_eager_matches_numpy(self):
        op = cpp_extension.register_custom_op(
            "my_swiglu_fwd_only",
            lambda x, y: paddle.nn.functional.silu(
                paddle.Tensor(x))._data * y)
        rng = np.random.RandomState(0)
        a = rng.randn(4, 8).astype(np.float32)
        b = rng.randn(4, 8).astype(np.float32)
        check_output(op, lambda x, y: _swiglu_np(x, y), [a, b],
                     atol=1e-5, rtol=1e-5)

    def test_autodiff_backward_when_no_custom_vjp(self):
        import jax.numpy as jnp

        op = cpp_extension.register_custom_op(
            "my_cube", lambda x: x * x * x)
        check_grad(op, [np.random.RandomState(1).randn(3, 4)])

    def test_custom_vjp_is_actually_used(self):
        """Forward is 2x; the registered backward deliberately returns
        3*grad — the tape must see 3, not the autodiff 2."""
        op = cpp_extension.register_custom_op(
            "my_marked_double",
            lambda x: x * 2.0,
            backward=lambda x, g: (g * 3.0,))
        t = paddle.to_tensor(np.ones((2, 2), np.float32),
                             stop_gradient=False)
        op(t).sum().backward()
        np.testing.assert_allclose(t.grad.numpy(),
                                   np.full((2, 2), 3.0), rtol=1e-6)

    def test_custom_vjp_swiglu_grad_check(self):
        import jax
        import jax.numpy as jnp

        def fwd(x, y):
            return jax.nn.silu(x) * y

        def bwd(x, y, g):
            sig = jax.nn.sigmoid(x)
            dsilu = sig * (1 + x * (1 - sig))
            return g * y * dsilu, g * jax.nn.silu(x)

        op = cpp_extension.register_custom_op(
            "my_swiglu", fwd, backward=bwd)
        rng = np.random.RandomState(2)
        check_grad(op, [rng.randn(3, 5), rng.randn(3, 5)])

    def test_save_outputs_residual_mode(self):
        import jax.numpy as jnp

        op = cpp_extension.register_custom_op(
            "my_expm1", lambda x: jnp.exp(x) - 1.0,
            backward=lambda x, out, g: (g * (out + 1.0),),
            save_outputs=True)
        check_grad(op, [np.random.RandomState(3).randn(4)])

    def test_none_grad_input(self):
        op = cpp_extension.register_custom_op(
            "my_scale_by", lambda x, s: x * s,
            backward=lambda x, s, g: (g * s, None))
        x = paddle.to_tensor(np.ones((3,), np.float32),
                             stop_gradient=False)
        s = paddle.to_tensor(np.full((3,), 2.0, np.float32),
                             stop_gradient=False)
        op(x, s).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.full((3,), 2.0))

    def test_registry_entry_and_method(self):
        from paddle_tpu.ops.registry import get_op

        op = cpp_extension.register_custom_op(
            "my_negate", lambda x: -x, methods=("my_negate",))
        d = get_op("my_negate")
        assert "custom" in d.tags
        t = paddle.to_tensor(np.array([1.0, -2.0], np.float32))
        np.testing.assert_allclose(t.my_negate().numpy(),
                                   np.array([-1.0, 2.0]))

    def test_custom_op_in_train_step(self):
        """The custom VJP must also govern the whole-step compiled
        TrainStep program (to_static path)."""
        import jax

        def bwd(x, g):
            return (g * jax.nn.sigmoid(x) * (
                1 + x * (1 - jax.nn.sigmoid(x))),)

        op = cpp_extension.register_custom_op(
            "my_silu_ts", lambda x: jax.nn.silu(x), backward=bwd)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return op(self.fc(x))

        model = Net()
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        step = paddle.jit.TrainStep(
            model, lambda out, y: ((out - y) ** 2).mean(), opt)
        x = paddle.to_tensor(np.random.RandomState(4)
                             .randn(2, 4).astype(np.float32))
        y = paddle.to_tensor(np.zeros((2, 4), np.float32))
        l0 = float(step([x], [y]).numpy())
        l1 = float(step([x], [y]).numpy())
        assert np.isfinite(l0) and l1 < l0


class TestCustomOpJitSave:
    def test_jit_save_load_roundtrip(self, tmp_path):
        import jax

        op = cpp_extension.register_custom_op(
            "my_swiglu_saved",
            lambda x, y: jax.nn.silu(x) * y,
            backward=lambda x, y, g: (
                g * y * jax.nn.sigmoid(x) * (
                    1 + x * (1 - jax.nn.sigmoid(x))),
                g * jax.nn.silu(x)))

        class Gate(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(6, 12)

            def forward(self, x):
                h = self.fc(x)
                return op(h[:, :6], h[:, 6:])

        model = Gate()
        model.eval()
        x = paddle.to_tensor(np.random.RandomState(5)
                             .randn(3, 6).astype(np.float32))
        ref = model(x).numpy()
        path = str(tmp_path / "gate")
        from paddle_tpu.static.input_spec import InputSpec

        paddle.jit.save(model, path,
                        input_spec=[InputSpec([3, 6], "float32")])
        loaded = paddle.jit.load(path)
        np.testing.assert_allclose(loaded(x).numpy(), ref,
                                   atol=1e-5, rtol=1e-5)

    def test_predictor_runs_saved_custom_op(self, tmp_path):
        """Inference Config/Predictor consumes the saved artifact."""
        import jax

        op = cpp_extension.register_custom_op(
            "my_gelu_pred", lambda x: jax.nn.gelu(x))

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return op(self.fc(x))

        model = Net()
        model.eval()
        x = np.random.RandomState(6).randn(2, 4).astype(np.float32)
        ref = model(paddle.to_tensor(x)).numpy()
        path = str(tmp_path / "net")
        from paddle_tpu.static.input_spec import InputSpec

        paddle.jit.save(model, path,
                        input_spec=[InputSpec([2, 4], "float32")])
        from paddle_tpu.inference import Config, create_predictor

        cfg = Config(path + ".pdmodel", path + ".pdiparams")
        pred = create_predictor(cfg)
        names = pred.get_input_names()
        pred.get_input_handle(names[0]).copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(
            pred.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


CPP_SRC = r"""
#include <cstdint>
#include <cmath>
extern "C" void my_csquare(const float* x, float* out, int64_t n) {
    for (int64_t i = 0; i < n; ++i) out[i] = x[i] * x[i];
}
extern "C" void my_chardtanh(const float* x, float* out, int64_t n) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = x[i] < -1.f ? -1.f : (x[i] > 1.f ? 1.f : x[i]);
}
"""


class TestCppExtensionLoad:
    @pytest.fixture(scope="class")
    def ext(self, tmp_path_factory):
        d = tmp_path_factory.mktemp("ext")
        src = d / "my_ops.cc"
        src.write_text(CPP_SRC)
        return cpp_extension.load(
            "my_ops", [str(src)], build_directory=str(d), verbose=True)

    def test_build_and_elementwise_op(self, ext):
        op = ext.elementwise_op("my_csquare")
        x = paddle.to_tensor(np.array([1.0, 2.0, -3.0], np.float32))
        np.testing.assert_allclose(op(x).numpy(),
                                   np.array([1.0, 4.0, 9.0]))

    def test_host_op_with_custom_backward_on_tape(self, ext):
        op = ext.elementwise_op(
            "my_chardtanh", op_name="my_chardtanh_g",
            backward=lambda x, g: (
                g * ((x > -1.0) & (x < 1.0)).astype(g.dtype),))
        x = paddle.to_tensor(np.array([-2.0, 0.5, 2.0], np.float32),
                             stop_gradient=False)
        op(x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   np.array([0.0, 1.0, 0.0]))

    def test_build_cache_reused(self, ext, tmp_path):
        # same content → same hash → no recompile (path identical)
        src = tmp_path / "my_ops.cc"
        src.write_text(CPP_SRC)
        again = cpp_extension.load(
            "my_ops", [str(src)],
            build_directory=os.path.dirname(ext.lib_path))
        assert again.lib_path == ext.lib_path

    def test_jit_save_host_op_raises_clear_error(self, ext, tmp_path):
        """A model using a host C++ callback op must fail jit.save with
        guidance, not a raw serialization error or a broken artifact."""
        op = ext.elementwise_op("my_csquare", op_name="my_csquare_save")

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return op(self.fc(x))

        model = Net()
        model.eval()
        from paddle_tpu.static.input_spec import InputSpec

        with pytest.raises(RuntimeError, match="HOST custom op"):
            paddle.jit.save(model, str(tmp_path / "hostnet"),
                            input_spec=[InputSpec([2, 4], "float32")])

    def test_cuda_extension_raises(self):
        with pytest.raises(RuntimeError, match="Pallas"):
            cpp_extension.CUDAExtension(sources=["x.cu"])

    def test_setup_builds(self, tmp_path):
        src = tmp_path / "ops2.cc"
        src.write_text(CPP_SRC)
        mod = cpp_extension.setup(
            "ops2",
            [cpp_extension.CppExtension([str(src)], name="ops2")])
        assert isinstance(mod, cpp_extension.CustomOpModule)
