"""Disaggregated prefill/decode fleet + prefix directory (ISSUE 20).

Tier-1 acceptance pins:
- role split changes WHERE work runs, never WHAT comes out: the same
  prompts through a symmetric fleet and a ``disagg='1:1'`` fleet
  produce identical greedy tokens, every request handed off exactly
  once over the migration path, and the decode replica runs zero
  prefill actions;
- the fleet prefix DIRECTORY generalizes chain→replica affinity to
  chain→(replica, tier): a spill flips the entry to "host", a restore
  back to "hbm", a host-LRU drop forgets it, and admission consults
  the restore-vs-re-prefill cost model (``FLAGS_kv_restore_gbps`` /
  ``FLAGS_disagg_prefill_tflops``) before routing to a host holder.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import set_flags
from paddle_tpu.inference import FusedCausalLM
from paddle_tpu.profiler import stats
from paddle_tpu.serving import FleetRouter, ServingEngine, SLOConfig
from paddle_tpu.serving.router import _parse_disagg

pytestmark = pytest.mark.chaos


def _model(seed=7, max_position=256):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=max_position)


def _engine(seed=7, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_length", 96)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("slo", SLOConfig(prefill_chunk=8))
    return ServingEngine(_model(seed), **kw)


def _router(n=2, seed=7, **kw):
    return FleetRouter(engine_factory=lambda i: _engine(seed),
                       n_replicas=n, **kw)


@pytest.fixture
def host_tier_flag():
    set_flags({"kv_host_tier_bytes": 1 << 22})
    yield
    set_flags({"kv_host_tier_bytes": 0})


class TestParseDisagg:
    def test_specs(self):
        assert _parse_disagg("", 4) is None
        assert _parse_disagg(None, 4) is None
        assert _parse_disagg(False, 4) is None
        assert _parse_disagg("auto", 4) == (2, 2)
        assert _parse_disagg(True, 5) == (2, 3)
        assert _parse_disagg("auto", 1) is None
        assert _parse_disagg("1:3", 4) == (1, 3)
        assert _parse_disagg("3:1", 4) == (3, 1)

    def test_invalid_specs(self):
        for bad in ("0:2", "2:0", "1:1", "nonsense"):
            with pytest.raises(ValueError):
                _parse_disagg(bad, 3)


class TestDisaggHandoff:
    def _prompts(self, seed=5):
        rng = np.random.RandomState(seed)
        return [rng.randint(0, 64, (L,)).astype(np.int32)
                for L in (12, 21, 9, 16)]

    def _drive(self, router, prompts, n=8):
        rids = [router.submit(p, max_new_tokens=n) for p in prompts]
        done = {r.id: r for r in router.run()}
        return [list(done[r].generated) for r in rids]

    def test_token_parity_and_handoff_census(self):
        """Symmetric vs 1P:1D on identical prompts: same tokens, one
        handoff per request, journal == counter, and role discipline
        (the decode replica never prefilled)."""
        prompts = self._prompts()
        ref = self._drive(_router(), prompts)
        stats.reset()
        router = _router(disagg="1:1")
        assert [r.role for r in router.replicas] == \
            ["prefill", "decode"]
        assert self._drive(router, prompts) == ref
        handoffs = int(stats.counter("fleet.handoffs").value)
        assert handoffs == len(prompts)
        assert int(stats.counter("fleet.handoff_pages").value) > 0
        # plain migrations stayed zero — handoffs are accounted apart
        assert int(stats.counter("fleet.migrations").value) == 0
        jr = router.replicas[1].eng.journal
        if jr is not None:
            evs = [e for e in jr.events() if e["ev"] == "handoff"]
            assert len(evs) == handoffs
            assert all(e["from"] == 0 and e["to"] == 1 for e in evs)
        assert "prefill" not in set(router.replicas[1].eng.action_log)

    def test_async_handoff_parity(self):
        """The same census with FLAGS_migrate_async on — handoffs ride
        the PR 19 streamed path (ticketed import, tail catch-up) and
        must stay token-exact."""
        prompts = self._prompts(seed=9)
        ref = self._drive(_router(), prompts)
        stats.reset()
        set_flags({"migrate_async": True})
        try:
            router = _router(disagg="1:1")
            assert self._drive(router, prompts) == ref
        finally:
            set_flags({"migrate_async": False})
        # unlike the sync path, a streamed handoff can lose the race
        # with its own decode (the request finishes before the pages
        # do and the import aborts) — so >=1, not one-per-request
        assert int(stats.counter("fleet.handoffs").value) >= 1
        assert int(stats.counter("fleet.async_migrations").value) > 0

    def test_roles_are_preference_not_availability(self):
        """With every prefill replica excluded (dead), dispatch falls
        back to decode-role replicas — the split degrades, it never
        deadlocks."""
        router = _router(disagg="1:1")
        router.replicas[0].state = "dead"   # .dead property reads it
        prompts = self._prompts(seed=3)[:2]
        outs = self._drive(router, prompts)
        assert all(len(o) == 8 for o in outs)

    def test_flag_driven_roles(self):
        """FLAGS_disagg wires the split without the constructor arg."""
        set_flags({"disagg": "auto"})
        try:
            router = _router(n=3)
        finally:
            set_flags({"disagg": ""})
        assert router.disagg == (1, 2)
        assert [r.role for r in router.replicas] == \
            ["prefill", "decode", "decode"]
        # role burst weights stamped onto the scheduler SLO config
        assert router.replicas[0].eng.slo.prefill_burst >= 8
        assert router.replicas[1].eng.slo.decode_burst >= 8


class TestPrefixDirectory:
    def test_hbm_hit_routes_to_holder(self):
        """Second request with a cached prefix routes to the replica
        whose pool holds the chain — the directory hit path."""
        rng = np.random.RandomState(4)
        prefix = rng.randint(0, 64, (16,)).astype(np.int32)
        router = _router()
        stats.reset()
        r1 = router.submit(np.concatenate(
            [prefix, rng.randint(0, 64, (6,))]), max_new_tokens=4)
        list(router.run())
        owner = next(iter(router._directory.values()))[0]
        assert all(v == (owner, "hbm")
                   for v in router._directory.values())
        router.submit(np.concatenate(
            [prefix, rng.randint(0, 64, (9,))]), max_new_tokens=4)
        list(router.run())
        assert int(stats.counter("fleet.directory_hits").value) >= 1
        assert router._affinity  # legacy owner-only view still reads

    def test_spill_flips_tier_and_restore_flips_back(
            self, host_tier_flag):
        """The tentpole directory pin: evicting a registered chain to
        the host tier flips its entries to (owner, "host"); restoring
        flips them back to "hbm"; a host-LRU drop forgets them."""
        rng = np.random.RandomState(8)
        prefix = rng.randint(0, 64, (16,)).astype(np.int32)
        prompt = np.concatenate([prefix, rng.randint(0, 64, (5,))])
        router = _router()
        eng0 = router.replicas[0].eng
        assert eng0.host_tier is not None
        router.submit(prompt, max_new_tokens=4)
        list(router.run())
        keys = router._affinity_chain(prompt)
        assert keys and all(
            router._directory.get(k, (None, None))[1] == "hbm"
            for k in keys)
        owner = router._directory[keys[0]][0]
        eng = router.replicas[owner].eng
        eng.prefix_cache.evict(len(eng.prefix_cache))
        assert all(router._directory[k] == (owner, "host")
                   for k in keys)
        restored = eng.prefix_cache.restore_chain(prompt, reserve=0)
        assert restored > 0
        for k in keys[:restored]:
            assert router._directory[k] == (owner, "hbm")
        # drop the rest from the host tier -> directory forgets them
        eng.host_tier.clear()
        for k in keys[restored:]:
            assert k not in router._directory

    def test_pull_worth_cost_model_flags(self):
        """_pull_worth flips with the flag-priced arms: a slow
        re-prefill (tiny TFLOPs) makes the restore win; the default
        real-hardware pricing makes re-prefilling this toy model
        free by comparison."""
        router = _router()
        assert not router._pull_worth(4)   # defaults: prefill wins
        set_flags({"disagg_prefill_tflops": 1e-6})
        try:
            assert router._pull_worth(4)
        finally:
            set_flags({"disagg_prefill_tflops": 100.0})
        set_flags({"kv_restore_gbps": 1e-12})
        try:
            assert not router._pull_worth(4)  # bandwidth-starved
        finally:
            set_flags({"kv_restore_gbps": 10.0})

    def test_directory_pull_end_to_end(self, host_tier_flag):
        """A host-resident chain + a cost model that prices restore
        cheaper routes the request to the holder, whose admission
        PULLS the chain back (fleet.directory_pulls + fleet.restores),
        with tokens identical to a cold fleet."""
        rng = np.random.RandomState(11)
        prefix = rng.randint(0, 64, (16,)).astype(np.int32)
        p1 = np.concatenate([prefix, rng.randint(0, 64, (6,))])
        p2 = np.concatenate([prefix, rng.randint(0, 64, (9,))])
        set_flags({"kv_host_tier_bytes": 0})
        ref_router = _router()
        ra = ref_router.submit(p1, max_new_tokens=4)
        rb = ref_router.submit(p2, max_new_tokens=4)
        ref_done = {r.id: r for r in ref_router.run()}
        set_flags({"kv_host_tier_bytes": 1 << 22})
        stats.reset()
        router = _router()
        r1 = router.submit(p1, max_new_tokens=4)
        done1 = {r.id: r for r in router.run()}
        owner = next(iter(router._directory.values()))[0]
        eng = router.replicas[owner].eng
        eng.prefix_cache.evict(len(eng.prefix_cache))  # -> host tier
        set_flags({"disagg_prefill_tflops": 1e-6})     # restore wins
        try:
            r2 = router.submit(p2, max_new_tokens=4)
            done2 = {r.id: r for r in router.run()}
        finally:
            set_flags({"disagg_prefill_tflops": 100.0})
        assert list(done1[r1].generated) == \
            list(ref_done[ra].generated)
        assert list(done2[r2].generated) == \
            list(ref_done[rb].generated)
        assert int(stats.counter("fleet.directory_pulls").value) >= 1
        assert int(stats.counter("fleet.restores").value) >= 1

    def test_miss_counter_on_cold_and_priced_out(self, host_tier_flag):
        """Cold chains and host chains the cost model prices out both
        count as directory misses (the re-prefill arm)."""
        rng = np.random.RandomState(14)
        prefix = rng.randint(0, 64, (16,)).astype(np.int32)
        prompt = np.concatenate([prefix, rng.randint(0, 64, (5,))])
        stats.reset()
        router = _router()
        router.submit(prompt, max_new_tokens=4)
        list(router.run())
        assert int(stats.counter(
            "fleet.directory_misses").value) >= 1  # cold chain
        owner = next(iter(router._directory.values()))[0]
        eng = router.replicas[owner].eng
        eng.prefix_cache.evict(len(eng.prefix_cache))
        before = int(stats.counter("fleet.directory_misses").value)
        # defaults price the toy re-prefill cheaper than any restore
        router.submit(np.concatenate(
            [prefix, rng.randint(0, 64, (7,))]), max_new_tokens=4)
        list(router.run())
        assert int(stats.counter(
            "fleet.directory_misses").value) > before


class TestObservability:
    def test_journal_lifecycle_events(self):
        from paddle_tpu.serving.journal import LIFECYCLE_EVENTS

        for ev in ("handoff", "spill", "restore"):
            assert ev in LIFECYCLE_EVENTS

    def test_serve_top_counts_and_fleet_tier_view(self, host_tier_flag):
        """serve_top folds handoff/spill/restore events and the fleet
        renderer shows the per-replica tier occupancy + directory hit
        rate."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        from tools import serve_top

        events = [
            {"seq": 1, "ts": 0.0, "ev": "submit", "rid": 1,
             "prompt_len": 16},
            {"seq": 2, "ts": 0.1, "ev": "handoff", "rid": 1,
             "slot": 0, "from": 0, "to": 1, "pages": 4},
            {"seq": 3, "ts": 0.2, "ev": "spill", "rid": -1,
             "pages": 3, "bytes": 6144},
            {"seq": 4, "ts": 0.3, "ev": "restore", "rid": -1,
             "pages": 2, "bytes": 4096},
        ]
        s = serve_top.summarize(events)
        assert s["handoffs"] == 1
        assert s["spilled_pages"] == 3
        assert s["restored_pages"] == 2
        assert s["requests"][1]["phase"] == "decode"
        text = serve_top.render(s)
        assert "handoffs_in 1" in text
        assert "spilled_pages 3" in text
        stats.reset()
        router = _router(disagg="1:1")
        rng = np.random.RandomState(2)
        router.submit(rng.randint(0, 64, (12,)).astype(np.int32),
                      max_new_tokens=4)
        list(router.run())
        out = serve_top.render_fleet(router)
        assert "role prefill" in out and "role decode" in out
        assert "directory:" in out and "host" in out

    def test_convention_prefixes_cover_tier(self):
        from paddle_tpu.profiler.stats import CONVENTION_PREFIXES

        assert "tier." in CONVENTION_PREFIXES
        assert "fleet." in CONVENTION_PREFIXES
