"""Distributed checkpoint: per-rank shards + metadata + load-time
resharding (reference: distributed/checkpoint/save_state_dict.py:104,
load_state_dict.py:365)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.auto_parallel.placement import ProcessMesh


def _mesh(shape, names):
    return ProcessMesh(np.arange(int(np.prod(shape))).reshape(shape),
                       dim_names=names)


def _sharded(arr, mesh, placements):
    t = paddle.to_tensor(arr)
    return dist.shard_tensor(t, mesh, placements)


class TestDistCheckpoint:
    def test_save_mesh8_load_2x4_and_4(self, tmp_path):
        path = str(tmp_path / "ckpt")
        rng = np.random.RandomState(0)
        w = rng.randn(16, 8).astype(np.float32)
        b = rng.randn(8).astype(np.float32)

        mesh8 = _mesh((8,), ["x"])
        sd = {
            "w": _sharded(w, mesh8, [dist.Shard(0)]),
            "b": _sharded(b, mesh8, [dist.Replicate()]),
        }
        dist.save_state_dict(sd, path)
        assert os.path.exists(os.path.join(path, "metadata.json"))

        # load onto a 2x4 mesh, w sharded on dim1 over the second axis
        mesh24 = _mesh((2, 4), ["a", "b"])
        tgt = {
            "w": _sharded(np.zeros_like(w), mesh24,
                          [dist.Replicate(), dist.Shard(1)]),
            "b": _sharded(np.zeros_like(b), mesh24,
                          [dist.Shard(0), dist.Replicate()]),
        }
        dist.load_state_dict(tgt, path)
        np.testing.assert_array_equal(np.asarray(tgt["w"]._data), w)
        np.testing.assert_array_equal(np.asarray(tgt["b"]._data), b)
        # target sharding preserved
        assert not tgt["w"]._data.sharding.is_fully_replicated

        # load onto a 4-device mesh, sharded dim0
        mesh4 = _mesh((4,), ["y"])
        tgt2 = {"w": _sharded(np.zeros_like(w), mesh4, [dist.Shard(0)]),
                "b": _sharded(np.zeros_like(b), mesh4, [dist.Replicate()])}
        dist.load_state_dict(tgt2, path)
        np.testing.assert_array_equal(np.asarray(tgt2["w"]._data), w)

        # plain replicated target
        tgt3 = {"w": paddle.to_tensor(np.zeros_like(w)),
                "b": paddle.to_tensor(np.zeros_like(b))}
        dist.load_state_dict(tgt3, path)
        np.testing.assert_array_equal(np.asarray(tgt3["w"]._data), w)

    def test_replicated_shards_deduplicated(self, tmp_path):
        path = str(tmp_path / "ckpt")
        mesh8 = _mesh((8,), ["x"])
        w = np.arange(32, dtype=np.float32).reshape(8, 4)
        sd = {"w": _sharded(w, mesh8, [dist.Replicate()])}
        dist.save_state_dict(sd, path)
        files = [f for f in os.listdir(path) if f.endswith(".npy")]
        assert len(files) == 1, files  # 8 replicas → 1 file

    def test_missing_tensor_raises(self, tmp_path):
        path = str(tmp_path / "ckpt")
        mesh8 = _mesh((8,), ["x"])
        sd = {"w": _sharded(np.zeros((8, 4), np.float32), mesh8,
                            [dist.Shard(0)])}
        dist.save_state_dict(sd, path)
        tgt = {"nope": paddle.to_tensor(np.zeros((8, 4), np.float32))}
        with pytest.raises(KeyError):
            dist.load_state_dict(tgt, path)

    def test_model_state_roundtrip_resharded(self, tmp_path):
        """End to end: TP-sharded model saved, reloaded onto a different
        topology, numerics identical."""
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed import fleet

        path = str(tmp_path / "model")
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            **strategy.hybrid_configs,
            "dp_degree": 2, "mp_degree": 4, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        fleet.init(is_collective=True, strategy=strategy)
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ColumnParallelLinear)

        paddle.seed(0)
        layer = ColumnParallelLinear(8, 16, gather_output=True)
        sd = {n: p for n, p in layer.named_parameters()}
        dist.save_state_dict(sd, path)

        paddle.seed(123)  # different init
        layer2 = ColumnParallelLinear(8, 16, gather_output=True)
        tgt = {n: p for n, p in layer2.named_parameters()}
        dist.load_state_dict(tgt, path)
        for (n, p1), (_, p2) in zip(layer.named_parameters(),
                                    layer2.named_parameters()):
            np.testing.assert_array_equal(np.asarray(p1._data),
                                          np.asarray(p2._data))
