"""Distributed foundation tests on the virtual 8-device CPU mesh
(SURVEY.md §4: fake-device testing precedent; conftest forces
xla_force_host_platform_device_count=8).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn


# mesh8 / mesh2x4 come from the shared session-scoped conftest fixtures
# (the virtual 8-device CPU mesh the SPMD lint pass also runs on)


class TestProcessMesh:
    def test_shape_names(self, mesh2x4):
        assert mesh2x4.shape == [2, 4]
        assert mesh2x4.dim_names == ["dp", "mp"]
        assert mesh2x4.process_ids == list(range(8))
        assert mesh2x4.get_dim_size("mp") == 4

    def test_jax_mesh(self, mesh2x4):
        m = mesh2x4.jax_mesh()
        assert m.shape == {"dp": 2, "mp": 4}

    def test_equality_hash(self, mesh2x4):
        other = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                                 dim_names=["dp", "mp"])
        assert other == mesh2x4
        assert hash(other) == hash(mesh2x4)


class TestShardReshard:
    def test_shard_tensor_layout(self, mesh2x4):
        x = paddle.randn([8, 16])
        dx = dist.shard_tensor(x, mesh2x4, [dist.Shard(0), dist.Shard(1)])
        shard_shape = dx._data.sharding.shard_shape(dx._data.shape)
        assert shard_shape == (4, 4)  # 8/2 by 16/4
        np.testing.assert_allclose(dx.numpy(), x.numpy())

    def test_replicated(self, mesh8):
        x = paddle.randn([4, 4])
        dx = dist.shard_tensor(x, mesh8, [dist.Replicate()])
        assert dx._data.sharding.is_fully_replicated

    def test_reshard_s_to_r(self, mesh8):
        x = paddle.randn([8, 4])
        dx = dist.shard_tensor(x, mesh8, [dist.Shard(0)])
        r = dist.reshard(dx, mesh8, [dist.Replicate()])
        assert r._data.sharding.is_fully_replicated
        np.testing.assert_allclose(r.numpy(), x.numpy())

    def test_reshard_r_to_s(self, mesh8):
        x = paddle.randn([4, 8])
        dx = dist.shard_tensor(x, mesh8, [dist.Replicate()])
        s = dist.reshard(dx, mesh8, [dist.Shard(1)])
        assert s._data.sharding.shard_shape(s._data.shape) == (4, 1)

    def test_reshard_s_to_s(self, mesh8):
        x = paddle.randn([8, 8])
        dx = dist.shard_tensor(x, mesh8, [dist.Shard(0)])
        s = dist.reshard(dx, mesh8, [dist.Shard(1)])
        assert s._data.sharding.shard_shape(s._data.shape) == (8, 1)
        np.testing.assert_allclose(s.numpy(), x.numpy())

    def test_partial_to_replicate_psum(self, mesh2x4):
        # replicated-local partial: logical value = sum over the dp axis (2)
        p = dist.shard_tensor(paddle.ones([4, 4]), mesh2x4,
                              [dist.Partial(), dist.Replicate()])
        r = dist.reshard(p, mesh2x4, [dist.Replicate(), dist.Replicate()])
        np.testing.assert_allclose(r.numpy(), np.full((4, 4), 2.0))

    def test_unshard(self, mesh8):
        x = paddle.randn([8, 2])
        dx = dist.shard_tensor(x, mesh8, [dist.Shard(0)])
        u = dist.unshard_dtensor(dx)
        assert u._dist_attr is None
        np.testing.assert_allclose(u.numpy(), x.numpy())

    def test_shard_layer(self, mesh8):
        layer = nn.Linear(4, 4)
        dist.shard_layer(layer, mesh8)
        assert layer.weight._dist_attr is not None

    def test_dist_matmul_spmd(self, mesh2x4):
        """GSPMD propagates shardings through a compiled matmul (the
        InferSpmd+reshard path, dist_api_gen.py:49, done by XLA)."""
        a = paddle.randn([8, 16])
        b = paddle.randn([16, 32])
        da = dist.shard_tensor(a, mesh2x4, [dist.Shard(0)])
        db = dist.shard_tensor(b, mesh2x4, [dist.Replicate(), dist.Shard(1)])
        out = paddle.matmul(da, db)
        np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(),
                                   rtol=2e-4)


class TestCollectivesSingleRank:
    """Degenerate (world=1) semantics parity, as in the reference when
    run on one rank."""

    def test_all_reduce_identity(self):
        t = paddle.ones([3])
        task = dist.all_reduce(t)
        assert task.is_completed()
        np.testing.assert_allclose(t.numpy(), np.ones(3))

    def test_all_gather(self):
        lst = []
        dist.all_gather(lst, paddle.ones([2]))
        assert len(lst) == 1

    def test_broadcast_scatter(self):
        t = paddle.zeros([2])
        dist.broadcast(t, src=0)
        dist.scatter(t, [paddle.ones([2])], src=0)
        np.testing.assert_allclose(t.numpy(), np.ones(2))

    def test_reduce_scatter(self):
        out = paddle.zeros([2])
        dist.reduce_scatter(out, [paddle.full([2], 5.0)])
        np.testing.assert_allclose(out.numpy(), np.full(2, 5.0))

    def test_all_to_all(self):
        outs = []
        dist.all_to_all(outs, [paddle.ones([2])])
        assert len(outs) == 1

    def test_send_recv_loopback(self):
        dist.send(paddle.full([2], 7.0), dst=0)
        t = paddle.zeros([2])
        dist.recv(t, src=0)
        np.testing.assert_allclose(t.numpy(), np.full(2, 7.0))

    def test_object_collectives(self):
        objs = []
        dist.all_gather_object(objs, {"a": 1})
        assert objs == [{"a": 1}]

    def test_groups(self):
        g = dist.new_group([0])
        assert g.nranks == 1
        assert dist.get_group(g.id) is g
        assert dist.get_backend() == "xla"


class TestDataParallel:
    def test_wrapper_transparent(self):
        model = nn.Linear(4, 2)
        dp = dist.DataParallel(model)
        x = paddle.randn([3, 4])
        np.testing.assert_allclose(dp(x).numpy(), model(x).numpy())
        dp(x).sum().backward()
        assert model.weight.grad is not None

    def test_state_dict_passthrough(self):
        model = nn.Linear(2, 2)
        dp = dist.DataParallel(model)
        assert set(dp.state_dict()) == set(model.state_dict())

    def test_no_sync_ctx(self):
        dp = dist.DataParallel(nn.Linear(2, 2))
        with dp.no_sync():
            out = dp(paddle.randn([1, 2]))
            out.sum().backward()


class TestDPTrainStepOverMesh:
    """The TPU-native DP path: batch sharded over the mesh, whole step
    compiled, GSPMD adds the gradient allreduce."""

    def test_sharded_batch_training(self, mesh8):
        paddle.seed(0)
        import paddle_tpu.nn.functional as F

        net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
        # replicate params over the mesh
        dist.shard_layer(net, mesh8)
        opt = paddle.optimizer.Adam(0.01, parameters=net.parameters())
        step = paddle.jit.TrainStep(net, F.mse_loss, opt)
        target = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
        rng = np.random.RandomState(0)
        for _ in range(150):
            xb = rng.randn(16, 4).astype(np.float32)
            x = dist.shard_tensor(paddle.to_tensor(xb), mesh8,
                                  [dist.Shard(0)])
            y = dist.shard_tensor(paddle.to_tensor(xb @ target), mesh8,
                                  [dist.Shard(0)])
            loss = step([x], [y])
        assert float(loss.numpy()) < 0.1


class TestCrossMeshReshard:
    """reshard across DIFFERENT meshes (reference: cross-mesh reshard
    functions, reshard_function_registry.h + same_status reshard) —
    device_put retiles between the meshes' shardings."""

    def test_1d_to_2d_mesh(self):
        import numpy as np

        from paddle_tpu.distributed import (ProcessMesh, Replicate,
                                            Shard, reshard, shard_tensor)

        m1 = ProcessMesh(np.arange(8), dim_names=["dp"])
        m2 = ProcessMesh(np.arange(8).reshape(2, 4),
                         dim_names=["dp", "mp"])
        x = paddle.to_tensor(
            np.arange(64, dtype=np.float32).reshape(8, 8))
        dx = shard_tensor(x, m1, [Shard(0)])
        dy = reshard(dx, m2, [Shard(0), Shard(1)])
        assert dy._dist_attr[0].dim_names == ["dp", "mp"]
        np.testing.assert_allclose(dy.numpy(), x.numpy())
        assert dy._data.addressable_shards[0].data.shape == (4, 2)
        # and back to replicated on the original mesh
        dz = reshard(dy, m1, [Replicate()])
        np.testing.assert_allclose(dz.numpy(), x.numpy())
