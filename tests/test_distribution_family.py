"""Distribution family long tail: sample/log_prob/entropy/kl vs scipy.

Reference parity targets: python/paddle/distribution/{beta,dirichlet,
laplace,lognormal,gumbel,multinomial,multivariate_normal,poisson,
binomial,geometric,cauchy,continuous_bernoulli,independent}.py.
"""
import numpy as np
import pytest
import scipy.stats as ss

import paddle_tpu as paddle
import paddle_tpu.distribution as D


def _np(t):
    return np.asarray(t.numpy(), dtype=np.float64)


class TestLogProbVsScipy:
    """log_prob must match scipy's logpdf/logpmf."""

    def test_beta(self):
        d = D.Beta(2.0, 3.0)
        x = np.array([0.1, 0.5, 0.9])
        np.testing.assert_allclose(
            _np(d.log_prob(x)), ss.beta.logpdf(x, 2, 3), rtol=1e-4, atol=1e-6)

    def test_dirichlet(self):
        a = np.array([1.5, 2.0, 3.0])
        d = D.Dirichlet(a.astype(np.float32))
        x = np.array([0.2, 0.3, 0.5])
        np.testing.assert_allclose(
            float(_np(d.log_prob(x.astype(np.float32)))),
            ss.dirichlet.logpdf(x, a), rtol=1e-4, atol=1e-6)

    def test_gamma(self):
        d = D.Gamma(3.0, 2.0)
        x = np.array([0.5, 1.0, 4.0])
        np.testing.assert_allclose(
            _np(d.log_prob(x)), ss.gamma.logpdf(x, 3, scale=0.5),
            rtol=1e-4, atol=1e-6)

    def test_laplace(self):
        d = D.Laplace(1.0, 2.0)
        x = np.array([-1.0, 1.0, 3.0])
        np.testing.assert_allclose(
            _np(d.log_prob(x)), ss.laplace.logpdf(x, 1, 2), rtol=1e-4, atol=1e-6)

    def test_lognormal(self):
        d = D.LogNormal(0.5, 0.8)
        x = np.array([0.5, 1.0, 3.0])
        np.testing.assert_allclose(
            _np(d.log_prob(x)),
            ss.lognorm.logpdf(x, 0.8, scale=np.exp(0.5)), rtol=1e-4, atol=1e-6)

    def test_gumbel(self):
        d = D.Gumbel(1.0, 2.0)
        x = np.array([-1.0, 1.0, 4.0])
        np.testing.assert_allclose(
            _np(d.log_prob(x)), ss.gumbel_r.logpdf(x, 1, 2), rtol=1e-4, atol=1e-6)

    def test_poisson(self):
        d = D.Poisson(3.5)
        k = np.array([0.0, 2.0, 7.0])
        np.testing.assert_allclose(
            _np(d.log_prob(k)), ss.poisson.logpmf(k, 3.5), rtol=1e-4, atol=1e-6)

    def test_binomial(self):
        d = D.Binomial(10, 0.3)
        k = np.array([0.0, 3.0, 10.0])
        np.testing.assert_allclose(
            _np(d.log_prob(k)), ss.binom.logpmf(k, 10, 0.3),
            rtol=1e-4, atol=1e-5)

    def test_geometric(self):
        d = D.Geometric(0.25)
        k = np.array([0.0, 1.0, 5.0])
        # scipy geom counts trials (support {1,..}); ours counts failures
        np.testing.assert_allclose(
            _np(d.log_prob(k)), ss.geom.logpmf(k + 1, 0.25), rtol=1e-4, atol=1e-6)

    def test_cauchy(self):
        d = D.Cauchy(1.0, 2.0)
        x = np.array([-2.0, 1.0, 5.0])
        np.testing.assert_allclose(
            _np(d.log_prob(x)), ss.cauchy.logpdf(x, 1, 2), rtol=1e-4, atol=1e-6)

    def test_multinomial(self):
        d = D.Multinomial(6, np.array([0.2, 0.3, 0.5], np.float32))
        x = np.array([1.0, 2.0, 3.0])
        np.testing.assert_allclose(
            float(_np(d.log_prob(x.astype(np.float32)))),
            ss.multinomial.logpmf(x, 6, [0.2, 0.3, 0.5]), rtol=1e-4, atol=1e-6)

    def test_mvn(self):
        mu = np.array([1.0, -1.0])
        cov = np.array([[2.0, 0.5], [0.5, 1.0]])
        d = D.MultivariateNormal(mu.astype(np.float32),
                                 cov.astype(np.float32))
        x = np.array([0.5, 0.5])
        np.testing.assert_allclose(
            float(_np(d.log_prob(x.astype(np.float32)))),
            ss.multivariate_normal.logpdf(x, mu, cov), rtol=1e-4, atol=1e-6)


class TestEntropyVsScipy:
    def test_entropies(self):
        cases = [
            (D.Beta(2.0, 3.0), ss.beta(2, 3).entropy()),
            (D.Gamma(3.0, 2.0), ss.gamma(3, scale=0.5).entropy()),
            (D.Laplace(1.0, 2.0), ss.laplace(1, 2).entropy()),
            (D.LogNormal(0.5, 0.8),
             ss.lognorm(0.8, scale=np.exp(0.5)).entropy()),
            (D.Gumbel(1.0, 2.0), ss.gumbel_r(1, 2).entropy()),
            (D.Poisson(3.5), ss.poisson(3.5).entropy()),
            (D.Binomial(10, 0.3), ss.binom(10, 0.3).entropy()),
            (D.Cauchy(1.0, 2.0), ss.cauchy(1, 2).entropy()),
        ]
        for d, ref in cases:
            np.testing.assert_allclose(
                float(_np(d.entropy())), float(ref), rtol=1e-4,
                err_msg=type(d).__name__)

    def test_dirichlet_entropy(self):
        a = np.array([1.5, 2.0, 3.0])
        d = D.Dirichlet(a.astype(np.float32))
        np.testing.assert_allclose(
            float(_np(d.entropy())), ss.dirichlet(a).entropy(), rtol=1e-4)

    def test_mvn_entropy(self):
        cov = np.array([[2.0, 0.5], [0.5, 1.0]])
        d = D.MultivariateNormal(np.zeros(2, np.float32),
                                 cov.astype(np.float32))
        np.testing.assert_allclose(
            float(_np(d.entropy())),
            ss.multivariate_normal(np.zeros(2), cov).entropy(), rtol=1e-4)

    def test_geometric_entropy(self):
        d = D.Geometric(0.25)
        np.testing.assert_allclose(
            float(_np(d.entropy())), ss.geom(0.25).entropy(), rtol=1e-4)


class TestSampling:
    """Sample moments approach analytic mean/variance; paddle.seed governs."""

    @pytest.mark.parametrize("dist,mean,var", [
        (lambda: D.Beta(2.0, 3.0), 0.4, 0.04),
        (lambda: D.Gamma(3.0, 2.0), 1.5, 0.75),
        (lambda: D.Laplace(1.0, 2.0), 1.0, 8.0),
        (lambda: D.Gumbel(1.0, 2.0), 1.0 + 2 * 0.57721566, np.pi**2 / 6 * 4),
        (lambda: D.Poisson(3.5), 3.5, 3.5),
        (lambda: D.Binomial(10, 0.3), 3.0, 2.1),
        (lambda: D.Geometric(0.25), 3.0, 12.0),
    ])
    def test_moments(self, dist, mean, var):
        paddle.seed(7)
        s = _np(dist().sample((20000,)))
        np.testing.assert_allclose(s.mean(), mean, rtol=0.1, atol=0.05)
        np.testing.assert_allclose(s.var(), var, rtol=0.2, atol=0.1)

    def test_dirichlet_sample(self):
        paddle.seed(7)
        d = D.Dirichlet(np.array([1.5, 2.0, 3.0], np.float32))
        s = _np(d.sample((5000,)))
        assert s.shape == (5000, 3)
        np.testing.assert_allclose(s.sum(-1), 1.0, atol=1e-5)
        np.testing.assert_allclose(s.mean(0), [1.5 / 6.5, 2 / 6.5, 3 / 6.5],
                                   atol=0.02)

    def test_mvn_sample(self):
        paddle.seed(7)
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
        d = D.MultivariateNormal(np.array([1.0, -1.0], np.float32), cov)
        s = _np(d.sample((20000,)))
        np.testing.assert_allclose(s.mean(0), [1.0, -1.0], atol=0.05)
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.1)

    def test_multinomial_sample(self):
        paddle.seed(7)
        d = D.Multinomial(6, np.array([0.2, 0.3, 0.5], np.float32))
        s = _np(d.sample((2000,)))
        np.testing.assert_allclose(s.sum(-1), 6.0, atol=1e-5)
        np.testing.assert_allclose(s.mean(0), [1.2, 1.8, 3.0], atol=0.15)

    def test_seed_reproducible(self):
        paddle.seed(123)
        a = _np(D.Beta(2.0, 3.0).sample((8,)))
        paddle.seed(123)
        b = _np(D.Beta(2.0, 3.0).sample((8,)))
        np.testing.assert_array_equal(a, b)


class TestKL:
    """Closed-form KL vs numeric integral / MC estimate."""

    def _mc_kl(self, p, q, n=200000, seed=0):
        paddle.seed(seed)
        x = p.sample((n,))
        v = _np(p.log_prob(x)) - _np(q.log_prob(x))
        return float(np.mean(v))

    @pytest.mark.parametrize("make", [
        lambda: (D.Beta(2.0, 3.0), D.Beta(3.0, 2.0)),
        lambda: (D.Gamma(3.0, 2.0), D.Gamma(2.5, 1.0)),
        lambda: (D.Laplace(1.0, 2.0), D.Laplace(0.0, 1.0)),
        lambda: (D.LogNormal(0.5, 0.8), D.LogNormal(0.0, 1.0)),
        lambda: (D.Gumbel(1.0, 2.0), D.Gumbel(0.0, 1.5)),
        lambda: (D.Poisson(3.5), D.Poisson(2.0)),
        lambda: (D.Geometric(0.25), D.Geometric(0.5)),
        lambda: (D.Cauchy(1.0, 2.0), D.Cauchy(0.0, 1.0)),
        lambda: (D.Binomial(10, 0.3), D.Binomial(10, 0.6)),
    ])
    def test_kl_vs_mc(self, make):
        p, q = make()
        kl = float(_np(D.kl_divergence(p, q)))
        mc = self._mc_kl(p, q)
        assert kl >= -1e-6, f"negative KL {kl} for {type(p).__name__}"
        np.testing.assert_allclose(kl, mc, rtol=0.1, atol=0.02,
                                   err_msg=type(p).__name__)

    def test_kl_dirichlet(self):
        p = D.Dirichlet(np.array([1.5, 2.0, 3.0], np.float32))
        q = D.Dirichlet(np.array([2.0, 2.0, 2.0], np.float32))
        kl = float(_np(D.kl_divergence(p, q)))
        mc = self._mc_kl(p, q, n=100000)
        np.testing.assert_allclose(kl, mc, rtol=0.1, atol=0.02)

    def test_kl_mvn(self):
        p = D.MultivariateNormal(
            np.array([1.0, -1.0], np.float32),
            np.array([[2.0, 0.5], [0.5, 1.0]], np.float32))
        q = D.MultivariateNormal(
            np.zeros(2, np.float32), np.eye(2, dtype=np.float32))
        kl = float(_np(D.kl_divergence(p, q)))
        mc = self._mc_kl(p, q, n=100000)
        np.testing.assert_allclose(kl, mc, rtol=0.05, atol=0.02)

    def test_kl_independent(self):
        base_p = D.Normal(np.zeros(3, np.float32),
                          np.ones(3, np.float32))
        base_q = D.Normal(np.ones(3, np.float32),
                          np.full(3, 2.0, np.float32))
        p = D.Independent(base_p, 1)
        q = D.Independent(base_q, 1)
        kl = float(_np(D.kl_divergence(p, q)))
        direct = float(np.sum(_np(D.kl_divergence(base_p, base_q))))
        np.testing.assert_allclose(kl, direct, rtol=1e-6)

    def test_kl_same_is_zero(self):
        for d in (D.Beta(2.0, 3.0), D.Gamma(3.0, 2.0),
                  D.Laplace(1.0, 2.0), D.Poisson(3.0),
                  D.Cauchy(0.0, 1.0)):
            kl = float(_np(D.kl_divergence(d, d)))
            np.testing.assert_allclose(kl, 0.0, atol=1e-5)


class TestStructure:
    def test_independent_shapes(self):
        base = D.Normal(np.zeros((4, 3), np.float32),
                        np.ones((4, 3), np.float32))
        d = D.Independent(base, 1)
        assert d.batch_shape == (4,)
        assert d.event_shape == (3,)
        assert _np(d.log_prob(np.zeros((4, 3), np.float32))).shape == (4,)

    def test_cauchy_no_moments(self):
        d = D.Cauchy(0.0, 1.0)
        with pytest.raises(ValueError):
            _ = d.mean
        with pytest.raises(ValueError):
            _ = d.variance

    def test_continuous_bernoulli(self):
        d = D.ContinuousBernoulli(0.3)
        paddle.seed(5)
        s = _np(d.sample((20000,)))
        assert ((s >= 0) & (s <= 1)).all()
        np.testing.assert_allclose(s.mean(), float(_np(d.mean)), atol=0.01)
        # log_prob integrates to ~1 over [0,1]
        xs = np.linspace(1e-4, 1 - 1e-4, 2001).astype(np.float32)
        dens = np.exp(_np(d.log_prob(xs)))
        np.testing.assert_allclose(np.trapz(dens, xs), 1.0, atol=1e-3)

    def test_mvn_requires_one_param(self):
        with pytest.raises(ValueError):
            D.MultivariateNormal(np.zeros(2, np.float32))
