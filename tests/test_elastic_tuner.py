"""Elastic manager + auto-tuner + comm checks/watchdog tests.

Mirrors the reference's coverage (reference: test/collective/fleet
elastic tests; auto_tuner unit tests; static_check semantics).
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle


class TestElasticManager:
    def _mgr(self, tmp_path, host, np_spec="2:3", ttl=2):
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticManager, LocalFileStore)

        store = LocalFileStore(str(tmp_path / "store"))
        return ElasticManager(job_id="job1", np=np_spec, host=host,
                              store=store, ttl=ttl, elastic_timeout=1)

    def test_parse_np(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        assert ElasticManager._parse_np("4") == (4, 4)
        assert ElasticManager._parse_np("2:8") == (2, 8)

    def test_register_and_membership(self, tmp_path):
        a = self._mgr(tmp_path, "hostA")
        b = self._mgr(tmp_path, "hostB")
        a.register()
        b.register()
        assert a.hosts() == ["hostA", "hostB"]
        assert a.viable()  # 2 in [2,3]
        a.snapshot_launched()
        assert not a.need_scale()
        a.deregister()
        b.deregister()

    def test_scale_event_and_restart_decision(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticStatus

        a = self._mgr(tmp_path, "hostA")
        b = self._mgr(tmp_path, "hostB")
        c = self._mgr(tmp_path, "hostC")
        for m in (a, b):
            m.register()
        a.snapshot_launched()
        assert a.watch_once() == ElasticStatus.HOLD
        c.register()  # scale up: membership changed, still viable (3<=3)
        assert a.watch_once() == ElasticStatus.RESTART
        for m in (a, b, c):
            m.deregister()

    def test_ttl_expiry_detects_dead_host(self, tmp_path):
        a = self._mgr(tmp_path, "hostA", ttl=1)
        b = self._mgr(tmp_path, "hostB", ttl=1)
        a.register()
        b._heartbeat()  # b registers once, no heartbeat thread
        assert set(a.hosts()) == {"hostA", "hostB"}
        time.sleep(1.3)  # b's heartbeat expires, a's thread keeps beating
        assert a.hosts() == ["hostA"]
        assert not a.viable()  # 1 < min 2
        assert not a.wait_viable(poll=0.05)  # times out → exit 101 path
        a.deregister()

    def test_exit_codes(self):
        from paddle_tpu.distributed.fleet.elastic import (
            ELASTIC_AUTO_PARALLEL_EXIT_CODE, ELASTIC_EXIT_CODE)

        assert ELASTIC_EXIT_CODE == 101
        assert ELASTIC_AUTO_PARALLEL_EXIT_CODE == 102


class TestAutoTuner:
    CFG = {
        "num_devices": 8,
        "n_params": 350e6,
        "global_batch_size": 32,
        "num_layers": 24,
        "num_attention_heads": 16,
        "hidden_size": 1024,
        "seq_length": 1024,
    }

    def test_candidates_pruned_by_divisibility(self):
        from paddle_tpu.distributed.auto_tuner import GridSearch

        gs = GridSearch(dict(self.CFG))
        for cfg in gs.all_tasks:
            assert (cfg["dp_degree"] * cfg["mp_degree"]
                    * cfg["pp_degree"]) == 8
            assert cfg["sharding_degree"] <= cfg["dp_degree"]
            assert 24 % cfg["pp_degree"] == 0
        assert len(gs.all_tasks) > 0
        assert len(gs.pruned) > 0

    def test_memory_prune(self):
        from paddle_tpu.distributed.auto_tuner import GridSearch

        tight = dict(self.CFG, memory_limit_bytes=1e9)
        loose = dict(self.CFG, memory_limit_bytes=1e15)
        assert len(GridSearch(tight).all_tasks) < \
            len(GridSearch(loose).all_tasks)

    def test_tune_with_runner_picks_best(self):
        from paddle_tpu.distributed.auto_tuner import AutoTuner

        def runner(cfg):
            if cfg["pp_degree"] > 1:
                raise MemoryError("pretend OOM")
            # pretend throughput: favor dp=4, mp=2
            return 100.0 + (10 if cfg["dp_degree"] == 4 else 0) \
                + (5 if cfg["mp_degree"] == 2 else 0)

        tuner = AutoTuner(dict(self.CFG, task_limit=200))
        best = tuner.tune(runner)  # exhaust the (pruned) grid
        assert best["cfg"]["dp_degree"] == 4
        assert best["cfg"]["mp_degree"] == 2
        assert best["metric"] == 115.0
        # failed trials recorded, not fatal
        assert any(h["error"] for h in tuner.history)

    def test_tune_without_runner_uses_cost_model(self):
        from paddle_tpu.distributed.auto_tuner import AutoTuner

        best = AutoTuner(dict(self.CFG)).tune()
        assert best["cfg"]["dp_degree"] * best["cfg"]["mp_degree"] \
            * best["cfg"]["pp_degree"] == 8

    def test_cost_model_monotonic_in_world(self):
        from paddle_tpu.distributed.auto_tuner import estimate_step_cost

        base = dict(self.CFG, mp_degree=1, pp_degree=1,
                    micro_batch_size=4, recompute=True)
        t1 = estimate_step_cost(dict(base, dp_degree=1))
        t8 = estimate_step_cost(dict(base, dp_degree=8))
        assert t8 < t1  # more chips → faster step


class TestCommChecks:
    def test_check_tensor_list_mismatch(self):
        from paddle_tpu.distributed.check import check_tensor_list

        a = paddle.to_tensor(np.zeros((2, 3), np.float32))
        b = paddle.to_tensor(np.zeros((2, 4), np.float32))
        with pytest.raises(ValueError):
            check_tensor_list([a, b], None, "reduce_scatter")
        c = paddle.to_tensor(np.zeros((2, 3), np.int32))
        with pytest.raises(ValueError):
            check_tensor_list([a, c], None, "reduce_scatter")
        check_tensor_list([a, a], a, "ok")  # no raise

    def test_reduce_scatter_entry_check(self):
        from paddle_tpu.distributed.communication.collectives import (
            reduce_scatter)

        out = paddle.to_tensor(np.zeros(2, np.float32))
        bad = [paddle.to_tensor(np.zeros(2, np.float32)),
               paddle.to_tensor(np.zeros(3, np.float32))]
        with pytest.raises(ValueError):
            reduce_scatter(out, bad)

    def test_watchdog_reports_stuck_op(self):
        from paddle_tpu.core.flags import set_flags
        from paddle_tpu.distributed.check import CommWatchdog

        hits = []
        wd = CommWatchdog(on_timeout=hits.append, scan_interval=0.05)
        set_flags({"comm_timeout_sec": 0.1})
        try:
            with wd.track("fake_allreduce", None):
                time.sleep(0.4)
            assert len(hits) == 1
            assert hits[0]["op"] == "fake_allreduce"
            # completed op is no longer tracked
            assert not wd._inflight
        finally:
            set_flags({"comm_timeout_sec": 300})
            wd.stop()

    def test_dynamic_check_disabled_is_noop(self):
        from paddle_tpu.distributed.check import dynamic_check

        t = paddle.to_tensor(np.zeros(2, np.float32))
        dynamic_check(t, "all_reduce")  # flag off → no store traffic
