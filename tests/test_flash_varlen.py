"""Segment-aware block-skipping varlen flash attention (ISSUE 13).

Covers the tentpole contracts:
- block map skips every cross-segment tile (skip count pinned exactly)
- fwd/grad parity with the dense masked reference on small shapes
- the Pallas kernel (interpret mode) is math-identical to the XLA
  tile-walk fallback
- flash_attn_unpadded no longer retraces per packing (cu_seqlens are
  traced operands — the recompile-storm fix, pinned via fwd_cache)
- attention memory is O(T·d): a T=16k packed batch runs through the
  varlen path while the dense path provably materializes a [h, T, T]
  intermediate
- chunked prefill routes through the paged varlen walk with identical
  hidden states / greedy tokens, and the per-chunk dense
  gather_kv_pages copy is gone from the traced program
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.analysis.jaxpr_util import walk_eqns
from paddle_tpu.incubate.nn.fused_transformer import (
    FusedMultiTransformer, PagedKV, rope_table)
from paddle_tpu.inference.kv_cache import BlockKVCacheManager
from paddle_tpu.nn.functional.attention import (_unpadded_dense_raw,
                                                _unpadded_varlen_raw)
from paddle_tpu.nn.functional.flash_varlen import (
    flash_varlen_packed, paged_prefill_attention, varlen_block_map)
from paddle_tpu.profiler import stats


def _cu(lens):
    return jnp.asarray(np.concatenate([[0], np.cumsum(lens)])
                       .astype(np.int32))


def _qkv(T, h, d, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(T, h, d), jnp.float32)
                 for _ in range(3))


def _dense(q, k, v, cu, scale, causal):
    return _unpadded_dense_raw(q, k, v, cu, cu, scale=scale,
                               causal=causal)


# =====================================================================
# block map
# =====================================================================

class TestBlockMap:
    def test_zero_cross_segment_tiles(self):
        """With tile-aligned segments the visited-tile count equals the
        per-segment closed form EXACTLY — no cross-segment tile is ever
        computed (the skip-count pin)."""
        lens = [256, 512, 128, 384]
        cu = _cu(lens)
        T = int(sum(lens))
        for causal in (False, True):
            bm = varlen_block_map(cu, cu, T, T, 128, 128, causal)
            if causal:
                expected = sum(
                    sum(range(1, L // 128 + 1)) for L in lens)
            else:
                expected = sum((L // 128) ** 2 for L in lens)
            assert int(bm.n_active) == expected, (causal, lens)
            total = (T // 128) ** 2
            assert int(bm.n_active) < total  # actually skipping

    def test_visited_tiles_cover_all_segment_pairs(self):
        """Every (q tile, k tile) pair that contains same-segment
        token pairs is inside the visit intervals (no under-visiting),
        for unaligned segment boundaries and padding."""
        lens = [100, 260, 60]
        cu = _cu(lens)
        T = int(sum(lens))
        Tp = -(-T // 128) * 128
        bm = varlen_block_map(cu, cu, Tp, Tp, 128, 128, False)
        seg = np.searchsorted(np.cumsum(lens), np.arange(T),
                              side="right")
        kstart = np.asarray(bm.kstart)
        klen = np.asarray(bm.klen)
        for i in range(Tp // 128):
            rows = seg[i * 128:(i + 1) * 128]
            if rows.size == 0:
                continue
            for j in range(Tp // 128):
                cols = seg[j * 128:(j + 1) * 128]
                if cols.size and np.intersect1d(rows, cols).size:
                    assert kstart[i] <= j < kstart[i] + klen[i], (i, j)

    def test_transposed_map_consistent(self):
        lens = [200, 312]
        cu = _cu(lens)
        Tp = 512
        bm = varlen_block_map(cu, cu, Tp, Tp, 128, 128, True)
        kstart, klen = np.asarray(bm.kstart), np.asarray(bm.klen)
        qstart2, qlen2 = np.asarray(bm.qstart2), np.asarray(bm.qlen2)
        # forward visit (i, j) implies transposed visit (j, i)
        for i in range(Tp // 128):
            for s in range(klen[i]):
                j = kstart[i] + s
                assert qstart2[j] <= i < qstart2[j] + qlen2[j], (i, j)


# =====================================================================
# numerics: fwd + grads vs the dense masked reference
# =====================================================================

class TestPackedParity:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("lens", [[64, 200, 120], [5, 251, 100, 28]])
    def test_fwd_matches_dense(self, causal, lens):
        cu = _cu(lens)
        T = int(sum(lens))
        q, k, v = _qkv(T, 2, 32)
        scale = 32 ** -0.5
        ref = _dense(q, k, v, cu, scale, causal)
        out = flash_varlen_packed(q, k, v, cu, cu, scale=scale,
                                  causal=causal, backend="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_grads_match_dense(self):
        lens = [64, 200, 120]
        cu = _cu(lens)
        T = int(sum(lens))
        q, k, v = _qkv(T, 2, 32)
        w = jnp.asarray(np.random.RandomState(9).randn(T, 2, 32),
                        jnp.float32)
        scale = 32 ** -0.5

        def loss_dense(q, k, v):
            return jnp.sum(_dense(q, k, v, cu, scale, True) * w)

        def loss_varlen(q, k, v):
            return jnp.sum(flash_varlen_packed(
                q, k, v, cu, cu, scale=scale, causal=True,
                backend="xla") * w)

        g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        g = jax.grad(loss_varlen, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4)

    def test_interpret_kernel_math_identical_to_xla(self):
        """The Pallas kernels run through the interpreter produce
        BITWISE-identical results to the XLA tile walk — same visit
        order, same fp32 accumulation (fwd and both backward
        kernels)."""
        lens = [64, 200]
        cu = _cu(lens)
        T = int(sum(lens))
        q, k, v = _qkv(T, 2, 32)
        w = jnp.asarray(np.random.RandomState(9).randn(T, 2, 32),
                        jnp.float32)

        def run(backend):
            def loss(q, k, v):
                return jnp.sum(flash_varlen_packed(
                    q, k, v, cu, cu, causal=True, backend=backend) * w)

            out = flash_varlen_packed(q, k, v, cu, cu, causal=True,
                                      backend=backend)
            return (out,) + jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        for a, b in zip(run("interpret"), run("xla")):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("T", [1, 130])
    def test_sub_tile_totals(self, T):
        """Totals smaller than (or barely over) one tile: the padded
        tail must stay masked — a partially-padded tile is a BOUNDARY
        tile even when its real rows are one segment (regression: the
        interior test once used pad-clamped aggregates and attended
        the zero-padding)."""
        cu = jnp.asarray([0, T], jnp.int32)
        q, k, v = _qkv(T, 2, 16)
        ref = _dense(q, k, v, cu, 16 ** -0.5, True)
        for backend in ("xla", "interpret"):
            out = flash_varlen_packed(q, k, v, cu, cu, causal=True,
                                      backend=backend)
            np.testing.assert_allclose(np.asarray(out),
                                       np.asarray(ref), atol=2e-5)

    def test_cross_lengths_q_neq_k(self):
        """cu_seqlens_q != cu_seqlens_k (cross-attention packing)."""
        cu_q = jnp.asarray([0, 40, 100], jnp.int32)
        cu_k = jnp.asarray([0, 90, 230], jnp.int32)
        q, _, _ = _qkv(100, 2, 16, seed=1)
        k, v, _ = _qkv(230, 2, 16, seed=2)
        out = flash_varlen_packed(q, k, v, cu_q, cu_k, backend="xla")
        ref = _unpadded_dense_raw(q, k, v, cu_q, cu_k,
                                  scale=16 ** -0.5, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_composes_with_vmap_and_remat(self):
        """The packed training path composes with the parallelism
        machinery: vmap (a batch of packed batches — the sequence/
        data-parallel regime) and jax.checkpoint (the recompute
        training path) both trace through the custom_vjp."""
        lens = [64, 128]
        cu = _cu(lens)
        T = int(sum(lens))
        rng = np.random.RandomState(0)
        qb = jnp.asarray(rng.randn(2, T, 1, 16), jnp.float32)

        @jax.vmap
        def one(q):
            fn = jax.checkpoint(
                lambda q: flash_varlen_packed(q, q, q, cu, cu,
                                              causal=True,
                                              backend="xla"))
            return fn(q)

        out = one(qb)
        g = jax.grad(lambda qb: jnp.sum(one(qb) ** 2))(qb)
        assert out.shape == qb.shape and g.shape == qb.shape
        assert np.isfinite(np.asarray(g)).all()


# =====================================================================
# recompile storm: cu_seqlens as traced operands
# =====================================================================

class TestTraceCountPin:
    def test_repacking_hits_compiled_cache(self):
        """Same shapes + same segment COUNT, different packings: ONE
        compiled program serves them all (the old closure-captured
        cu_seqlens re-traced every call)."""
        T, h, d = 256, 2, 16
        rng = np.random.RandomState(0)
        q = paddle.to_tensor(rng.randn(T, h, d).astype("float32"))
        packings = [[64, 192], [128, 128], [30, 226], [200, 56]]
        hit0 = stats.counter("fwd_cache.hit").value
        admit0 = stats.counter("fwd_cache.admit").value
        outs = []
        for lens in packings:
            cu = paddle.to_tensor(np.asarray(
                np.concatenate([[0], np.cumsum(lens)]), np.int32))
            out, _ = F.flash_attn_unpadded(q, q, q, cu, cu, T, T,
                                           d ** -0.5, causal=True)
            outs.append(out.numpy())
        # call 1 sights, call 2 admits (compiles ONCE), calls 3..4 hit
        assert stats.counter("fwd_cache.admit").value - admit0 == 1
        assert stats.counter("fwd_cache.hit").value - hit0 >= 2
        # and the numbers are right (vs dense, first packing)
        cu0 = _cu(packings[0])
        ref = _dense(jnp.asarray(q.numpy()), jnp.asarray(q.numpy()),
                     jnp.asarray(q.numpy()), cu0, d ** -0.5, True)
        np.testing.assert_allclose(outs[0], np.asarray(ref), atol=2e-5)


# =====================================================================
# memory: O(T·d) vs the dense path's [h, T, T]
# =====================================================================

def _max_eqn_size(closed):
    worst = 0
    for eqn, _ in walk_eqns(closed.jaxpr):
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is not None and hasattr(aval, "size"):
                worst = max(worst, int(aval.size))
    return worst


class TestLongContextMemory:
    T = 16384
    H, D = 1, 64
    LENS = [2048] * 8

    def test_dense_path_provably_quadratic(self):
        """The dense reference materializes a [h, T, T] intermediate at
        T=16k — 1 GiB fp32 per head, provably O(T²): the varlen path's
        biggest intermediate is >100x smaller."""
        cu = _cu(self.LENS)
        sds = jax.ShapeDtypeStruct((self.T, self.H, self.D),
                                   jnp.float32)
        closed = jax.make_jaxpr(
            lambda q, k, v: _unpadded_dense_raw(
                q, k, v, cu, cu, scale=0.125, causal=True))(sds, sds,
                                                           sds)
        dense_worst = _max_eqn_size(closed)
        assert dense_worst >= self.H * self.T * self.T  # the T² mask
        closed_v = jax.make_jaxpr(
            lambda q, k, v: flash_varlen_packed(
                q, k, v, cu, cu, causal=True, backend="xla"))(
                    sds, sds, sds)
        varlen_worst = _max_eqn_size(closed_v)
        assert varlen_worst * 100 <= dense_worst, (
            varlen_worst, dense_worst)
        # O(T·d)-class: bounded by a small multiple of the operand size
        assert varlen_worst <= 8 * self.T * self.H * self.D

    def test_16k_packed_runs_and_is_correct(self):
        """The T=16k packed batch RUNS through the varlen path (the
        dense path would need a 1 GiB [h, T, T] intermediate) and its
        output matches a per-segment dense computation on a sampled
        segment."""
        cu = _cu(self.LENS)
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(self.T, self.H, self.D),
                        jnp.float32)
        out = flash_varlen_packed(q, q, q, cu, cu, causal=True,
                                  backend="xla")
        assert out.shape == (self.T, self.H, self.D)
        # segment 3 alone, dense (2048² is tractable; 16384² is not)
        s, e = 3 * 2048, 4 * 2048
        seg_cu = jnp.asarray([0, 2048], jnp.int32)
        ref = _dense(q[s:e], q[s:e], q[s:e], seg_cu,
                     self.D ** -0.5, True)
        np.testing.assert_allclose(np.asarray(out[s:e]),
                                   np.asarray(ref), atol=2e-5,
                                   rtol=2e-5)


# =====================================================================
# paged variant: chunked prefill / speculative verify
# =====================================================================

def _tiny_stack(seed=13):
    paddle.seed(seed)
    st = FusedMultiTransformer(32, 4, 64, 2, max_position=128)
    cos, sin = rope_table(128, st.head_dim)
    return st, st._stack(), cos, sin


def _prefilled(st, w, cos, sin, b=2, L=10, ps=4, pp=8, pages=64):
    mgr = BlockKVCacheManager(st.num_layers, st.num_kv_heads,
                              st.head_dim, ps, num_pages=pages,
                              reserve_scratch=True)
    for i in range(b):
        mgr.allocate(i, L + 8)
    tables = mgr.block_tables(range(b), pp)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(b, L, 32).astype(np.float32))
    _h, cache = st.prefill_raw(w, x, mgr.fresh_cache(), tables, cos,
                               sin)
    return cache, tables, rng


class TestPagedPrefillRouting:
    def test_chunk_hidden_parity_varlen_vs_gather(self):
        """prefill_chunk_raw through the paged varlen walk ==
        the legacy dense-gather path (hidden states allclose, greedy
        argmax byte-identical)."""
        st, w, cos, sin = _tiny_stack()
        cache, tables, rng = _prefilled(st, w, cos, sin)
        b, L, win = 2, 10, 5
        x = jnp.asarray(rng.randn(b, win, 32).astype(np.float32))
        start = jnp.full((b,), L, jnp.int32)
        clens = jnp.full((b,), win, jnp.int32)

        paddle.set_flags({"prefill_attention_backend": "gather"})
        try:
            h_gather, _ = st.prefill_chunk_raw(
                w, x, cache, tables, start, clens, cos, sin)
        finally:
            paddle.set_flags({"prefill_attention_backend": "auto"})
        h_varlen, _ = st.prefill_chunk_raw(
            w, x, cache, tables, start, clens, cos, sin)
        np.testing.assert_allclose(np.asarray(h_varlen),
                                   np.asarray(h_gather), atol=2e-4,
                                   rtol=2e-4)
        # greedy picks over a projection: byte-identical tokens
        proj = jnp.asarray(rng.randn(32, 64).astype(np.float32))
        t1 = np.asarray(jnp.argmax(h_varlen @ proj, -1))
        t2 = np.asarray(jnp.argmax(h_gather @ proj, -1))
        assert np.array_equal(t1, t2)

    def test_paged_interpret_matches_xla(self):
        st, w, cos, sin = _tiny_stack()
        cache, tables, rng = _prefilled(st, w, cos, sin)
        b, win = 2, 5
        q = jnp.asarray(
            rng.randn(b, win, st.num_heads, st.head_dim)
            .astype(np.float32))
        start = jnp.asarray([10, 3], jnp.int32)
        o1 = paged_prefill_attention(q, cache.k, cache.v, tables,
                                     start, n_kv=st.num_kv_heads,
                                     backend="xla")
        o2 = paged_prefill_attention(q, cache.k, cache.v, tables,
                                     start, n_kv=st.num_kv_heads,
                                     backend="interpret")
        assert np.array_equal(np.asarray(o1), np.asarray(o2))

    def test_gqa_paged_matches_gather_math(self):
        """Grouped-query heads (n_q > n_kv) through the paged walk
        match an explicit gather+softmax reference."""
        b, c, n_kv, g, d, ps, pp, P = 2, 6, 2, 3, 16, 4, 6, 32
        rng = np.random.RandomState(0)
        kc = jnp.asarray(rng.randn(P, n_kv, ps, d), jnp.float32)
        vc = jnp.asarray(rng.randn(P, n_kv, ps, d), jnp.float32)
        tables = jnp.asarray(rng.randint(1, P, (b, pp)), jnp.int32)
        start = jnp.asarray([0, 9], jnp.int32)
        q = jnp.asarray(rng.randn(b, c, n_kv * g, d), jnp.float32)
        scale = d ** -0.5
        out = paged_prefill_attention(q, kc, vc, tables, start,
                                      n_kv=n_kv, scale=scale,
                                      backend="xla")
        # reference: dense gather + masked softmax
        kg = jnp.moveaxis(kc[tables], 2, 3).reshape(b, pp * ps, n_kv,
                                                    d)
        vg = jnp.moveaxis(vc[tables], 2, 3).reshape(b, pp * ps, n_kv,
                                                    d)
        qh = q.reshape(b, c, n_kv, g, d)
        lg = jnp.einsum("btngd,bsnd->bngts",
                        qh.astype(jnp.float32) * scale,
                        kg.astype(jnp.float32))
        pos = start[:, None] + jnp.arange(c)[None, :]
        mask = jnp.arange(pp * ps)[None, None, :] <= pos[:, :, None]
        lg = jnp.where(mask[:, None, None], lg,
                       jnp.finfo(jnp.float32).min)
        wts = jax.nn.softmax(lg, -1)
        ref = jnp.einsum("bngts,bsnd->btngd", wts,
                         vg.astype(jnp.float32)).reshape(b, c,
                                                         n_kv * g, d)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_partial_last_tile_page_alignment(self):
        """pp not divisible by the pages-per-tile (npp=16 at ps=8,
        pp=20) with a deep cached prefix: the last k tile is PARTIAL
        and its pages must stay position-aligned (regression: a
        clamped slice start shifted the whole window backward)."""
        b, c, n_kv, d, ps, pp, P = 2, 4, 1, 16, 8, 20, 48
        rng = np.random.RandomState(3)
        kc = jnp.asarray(rng.randn(P, n_kv, ps, d), jnp.float32)
        vc = jnp.asarray(rng.randn(P, n_kv, ps, d), jnp.float32)
        tables = jnp.asarray(rng.randint(1, P, (b, pp)), jnp.int32)
        start = jnp.asarray([140, 97], jnp.int32)   # deep prefixes
        q = jnp.asarray(rng.randn(b, c, n_kv, d), jnp.float32)
        scale = d ** -0.5
        # dense gather reference
        kg = jnp.moveaxis(kc[tables], 2, 3).reshape(b, pp * ps, n_kv,
                                                    d)
        vg = jnp.moveaxis(vc[tables], 2, 3).reshape(b, pp * ps, n_kv,
                                                    d)
        lg = jnp.einsum("btnd,bsnd->bnts",
                        q.astype(jnp.float32) * scale,
                        kg.astype(jnp.float32))
        pos = start[:, None] + jnp.arange(c)[None, :]
        mask = jnp.arange(pp * ps)[None, None, :] <= pos[:, :, None]
        lg = jnp.where(mask[:, None], lg, jnp.finfo(jnp.float32).min)
        ref = jnp.einsum("bnts,bsnd->btnd", jax.nn.softmax(lg, -1),
                         vg.astype(jnp.float32))
        for backend in ("xla", "interpret"):
            out = paged_prefill_attention(q, kc, vc, tables, start,
                                          n_kv=n_kv, scale=scale,
                                          backend=backend)
            np.testing.assert_allclose(np.asarray(out),
                                       np.asarray(ref), atol=2e-5,
                                       rtol=2e-5, err_msg=backend)

    def test_traced_prefill_has_no_dense_gather(self):
        """The pin from the acceptance criteria: with varlen routing
        the traced prefill-chunk program contains NO intermediate the
        size of the gathered pool span ([b, S, n_kv, d] per side);
        with gather routing it does. The span (pp=64 pages) is sized to
        dwarf every legitimate intermediate (weights, activations, the
        per-step varlen k tile) so the pin discriminates."""
        st, w, cos, sin = _tiny_stack()
        b, win, pp, ps = 2, 5, 64, 4
        cache, tables, rng = _prefilled(st, w, cos, sin, pp=pp, ps=ps,
                                        pages=160)
        S = pp * ps
        gathered = b * S * st.num_kv_heads * st.head_dim
        pool = int(np.prod(cache.k.shape))
        assert pool > gathered  # the pin's discrimination premise
        x = jax.ShapeDtypeStruct((b, win, 32), jnp.float32)
        start = jnp.full((b,), 10, jnp.int32)
        clens = jnp.full((b,), win, jnp.int32)

        def trace():
            return jax.make_jaxpr(
                lambda x, ck, cv: st.prefill_chunk_raw(
                    w, x, PagedKV(ck, cv), tables, start, clens, cos,
                    sin)[0])(x, cache.k, cache.v)

        def has_gathered(closed):
            pool = int(np.prod(cache.k.shape))
            for eqn, _ in walk_eqns(closed.jaxpr):
                for var in eqn.outvars:
                    aval = getattr(var, "aval", None)
                    if aval is None or not hasattr(aval, "size"):
                        continue
                    # a gather output: span-sized but not the pool
                    if int(aval.size) >= gathered \
                            and int(aval.size) < pool:
                        return True
            return False

        paddle.set_flags({"prefill_attention_backend": "gather"})
        try:
            assert has_gathered(trace())
        finally:
            paddle.set_flags({"prefill_attention_backend": "auto"})
        assert not has_gathered(trace())
