"""Fault-tolerant fleet serving (ISSUE 14): health-checked
multi-replica router with crash failover, graceful drain via KV-page
migration, and fleet-wide chaos.

Tier-1 acceptance pins:

- killing 1 of 2 replicas mid-load loses ZERO admitted requests:
  every in-flight request finishes on the survivor with greedy-token
  parity vs an undisturbed run
  (``TestCrashFailover.test_kill_one_of_two_zero_loss_parity``);
- graceful drain migrates a mid-decode request's KV pages across
  replicas with byte-identical subsequent tokens and EXACT page
  accounting on both pools — no recompute on the drain path
  (``TestMigration``);
- prefix-affinity routing beats round-robin on goodput under a
  skewed-prefix Poisson load, pinned deterministically on a
  work-proportional ManualClock (``TestRoutedBeatsRoundRobin``);
- circuit breaker trip/half-open/re-close, the heartbeat
  missed-beat → suspect → dead machine on a ManualClock, hedged
  re-dispatch past a suspect replica, and router-tier
  ``FleetOverloaded`` shedding.
"""
import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import stats
from paddle_tpu.inference import FusedCausalLM
from paddle_tpu.serving import (CircuitBreaker, FaultInjector,
                                FleetOverloaded, FleetRouter,
                                ManualClock, ReplicaKilled, Request,
                                ServerOverloaded, ServingEngine,
                                SLOConfig, use_clock)
from paddle_tpu.serving import faults as faults_mod

pytestmark = pytest.mark.chaos

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model(seed=7, max_position=256):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=max_position)


def _engine(seed=7, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_length", 96)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("slo", SLOConfig(prefill_chunk=8))
    return ServingEngine(_model(seed), **kw)


def _router(n=2, seed=7, policy="affinity", faults=None, **kw):
    return FleetRouter(
        engine_factory=lambda i: _engine(seed, **kw),
        n_replicas=n, policy=policy, faults=faults)


#: fault-free single-engine reference outputs, memoized per workload —
#: chunked-serving parity is prompt-deterministic (pinned since ISSUE
#: 8), so ONE ServingEngine run references every fleet run over the
#: same prompts whatever replica each lands on
_REF_CACHE: dict = {}


def _ref_tokens(prompts, max_new, seed=7):
    key = (tuple(np.asarray(p, np.int32).tobytes() for p in prompts),
           int(max_new), int(seed))
    if key not in _REF_CACHE:
        eng = _engine(seed)
        rids = [eng.submit(p, max_new_tokens=max_new)
                for p in prompts]
        done = {r.id: r for r in eng.run()}
        assert all(done[r].state == "ok" for r in rids)
        _REF_CACHE[key] = [list(done[r].generated) for r in rids]
    return _REF_CACHE[key]


_PROMPTS = None


def _prompts():
    global _PROMPTS
    if _PROMPTS is None:
        rng = np.random.RandomState(0)
        _PROMPTS = [rng.randint(0, 64, (L,)) for L in (6, 10, 14, 9)]
    return _PROMPTS


class _flags:
    """Scoped flag override (flags are process-global)."""

    def __init__(self, **kw):
        self._new = {f"FLAGS_{k}": v for k, v in kw.items()}

    def __enter__(self):
        self._old = paddle.get_flags(list(self._new))
        paddle.set_flags(self._new)
        return self

    def __exit__(self, *exc):
        paddle.set_flags(self._old)


# =====================================================================
# fault kinds / typed errors
# =====================================================================

class TestFaultVocabulary:
    def test_new_sites_registered(self):
        for site in ("router.dispatch", "replica.step",
                     "replica.heartbeat"):
            assert site in faults_mod.FAULT_SITES

    def test_kill_kind_raises_replica_killed(self):
        inj = FaultInjector().add("replica.step", kind="kill", at=1)
        inj.fire("replica.step")                     # hit 0: clean
        with pytest.raises(ReplicaKilled) as ei:
            inj.fire("replica.step")                 # hit 1: kill
        assert ei.value.site == "replica.step"
        assert ei.value.hit == 1

    def test_hang_kind_warps_the_clock(self):
        with use_clock(ManualClock()) as clk:
            inj = FaultInjector().add("replica.step", kind="hang",
                                      at=0, delay_ms=250.0)
            inj.fire("replica.step")
            assert clk.now() == pytest.approx(0.25)
        # default hang duration is far past any heartbeat budget
        with use_clock(ManualClock()) as clk:
            inj = FaultInjector().add("replica.step", kind="hang",
                                      at=0)
            inj.fire("replica.step")
            assert clk.now() == pytest.approx(
                faults_mod.DEFAULT_HANG_MS / 1e3)

    def test_fleet_overloaded_is_server_overloaded(self):
        # producers catching ServerOverloaded keep working unchanged
        assert issubclass(FleetOverloaded, ServerOverloaded)

    def test_fleet_prefix_registered(self):
        assert "fleet." in stats.CONVENTION_PREFIXES

    def test_journal_events_extended(self):
        from paddle_tpu.serving.journal import LIFECYCLE_EVENTS

        for ev in ("failover", "migrate", "drain"):
            assert ev in LIFECYCLE_EVENTS


# =====================================================================
# circuit breaker
# =====================================================================

class TestCircuitBreaker:
    def test_trip_half_open_reclose(self):
        with use_clock(ManualClock()) as clk:
            br = CircuitBreaker(threshold=3, cooldown_ms=100.0)
            assert br.allow()
            br.record_failure()
            br.record_failure()
            assert br.state == "closed"      # under threshold
            br.record_failure()              # 3rd consecutive: trip
            assert br.state == "open" and br.trips == 1
            assert not br.allow()
            clk.advance(0.05)
            assert not br.allow()            # cooldown not elapsed
            clk.advance(0.06)
            assert br.allow()                # half-open probe
            assert br.state == "half_open"
            br.record_success()              # probe succeeded
            assert br.state == "closed" and br.failures == 0

    def test_half_open_failure_reopens(self):
        with use_clock(ManualClock()) as clk:
            br = CircuitBreaker(threshold=2, cooldown_ms=100.0)
            br.record_failure()
            br.record_failure()
            assert br.state == "open"
            clk.advance(0.11)
            assert br.allow()
            br.record_failure()              # probe failed
            assert br.state == "open" and br.trips == 2
            assert not br.allow()

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(threshold=2)
        br.record_failure()
        br.record_success()
        br.record_failure()
        assert br.state == "closed"          # never 2 consecutive

    def test_threshold_follows_flag(self):
        with _flags(fleet_breaker_threshold=5):
            assert CircuitBreaker().threshold == 5

    def test_dispatch_faults_trip_breaker_and_reroute(self):
        """Injected router.dispatch raises always land on replica 0
        (it stays least-loaded because it never admits): three
        consecutive failures OPEN its breaker, every request still
        lands on the peer, and the fleet.circuit_open gauge reports
        the trip."""
        stats.reset()
        # replica 0 is tried first on every submit (empty = least
        # loaded); hits 0/2/4 are exactly those first attempts
        inj = FaultInjector().add("router.dispatch", kind="raise",
                                  at=(0, 2, 4), times=3)
        router = _router(2, faults=inj)
        prompts = _prompts()
        rids = [router.submit(p, max_new_tokens=4) for p in prompts]
        r0, r1 = router.replicas
        assert r0.breaker.state == "open" and r0.breaker.trips == 1
        assert r0.eng.queue_depth == 0       # nothing ever landed
        assert r1.eng.queue_depth == len(rids)
        assert stats.gauge("fleet.circuit_open").value == 1
        done = {r.id: r for r in router.run()}
        assert all(done[r].state == "ok" for r in rids)


# =====================================================================
# heartbeat state machine (ManualClock)
# =====================================================================

class TestHeartbeatStateMachine:
    def test_alive_suspect_dead_walk(self):
        with use_clock(ManualClock()), \
                _flags(fleet_heartbeat_ms=50.0, fleet_suspect_beats=3):
            router = _router(2)
            router.enforce_beats = True
            r0, r1 = router.replicas
            assert (r0.state, r1.state) == ("alive", "alive")
            # r1 beats, r0 goes silent
            clk = faults_mod.clock()
            clk.advance(0.16)                # 3.2 missed beats
            r1.beat()
            router.check_health()
            assert r0.state == "suspect"
            assert r1.state == "alive"
            clk.advance(0.15)                # 6.2 missed total
            r1.beat()
            router.check_health()
            assert r0.state == "dead"
            assert stats.gauge("fleet.replicas_alive").value == 1

    def test_recovered_beats_walk_suspect_back_alive(self):
        with use_clock(ManualClock()), \
                _flags(fleet_heartbeat_ms=50.0, fleet_suspect_beats=3):
            router = _router(2)
            router.enforce_beats = True
            r0, r1 = router.replicas
            faults_mod.clock().advance(0.16)
            r1.beat()
            router.check_health()
            assert r0.state == "suspect"
            r0.beat()                        # it was only slow
            router.check_health()
            assert r0.state == "alive"

    def test_sync_mode_never_beat_kills(self):
        """Without enforce_beats (synchronous driving), wall-clock
        silence never kills a replica — one driver stepping replicas
        sequentially through multi-second compiles must not false-kill
        the fleet. Crash detection stays on."""
        with use_clock(ManualClock()):
            router = _router(2)
            faults_mod.clock().advance(999.0)
            router.check_health()
            assert all(r.state == "alive" for r in router.replicas)

    def test_suppressed_heartbeats_drive_suspicion(self):
        """A raise scheduled at replica.heartbeat SUPPRESSES the stamp
        — the replica keeps stepping but looks silent, which is
        exactly the partial-failure the state machine must catch."""
        with use_clock(ManualClock()), \
                _flags(fleet_heartbeat_ms=50.0, fleet_suspect_beats=3):
            inj = FaultInjector().add("replica.heartbeat",
                                      kind="raise", every=1, times=-1)
            router = _router(1, faults=inj)
            router.enforce_beats = True
            rep = router.replicas[0]
            rep.beat()                       # suppressed
            faults_mod.clock().advance(0.16)
            rep.beat()                       # suppressed again
            router.check_health()
            assert rep.state == "suspect"


# =====================================================================
# crash failover
# =====================================================================

class TestCrashFailover:
    def test_kill_one_of_two_zero_loss_parity(self):
        """THE acceptance pin: killing 1 of 2 replicas mid-load loses
        zero admitted requests — every one finishes on the survivor
        in the ``ok`` state with greedy tokens identical to an
        undisturbed run."""
        stats.reset()
        prompts = _prompts()
        ref = _ref_tokens(prompts, 6)
        router = _router(2)
        rids = [router.submit(p, max_new_tokens=6) for p in prompts]
        for _ in range(3):                   # some tokens in flight
            router.step()
        victim = next(r.idx for r in router.replicas
                      if r.eng.has_work)
        router.kill(victim)
        assert router.replicas[victim].state == "dead"
        done = {r.id: r for r in router.run()}
        assert all(done[r].state == "ok" for r in rids), \
            [(done[r].state, repr(done[r].error)) for r in rids]
        for i, rid in enumerate(rids):
            assert list(done[rid].generated) == ref[i], i
        assert stats.counter("fleet.failovers").value == 1
        assert stats.counter("fleet.failover_requests").value >= 1
        assert stats.gauge("fleet.replicas_alive").value == 1

    def test_injected_kill_at_replica_step(self):
        """The same pin driven end-to-end by a scheduled ``kill``
        fault at the replica.step site (the chaos-bench form)."""
        stats.reset()
        prompts = _prompts()
        ref = _ref_tokens(prompts, 6)
        inj = FaultInjector().add("replica.step", kind="kill", at=4)
        router = _router(2, faults=inj)
        rids = [router.submit(p, max_new_tokens=6) for p in prompts]
        done = {r.id: r for r in router.run()}
        assert sum(r.dead for r in router.replicas) == 1
        assert all(done[r].state == "ok" for r in rids)
        for i, rid in enumerate(rids):
            assert list(done[rid].generated) == ref[i], i
        assert any(f["kind"] == "kill" for f in inj.fired)

    def test_failover_journaled_on_destination(self):
        router = _router(2)
        prompts = _prompts()
        rids = [router.submit(p, max_new_tokens=6) for p in prompts]
        for _ in range(3):
            router.step()
        victim = next(r.idx for r in router.replicas
                      if r.eng.has_work)
        router.kill(victim)
        router.run()
        survivor = router.replicas[1 - victim]
        evs = [e for e in survivor.eng.journal.events()
               if e["ev"] == "failover"]
        assert evs, "no failover event on the survivor's journal"
        assert all(e["from"] == victim and e["to"] == survivor.idx
                   for e in evs)

    def test_all_replicas_dead_fails_requests_not_the_fleet(self):
        """Total fleet death terminates the tracked requests (typed
        errors) instead of hanging run() or raising out of it."""
        router = _router(2)
        rids = [router.submit(p, max_new_tokens=4)
                for p in _prompts()[:2]]
        router.kill(0)
        router.kill(1)
        done = {r.id: r for r in router.run()}
        for rid in rids:
            assert done[rid].state == "error"
            assert isinstance(done[rid].error,
                              (FleetOverloaded, ReplicaKilled))

    def test_submit_after_total_death_sheds(self):
        router = _router(2)
        router.kill(0)
        router.kill(1)
        with pytest.raises(FleetOverloaded):
            router.submit(_prompts()[0], max_new_tokens=4)


# =====================================================================
# graceful drain / KV-page migration
# =====================================================================

class TestMigration:
    def _mid_decode_router(self, n_generated=2, max_new=8):
        """A 2-replica fleet with one request mid-decode on replica
        ``src`` (>= n_generated tokens out, not done)."""
        router = _router(2)
        rid = router.submit(_prompts()[1], max_new_tokens=max_new)
        steps = 0
        while True:
            router.step()
            steps += 1
            assert steps < 500
            req = router.results()[rid]
            if len(req.generated) >= n_generated and not req.done:
                break
        src = next(r.idx for r in router.replicas
                   if r.eng.num_active)
        return router, rid, src

    def test_migration_token_parity_and_exact_accounting(self):
        """THE drain acceptance pin: the mid-decode request's KV pages
        hand over page-granularly (no recompute anywhere on the drain
        path), subsequent tokens are byte-identical to an undisturbed
        run, and page accounting closes EXACTLY on both pools."""
        stats.reset()
        ref = _ref_tokens([_prompts()[1]], 8)[0]
        router, rid, src = self._mid_decode_router()
        src_eng = router.replicas[src].eng
        dst_eng = router.replicas[1 - src].eng
        pages_live = len(src_eng._mgr._owned[
            ("slot", next(i for i in range(src_eng.max_batch)
                          if src_eng._slots[i] is not None))])
        dst_free_before = dst_eng._mgr.free_pages
        router.drain(src)
        assert router.replicas[src].state == "drained"
        # no recompute: pages moved, nothing preempted/re-admitted
        assert stats.counter("fleet.migrations").value == 1
        assert stats.counter("fleet.migrated_pages").value \
            == pages_live
        assert stats.counter("serving.preemptions").value == 0
        # exact accounting: the source pool drained to empty (scratch
        # page 0 stays reserved) with zero live refcounts ...
        assert src_eng._mgr.free_pages == src_eng._mgr.num_pages - 1
        assert src_eng._mgr._refs == {}
        assert src_eng._mgr._owned == {}
        # ... and the destination paid exactly the migrated pages,
        # each at refcount 1
        assert dst_free_before - dst_eng._mgr.free_pages == pages_live
        j = next(i for i in range(dst_eng.max_batch)
                 if dst_eng._slots[i] is not None)
        for p in dst_eng._mgr._owned[("slot", j)]:
            assert dst_eng._mgr.refcount(p) == 1
        # destination journal carries the migrate event and NO
        # admitted event for this request — it never re-prefilled
        evs = dst_eng.journal.events(rid)
        assert any(e["ev"] == "migrate" for e in evs)
        assert not any(e["ev"] == "admitted" for e in evs)
        done = {r.id: r for r in router.run()}
        assert done[rid].state == "ok"
        assert list(done[rid].generated) == ref
        assert stats.counter("serving.preemptions").value == 0

    def test_drain_with_no_peer_slot_falls_back_to_recompute(self):
        """Every destination slot busy -> the drain still empties the
        replica, via the resume path, with token parity."""
        stats.reset()
        prompts = _prompts()
        ref = _ref_tokens(prompts, 6)
        # max_batch=1 per replica: one decoding request each, so the
        # drained replica's slot has nowhere to migrate
        router = _router(2, max_batch=1)
        rids = [router.submit(p, max_new_tokens=6)
                for p in prompts[:2]]
        steps = 0
        while not all(r.eng.num_active for r in router.replicas):
            router.step()
            steps += 1
            assert steps < 500
        router.drain(0)
        assert router.replicas[0].state == "drained"
        assert stats.counter("fleet.migrations").value == 0
        done = {r.id: r for r in router.run()}
        for i, rid in enumerate(rids):
            assert done[rid].state == "ok"
            assert list(done[rid].generated) == ref[i], i

    def test_drained_replica_receives_no_new_dispatch(self):
        router = _router(2)
        router.drain(0)
        assert router.replicas[0].state == "drained"
        rids = [router.submit(p, max_new_tokens=4)
                for p in _prompts()]
        assert router.replicas[0].eng.queue_depth == 0
        done = {r.id: r for r in router.run()}
        assert all(done[r].state == "ok" for r in rids)

    def test_queued_and_prefilling_requests_redispatch(self):
        """Drain of a replica mid-prefill: the half-prefilled request
        re-dispatches (its pages freed) and still finishes with
        parity."""
        prompts = _prompts()
        ref = _ref_tokens(prompts, 6)
        router = _router(2)
        rids = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.step()                        # some mid-prefill
        tgt = next((r.idx for r in router.replicas
                    if r.eng.num_prefilling), None)
        if tgt is None:
            tgt = next(r.idx for r in router.replicas
                       if r.eng.has_work)
        router.drain(tgt)
        eng = router.replicas[tgt].eng
        assert eng.num_prefilling == 0 and eng.queue_depth == 0
        done = {r.id: r for r in router.run()}
        for i, rid in enumerate(rids):
            assert done[rid].state == "ok"
            assert list(done[rid].generated) == ref[i], i


# =====================================================================
# routed beats round-robin (the goodput pin)
# =====================================================================

class TestRoutedBeatsRoundRobin:
    #: the skewed-prefix Poisson workload: 4 distinct system prompts
    #: (16 tokens = 4 full pages) with Zipf-ish popularity, short
    #: bodies, exponential inter-arrival gaps — all seeded
    TTFT_TARGET_MS = 12.0

    def _workload(self):
        rng = np.random.RandomState(3)
        prefixes = [rng.randint(0, 64, (16,)) for _ in range(4)]
        order = list(rng.choice(4, size=12,
                                p=[0.4, 0.3, 0.2, 0.1]))
        bodies = [rng.randint(0, 64, (4,)) for _ in range(12)]
        arrivals = np.cumsum(rng.exponential(0.025, size=12))
        return prefixes, order, bodies, arrivals

    def _run(self, policy):
        """Deterministic serve: Poisson arrivals and TTFTs measured on
        a WORK-PROPORTIONAL ManualClock (1ms per prefill token, 0.1ms
        per decode step) — prefix hits save prefill work, so they save
        'time', exactly the mechanism affinity routing exploits."""
        prefixes, order, bodies, arrivals = self._workload()
        stats.reset()
        with use_clock(ManualClock()) as clk:
            router = _router(2, policy=policy)

            def work_ms():
                return (stats.counter("serve.prefill_tokens").value
                        * 1.0
                        + stats.counter(
                            "serving.decode_steps").value * 0.1)

            rids, w0, i, steps = [], work_ms(), 0, 0
            while i < len(order) or router.pending():
                while i < len(order) and clk.now() >= arrivals[i]:
                    prompt = np.concatenate(
                        [prefixes[order[i]], bodies[i]])
                    rids.append(router.submit(prompt,
                                              max_new_tokens=4))
                    i += 1
                did = False
                for rep in router.replicas:
                    did = rep.step_once() or did
                    w1 = work_ms()
                    clk.advance((w1 - w0) / 1e3)
                    w0 = w1
                if not did and i < len(order):
                    clk.advance(max(arrivals[i] - clk.now(), 0.0)
                                + 1e-6)
                steps += 1
                assert steps < 20000
            done = router.results()
            ttfts = [done[r].ttft_s * 1e3 for r in rids]
        goodput = sum(t <= self.TTFT_TARGET_MS
                      for t in ttfts) / len(ttfts)
        return goodput, \
            stats.counter("serving.prefix_pages_saved").value

    def test_affinity_beats_round_robin_goodput(self):
        good_aff, saved_aff = self._run("affinity")
        good_rr, saved_rr = self._run("rr")
        # affinity keeps every prefix on ONE replica: fewer cold
        # prefills fleet-wide -> strictly more pages saved AND
        # strictly better goodput at the pinned target
        assert saved_aff > saved_rr, (saved_aff, saved_rr)
        assert good_aff > good_rr, (good_aff, good_rr)

    def test_affinity_routes_same_prefix_to_same_replica(self):
        router = _router(2)
        rng = np.random.RandomState(5)
        prefix = rng.randint(0, 64, (8,))    # 2 full pages
        reps = []
        for _ in range(4):
            body = rng.randint(0, 64, (5,))
            rep = router._dispatch(Request(
                np.concatenate([prefix, body]), 4))
            reps.append(rep.idx)
        assert len(set(reps)) == 1
        # a disjoint prefix balances to the OTHER (now less loaded)
        other = router._dispatch(Request(rng.randint(0, 64, (9,)), 4))
        assert other.idx != reps[0]


# =====================================================================
# hedging + router-tier shedding
# =====================================================================

class TestHedgingAndShedding:
    def test_suspect_inbox_hedges_to_healthy_peer(self):
        stats.reset()
        with use_clock(ManualClock()), \
                _flags(fleet_heartbeat_ms=50.0, fleet_suspect_beats=3):
            router = _router(2)
            router.enforce_beats = True
            rng = np.random.RandomState(5)
            prefix = rng.randint(0, 64, (8,))
            rids = [router.submit(
                np.concatenate([prefix, rng.randint(0, 64, (4,))]),
                max_new_tokens=4) for _ in range(2)]
            tgt = router.replicas[
                router._affinity[router._affinity_chain(prefix)[0]]]
            assert len(tgt.eng._inbox) == 2
            other = router.replicas[1 - tgt.idx]
            # tgt goes silent; the peer keeps beating
            faults_mod.clock().advance(0.16)
            other.beat()
            router.check_health()
            assert tgt.state == "suspect"
            assert len(tgt.eng._inbox) == 0      # stolen
            assert stats.counter("fleet.hedges").value == 2
            done = {r.id: r for r in router.run()}
            assert all(done[r].state == "ok" for r in rids)
            # the hedged requests ran on the healthy peer
            assert {r.id for r in other.eng.finished} == set(rids)

    def test_dispatch_queue_bound_sheds_typed(self):
        stats.reset()
        with _flags(fleet_dispatch_queue=2):
            router = _router(2)
            router.submit(_prompts()[0], max_new_tokens=4)
            router.submit(_prompts()[1], max_new_tokens=4)
            with pytest.raises(FleetOverloaded):
                router.submit(_prompts()[2], max_new_tokens=4)
            assert stats.counter("fleet.shed").value == 1
            # shed before ANY replica admitted it
            assert sum(r.eng.queue_depth
                       for r in router.replicas) == 2
            done = router.run()
            assert all(r.state == "ok" for r in done)

    def test_engine_shed_reroutes_via_breaker(self):
        """A replica whose OWN inbox bound rejects (engine-tier
        ServerOverloaded) counts as a dispatch failure: the router
        retries the peer instead of surfacing the shed, and the
        request is never lost."""
        with _flags(serve_inbox_limit=1):
            router = _router(2)
            rng = np.random.RandomState(5)
            prefix = rng.randint(0, 64, (8,))
            mk = lambda: np.concatenate(  # noqa: E731
                [prefix, rng.randint(0, 64, (4,))])
            rid1 = router.submit(mk(), max_new_tokens=4)
            tgt = router.replicas[
                router._affinity[router._affinity_chain(prefix)[0]]]
            # same prefix routes to tgt first, whose inbox (limit 1)
            # rejects -> breaker failure -> peer takes it
            rid2 = router.submit(mk(), max_new_tokens=4)
            other = router.replicas[1 - tgt.idx]
            assert tgt.breaker.failures == 1
            assert tgt.eng.queue_depth == 1
            assert other.eng.queue_depth == 1
            done = {r.id: r for r in router.run()}
            assert done[rid1].state == "ok"
            assert done[rid2].state == "ok"


# =====================================================================
# serve_top / bench plumbing
# =====================================================================

class TestFleetTooling:
    def test_render_fleet_and_offline_dashboard(self):
        sys.path.insert(0, _REPO)
        from tools import serve_top

        router = _router(2)
        rids = [router.submit(p, max_new_tokens=4)
                for p in _prompts()]
        for _ in range(3):
            router.step()
        victim = next(r.idx for r in router.replicas
                      if r.eng.has_work)
        router.kill(victim)
        router.run()
        live = serve_top.render_fleet(router)
        assert "replicas (policy affinity)" in live
        assert "dead" in live and "failovers" in live
        with tempfile.TemporaryDirectory() as d:
            paths = router.export_journals(d)
            assert len(paths) == 2
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "tools", "serve_top.py"),
                 "--fleet"] + paths,
                capture_output=True, text=True, timeout=120)
            assert proc.returncode == 0, proc.stderr[-2000:]
            assert "replica journals" in proc.stdout
            assert "merged fleet view:" in proc.stdout
            # replica-stamped chrome traces fold through trace_merge:
            # one pid per replica in a single fleet timeline
            from tools.trace_merge import merge_traces

            tpaths = router.export_traces(d)
            merged = merge_traces(tpaths)
            pids = {e.get("pid") for e in merged["traceEvents"]}
            assert pids == {0, 1}
        # every tracked request's journal lanes fold by rid across
        # replica files; the survivor's journal carries the failover
        survivor = router.replicas[1 - victim]
        assert any(e["ev"] == "failover"
                   for e in survivor.eng.journal.events())
        assert all(router.results()[r].state == "ok" for r in rids)

    def test_bench_gate_directions_for_fleet_keys(self):
        from tools.bench_gate import DEFAULT_METRICS

        assert DEFAULT_METRICS["fleet_goodput"] == "down"
        assert DEFAULT_METRICS["fleet_tokens_per_sec"] == "down"
        assert DEFAULT_METRICS["fleet_p99_ttft_ms"] == "up"
        assert DEFAULT_METRICS["fleet_chaos_survivor_parity"] \
            == "down"
        assert DEFAULT_METRICS["fleet_chaos_lost"] == "up"
        assert DEFAULT_METRICS["fleet_chaos_request_errors"] == "up"
        assert DEFAULT_METRICS["fleet_failovers"] == "up"

    def test_serve_bench_fleet_chaos_cli(self, tmp_path):
        """CPU CLI smoke of the fleet bench WITH the chaos pins: the
        bench itself exits nonzero if the zero-loss failover, parity,
        goodput-bound, or site-coverage pins fail.  ISSUE 16 rides the
        same run: ``--telemetry-out`` dumps the time series, and the
        chaos re-drive's replica kill must show up as a fired
        ``fleet-replica-down`` alert in the ``.chaos`` dump."""
        tele = str(tmp_path / "fleet.jsonl")
        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "serve_bench.py"),
             "--fleet", "2", "--streams", "2", "--requests", "8",
             "--seed", "0", "--prompt-mix", "6,14",
             "--system-prompt", "8", "--system-prompts", "3",
             "--max-new", "4", "--prefill-chunk", "8",
             "--decode-chunk", "2", "--d-model", "32",
             "--layers", "1", "--heads", "2", "--vocab", "64",
             "--rate", "200", "--chaos", "--no-lint",
             "--telemetry-out", tele,
             "--telemetry-interval-ms", "20"],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, \
            proc.stdout[-1000:] + proc.stderr[-2000:]
        doc = json.loads(
            [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")][-1])
        assert doc["fleet_replicas"] == 2
        for key in ("fleet_goodput", "fleet_tokens_per_sec",
                    "fleet_p99_ttft_ms"):
            assert isinstance(doc[key], (int, float)), key
        assert doc["fleet_chaos_survivor_parity"] == 1.0
        assert doc["fleet_chaos_lost"] == 0
        assert doc["fleet_chaos_replicas_dead"] == 1
        assert doc["fleet_chaos_failovers"] >= 1
        assert len(doc["fleet_chaos_sites_fired"]) >= 5
        # the injected kill fired the replica-down alert and the
        # series dumps landed on disk
        assert doc["fleet_chaos_alert_fired"] >= 1
        assert doc["telemetry_ticks"] >= 1
        assert os.path.exists(tele)
        with open(tele + ".chaos") as f:
            ticks = [json.loads(ln) for ln in f if ln.strip()]
        alert_ticks = [t for t in ticks
                       if "fleet-replica-down" in t.get("alerts", ())]
        assert alert_ticks, "replica kill never reached the sampler"
        # the killed replica stays dead, so the alert is still firing
        # at the sampler's final (stop-time) tick
        assert "fleet-replica-down" in ticks[-1].get("alerts", ())
