"""LocalFS/HDFSClient + model crypto (closes SURVEY row 28: the
string/crypto/io long tail — reference fleet/utils/fs.py and
framework/io/crypto/)."""
import os

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.utils import LocalFS
from paddle_tpu.distributed.fleet.utils.fs import (
    FSFileExistsError, FSFileNotExistsError, HDFSClient)
from paddle_tpu.utils.crypto import Cipher, CipherFactory, CipherUtils


class TestLocalFS:
    def test_dir_file_lifecycle(self, tmp_path):
        fs = LocalFS()
        d = str(tmp_path / "a" / "b")
        fs.mkdirs(d)
        assert fs.is_dir(d) and fs.is_exist(d)
        f = os.path.join(d, "x.txt")
        fs.touch(f)
        assert fs.is_file(f)
        with open(f, "w") as fh:
            fh.write("hello")
        assert fs.cat(f) == "hello"
        dirs, files = fs.ls_dir(str(tmp_path / "a"))
        assert dirs == ["b"] and files == []
        dirs, files = fs.ls_dir(d)
        assert files == ["x.txt"]
        assert fs.list_dirs(str(tmp_path / "a")) == ["b"]

    def test_mv_semantics(self, tmp_path):
        fs = LocalFS()
        src, dst = str(tmp_path / "s"), str(tmp_path / "d")
        fs.touch(src)
        fs.touch(dst)
        with pytest.raises(FSFileExistsError):
            fs.mv(src, dst)
        fs.mv(src, dst, overwrite=True)
        assert not fs.is_exist(src) and fs.is_exist(dst)
        with pytest.raises(FSFileNotExistsError):
            fs.mv(str(tmp_path / "missing"), dst, test_exists=True)

    def test_upload_download_delete(self, tmp_path):
        fs = LocalFS()
        src = str(tmp_path / "f.bin")
        with open(src, "wb") as fh:
            fh.write(b"\x01\x02")
        fs.upload(src, str(tmp_path / "g.bin"))
        assert fs.cat(str(tmp_path / "g.bin")) == "\x01\x02"
        fs.delete(str(tmp_path / "g.bin"))
        assert not fs.is_exist(str(tmp_path / "g.bin"))
        assert fs.need_upload_download() is False

    def test_hdfs_without_hadoop_raises(self):
        if os.environ.get("HADOOP_HOME") or \
                __import__("shutil").which("hadoop"):
            pytest.skip("hadoop present")
        with pytest.raises(RuntimeError, match="LocalFS"):
            HDFSClient()


class TestCrypto:
    def test_roundtrip_and_file(self, tmp_path):
        key = CipherUtils.gen_key_to_file(32, str(tmp_path / "k"))
        c = Cipher(key)
        msg = os.urandom(1000) + b"model-bytes"
        blob = c.encrypt(msg)
        assert blob != msg and len(blob) > len(msg)
        assert c.decrypt(blob) == msg
        c.encrypt_to_file(msg, str(tmp_path / "m.enc"))
        c2 = CipherFactory.create_cipher(str(tmp_path / "k"))
        assert c2.decrypt_from_file(str(tmp_path / "m.enc")) == msg

    def test_wrong_key_and_tamper_detected(self, tmp_path):
        c = Cipher(b"0" * 32)
        blob = c.encrypt(b"secret weights")
        with pytest.raises(ValueError, match="authentication"):
            Cipher(b"1" * 32).decrypt(blob)
        tampered = bytearray(blob)
        tampered[-1] ^= 0xFF
        with pytest.raises(ValueError, match="authentication"):
            c.decrypt(bytes(tampered))

    def test_nondeterministic_nonce(self):
        c = Cipher(b"0" * 32)
        assert c.encrypt(b"x") != c.encrypt(b"x")

    def test_v1_format_still_decrypts(self):
        """Blobs written by the r4 per-block-HMAC format (v1 magic)
        must keep decrypting after the SHAKE-256 v2 keystream switch."""
        import hashlib
        import hmac as hmac_mod

        from paddle_tpu.utils import crypto as C

        c = Cipher(b"0" * 32)
        msg = os.urandom(4096) + b"legacy"
        nonce = os.urandom(16)
        ks = c._keystream_v1(nonce, len(msg))
        ct = c._xor(msg, ks)
        tag = hmac_mod.new(c._mac_key, C._MAGIC_V1 + nonce + ct,
                           hashlib.sha256).digest()
        assert c.decrypt(C._MAGIC_V1 + nonce + tag + ct) == msg

    def test_keystream_is_one_shot_xof(self):
        """v2 keystream must be the single-call SHAKE-256 XOF (the
        revert-to-per-block-HMAC-loop regression, ADVICE r4) —
        asserted structurally, no load-sensitive wall-clock bound."""
        import hashlib

        c = Cipher(b"0" * 32)
        nonce = b"n" * 16
        n = 1 << 20
        assert c._keystream(nonce, n) == \
            hashlib.shake_256(c._enc_key + nonce).digest(n)

    def test_encrypted_model_artifact_roundtrip(self, tmp_path):
        """End-to-end: encrypt a jit.save params artifact at rest."""
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.static.input_spec import InputSpec

        paddle.seed(0)
        m = nn.Linear(4, 2)
        m.eval()
        x = paddle.to_tensor(np.ones((1, 4), np.float32))
        ref = m(x).numpy()
        path = str(tmp_path / "net")
        paddle.jit.save(m, path, input_spec=[InputSpec([1, 4], "float32")])
        key = CipherUtils.gen_key(32)
        c = Cipher(key)
        for ext in (".pdmodel", ".pdiparams"):
            with open(path + ext, "rb") as f:
                c.encrypt_to_file(f.read(), path + ext + ".enc")
            os.remove(path + ext)
        # consumer decrypts then loads
        for ext in (".pdmodel", ".pdiparams"):
            with open(path + ext, "wb") as f:
                f.write(c.decrypt_from_file(path + ext + ".enc"))
        loaded = paddle.jit.load(path)
        np.testing.assert_allclose(loaded(x).numpy(), ref, atol=1e-6)
