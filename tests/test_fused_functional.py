"""incubate.nn.functional fused-op tests (reference:
test/legacy_test/test_fused_* suites)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as FF
from paddle_tpu.incubate.nn import rope_table


def test_fused_rotary_position_embedding():
    rng = np.random.RandomState(0)
    b, s, h, d = 2, 4, 2, 8
    q = paddle.to_tensor(rng.randn(b, s, h, d).astype(np.float32))
    k = paddle.to_tensor(rng.randn(b, s, h, d).astype(np.float32))
    cos, sin = rope_table(16, d)
    qq, kk, vv = FF.fused_rotary_position_embedding(
        q, k, None, sin=paddle.Tensor(sin), cos=paddle.Tensor(cos))
    assert vv is None
    # position 0 is identity; norms are preserved (rotation)
    np.testing.assert_allclose(qq.numpy()[:, 0], q.numpy()[:, 0],
                               rtol=1e-5)
    np.testing.assert_allclose(
        np.linalg.norm(qq.numpy(), axis=-1),
        np.linalg.norm(q.numpy(), axis=-1), rtol=1e-4)
    # position_ids override the implicit arange
    pos = paddle.to_tensor(np.zeros((b, s), np.int32))
    q0, k0, _ = FF.fused_rotary_position_embedding(
        q, k, None, sin=paddle.Tensor(sin), cos=paddle.Tensor(cos),
        position_ids=pos)
    np.testing.assert_allclose(q0.numpy(), q.numpy(), rtol=1e-5)


def test_fused_layer_norm_residual():
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(2, 8).astype(np.float32))
    res = paddle.to_tensor(rng.randn(2, 8).astype(np.float32))
    w = paddle.to_tensor(np.ones(8, np.float32))
    b = paddle.to_tensor(np.zeros(8, np.float32))
    out, res_out = FF.fused_layer_norm(x, w, b, residual=res)
    np.testing.assert_allclose(res_out.numpy(),
                               x.numpy() + res.numpy(), rtol=1e-5)
    h = res_out.numpy()
    ref = (h - h.mean(-1, keepdims=True)) / np.sqrt(
        h.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_fused_linear_grad():
    rng = np.random.RandomState(2)
    x = paddle.to_tensor(rng.randn(2, 8).astype(np.float32))
    w = paddle.to_tensor(rng.randn(8, 4).astype(np.float32),
                         stop_gradient=False)
    bias = paddle.to_tensor(np.ones(4, np.float32))
    out = FF.fused_linear(x, w, bias)
    np.testing.assert_allclose(out.numpy(),
                               x.numpy() @ w.numpy() + 1.0, rtol=1e-4)
    out.sum().backward()
    assert w.grad is not None


def test_fused_multi_head_attention():
    rng = np.random.RandomState(3)
    dm, nh = 16, 4
    x = paddle.to_tensor(rng.randn(2, 6, dm).astype(np.float32))
    qkvw = paddle.to_tensor(rng.randn(dm, 3 * dm).astype(np.float32)
                            * 0.1)
    lw = paddle.to_tensor(rng.randn(dm, dm).astype(np.float32) * 0.1)
    out = FF.fused_multi_head_attention(x, qkvw, lw, num_heads=nh,
                                        causal=True)
    assert out.shape == [2, 6, dm]
    # residual identity: zero projection weight -> output == input
    zero_lw = paddle.to_tensor(np.zeros((dm, dm), np.float32))
    out0 = FF.fused_multi_head_attention(x, qkvw, zero_lw, num_heads=nh)
    np.testing.assert_allclose(out0.numpy(), x.numpy(), rtol=1e-5)


def test_rope_v_passthrough_without_k():
    rng = np.random.RandomState(4)
    b, s, h, d = 1, 3, 2, 8
    q = paddle.to_tensor(rng.randn(b, s, h, d).astype(np.float32))
    v = paddle.to_tensor(rng.randn(b, s, h, d).astype(np.float32))
    cos, sin = rope_table(16, d)
    qq, kk, vv = FF.fused_rotary_position_embedding(
        q, None, v, sin=paddle.Tensor(sin), cos=paddle.Tensor(cos))
    assert kk is None
    np.testing.assert_allclose(vv.numpy(), v.numpy())  # v NOT rotated


def test_mha_post_layer_norm():
    rng = np.random.RandomState(5)
    dm, nh = 16, 4
    x = paddle.to_tensor(rng.randn(2, 4, dm).astype(np.float32))
    qkvw = paddle.to_tensor(rng.randn(dm, 3 * dm).astype(np.float32)
                            * 0.1)
    zero_lw = paddle.to_tensor(np.zeros((dm, dm), np.float32))
    w = paddle.to_tensor(np.ones(dm, np.float32))
    b = paddle.to_tensor(np.zeros(dm, np.float32))
    out = FF.fused_multi_head_attention(
        x, qkvw, zero_lw, num_heads=nh, pre_layer_norm=False,
        ln_scale=w, ln_bias=b)
    # zero projection -> residual == x; post-LN applies to it
    h = x.numpy()
    ref = (h - h.mean(-1, keepdims=True)) / np.sqrt(
        h.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


class TestASP:
    def test_prune_and_density(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate.asp import (calculate_density,
                                             check_mask_1d, prune_model)

        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(16, 32)
                self.fc2 = nn.Linear(32, 8)

            def forward(self, x):
                return self.fc2(self.fc1(x))

        net = Net()
        report = prune_model(net)
        for name, density in report.items():
            assert abs(density - 0.5) < 1e-6, (name, density)
        assert check_mask_1d(net.fc1.weight)
        assert check_mask_1d(net.fc2.weight)
        assert abs(calculate_density(net.fc1.weight) - 0.5) < 1e-6

    def test_sparsity_survives_training(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu.incubate.asp import (check_mask_1d, decorate,
                                             prune_model)

        paddle.seed(1)
        net = nn.Linear(8, 8)
        prune_model(net)
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        net, opt = decorate(net, opt)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        for _ in range(3):
            loss = F.mse_loss(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        # dense SGD updates would densify the weight; the decorated
        # optimizer re-applies the 2:4 mask each step
        assert check_mask_1d(net.weight)

    def test_non_divisible_width_and_mask_algo(self):
        import numpy as np

        from paddle_tpu.incubate.asp import (check_mask_1d, create_mask)
        import pytest

        w = np.random.RandomState(0).randn(8, 6).astype(np.float32)
        mask = create_mask(w)  # groups never straddle rows
        assert check_mask_1d(w * mask)
        # each row's first group of 4 has exactly 2 kept
        assert (np.count_nonzero(mask[:, :4], axis=1) == 2).all()
        with pytest.raises(NotImplementedError):
            create_mask(w, mask_algo="mask_2d_best")

    def test_check_mask_2d_column_concentration(self):
        import numpy as np

        from paddle_tpu.incubate.asp import check_mask_1d, check_mask_2d

        # every row keeps the SAME two columns: 1-D valid, 2-D invalid
        m = np.zeros((4, 4), np.float32)
        m[:, :2] = 1.0
        assert check_mask_1d(m)
        assert not check_mask_2d(m)


class TestIncubateFusedLayers:
    """Round-3 layer-class fills (reference: incubate/nn/layer/
    fused_transformer.py FusedMultiHeadAttention:196 FusedFeedForward:502
    FusedTransformerEncoderLayer:728, fused_linear.py:19,
    fused_dropout_add.py:19, fused_ec_moe.py:19)."""

    def test_fused_linear(self):
        from paddle_tpu.incubate.nn import FusedLinear

        paddle.seed(0)
        fl = FusedLinear(8, 4)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(3, 8).astype(np.float32))
        out = fl(x)
        ref = x.numpy() @ fl.weight.numpy() + fl.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)
        # transpose_weight stores [out, in]
        flt = FusedLinear(8, 4, transpose_weight=True)
        assert tuple(flt.weight.shape) == (4, 8)
        out_t = flt(x)
        np.testing.assert_allclose(
            out_t.numpy(), x.numpy() @ flt.weight.numpy().T
            + flt.bias.numpy(), rtol=1e-5)

    def test_fused_dropout_add_eval_identity(self):
        from paddle_tpu.incubate.nn import FusedDropoutAdd

        fda = FusedDropoutAdd(p=0.9)
        fda.eval()
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        y = paddle.to_tensor(np.full((2, 3), 2.0, np.float32))
        np.testing.assert_allclose(fda(x, y).numpy(), 3.0)

    def test_bias_dropout_residual_ln(self):
        from paddle_tpu.incubate.nn import (
            FusedBiasDropoutResidualLayerNorm)

        paddle.seed(1)
        l = FusedBiasDropoutResidualLayerNorm(6, dropout_rate=0.0)
        l.eval()
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(2, 6).astype(np.float32))
        r = paddle.to_tensor(rng.randn(2, 6).astype(np.float32))
        out = l(x, r).numpy()
        h = x.numpy() + l.linear_bias.numpy() + r.numpy()
        mu = h.mean(-1, keepdims=True)
        sd = h.std(-1, keepdims=True)
        ref = (h - mu) / np.sqrt(sd ** 2 + 1e-5) * l.ln_scale.numpy() \
            + l.ln_bias.numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_encoder_layer_forward_and_train(self):
        from paddle_tpu.incubate.nn import FusedTransformerEncoderLayer

        paddle.seed(2)
        enc = FusedTransformerEncoderLayer(16, 4, 32, dropout_rate=0.0)
        x = paddle.to_tensor(np.random.RandomState(2)
                             .randn(2, 5, 16).astype(np.float32))
        out = enc(x)
        assert tuple(out.shape) == (2, 5, 16)
        opt = paddle.optimizer.Adam(1e-3, parameters=enc.parameters())
        loss = (out ** 2).mean()
        loss.backward()
        opt.step()
        assert np.isfinite(float(loss.numpy()))

    def test_fused_ec_moe(self):
        from paddle_tpu.incubate.nn import FusedEcMoe

        paddle.seed(3)
        moe = FusedEcMoe(8, 16, num_experts=3, act_type="gelu")
        x = paddle.to_tensor(np.random.RandomState(3)
                             .randn(2, 4, 8).astype(np.float32))
        out = moe(x)
        assert tuple(out.shape) == (2, 4, 8)
        # single-expert sanity: output equals that expert's FFN
        moe1 = FusedEcMoe(8, 16, num_experts=1, act_type="relu")
        o1 = moe1(x).numpy()
        import scipy.special  # noqa: F401
        h = np.maximum(
            x.numpy() @ moe1.w1.numpy()[0] + moe1.b1.numpy()[0], 0)
        ref = h @ moe1.w2.numpy()[0] + moe1.b2.numpy()[0]
        np.testing.assert_allclose(o1, ref, rtol=1e-4, atol=1e-5)

    def test_fused_ec_moe_gradients_flow(self):
        """MoE params and inputs must receive gradients (review fix:
        forward now routes through the dispatch tape)."""
        from paddle_tpu.incubate.nn import FusedEcMoe

        paddle.seed(4)
        moe = FusedEcMoe(8, 16, num_experts=2, act_type="gelu")
        x = paddle.to_tensor(np.random.RandomState(4)
                             .randn(2, 3, 8).astype(np.float32))
        x.stop_gradient = False
        loss = (moe(x) ** 2).mean()
        loss.backward()
        assert x.grad is not None and np.abs(x.grad.numpy()).sum() > 0
        for p in (moe.gate, moe.w1, moe.b1, moe.w2, moe.b2):
            assert p.grad is not None, p.name
            assert np.isfinite(p.grad.numpy()).all()

    def test_mha_guards_and_out_dropout(self):
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention

        paddle.seed(5)
        mha = FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                      attn_dropout_rate=0.0)
        x = paddle.to_tensor(np.random.RandomState(5)
                             .randn(1, 4, 16).astype(np.float32))
        other = paddle.to_tensor(np.zeros((1, 4, 16), np.float32))
        with pytest.raises(NotImplementedError):
            mha(x, key=other)
        with pytest.raises(NotImplementedError):
            mha(x, cache=object())
        assert tuple(mha(x).shape) == (1, 4, 16)
