"""Signature-keyed compiled-forward cache for no-grad eager dispatch
(ops/dispatch.py).

The reference amortizes per-op eager dispatch with codegen'd PHI kernels
(eager_gen.py + kernel_dispatch.h); we amortize the no-grad path with a
jit-compiled executable per (raw_fn identity, static kwargs, input avals
incl. weak_type), admitted under the shared seen-twice discipline and
LRU bounded. These tests pin the cache's semantics: keying, eviction,
per-call-closure randomness NEVER frozen, donation correctness for the
in-place family, graceful blocklisting of concrete-value traces, the
admission tracker's id-reuse purge, and a CPU mini op-bench keeping
cached-eager within a generous multiple of jitted latency.
"""
import gc
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.ops import dispatch
from paddle_tpu.ops import registry
from paddle_tpu.profiler import stats


@pytest.fixture(autouse=True)
def _fresh_cache():
    dispatch._FWD_CACHE.clear()
    dispatch._FWD_SEEN.clear()
    dispatch._FWD_BLOCK.clear()
    yield
    dispatch._FWD_CACHE.clear()
    dispatch._FWD_SEEN.clear()
    dispatch._FWD_BLOCK.clear()


def _counter(name):
    return stats.counter(name).value


class TestForwardCache:
    def test_admit_on_second_sighting_then_hit(self):
        x = paddle.to_tensor(np.linspace(-2, 2, 32).astype(np.float32))
        h0, m0 = _counter("fwd_cache.hit"), _counter("fwd_cache.miss")
        y0 = F.gelu(x)                       # sighting 1: plain path
        assert len(dispatch._FWD_CACHE) == 0
        y1 = F.gelu(x)                       # sighting 2: builds + runs
        assert len(dispatch._FWD_CACHE) == 1
        y2 = F.gelu(x)                       # hit: compiled executable
        assert _counter("fwd_cache.hit") == h0 + 1
        assert _counter("fwd_cache.miss") == m0 + 1
        np.testing.assert_allclose(y2.numpy(), y0.numpy(), rtol=1e-6)
        np.testing.assert_allclose(y1.numpy(), y0.numpy(), rtol=1e-6)

    def test_trace_time_histogram_observed(self):
        h = stats.histogram("compile.fwd_trace_us")
        before = h.count
        x = paddle.to_tensor(np.ones((8, 8), np.float32))
        F.gelu(x)
        F.gelu(x)  # admission traces+compiles here
        assert h.count == before + 1

    def test_key_discriminates_shape_dtype_weak_type(self):
        for shape in ((4,), (2, 3), (4,)):
            for _ in range(2):
                paddle.exp(paddle.to_tensor(np.ones(shape, np.float32)))
        for _ in range(2):
            paddle.exp(paddle.to_tensor(np.ones((4,), np.float64)))
        # weak_type discriminates: a python-scalar array is weakly typed
        for _ in range(2):
            paddle.exp(paddle.Tensor(jnp.asarray(1.0)))
        for _ in range(2):
            paddle.exp(paddle.Tensor(jnp.asarray(np.float32(1.0))))
        keys = list(dispatch._FWD_CACHE)
        # (4,) f32, (2,3) f32, (4,) f64, scalar weak, scalar strong
        assert len(keys) == 5

    def test_static_kwargs_in_key(self):
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(3, 5).astype(np.float32))
        for ax in (0, 1):
            for _ in range(3):
                s = F.softmax(x, axis=ax)
        assert len(dispatch._FWD_CACHE) == 2
        np.testing.assert_allclose(s.numpy().sum(axis=1), np.ones(3),
                                   rtol=1e-5)

    def test_unhashable_static_kwargs_fall_back(self):
        u0 = _counter("fwd_cache.uncacheable")

        def raw(a, factors=None):
            return a * factors[0]

        t = paddle.to_tensor(np.ones((4,), np.float32))
        for _ in range(3):
            out = dispatch.eager_apply("t_listkw", raw, [t],
                                       {"factors": [2.0]})
        assert len(dispatch._FWD_CACHE) == 0
        assert _counter("fwd_cache.uncacheable") >= u0 + 3
        np.testing.assert_allclose(out.numpy(), 2.0 * np.ones(4))

    def test_tensor_valued_static_kwarg_never_baked(self):
        # a Tensor hash()es by identity but must NOT be admitted: its
        # VALUE would be frozen into the compiled executable
        scale = paddle.to_tensor(np.float32(3.0))

        def raw(a, s=None):
            return a * s._data

        t = paddle.to_tensor(np.ones((4,), np.float32))
        for _ in range(3):
            dispatch.eager_apply("t_tensorkw", raw, [t], {"s": scale})
        assert len(dispatch._FWD_CACHE) == 0

    def test_lru_eviction_at_bound(self, monkeypatch):
        monkeypatch.setattr(dispatch, "_FWD_CACHE_MAX", 3)
        for n in (1, 2, 3, 4, 5):
            x = paddle.to_tensor(np.ones((n,), np.float32))
            paddle.exp(x)
            paddle.exp(x)  # admit entry for shape (n,)
        assert len(dispatch._FWD_CACHE) == 3
        shapes = [key[2][0][0] for key in dispatch._FWD_CACHE]
        assert shapes == [(3,), (4,), (5,)]  # oldest two evicted

    def test_lru_recency_on_hit(self, monkeypatch):
        monkeypatch.setattr(dispatch, "_FWD_CACHE_MAX", 2)
        a = paddle.to_tensor(np.ones((2,), np.float32))
        b = paddle.to_tensor(np.ones((3,), np.float32))
        c = paddle.to_tensor(np.ones((4,), np.float32))
        for t in (a, a, b, b):
            paddle.exp(t)
        paddle.exp(a)          # hit refreshes (2,)'s recency
        paddle.exp(c)
        paddle.exp(c)          # admitting (4,) evicts (3,), not (2,)
        shapes = [key[2][0][0] for key in dispatch._FWD_CACHE]
        assert (2,) in shapes and (3,) not in shapes

    def test_dropout_randomness_never_frozen(self):
        paddle.seed(0)
        x = paddle.to_tensor(np.ones((64,), np.float32))
        masks = set()
        for _ in range(6):
            y = F.dropout(x, p=0.5, training=True)
            masks.add(tuple((y.numpy() != 0).tolist()))
        # fresh mask (fresh closure) every call: caching must not bake it
        assert len(masks) >= 4
        assert len(dispatch._FWD_CACHE) == 0

    def test_gumbel_style_noise_not_frozen(self):
        paddle.seed(0)
        draws = set()
        for _ in range(6):
            t = paddle.rand([16])
            draws.add(round(float(t.numpy().sum()), 6))
        assert len(draws) >= 4

    def test_blocklisted_concrete_trace_falls_back(self):
        b0 = _counter("fwd_cache.blocklisted")
        k0 = _counter("fwd_cache.blocked")

        def raw(a):
            if float(jnp.sum(a)) > 0:  # concretizes under jit
                return a * 2.0
            return a

        t = paddle.to_tensor(np.ones((4,), np.float32))
        outs = [dispatch.eager_apply("t_concrete", raw, [t])
                for _ in range(4)]
        for out in outs:
            np.testing.assert_allclose(out.numpy(), 2.0 * np.ones(4))
        assert _counter("fwd_cache.blocklisted") == b0 + 1
        assert len(dispatch._FWD_BLOCK) == 1
        assert _counter("fwd_cache.blocked") >= k0 + 1
        assert len(dispatch._FWD_CACHE) == 0

    def test_disabled_by_flag(self):
        paddle.set_flags({"FLAGS_eager_fwd_cache": False})
        try:
            x = paddle.to_tensor(np.ones((4,), np.float32))
            for _ in range(4):
                y = paddle.exp(x)
            assert len(dispatch._FWD_CACHE) == 0
            np.testing.assert_allclose(y.numpy(), np.e * np.ones(4),
                                       rtol=1e-6)
        finally:
            paddle.set_flags({"FLAGS_eager_fwd_cache": True})

    def test_multi_output_op_cached(self):
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(8).astype(np.float32))
        for _ in range(3):
            vals, idx = paddle.topk(x, k=3)
        assert len(dispatch._FWD_CACHE) == 1
        np.testing.assert_allclose(
            np.sort(vals.numpy()), np.sort(np.sort(x.numpy())[-3:]))

    def test_grad_mode_untouched_by_fwd_cache(self):
        x = paddle.to_tensor(np.linspace(-1, 1, 8).astype(np.float32),
                             stop_gradient=False)
        for _ in range(3):
            y = paddle.tanh(x)
            y.sum().backward()
            g = x.grad.numpy()
            x.clear_grad()
        np.testing.assert_allclose(g, 1 - np.tanh(x.numpy()) ** 2,
                                   rtol=1e-5)
        assert len(dispatch._FWD_CACHE) == 0  # taped calls use vjp cache


class TestAdmissionTracker:
    def test_seen_twice_same_object(self):
        tr = dispatch._AdmissionTracker()
        f = lambda a: a  # noqa: E731
        assert tr.admit("k", f) is False
        assert tr.admit("k", f) is True
        assert tr.admit("k", f) is True

    def test_fresh_closure_never_admitted(self):
        tr = dispatch._AdmissionTracker()
        for _ in range(8):
            assert tr.admit("k", (lambda a: a)) is False

    def test_id_reuse_purged_on_death(self):
        # the latent bug: entries keyed by a dead referent must not let a
        # recycled id inherit the sighting — the weakref callback purges
        tr = dispatch._AdmissionTracker()

        def make():
            return lambda a: a + 1

        f = make()
        assert tr.admit(("k", id(f)), f) is False
        assert len(tr) == 1
        del f
        gc.collect()
        assert len(tr) == 0  # purged by the weakref callback
        g = make()
        assert tr.admit(("k", id(g)), g) is False  # no stale inheritance

    def test_bound_evicts_dead_then_oldest(self):
        tr = dispatch._AdmissionTracker(max_entries=4)
        keep = [lambda a, _i=i: a for i in range(6)]
        for i, f in enumerate(keep):
            tr.admit(i, f)
        assert len(tr) <= 4

    def test_vjp_seen_shares_fixed_tracker(self):
        assert isinstance(dispatch._VJP_SEEN, dispatch._AdmissionTracker)
        assert isinstance(dispatch._FWD_SEEN, dispatch._AdmissionTracker)


class TestDonation:
    def test_inplace_relu_matches_functional(self):
        x_np = np.linspace(-2, 2, 64).astype(np.float32)
        ref = F.relu(paddle.to_tensor(x_np)).numpy()
        for _ in range(4):  # warm the donated-signature entry
            x = paddle.to_tensor(x_np)
            out = F.relu_(x)
            assert out is x
            np.testing.assert_array_equal(x.numpy(), ref)

    def test_aliased_buffer_never_donated(self):
        x_np = np.linspace(-2, 2, 64).astype(np.float32)
        for _ in range(4):
            x = paddle.to_tensor(x_np)
            alias = x.detach()          # shares the jax buffer
            F.relu_(x)
            # the alias must still be readable: donation was skipped
            np.testing.assert_array_equal(alias.numpy(), x_np)

    def test_donated_and_undonated_bit_identical(self):
        x_np = np.random.RandomState(3).randn(128).astype(np.float32)
        outs = []
        for keep_alias in (False, True):
            dispatch._FWD_CACHE.clear()
            dispatch._FWD_SEEN.clear()
            for _ in range(4):
                x = paddle.to_tensor(x_np)
                alias = x.detach() if keep_alias else None
                F.tanh_(x)
                outs.append(x.numpy())
            del alias
        first = outs[0]
        for o in outs[1:]:
            np.testing.assert_array_equal(o, first)

    def test_inplace_family_registered_with_donation(self):
        fam = registry.inplace_ops()
        for name in ("relu_", "tanh_", "elu_", "softmax_"):
            assert name in fam, name
            assert fam[name].donates == (0,)
            assert fam[name].inplace_of == name.rstrip("_")

    def test_optimizer_donate_grads_flag(self):
        paddle.seed(0)
        import paddle_tpu.nn as nn

        def train(donate):
            paddle.set_flags({"FLAGS_optimizer_donate_grads": donate})
            try:
                paddle.seed(7)
                net = nn.Linear(4, 4)
                opt = paddle.optimizer.SGD(0.1,
                                           parameters=net.parameters())
                xs = paddle.to_tensor(
                    np.random.RandomState(0).randn(8, 4).astype(np.float32))
                for _ in range(3):
                    loss = (net(xs) ** 2).mean()
                    loss.backward()
                    opt.step()
                    if donate:
                        assert all(p.grad is None
                                   for p in net.parameters())
                    opt.clear_grad()
                return [p.numpy().copy() for p in net.parameters()]
            finally:
                paddle.set_flags({"FLAGS_optimizer_donate_grads": False})

        ref = train(False)
        don = train(True)
        for a, b in zip(ref, don):
            np.testing.assert_array_equal(a, b)


class TestMiniOpBench:
    """CPU stand-in for the on-TPU OPBENCH acceptance: cached-eager
    composite ops must stay within a generous multiple of their jitted
    latency (catches fast-path regressions without a TPU)."""

    @staticmethod
    def _median_us(fn, reps=15):
        out = fn()
        jax.block_until_ready(getattr(out, "_data", out))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            jax.block_until_ready(getattr(out, "_data", out))
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts) * 1e6)

    def test_cached_eager_within_bound_of_jit(self):
        rng = np.random.RandomState(0)
        big = paddle.to_tensor(rng.randn(512, 1024).astype(np.float32))
        logits = paddle.to_tensor(rng.randn(256, 1000).astype(np.float32))
        cases = [
            ("gelu", lambda: F.gelu(big), big),
            ("softmax", lambda: F.softmax(logits, axis=-1), logits),
        ]
        h0 = _counter("fwd_cache.hit")
        for name, fn, src in cases:
            for _ in range(3):  # sight + admit + first hit
                fn()
            eager_us = self._median_us(fn)
            jit_fn = jax.jit(
                {"gelu": lambda a: jax.nn.gelu(a, approximate=False),
                 "softmax": lambda a: jax.nn.softmax(a, axis=-1)}[name])
            arr = src._data
            jit_fn(arr)
            jit_us = self._median_us(lambda: jit_fn(arr))
            # generous: CI boxes are noisy — the uncached composite path
            # is O(5-50x), so 4x + 1ms slack still catches a fall-off
            assert eager_us <= 4.0 * jit_us + 1000.0, \
                (name, eager_us, jit_us)
        assert _counter("fwd_cache.hit") > h0

    def test_telemetry_block_carries_fwd_cache(self):
        x = paddle.to_tensor(np.ones((16, 16), np.float32))
        for _ in range(3):
            F.gelu(x)
        snap = stats.snapshot()
        assert any(k.startswith("fwd_cache.") for k in snap["counters"])
        assert stats.fwd_cache_hit_rate() is not None


class TestBenchGateNewFields:
    """bench_gate must cover the new OPBENCH telemetry fields."""

    @staticmethod
    def _doc(miss, hit_rate, trace_avg):
        return {"telemetry": {
            "counters": {"fwd_cache.miss": miss, "fwd_cache.hit": 50},
            "fwd_cache_hit_rate": hit_rate,
            "histograms": {"compile.fwd_trace_us": {
                "count": 10, "avg": trace_avg}},
        }}

    def _gate(self, prev, cur):
        import importlib
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        bench_gate = importlib.import_module("bench_gate")
        return bench_gate.gate(prev, cur)

    def test_miss_regresses_up(self):
        bad, compared = self._gate(self._doc(10, 0.9, 100.0),
                                   self._doc(40, 0.9, 100.0))
        assert compared >= 3
        assert any("fwd_cache.miss" in line for line in bad)

    def test_hit_rate_regresses_down(self):
        bad, _ = self._gate(self._doc(10, 0.9, 100.0),
                            self._doc(10, 0.4, 100.0))
        assert any("fwd_cache_hit_rate" in line for line in bad)

    def test_trace_time_regresses_up(self):
        bad, _ = self._gate(self._doc(10, 0.9, 100.0),
                            self._doc(10, 0.9, 500.0))
        assert any("compile.fwd_trace_us" in line for line in bad)

    def test_clean_round_passes(self):
        bad, compared = self._gate(self._doc(10, 0.9, 100.0),
                                   self._doc(10, 0.92, 99.0))
        assert bad == [] and compared >= 3

    def test_op_bench_taped_backward_column(self):
        import importlib
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        op_bench = importlib.import_module("op_bench")
        t = paddle.to_tensor(np.ones((8,), np.float32))
        us = op_bench._taped_backward_us(lambda a: a.exp(), (t,),
                                         reps=3, warmup=1)
        assert us is not None and us > 0
        # int-only inputs have no taped path
        ti = paddle.to_tensor(np.ones((8,), np.int32))
        assert op_bench._taped_backward_us(lambda a: a + a, (ti,),
                                           reps=2, warmup=1) is None
