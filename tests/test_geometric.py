"""paddle.geometric: segment reductions + message passing.

Reference parity targets: python/paddle/geometric/math.py,
message_passing/send_recv.py:36.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.geometric as G


def _t(x):
    return paddle.to_tensor(np.asarray(x))


class TestSegment:
    def test_segment_reductions(self):
        data = np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]],
                        np.float32)
        ids = np.array([0, 0, 1, 1])
        np.testing.assert_allclose(
            G.segment_sum(_t(data), _t(ids)).numpy(),
            [[4, 6], [12, 14]])
        np.testing.assert_allclose(
            G.segment_mean(_t(data), _t(ids)).numpy(),
            [[2, 3], [6, 7]])
        np.testing.assert_allclose(
            G.segment_max(_t(data), _t(ids)).numpy(),
            [[3, 4], [7, 8]])
        np.testing.assert_allclose(
            G.segment_min(_t(data), _t(ids)).numpy(),
            [[1, 2], [5, 6]])

    def test_empty_segment_is_zero(self):
        data = np.array([[1.0]], np.float32)
        ids = np.array([2])
        out = G.segment_max(_t(data), _t(ids)).numpy()
        np.testing.assert_allclose(out, [[0.0], [0.0], [1.0]])


class TestMessagePassing:
    def test_send_u_recv_sum_mean(self):
        x = np.array([[0., 2., 3.], [1., 4., 5.], [2., 6., 7.]],
                     np.float32)
        src = np.array([0, 1, 2, 0])
        dst = np.array([1, 2, 1, 0])
        out = G.send_u_recv(_t(x), _t(src), _t(dst),
                            reduce_op="sum").numpy()
        want = np.zeros_like(x)
        for s, d in zip(src, dst):
            want[d] += x[s]
        np.testing.assert_allclose(out, want)
        outm = G.send_u_recv(_t(x), _t(src), _t(dst),
                             reduce_op="mean").numpy()
        np.testing.assert_allclose(outm[1], (x[0] + x[2]) / 2)

    def test_send_u_recv_out_size(self):
        x = np.array([[1.0], [2.0]], np.float32)
        out = G.send_u_recv(_t(x), _t([0, 1]), _t([0, 0]),
                            reduce_op="max", out_size=4).numpy()
        assert out.shape == (4, 1)
        np.testing.assert_allclose(out[:, 0], [2, 0, 0, 0])

    def test_send_ue_recv(self):
        x = np.array([[1.0], [2.0]], np.float32)
        e = np.array([[10.0], [20.0], [30.0]], np.float32)
        src = np.array([0, 1, 1])
        dst = np.array([1, 0, 1])
        out = G.send_ue_recv(_t(x), _t(e), _t(src), _t(dst),
                             message_op="add", reduce_op="sum").numpy()
        np.testing.assert_allclose(out, [[22.0], [11.0 + 32.0]])

    def test_send_uv(self):
        x = np.array([[1.0], [2.0], [3.0]], np.float32)
        y = np.array([[10.0], [20.0], [30.0]], np.float32)
        out = G.send_uv(_t(x), _t(y), _t([0, 2]), _t([1, 0]),
                        message_op="mul").numpy()
        np.testing.assert_allclose(out, [[20.0], [30.0]])


class TestDtypes:
    def test_int_segment_max_keeps_dtype(self):
        data = np.array([[3], [1]], np.int32)
        out = G.segment_max(_t(data), _t([1, 1]))
        assert out.numpy().dtype == np.int32
        np.testing.assert_array_equal(out.numpy(), [[0], [3]])
