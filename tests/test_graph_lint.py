"""graph_lint: whole-program jaxpr/HLO analyzer (ISSUE 7 tentpole).

Tier-1 coverage of the four program-level passes:

- the repo's program inventory is CLEAN (dtype/sync/memory/spmd, zero
  unwaivered findings) within the 60s CI budget;
- every rule fires on a synthetic bad program AND an inline waiver
  silences it (X-PROMOTE, X-F64, X-SYNC, X-CHURN, M-HBM, S-GATHER,
  S-MATCH, S-UNSPEC);
- the MEMORY pass's donation-aware liveness model is pinned exactly on
  a known-peak chain, and the decode program's estimate lands within
  20% of ``compiled.memory_analysis()`` (acceptance criterion);
- the SPMD pass flags an injected missing-sharding-constraint
  all-gather on the virtual 8-device mesh (acceptance criterion);
- the preflight gate refuses on findings and honors --no-lint;
- the ratchet (per-rule counts) only tightens;
- bench_gate gates the new lint metrics.
"""
import importlib.util
import os
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu import analysis
from paddle_tpu.analysis import site_for_fn, trace_program
from paddle_tpu.analysis.dtype_flow import check_dtype_flow
from paddle_tpu.analysis.hbm import peak_live_bytes
from paddle_tpu.analysis.host_sync import check_churn, check_host_sync
from paddle_tpu.analysis.spmd import SpmdSite, check_spmd_site
from paddle_tpu.device import vmem as dvmem

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _mod_from(tmp_path, name, source):
    """Import ``source`` as a module from a tmp file — synthetic bad
    programs live in real files so eqn anchoring + inline waivers work
    exactly as they do for repo code."""
    p = tmp_path / f"{name}.py"
    p.write_text(source)
    spec = importlib.util.spec_from_file_location(name, str(p))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------
# the repo is clean (the acceptance gate)
# ---------------------------------------------------------------------

class TestRepoProgramsClean:
    def test_program_passes_clean_under_60s(self):
        t0 = time.time()
        results = analysis.run_program_passes()
        elapsed = time.time() - t0
        assert set(results) == {"dtype", "sync", "memory", "spmd",
                                "overlap"}
        for name, findings in results.items():
            live = analysis.unwaivered(findings)
            assert not live, (
                f"pass {name!r} has unwaivered findings:\n  "
                + "\n  ".join(f.render() for f in live))
        assert elapsed < 60, f"program passes took {elapsed:.1f}s (>60s)"

    def test_program_inventory_traces(self):
        traced = analysis.trace_all_programs()
        assert {"dispatch.gelu", "jit.train_step", "inference.prefill",
                "inference.decode"} <= set(traced)
        for name, tp in traced.items():
            assert tp.closed.jaxpr.eqns, f"{name}: empty jaxpr"
        # donation declared for the serving programs (cache operands)
        assert traced["inference.decode"].donated_invars
        assert traced["jit.train_step"].donated_invars

    def test_lint_prefix_registered(self):
        from paddle_tpu.profiler import stats

        assert "lint." in stats.CONVENTION_PREFIXES


# ---------------------------------------------------------------------
# DTYPE: X-PROMOTE / X-F64
# ---------------------------------------------------------------------

class TestDtypePass:
    def test_injected_f32_upcast_flagged(self):
        def f(x, w):
            return x.astype(jnp.float32) @ w

        tp = trace_program(site_for_fn(
            "t.bad_promote", f,
            (_sds((8, 16), jnp.bfloat16), _sds((16, 4), jnp.float32)),
            compute_dtype="bfloat16"))
        assert any(fd.rule == "X-PROMOTE" for fd in check_dtype_flow(tp))

    def test_bf16_operands_with_f32_accumulation_pass(self):
        def f(x, w):
            return jax.lax.dot_general(
                x, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        tp = trace_program(site_for_fn(
            "t.accum_ok", f,
            (_sds((8, 16), jnp.bfloat16), _sds((16, 4), jnp.bfloat16)),
            compute_dtype="bfloat16"))
        assert check_dtype_flow(tp) == []

    def test_undeclared_site_not_promotion_checked(self):
        def f(x, w):
            return x.astype(jnp.float32) @ w

        tp = trace_program(site_for_fn(
            "t.f32_site", f,
            (_sds((8, 16), jnp.bfloat16), _sds((16, 4), jnp.float32))))
        assert check_dtype_flow(tp) == []

    def test_f64_leak_flagged(self):
        x64 = bool(jax.config.jax_enable_x64)
        try:
            jax.config.update("jax_enable_x64", True)
            closed = jax.make_jaxpr(lambda x: x * 2.0)(
                _sds((4,), jnp.float64))
        finally:
            jax.config.update("jax_enable_x64", x64)
        tp = analysis.TracedProgram(
            site=site_for_fn("t.f64", lambda: None, ()),
            closed=closed, donated_invars=frozenset())
        assert any(fd.rule == "X-F64" for fd in check_dtype_flow(tp))

    def test_waiver_silences_promote(self, tmp_path):
        mod = _mod_from(tmp_path, "bad_promote_waived", (
            "import jax.numpy as jnp\n"
            "def f(x, w):\n"
            "    xf = x.astype(jnp.float32)\n"
            "    return xf @ w"
            "  # tpu-lint: ok(X-PROMOTE) -- test fixture\n"))
        tp = trace_program(site_for_fn(
            "t.waived_promote", mod.f,
            (_sds((8, 16), jnp.bfloat16), _sds((16, 4), jnp.float32)),
            compute_dtype="bfloat16"))
        findings = analysis.run_dtype_pass(traced={"t": tp})
        assert findings and all(fd.waived for fd in findings)


# ---------------------------------------------------------------------
# SYNC: X-SYNC / X-CHURN
# ---------------------------------------------------------------------

_CALLBACK_IN_SCAN = (
    "import jax\n"
    "def f(x):\n"
    "    def body(c, _):\n"
    "        jax.debug.print('c={c}', c=c)WAIVER\n"
    "        return c + 1.0, c\n"
    "    return jax.lax.scan(body, x, None, length=4)\n")


class TestSyncPass:
    def test_callback_in_scan_flagged(self, tmp_path):
        mod = _mod_from(tmp_path, "cb_scan",
                        _CALLBACK_IN_SCAN.replace("WAIVER", ""))
        tp = trace_program(site_for_fn("t.cb", mod.f,
                                       (_sds((), jnp.float32),)))
        assert any(fd.rule == "X-SYNC" for fd in check_host_sync(tp))

    def test_hot_loop_flags_top_level_callback(self):
        def f(x):
            jax.debug.print("x={x}", x=x)
            return x + 1.0

        tp = trace_program(site_for_fn(
            "t.hot", f, (_sds((), jnp.float32),), hot_loop=True))
        assert any(fd.rule == "X-SYNC" for fd in check_host_sync(tp))
        # the same program outside a hot loop is fine (one-shot sync)
        tp2 = trace_program(site_for_fn(
            "t.cold", f, (_sds((), jnp.float32),)))
        assert check_host_sync(tp2) == []

    def test_clean_loop_not_flagged(self):
        def f(x):
            return jax.lax.fori_loop(0, 4, lambda i, c: c + i, x)

        tp = trace_program(site_for_fn(
            "t.clean", f, (_sds((), jnp.int32),), hot_loop=True))
        assert check_host_sync(tp) == []

    def test_unhashable_static_kwargs_flag_churn(self):
        site = site_for_fn("t.churn", lambda x: x, (),
                           static_kwargs={"axes": [1, 2]})
        assert [fd.rule for fd in check_churn(site)] == ["X-CHURN"]
        ok = site_for_fn("t.ok", lambda x: x, (),
                         static_kwargs={"axis": -1, "mode": "full"})
        assert check_churn(ok) == []

    def test_waiver_silences_sync(self, tmp_path):
        mod = _mod_from(tmp_path, "cb_scan_waived",
                        _CALLBACK_IN_SCAN.replace(
                            "WAIVER", "  # tpu-lint: ok(X-SYNC) -- "
                                      "debug fixture"))
        tp = trace_program(site_for_fn("t.cbw", mod.f,
                                       (_sds((), jnp.float32),)))
        findings = analysis.run_sync_pass(traced={"t": tp})
        assert findings and all(fd.waived for fd in findings)


# ---------------------------------------------------------------------
# MEMORY: liveness model + M-HBM + XLA cross-check
# ---------------------------------------------------------------------

class TestMemoryPass:
    def test_known_peak_chain_exact(self):
        """y = x+1; z = y+1 — peak is exactly 3 buffers undonated
        (caller holds x across the whole program), 2 donated."""
        n = 256 * 256 * 4

        def f(x):
            y = x + 1.0
            return y + 1.0

        closed = jax.make_jaxpr(f)(_sds((256, 256), jnp.float32))
        est = peak_live_bytes(closed)
        assert est.peak_bytes == 3 * n
        est_don = peak_live_bytes(closed, donated_invars=frozenset({0}))
        assert est_don.peak_bytes == 2 * n
        assert est.arg_bytes == n and est.out_bytes == n

    def test_loop_body_temp_counted(self):
        """A scan body materializing a [512, 512] outer product must
        surface in the outer peak (inner peak net of boundary)."""
        def f(c):
            def body(c, _):
                t = jnp.outer(c, c)          # 1 MiB f32 temp
                return t.sum(axis=1) * 1e-3, ()
            out, _ = jax.lax.scan(body, c, None, length=3)
            return out

        closed = jax.make_jaxpr(f)(_sds((512,), jnp.float32))
        est = peak_live_bytes(closed)
        assert est.peak_bytes >= 512 * 512 * 4

    def test_m_hbm_fires_on_v5e_fits_on_v5p(self):
        def f(w):
            return (w * 2.0).sum()

        tp = trace_program(site_for_fn(
            "t.oversize", f, (_sds((1 << 33,), jnp.float32),)))
        bad = analysis.run_memory_pass(generation="v5e",
                                       traced={"t": tp})
        assert [fd.rule for fd in bad] == ["M-HBM"]
        assert "v5e" in bad[0].message
        assert analysis.run_memory_pass(generation="v5p",
                                        traced={"t": tp}) == []

    def test_waiver_silences_m_hbm(self, tmp_path):
        mod = _mod_from(tmp_path, "oversize_waived", (
            "def build():"
            "  # tpu-lint: ok(M-HBM) -- known-oversize fixture\n"
            "    import jax, jax.numpy as jnp\n"
            "    fn = lambda w: (w * 2.0).sum()\n"
            "    return fn, (jax.ShapeDtypeStruct((1 << 33,),"
            " jnp.float32),)\n"))
        site = analysis.ProgramSite("t.waived_big", mod.build)
        tp = trace_program(site)
        findings = analysis.run_memory_pass(generation="v5e",
                                            traced={"t": tp})
        assert findings and all(fd.waived for fd in findings)

    def test_decode_estimate_within_20pct_of_xla(self):
        """Acceptance criterion: the static peak-live bound for the
        decode program lands within 20% of the compiled program's own
        memory accounting (CPU backend; both sides undonated so args
        are counted once on each). The f32 program variant is the
        apples-to-apples one here — XLA:CPU emulates bf16 through f32
        temp copies of every weight, which no real TPU run pays."""
        from paddle_tpu.analysis import program_sites as ps

        fn, args = ps.build_decode_program(cast_bf16=False)
        est = peak_live_bytes(jax.make_jaxpr(fn)(*args))
        ma = jax.jit(fn).lower(*args).compile().memory_analysis()
        xla = (ma.argument_size_in_bytes + ma.output_size_in_bytes
               + ma.temp_size_in_bytes)
        assert xla > 0
        ratio = est.peak_bytes / xla
        assert 0.8 <= ratio <= 1.2, (est.peak_bytes, xla, ratio)

    def test_hbm_table_shape(self):
        # the issue-pinned capacities: v4 32G, v5e 16G
        assert dvmem.HBM_BUDGET_BYTES["v4"] == 32 * dvmem.GiB
        assert dvmem.HBM_BUDGET_BYTES["v5e"] == 16 * dvmem.GiB
        assert set(dvmem.HBM_BUDGET_BYTES) == set(dvmem.VMEM_BUDGET_BYTES)
        assert dvmem.hbm_budget_bytes("v5e") == \
            16 * dvmem.GiB - dvmem.HBM_RESERVE_BYTES


# ---------------------------------------------------------------------
# SPMD: S-GATHER / S-MATCH / S-UNSPEC on the virtual mesh
# ---------------------------------------------------------------------

def _gather_build():
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = analysis.virtual_mesh()
    repl = NamedSharding(mesh, P())

    def fn(x):
        return jax.lax.with_sharding_constraint(x * 2.0, repl)

    x = jax.device_put(jnp.ones((8, 8)),
                       NamedSharding(mesh, P("x", None)))
    return fn, (x,)


class TestSpmdPass:
    def test_virtual_mesh_available(self, virtual_devices):
        assert analysis.mesh_available()
        assert analysis.virtual_mesh() is not None

    def test_injected_missing_constraint_all_gather(self):
        """Acceptance criterion: a sharded input forced replicated
        (the dropped-sharding-constraint shape) must flag the GSPMD
        all-gather on the virtual 8-device mesh."""
        site = SpmdSite("t.gather", _gather_build, allowed=frozenset())
        findings = check_spmd_site(site)
        assert [fd.rule for fd in findings] == ["S-GATHER"]
        assert "all-gather" in findings[0].message

    def test_declared_collective_passes(self):
        site = SpmdSite("t.gather_ok", _gather_build,
                        allowed=frozenset({"all-gather"}))
        assert check_spmd_site(site) == []

    def test_asymmetric_branch_collectives_flag_s_match(self):
        try:
            from jax.experimental.shard_map import shard_map
        except ImportError:
            from jax import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = analysis.virtual_mesh()

        def build(asym):
            def body(x):
                def hot(v):
                    return jax.lax.psum(v, "x")

                def cold(v):
                    return v if asym else jax.lax.psum(v, "x") * 0.5
                return jax.lax.cond(x.sum() > 0, hot, cold, x)

            kwargs = {}
            if getattr(jax.lax, "pcast", None) is None:
                kwargs["check_rep"] = False
            fn = shard_map(body, mesh=mesh, in_specs=(P("x"),),
                           out_specs=P("x"), **kwargs)
            x = jax.device_put(jnp.ones((8, 4)),
                               NamedSharding(mesh, P("x", None)))
            return fn, (x,)

        bad = SpmdSite("t.asym", lambda: build(True),
                       allowed=frozenset({"all-reduce"}))
        assert any(fd.rule == "S-MATCH" for fd in check_spmd_site(bad))
        good = SpmdSite("t.sym", lambda: build(False),
                        allowed=frozenset({"all-reduce"}))
        assert check_spmd_site(good) == []

    def test_missing_output_constraint_flags_s_unspec(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = analysis.virtual_mesh()

        def build():
            def fn(x, w):
                return x @ w

            x = jax.device_put(jnp.ones((8, 16)),
                               NamedSharding(mesh, P("x", None)))
            w = jax.device_put(jnp.ones((16, 4)),
                               NamedSharding(mesh, P()))
            return fn, (x, w)

        site = SpmdSite("t.unspec", build,
                        allowed=frozenset({"all-gather", "all-reduce"}),
                        expects_constraint=True)
        assert any(fd.rule == "S-UNSPEC"
                   for fd in check_spmd_site(site))
        # the same program WITH the constraint is clean
        ok = SpmdSite("t.spec", _gather_build,
                      allowed=frozenset({"all-gather"}),
                      expects_constraint=True)
        assert check_spmd_site(ok) == []

    def test_waiver_silences_s_gather(self, tmp_path):
        mod = _mod_from(tmp_path, "gather_waived", (
            "def build():"
            "  # tpu-lint: ok(S-GATHER) -- replication intended\n"
            "    import jax, jax.numpy as jnp\n"
            "    from jax.sharding import NamedSharding,"
            " PartitionSpec as P\n"
            "    from paddle_tpu import analysis\n"
            "    mesh = analysis.virtual_mesh()\n"
            "    repl = NamedSharding(mesh, P())\n"
            "    fn = lambda x: jax.lax.with_sharding_constraint("
            "x * 2.0, repl)\n"
            "    x = jax.device_put(jnp.ones((8, 8)),"
            " NamedSharding(mesh, P('x', None)))\n"
            "    return fn, (x,)\n"))
        site = SpmdSite("t.waived_gather", mod.build,
                        allowed=frozenset())
        findings = analysis.run_spmd_pass(sites=[site])
        assert findings and all(fd.waived for fd in findings)


# ---------------------------------------------------------------------
# preflight gate + ratchet + bench_gate wiring
# ---------------------------------------------------------------------

class TestPreflightGate:
    def test_refuses_on_unwaivered_findings(self, monkeypatch, capsys):
        from paddle_tpu.analysis import preflight as pf

        monkeypatch.setattr(
            analysis, "run_all_passes",
            lambda generation=None: {"t": [analysis.Finding(
                rule="T-BAD", message="injected")]})
        with pytest.raises(SystemExit) as ei:
            pf.preflight("t_tool")
        assert ei.value.code == 2
        assert "REFUSING" in capsys.readouterr().err

    def test_no_lint_and_env_escape_hatches(self, monkeypatch):
        from paddle_tpu.analysis import preflight as pf

        boom = lambda generation=None: (_ for _ in ()).throw(
            AssertionError("lint ran"))
        monkeypatch.setattr(analysis, "run_all_passes", boom)
        pf.preflight("t_tool", no_lint=True)     # flag skips
        monkeypatch.setenv("PADDLE_TPU_NO_LINT", "1")
        pf.preflight("t_tool")                   # env skips

    def test_publish_lint_stats_counters(self):
        from paddle_tpu.analysis.preflight import publish_lint_stats
        from paddle_tpu.profiler import stats

        before_f = stats.counter("lint.findings").value
        before_w = stats.counter("lint.waived").value
        publish_lint_stats({"t": [
            analysis.Finding(rule="A", message="m"),
            analysis.Finding(rule="B", message="m", waived=True,
                             waive_reason="r")]})
        assert stats.counter("lint.findings").value == before_f + 1
        assert stats.counter("lint.waived").value == before_w + 1
        # gauges mirror the per-run state so a CLEAN run (counter value
        # 0, filtered from snapshots) still materializes in telemetry
        assert stats.gauge("lint.findings").value == 1
        assert stats.gauge("lint.waived").value == 1
        publish_lint_stats({"t": []})
        assert stats.gauge("lint.findings").value == 0
        assert "lint.findings" in stats.snapshot()["gauges"]

    def test_bench_and_profile_tools_wired(self):
        """The chip-time entry points all run the preflight gate and
        expose the --no-lint escape hatch."""
        for rel in ("bench.py", "tools/decode_profile.py",
                    "tools/bert_profile.py", "tools/train_profile.py"):
            src = open(os.path.join(REPO, rel), encoding="utf-8").read()
            assert "preflight(" in src, rel
            assert "--no-lint" in src or "no_lint" in src, rel


class TestRatchet:
    def test_rule_counts_exclude_waived(self):
        results = {"p": [
            analysis.Finding(rule="X-SYNC", message="m"),
            analysis.Finding(rule="X-SYNC", message="m"),
            analysis.Finding(rule="M-HBM", message="m", waived=True,
                             waive_reason="legacy")]}
        assert analysis.rule_counts(results) == {"X-SYNC": 2}

    def test_ratchet_only_tightens(self):
        base = {"X-SYNC": 2, "M-HBM": 1}
        # equal or fewer: clean, even though findings exist (legacy)
        assert analysis.ratchet({"X-SYNC": 2}, base) == []
        assert analysis.ratchet({"X-SYNC": 1, "M-HBM": 1}, base) == []
        # any growth (or a new rule) fails
        assert analysis.ratchet({"X-SYNC": 3}, base)
        assert analysis.ratchet({"S-GATHER": 1}, base)

    def test_cli_baseline_parser_accepts_both_formats(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import tpu_lint
        finally:
            sys.path.pop(0)
        assert tpu_lint._baseline_counts(
            {"rule_counts": {"X-SYNC": 2}}) == {"X-SYNC": 2}
        report = {"passes": {"sync": [
            {"rule": "X-SYNC", "waived": False},
            {"rule": "X-SYNC", "waived": True}]}}
        assert tpu_lint._baseline_counts(report) == {"X-SYNC": 1}
        assert tpu_lint.SCHEMA_VERSION == 2


class TestBenchGateLintMetric:
    def test_lint_findings_gate_direction_up(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import bench_gate
        finally:
            sys.path.pop(0)
        assert bench_gate.DEFAULT_METRICS["lint_findings"] == "up"
        assert bench_gate.DEFAULT_METRICS["lint.findings"] == "up"
        prev = {"lint_findings": 0,
                "telemetry": {"counters": {"lint.findings": 0}}}
        worse = {"lint_findings": 5,
                 "telemetry": {"counters": {"lint.findings": 5}}}
        bad, n = bench_gate.gate(prev, worse)
        assert n >= 2 and bad
        assert any("lint" in b for b in bad)
        # improvement (fewer findings) must NOT trip the gate
        bad2, _ = bench_gate.gate(worse, prev)
        assert not bad2

    def test_single_new_finding_trips_no_floor(self):
        """ANY lint growth regresses — the count noise floor (3) that
        protects cache counters must not swallow 0 -> 1 findings, and a
        clean run records lint state as a GAUGE (zero counters are
        snapshot-filtered) so the comparison actually happens."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import bench_gate
        finally:
            sys.path.pop(0)
        clean = {"telemetry": {"gauges": {"lint.findings": 0}}}
        one = {"telemetry": {"counters": {"lint.findings": 1},
                             "gauges": {"lint.findings": 1}}}
        bad, n = bench_gate.gate(clean, one)
        assert n and bad, (bad, n)
