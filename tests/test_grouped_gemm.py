"""Ragged grouped-GEMM kernel (ISSUE 15 tentpole,
nn/functional/grouped_gemm.py).

Pinned here: the work-unit schedule's invariants, forward parity
against a dense per-row reference, BITWISE equality between the
interpreter-run Pallas kernel and the tiled XLA fallback (fwd and
grads — the off-TPU path must be the exact serving numerics), gradient
parity against jax autodiff of the dense reference, and the ragged
edge cases (empty experts, total skew, pad rows past offsets[E]).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.nn.functional.grouped_gemm import (
    DEFAULT_BLOCK_ROWS, grouped_gemm, grouped_work_map, moe_route)


def _mk(T=200, K=256, N=384, E=4, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(T, K).astype(dtype))
    w = jnp.asarray((rng.randn(E, K, N) * 0.05).astype(dtype))
    b = jnp.asarray((rng.randn(E, N) * 0.1).astype(np.float32))
    eids = np.sort(rng.randint(0, E, T))
    offsets = jnp.asarray(
        np.concatenate([[0], np.cumsum(np.bincount(eids, minlength=E))])
        .astype(np.int32))
    return x, w, b, eids, offsets


def _dense_ref(x, w, b, eids, activation=None):
    rows = jnp.take(w, jnp.asarray(eids), axis=0)
    bb = jnp.take(b, jnp.asarray(eids), axis=0)
    y = jnp.einsum("tk,tkn->tn", x, rows) + bb
    if activation == "gelu":
        y = jax.nn.gelu(y)
    return y


class TestWorkMap:
    def test_invariants(self):
        """tids non-decreasing, units expert-sorted, every tile and
        every expert covered — the accumulation-correctness contract
        the kernel's zero-init logic rests on."""
        bm = 8
        offsets = jnp.asarray([0, 3, 3, 17, 20], jnp.int32)  # E=4, T=20
        t_pad = 24
        gids, tids, lo, hi = (np.asarray(a) for a in grouped_work_map(
            offsets, t_pad, bm))
        assert (np.diff(tids) >= 0).all()
        assert (np.diff(gids) >= 0).all()
        assert set(range(t_pad // bm)) <= set(tids.tolist())
        assert set(range(4)) <= set(gids.tolist())
        # masks partition [0, 20): each real row in exactly one unit
        covered = np.zeros(24, np.int32)
        for u in range(len(gids)):
            covered[lo[u]:hi[u]] += 1
        # a row straddling a tile boundary appears in the mask of each
        # of its units, but is in-range of exactly ONE tile per unit —
        # count (row in [lo,hi)) AND (row in unit's tile)
        covered[:] = 0
        for u in range(len(gids)):
            t0, t1 = tids[u] * bm, (tids[u] + 1) * bm
            a, z = max(int(lo[u]), t0), min(int(hi[u]), t1)
            if z > a:
                covered[a:z] += 1
        assert (covered[:20] == 1).all()
        assert (covered[20:] == 0).all()

    def test_static_shape(self):
        offsets = jnp.asarray([0, 5, 9], jnp.int32)
        gids, tids, lo, hi = grouped_work_map(offsets, 16, 8)
        nwu = 16 // 8 + 2 * 2 + 1
        assert gids.shape == tids.shape == lo.shape == hi.shape == (nwu,)


class TestGroupedGemm:
    def test_fwd_matches_dense_reference(self):
        x, w, b, eids, offsets = _mk()
        y = grouped_gemm(x, w, offsets, bias=b, activation="gelu",
                         backend="xla")
        ref = _dense_ref(x, w, b, eids, "gelu")
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   atol=2e-6)

    def test_interpret_bitwise_equals_xla(self):
        """The off-TPU contract: the interpreter-run Pallas kernel and
        the tiled XLA walk produce IDENTICAL bits (same unit order,
        same fp32 accumulation from zero)."""
        x, w, b, eids, offsets = _mk()
        yx = grouped_gemm(x, w, offsets, bias=b, activation="gelu",
                          backend="xla")
        yi = grouped_gemm(x, w, offsets, bias=b, activation="gelu",
                          backend="interpret")
        assert np.array_equal(np.asarray(yx), np.asarray(yi))

    def test_grads_match_dense_autodiff(self):
        x, w, b, eids, offsets = _mk()

        def loss(x, w, b):
            y = grouped_gemm(x, w, offsets, bias=b, activation="gelu",
                             backend="xla")
            return jnp.sum(y ** 2)

        gx, gw, gb = jax.grad(loss, argnums=(0, 1, 2))(x, w, b)

        def loss_ref(x, w, b):
            return jnp.sum(_dense_ref(x, w, b, eids, "gelu") ** 2)

        rx, rw, rb = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(gb), np.asarray(rb),
                                   atol=2e-4)

    def test_grads_interpret_bitwise_equals_xla(self):
        x, w, b, eids, offsets = _mk()

        def mk_loss(backend):
            def loss(x, w):
                y = grouped_gemm(x, w, offsets, bias=b,
                                 activation="gelu", backend=backend)
                return jnp.sum(y ** 2)
            return loss

        gx, gw = jax.grad(mk_loss("xla"), argnums=(0, 1))(x, w)
        hx, hw = jax.grad(mk_loss("interpret"), argnums=(0, 1))(x, w)
        assert np.array_equal(np.asarray(gx), np.asarray(hx))
        assert np.array_equal(np.asarray(gw), np.asarray(hw))

    def test_total_skew_and_empty_experts(self):
        """Every token routed to ONE expert: the other experts are
        empty segments (forced min-1 units keep their dw blocks
        initialized) and the output is a plain dense GEMM."""
        x, w, b, _eids, _ = _mk()
        T, E = x.shape[0], w.shape[0]
        eids = np.full(T, 2)
        offsets = jnp.asarray(
            np.concatenate([[0],
                            np.cumsum(np.bincount(eids, minlength=E))])
            .astype(np.int32))
        y = grouped_gemm(x, w, offsets, bias=b, backend="xla")
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(x @ w[2] + b[2]),
                                   atol=2e-6)
        gw = jax.grad(lambda w: jnp.sum(grouped_gemm(
            x, w, offsets, bias=b, backend="xla") ** 2))(w)
        # empty experts: exactly-zero weight grads (not garbage)
        for e in (0, 1, 3):
            assert float(jnp.abs(gw[e]).max()) == 0.0

    def test_rows_past_offsets_end_are_zero(self):
        """offsets[E] < T: trailing rows belong to no expert and must
        come out exactly zero (the phantom unit zero-fills pad tiles)."""
        x, w, b, eids, _ = _mk()
        T, E = x.shape[0], w.shape[0]
        live = T - 37
        eids = np.sort(np.random.RandomState(3).randint(0, E, live))
        offsets = jnp.asarray(
            np.concatenate([[0],
                            np.cumsum(np.bincount(eids, minlength=E))])
            .astype(np.int32))
        y = np.asarray(grouped_gemm(x, w, offsets, bias=b,
                                    backend="xla"))
        assert (y[live:] == 0).all()
        ref = _dense_ref(x[:live], w, b, eids)
        np.testing.assert_allclose(y[:live], np.asarray(ref), atol=2e-6)

    def test_no_bias_no_activation(self):
        x, w, _b, eids, offsets = _mk()
        y = grouped_gemm(x, w, offsets, backend="xla")
        zb = jnp.zeros((w.shape[0], w.shape[-1]), jnp.float32)
        ref = _dense_ref(x, w, zb, eids)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   atol=2e-6)

    def test_offsets_shape_validated(self):
        x, w, b, _eids, _ = _mk()
        with pytest.raises(ValueError, match="E\\+1"):
            grouped_gemm(x, w, jnp.zeros((3,), jnp.int32))

    def test_tile_aligned_shapes_take_kernel_geometry(self):
        """128-aligned shapes run the kernel path (interpret off-TPU)
        and still match the fallback bitwise — the geometry the chip
        runs."""
        x, w, b, eids, offsets = _mk(T=DEFAULT_BLOCK_ROWS * 2, K=128,
                                     N=256, E=4, seed=5)
        yi = grouped_gemm(x, w, offsets, bias=b, backend="interpret")
        yx = grouped_gemm(x, w, offsets, bias=b, backend="xla")
        assert np.array_equal(np.asarray(yi), np.asarray(yx))


class TestRouter:
    def test_fp32_routing_under_bf16_inputs(self):
        """The fp32-router satellite: logits whose top-2 margin is
        below bf16 resolution must still route by the TRUE ordering.
        A bf16 router collapses the pair into a tie (top_k then picks
        the lower index) — the exact instability the fp32 rule fixes."""
        # gate crafted so expert 1's logit exceeds expert 0's by 2^-10
        # (bf16 has 8 mantissa bits: both round to 1.0)
        d = 4
        x = jnp.ones((1, d), jnp.bfloat16)
        wg = np.zeros((d, 3), np.float32)
        wg[:, 0] = 1.0 / d
        wg[:, 1] = (1.0 + 2.0 ** -10) / d
        wg[:, 2] = -1.0
        wg = jnp.asarray(wg)

        _, _, idx = moe_route(x, wg, 1)
        assert int(idx[0, 0]) == 1  # true max, not the bf16 tie pick

        # the bf16 formulation demonstrably picks the WRONG expert
        bf_logits = (x @ wg.astype(jnp.bfloat16)).astype(jnp.bfloat16)
        _, bf_idx = jax.lax.top_k(jax.nn.softmax(bf_logits, -1), 1)
        assert int(bf_idx[0, 0]) == 0

    def test_bf16_and_fp32_inputs_route_identically(self):
        rng = np.random.RandomState(0)
        x32 = jnp.asarray(rng.randn(64, 16).astype(np.float32))
        xbf = x32.astype(jnp.bfloat16)
        wg = jnp.asarray(rng.randn(16, 8).astype(np.float32) * 0.3)
        _, _, i32 = moe_route(xbf.astype(jnp.float32), wg, 2)
        _, _, ibf = moe_route(xbf, wg, 2)
        # same VALUES in (the bf16 tensor) -> identical fp32 routing
        assert np.array_equal(np.asarray(i32), np.asarray(ibf))
