"""hapi.Model distributed wiring (VERDICT r3 weak #5): Model.prepare in
a launched 2-proc run auto-wraps with DataParallel + shards batches via
DistributedBatchSampler, and training matches the single-process run on
the same global data (reference: hapi/model.py:1054 DynamicGraphAdapter
init_parallel_env + paddle.DataParallel wiring)."""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

WORKER = textwrap.dedent("""
    import os
    for var in list(os.environ):
        if var.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            os.environ.pop(var)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.distributed as dist
    from paddle_tpu.io import Dataset

    dist.init_parallel_env()
    rank = dist.get_rank()

    class Reg(Dataset):
        def __init__(self):
            rng = np.random.RandomState(0)
            self.x = rng.randn(32, 4).astype("float32")
            w = rng.randn(4, 1).astype("float32")
            self.y = self.x @ w

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return 32

    paddle.seed(0)
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(optimizer=paddle.optimizer.SGD(
        0.1, parameters=net.parameters()), loss=F.mse_loss)
    # prepare must have auto-wrapped (world=2, env initialized)
    assert isinstance(model.network, dist.DataParallel), type(model.network)

    ds = Reg()
    model.fit(ds, batch_size=8, epochs=3, shuffle=False, verbose=0)

    w = np.asarray(net.weight._data).ravel()
    # ranks must agree bit-for-bit after synced training
    outs = []
    t = paddle.to_tensor(w.astype(np.float32))
    dist.all_gather(outs, t)
    np.testing.assert_allclose(outs[0].numpy(), outs[1].numpy(),
                               rtol=0, atol=0)
    np.save(os.environ["HAPI_OUT"] + f".{rank}.npy", w)
    print(f"RANK{rank}_OK")
""")

SINGLE = textwrap.dedent("""
    import os
    for var in list(os.environ):
        if var.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            os.environ.pop(var)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.io import Dataset

    class Reg(Dataset):
        def __init__(self):
            rng = np.random.RandomState(0)
            self.x = rng.randn(32, 4).astype("float32")
            w = rng.randn(4, 1).astype("float32")
            self.y = self.x @ w

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return 32

    paddle.seed(0)
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(optimizer=paddle.optimizer.SGD(
        0.1, parameters=net.parameters()), loss=F.mse_loss)
    # replicate the 2-rank global batches: DistributedBatchSampler
    # splits contiguously (rank0: samples 0-15, rank1: 16-31), so DP
    # global step k averages over rows [8k:8k+8] U [16+8k:16+8k+8]
    ds = Reg()
    batches = []
    for k in range(2):
        idx = list(range(8 * k, 8 * k + 8)) + \
            list(range(16 + 8 * k, 16 + 8 * k + 8))
        batches.append((ds.x[idx], ds.y[idx]))
    model.fit(batches * 3, epochs=1, verbose=0)  # 3 epochs of 2 steps
    np.save(os.environ["HAPI_OUT"] + ".single.npy",
            np.asarray(net.weight._data).ravel())
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_hapi_fit_two_proc_parity(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_base = str(tmp_path / "w")

    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
            "HAPI_OUT": out_base,
            "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    for rank, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"rank {rank} failed:\n{err[-3000:]}"
        assert f"RANK{rank}_OK" in out

    single = tmp_path / "single.py"
    single.write_text(SINGLE)
    env = dict(os.environ)
    env.update({"HAPI_OUT": out_base,
                "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", "")})
    r = subprocess.run([sys.executable, str(single)], env=env, cwd=repo,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-3000:]

    w_dp = np.load(out_base + ".0.npy")
    w_single = np.load(out_base + ".single.npy")
    # 2-rank DP with local batch 8 averages grads over the same global
    # 16-sample batch as the single run -> same trajectory (fp tolerance)
    np.testing.assert_allclose(w_dp, w_single, rtol=1e-4, atol=1e-5)
