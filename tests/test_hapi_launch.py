"""hapi.Model.fit + launcher CLI (reference: hapi/model.py:1054,
distributed/launch/main.py:20)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.io import Dataset


class XorDataset(Dataset):
    def __init__(self, n=128):
        w = np.random.RandomState(1).randn(8, 1).astype("float32")
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype("float32")
        self.y = (self.x @ w > 0).astype("int64")[:, 0]

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class TestHapiModel:
    def _model(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.Adam(
                0.01, parameters=net.parameters()),
            loss=F.cross_entropy,
            metrics=paddle.metric.Accuracy())
        return model

    def test_fit_trains_and_history(self):
        model = self._model()
        ds = XorDataset()
        hist = model.fit(ds, epochs=3, batch_size=32, verbose=0)
        assert "loss" in hist and len(hist["loss"]) == 3
        assert hist["loss"][-1] < hist["loss"][0]

    def test_fit_with_eval_and_metrics(self):
        model = self._model()
        ds = XorDataset()
        hist = model.fit(ds, eval_data=XorDataset(64), epochs=6,
                         batch_size=32, verbose=0)
        assert any(k.startswith("eval_") for k in hist)
        logs = model.evaluate(XorDataset(64), batch_size=32, verbose=0)
        assert "acc" in logs and logs["acc"] > 0.5

    def test_predict(self):
        model = self._model()
        out = model.predict(XorDataset(32), batch_size=16,
                            stack_outputs=True)
        assert out[0].shape == (32, 2)

    def test_save_load_roundtrip(self, tmp_path):
        model = self._model()
        ds = XorDataset(64)
        model.fit(ds, epochs=1, batch_size=32, verbose=0)
        path = str(tmp_path / "ckpt" / "model")
        model.save(path)
        assert os.path.exists(path + ".pdparams")
        assert os.path.exists(path + ".pdopt")

        model2 = self._model()
        model2.load(path)
        for p1, p2 in zip(model.parameters(), model2.parameters()):
            np.testing.assert_array_equal(np.asarray(p1._data),
                                          np.asarray(p2._data))

    def test_early_stopping_and_checkpoint(self, tmp_path):
        from paddle_tpu.hapi.callbacks import EarlyStopping

        model = self._model()
        ds = XorDataset()
        es = EarlyStopping(monitor="loss", patience=0, verbose=0,
                           save_best_model=False)
        hist = model.fit(ds, eval_data=XorDataset(64), epochs=20,
                         batch_size=32, verbose=0,
                         save_dir=str(tmp_path / "ck"), callbacks=[es])
        # checkpointing wrote epoch dirs + final
        assert os.path.exists(str(tmp_path / "ck" / "final.pdparams"))

    def test_summary(self, capsys):
        model = self._model()
        info = model.summary()
        assert info["total_params"] == 8 * 32 + 32 + 32 * 2 + 2

    def test_mnist_lenet_via_fit(self):
        """The BASELINE config-anchor #1 through the high-level API."""
        from paddle_tpu.vision.models import LeNet

        paddle.seed(1)
        net = LeNet(num_classes=10)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.Adam(
                1e-3, parameters=net.parameters()),
            loss=F.cross_entropy, metrics=paddle.metric.Accuracy())

        class FakeMnist(Dataset):
            def __init__(self, n=64):
                rng = np.random.RandomState(0)
                self.x = rng.randn(n, 1, 28, 28).astype("float32")
                self.y = rng.randint(0, 10, (n,)).astype("int64")

            def __getitem__(self, i):
                return self.x[i], self.y[i]

            def __len__(self):
                return len(self.x)

        hist = model.fit(FakeMnist(), epochs=2, batch_size=16, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0]


class TestLaunchCLI:
    def test_two_process_launch_smoke(self, tmp_path):
        """2-process CPU launch: PADDLE_* env contract + both ranks run
        (reference: launch/main.py:20 + collective.py:22)."""
        script = tmp_path / "worker.py"
        script.write_text(textwrap.dedent("""
            import os
            rank = int(os.environ["PADDLE_TRAINER_ID"])
            world = int(os.environ["PADDLE_TRAINERS_NUM"])
            eps = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
            assert world == 2 and len(eps) == 2
            assert os.environ["MASTER_ADDR"]
            print(f"worker {rank}/{world} ok", flush=True)
        """))
        logdir = str(tmp_path / "logs")
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))}
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", logdir, str(script)],
            cwd="/root/repo", env=env, capture_output=True, text=True,
            timeout=120)
        assert proc.returncode == 0, proc.stderr[-800:]
        logs = sorted(os.listdir(logdir))
        assert logs == ["workerlog.0", "workerlog.1"]
        body = "".join(open(os.path.join(logdir, f)).read() for f in logs)
        assert "worker 0/2 ok" in body and "worker 1/2 ok" in body

    def test_failure_propagates(self, tmp_path):
        script = tmp_path / "bad.py"
        script.write_text("import sys; sys.exit(3)")
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))}
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", str(script)],
            cwd="/root/repo", env=env, capture_output=True, text=True,
            timeout=120)
        assert proc.returncode == 3


class TestElasticExitCode:
    def test_exit_101_triggers_relaunch_without_elastic_level(self, tmp_path):
        """Exit code 101 is the elastic-restart REQUEST (manager.py:32):
        the launcher relaunches even without --elastic_level."""
        script = tmp_path / "flaky.py"
        marker = tmp_path / "ran_once"
        script.write_text(
            "import os, sys\n"
            f"m = {str(marker)!r}\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').write('1')\n"
            "    sys.exit(101)\n"  # first run requests elastic restart
            "print('SECOND_RUN_OK')\n")
        import subprocess
        import sys as _sys

        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [_sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "1", "--max_restarts", "2", str(script)],
            env=env, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr[-1500:]
        assert "elastic restart requested" in proc.stderr
