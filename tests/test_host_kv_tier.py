"""Host-DRAM KV tier (ISSUE 20): spill/restore byte parity and exact
page accounting.

Tier-1 acceptance pins:
- spill -> restore is BYTE-EXACT: an evicted prefix chain pulled back
  from host buffers decodes greedy tokens identical to an engine that
  never felt pool pressure, for both the bf16 and the int8 cache-KV
  pools (the int8 path round-trips quantized rows + f32 scale-plane
  columns bit-for-bit);
- accounting is conserved: after any mix of spills, host-LRU
  evictions and restores, ``fleet.spills - fleet.restores -
  fleet.host_evictions == len(tier)`` and the pool's free-page count
  returns exactly to its starting point;
- with NO tier configured, the eviction decision point degrades to
  the plain release it always was (zero spill counters, pages free).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import set_flags
from paddle_tpu.inference import FusedCausalLM
from paddle_tpu.profiler import stats
from paddle_tpu.serving import HostKVTier, Request, ServingEngine, SLOConfig


def _model(seed=7, max_position=256, vocab=64):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=vocab, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=max_position)


def _engine(seed=7, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_length", 96)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("slo", SLOConfig(prefill_chunk=8))
    return ServingEngine(_model(seed), **kw)


@pytest.fixture
def host_tier_flag():
    set_flags({"kv_host_tier_bytes": 1 << 22})
    yield
    set_flags({"kv_host_tier_bytes": 0})


def _run_one(eng, prompt, n=8):
    eng.submit_request(Request(np.array(prompt, np.int32),
                               max_new_tokens=n))
    while eng.has_work:
        eng.step()
    r = eng.finished[-1]
    return list(r.generated)


class TestSpillRestoreParity:
    def test_bf16_spill_restore_token_parity(self, host_tier_flag):
        """The headline byte-parity pin: run, evict the ENTIRE prefix
        cache to the host tier, re-run the same prompt — tokens must
        match an engine that never spilled, and the re-run must have
        RESTORED (not re-prefilled) the chain."""
        prompt = np.arange(24, dtype=np.int32) % 64
        set_flags({"kv_host_tier_bytes": 0})
        ref = _run_one(_engine(), prompt)
        set_flags({"kv_host_tier_bytes": 1 << 22})
        stats.reset()
        eng = _engine()
        assert eng.host_tier is not None
        assert list(_run_one(eng, prompt)) == ref
        pc = eng.prefix_cache
        n_cached = len(pc)
        assert pc.evict(n_cached) == n_cached
        assert len(eng.host_tier) == n_cached
        assert int(stats.counter("fleet.spills").value) == n_cached
        eng.finished.clear()
        assert _run_one(eng, prompt) == ref
        # match() caps reuse at (len-1)//ps pages, so exactly that
        # many restored; the final chain page stays host-resident
        expect = (len(prompt) - 1) // eng.page_size
        assert int(stats.counter("fleet.restores").value) == expect
        assert int(stats.counter(
            "serving.prefix_restored_pages").value) == expect

    def test_spill_blob_restores_bit_exact(self, host_tier_flag):
        """Raw pool bytes through the tier: export the spilled pages'
        rows before eviction, restore, export again — identical."""
        prompt = np.arange(20, dtype=np.int32) % 64
        eng = _engine()
        _run_one(eng, prompt)
        pc = eng.prefix_cache
        pages_before = dict(pc._entries)  # key -> page
        blobs = {k: eng.export_kv_pages([p])
                 for k, p in pages_before.items()}
        pc.evict(len(pc))
        restored = pc.restore_chain(prompt, reserve=0)
        assert restored == (len(prompt) - 1) // eng.page_size
        for k, page in pc._entries.items():
            after = eng.export_kv_pages([page])
            np.testing.assert_array_equal(blobs[k]["k"], after["k"])
            np.testing.assert_array_equal(blobs[k]["v"], after["v"])

    def test_seeded_deterministic_parity(self, host_tier_flag):
        """Two identically seeded engines, one driven through a full
        spill/restore cycle mid-stream — same greedy tokens (the
        serving path is greedy, so seeded-determinism == the pressure
        cycle being invisible to the decode)."""
        rng = np.random.RandomState(13)
        prompts = [rng.randint(0, 64, (L,)).astype(np.int32)
                   for L in (18, 26)]
        ref_eng = _engine(seed=21)
        refs = [_run_one(ref_eng, p, n=6) for p in prompts]
        eng = _engine(seed=21)
        outs = []
        for p in prompts:
            outs.append(_run_one(eng, p, n=6))
            pc = eng.prefix_cache
            pc.evict(len(pc))      # spill everything between requests
            pc.restore_chain(p, reserve=0)
        assert outs == refs

    def test_int8_pool_spill_restore_parity(self, host_tier_flag):
        """int8 cache-KV spills quantized rows + f32 scale columns;
        the round-trip must be bit-exact and roughly HALVE the spilled
        bytes vs the bf16 pool (the int8-aware tier pin)."""
        prompt = (np.arange(24, dtype=np.int32) * 3) % 64
        set_flags({"kv_host_tier_bytes": 0})
        ref = _run_one(_engine(kv_dtype="int8"), prompt)
        set_flags({"kv_host_tier_bytes": 1 << 22})
        stats.reset()
        eng = _engine(kv_dtype="int8")
        assert eng.host_tier is not None and eng.can_spill()
        assert _run_one(eng, prompt) == ref
        pc = eng.prefix_cache
        pages_before = dict(pc._entries)
        blobs = {k: eng.export_kv_pages([p])
                 for k, p in pages_before.items()}
        pc.evict(len(pc))
        int8_bytes = int(stats.counter("fleet.spill_bytes").value)
        assert pc.restore_chain(prompt, reserve=0) > 0
        for k, page in pc._entries.items():
            after = eng.export_kv_pages([page])
            assert after["int8"]
            for part in ("k", "v", "k_scale", "v_scale"):
                np.testing.assert_array_equal(blobs[k][part],
                                              after[part])
        eng.finished.clear()
        assert _run_one(eng, prompt) == ref
        # vs bf16: same workload spills ~2x the bytes
        stats.reset()
        bf = _engine()
        _run_one(bf, prompt)
        bf.prefix_cache.evict(len(bf.prefix_cache))
        bf16_bytes = int(stats.counter("fleet.spill_bytes").value)
        assert int8_bytes < 0.75 * bf16_bytes

    def test_preempt_spill_restore_cycle(self, host_tier_flag):
        """Pool pressure end-to-end: concurrent decoders overflow a
        tiny pool (preempted slots park their full pages in the prefix
        cache; evictions spill), and every stream still matches the
        unpressured reference."""
        rng = np.random.RandomState(29)
        prompts = [rng.randint(0, 64, (16,)).astype(np.int32)
                   for _ in range(3)]
        set_flags({"kv_host_tier_bytes": 0})
        ref_eng = _engine(max_batch=3, max_length=64)
        for p in prompts:
            ref_eng.submit_request(Request(p, max_new_tokens=24))
        refs = [list(r.generated)
                for r in sorted(ref_eng.run(), key=lambda r: r.id)]
        set_flags({"kv_host_tier_bytes": 1 << 22})
        stats.reset()
        eng = _engine(max_batch=3, max_length=64, num_pages=15)
        for p in prompts:
            eng.submit_request(Request(p, max_new_tokens=24))
        done = sorted(eng.run(), key=lambda r: r.id)
        assert [list(r.generated) for r in done] == refs
        assert stats.counter("serving.preemptions").value > 0


class TestAccounting:
    def test_conservation_after_mixed_traffic(self, host_tier_flag):
        """spills - restores - host_evictions == live entries, pool
        free pages conserved, bytes_used == sum of entry blobs."""
        prompt = np.arange(28, dtype=np.int32) % 64
        stats.reset()
        eng = _engine()
        free0 = eng._mgr.free_pages
        _run_one(eng, prompt)
        eng.finished.clear()
        pc, ht = eng.prefix_cache, eng.host_tier
        pc.evict(len(pc))                       # all spill
        # tier.* occupancy gauges published (naming-lint covered
        # prefix; summed over every live tier in the process)
        assert stats.gauge("tier.host_pages").value >= len(ht)
        pc.restore_chain(prompt, reserve=0)     # most restore
        pc.evict(2)                             # spill again (dedupe)
        ht.drop(1)                              # host LRU eviction
        spills = int(stats.counter("fleet.spills").value)
        restores = int(stats.counter("fleet.restores").value)
        hevict = int(stats.counter("fleet.host_evictions").value)
        assert spills - restores - hevict == len(ht)
        assert ht.bytes_used == sum(
            e["_bytes"] for e in ht._entries.values())
        assert int(stats.counter("fleet.spill_bytes").value) >= \
            int(stats.counter("fleet.restore_bytes").value)
        # release every cache-held page: the pool must return exactly
        # to its starting free count (no leaked restore references)
        pc.evict(len(pc))
        assert eng._mgr.free_pages == free0
        ht.clear()
        assert ht.bytes_used == 0 and len(ht) == 0

    def test_capacity_lru_eviction(self, host_tier_flag):
        """A tier sized for two pages LRU-drops the oldest entry on
        the third spill, firing on_drop for the directory."""
        prompt = np.arange(24, dtype=np.int32) % 64
        eng = _engine()
        _run_one(eng, prompt)
        ht = eng.host_tier
        ht.capacity_bytes = 2 * ht.page_bytes + 2  # blobs ~ page size
        dropped = []
        ht.on_drop = dropped.append
        stats.reset()
        pc = eng.prefix_cache
        n = len(pc)
        pc.evict(n)
        assert len(ht) == 2
        hevict = int(stats.counter("fleet.host_evictions").value)
        assert hevict == int(stats.counter("fleet.spills").value) - 2
        assert len(dropped) == hevict >= 1

    def test_no_tier_eviction_unchanged(self):
        """Satellite 5 regression guard: with the tier disabled the
        decision point is the old release — pages free, no counters."""
        set_flags({"kv_host_tier_bytes": 0})
        prompt = np.arange(24, dtype=np.int32) % 64
        stats.reset()
        eng = _engine()
        assert eng.host_tier is None
        _run_one(eng, prompt)
        pc = eng.prefix_cache
        free_before = eng._mgr.free_pages
        n = len(pc)
        assert pc.evict(n) == n
        assert eng._mgr.free_pages == free_before + n
        assert int(stats.counter("fleet.spills").value) == 0

    def test_page_hbm_bytes_geometry(self):
        """page_hbm_bytes is the cost model's unit: K+V rows for one
        logical page across layers (+ scale planes in int8 mode)."""
        eng = _engine()
        m = eng._mgr
        import jax.numpy as jnp

        elems = m.num_layers * m._pool_heads * m.page_size * m.head_dim
        assert m.page_hbm_bytes() == \
            2 * elems * jnp.dtype(m.dtype).itemsize
        eng8 = _engine(kv_dtype="int8")
        m8 = eng8._mgr
        elems8 = (m8.num_layers * m8._pool_heads * m8.page_size
                  * m8.head_dim)
        scales = m8._pool_heads * m8.num_layers * m8.page_size * 4
        assert m8.page_hbm_bytes() == 2 * (elems8 + scales)
        assert m8.page_hbm_bytes() < m.page_hbm_bytes()


class TestTierUnit:
    def test_restore_run_missing_key_is_none(self, host_tier_flag):
        eng = _engine()
        ht = eng.host_tier
        assert ht.restore_run([b"nope"]) is None
        assert ht.restore_run([]) == []

    def test_direct_tier_roundtrip(self, host_tier_flag):
        """HostKVTier against a live engine pool without the prefix
        cache in the loop: spill two pages, restore them into fresh
        pages, bytes identical."""
        prompt = np.arange(16, dtype=np.int32) % 64
        eng = _engine()
        _run_one(eng, prompt)
        pc = eng.prefix_cache
        (k1, p1), (k2, p2) = list(pc._entries.items())[:2]
        ht = HostKVTier(eng, capacity_bytes=1 << 20)
        before = eng.export_kv_pages([p1, p2])
        assert ht.spill_pages([k1, k2], [p1, p2]) == 2
        pages = ht.restore_run([k1, k2])
        assert pages is not None and len(pages) == 2
        after = eng.export_kv_pages(pages)
        np.testing.assert_array_equal(before["k"], after["k"])
        np.testing.assert_array_equal(before["v"], after["v"])
        eng._mgr.release_pages(pages)
