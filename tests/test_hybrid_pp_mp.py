"""Hybrid TP+PP composition: ColumnParallel/RowParallel linears INSIDE
pipeline stages, pp=2 x mp=2 (x dp=2) on the 8-device mesh.

Reference parity target: the reference exercises dp+pp+mp jointly
(/root/reference/test/collective/multinode/dygraph_hybrid_dpppmp.py,
fleet/meta_parallel/pipeline_parallel.py running inside an mp group).
Here mp rides GSPMD's auto axes inside the pp shard_map: stacked stage
params keep their per-dim mp sharding, the RowParallel contraction emits
the mp all-reduce inside every pipeline tick.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet

PP, MP, DP = 2, 2, 2
VOCAB, D = 32, 16


@pytest.fixture(scope="module", autouse=True)
def _fleet_init():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        **strategy.hybrid_configs,
        "dp_degree": DP, "mp_degree": MP, "pp_degree": PP,
        "sharding_degree": 1, "sep_degree": 1,
    }
    fleet.init(is_collective=True, strategy=strategy)
    yield


class TPBlock(nn.Layer):
    """Megatron-style TP MLP block: column-parallel up, row-parallel
    down, no gather in between."""

    def __init__(self):
        super().__init__()
        from paddle_tpu.distributed.fleet.layers.mpu import (
            ColumnParallelLinear, RowParallelLinear)

        self.ln = nn.LayerNorm(D)
        self.up = ColumnParallelLinear(D, 4 * D, gather_output=False)
        self.down = RowParallelLinear(4 * D, D, input_is_parallel=True)

    def forward(self, x):
        return x + self.down(F.gelu(self.up(self.ln(x))))


def _loss_fn(logits, labels):
    return F.cross_entropy(logits.reshape([-1, VOCAB]),
                           labels.reshape([-1]))


def _build(seed, n_blocks=PP):
    from paddle_tpu.distributed.fleet.meta_parallel import (
        LayerDesc, PipelineLayer)

    paddle.seed(seed)
    descs = [LayerDesc(nn.Embedding, VOCAB, D)]
    descs += [LayerDesc(TPBlock) for _ in range(n_blocks)]
    descs += [LayerDesc(nn.LayerNorm, D), LayerDesc(nn.Linear, D, VOCAB)]
    return PipelineLayer(layers=descs, num_stages=PP, loss_fn=_loss_fn)


def _data(M=4, mb=2, seq=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(0, VOCAB, (M * mb, seq))
    y = rng.randint(0, VOCAB, (M * mb, seq))
    return paddle.to_tensor(x), paddle.to_tensor(y)


class TestHybridPPMP:
    def _wrap(self, seed, acc=4):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallel)

        s = fleet.DistributedStrategy()
        s.hybrid_configs["pp_configs"].accumulate_steps = acc
        hcg = fleet.get_hybrid_communicate_group()
        return PipelineParallel(_build(seed), hcg, s)

    def test_mesh_axes(self):
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == MP
        assert hcg.get_pipe_parallel_world_size() == PP

    def test_stacked_params_carry_mp_sharding(self):
        """The stacked ColumnParallel weight must be sharded over BOTH
        pp (stage dim) and mp (feature dim)."""
        pp = self._wrap(0)
        specs = {}
        for sp in pp._stacked_params:
            spec = tuple(sp._data.sharding.spec)
            specs[sp.name] = spec
        col = [s for n, s in specs.items() if "up.weight" in n]
        row = [s for n, s in specs.items() if "down.weight" in n]
        assert col and col[0][0] == "pp" and col[0][2] == "mp", col
        assert row and row[0][0] == "pp" and row[0][1] == "mp", row

    def test_pp_mp_matches_single_program(self):
        """pp=2 x mp=2 1F1B training must track the unpipelined
        single-program model step for step."""
        data = _data()
        # reference: same model, plain sequential execution
        pl_ref = _build(42)
        opt_ref = paddle.optimizer.SGD(0.1, parameters=pl_ref.parameters())
        ref_losses = []
        for _ in range(3):
            loss = _loss_fn(pl_ref(data[0]), data[1])
            loss.backward()
            opt_ref.step()
            opt_ref.clear_grad()
            ref_losses.append(float(loss.numpy()))

        pp = self._wrap(42)
        opt = paddle.optimizer.SGD(0.1, parameters=pp.parameters())
        losses = [float(pp.train_batch(list(data), opt).numpy())
                  for _ in range(3)]
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-4,
                                   atol=1e-5)

    def test_hlo_has_both_collectives(self):
        """The compiled hybrid step must contain collective-permute
        (pp handoff) AND an mp reduction (all-reduce) from the
        RowParallel contraction."""
        pp = self._wrap(7)
        data = _data()
        pp.train_batch(list(data), paddle.optimizer.SGD(
            0.1, parameters=pp.parameters()))
        x_all = pp._split_micro_arrays(data[0])
        (labels_all,) = pp._split_micro_arrays(data[1])
        import jax.random as jr

        lowered = pp._step_fn.lower(
            [p._data for p in pp._pre_params],
            [p._data for p in pp._stacked_params],
            [p._data for p in pp._post_params],
            jr.key(0), x_all, labels_all)
        txt = lowered.compile().as_text()
        assert "collective-permute" in txt
        assert "all-reduce" in txt
