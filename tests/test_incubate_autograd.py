"""incubate.autograd functional transforms (reference:
python/paddle/incubate/autograd jvp/vjp/Jacobian/Hessian tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.autograd import (Hessian, Jacobian, grad_fn,
                                          hessian, jacobian, jvp, vjp)


def _x(v):
    return paddle.to_tensor(np.asarray(v, np.float32))


class TestFunctionalTransforms:
    def test_vjp(self):
        def f(x):
            return (x * x).sum()

        out, g = vjp(f, _x([1.0, 2.0, 3.0]))
        np.testing.assert_allclose(float(out.numpy()), 14.0)
        np.testing.assert_allclose(g.numpy(), [2.0, 4.0, 6.0])

    def test_vjp_with_cotangent(self):
        def f(x):
            return x * 3.0

        _, g = vjp(f, _x([1.0, 1.0]), v=_x([2.0, 5.0]))
        np.testing.assert_allclose(g.numpy(), [6.0, 15.0])

    def test_jvp(self):
        def f(x):
            return x * x

        out, t = jvp(f, _x([2.0, 3.0]), v=_x([1.0, 0.0]))
        np.testing.assert_allclose(out.numpy(), [4.0, 9.0])
        np.testing.assert_allclose(t.numpy(), [4.0, 0.0])  # 2x * v

    def test_jacobian(self):
        def f(x):
            import paddle_tpu

            return paddle_tpu.matmul(
                _x([[1.0, 2.0], [3.0, 4.0]]), x)

        j = jacobian(f, _x([1.0, 1.0]))
        np.testing.assert_allclose(j.numpy(), [[1, 2], [3, 4]])

    def test_hessian(self):
        def f(x):
            return (x * x * x).sum()  # H = diag(6x)

        h = hessian(f, _x([1.0, 2.0]))
        np.testing.assert_allclose(h.numpy(), [[6.0, 0.0], [0.0, 12.0]])

    def test_lazy_matrix_api(self):
        def f(x):
            return (x * x).sum()

        H = Hessian(f, _x([3.0]))
        np.testing.assert_allclose(H[0].numpy(), [2.0])
        J = Jacobian(lambda x: x * 2.0, _x([1.0, 2.0]))
        assert tuple(J.shape) == (2, 2)

    def test_grad_fn(self):
        g = grad_fn(lambda x: x * x)
        np.testing.assert_allclose(g(_x([3.0])).numpy(), [6.0])


class TestPSStubs:
    def test_ps_raises_with_guidance(self):
        from paddle_tpu.distributed.ps import TheOnePSRuntime

        with pytest.raises(NotImplementedError, match="SPMD"):
            TheOnePSRuntime()
