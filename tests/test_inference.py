"""Inference/serving slice tests: predictor API, paged-KV attention,
fused decode parity, e2e greedy generation.

Mirrors the reference's serving surface tests (reference:
test/legacy_test/test_block_multihead_attention.py pattern — paged decode
vs dense reference; paddle/fluid/inference/tests for predictor API).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.inference import (
    BlockKVCacheManager, Config, FusedCausalLM, GenerationEngine,
    create_predictor)
from paddle_tpu.incubate.nn.fused_transformer import (
    FusedMultiTransformer, PagedKV, qkv_split_rope_fused, rope_table)
from paddle_tpu.nn.functional.paged_attention import (
    paged_attention, write_kv_pages, write_prefill_kv_pages)


class TestPagedAttention:
    def _dense_ref(self, q, k_full, v_full, seq_lens):
        """Dense masked attention reference: q [b,h,d], k/v [b,L,h_kv,d]."""
        b, h, d = q.shape
        n_kv = k_full.shape[2]
        group = h // n_kv
        k = np.repeat(k_full, group, axis=2)
        v = np.repeat(v_full, group, axis=2)
        logits = np.einsum("bhd,blhd->bhl", q, k) * (d ** -0.5)
        L = k.shape[1]
        mask = np.arange(L)[None, :] < seq_lens[:, None]
        logits = np.where(mask[:, None, :], logits, -1e30)
        w = np.exp(logits - logits.max(-1, keepdims=True))
        w /= w.sum(-1, keepdims=True)
        return np.einsum("bhl,blhd->bhd", w, v)

    def test_paged_matches_dense(self):
        rng = np.random.RandomState(0)
        b, h, n_kv, d, page, pages_per_seq = 3, 4, 2, 8, 4, 5
        max_len = page * pages_per_seq
        seq_lens = np.array([7, 20, 13], np.int32)
        q = rng.randn(b, h, d).astype(np.float32)
        k_full = rng.randn(b, max_len, n_kv, d).astype(np.float32)
        v_full = rng.randn(b, max_len, n_kv, d).astype(np.float32)

        # scatter the dense kv into PAGE-MAJOR head-major pages via
        # contiguous tables ([P, n_kv, page, d] — r5 layout)
        key_cache = np.zeros((b * pages_per_seq, n_kv, page, d), np.float32)
        val_cache = np.zeros_like(key_cache)
        tables = np.arange(b * pages_per_seq,
                           dtype=np.int32).reshape(b, pages_per_seq)
        for i in range(b):
            for t in range(max_len):
                pg, sl = tables[i, t // page], t % page
                key_cache[pg, :, sl] = k_full[i, t]
                val_cache[pg, :, sl] = v_full[i, t]

        out = paged_attention(jnp.asarray(q), jnp.asarray(key_cache),
                              jnp.asarray(val_cache),
                              jnp.asarray(seq_lens), jnp.asarray(tables))
        ref = self._dense_ref(q, k_full, v_full, seq_lens)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                                   atol=2e-5)

    def test_write_then_read_roundtrip(self):
        rng = np.random.RandomState(1)
        b, n_kv, d, page, pps = 2, 2, 4, 4, 3
        cache_k = jnp.zeros((b * pps, n_kv, page, d))
        cache_v = jnp.zeros_like(cache_k)
        tables = jnp.asarray(
            np.arange(b * pps, dtype=np.int32).reshape(b, pps))
        # prefill 5 tokens then append 2 more one at a time
        k_pre = rng.randn(b, 5, n_kv, d).astype(np.float32)
        v_pre = rng.randn(b, 5, n_kv, d).astype(np.float32)
        cache_k, cache_v = write_prefill_kv_pages(
            cache_k, cache_v, jnp.asarray(k_pre), jnp.asarray(v_pre),
            tables)
        ks, vs = [k_pre], [v_pre]
        for t in range(5, 7):
            nk = rng.randn(b, n_kv, d).astype(np.float32)
            nv = rng.randn(b, n_kv, d).astype(np.float32)
            cache_k, cache_v = write_kv_pages(
                cache_k, cache_v, jnp.asarray(nk), jnp.asarray(nv),
                jnp.full((b,), t, jnp.int32), tables)
            ks.append(nk[:, None])
            vs.append(nv[:, None])
        k_all = np.concatenate(ks, axis=1)
        v_all = np.concatenate(vs, axis=1)
        # read back through paged attention vs dense reference
        q = rng.randn(b, n_kv, d).astype(np.float32)
        lens = np.full((b,), 7, np.int32)
        out = paged_attention(jnp.asarray(q), cache_k, cache_v,
                              jnp.asarray(lens), tables)
        pad = page * pps - 7
        k_pad = np.pad(k_all, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v_pad = np.pad(v_all, ((0, 0), (0, pad), (0, 0), (0, 0)))
        ref = self._dense_ref(q, k_pad, v_pad, lens)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                                   atol=2e-5)


class TestKVCacheManager:
    def test_alloc_free_reuse(self):
        mgr = BlockKVCacheManager(num_layers=1, num_kv_heads=2, head_dim=4,
                                  page_size=4, num_pages=8)
        mgr.allocate("a", 10)  # 3 pages
        mgr.allocate("b", 16)  # 4 pages
        assert mgr.free_pages == 1
        with pytest.raises(RuntimeError):
            mgr.allocate("c", 10)
        mgr.free("a")
        assert mgr.free_pages == 4
        mgr.allocate("c", 14)  # fits again
        t = mgr.block_tables(["b", "c"])
        assert t.shape == (2, 4)


class TestFusedDecodeParity:
    """Greedy decode through the paged path must reproduce the dense
    full-forward argmax sequence — the correctness contract of
    fused_multi_transformer + block attention."""

    def _model(self):
        paddle.seed(7)
        return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                             dim_feedforward=64, num_layers=2,
                             max_position=128)

    def test_decode_matches_dense_forward(self):
        model = self._model()
        rng = np.random.RandomState(3)
        ids = rng.randint(0, 64, (2, 6))
        engine = GenerationEngine(model, page_size=4, max_length=64)
        out = engine.generate(ids, max_new_tokens=5)
        assert out.shape == (2, 11)

        # dense reference: re-run the whole sequence each step
        seq = ids.copy()
        for _ in range(5):
            logits = model(paddle.to_tensor(seq)).numpy()
            nxt = logits[:, -1].argmax(-1)
            seq = np.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, seq)

    def test_eos_early_stop(self):
        model = self._model()
        ids = np.array([[1, 2, 3]])
        engine = GenerationEngine(model, page_size=4, max_length=32)
        logits = model(paddle.to_tensor(ids)).numpy()
        eos = int(logits[0, -1].argmax())  # first generated token = EOS
        out = engine.generate(ids, max_new_tokens=4, eos_token_id=eos)
        assert (out[0, 3:] == eos).all()

    def test_qkv_split_rope_shapes(self):
        d, nq, nkv, hd = 16, 4, 2, 4
        cos, sin = rope_table(32, hd)
        x = jnp.ones((3, d))
        w = jnp.ones((d, (nq + 2 * nkv) * hd))
        q, k, v = qkv_split_rope_fused(
            x, w, None, jnp.array([0, 1, 2]), nq, nkv, hd, cos, sin)
        assert q.shape == (3, nq, hd)
        assert k.shape == (3, nkv, hd)
        assert v.shape == (3, nkv, hd)
        # position 0 rope is identity on q/k halves
        q0, _, _ = qkv_split_rope_fused(
            x[:1], w, None, jnp.array([0]), nq, nkv, hd, cos, sin)
        base = (x[:1] @ w).reshape(1, nq + 2 * nkv, hd)[:, :nq]
        np.testing.assert_allclose(np.asarray(q0), np.asarray(base),
                                   rtol=1e-6)


class TestPredictorAPI:
    def test_save_load_predict(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.static.input_spec import InputSpec

        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 4)

            def forward(self, x):
                return self.fc(x)

        net = Net()
        path = str(tmp_path / "net")
        paddle.jit.save(net, path,
                        input_spec=[InputSpec([2, 8], "float32")])

        config = Config(path)
        assert "tpu" in config.summary()
        predictor = create_predictor(config)
        names = predictor.get_input_names()
        assert names == ["input_0"]
        x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
        predictor.get_input_handle(names[0]).copy_from_cpu(x)
        assert predictor.run()
        out_name = predictor.get_output_names()[0]
        got = predictor.get_output_handle(out_name).copy_to_cpu()

        want = net(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_generate_zero_tokens():
    paddle.seed(0)
    lm = FusedCausalLM(32, 16, 2, 32, 1, max_position=64)
    eng = GenerationEngine(lm, page_size=4, max_length=32)
    ids = np.array([[1, 2, 3]])
    np.testing.assert_array_equal(eng.generate(ids, max_new_tokens=0),
                                  ids)
