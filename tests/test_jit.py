"""jit/to_static tests + ResNet AMP anchor (BASELINE.md config #2)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.static import InputSpec


class TestToStatic:
    def test_forward_parity(self):
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        x = paddle.randn([4, 8])
        eager = model(x).numpy()
        static_model = paddle.jit.to_static(model)
        out = static_model(x).numpy()
        np.testing.assert_allclose(eager, out, rtol=1e-5)

    def test_backward_through_compiled(self):
        model = nn.Linear(6, 3)
        sm = paddle.jit.to_static(model)
        x = paddle.randn([5, 6])
        sm(x).sum().backward()
        expected = x.numpy().T @ np.ones((5, 3), np.float32)
        np.testing.assert_allclose(model.weight.grad.numpy(), expected,
                                   rtol=1e-4)

    def test_arg_gradient(self):
        model = paddle.jit.to_static(nn.Linear(4, 2))
        x = paddle.randn([3, 4])
        x.stop_gradient = False
        model(x).sum().backward()
        assert x.grad is not None
        assert x.grad.shape == [3, 4]

    def test_program_cache_hit(self):
        model = paddle.jit.to_static(nn.Linear(4, 2))
        model(paddle.randn([2, 4]))
        assert len(model.forward.program_cache) == 1
        model(paddle.randn([2, 4]))
        assert len(model.forward.program_cache) == 1
        model(paddle.randn([8, 4]))  # new shape → new program
        assert len(model.forward.program_cache) == 2

    def test_bn_buffers_update(self):
        model = nn.Sequential(nn.Conv2D(1, 2, 3, padding=1),
                              nn.BatchNorm2D(2))
        sm = paddle.jit.to_static(model)
        before = model[1]._mean.numpy().copy()
        sm(paddle.randn([4, 1, 6, 6]))
        assert not np.allclose(before, model[1]._mean.numpy())

    def test_dropout_fresh_masks(self):
        model = nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5))
        model.train()
        sm = paddle.jit.to_static(model)
        o1 = sm(paddle.ones([2, 8])).numpy()
        o2 = sm(paddle.ones([2, 8])).numpy()
        assert not np.allclose(o1, o2)

    def test_decorator_and_function_form(self):
        @paddle.jit.to_static
        def f(a, b):
            return paddle.matmul(a, b) + 1.0

        x = paddle.randn([3, 3])
        np.testing.assert_allclose(
            f(x, x).numpy(), x.numpy() @ x.numpy() + 1.0, rtol=1e-5)

    def test_rollback(self):
        model = paddle.jit.to_static(nn.Linear(2, 2))
        model(paddle.randn([1, 2]))
        model.forward.rollback()
        out = model(paddle.randn([1, 2]))
        assert out.shape == [1, 2]

    def test_train_eval_programs_distinct(self):
        model = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.9))
        sm = paddle.jit.to_static(model)
        model.train()
        sm(paddle.ones([2, 4]))
        model.eval()
        o1 = sm(paddle.ones([2, 4])).numpy()
        o2 = sm(paddle.ones([2, 4])).numpy()
        np.testing.assert_allclose(o1, o2)


class TestTrainStep:
    def test_whole_step_converges(self):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
        opt = paddle.optimizer.Adam(0.01, parameters=net.parameters())
        step = paddle.jit.TrainStep(net, F.mse_loss, opt)
        target = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
        rng = np.random.RandomState(0)
        for _ in range(150):
            xb = rng.randn(32, 4).astype(np.float32)
            loss = step([paddle.to_tensor(xb)],
                        [paddle.to_tensor(xb @ target)])
        assert float(loss.numpy()) < 0.05

    def test_matches_eager_step(self):
        def build():
            paddle.seed(11)
            net = nn.Linear(3, 2)
            opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
            return net, opt

        xb = np.random.RandomState(1).randn(4, 3).astype(np.float32)
        yb = np.zeros((4, 2), np.float32)

        net_e, opt_e = build()
        loss_e = F.mse_loss(net_e(paddle.to_tensor(xb)), paddle.to_tensor(yb))
        loss_e.backward()
        opt_e.step()

        net_c, opt_c = build()
        step = paddle.jit.TrainStep(net_c, F.mse_loss, opt_c)
        loss_c = step([paddle.to_tensor(xb)], [paddle.to_tensor(yb)])

        np.testing.assert_allclose(loss_e.numpy(), loss_c.numpy(), rtol=1e-5)
        np.testing.assert_allclose(net_e.weight.numpy(), net_c.weight.numpy(),
                                   rtol=1e-5)

    def test_grad_clip_and_scheduler(self):
        net = nn.Linear(2, 2)
        sched = paddle.optimizer.lr.StepDecay(0.1, step_size=1, gamma=0.5)
        opt = paddle.optimizer.SGD(sched, parameters=net.parameters(),
                                   grad_clip=nn.ClipGradByGlobalNorm(1.0))
        step = paddle.jit.TrainStep(net, F.mse_loss, opt)
        x = paddle.randn([4, 2])
        y = paddle.zeros([4, 2])
        step([x], [y])
        sched.step()
        step([x], [y])  # lr change must not retrigger compile errors


class TestSaveLoad:
    def test_roundtrip(self, tmp_path):
        m = nn.Sequential(nn.Linear(4, 3), nn.Tanh())
        m.eval()
        path = str(tmp_path / "model")
        paddle.jit.save(m, path, input_spec=[InputSpec([1, 4], "float32")])
        loaded = paddle.jit.load(path)
        x = paddle.randn([1, 4])
        np.testing.assert_allclose(m(x).numpy(), loaded(x).numpy(), rtol=1e-5)

    def test_state_only_save(self, tmp_path):
        m = nn.Linear(2, 2)
        path = str(tmp_path / "m2")
        paddle.jit.save(m, path)
        loaded = paddle.jit.load(path)
        sd = loaded.state_dict()
        assert "weight" in sd


class TestResNetAMPAnchor:
    """Config anchor #2: ResNet to_static + AMP O2 (scaled-down input)."""

    def test_resnet18_static_amp_o2_step(self):
        from paddle_tpu.vision.models import resnet18

        paddle.seed(0)
        model = resnet18(num_classes=10)
        opt = paddle.optimizer.Momentum(0.01, parameters=model.parameters())
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16")
        model = paddle.jit.to_static(model)
        x = paddle.randn([2, 3, 32, 32]).astype("bfloat16")
        y = paddle.to_tensor(np.random.randint(0, 10, (2,)))
        with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
            logits = model(x)
            loss = F.cross_entropy(logits, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        assert np.isfinite(float(loss.numpy()))

    def test_resnet50_static_amp_o2_step(self):
        """BASELINE configs[1] names ResNet-50 — exercise it e2e under
        its own name (to_static + AMP O2 + optimizer step; CPU-sized
        input, the chip bench scales it up)."""
        from paddle_tpu.vision.models import resnet50

        paddle.seed(0)
        model = resnet50(num_classes=10)
        opt = paddle.optimizer.Momentum(0.01, parameters=model.parameters())
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16")
        model = paddle.jit.to_static(model)
        x = paddle.randn([2, 3, 32, 32]).astype("bfloat16")
        y = paddle.to_tensor(np.random.randint(0, 10, (2,)))
        with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
            logits = model(x)
            loss = F.cross_entropy(logits, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        assert logits.shape[-1] == 10
        assert np.isfinite(float(loss.numpy()))

    def test_resnet18_train_step_compiled(self):
        from paddle_tpu.vision.models import resnet18

        paddle.seed(0)
        model = resnet18(num_classes=4)
        opt = paddle.optimizer.Momentum(0.05, parameters=model.parameters())
        step = paddle.jit.TrainStep(
            model, lambda logits, y: F.cross_entropy(logits, y), opt)
        x = paddle.randn([2, 3, 32, 32])
        y = paddle.to_tensor(np.array([0, 1]))
        l1 = float(step([x], [y]).numpy())
        for _ in range(8):
            l2 = float(step([x], [y]).numpy())
        assert l2 < l1  # memorizes a 2-sample batch quickly
