"""Built-in launch master (VERDICT r3 missing #4): two launcher
processes on localhost rendezvous through the KV master with NO
hand-wired per-node config beyond a shared --master address, heartbeat
each other, and survive one node restart via generation-scoped
re-rendezvous (reference: launch/controllers/master.py HTTPMaster/
ETCDMaster; utils/kv_server.py)."""
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRAIN = textwrap.dedent("""
    import os, sys, time
    for var in list(os.environ):
        if var.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            os.environ.pop(var)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    gen = int(os.environ.get("PADDLE_RESTART_GENERATION", "0"))
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    out = os.environ["LAUNCH_OUT"]

    dist.init_parallel_env()
    # prove the data plane works this generation
    t = paddle.to_tensor(np.full(2, rank + 1.0, np.float32))
    dist.all_reduce(t)
    assert t.numpy()[0] == 3.0, t.numpy()
    open(f"{out}/g{gen}.rank{rank}.start", "w").write("ok")

    if gen == 0:
        time.sleep(60)   # generation 0 lingers so the test can kill a node
    open(f"{out}/g{gen}.rank{rank}.done", "w").write("ok")
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _launcher(master, script, out_dir, extra=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["LAUNCH_OUT"] = out_dir
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nnodes", "2", "--nproc_per_node", "1",
           "--master", master, "--elastic_level", "1",
           "--max_restarts", "2", *extra, script]
    return subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            start_new_session=True)


@pytest.mark.timeout(300)
def test_two_node_rendezvous_and_failover(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(TRAIN)
    out = str(tmp_path)
    master = f"127.0.0.1:{_free_port()}"

    la = _launcher(master, str(script), out)
    time.sleep(1.5)  # deterministic: A hosts the KV server (B gets killed)
    lb = _launcher(master, str(script), out)

    # generation 0 rendezvoused: both ranks ran a real collective
    deadline = time.time() + 120
    want0 = [f"{out}/g0.rank0.start", f"{out}/g0.rank1.start"]
    while time.time() < deadline and not all(
            os.path.exists(p) for p in want0):
        assert la.poll() is None and lb.poll() is None, (
            la.communicate()[1][-2000:] if la.poll() is not None
            else lb.communicate()[1][-2000:])
        time.sleep(0.5)
    assert all(os.path.exists(p) for p in want0), \
        "generation-0 rendezvous did not complete"

    # kill node B's whole process group mid-run (launcher + worker)
    os.killpg(os.getpgid(lb.pid), signal.SIGKILL)
    lb.wait(timeout=30)

    # restart node B after the heartbeat TTL so the survivor has
    # already torn down and bumped the generation
    time.sleep(7)
    lb2 = _launcher(master, str(script), out)

    # both launchers must finish generation 1 cleanly
    rc_a = la.wait(timeout=150)
    rc_b = lb2.wait(timeout=150)
    err_a = la.communicate()[1]
    err_b = lb2.communicate()[1]
    assert rc_a == 0, err_a[-3000:]
    assert rc_b == 0, err_b[-3000:]
    for r in (0, 1):
        assert os.path.exists(f"{out}/g1.rank{r}.start"), \
            f"rank {r} never rendezvoused at generation 1\n{err_a[-1500:]}"
        assert os.path.exists(f"{out}/g1.rank{r}.done")
    # the survivor reported the failover
    assert "re-rendezvous at generation 1" in err_a


def test_single_node_unchanged(tmp_path):
    """nnodes=1 keeps the no-master fast path."""
    script = tmp_path / "ok.py"
    script.write_text("print('hi')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(script)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
