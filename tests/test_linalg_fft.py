"""paddle.linalg + paddle.fft namespace tests (reference:
test/legacy_test/test_linalg_*.py, test/fft)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _spd(n=4, seed=0):
    rng = np.random.RandomState(seed)
    a = rng.randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


class TestLinalg:
    def test_svd_reconstruction_and_grad(self):
        spd = _spd()
        a = paddle.to_tensor(spd, stop_gradient=False)
        u, s, vh = paddle.linalg.svd(a)
        np.testing.assert_allclose(
            (u.numpy() * s.numpy()) @ vh.numpy(), spd, rtol=1e-3,
            atol=1e-3)
        s.sum().backward()
        assert a.grad is not None  # svd differentiable through the tape

    def test_inv_solve_cholesky(self):
        spd = _spd()
        a = paddle.to_tensor(spd)
        np.testing.assert_allclose(
            paddle.linalg.inv(a).numpy() @ spd, np.eye(4), atol=1e-4)
        b = paddle.to_tensor(
            np.random.RandomState(1).randn(4, 2).astype(np.float32))
        x = paddle.linalg.solve(a, b)
        np.testing.assert_allclose(spd @ x.numpy(), b.numpy(), atol=1e-4)
        L = paddle.linalg.cholesky(a)
        np.testing.assert_allclose(L.numpy() @ L.numpy().T, spd,
                                   rtol=1e-4, atol=1e-4)
        U = paddle.linalg.cholesky(a, upper=True)
        np.testing.assert_allclose(U.numpy().T @ U.numpy(), spd,
                                   rtol=1e-4, atol=1e-4)

    def test_eigh_qr_det(self):
        spd = _spd()
        w, v = paddle.linalg.eigh(paddle.to_tensor(spd))
        np.testing.assert_allclose(
            v.numpy() @ np.diag(w.numpy()) @ v.numpy().T, spd,
            rtol=1e-3, atol=1e-3)
        a_np = np.random.RandomState(0).randn(4, 3).astype(np.float32)
        q, r = paddle.linalg.qr(paddle.to_tensor(a_np))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a_np,
                                   atol=1e-4)
        res = paddle.linalg.slogdet(paddle.to_tensor(spd))
        # Paddle returns one stacked tensor [2, ...]: [sign, logdet]
        sign, logdet = float(res.numpy()[0]), float(res.numpy()[1])
        np.testing.assert_allclose(sign * np.exp(logdet),
                                   np.linalg.det(spd), rtol=1e-3)

    def test_pinv_matrix_power_multi_dot(self):
        a_np = np.random.RandomState(0).randn(3, 5).astype(np.float32)
        p = paddle.linalg.pinv(paddle.to_tensor(a_np))
        np.testing.assert_allclose(a_np @ p.numpy() @ a_np, a_np,
                                   atol=1e-3)
        spd = _spd(3)
        mp = paddle.linalg.matrix_power(paddle.to_tensor(spd), 3)
        np.testing.assert_allclose(mp.numpy(), spd @ spd @ spd,
                                   rtol=1e-3)
        xs = [paddle.to_tensor(
            np.random.RandomState(i).randn(3, 3).astype(np.float32))
            for i in range(3)]
        md = paddle.linalg.multi_dot(xs)
        np.testing.assert_allclose(
            md.numpy(), xs[0].numpy() @ xs[1].numpy() @ xs[2].numpy(),
            rtol=1e-4)

    def test_triangular_solve(self):
        spd = _spd()
        L = np.linalg.cholesky(spd).astype(np.float32)
        b = np.random.RandomState(2).randn(4, 1).astype(np.float32)
        x = paddle.linalg.triangular_solve(
            paddle.to_tensor(L), paddle.to_tensor(b), upper=False)
        np.testing.assert_allclose(L @ x.numpy(), b, atol=1e-4)


class TestFFT:
    def test_fft_matches_numpy(self):
        x = np.random.RandomState(0).randn(16).astype(np.float32)
        f = paddle.fft.fft(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(f.numpy()), np.fft.fft(x),
                                   rtol=1e-4, atol=1e-4)

    def test_rfft_roundtrip(self):
        x = np.random.RandomState(1).randn(16).astype(np.float32)
        r = paddle.fft.irfft(paddle.fft.rfft(paddle.to_tensor(x)))
        np.testing.assert_allclose(r.numpy(), x, atol=1e-5)

    def test_fft2_and_shift(self):
        x = np.random.RandomState(2).randn(4, 8).astype(np.float32)
        f = paddle.fft.fft2(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(f.numpy()),
                                   np.fft.fft2(x), rtol=1e-4, atol=1e-4)
        sh = paddle.fft.fftshift(paddle.to_tensor(x))
        np.testing.assert_allclose(sh.numpy(), np.fft.fftshift(x))

    def test_fftfreq(self):
        np.testing.assert_allclose(paddle.fft.fftfreq(8, d=0.5).numpy(),
                                   np.fft.fftfreq(8, d=0.5))

    def test_onnx_stub(self):
        with pytest.raises(NotImplementedError, match="jit.save"):
            paddle.onnx.export(None, "x")


class TestLinalgSemantics:
    def test_eigh_uplo_ignores_other_triangle(self):
        spd = _spd()
        garbage = spd.copy()
        garbage[np.tril_indices(4, -1)] = 99.0  # junk lower triangle
        w_u, _ = paddle.linalg.eigh(paddle.to_tensor(garbage), UPLO="U")
        w_ref, _ = paddle.linalg.eigh(paddle.to_tensor(spd))
        np.testing.assert_allclose(np.sort(w_u.numpy()),
                                   np.sort(w_ref.numpy()), rtol=1e-4)

    def test_matrix_rank_absolute_tol(self):
        a = np.diag([1e-4, 1e-6]).astype(np.float32)
        r = paddle.linalg.matrix_rank(paddle.to_tensor(a), tol=1e-5)
        assert int(r.numpy()) == 1  # absolute threshold, not relative

    def test_cross_first_dim3_axis(self):
        x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        y = np.random.RandomState(1).randn(3, 4).astype(np.float32)
        out = paddle.linalg.cross(paddle.to_tensor(x),
                                  paddle.to_tensor(y))
        np.testing.assert_allclose(out.numpy(),
                                   np.cross(x, y, axis=0), rtol=1e-5)
