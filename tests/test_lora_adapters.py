"""AdapterBank + ragged batched-LoRA delta kernel (ISSUE 18,
serving/adapters.py + nn/functional/lora.py).

Pinned here: the adapter-sort helpers' semantics (stable order, base
tokens past ``offsets[-1]``, exact inverse), forward parity of
``lora_delta`` against a dense per-segment reference, BITWISE equality
between the interpreter-run Pallas kernel and the tiled XLA walk (the
off-TPU path is the exact serving numerics), the structural zero-delta
for base/pad rows and padded rank columns, and the bank lifecycle:
hot load/unload, refcounted draining, alpha folding, rank padding,
full-bank errors, and the version-keyed operand cache.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.nn.functional.lora import (
    inverse_order, lora_delta, pad_rank, sort_by_adapter)
from paddle_tpu.profiler import stats
from paddle_tpu.serving.adapters import (
    AdapterBank, LoRAAdapter, TARGET_PROJECTIONS)


def _mk(T=96, K=256, N=384, S=3, R=8, seed=0, base_frac=0.3):
    """Mixed base+adapter chunk: x sorted by slot, plus the sorted
    offsets — the exact layout the serve path hands to lora_delta."""
    rng = np.random.RandomState(seed)
    x = rng.randn(T, K).astype(np.float32)
    a = (rng.randn(S, K, R) * 0.05).astype(np.float32)
    b = (rng.randn(S, R, N) * 0.05).astype(np.float32)
    slots = rng.randint(0, S, T).astype(np.int32)
    slots[rng.rand(T) < base_frac] = -1          # base-model tokens
    order, offsets, counts = sort_by_adapter(jnp.asarray(slots), S)
    x_sorted = jnp.asarray(x)[order]
    return (jnp.asarray(x), jnp.asarray(a), jnp.asarray(b),
            slots, np.asarray(order), np.asarray(offsets),
            np.asarray(counts), x_sorted)


def _dense_ref(x_sorted, a, b, offsets):
    """Per-segment dense reference in fp64 — rows past offsets[-1]
    stay zero."""
    T = x_sorted.shape[0]
    out = np.zeros((T, b.shape[-1]), np.float64)
    for s in range(a.shape[0]):
        lo, hi = int(offsets[s]), int(offsets[s + 1])
        seg = np.asarray(x_sorted[lo:hi], np.float64)
        out[lo:hi] = (seg @ np.asarray(a[s], np.float64)
                      ) @ np.asarray(b[s], np.float64)
    return out


class TestSortHelpers:
    def test_pad_rank_tiles(self):
        assert pad_rank(8, jnp.float32) == 8
        assert pad_rank(9, jnp.float32) == 16
        assert pad_rank(4, jnp.bfloat16) == 16
        assert pad_rank(16, jnp.bfloat16) == 16
        assert pad_rank(33, jnp.int8) == 64

    def test_sort_semantics(self):
        slots = jnp.asarray([2, -1, 0, 2, 0, 7, 1, -1], jnp.int32)
        order, offsets, counts = sort_by_adapter(slots, 3)
        # 7 is out of range for a 3-slot bank -> base, like -1
        assert np.asarray(counts).tolist() == [2, 1, 2]
        assert np.asarray(offsets).tolist() == [0, 2, 3, 5]
        order = np.asarray(order)
        # stable: same-slot tokens keep batch order
        assert order.tolist()[:5] == [2, 4, 6, 0, 3]
        # base tokens land past offsets[-1]
        assert set(order.tolist()[5:]) == {1, 5, 7}
        inv = np.asarray(inverse_order(jnp.asarray(order)))
        assert (inv[order] == np.arange(8)).all()

    def test_all_base(self):
        order, offsets, counts = sort_by_adapter(
            jnp.full((5,), -1, jnp.int32), 2)
        assert np.asarray(offsets).tolist() == [0, 0, 0]
        assert np.asarray(counts).tolist() == [0, 0]


class TestLoraDelta:
    def test_parity_vs_dense(self):
        _, a, b, _, _, offsets, _, x_sorted = _mk()
        got = np.asarray(lora_delta(
            x_sorted, a, b, jnp.asarray(offsets), backend="xla"))
        ref = _dense_ref(x_sorted, a, b, offsets)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)

    def test_interpret_bitwise_equals_xla(self):
        """The CPU fallback IS the serving numerics: the tiled XLA
        walk must match the interpreter-run Pallas kernel bitwise."""
        _, a, b, _, _, offsets, _, x_sorted = _mk(seed=3)
        off = jnp.asarray(offsets)
        xla = np.asarray(lora_delta(x_sorted, a, b, off,
                                    backend="xla"))
        itp = np.asarray(lora_delta(x_sorted, a, b, off,
                                    backend="interpret"))
        assert np.array_equal(xla, itp)

    @pytest.mark.parametrize("backend", ["xla", "interpret"])
    def test_base_rows_exact_zero(self, backend):
        _, a, b, _, _, offsets, _, x_sorted = _mk(seed=1,
                                                  base_frac=0.5)
        got = np.asarray(lora_delta(
            x_sorted, a, b, jnp.asarray(offsets), backend=backend))
        tail = got[int(offsets[-1]):]
        assert tail.size and (tail == 0.0).all()

    def test_padded_rank_columns_zero_delta(self):
        """rank padded to the sublane tile with zero columns gives the
        SAME delta as the unpadded rank — the +0.0 contract the bank's
        rank padding rests on."""
        _, a, b, _, _, offsets, _, x_sorted = _mk(R=8)
        R_pad = pad_rank(8 + 1, jnp.float32)     # 16
        a_pad = np.zeros((a.shape[0], a.shape[1], R_pad), np.float32)
        b_pad = np.zeros((b.shape[0], R_pad, b.shape[2]), np.float32)
        a_pad[..., :8] = np.asarray(a)
        b_pad[:, :8, :] = np.asarray(b)
        off = jnp.asarray(offsets)
        base = np.asarray(lora_delta(x_sorted, a, b, off,
                                     backend="xla"))
        padded = np.asarray(lora_delta(
            x_sorted, jnp.asarray(a_pad), jnp.asarray(b_pad), off,
            backend="xla"))
        np.testing.assert_allclose(padded, base, rtol=1e-6, atol=1e-7)

    def test_shape_validation(self):
        x, a, b, _, _, offsets, _, _ = _mk()
        with pytest.raises(ValueError, match="offsets"):
            lora_delta(x, a, b, jnp.zeros((2,), jnp.int32))
        with pytest.raises(ValueError, match="bank mismatch"):
            lora_delta(x, a, b[:, :4], jnp.asarray(offsets))


def _bank(slots=3, rank=4, dtype=np.float32):
    return AdapterBank(2, {"qkv": (16, 48), "ffn1": (16, 32)},
                       slots=slots, rank=rank, dtype=dtype)


class TestAdapterBank:
    def test_from_stack_dims_and_int8_base(self):
        L, d = 2, 16
        weights = {f"{p}_weight": np.zeros((L, d, 24), np.int8)
                   for p in TARGET_PROJECTIONS}
        bank = AdapterBank.from_stack(weights, slots=2, rank=4)
        assert bank.num_layers == L
        assert set(bank.dims) == set(TARGET_PROJECTIONS)
        assert bank.dims["qkv"] == (d, 24)
        # quantized base: adapters stay fp32 (and rank pads for fp32)
        assert bank.dtype == jnp.dtype(jnp.float32)
        assert bank.rank_pad == pad_rank(4, jnp.float32)

    def test_load_acquire_release_lifecycle(self):
        bank = _bank()
        s0 = bank.load(bank.random_adapter("t0"))
        s1 = bank.load(bank.random_adapter("t1"))
        assert s0 != s1 and bank.loaded() == {"t0": s0, "t1": s1}
        assert bank.acquire("t0", "r1") == s0
        assert bank.acquire("t0", "r1") == s0        # idempotent by rid
        assert bank.refcount("t0") == 1
        bank.acquire("t0", "r2")
        assert bank.refcount("t0") == 2
        bank.release("r1")
        bank.release("r1")                            # idempotent
        assert bank.refcount("t0") == 1
        with pytest.raises(KeyError):
            bank.acquire("missing", "r3")

    def test_draining_frees_on_last_release(self):
        bank = _bank()
        bank.load(bank.random_adapter("t0"))
        bank.acquire("t0", "r1")
        assert bank.unload("t0") is False             # draining
        assert bank.is_draining("t0")
        with pytest.raises(KeyError, match="draining"):
            bank.acquire("t0", "r2")                  # no new admits
        assert "t0" in bank.loaded()                  # still resident
        v = bank.version
        bank.release("r1")                            # last ref frees
        assert "t0" not in bank.loaded()
        assert bank.version > v
        # slot is reusable immediately
        bank.load(bank.random_adapter("t2"))

    def test_full_bank_and_double_load(self):
        bank = _bank(slots=2)
        bank.load(bank.random_adapter("t0"))
        bank.load(bank.random_adapter("t1"))
        with pytest.raises(RuntimeError, match="full"):
            bank.load(bank.random_adapter("t2"))
        with pytest.raises(ValueError, match="already loaded"):
            bank.load(bank.random_adapter("t0"))
        assert bank.unload("t0") is True
        bank.load(bank.random_adapter("t2"))
        with pytest.raises(KeyError):
            bank.unload("nope")

    def test_alpha_folds_into_b(self):
        bank = _bank()
        ad = bank.random_adapter("t0")
        a, b = ad.weights["qkv"]
        doubled = LoRAAdapter("t0x2", bank.rank,
                              {"qkv": (a, b)}, alpha=2 * bank.rank)
        a2, b2 = doubled.weights["qkv"]
        np.testing.assert_allclose(b2, b * 2.0)
        np.testing.assert_allclose(a2, a)

    def test_rank_padding_in_slot_page(self):
        bank = _bank(rank=4)                          # rank_pad 8
        assert bank.rank_pad == 8
        slot = bank.load(bank.random_adapter("t0", rank=2))
        ops = bank.operands()
        qa = np.asarray(ops["qkv_a"])                 # [L, S, K, R]
        qb = np.asarray(ops["qkv_b"])                 # [L, S, R, N]
        assert (qa[:, slot, :, 2:] == 0).all()
        assert (qb[:, slot, 2:, :] == 0).all()
        assert np.abs(qa[:, slot, :, :2]).sum() > 0

    def test_operand_cache_keyed_by_version(self):
        bank = _bank()
        bank.load(bank.random_adapter("t0"))
        ops1 = bank.operands()
        assert bank.operands() is ops1                # cache hit
        bank.load(bank.random_adapter("t1"))          # version bump
        ops2 = bank.operands()
        assert ops2 is not ops1
        assert set(ops2) == {"qkv_a", "qkv_b", "ffn1_a", "ffn1_b"}

    def test_telemetry(self):
        stats.reset()
        bank = _bank()
        bank.load(bank.random_adapter("t0"))
        bank.load(bank.random_adapter("t1"))
        assert stats.counter("lora.swaps").value == 2
        assert stats.gauge("lora.active_adapters").value == 2
        bank.acquire("t0", "r1")
        bank.unload("t0")                             # draining
        assert stats.gauge("lora.active_adapters").value == 1
        bank.release("r1")                            # freed -> swap #3
        assert stats.counter("lora.swaps").value == 3
