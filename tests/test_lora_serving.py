"""Batched multi-LoRA serving (ISSUE 18, scheduler/engine/router).

Tier-1 acceptance pins:

- greedy parity: batched multi-adapter serving (mixed base+adapter
  batches included) produces token streams IDENTICAL to serving each
  request alone, for K ∈ {1, 4} here and K=32 in the slow tier
  (``serve_bench --adapters 32`` drives the same pin at bench scale);
- compiled-program count is independent of the adapter set: hot
  load/unload under live traffic never recompiles, drops or restarts
  anything;
- preempt/resume (pool-pressure recompute) and fleet failover keep
  adaptered streams exact — replicas share ONE AdapterBank, so
  adoption re-resolves the same weights;
- DWRR tenant-fair admission delivers weighted shares with the
  starvation bound intact, and the router's per-tenant rate quota
  sheds with typed ``TenantQuotaExceeded`` on the injectable clock.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flags as F
from paddle_tpu.inference import FusedCausalLM
from paddle_tpu.serving import (AdapterBank, FaultInjector, FleetRouter,
                                ManualClock, SLOConfig, ServingEngine,
                                TenantQuotaExceeded, use_clock)
from paddle_tpu.profiler import stats


def _model(seed=7):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=256)


def _bank(model, names, slots=None, rank=4, seed=3):
    """init_scale=0.3: on the tiny test model the default 0.02 deltas
    are too small to flip a greedy argmax — divergence tests need the
    adapter to actually steer tokens."""
    bank = AdapterBank.from_stack(model.stack._stack(),
                                  slots=slots or max(len(names), 1),
                                  rank=rank)
    for name in names:
        bank.load(bank.random_adapter(name, seed=seed,
                                      init_scale=0.3))
    return bank


def _engine(model, bank=None, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_length", 128)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("slo", SLOConfig(prefill_chunk=16))
    return ServingEngine(model, adapters=bank, **kw)


def _workload(K, n_req, seed=5, lens=(12, 9, 17, 6)):
    """Mixed base+adapter request list: every 4th request is a
    base-model request, the rest round-robin the K adapters."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n_req):
        p = rng.randint(0, 64, (lens[i % len(lens)],))
        a = None if i % 4 == 3 else f"t{i % K}"
        out.append((p, a))
    return out


class TestGreedyParity:
    @pytest.mark.parametrize("K", [1, 4])
    def test_batched_equals_sequential(self, K):
        model = _model()
        bank = _bank(model, [f"t{i}" for i in range(K)])
        reqs = _workload(K, n_req=max(K, 4) + 2)
        eng = _engine(model, bank)
        rids = [eng.submit(p, max_new_tokens=8, adapter_id=a)
                for p, a in reqs]
        done = {r.id: r for r in eng.run()}
        assert all(done[r].state == "ok" for r in rids)
        nprog = len(eng._gen._decode_k_jit)
        for rid, (p, a) in zip(rids, reqs):
            solo = _engine(model, bank)
            sid = solo.submit(p, max_new_tokens=8, adapter_id=a)
            ref = {r.id: r for r in solo.run()}[sid]
            np.testing.assert_array_equal(done[rid].output, ref.output)
        # one adaptered + one base decode variant at most
        assert nprog <= 2
        assert all(bank.refcount(n) == 0 for n in bank.loaded())

    @pytest.mark.slow
    def test_batched_equals_sequential_k32(self):
        model = _model()
        bank = _bank(model, [f"t{i}" for i in range(32)], slots=32)
        reqs = _workload(32, n_req=36)
        eng = _engine(model, bank, max_batch=8)
        rids = [eng.submit(p, max_new_tokens=6, adapter_id=a)
                for p, a in reqs]
        done = {r.id: r for r in eng.run()}
        for rid, (p, a) in zip(rids, reqs):
            solo = _engine(model, bank)
            sid = solo.submit(p, max_new_tokens=6, adapter_id=a)
            ref = {r.id: r for r in solo.run()}[sid]
            np.testing.assert_array_equal(done[rid].output, ref.output)
        assert len(eng._gen._decode_k_jit) <= 2

    def test_adapter_steers_tokens(self):
        model = _model()
        bank = _bank(model, ["t0"])
        eng = _engine(model, bank)
        rng = np.random.RandomState(5)
        p = rng.randint(0, 64, (12,))
        r_base = eng.submit(p, max_new_tokens=8)
        r_ad = eng.submit(p, max_new_tokens=8, adapter_id="t0")
        done = {r.id: r for r in eng.run()}
        assert not np.array_equal(done[r_base].output,
                                  done[r_ad].output)

    def test_unknown_and_bankless_adapter_rejected(self):
        model = _model()
        eng = _engine(model, _bank(model, ["t0"]))
        with pytest.raises(KeyError):
            eng.submit(np.arange(4), max_new_tokens=2,
                       adapter_id="nope")
        bankless = _engine(model)
        with pytest.raises(ValueError, match="no adapter bank"):
            bankless.submit(np.arange(4), max_new_tokens=2,
                            adapter_id="t0")


class TestHotSwap:
    def test_swap_under_live_load_zero_dropped(self):
        """load/unload mid-decode: nothing drops, nothing recompiles,
        the draining adapter serves its live request to completion and
        frees on the last release."""
        model = _model()
        bank = _bank(model, ["t0"], slots=3)
        eng = _engine(model, bank)
        rng = np.random.RandomState(5)
        r0 = eng.submit(rng.randint(0, 64, (12,)), max_new_tokens=12,
                        adapter_id="t0")
        rb = eng.submit(rng.randint(0, 64, (9,)), max_new_tokens=12)
        for _ in range(3):
            eng.step()
        nprog_mid = len(eng._gen._decode_k_jit)
        bank.load(bank.random_adapter("t1", seed=4, init_scale=0.3))
        r1 = eng.submit(rng.randint(0, 64, (7,)), max_new_tokens=8,
                        adapter_id="t1")
        assert bank.unload("t0") is False         # draining, r0 live
        done = {r.id: r for r in eng.run()}
        assert all(done[r].state == "ok" for r in (r0, rb, r1))
        assert all(len(done[r].generated) > 0 for r in (r0, rb, r1))
        # drained slot freed itself at r0's terminal release
        assert "t0" not in bank.loaded()
        # the swaps changed VALUES only — no new decode programs
        assert len(eng._gen._decode_k_jit) == nprog_mid

    def test_program_count_independent_of_adapter_set(self):
        model = _model()
        bank = _bank(model, ["t0"], slots=4)
        eng = _engine(model, bank)
        rng = np.random.RandomState(9)
        eng.submit(rng.randint(0, 64, (10,)), max_new_tokens=4,
                   adapter_id="t0")
        eng.run()
        progs = (len(eng._gen._decode_k_jit), len(eng._chunk_jit))
        for name in ("t1", "t2"):
            bank.load(bank.random_adapter(name, seed=8,
                                          init_scale=0.3))
        rids = [eng.submit(rng.randint(0, 64, (10,)),
                           max_new_tokens=4, adapter_id=n)
                for n in ("t0", "t1", "t2")]
        done = {r.id: r for r in eng.run()}
        assert all(done[r].state == "ok" for r in rids)
        assert (len(eng._gen._decode_k_jit),
                len(eng._chunk_jit)) == progs

    def test_speculative_composition_rejected(self):
        model = _model()
        bank = _bank(model, ["t0"])
        eng = ServingEngine(model, max_batch=2, page_size=4,
                            max_length=128, adapters=bank,
                            speculative="self")
        with pytest.raises(ValueError, match="speculative"):
            eng.submit(np.arange(6), max_new_tokens=2,
                       adapter_id="t0")


class TestPreemptResume:
    def test_squeeze_preempts_adaptered_with_parity(self):
        """Pool-pressure preemption-by-recompute on adaptered
        decoders: streams stay exact vs the fault-free adaptered
        run (the resume path re-acquires the same slot)."""
        model = _model()
        bank = _bank(model, ["t0", "t1"])
        rng = np.random.RandomState(31)
        prompts = [rng.randint(0, 64, (16,)) for _ in range(3)]
        ads = ["t0", "t1", None]
        refs = []
        for p, a in zip(prompts, ads):
            solo = _engine(model, bank)
            sid = solo.submit(p, max_new_tokens=16, adapter_id=a)
            refs.append({r.id: r for r in solo.run()}[sid].output)
        before = stats.counter("serving.preemptions").value
        inj = FaultInjector().add("decode.step", kind="squeeze",
                                  pages=2, at=2)
        eng = ServingEngine(model, faults=inj, max_batch=3,
                            page_size=4, max_length=64,
                            decode_chunk=2, num_pages=15,
                            adapters=bank,
                            slo=SLOConfig(prefill_chunk=8))
        rids = [eng.submit(p, max_new_tokens=16, adapter_id=a)
                for p, a in zip(prompts, ads)]
        done = {r.id: r for r in eng.run()}
        for rid, ref in zip(rids, refs):
            assert done[rid].state == "ok"
            np.testing.assert_array_equal(done[rid].output, ref)
        assert stats.counter("serving.preemptions").value > before
        assert all(bank.refcount(n) == 0 for n in bank.loaded())
        inj.release_all()


class TestFleetFailover:
    def test_adaptered_failover_parity_shared_bank(self):
        """Replica death mid-decode: the adaptered request migrates,
        re-acquires from the SHARED bank on the adopting replica, and
        its greedy stream matches the single-engine reference."""
        model = _model()
        bank = _bank(model, ["t0"], slots=4)
        rng = np.random.RandomState(5)
        p = rng.randint(0, 64, (12,))
        ref_eng = _engine(_model(), bank)
        ref_id = ref_eng.submit(p, max_new_tokens=8, adapter_id="t0")
        ref = {r.id: r for r in ref_eng.run()}[ref_id].output

        router = FleetRouter(
            engine_factory=lambda i: _engine(_model(), bank),
            n_replicas=2)
        rid = router.submit(p, max_new_tokens=8, adapter_id="t0")
        for _ in range(4):
            router.step()
        victim = next(
            i for i, rep in enumerate(router.replicas)
            if rep.eng.num_active or rep.eng.num_prefilling
            or rep.eng.queue_depth)
        router.kill(victim)
        done = {r.id: r for r in router.run()}
        assert done[rid].state == "ok"
        np.testing.assert_array_equal(done[rid].output, ref)
        # the dead replica's pin was released, the adopter's drained
        assert bank.refcount("t0") == 0


class TestTenantFairness:
    def _fair_engine(self, weights, **kw):
        return _engine(_model(), None,
                       slo=SLOConfig(prefill_chunk=16,
                                     tenant_fair=True,
                                     tenant_weights=weights,
                                     fair_quantum=16), **kw)

    def _pick_order(self, eng, n):
        eng._drain_inbox()
        order = []
        for _ in range(n):
            r = eng._pick_waiting()
            if r is None:
                break
            order.append(r.tenant)
        return order

    def test_dwrr_weighted_share(self):
        """heavy (weight 3) admits ~3x light's share under equal
        per-request cost — a flood cannot starve the light tenant."""
        eng = self._fair_engine({"heavy": 3.0, "light": 1.0})
        for i in range(12):
            eng.submit(np.arange(8), max_new_tokens=8,
                       tenant="light" if i < 6 else "heavy")
        order = self._pick_order(eng, 8)
        assert len(order) == 8
        n_heavy = order.count("heavy")
        n_light = order.count("light")
        assert n_light >= 2                     # light keeps flowing
        assert n_heavy > n_light                # ...at weighted share

    def test_starvation_bound_preserved(self):
        """Even a weight-50 flood cannot pass the queue head over
        more than ``starvation_bound`` times."""
        bound = 4
        eng = self._fair_engine({"flood": 50.0},
                                starvation_bound=bound)
        eng.submit(np.arange(8), max_new_tokens=8, tenant="slim")
        for _ in range(20):
            eng.submit(np.arange(8), max_new_tokens=8,
                       tenant="flood")
        order = self._pick_order(eng, bound + 2)
        assert "slim" in order[: bound + 1]


class TestTenantQuota:
    def test_rate_quota_sheds_typed_and_rolls(self):
        F.set_flags({"FLAGS_tenant_quota_rps": 2.0,
                     "FLAGS_tenant_quota_window_s": 1.0})
        try:
            with use_clock(ManualClock()) as clk:
                router = FleetRouter(engines=[_engine(_model())])
                p = np.arange(8)
                router.submit(p, max_new_tokens=2, tenant="a")
                router.submit(p, max_new_tokens=2, tenant="a")
                with pytest.raises(TenantQuotaExceeded) as ei:
                    router.submit(p, max_new_tokens=2, tenant="a")
                assert ei.value.tenant == "a"
                assert ei.value.kind == "rate"
                # typed as an overload: callers' shed handling applies
                from paddle_tpu.serving import ServerOverloaded
                assert isinstance(ei.value, ServerOverloaded)
                # other tenants are untouched by a's quota
                router.submit(p, max_new_tokens=2, tenant="b")
                clk.advance(1.5)                 # window rolls
                router.submit(p, max_new_tokens=2, tenant="a")
                router.run()
        finally:
            F.set_flags({"FLAGS_tenant_quota_rps": 0.0})
