"""End-to-end anchor #1: MNIST LeNet dygraph training
(BASELINE.md config anchor; reference flow = paddle dygraph train loop).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def test_lenet_mnist_converges():
    paddle.seed(7)
    train_ds = MNIST(mode="train", synthetic_size=256)
    loader = DataLoader(train_ds, batch_size=64, shuffle=True)
    model = LeNet(num_classes=10)
    opt = paddle.optimizer.Adam(3e-3, parameters=model.parameters())

    first_loss = None
    last_loss = None
    model.train()
    for epoch in range(10):
        for x, y in loader:
            logits = model(x)
            loss = F.cross_entropy(logits, y.squeeze(-1))
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first_loss is None:
                first_loss = float(loss.numpy())
            last_loss = float(loss.numpy())

    assert last_loss < first_loss * 0.7, (first_loss, last_loss)

    # eval accuracy on the (learnable synthetic) train set beats chance by far
    model.eval()
    correct = total = 0
    for x, y in DataLoader(train_ds, batch_size=128):
        pred = model(x).numpy().argmax(-1)
        correct += int((pred == y.numpy().ravel()).sum())
        total += len(pred)
    assert correct / total > 0.5, correct / total


def test_lenet_amp_o1_step():
    paddle.seed(0)
    model = LeNet(num_classes=10)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(enable=False)  # bf16 needs no scaling
    x = paddle.randn([8, 1, 28, 28])
    y = paddle.to_tensor(np.random.randint(0, 10, (8,)))
    with paddle.amp.auto_cast(dtype="bfloat16"):
        loss = F.cross_entropy(model(x), y)
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    assert np.isfinite(float(loss.numpy()))
