"""Tests for device / distribution / sparse / quantization modules.

Mirrors the reference's per-module tests (reference: test/distribution/*,
test/legacy_test/test_sparse_*_op.py, test/quantization/*,
device API tests)."""
import numpy as np
import pytest
from scipy import stats as sps

import paddle_tpu as paddle


class TestDevice:
    def test_get_set_device(self):
        import paddle_tpu.device as device

        dev = device.get_device()
        assert isinstance(dev, str) and ":" in dev or dev == "cpu"
        device.synchronize()  # must not raise

    def test_memory_stats_shape(self):
        import paddle_tpu.device as device

        stats = device.memory_stats()
        assert isinstance(stats, dict)
        # counters are ints and monotone-consistent where present
        alloc = device.memory_allocated()
        peak = device.max_memory_allocated()
        assert isinstance(alloc, int) and isinstance(peak, int)
        assert peak >= alloc or peak == 0

    def test_cuda_namespace_alias(self):
        import paddle_tpu.device as device

        assert device.cuda.device_count() >= 1
        device.cuda.synchronize()

    def test_reset_peak_raises(self):
        import paddle_tpu.device as device

        with pytest.raises(NotImplementedError):
            device.reset_peak_memory_stats()


class TestDistribution:
    def test_normal_log_prob_entropy(self):
        from paddle_tpu.distribution import Normal

        d = Normal(1.0, 2.0)
        x = np.array([0.0, 1.0, 3.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(paddle.to_tensor(x)).numpy(),
            sps.norm(1.0, 2.0).logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy().numpy()),
                                   sps.norm(1.0, 2.0).entropy(), rtol=1e-5)

    def test_normal_sample_moments(self):
        from paddle_tpu.distribution import Normal

        d = Normal(np.float32(3.0), np.float32(0.5))
        s = d.sample((20000,)).numpy()
        assert abs(s.mean() - 3.0) < 0.02
        assert abs(s.std() - 0.5) < 0.02

    def test_seed_determinism(self):
        from paddle_tpu.distribution import Normal

        paddle.seed(123)
        a = Normal(0.0, 1.0).sample((8,)).numpy()
        paddle.seed(123)
        b = Normal(0.0, 1.0).sample((8,)).numpy()
        np.testing.assert_array_equal(a, b)

    def test_uniform(self):
        from paddle_tpu.distribution import Uniform

        d = Uniform(2.0, 6.0)
        s = d.sample((1000,)).numpy()
        assert s.min() >= 2.0 and s.max() < 6.0
        np.testing.assert_allclose(float(d.mean.numpy()), 4.0)
        lp = d.log_prob(paddle.to_tensor(np.array([3.0, 7.0], np.float32)))
        np.testing.assert_allclose(lp.numpy()[0], -np.log(4.0), rtol=1e-6)
        assert lp.numpy()[1] == -np.inf

    def test_categorical(self):
        from paddle_tpu.distribution import Categorical

        logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
        d = Categorical(logits)
        lp = d.log_prob(paddle.to_tensor(np.array([2])))
        np.testing.assert_allclose(lp.numpy(), [np.log(0.5)], rtol=1e-5)
        np.testing.assert_allclose(
            float(d.entropy().numpy()),
            sps.entropy([0.2, 0.3, 0.5]), rtol=1e-5)
        s = d.sample((5000,)).numpy()
        freq = np.bincount(s, minlength=3) / 5000
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.03)

    def test_bernoulli(self):
        from paddle_tpu.distribution import Bernoulli

        d = Bernoulli(np.float32(0.3))
        s = d.sample((10000,)).numpy()
        assert abs(s.mean() - 0.3) < 0.02
        np.testing.assert_allclose(float(d.variance.numpy()), 0.21,
                                   rtol=1e-5)

    def test_kl_divergence(self):
        from paddle_tpu.distribution import (Bernoulli, Categorical,
                                             Normal, kl_divergence)

        p, q = Normal(0.0, 1.0), Normal(1.0, 2.0)
        # closed form: log(s2/s1) + (s1^2 + (m1-m2)^2)/(2 s2^2) - 1/2
        want = np.log(2.0) + (1 + 1) / 8 - 0.5
        np.testing.assert_allclose(float(kl_divergence(p, q).numpy()),
                                   want, rtol=1e-5)
        c1 = Categorical(np.log(np.array([0.5, 0.5], np.float32)))
        c2 = Categorical(np.log(np.array([0.9, 0.1], np.float32)))
        want = 0.5 * np.log(0.5 / 0.9) + 0.5 * np.log(0.5 / 0.1)
        np.testing.assert_allclose(float(kl_divergence(c1, c2).numpy()),
                                   want, rtol=1e-5)
        b1, b2 = Bernoulli(0.3), Bernoulli(0.7)
        want = 0.3 * np.log(0.3 / 0.7) + 0.7 * np.log(0.7 / 0.3)
        np.testing.assert_allclose(float(kl_divergence(b1, b2).numpy()),
                                   want, rtol=1e-4)

    def test_kl_unregistered_raises(self):
        from paddle_tpu.distribution import Normal, Uniform, kl_divergence

        with pytest.raises(NotImplementedError):
            kl_divergence(Normal(0.0, 1.0), Uniform(0.0, 1.0))


class TestSparse:
    def test_coo_create_to_dense(self):
        import paddle_tpu.sparse as sparse

        indices = [[0, 1, 2], [1, 2, 0]]
        values = [1.0, 2.0, 3.0]
        sp = sparse.sparse_coo_tensor(indices, values, shape=[3, 3])
        dense = sp.to_dense().numpy()
        want = np.zeros((3, 3), np.float32)
        want[0, 1], want[1, 2], want[2, 0] = 1, 2, 3
        np.testing.assert_allclose(dense, want)
        assert sp.nnz() == 3

    def test_coo_matmul(self):
        import paddle_tpu.sparse as sparse

        rng = np.random.RandomState(0)
        dense = rng.randn(4, 4).astype(np.float32)
        mask = rng.rand(4, 4) < 0.4
        a = dense * mask
        idx = np.nonzero(a)
        sp = sparse.sparse_coo_tensor(np.stack(idx), a[idx], shape=[4, 4])
        x = rng.randn(4, 3).astype(np.float32)
        out = sparse.matmul(sp, paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, a @ x, rtol=1e-5, atol=1e-5)

    def test_csr_roundtrip(self):
        import paddle_tpu.sparse as sparse

        a = np.array([[0, 2, 0], [1, 0, 3], [0, 0, 0]], np.float32)
        idx = np.nonzero(a)
        coo = sparse.sparse_coo_tensor(np.stack(idx), a[idx], shape=[3, 3])
        csr = coo.to_sparse_csr()
        np.testing.assert_array_equal(np.asarray(csr.crows().numpy()),
                                      [0, 1, 3, 3])
        np.testing.assert_allclose(csr.to_dense().numpy(), a)
        back = csr.to_sparse_coo()
        np.testing.assert_allclose(back.to_dense().numpy(), a)

    def test_csr_create(self):
        import paddle_tpu.sparse as sparse

        csr = sparse.sparse_csr_tensor(
            [0, 2, 3], [0, 2, 1], [1.0, 2.0, 3.0], shape=[2, 3])
        want = np.array([[1, 0, 2], [0, 3, 0]], np.float32)
        np.testing.assert_allclose(csr.to_dense().numpy(), want)

    def test_add_and_relu(self):
        import paddle_tpu.sparse as sparse

        a = np.array([[0, -2.0], [1.0, 0]], np.float32)
        idx = np.nonzero(a)
        sp = sparse.sparse_coo_tensor(np.stack(idx), a[idx], shape=[2, 2])
        both = sparse.add(sp, sp)
        np.testing.assert_allclose(both.to_dense().numpy(), 2 * a)
        r = sparse.relu(sp)
        np.testing.assert_allclose(r.to_dense().numpy(),
                                   np.maximum(a, 0))
        r2 = sparse.nn.ReLU()(sp)
        np.testing.assert_allclose(r2.to_dense().numpy(),
                                   np.maximum(a, 0))

    def test_multiply_keeps_sparsity(self):
        import paddle_tpu.sparse as sparse

        a = np.array([[0, 2.0], [3.0, 0]], np.float32)
        idx = np.nonzero(a)
        sp = sparse.sparse_coo_tensor(np.stack(idx), a[idx], shape=[2, 2])
        d = np.array([[5.0, 6.0], [7.0, 8.0]], np.float32)
        out = sparse.multiply(sp, paddle.to_tensor(d))
        assert sparse.is_sparse_coo(out)
        np.testing.assert_allclose(out.to_dense().numpy(), a * d)
        # symmetric order: dense * sparse
        out2 = sparse.multiply(paddle.to_tensor(d), sp)
        assert sparse.is_sparse_coo(out2)
        np.testing.assert_allclose(out2.to_dense().numpy(), a * d)


class TestQuantization:
    def _model(self):
        import paddle_tpu.nn as nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(8, 16)
                self.fc2 = nn.Linear(16, 4)

            def forward(self, x):
                return self.fc2(nn.functional.relu(self.fc1(x)))

        paddle.seed(0)
        return Net()

    def test_ptq_roundtrip_accuracy(self):
        from paddle_tpu.quantization import PTQ, QuantedLinear

        model = self._model()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(16, 8).astype(np.float32))
        ref = model(x).numpy()

        ptq = PTQ()
        model = ptq.quantize(model)
        for _ in range(4):  # calibration passes
            model(x)
        model = ptq.convert(model)
        assert isinstance(model.fc1, QuantedLinear)
        assert model.fc1.w_int.dtype == np.int8
        got = model(x).numpy()
        # int8 weight-only: small relative error vs float model
        rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
        assert rel < 0.05, rel

    def test_qat_trains_and_converts(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.quantization import QAT, QuantedLinear

        model = self._model()
        qat = QAT()
        model = qat.quantize(model)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(32, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randn(32, 4).astype(np.float32))
        losses = []
        for _ in range(30):
            out = model(x)
            loss = F.mse_loss(out, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]  # STE gradients actually train
        model = qat.convert(model)
        assert isinstance(model.fc1, QuantedLinear)
        out = model(x)
        assert out.shape == [32, 4]

    def test_observer_scales(self):
        from paddle_tpu.quantization import AbsmaxObserver, quant_dequant
        import jax.numpy as jnp

        obs = AbsmaxObserver()
        obs.observe(jnp.asarray([-5.0, 3.0]))
        assert abs(obs.scale() - 5.0 / 127) < 1e-6
        qd = quant_dequant(jnp.asarray([1.0]), obs.scale())
        assert abs(float(qd[0]) - 1.0) < obs.scale()


class TestDistributionTransforms:
    def test_affine_roundtrip_and_lognormal(self):
        from scipy import stats as sps

        from paddle_tpu.distribution import (AffineTransform,
                                             ExpTransform, Normal,
                                             TransformedDistribution)

        t = AffineTransform(2.0, 3.0)
        x = paddle.to_tensor(np.array([1.0, -1.0], np.float32))
        y = t.forward(x)
        np.testing.assert_allclose(y.numpy(), [5.0, -1.0])
        np.testing.assert_allclose(t.inverse(y).numpy(), x.numpy(),
                                   rtol=1e-6)
        np.testing.assert_allclose(
            t.forward_log_det_jacobian(x).numpy(),
            np.log(3.0) * np.ones(2), rtol=1e-6)

        # LogNormal = exp(Normal): log_prob matches scipy
        d = TransformedDistribution(Normal(0.0, 1.0), [ExpTransform()])
        v = np.array([0.5, 1.0, 2.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(paddle.to_tensor(v)).numpy(),
            sps.lognorm(s=1.0).logpdf(v), rtol=1e-5)
        paddle.seed(5)
        s = d.sample((20000,)).numpy()
        np.testing.assert_allclose(np.log(s).mean(), 0.0, atol=0.03)

    def test_sigmoid_and_chain(self):
        from paddle_tpu.distribution import (AffineTransform,
                                             ChainTransform,
                                             SigmoidTransform)

        chain = ChainTransform([AffineTransform(0.0, 2.0),
                                SigmoidTransform()])
        x = paddle.to_tensor(np.array([0.3], np.float32))
        y = chain.forward(x)
        np.testing.assert_allclose(
            y.numpy(), 1 / (1 + np.exp(-0.6)), rtol=1e-6)
        np.testing.assert_allclose(chain.inverse(y).numpy(), [0.3],
                                   rtol=1e-5)
        # chain fldj = sum of parts at the propagated points
        fl = chain.forward_log_det_jacobian(x).numpy()
        s = 1 / (1 + np.exp(-0.6))
        np.testing.assert_allclose(
            fl, np.log(2.0) + np.log(s * (1 - s)), rtol=1e-5)
