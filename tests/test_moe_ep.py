"""MoE expert parallelism via all-to-all (MoELayer ep_mesh path).

Reference: incubate/distributed/models/moe — global_scatter /
global_gather are all-to-all ops; here the exchange is two
lax.all_to_all inside a shard_map over the ep axis. Pinned: HLO
contains all-to-all, numerics match the dense (single-device GShard
einsum) path when capacity doesn't bind, and gradients flow to experts
and gate.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.incubate.moe import MoELayer

D = 16
E = 4

# the dp4 x mp2 hybrid mesh comes from the shared session-scoped
# ``fleet_mesh`` conftest fixture (one fleet.init per session)


def _shard_experts(moe, mesh, axis="dp"):
    st = moe.stacked
    for pname in ("w1", "b1", "w2", "b2"):
        p = getattr(st, pname)
        pls = [dist.Replicate()] * mesh.ndim
        pls[mesh.dim_names.index(axis)] = dist.Shard(0)
        st._parameters[pname] = dist.shard_tensor(p, mesh, pls)


class TestGShardDispatch:
    def test_identity_property_no_slot_collisions(self):
        """With ample capacity, dispatch->combine must reconstruct each
        token exactly (r5 regression: per-k cumsum restarted at slot 0,
        so k=0/k=1 assignments to one expert summed two tokens)."""
        import jax.numpy as jnp
        from paddle_tpu.incubate.moe.moe_layer import _gshard_dispatch

        rng = np.random.RandomState(0)
        T, Ex, K, Dx = 32, 4, 2, 16
        x = jnp.asarray(rng.randn(T, Dx).astype(np.float32))
        wg = jnp.asarray(rng.randn(Dx, Ex).astype(np.float32) * 0.3)
        probs = jax.nn.softmax(x @ wg, -1)
        combine, dispatch, _, dropped, counts = _gshard_dispatch(
            probs, Ex, K, T * K)
        assert int(counts.sum()) == T * K  # every assignment routed
        assert float(dropped) == 0.0  # ample capacity: nothing dropped
        out = jnp.einsum("tec,ecd->td", combine,
                         jnp.einsum("tec,td->ecd", dispatch, x))
        np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                                   atol=1e-5)
        assert float(dispatch.sum(0).max()) == 1.0  # one token per slot


class TestMoEExpertParallel:
    def test_matches_dense_path_when_capacity_ample(self, fleet_mesh):
        mesh = fleet_mesh
        paddle.seed(0)
        # generous capacity so neither the global nor per-shard
        # formulation drops tokens -> identical outputs
        ep = MoELayer(d_model=D, num_experts=E, gate="gshard",
                      d_hidden=32, capacity_factor=8.0,
                      ep_mesh=(mesh, "dp"))
        paddle.seed(0)
        dense = MoELayer(d_model=D, num_experts=E, gate="gshard",
                         d_hidden=32, capacity_factor=8.0)
        # same init by construction (same seed); verify then shard
        np.testing.assert_allclose(np.asarray(ep.stacked.w1._data),
                                   np.asarray(dense.stacked.w1._data))
        _shard_experts(ep, mesh)
        rng = np.random.RandomState(0)
        x = rng.randn(8, 4, D).astype(np.float32)
        pls = [dist.Replicate()] * mesh.ndim
        pls[mesh.dim_names.index("dp")] = dist.Shard(0)
        xe = dist.shard_tensor(paddle.to_tensor(x), mesh, pls)
        out_ep = ep(xe).numpy()
        out_dense = dense(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out_ep, out_dense, rtol=2e-4,
                                   atol=2e-5)

    def test_all_to_all_in_hlo_and_grads_flow(self, fleet_mesh):
        mesh = fleet_mesh
        paddle.seed(1)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.moe = MoELayer(d_model=D, num_experts=E,
                                    gate="gshard", d_hidden=32,
                                    ep_mesh=(mesh, "dp"))
                _shard_experts(self.moe, mesh)

            def forward(self, x):
                return x + self.moe(x)

        net = Net()
        opt = paddle.optimizer.AdamW(1e-2, parameters=net.parameters())
        step = paddle.jit.TrainStep(
            net, lambda o, y: ((o - y) ** 2).mean(), opt)
        rng = np.random.RandomState(0)
        pls = [dist.Replicate()] * mesh.ndim
        pls[mesh.dim_names.index("dp")] = dist.Shard(0)
        x = dist.shard_tensor(paddle.to_tensor(
            rng.randn(8, 4, D).astype(np.float32)), mesh, pls)
        y = dist.shard_tensor(paddle.to_tensor(
            rng.randn(8, 4, D).astype(np.float32)), mesh, pls)
        txt = step.lower_hlo([x], [y])
        assert "all-to-all" in txt
        w1_before = np.asarray(net.moe.stacked.w1._data).copy()
        gate_before = np.asarray(net.moe.gate.weight._data).copy()
        l0 = float(step([x], [y]).numpy())
        for _ in range(10):
            loss = step([x], [y])
        assert float(loss.numpy()) < l0
        assert not np.allclose(np.asarray(net.moe.stacked.w1._data),
                               w1_before)
        assert not np.allclose(np.asarray(net.moe.gate.weight._data),
                               gate_before)

    def test_rejects_indivisible_experts(self, fleet_mesh):
        mesh = fleet_mesh
        moe = MoELayer(d_model=D, num_experts=6, gate="gshard",
                       d_hidden=32, ep_mesh=(mesh, "dp"))
        x = paddle.to_tensor(np.ones((8, 4, D), np.float32))
        with pytest.raises(ValueError, match="divisible"):
            moe(x)


class TestDroppedTokensObservability:
    """moe.dropped_tokens: capacity-overflow drops become a stats
    counter on the eager forward (ISSUE r6 satellite — slice of
    VERDICT weak #6's silent-drop problem)."""

    def test_stacked_path_counts_drops(self):
        from paddle_tpu.profiler import stats

        paddle.seed(0)
        # capacity_factor 0.05 -> capacity 1 slot/expert: with T*K=64
        # assignments into 4 experts, >= 60 must drop
        moe = MoELayer(d_model=D, num_experts=E, gate="gshard",
                       d_hidden=32, capacity_factor=0.05)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 4, D).astype(np.float32))
        before = stats.counter("moe.dropped_tokens").value
        moe(x)
        got = stats.counter("moe.dropped_tokens").value - before
        assert got >= 32 * 2 - E * 1  # T*K minus total capacity slots

    def test_ample_capacity_counts_zero(self):
        from paddle_tpu.profiler import stats

        paddle.seed(1)
        moe = MoELayer(d_model=D, num_experts=E, gate="gshard",
                       d_hidden=32, capacity_factor=8.0)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(8, 4, D).astype(np.float32))
        before = stats.counter("moe.dropped_tokens").value
        moe(x)
        assert stats.counter("moe.dropped_tokens").value == before

    def test_counter_uses_convention_prefix(self):
        from paddle_tpu.profiler import stats

        assert any(p == "moe." for p in stats.CONVENTION_PREFIXES)
