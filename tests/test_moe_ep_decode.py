"""Expert-parallel MoE decode on the serving mesh (ISSUE 15).

The acceptance pins: a 2-device ``ep`` shard_map decode produces
greedy tokens IDENTICAL to the single-device no-drop MoE decode, its
traced program carries EXACTLY the declared EP collective set (the
all_to_all dispatch/combine pair plus one replicated-hidden all_gather
per MoE layer — the layer body is traced once), and the whole thing
composes with chunked prefill through the ServingEngine step loop.
Plus the TPContext ``ep`` mesh-axis geometry (expert-bank shard specs,
replicated KV pool, shard-at-load).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.analysis import trace_census
from paddle_tpu.distributed.tp import TPContext
from paddle_tpu.incubate.nn.fused_transformer import (
    FusedMultiTransformer, PagedKV, rope_table)
from paddle_tpu.inference import FusedCausalLM, GenerationEngine
from paddle_tpu.inference.kv_cache import BlockKVCacheManager
from paddle_tpu.serving import ServingEngine, SLOConfig

V, D, H, DFF, L, E = 96, 32, 4, 64, 2, 4


def _mk_model(seed=11):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=V, embed_dim=D, num_heads=H,
                         dim_feedforward=DFF, num_layers=L,
                         max_position=128, moe_num_experts=E,
                         moe_top_k=2)


class TestTPContextEP:
    def test_ep_axis_geometry(self):
        tp = TPContext.create(H, H, D // H, ep_degree=2)
        assert tp.ep == 2 and tp.mp == 1
        assert tp.ep_axis in tp.mesh.axis_names
        # expert bank shards dim 1 over ep; gate/attention replicated
        assert tuple(tp.stack_spec("moe_w1")) == (None, "ep", None, None)
        assert tuple(tp.stack_spec("moe_b2")) == (None, "ep", None)
        assert tuple(tp.stack_spec("gate_weight")) == ()
        assert tuple(tp.stack_spec("qkv_weight")) == ()
        # ep-only pool is replicated (EP shards experts, not kv heads)
        assert tuple(tp.kv_spec()) == ()

    def test_ep_times_mp_mesh(self):
        tp = TPContext.create(4, 4, 8, mp_degree=2, ep_degree=2)
        assert tp.ep == 2 and tp.mp == 2
        assert set(tp.mesh.axis_names) == {"ep", "mp"}
        assert tuple(tp.stack_spec("qkv_weight")) == (None, None, "mp")
        assert tuple(tp.stack_spec("moe_w1")) == (None, "ep", None, None)

    def test_shard_stack_places_expert_slices(self):
        m = _mk_model()
        tp = TPContext.create(H, H, D // H, ep_degree=2)
        w = tp.shard_stack(m.stack._stack())
        assert set(w) >= {"gate_weight", "moe_w1", "moe_b1", "moe_w2",
                          "moe_b2"}
        spec = w["moe_w1"].sharding.spec
        assert tuple(spec)[:2] == (None, "ep")

    def test_ep_on_dense_stack_rejected(self):
        paddle.seed(0)
        dense = FusedCausalLM(vocab_size=V, embed_dim=D, num_heads=H,
                              dim_feedforward=DFF, num_layers=L,
                              max_position=128)
        with pytest.raises(ValueError, match="expert"):
            GenerationEngine(dense, page_size=4, max_length=64,
                             ep_degree=2)

    def test_moe_under_mp_rejected(self):
        """MoE + mp tensor parallelism is explicitly unwired (the
        fused attention-stack sharding around an expert FFN): loud
        NotImplementedError, not silent wrong math."""
        m = _mk_model()
        st = m.stack
        tp = TPContext.create(H, H, D // H, mp_degree=2)
        w_tp = tp.shard_stack(st._stack())
        mgr = BlockKVCacheManager(L, H, D // H, 4, num_pages=16,
                                  reserve_scratch=True,
                                  mp_degree=tp.mp, mesh=tp.mesh)
        mgr.allocate(0, 8)
        tbl = mgr.block_tables(range(1), 4)
        cache = mgr.fresh_cache()
        cos, sin = rope_table(64, st.head_dim)
        with pytest.raises(NotImplementedError, match="ep"):
            st.decode_raw(w_tp, jnp.ones((1, D), jnp.float32),
                          cache, tbl, jnp.array([6], jnp.int32),
                          cos, sin, tp=tp)


class TestEPDecode:
    def test_greedy_token_parity_vs_single_device(self):
        rng = np.random.RandomState(5)
        ids = rng.randint(0, V, (2, 10))
        eng1 = GenerationEngine(_mk_model(), page_size=4, max_length=64)
        out1 = eng1.generate(ids, max_new_tokens=12)
        eng2 = GenerationEngine(_mk_model(), page_size=4, max_length=64,
                                ep_degree=2)
        out2 = eng2.generate(ids, max_new_tokens=12)
        assert np.array_equal(out1, out2)

    def test_collective_census_is_declared_pair_plus_gather(self):
        """Exactly (all_to_all, all_to_all, all_gather) in the traced
        ep2 decode program — the MoE layer body traces once inside the
        layer fori_loop, so this IS the per-layer schedule; anything
        extra means GSPMD repaired a dropped sharding."""
        m = _mk_model()
        st = m.stack
        tp = TPContext.create(H, H, D // H, ep_degree=2)
        w_tp = tp.shard_stack(st._stack())
        mgr = BlockKVCacheManager(L, st.num_kv_heads, st.head_dim, 4,
                                  num_pages=16, reserve_scratch=True,
                                  mp_degree=tp.mp, mesh=tp.mesh)
        for i in range(2):
            mgr.allocate(i, 8)
        tbl = mgr.block_tables(range(2), 4)
        cache = mgr.fresh_cache()
        cos, sin = rope_table(128, st.head_dim)
        lens = jnp.array([6, 6], jnp.int32)

        def decode_fn(w, xb, ck, cv):
            h, c2 = st.decode_raw(w, xb, PagedKV(ck, cv), tbl, lens,
                                  cos, sin, tp=tp)
            return h, c2.k, c2.v

        seq = trace_census(decode_fn, w_tp,
                           jnp.ones((2, D), jnp.float32), cache.k,
                           cache.v)
        assert [p for p, _ in seq] == \
            ["all_to_all", "all_to_all", "all_gather"], seq
        assert all(tp.ep_axis in ax for _, ax in seq)

    def test_serving_engine_chunked_prefill_parity(self):
        """ep2 through the FULL serving frontend — chunked prefill
        interleaved with decode chunks — reproduces the single-device
        tokens (the compose-with-the-step-loop acceptance)."""
        s1 = ServingEngine(_mk_model(), max_batch=2, page_size=4,
                           max_length=64, decode_chunk=4,
                           slo=SLOConfig(prefill_chunk=4))
        s2 = ServingEngine(_mk_model(), max_batch=2, page_size=4,
                           max_length=64, decode_chunk=4,
                           slo=SLOConfig(prefill_chunk=4), ep_degree=2)
        rng = np.random.RandomState(5)
        sysp = list(rng.randint(0, V, (8,)))
        for s in (s1, s2):
            s.submit(sysp + [1, 2, 3], max_new_tokens=8)
            s.submit(sysp + [4, 5], max_new_tokens=8)
            s.run()
        g1 = sorted(tuple(r.generated) for r in s1.finished)
        g2 = sorted(tuple(r.generated) for r in s2.finished)
        assert g1 == g2

    def test_decode_rung_carries_ep_coordinate(self):
        eng = GenerationEngine(_mk_model(), page_size=4, max_length=64,
                               ep_degree=2)
        assert eng._decode_rung(8) == "decode.moe[k=8,ep=2]"
        assert eng._mp_suffix() == "[ep=2]"

    def test_single_device_moe_decode_matches_eager_forward(self):
        """The no-drop MoE decode stack is self-consistent: one decode
        step's hidden state matches the dense eager forward's last
        position (the same cross-check the dense engines rely on)."""
        m = _mk_model()
        st = m.stack
        rng = np.random.RandomState(0)
        ids = rng.randint(0, V, (1, 6))
        logits = m(paddle.to_tensor(ids)).numpy()      # dense forward
        eng = GenerationEngine(m, page_size=4, max_length=32)
        out = eng.generate(ids, max_new_tokens=1)
        assert int(out[0, 6]) == int(np.argmax(logits[0, 5]))
