"""No-drop MoE semantics (ISSUE 15): ``capacity_factor=None`` routes
the stacked MoELayer through the ragged grouped-GEMM path with ZERO
dropped tokens (asserted under adversarial skew), exact fwd+bwd parity
against the GShard einsum path at capacity→∞, and a trace pin that no
``[T, E, capacity]`` intermediate exists in the compiled program.
Plus the ``moe.*`` telemetry satellite (tokens_per_expert histogram,
imbalance gauge).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core import engine as ce
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.incubate.moe import MoELayer
from paddle_tpu.profiler import stats

D, E, DFF = 16, 4, 32


def _mk_pair(seed=0, top_k=2):
    """(no-drop layer, ample-capacity einsum layer) with identical init
    — capacity_factor=E makes capacity exactly T*K, the capacity→∞
    behavior without the astronomically sized buffer."""
    paddle.seed(seed)
    nodrop = MoELayer(d_model=D, num_experts=E, gate="gshard",
                      top_k=top_k, d_hidden=DFF, capacity_factor=None)
    paddle.seed(seed)
    einsum = MoELayer(d_model=D, num_experts=E, gate="gshard",
                      top_k=top_k, d_hidden=DFF, capacity_factor=float(E))
    return nodrop, einsum


class TestNoDropSemantics:
    def test_zero_drops_under_adversarial_skew(self):
        """ALL tokens routed to one expert — the shape that shreds any
        capacity factor — must drop nothing and still reconstruct the
        single-expert FFN exactly."""
        paddle.seed(0)
        moe = MoELayer(d_model=D, num_experts=E, gate="gshard",
                       d_hidden=DFF, capacity_factor=None)
        # gate weight forced: expert 2 wins every token by a mile
        wg = np.full((D, E), -10.0, np.float32)
        wg[:, 2] = 10.0
        wg[:, 0] = 9.0   # deterministic runner-up for top-2
        moe.gate.weight._rebind(jnp.asarray(wg))
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 4, D).astype(np.float32))
        before = stats.counter("moe.dropped_tokens").value
        out = moe(x)
        assert stats.counter("moe.dropped_tokens").value == before
        assert np.isfinite(out.numpy()).all()
        # the capacity path at the same skew DOES drop — the contrast
        # that makes the no-drop pin meaningful
        paddle.seed(0)
        cap = MoELayer(d_model=D, num_experts=E, gate="gshard",
                       d_hidden=DFF, capacity_factor=1.0)
        cap.gate.weight._rebind(jnp.asarray(wg))
        cap(x)
        assert stats.counter("moe.dropped_tokens").value > before

    def test_fwd_parity_vs_einsum_at_infinite_capacity(self):
        nodrop, einsum = _mk_pair()
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(8, 4, D).astype(np.float32))
        np.testing.assert_allclose(nodrop(x).numpy(), einsum(x).numpy(),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(nodrop.aux_loss._data),
                                   np.asarray(einsum.aux_loss._data),
                                   rtol=1e-6)

    def test_grad_parity_vs_einsum_through_train_step(self):
        """One optimizer step on each formulation from identical init:
        identical post-step weights == identical gradients (gate AND
        experts — the combine-weight grads flow through the ragged
        scatter exactly as through the one-hot einsum)."""
        rng = np.random.RandomState(2)
        x = paddle.to_tensor(rng.randn(4, 4, D).astype(np.float32))
        y = paddle.to_tensor(rng.randn(4, 4, D).astype(np.float32))
        outs = []
        for cf in (None, float(E)):
            paddle.seed(7)
            net = MoELayer(d_model=D, num_experts=E, gate="gshard",
                           d_hidden=DFF, capacity_factor=cf)
            opt = paddle.optimizer.AdamW(
                1e-2, parameters=net.parameters())
            step = paddle.jit.TrainStep(
                net, lambda o, t: ((o - t) ** 2).mean(), opt)
            step([x], [y])
            outs.append({n: np.asarray(p._data)
                         for n, p in net.named_parameters()})
        a, b = outs
        assert set(a) == set(b)
        for n in a:
            np.testing.assert_allclose(
                a[n], b[n], rtol=2e-4, atol=1e-6,
                err_msg=f"post-step parity broke on {n}")

    def test_trace_has_no_tec_intermediate(self):
        """The acceptance pin: the traced no-drop program carries NO
        3-D ``[T, E, *]`` dispatch/combine tensor; the capacity path's
        trace DOES (sanity that the detector detects)."""
        from paddle_tpu.analysis.jaxpr_util import sub_jaxprs

        T = 32  # != E so the shape test can't alias the expert bank
        x = jnp.asarray(
            np.random.RandomState(0).randn(8, 4, D).astype(np.float32))

        def shapes_of(moe):
            def fn(xa):
                with ce.no_grad():
                    return moe(Tensor(xa))._data

            closed = jax.make_jaxpr(fn)(x)
            seen = set()

            def walk(jx):
                for eqn in jx.eqns:
                    for v in list(eqn.invars) + list(eqn.outvars):
                        aval = getattr(v, "aval", None)
                        if aval is not None and hasattr(aval, "shape"):
                            seen.add(tuple(aval.shape))
                    for sj in sub_jaxprs(eqn):
                        walk(sj)

            walk(closed.jaxpr)
            return seen

        paddle.seed(0)
        nodrop = MoELayer(d_model=D, num_experts=E, gate="gshard",
                          d_hidden=DFF, capacity_factor=None)
        bad = [s for s in shapes_of(nodrop)
               if len(s) == 3 and s[0] == T and s[1] == E]
        assert not bad, f"[T, E, C]-shaped intermediates leaked: {bad}"

        paddle.seed(0)
        cap = MoELayer(d_model=D, num_experts=E, gate="gshard",
                       d_hidden=DFF, capacity_factor=1.25)
        assert any(len(s) == 3 and s[0] == T and s[1] == E
                   for s in shapes_of(cap))

    def test_generic_expert_list_rejected(self):
        experts = [nn.Linear(D, D) for _ in range(E)]
        moe = MoELayer(d_model=D, experts=experts, gate="gshard",
                       capacity_factor=None)
        with pytest.raises(ValueError, match="stacked"):
            moe(paddle.to_tensor(np.ones((4, 2, D), np.float32)))

    def test_ep_mesh_nodrop_drops_nothing(self, fleet_mesh):
        """capacity_factor=None + ep_mesh: worst-case per-shard
        capacity — the all-to-all exchange cannot drop either."""
        paddle.seed(0)
        import paddle_tpu.distributed as dist

        moe = MoELayer(d_model=D, num_experts=E, gate="gshard",
                       d_hidden=DFF, capacity_factor=None,
                       ep_mesh=(fleet_mesh, "dp"))
        st = moe.stacked
        for pname in ("w1", "b1", "w2", "b2"):
            p = getattr(st, pname)
            pls = [dist.Replicate()] * fleet_mesh.ndim
            pls[fleet_mesh.dim_names.index("dp")] = dist.Shard(0)
            st._parameters[pname] = dist.shard_tensor(p, fleet_mesh, pls)
        wg = np.full((D, E), -10.0, np.float32)
        wg[:, 1] = 10.0
        wg[:, 3] = 9.0
        moe.gate.weight._rebind(jnp.asarray(wg))
        pls = [dist.Replicate()] * fleet_mesh.ndim
        pls[fleet_mesh.dim_names.index("dp")] = dist.Shard(0)
        x = dist.shard_tensor(paddle.to_tensor(
            np.random.RandomState(0).randn(8, 4, D).astype(np.float32)),
            fleet_mesh, pls)
        before = stats.counter("moe.dropped_tokens").value
        out = moe(x)
        assert stats.counter("moe.dropped_tokens").value == before
        assert np.isfinite(out.numpy()).all()


class TestMoETelemetry:
    def test_tokens_per_expert_and_imbalance_stamped(self):
        paddle.seed(0)
        moe = MoELayer(d_model=D, num_experts=E, gate="gshard",
                       d_hidden=DFF, capacity_factor=None)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 4, D).astype(np.float32))
        h = stats.histogram("moe.tokens_per_expert")
        before = h.summary()["count"]
        moe(x)
        s = h.summary()
        assert s["count"] == before + E          # one observation/expert
        assert s["total"] >= 32 * 2              # T*K assignments routed
        imb = stats.gauge("moe.imbalance").value
        assert imb >= 1.0                        # max/mean >= 1

    def test_capacity_path_stamps_too(self):
        paddle.seed(1)
        moe = MoELayer(d_model=D, num_experts=E, gate="gshard",
                       d_hidden=DFF, capacity_factor=2.0)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(8, 4, D).astype(np.float32))
        h = stats.histogram("moe.tokens_per_expert")
        before = h.summary()["count"]
        moe(x)
        assert h.summary()["count"] == before + E

    def test_metric_names_use_convention_prefix(self):
        assert any(p == "moe." for p in stats.CONVENTION_PREFIXES)
