"""Native C++ TCPStore + multiprocess DataLoader tests.

Mirrors the reference's store tests (reference:
paddle/phi/core/distributed/store/test_tcp_store.cc) and the
multiprocess dataloader tests (test/legacy_test dataloader suites).
"""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle


class TestNativeTCPStore:
    def test_set_get_add_wait_delete(self):
        from paddle_tpu.distributed import TCPStore

        master = TCPStore(is_master=True)
        client = TCPStore(port=master.port)
        master.set("k1", b"v1")
        assert client.get("k1") == b"v1"
        assert client.add("cnt", 3) == 3
        assert master.add("cnt", -1) == 2
        client.wait(["k1", "cnt"], timeout=1)
        assert client.check("k1")
        client.delete_key("k1")
        assert not client.check("k1")
        with pytest.raises(TimeoutError):
            client.get("missing", timeout=0.2)

    def test_blocking_get_rendezvous(self):
        """get() blocks until another participant sets the key — the
        ncclUniqueId-exchange pattern (tcp_store.h:121)."""
        from paddle_tpu.distributed import TCPStore

        master = TCPStore(is_master=True)
        client = TCPStore(port=master.port)

        def late_set():
            time.sleep(0.3)
            master.set("uid", b"rendezvous-payload")

        t = threading.Thread(target=late_set)
        t.start()
        t0 = time.time()
        assert client.get("uid", timeout=5) == b"rendezvous-payload"
        assert time.time() - t0 >= 0.25
        t.join()

    def test_cross_process(self, tmp_path):
        """Two real processes rendezvous through the store (the
        reference's multi-proc store test)."""
        from paddle_tpu.distributed import TCPStore

        master = TCPStore(is_master=True)
        script = tmp_path / "peer.py"
        script.write_text(
            "import sys\n"
            "from paddle_tpu.core.native import TCPStore\n"
            f"s = TCPStore(port={master.port})\n"
            "s.set('from_child', b'hi')\n"
            "print(s.get('from_parent', timeout=30).decode())\n")
        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen([sys.executable, str(script)], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        assert master.get("from_child", timeout=30) == b"hi"
        master.set("from_parent", b"hello-child")
        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, err[-2000:]
        assert "hello-child" in out

    def test_concurrent_adds_atomic(self):
        from paddle_tpu.distributed import TCPStore

        master = TCPStore(is_master=True)
        clients = [TCPStore(port=master.port) for _ in range(4)]

        def bump(c):
            for _ in range(50):
                c.add("atomic", 1)

        threads = [threading.Thread(target=bump, args=(c,))
                   for c in clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert master.add("atomic", 0) == 200


class TestMultiprocessDataLoader:
    def _dataset(self, n=37):
        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __len__(self):
                return n

            def __getitem__(self, i):
                return (np.full((3,), i, np.float32),
                        np.asarray(i, np.int64))

        return DS()

    def test_num_workers_order_and_content(self):
        from paddle_tpu.io import DataLoader

        loader = DataLoader(self._dataset(), batch_size=4, shuffle=False,
                            num_workers=2)
        seen = []
        for x, y in loader:
            assert x.shape[0] == y.shape[0]
            # every sample's feature row equals its index
            np.testing.assert_allclose(
                x.numpy(), np.tile(y.numpy()[:, None], (1, 3)))
            seen.extend(int(v) for v in y.numpy())
        assert seen == list(range(37))  # ordered, incl. partial tail

    def test_matches_single_process(self):
        from paddle_tpu.io import DataLoader

        ds = self._dataset(16)
        single = [y.numpy().tolist() for _, y in
                  DataLoader(ds, batch_size=4, num_workers=0)]
        multi = [y.numpy().tolist() for _, y in
                 DataLoader(ds, batch_size=4, num_workers=3)]
        assert single == multi

    def test_worker_exception_surfaces(self):
        from paddle_tpu.io import DataLoader, Dataset

        class Bad(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("boom at 5")
                return np.zeros(2, np.float32)

        loader = DataLoader(Bad(), batch_size=2, num_workers=2)
        with pytest.raises(RuntimeError, match="boom at 5"):
            list(loader)

    def test_worker_init_fn_and_info(self):
        import multiprocessing as mp

        from paddle_tpu.io import DataLoader, Dataset

        ctx = mp.get_context("fork")
        ids = ctx.Queue()

        def init(worker_id):
            ids.put(worker_id)

        loader = DataLoader(self._dataset(8), batch_size=2,
                            num_workers=2, worker_init_fn=init)
        list(loader)
        got = {ids.get(timeout=5) for _ in range(2)}
        assert got == {0, 1}
