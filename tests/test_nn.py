"""nn layer tests: numpy-reference comparisons + grad checks.

Port of the reference's OpTest pattern for layers (SURVEY.md §4:
test/legacy_test numpy-reference comparisons).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def allclose(a, b, rtol=1e-5, atol=1e-5):
    np.testing.assert_allclose(
        a.numpy() if hasattr(a, "numpy") else a,
        b.numpy() if hasattr(b, "numpy") else b, rtol=rtol, atol=atol)


class TestLinear:
    def test_forward_matches_numpy(self):
        lin = nn.Linear(8, 3)
        x = paddle.randn([4, 8])
        ref = x.numpy() @ lin.weight.numpy() + lin.bias.numpy()
        allclose(lin(x), ref)

    def test_grad(self):
        lin = nn.Linear(5, 2)
        x = paddle.randn([3, 5])
        loss = lin(x).sum()
        loss.backward()
        # dL/dW = x^T @ ones
        expected = x.numpy().T @ np.ones((3, 2), np.float32)
        allclose(lin.weight.grad, expected)
        allclose(lin.bias.grad, np.full(2, 3.0, np.float32))


class TestActivations:
    @pytest.mark.parametrize("name,npfn", [
        ("relu", lambda x: np.maximum(x, 0)),
        ("sigmoid", lambda x: 1 / (1 + np.exp(-x))),
        ("tanh", np.tanh),
        ("relu6", lambda x: np.clip(x, 0, 6)),
        ("hardswish", lambda x: x * np.clip(x + 3, 0, 6) / 6),
        ("softsign", lambda x: x / (1 + np.abs(x))),
    ])
    def test_unary(self, name, npfn):
        x = paddle.randn([3, 7])
        allclose(getattr(F, name)(x), npfn(x.numpy()), rtol=1e-4, atol=1e-5)

    def test_softmax(self):
        x = paddle.randn([2, 5])
        out = F.softmax(x, axis=-1).numpy()
        e = np.exp(x.numpy() - x.numpy().max(-1, keepdims=True))
        allclose(out, e / e.sum(-1, keepdims=True), rtol=1e-5)

    def test_gelu_grad_finite_diff(self):
        x = paddle.randn([4, 4])
        x.stop_gradient = False
        F.gelu(x).sum().backward()
        eps = 1e-3
        xn = x.numpy()
        num = np.zeros_like(xn)
        for i in np.ndindex(*xn.shape):
            xp, xm = xn.copy(), xn.copy()
            xp[i] += eps
            xm[i] -= eps

            def f(v):
                from scipy.special import erf  # not avail? fallback
                return v
            # numeric via paddle itself
            num[i] = (F.gelu(paddle.to_tensor(xp)).sum().item()
                      - F.gelu(paddle.to_tensor(xm)).sum().item()) / (2 * eps)
        allclose(x.grad, num, rtol=1e-2, atol=1e-3)


class TestConvPool:
    def test_conv2d_matches_manual(self):
        conv = nn.Conv2D(2, 3, 3, padding=1)
        x = paddle.randn([1, 2, 5, 5])
        out = conv(x)
        assert out.shape == [1, 3, 5, 5]
        # spot check one output position against manual correlation
        w = conv.weight.numpy()
        b = conv.bias.numpy()
        xp = np.pad(x.numpy(), [(0, 0), (0, 0), (1, 1), (1, 1)])
        manual = (xp[0, :, 1:4, 1:4] * w[1]).sum() + b[1]
        allclose(out.numpy()[0, 1, 1, 1], manual, rtol=1e-4)

    def test_conv_grad_shapes(self):
        conv = nn.Conv2D(3, 4, 3, stride=2, padding=1)
        x = paddle.randn([2, 3, 8, 8])
        conv(x).sum().backward()
        assert conv.weight.grad.shape == [4, 3, 3, 3]
        assert conv.bias.grad.shape == [4]

    def test_conv2d_transpose_shape(self):
        convt = nn.Conv2DTranspose(4, 2, 3, stride=2, padding=1)
        x = paddle.randn([1, 4, 5, 5])
        assert convt(x).shape == [1, 2, 9, 9]

    def test_grouped_conv(self):
        conv = nn.Conv2D(4, 8, 3, groups=2, padding=1)
        x = paddle.randn([1, 4, 6, 6])
        assert conv(x).shape == [1, 8, 6, 6]

    def test_maxpool_avgpool(self):
        x = paddle.to_tensor(np.arange(16, np.float32).reshape(1, 1, 4, 4)
                             if False else
                             np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        mp = F.max_pool2d(x, 2)
        ap = F.avg_pool2d(x, 2)
        allclose(mp, [[[[5, 7], [13, 15]]]])
        allclose(ap, [[[[2.5, 4.5], [10.5, 12.5]]]])

    def test_adaptive_avg_pool(self):
        x = paddle.randn([2, 3, 8, 8])
        out = F.adaptive_avg_pool2d(x, 1)
        allclose(out.numpy()[..., 0, 0], x.numpy().mean((2, 3)), rtol=1e-5)


class TestNorm:
    def test_layer_norm(self):
        ln = nn.LayerNorm(6)
        x = paddle.randn([4, 6])
        out = ln(x).numpy()
        xn = x.numpy()
        ref = (xn - xn.mean(-1, keepdims=True)) / np.sqrt(
            xn.var(-1, keepdims=True) + 1e-5)
        allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_batch_norm_train_eval(self):
        bn = nn.BatchNorm2D(3, momentum=0.5)
        x = paddle.randn([4, 3, 5, 5])
        bn.train()
        out = bn(x).numpy()
        assert abs(out.mean()) < 1e-4
        assert abs(out.std() - 1.0) < 1e-2
        # running stats moved toward batch stats
        assert not np.allclose(bn._mean.numpy(), 0.0)
        bn.eval()
        out2 = bn(x)
        assert out2.shape == [4, 3, 5, 5]

    def test_group_norm(self):
        gn = nn.GroupNorm(2, 4)
        x = paddle.randn([2, 4, 3, 3])
        out = gn(x).numpy()
        r = x.numpy().reshape(2, 2, 2, 3, 3)
        ref = (r - r.mean((2, 3, 4), keepdims=True)) / np.sqrt(
            r.var((2, 3, 4), keepdims=True) + 1e-5)
        allclose(out, ref.reshape(2, 4, 3, 3), rtol=1e-4, atol=1e-5)

    def test_rms_norm(self):
        rn = nn.RMSNorm(8)
        x = paddle.randn([2, 8])
        out = rn(x).numpy()
        xn = x.numpy()
        ref = xn / np.sqrt((xn ** 2).mean(-1, keepdims=True) + 1e-6)
        allclose(out, ref, rtol=1e-4, atol=1e-5)


class TestLosses:
    def test_cross_entropy_matches_numpy(self):
        logits = paddle.randn([6, 5])
        labels = paddle.to_tensor(np.array([0, 1, 2, 3, 4, 0]))
        loss = F.cross_entropy(logits, labels)
        z = logits.numpy()
        logp = z - np.log(np.exp(z - z.max(-1, keepdims=True)).sum(
            -1, keepdims=True)) - z.max(-1, keepdims=True)
        ref = -logp[np.arange(6), labels.numpy()].mean()
        allclose(loss, ref, rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = paddle.randn([4, 3])
        labels = paddle.to_tensor(np.array([0, -100, 2, -100]))
        loss = F.cross_entropy(logits, labels, ignore_index=-100)
        z = logits.numpy()
        logp = z - np.log(np.exp(z - z.max(-1, keepdims=True)).sum(
            -1, keepdims=True)) - z.max(-1, keepdims=True)
        ref = -(logp[0, 0] + logp[2, 2]) / 2
        allclose(loss, ref, rtol=1e-5)

    def test_soft_label(self):
        logits = paddle.randn([3, 4])
        soft = F.softmax(paddle.randn([3, 4]), axis=-1)
        loss = F.cross_entropy(logits, soft, soft_label=True)
        assert loss.ndim == 0 or loss.shape == []

    def test_bce_with_logits(self):
        z = paddle.randn([8])
        y = paddle.to_tensor(np.random.randint(0, 2, 8).astype(np.float32))
        loss = F.binary_cross_entropy_with_logits(z, y)
        p = 1 / (1 + np.exp(-z.numpy()))
        ref = -(y.numpy() * np.log(p) + (1 - y.numpy()) * np.log(1 - p)).mean()
        allclose(loss, ref, rtol=1e-4)

    def test_kl_smooth_l1(self):
        a = F.log_softmax(paddle.randn([4, 5]), axis=-1)
        b = F.softmax(paddle.randn([4, 5]), axis=-1)
        assert F.kl_div(a, b).ndim == 0
        assert F.smooth_l1_loss(paddle.randn([4]), paddle.randn([4])).ndim == 0


class TestEmbeddingDropout:
    def test_embedding(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
        out = emb(ids)
        allclose(out.numpy()[0, 0], emb.weight.numpy()[1])

    def test_embedding_grad_accumulates(self):
        emb = nn.Embedding(5, 3)
        ids = paddle.to_tensor(np.array([1, 1, 2]))
        emb(ids).sum().backward()
        g = emb.weight.grad.numpy()
        allclose(g[1], np.full(3, 2.0))
        allclose(g[2], np.full(3, 1.0))
        allclose(g[0], np.zeros(3))

    def test_dropout_train_eval(self):
        x = paddle.ones([1000])
        d = nn.Dropout(0.5)
        d.train()
        out = d(x)
        kept = float((out.numpy() != 0).mean())
        assert 0.35 < kept < 0.65
        # upscale keeps expectation
        assert abs(float(out.numpy().mean()) - 1.0) < 0.15
        d.eval()
        allclose(d(x), x.numpy())


class TestTransformer:
    def test_encoder_forward_backward(self):
        layer = nn.TransformerEncoderLayer(d_model=16, nhead=4,
                                           dim_feedforward=32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        x = paddle.randn([2, 6, 16])
        out = enc(x)
        assert out.shape == [2, 6, 16]
        out.mean().backward()
        assert layer.self_attn.q_proj.weight.grad is not None

    def test_mha_cache_decode(self):
        mha = nn.MultiHeadAttention(8, 2)
        x = paddle.randn([1, 1, 8])
        cache = mha.gen_cache(x)
        y, cache = mha(x, x, x, cache=cache)
        assert cache.k.shape[1] == 1
        y2, cache = mha(x, x, x, cache=cache)
        assert cache.k.shape[1] == 2

    def test_full_transformer(self):
        model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=1,
                               num_decoder_layers=1, dim_feedforward=32,
                               dropout=0.0)
        src = paddle.randn([2, 4, 16])
        tgt = paddle.randn([2, 3, 16])
        out = model(src, tgt)
        assert out.shape == [2, 3, 16]


class TestRNN:
    def test_lstm_shapes_and_grad(self):
        lstm = nn.LSTM(4, 8, num_layers=2)
        x = paddle.randn([3, 5, 4])
        out, (h, c) = lstm(x)
        assert out.shape == [3, 5, 8]
        assert h.shape == [2, 3, 8]
        out.sum().backward()
        assert lstm.weight_ih_l0.grad is not None

    def test_gru_bidirectional(self):
        gru = nn.GRU(4, 6, direction="bidirectional")
        x = paddle.randn([2, 5, 4])
        out, h = gru(x)
        assert out.shape == [2, 5, 12]
        assert h.shape == [2, 2, 6]

    def test_lstm_cell_manual_parity(self):
        cell = nn.LSTMCell(3, 4)
        x = paddle.randn([2, 3])
        h, (h2, c2) = cell(x)
        # manual: gates i,f,g,o
        xn = x.numpy()
        w_ih, w_hh = cell.weight_ih.numpy(), cell.weight_hh.numpy()
        b = cell.bias_ih.numpy() + cell.bias_hh.numpy()
        z = xn @ w_ih.T + b
        i, f, g, o = np.split(z, 4, -1)

        def sig(v):
            return 1 / (1 + np.exp(-v))
        c_ref = sig(i) * np.tanh(g)
        h_ref = sig(o) * np.tanh(c_ref)
        allclose(h, h_ref, rtol=1e-4, atol=1e-5)

    def test_rnn_wrapper_matches_multilayer(self):
        cell = nn.SimpleRNNCell(3, 5)
        rnn = nn.RNN(cell)
        x = paddle.randn([2, 4, 3])
        out, h = rnn(x)
        assert out.shape == [2, 4, 5]


class TestContainersStateDict:
    def test_sequential_and_state_dict(self):
        model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = model.state_dict()
        assert "0.weight" in sd and "2.bias" in sd
        model2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        model2.set_state_dict(sd)
        x = paddle.randn([2, 4])
        allclose(model(x), model2(x))

    def test_layerlist_parameterlist(self):
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(ll) == 3
        assert len(list(ll.parameters())) == 6
        pl = nn.ParameterList([paddle.Parameter(paddle.randn([2]))
                               for _ in range(2)])
        assert len(list(pl.parameters())) == 2

    def test_buffers_in_state_dict(self):
        bn = nn.BatchNorm2D(3)
        sd = bn.state_dict()
        assert "_mean" in sd and "_variance" in sd

    def test_apply_and_mode(self):
        model = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        model.eval()
        assert not model[1].training
        model.train()
        assert model[1].training

    def test_named_parameters_prefix(self):
        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(2, 2)
                self.out = nn.Linear(2, 1)

        m = M()
        names = {n for n, _ in m.named_parameters()}
        assert names == {"fc.weight", "fc.bias", "out.weight", "out.bias"}


class TestInitializers:
    def test_constant_uniform_normal(self):
        import paddle_tpu.nn.initializer as I
        c = I.Constant(3.0)((2, 2), "float32")
        assert float(np.asarray(c).min()) == 3.0
        u = np.asarray(I.Uniform(-0.5, 0.5)((1000,), "float32"))
        assert -0.5 <= u.min() and u.max() <= 0.5
        n = np.asarray(I.Normal(0, 0.1)((1000,), "float32"))
        assert abs(n.std() - 0.1) < 0.02

    def test_xavier_kaiming_shapes(self):
        import paddle_tpu.nn.initializer as I
        for init in [I.XavierNormal(), I.XavierUniform(), I.KaimingNormal(),
                     I.KaimingUniform(), I.Orthogonal()]:
            out = init((16, 8), "float32")
            assert tuple(out.shape) == (16, 8)

    def test_orthogonal_is_orthogonal(self):
        import paddle_tpu.nn.initializer as I
        w = np.asarray(I.Orthogonal()((4, 4), "float32"))
        allclose(w @ w.T, np.eye(4), rtol=1e-4, atol=1e-4)


class TestClip:
    def test_global_norm_clip(self):
        p1 = paddle.Parameter(paddle.randn([4]))
        p2 = paddle.Parameter(paddle.randn([3]))
        g1 = paddle.to_tensor(np.full(4, 3.0, np.float32))
        g2 = paddle.to_tensor(np.full(3, 4.0, np.float32))
        clip = nn.ClipGradByGlobalNorm(1.0)
        out = clip([(p1, g1), (p2, g2)])
        total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in out))
        assert abs(total - 1.0) < 1e-4

    def test_value_clip(self):
        p = paddle.Parameter(paddle.randn([4]))
        g = paddle.to_tensor(np.array([-5.0, 0.2, 5.0, 1.0], np.float32))
        out = nn.ClipGradByValue(1.0)([(p, g)])
        assert out[0][1].numpy().max() <= 1.0
        assert out[0][1].numpy().min() >= -1.0


class TestAttention:
    def test_sdpa_matches_numpy(self):
        q = paddle.randn([2, 4, 2, 8])
        k = paddle.randn([2, 4, 2, 8])
        v = paddle.randn([2, 4, 2, 8])
        out = F.scaled_dot_product_attention(q, k, v).numpy()
        qn, kn, vn = q.numpy(), k.numpy(), v.numpy()
        logits = np.einsum("bqhd,bkhd->bhqk", qn, kn) / np.sqrt(8)
        w = np.exp(logits - logits.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bkhd->bqhd", w, vn)
        allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_causal(self):
        q = paddle.randn([1, 5, 1, 4])
        out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        # first position attends only to itself → equals v[0]
        allclose(out.numpy()[0, 0, 0], q.numpy()[0, 0, 0], rtol=1e-4)

    def test_flash_attention_api(self):
        q = paddle.randn([2, 8, 2, 16])
        out, _ = F.flash_attention(q, q, q, causal=True)
        assert out.shape == [2, 8, 2, 16]


class TestMHAQuantized:
    """MHA forward must route through (possibly wrapped) projection
    layers — quantization observers/QAT wrappers replace the Linears."""

    def test_self_attn_implicit_equals_explicit(self):
        paddle.seed(0)
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 6, 16).astype("float32"))
        np.testing.assert_allclose(mha(x).numpy(), mha(x, x, x).numpy(),
                                   atol=1e-5)

    def test_quantized_projections_take_wrapped_path(self):
        from paddle_tpu.quantization import PTQ

        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.mha = nn.MultiHeadAttention(16, 4)

            def forward(self, x):
                return self.mha(x)

        net = PTQ().quantize(Net())
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 4, 16).astype("float32"))
        out = net(x)
        assert list(out.shape) == [2, 4, 16]
