"""The op audit must FAIL on stale covered-by claims (VERDICT r4: a
phantom `optimizer.Adamax` row hid behind "0 missing")."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import op_audit  # noqa: E402


@pytest.fixture(scope="module")
def roots():
    return op_audit._resolution_roots()


class TestNoteVerification:
    def test_real_symbols_resolve(self, roots):
        assert op_audit.verify_note("optimizer.Adamax", roots) == []
        assert op_audit.verify_note("optimizer.Rprop", roots) == []
        assert op_audit.verify_note(
            "F.cross_entropy gather-form fast path "
            "(nn/functional/loss.py)", roots) == []
        assert op_audit.verify_note(
            "paddle.matmul / Tensor.__matmul__", roots) == []

    def test_stale_symbol_fails(self, roots):
        assert op_audit.verify_note("optimizer.DoesNotExist", roots) \
            == ["optimizer.DoesNotExist"]
        assert op_audit.verify_note(
            "F.cross_entropy (nn/functional/no_such_file.py)", roots) \
            == ["nn/functional/no_such_file.py"]

    def test_prose_notes_pass_vacuously(self, roots):
        assert op_audit.verify_note(
            "Tensor aliasing is XLA buffer donation", roots) == []

    def test_every_covered_by_claim_in_table_resolves(self, roots):
        for note in op_audit.COVERED_BY.values():
            assert op_audit.verify_note(note, roots) == [], note

    @pytest.mark.skipif(not os.path.isdir(op_audit.REF),
                        reason="reference yaml not available")
    def test_full_audit_has_zero_missing(self, roots):
        ref_ops = op_audit.collect_reference_ops()
        impl = op_audit.collect_implemented()
        rows = op_audit.classify(ref_ops, impl)
        missing = []
        for op, _src, cat, note in rows:
            if cat == "missing":
                missing.append(op)
            elif cat == "covered-by" and op_audit.verify_note(note, roots):
                missing.append(f"{op} (stale: {note})")
        assert missing == []
