"""Op correctness: numpy-reference forward + finite-difference grad checks
(reference pattern: test/legacy_test/op_test.py check_output/check_grad)."""
import numpy as np
import pytest

import paddle_tpu as paddle

from op_test import check_grad, check_output

rng = np.random.RandomState(42)


def f32(*shape):
    return rng.rand(*shape).astype(np.float32) + 0.1


class TestUnaryOps:
    @pytest.mark.parametrize("name", [
        "exp", "log", "sqrt", "rsqrt", "tanh", "sigmoid", "sin", "cos",
        "abs", "square", "reciprocal", "erf", "log1p", "expm1",
    ])
    def test_forward_and_grad(self, name):
        np_map = {
            "rsqrt": lambda a: 1 / np.sqrt(a),
            "sigmoid": lambda a: 1 / (1 + np.exp(-a)),
            "square": np.square, "reciprocal": lambda a: 1 / a,
            "erf": lambda a: np.vectorize(__import__("math").erf)(a),
        }
        np_fn = np_map.get(name, getattr(np, name, None))
        op = getattr(paddle, name)
        x = f32(3, 4) + 0.5
        check_output(lambda t: op(t), lambda a: np_fn(a), [x], atol=1e-5)
        check_grad(lambda t: op(t), [x.astype(np.float64)])


class TestBinaryOps:
    @pytest.mark.parametrize("name,np_fn", [
        ("add", np.add), ("subtract", np.subtract),
        ("multiply", np.multiply), ("divide", np.divide),
        ("maximum", np.maximum), ("minimum", np.minimum),
        ("pow", np.power),
    ])
    def test_forward_and_grad(self, name, np_fn):
        op = getattr(paddle, name)
        x, y = f32(3, 4) + 0.5, f32(3, 4) + 0.5
        check_output(op, np_fn, [x, y])
        if name not in ("maximum", "minimum"):  # kinks break numeric diff
            check_grad(op, [x.astype(np.float64), y.astype(np.float64)])

    def test_broadcast_grad(self):
        x, y = f32(3, 4), f32(4)
        check_grad(paddle.add, [x.astype(np.float64), y.astype(np.float64)])
        check_grad(paddle.multiply,
                   [x.astype(np.float64), y.astype(np.float64)])

    def test_scalar_operand(self):
        x = paddle.to_tensor(f32(2, 2), stop_gradient=False)
        y = (2.0 * x + 1.0) / 2.0 - 0.5
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones((2, 2)), rtol=1e-6)

    def test_int_divide_promotes_to_float(self):
        out = paddle.divide(paddle.to_tensor([3, 4]), paddle.to_tensor([2, 2]))
        assert out.dtype == paddle.float32
        np.testing.assert_allclose(out.numpy(), [1.5, 2.0])


class TestReductions:
    @pytest.mark.parametrize("name", ["sum", "mean", "max", "min", "prod"])
    def test_forward(self, name):
        x = f32(3, 4, 5)
        op = getattr(paddle, name)
        np_fn = getattr(np, name)
        check_output(lambda t: op(t), lambda a: np_fn(a), [x])
        check_output(lambda t: op(t, axis=1), lambda a: np_fn(a, axis=1), [x])
        check_output(lambda t: op(t, axis=[0, 2], keepdim=True),
                     lambda a: np_fn(a, axis=(0, 2), keepdims=True), [x])

    def test_sum_grad(self):
        check_grad(lambda t: paddle.sum(t, axis=1), [f32(3, 4).astype(np.float64)])

    def test_mean_grad(self):
        check_grad(lambda t: paddle.mean(t), [f32(3, 4).astype(np.float64)])

    def test_argmax(self):
        x = f32(3, 4)
        assert paddle.argmax(paddle.to_tensor(x)).item() == np.argmax(x)
        np.testing.assert_array_equal(
            paddle.argmax(paddle.to_tensor(x), axis=1).numpy(),
            np.argmax(x, axis=1))
        assert paddle.argmax(paddle.to_tensor(x)).dtype == paddle.int64

    def test_cumsum(self):
        x = f32(3, 4)
        check_output(lambda t: paddle.cumsum(t, axis=1),
                     lambda a: np.cumsum(a, axis=1), [x])
        check_grad(lambda t: paddle.cumsum(t, axis=0), [x.astype(np.float64)])

    def test_logsumexp(self):
        from scipy.special import logsumexp as np_lse

        x = f32(3, 4)
        check_output(lambda t: paddle.logsumexp(t, axis=1),
                     lambda a: np_lse(a, axis=1), [x])


class TestManipulation:
    def test_reshape_transpose_grad(self):
        x = f32(3, 4).astype(np.float64)
        check_grad(lambda t: paddle.reshape(t, [4, 3]), [x])
        check_grad(lambda t: paddle.transpose(t, [1, 0]), [x])

    def test_concat_stack_split(self):
        x, y = f32(2, 3), f32(2, 3)
        check_output(lambda a, b: paddle.concat([a, b], axis=0),
                     lambda a, b: np.concatenate([a, b], axis=0), [x, y])
        check_output(lambda a, b: paddle.stack([a, b], axis=1),
                     lambda a, b: np.stack([a, b], axis=1), [x, y])
        parts = paddle.split(paddle.to_tensor(x), [1, 2], axis=1)
        assert parts[0].shape == [2, 1] and parts[1].shape == [2, 2]

    def test_concat_grad(self):
        x, y = f32(2, 3).astype(np.float64), f32(2, 3).astype(np.float64)
        check_grad(lambda a, b: paddle.concat([a, b], axis=1), [x, y])

    def test_gather(self):
        x = f32(5, 3)
        idx = np.array([0, 2, 4])
        check_output(lambda t: paddle.gather(t, paddle.to_tensor(idx)),
                     lambda a: a[idx], [x])
        check_grad(lambda t: paddle.gather(t, paddle.to_tensor(idx)),
                   [x.astype(np.float64)])

    def test_gather_nd(self):
        x = f32(3, 4, 5)
        idx = np.array([[0, 1], [2, 3]])
        out = paddle.gather_nd(paddle.to_tensor(x), paddle.to_tensor(idx))
        np.testing.assert_allclose(out.numpy(), x[[0, 2], [1, 3]], rtol=1e-6)

    def test_scatter(self):
        x = np.zeros((4, 2), np.float32)
        idx = np.array([1, 3])
        upd = np.ones((2, 2), np.float32)
        out = paddle.scatter(paddle.to_tensor(x), paddle.to_tensor(idx),
                             paddle.to_tensor(upd))
        expect = x.copy()
        expect[idx] = upd
        np.testing.assert_allclose(out.numpy(), expect)

    def test_where(self):
        c = np.array([True, False, True])
        x, y = f32(3), f32(3)
        check_output(
            lambda a, b: paddle.where(paddle.to_tensor(c), a, b),
            lambda a, b: np.where(c, a, b), [x, y])
        check_grad(
            lambda a, b: paddle.where(paddle.to_tensor(c), a, b),
            [x.astype(np.float64), y.astype(np.float64)])

    def test_topk_sort(self):
        x = f32(3, 5)
        v, i = paddle.topk(paddle.to_tensor(x), 2, axis=1)
        np.testing.assert_allclose(v.numpy(), np.sort(x, axis=1)[:, ::-1][:, :2],
                                   rtol=1e-6)
        s = paddle.sort(paddle.to_tensor(x), axis=1, descending=True)
        np.testing.assert_allclose(s.numpy(), np.sort(x, axis=1)[:, ::-1],
                                   rtol=1e-6)

    def test_pad(self):
        x = f32(2, 3)
        # flat all-dims form: [d0_before, d0_after, d1_before, d1_after]
        out = paddle.pad(paddle.to_tensor(x), [1, 1, 2, 0], value=9.0)
        assert out.shape == [4, 5]
        assert out.numpy()[0, 0] == 9.0
        np.testing.assert_allclose(out.numpy()[1:3, 2:], x, rtol=1e-6)

    def test_tile_expand(self):
        x = f32(1, 3)
        assert paddle.tile(paddle.to_tensor(x), [2, 2]).shape == [2, 6]
        assert paddle.expand(paddle.to_tensor(x), [4, 3]).shape == [4, 3]

    def test_dynamic_ops_eager(self):
        x = np.array([1.0, -1.0, 2.0, -2.0], np.float32)
        nz = paddle.nonzero(paddle.to_tensor(x > 0))
        np.testing.assert_array_equal(nz.numpy().ravel(), [0, 2])
        m = paddle.masked_select(paddle.to_tensor(x),
                                 paddle.to_tensor(x > 0))
        np.testing.assert_allclose(m.numpy(), [1.0, 2.0])
        u, counts = paddle.unique(paddle.to_tensor([1, 1, 2]),
                                  return_counts=True)
        np.testing.assert_array_equal(u.numpy(), [1, 2])
        np.testing.assert_array_equal(counts.numpy(), [2, 1])

    def test_cast(self):
        x = paddle.to_tensor([1.7, 2.3])
        assert paddle.cast(x, "int32").dtype == paddle.int32
        assert x.astype(paddle.float16).dtype == paddle.float16
        check_grad(lambda t: paddle.cast(t, "float32"),
                   [f32(2, 2).astype(np.float64)], atol=1e-2)


class TestLinalg:
    def test_matmul_grad(self):
        x, y = f32(3, 4).astype(np.float64), f32(4, 2).astype(np.float64)
        check_output(paddle.matmul, np.matmul, [x, y], atol=1e-6)
        check_grad(paddle.matmul, [x, y])

    def test_matmul_transpose_flags(self):
        x, y = f32(4, 3), f32(4, 2)
        check_output(lambda a, b: paddle.matmul(a, b, transpose_x=True),
                     lambda a, b: a.T @ b, [x, y], atol=1e-5)

    def test_batched_matmul(self):
        x, y = f32(5, 3, 4), f32(5, 4, 2)
        check_output(paddle.bmm, np.matmul, [x, y], atol=1e-5)

    def test_einsum(self):
        x, y = f32(3, 4), f32(4, 5)
        check_output(lambda a, b: paddle.einsum("ij,jk->ik", a, b),
                     lambda a, b: a @ b, [x, y], atol=1e-5)

    def test_norm(self):
        x = f32(3, 4)
        check_output(lambda t: paddle.norm(t),
                     lambda a: np.linalg.norm(a), [x])
        check_output(lambda t: paddle.norm(t, p=1, axis=1),
                     lambda a: np.abs(a).sum(axis=1), [x])

    def test_solve_inverse_det(self):
        a = f32(3, 3) + 3 * np.eye(3, dtype=np.float32)
        b = f32(3, 2)
        check_output(paddle.solve, np.linalg.solve, [a, b], atol=1e-4)
        check_output(paddle.inverse, np.linalg.inv, [a], atol=1e-4)
        check_output(paddle.det, np.linalg.det, [a], atol=1e-4)

    def test_cholesky_svd(self):
        m = f32(3, 3)
        a = m @ m.T + 3 * np.eye(3, dtype=np.float32)
        L = paddle.cholesky(paddle.to_tensor(a))
        np.testing.assert_allclose(L.numpy() @ L.numpy().T, a, atol=1e-4)
        u, s, vt = paddle.svd(paddle.to_tensor(a))
        np.testing.assert_allclose(
            (u.numpy() * s.numpy()) @ vt.numpy(), a, atol=1e-4)


class TestLogic:
    def test_comparisons(self):
        x = paddle.to_tensor([1.0, 2.0, 3.0])
        y = paddle.to_tensor([2.0, 2.0, 2.0])
        np.testing.assert_array_equal((x < y).numpy(), [True, False, False])
        np.testing.assert_array_equal((x == y).numpy(), [False, True, False])
        np.testing.assert_array_equal((x >= y).numpy(), [False, True, True])

    def test_allclose_equal_all(self):
        x = paddle.to_tensor([1.0, 2.0])
        assert paddle.allclose(x, x).item()
        assert paddle.equal_all(x, x).item()
        assert not paddle.equal_all(x, x + 1).item()

    def test_isnan_isinf(self):
        x = paddle.to_tensor([1.0, float("nan"), float("inf")])
        np.testing.assert_array_equal(paddle.isnan(x).numpy(),
                                      [False, True, False])
        np.testing.assert_array_equal(paddle.isinf(x).numpy(),
                                      [False, False, True])


class TestCreation:
    def test_basic(self):
        assert paddle.zeros([2, 3]).shape == [2, 3]
        assert paddle.ones([2], dtype="int32").dtype == paddle.int32
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        assert paddle.arange(5).dtype == paddle.int64
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                                   np.linspace(0, 1, 5), rtol=1e-6)
        assert paddle.full([2, 2], 7).numpy().tolist() == [[7, 7], [7, 7]]
        np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3))

    def test_like_family(self):
        x = paddle.to_tensor(f32(2, 3))
        assert paddle.zeros_like(x).shape == [2, 3]
        assert paddle.ones_like(x, dtype="int64").dtype == paddle.int64

    def test_tril_triu(self):
        x = f32(4, 4)
        check_output(paddle.tril, np.tril, [x])
        check_output(paddle.triu, np.triu, [x])
        check_grad(lambda t: paddle.tril(t), [x.astype(np.float64)])

    def test_one_hot(self):
        out = paddle.one_hot(paddle.to_tensor([0, 2]), 3)
        np.testing.assert_allclose(out.numpy(), [[1, 0, 0], [0, 0, 1]])


class TestRandom:
    def test_shapes_and_determinism(self):
        paddle.seed(7)
        a = paddle.randn([3, 4])
        paddle.seed(7)
        b = paddle.randn([3, 4])
        np.testing.assert_allclose(a.numpy(), b.numpy())
        assert paddle.rand([2, 2]).shape == [2, 2]
        r = paddle.randint(0, 10, [100])
        assert r.numpy().min() >= 0 and r.numpy().max() < 10
        p = paddle.randperm(10)
        assert sorted(p.numpy().tolist()) == list(range(10))

    def test_uniform_range(self):
        u = paddle.uniform([1000], min=-2.0, max=3.0)
        assert u.numpy().min() >= -2.0 and u.numpy().max() < 3.0


class TestIndexing:
    def test_getitem_grad(self):
        x = f32(4, 5).astype(np.float64)
        check_grad(lambda t: t[1:3, ::2], [x])

    def test_tensor_index(self):
        x = paddle.to_tensor(f32(5, 3))
        idx = paddle.to_tensor([0, 4])
        np.testing.assert_allclose(x[idx].numpy(), x.numpy()[[0, 4]])

    def test_bool_mask(self):
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        m = paddle.to_tensor(np.array([True, False, True]))
        np.testing.assert_allclose(x[m].numpy(), [1.0, 3.0])

    def test_setitem(self):
        x = paddle.to_tensor(np.zeros((3, 3), np.float32))
        x[1] = 5.0
        assert x.numpy()[1].tolist() == [5.0] * 3
        x[0, 0] = 1.0
        assert x.numpy()[0, 0] == 1.0


class TestTensorBasics:
    def test_properties(self):
        t = paddle.to_tensor(f32(2, 3, 4))
        assert t.shape == [2, 3, 4]
        assert t.ndim == 3
        assert t.size == 24
        assert t.numel() == 24
        assert len(t) == 2
        assert t.T.shape == [4, 3, 2]

    def test_item_and_conversion(self):
        t = paddle.to_tensor(3.5)
        assert t.item() == 3.5
        assert float(t) == 3.5
        assert int(paddle.to_tensor(7)) == 7

    def test_set_value_and_version(self):
        t = paddle.to_tensor(np.zeros(3, np.float32))
        v0 = t.inplace_version
        t.set_value(np.ones(3, np.float32))
        assert t.inplace_version == v0 + 1
        np.testing.assert_allclose(t.numpy(), [1, 1, 1])

    def test_default_dtype(self):
        assert paddle.get_default_dtype() == paddle.float32
        paddle.set_default_dtype("bfloat16")
        try:
            assert paddle.ones([1]).dtype == paddle.bfloat16
        finally:
            paddle.set_default_dtype("float32")


class TestReviewRegressions:
    def test_cummax_cummin_indices(self):
        v, i = paddle.cummax(paddle.to_tensor([3.0, 1.0, 2.0, 5.0]))
        assert v.numpy().tolist() == [3, 3, 3, 5]
        assert i.numpy().tolist() == [0, 0, 0, 3]
        v, i = paddle.cummin(paddle.to_tensor([[3.0, 1.0], [2.0, 5.0]]), axis=0)
        assert i.numpy().tolist() == [[0, 0], [1, 0]]

    def test_split_non_divisible_raises_chunk_allows(self):
        with pytest.raises(ValueError):
            paddle.split(paddle.ones([7]), 3)
        shapes = [t.shape for t in paddle.chunk(paddle.ones([7]), 3)]
        assert shapes == [[3], [3], [1]]

    def test_unique_consecutive_axis(self):
        u, inv = paddle.unique_consecutive(
            paddle.to_tensor([[1, 1], [1, 1], [2, 3]]),
            return_inverse=True, axis=0)
        assert u.shape == [2, 2]
        assert inv.numpy().tolist() == [0, 0, 1]

    def test_eye_zero_columns(self):
        assert paddle.eye(3, 0).shape == [3, 0]

    def test_gumbel_softmax_hard_is_one_hot(self):
        g = paddle.gumbel_softmax(paddle.to_tensor([[1.0, 5.0, 2.0]]),
                                  hard=True)
        assert abs(g.numpy().sum() - 1.0) < 1e-5

    def test_rng_tracker_stable_across_reset(self):
        from paddle_tpu.core.generator import get_rng_tracker

        tr = get_rng_tracker()
        if "test_stream" not in tr.states_:
            tr.add("test_stream", 0)
        paddle.seed(123)
        s1 = tr.states_["test_stream"].initial_seed()
        paddle.seed(123)
        s2 = tr.states_["test_stream"].initial_seed()
        assert s1 == s2
