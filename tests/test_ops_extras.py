"""Long-tail ops from the ops.yaml audit (tools/op_audit.py):
extras batch + ctc_loss/margin_cross_entropy/huber_loss +
grid_sample/affine_grid.

Numeric references: numpy/scipy/torch-free closed forms.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _t(x):
    return paddle.to_tensor(np.asarray(x))


class TestExtrasOps:
    def test_add_n(self):
        xs = [_t(np.full((2, 2), float(i))) for i in range(3)]
        np.testing.assert_allclose(paddle.add_n(xs).numpy(),
                                   np.full((2, 2), 3.0))

    def test_bincount_weights(self):
        x = _t(np.array([0, 1, 1, 3]))
        w = _t(np.array([0.5, 1.0, 2.0, 4.0], np.float32))
        np.testing.assert_allclose(
            paddle.bincount(x, weights=w, minlength=6).numpy(),
            [0.5, 3.0, 0, 4.0, 0, 0])

    def test_diagonal_and_diag_embed(self):
        a = np.arange(12).reshape(3, 4).astype(np.float32)
        np.testing.assert_allclose(
            paddle.diagonal(_t(a), offset=1).numpy(),
            np.diagonal(a, offset=1))
        d = np.array([1.0, 2.0, 3.0], np.float32)
        out = paddle.diag_embed(_t(d)).numpy()
        np.testing.assert_allclose(out, np.diag(d))
        out2 = paddle.diag_embed(_t(d), offset=1).numpy()
        np.testing.assert_allclose(out2, np.diag(d, k=1))

    def test_kron_complex_nextafter(self):
        a = np.array([[1.0, 2.0]], np.float32)
        b = np.eye(2, dtype=np.float32)
        np.testing.assert_allclose(paddle.kron(_t(a), _t(b)).numpy(),
                                   np.kron(a, b))
        c = paddle.complex(_t(np.array([1.0], np.float32)),
                           _t(np.array([2.0], np.float32))).numpy()
        assert c.dtype == np.complex64 and c[0] == 1 + 2j
        na = paddle.nextafter(_t(np.array([1.0], np.float32)),
                              _t(np.array([2.0], np.float32))).numpy()
        np.testing.assert_array_equal(na, np.nextafter(
            np.float32(1.0), np.float32(2.0)))

    def test_clip_by_norm_renorm_squared_l2(self):
        x = np.array([3.0, 4.0], np.float32)
        np.testing.assert_allclose(
            paddle.clip_by_norm(_t(x), 1.0).numpy(), x / 5.0, rtol=1e-6)
        np.testing.assert_allclose(
            paddle.squared_l2_norm(_t(x)).numpy(), [25.0])
        m = np.array([[3.0, 4.0], [0.3, 0.4]], np.float32)
        out = paddle.renorm(_t(m), p=2.0, axis=0, max_norm=1.0).numpy()
        np.testing.assert_allclose(np.linalg.norm(out[0]), 1.0, rtol=1e-5)
        np.testing.assert_allclose(out[1], m[1], rtol=1e-5)  # untouched

    def test_logit_logcumsumexp(self):
        p = np.array([0.2, 0.8], np.float32)
        np.testing.assert_allclose(paddle.logit(_t(p)).numpy(),
                                   np.log(p / (1 - p)), rtol=1e-5)
        x = np.array([0.1, 0.5, 2.0], np.float32)
        ref = np.log(np.cumsum(np.exp(x)))
        np.testing.assert_allclose(
            paddle.logcumsumexp(_t(x), axis=0).numpy(), ref, rtol=1e-5)

    def test_special_functions(self):
        import scipy.special as sp

        x = np.array([0.5, 1.5], np.float32)
        np.testing.assert_allclose(paddle.i0e(_t(x)).numpy(),
                                   sp.i0e(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.i1e(_t(x)).numpy(),
                                   sp.i1e(x), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.polygamma(_t(x), 1).numpy(),
            sp.polygamma(1, x).astype(np.float32), rtol=1e-4)

    def test_nanmedian_mode(self):
        x = np.array([[1.0, np.nan, 3.0]], np.float32)
        np.testing.assert_allclose(
            paddle.nanmedian(_t(x), axis=1).numpy(), [2.0])
        v, i = paddle.mode(_t(np.array([[2.0, 1.0, 2.0, 3.0]])))
        assert float(v.numpy()[0]) == 2.0
        assert int(i.numpy()[0]) == 2  # last occurrence

    def test_shard_index(self):
        x = _t(np.array([1, 5, 9, 14]))
        out = paddle.shard_index(x, index_num=16, nshards=2,
                                 shard_id=0).numpy()
        np.testing.assert_array_equal(out, [1, 5, -1, -1])
        out1 = paddle.shard_index(x, index_num=16, nshards=2,
                                  shard_id=1).numpy()
        np.testing.assert_array_equal(out1, [-1, -1, 1, 6])

    def test_temporal_shift(self):
        x = np.arange(2 * 4 * 1 * 1, dtype=np.float32).reshape(2, 4, 1, 1)
        out = paddle.temporal_shift(_t(x), seg_num=2,
                                    shift_ratio=0.25).numpy()
        # fold=1: channel 0 shifts back (t+1), channel 1 shifts fwd (t-1)
        assert out[0, 0, 0, 0] == x[1, 0, 0, 0]  # from next frame
        assert out[1, 0, 0, 0] == 0               # nothing after last
        assert out[0, 1, 0, 0] == 0               # nothing before first
        assert out[1, 1, 0, 0] == x[0, 1, 0, 0]
        np.testing.assert_allclose(out[:, 2:], x[:, 2:])  # untouched

    def test_fill_diagonal_gather_tree(self):
        a = np.zeros((3, 3), np.float32)
        out = paddle.fill_diagonal(_t(a), 5.0).numpy()
        np.testing.assert_allclose(out, np.eye(3) * 5.0)
        ids = np.array([[[2, 2]], [[6, 1]]], np.int64)  # [T=2, B=1, beam=2]
        parents = np.array([[[0, 0]], [[1, 0]]], np.int64)
        out = paddle.gather_tree(_t(ids), _t(parents)).numpy()
        # beam 0 at t=1 came from parent 1: path = ids[0][1], ids[1][0]
        np.testing.assert_array_equal(out[:, 0, 0], [2, 6])
        np.testing.assert_array_equal(out[:, 0, 1], [2, 1])

    def test_edit_distance(self):
        hyp = np.array([[1, 2, 3, 0]], np.int64)
        ref = np.array([[1, 3, 3, 0]], np.int64)
        d, n = paddle.edit_distance(_t(hyp), _t(ref), normalized=False,
                                    input_length=_t([3]),
                                    label_length=_t([3]))
        assert float(d.numpy()[0, 0]) == 1.0
        assert int(n.numpy()[0]) == 1

    def test_truncated_normal(self):
        paddle.seed(0)
        x = paddle.truncated_normal([20000], mean=1.0, std=2.0).numpy()
        assert ((x > 1.0 - 4.0 - 1e-5) & (x < 1.0 + 4.0 + 1e-5)).all()
        assert abs(x.mean() - 1.0) < 0.05


class TestCTCLoss:
    def test_matches_bruteforce(self):
        """Sum over all alignments for a tiny case."""
        rng = np.random.RandomState(0)
        T, B, C = 4, 1, 3
        logits = rng.randn(T, B, C).astype(np.float32)
        labels = np.array([[1, 2]], np.int64)
        loss = F.ctc_loss(_t(logits), _t(labels), _t([T]), _t([2]),
                          blank=0, reduction="none").numpy()

        # brute force: enumerate all T-length paths collapsing to [1, 2]
        import itertools

        logp = logits[:, 0] - np.log(
            np.exp(logits[:, 0]).sum(-1, keepdims=True))

        def collapse(path):
            out = []
            prev = None
            for p in path:
                if p != prev and p != 0:
                    out.append(p)
                prev = p
            return out

        total = -np.inf
        for path in itertools.product(range(C), repeat=T):
            if collapse(path) == [1, 2]:
                s = sum(logp[t, p] for t, p in enumerate(path))
                total = np.logaddexp(total, s)
        np.testing.assert_allclose(loss[0], -total, rtol=1e-4)

    def test_batch_with_lengths(self):
        rng = np.random.RandomState(1)
        T, B, C = 6, 3, 5
        logits = rng.randn(T, B, C).astype(np.float32)
        labels = np.array([[1, 2, 3], [4, 1, 0], [2, 0, 0]], np.int64)
        lab_len = np.array([3, 2, 1])
        in_len = np.array([6, 5, 4])
        loss = F.ctc_loss(_t(logits), _t(labels), _t(in_len),
                          _t(lab_len), reduction="none").numpy()
        assert loss.shape == (3,)
        assert np.isfinite(loss).all() and (loss > 0).all()
        # row independence: row 0 alone gives the same loss
        solo = F.ctc_loss(_t(logits[:, :1]), _t(labels[:1]),
                          _t(in_len[:1]), _t(lab_len[:1]),
                          reduction="none").numpy()
        np.testing.assert_allclose(solo[0], loss[0], rtol=1e-5)


class TestMarginCE:
    def test_reduces_to_scaled_ce_at_zero_margin(self):
        rng = np.random.RandomState(2)
        cos = np.clip(rng.randn(4, 6).astype(np.float32) * 0.3, -1, 1)
        y = rng.randint(0, 6, (4,))
        got = F.margin_cross_entropy(_t(cos), _t(y), margin1=1.0,
                                     margin2=0.0, margin3=0.0,
                                     scale=10.0,
                                     reduction="none").numpy()
        import scipy.special as sp

        z = cos * 10.0
        ref = sp.logsumexp(z, -1) - z[np.arange(4), y]
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_margin_increases_loss(self):
        rng = np.random.RandomState(3)
        cos = np.clip(rng.randn(4, 6).astype(np.float32) * 0.3, -1, 1)
        y = rng.randint(0, 6, (4,))
        plain = float(F.margin_cross_entropy(
            _t(cos), _t(y), margin2=0.0).numpy())
        arc = float(F.margin_cross_entropy(
            _t(cos), _t(y), margin2=0.5).numpy())
        assert arc > plain


class TestGridSample:
    def test_identity_grid(self):
        rng = np.random.RandomState(4)
        x = rng.randn(1, 2, 4, 4).astype(np.float32)
        theta = np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32)
        grid = F.affine_grid(_t(theta), [1, 2, 4, 4], align_corners=True)
        out = F.grid_sample(_t(x), grid, align_corners=True).numpy()
        np.testing.assert_allclose(out, x, rtol=1e-5, atol=1e-5)

    def test_translation_nearest(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        # shift right by one pixel (align_corners grid step = 2/3)
        theta = np.array([[[1.0, 0, -2.0 / 3], [0, 1.0, 0]]], np.float32)
        grid = F.affine_grid(_t(theta), [1, 1, 4, 4], align_corners=True)
        out = F.grid_sample(_t(x), grid, mode="nearest",
                            padding_mode="zeros",
                            align_corners=True).numpy()
        np.testing.assert_allclose(out[0, 0, :, 1:], x[0, 0, :, :3])
        np.testing.assert_allclose(out[0, 0, :, 0], 0.0)  # zeros pad

    def test_huber_loss(self):
        x = np.array([0.0, 2.0], np.float32)
        y = np.array([0.5, 0.0], np.float32)
        got = F.huber_loss(_t(x), _t(y), delta=1.0,
                           reduction="none").numpy()
        np.testing.assert_allclose(got, [0.125, 1.5], rtol=1e-6)


class TestReviewFixes:
    """Round-3 inline-review findings regression tests."""

    def test_ctc_empty_label(self):
        """Zero-length label: loss = -log P(all blank), no ln(2) bias."""
        rng = np.random.RandomState(5)
        T, C = 4, 3
        logits = rng.randn(T, 1, C).astype(np.float32)
        labels = np.zeros((1, 2), np.int64)
        loss = F.ctc_loss(_t(logits), _t(labels), _t([T]), _t([0]),
                          reduction="none").numpy()
        logp = logits[:, 0] - np.log(
            np.exp(logits[:, 0]).sum(-1, keepdims=True))
        ref = -logp[:, 0].sum()  # all-blank path
        np.testing.assert_allclose(loss[0], ref, rtol=1e-5)

    def test_margin_ce_saturated_cos_finite_grad(self):
        cos = np.zeros((1, 3), np.float32)
        cos[0, 1] = 1.0  # exactly saturated target
        t = _t(cos)
        t.stop_gradient = False
        loss = F.margin_cross_entropy(t, _t(np.array([1])), margin2=0.5)
        loss.backward()
        assert np.isfinite(t.grad.numpy()).all()

    def test_fill_diagonal_nonsquare(self):
        a = np.zeros((3, 5), np.float32)
        out = paddle.fill_diagonal(_t(a), 1.0, offset=2).numpy()
        want = np.zeros((3, 5), np.float32)
        for i in range(3):
            want[i, i + 2] = 1.0
        np.testing.assert_allclose(out, want)
        with pytest.raises(NotImplementedError):
            paddle.fill_diagonal(_t(a), 1.0, wrap=True)

    def test_block_tables_strict_on_stale_id(self):
        from paddle_tpu.inference.kv_cache import BlockKVCacheManager

        mgr = BlockKVCacheManager(1, 1, 4, page_size=4, num_pages=8)
        mgr.allocate("a", 8)
        mgr.free("a")
        with pytest.raises(KeyError):
            mgr.block_tables(["a"], 2)
        # continuous-batching idle slots opt in explicitly
        t = mgr.block_tables(["a"], 2, allow_missing=True)
        assert (np.asarray(t) == 0).all()

    def test_continuous_batching_near_max_length(self):
        """Prompt near max_length with small max_new must not overflow
        the block table (clamped page growth)."""
        from paddle_tpu.inference import (ContinuousBatchingEngine,
                                          FusedCausalLM)

        paddle.seed(7)
        model = FusedCausalLM(vocab_size=32, embed_dim=16, num_heads=2,
                              dim_feedforward=32, num_layers=1,
                              max_position=128)
        eng = ContinuousBatchingEngine(model, max_batch=1, page_size=4,
                                       max_length=64, decode_chunk=8)
        rng = np.random.RandomState(0)
        prompt = rng.randint(0, 32, (58,))
        eng.submit(prompt, max_new_tokens=6)  # 58+6=64 == max_length
        done = eng.run()
        assert len(done) == 1 and len(done[0].generated) == 6


class TestRNNTLoss:
    """rnnt_loss (the warprnnt op) vs brute-force path enumeration."""

    def _brute(self, logits, labels, T, U):
        """Sum over all monotonic (t,u) alignment paths."""
        import itertools
        import scipy.special as sp

        lp = logits - sp.logsumexp(logits, -1, keepdims=True)
        # path = order of U emits among T-1 time steps... enumerate move
        # sequences: from (0,0), moves: blank (t+1) x (T-1), emit (u+1)
        # x U, then final blank at (T-1, U)
        total = -np.inf
        moves = ["b"] * (T - 1) + ["e"] * U
        for perm in set(itertools.permutations(moves)):
            t = u = 0
            s = 0.0
            for mv in perm:
                if mv == "b":
                    s += lp[t, u, 0]
                    t += 1
                else:
                    s += lp[t, u, labels[u]]
                    u += 1
            s += lp[T - 1, U, 0]  # final blank
            total = np.logaddexp(total, s)
        return -total

    def test_matches_bruteforce(self):
        rng = np.random.RandomState(0)
        B, T, U, V = 2, 3, 2, 4
        logits = rng.randn(B, T, U + 1, V).astype(np.float32)
        labels = rng.randint(1, V, (B, U))
        loss = F.rnnt_loss(_t(logits), _t(labels), _t([T, T]),
                           _t([U, U]), blank=0,
                           reduction="none").numpy()
        for b in range(B):
            ref = self._brute(logits[b], labels[b], T, U)
            np.testing.assert_allclose(loss[b], ref, rtol=1e-4,
                                       err_msg=f"row {b}")

    def test_ragged_lengths(self):
        rng = np.random.RandomState(1)
        B, T, U, V = 3, 4, 3, 5
        logits = rng.randn(B, T, U + 1, V).astype(np.float32)
        labels = rng.randint(1, V, (B, U))
        in_len = np.array([4, 3, 2])
        lab_len = np.array([3, 2, 1])
        loss = F.rnnt_loss(_t(logits), _t(labels), _t(in_len),
                           _t(lab_len), reduction="none").numpy()
        assert np.isfinite(loss).all() and (loss > 0).all()
        for b in range(B):
            ref = self._brute(
                logits[b, : in_len[b], : lab_len[b] + 1],
                labels[b, : lab_len[b]], in_len[b], lab_len[b])
            np.testing.assert_allclose(loss[b], ref, rtol=1e-4,
                                       err_msg=f"row {b}")
