"""Optimizer + LR scheduler + AMP tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def train_quadratic(opt_factory, steps=120, tol=5e-2):
    paddle.seed(42)
    net = nn.Linear(4, 1)
    opt = opt_factory(net.parameters())
    target = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    rng = np.random.RandomState(0)
    for _ in range(steps):
        xb = rng.randn(32, 4).astype(np.float32)
        x = paddle.to_tensor(xb)
        y = paddle.to_tensor(xb @ target)
        loss = F.mse_loss(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float(loss.numpy()), net


class TestOptimizers:
    def test_sgd(self):
        loss, _ = train_quadratic(
            lambda p: paddle.optimizer.SGD(0.05, parameters=p), steps=300)
        assert loss < 0.05

    def test_momentum(self):
        loss, _ = train_quadratic(
            lambda p: paddle.optimizer.Momentum(0.02, 0.9, parameters=p))
        assert loss < 0.05

    def test_adam(self):
        loss, _ = train_quadratic(
            lambda p: paddle.optimizer.Adam(0.1, parameters=p))
        assert loss < 0.05

    def test_adamw(self):
        loss, _ = train_quadratic(
            lambda p: paddle.optimizer.AdamW(0.1, parameters=p))
        assert loss < 0.05

    def test_lamb(self):
        loss, _ = train_quadratic(
            lambda p: paddle.optimizer.Lamb(0.05, parameters=p), steps=300)
        assert loss < 0.2

    def test_rmsprop_adagrad_adadelta(self):
        # adadelta is scale-free and characteristically slow on tiny
        # problems; only require clear descent for it
        for fac, thresh in [
            (lambda p: paddle.optimizer.RMSProp(0.05, parameters=p), 0.5),
            (lambda p: paddle.optimizer.Adagrad(0.2, parameters=p), 0.5),
            (lambda p: paddle.optimizer.Adadelta(2.0, parameters=p), 3.0),
        ]:
            loss, _ = train_quadratic(fac, steps=300, tol=0.3)
            assert loss < thresh

    def test_sgd_exact_update(self):
        p = paddle.Parameter(paddle.to_tensor(np.ones(3, np.float32)))
        opt = paddle.optimizer.SGD(0.1, parameters=[p])
        p.grad = paddle.to_tensor(np.full(3, 2.0, np.float32))
        opt.step()
        np.testing.assert_allclose(p.numpy(), np.full(3, 0.8), rtol=1e-6)

    def test_adamw_decay_shrinks_weights(self):
        p = paddle.Parameter(paddle.to_tensor(np.full(3, 10.0, np.float32)))
        opt = paddle.optimizer.AdamW(0.01, parameters=[p], weight_decay=0.5)
        p.grad = paddle.to_tensor(np.zeros(3, np.float32))
        before = p.numpy().copy()
        opt.step()
        assert (np.abs(p.numpy()) < np.abs(before)).all()

    def test_weight_decay_l2(self):
        import paddle_tpu.regularizer as reg
        p = paddle.Parameter(paddle.to_tensor(np.full(2, 4.0, np.float32)))
        opt = paddle.optimizer.SGD(0.1, parameters=[p],
                                   weight_decay=reg.L2Decay(0.1))
        p.grad = paddle.to_tensor(np.zeros(2, np.float32))
        opt.step()
        np.testing.assert_allclose(p.numpy(), 4.0 - 0.1 * 0.4, rtol=1e-5)

    def test_grad_clip_in_optimizer(self):
        clip = nn.ClipGradByGlobalNorm(0.1)
        p = paddle.Parameter(paddle.to_tensor(np.zeros(4, np.float32)))
        opt = paddle.optimizer.SGD(1.0, parameters=[p], grad_clip=clip)
        p.grad = paddle.to_tensor(np.full(4, 100.0, np.float32))
        opt.step()
        assert np.abs(p.numpy()).max() <= 0.1

    def test_param_groups(self):
        a = paddle.Parameter(paddle.randn([2]))
        b = paddle.Parameter(paddle.randn([2]))
        opt = paddle.optimizer.SGD(0.1, parameters=[
            {"params": [a]}, {"params": [b], "learning_rate": 0.0}])
        # lr mult via optimize_attr
        b.optimize_attr["learning_rate"] = 0.0
        a.grad = paddle.to_tensor(np.ones(2, np.float32))
        b.grad = paddle.to_tensor(np.ones(2, np.float32))
        before_b = b.numpy().copy()
        opt.step()
        np.testing.assert_allclose(b.numpy(), before_b)

    def test_state_dict_roundtrip(self):
        net = nn.Linear(3, 3)
        opt = paddle.optimizer.Adam(0.01, parameters=net.parameters())
        x = paddle.randn([2, 3])
        net(x).sum().backward()
        opt.step()
        sd = opt.state_dict()
        opt2 = paddle.optimizer.Adam(0.01, parameters=net.parameters())
        opt2.set_state_dict(sd)
        k = net.weight.name + "_moment1"
        np.testing.assert_allclose(
            opt2._accumulators[id(net.weight)]["moment1"],
            opt._accumulators[id(net.weight)]["moment1"])


class TestLRSchedulers:
    def test_step_decay(self):
        s = paddle.optimizer.lr.StepDecay(1.0, step_size=2, gamma=0.1)
        vals = []
        for _ in range(5):
            vals.append(s())
            s.step()
        np.testing.assert_allclose(vals, [1.0, 1.0, 0.1, 0.1, 0.01], rtol=1e-6)

    def test_cosine(self):
        s = paddle.optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
        assert abs(s() - 1.0) < 1e-6
        for _ in range(10):
            s.step()
        assert s() < 1e-6

    def test_warmup(self):
        s = paddle.optimizer.lr.LinearWarmup(0.1, warmup_steps=10,
                                             start_lr=0.0, end_lr=0.1)
        first = s()
        for _ in range(10):
            s.step()
        assert first < 0.05 and abs(s() - 0.1) < 1e-6

    def test_noam_piecewise_poly(self):
        n = paddle.optimizer.lr.NoamDecay(d_model=512, warmup_steps=100)
        assert n() > 0
        p = paddle.optimizer.lr.PiecewiseDecay([3, 6], [0.1, 0.01, 0.001])
        p.step(4)
        assert abs(p() - 0.01) < 1e-9
        poly = paddle.optimizer.lr.PolynomialDecay(0.1, decay_steps=10)
        poly.step(10)
        assert abs(poly() - 0.0001) < 1e-6

    def test_reduce_on_plateau(self):
        s = paddle.optimizer.lr.ReduceOnPlateau(1.0, patience=1, factor=0.5)
        for loss in [1.0, 1.0, 1.0, 1.0]:
            s.step(loss)
        assert s() < 1.0

    def test_scheduler_in_optimizer(self):
        net = nn.Linear(2, 2)
        sched = paddle.optimizer.lr.ExponentialDecay(0.1, gamma=0.5)
        opt = paddle.optimizer.SGD(sched, parameters=net.parameters())
        assert abs(opt.get_lr() - 0.1) < 1e-9
        sched.step()
        assert abs(opt.get_lr() - 0.05) < 1e-9


class TestAMP:
    def test_auto_cast_o1_matmul_bf16(self):
        import jax.numpy as jnp
        x = paddle.randn([4, 4])
        with paddle.amp.auto_cast(dtype="bfloat16"):
            y = paddle.matmul(x, x)
        assert y._data.dtype == jnp.bfloat16
        # blacklist op stays f32
        with paddle.amp.auto_cast():
            z = F.softmax(x)
        assert z._data.dtype == jnp.float32

    def test_grad_scaler_scales_and_steps(self):
        net = nn.Linear(3, 1)
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
        x = paddle.randn([4, 3])
        loss = net(x).mean()
        scaled = scaler.scale(loss)
        assert abs(float(scaled.numpy()) - 128.0 * float(loss.numpy())) < 1e-3
        scaled.backward()
        scaler.step(opt)
        scaler.update()

    def test_grad_scaler_skips_on_inf(self):
        net = nn.Linear(2, 1)
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=100.0)
        before = net.weight.numpy().copy()
        net.weight.grad = paddle.to_tensor(
            np.array([[np.inf], [1.0]], np.float32))
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(net.weight.numpy(), before)
        assert scaler.get_init_loss_scaling() < 100.0

    def test_o2_decorate(self):
        import jax.numpy as jnp
        model = nn.Sequential(nn.Linear(4, 4), nn.LayerNorm(4))
        opt = paddle.optimizer.AdamW(0.01, parameters=model.parameters())
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16")
        assert model[0].weight._data.dtype == jnp.bfloat16
        assert model[1].weight._data.dtype == jnp.float32  # norm excluded
        assert opt._multi_precision
        # master weights keep full precision across a step
        x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        with paddle.amp.auto_cast(level="O2"):
            loss = model(x).mean()
        loss.backward()
        opt.step()
        st = opt._accumulators[id(model[0].weight)]
        assert st["_master"].dtype == jnp.float32


class TestIO:
    def test_tensor_dataset_loader(self):
        import paddle_tpu.io as io
        xs = np.arange(20, dtype=np.float32).reshape(10, 2)
        ys = np.arange(10, dtype=np.int64)
        ds = io.TensorDataset([xs, ys])
        dl = io.DataLoader(ds, batch_size=4, shuffle=False)
        batches = list(dl)
        assert len(batches) == 3
        xb, yb = batches[0]
        assert xb.shape == [4, 2]
        np.testing.assert_allclose(yb.numpy(), [0, 1, 2, 3])

    def test_shuffle_and_drop_last(self):
        import paddle_tpu.io as io
        ds = io.TensorDataset([np.arange(10, dtype=np.float32)])
        dl = io.DataLoader(ds, batch_size=3, shuffle=True, drop_last=True)
        assert len(list(dl)) == 3

    def test_distributed_batch_sampler_shards(self):
        import paddle_tpu.io as io
        ds = io.TensorDataset([np.arange(12, dtype=np.float32)])
        s0 = io.DistributedBatchSampler(ds, batch_size=2, num_replicas=2,
                                        rank=0)
        s1 = io.DistributedBatchSampler(ds, batch_size=2, num_replicas=2,
                                        rank=1)
        i0 = [i for b in s0 for i in b]
        i1 = [i for b in s1 for i in b]
        assert len(i0) == len(i1) == 6
        assert not (set(i0) & set(i1))

    def test_iterable_dataset(self):
        import paddle_tpu.io as io

        class Stream(io.IterableDataset):
            def __iter__(self):
                for i in range(7):
                    yield np.float32(i)

        dl = io.DataLoader(Stream(), batch_size=3)
        sizes = [b.shape[0] for b in dl]
        assert sizes == [3, 3, 1]

    def test_random_split_concat(self):
        import paddle_tpu.io as io
        ds = io.TensorDataset([np.arange(10, dtype=np.float32)])
        a, b = io.random_split(ds, [6, 4])
        assert len(a) == 6 and len(b) == 4
        cat = io.ConcatDataset([a, b])
        assert len(cat) == 10

    def test_prefetch_workers(self):
        import paddle_tpu.io as io
        ds = io.TensorDataset([np.arange(8, dtype=np.float32)])
        dl = io.DataLoader(ds, batch_size=2, num_workers=2)
        assert len(list(dl)) == 4


class TestMetric:
    def test_accuracy(self):
        import paddle_tpu.metric as metric
        m = metric.Accuracy()
        pred = paddle.to_tensor(np.array([[0.9, 0.1], [0.2, 0.8]], np.float32))
        label = paddle.to_tensor(np.array([[0], [0]]))
        correct = m.compute(pred, label)
        m.update(correct)
        assert abs(m.accumulate() - 0.5) < 1e-6

    def test_precision_recall(self):
        import paddle_tpu.metric as metric
        p = metric.Precision()
        r = metric.Recall()
        preds = np.array([1, 1, 0, 0], np.float32)
        labels = np.array([1, 0, 1, 0], np.float32)
        p.update(preds, labels)
        r.update(preds, labels)
        assert abs(p.accumulate() - 0.5) < 1e-6
        assert abs(r.accumulate() - 0.5) < 1e-6
