"""Adamax / Rprop / LBFGS / Lars — the r5 optimizer-roster closure.

Numerics are pinned against independent numpy reimplementations of the
reference rules (reference: python/paddle/optimizer/{adamax.py:27,
rprop.py:28, lbfgs.py:307}, fleet/meta_optimizers/lars_optimizer.py),
plus convergence and state-dict round-trips.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _make_param(vals):
    from paddle_tpu.core.tensor import Parameter
    import jax.numpy as jnp

    return Parameter(jnp.asarray(np.asarray(vals, np.float32)))


def _apply_grads(opt, p, g_seq):
    from paddle_tpu.core.tensor import Tensor

    traj = []
    for g in g_seq:
        p.grad = Tensor(np.asarray(g, np.float32))
        opt.step()
        opt.clear_grad()
        traj.append(np.asarray(p.numpy(), np.float64).copy())
    return traj


class TestAdamax:
    def test_matches_reference_rule(self):
        lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
        rng = np.random.RandomState(0)
        g_seq = [rng.randn(3) for _ in range(5)]
        p = _make_param([1.0, -2.0, 3.0])
        opt = paddle.optimizer.Adamax(lr, beta1=b1, beta2=b2, epsilon=eps,
                                      parameters=[p])
        traj = _apply_grads(opt, p, g_seq)
        # independent numpy model of the reference kernel
        w = np.array([1.0, -2.0, 3.0], np.float64)
        m = np.zeros(3)
        u = np.zeros(3)
        b1p = 1.0
        for t, g in enumerate(g_seq):
            g = g.astype(np.float64)
            m = b1 * m + (1 - b1) * g
            u = np.maximum(np.abs(g), b2 * u + eps)
            b1p *= b1
            w = w - (lr / (1 - b1p)) * m / u
            np.testing.assert_allclose(traj[t], w, rtol=2e-5, atol=1e-6)

    def test_converges(self):
        paddle.seed(1)
        net = nn.Linear(4, 1)
        opt = paddle.optimizer.Adamax(0.05, parameters=net.parameters())
        target = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
        rng = np.random.RandomState(0)
        for _ in range(200):
            xb = rng.randn(32, 4).astype(np.float32)
            loss = F.mse_loss(net(paddle.to_tensor(xb)),
                              paddle.to_tensor(xb @ target))
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss.numpy()) < 0.05

    def test_state_dict_roundtrip(self):
        p = _make_param([1.0, 2.0, 3.0])
        opt = paddle.optimizer.Adamax(0.1, parameters=[p])
        _apply_grads(opt, p, [np.ones(3)] * 3)
        sd = opt.state_dict()
        p2 = _make_param([1.0, 2.0, 3.0])
        import jax.numpy as jnp

        # optimizer state excludes params (model sd); copy — the donated
        # fused update would otherwise delete the shared buffer
        p2._rebind(jnp.array(p._data, copy=True))
        p2.name = p.name
        opt2 = paddle.optimizer.Adamax(0.1, parameters=[p2])
        opt2.set_state_dict(sd)
        t1 = _apply_grads(opt, p, [np.ones(3)])
        t2 = _apply_grads(opt2, p2, [np.ones(3)])
        np.testing.assert_allclose(t1[0], t2[0], rtol=1e-6)


class TestRprop:
    def test_sign_logic_matches_reference(self):
        # grad sign flip must shrink the step and SKIP the update;
        # agreement must grow the step (reference rprop.py math block)
        lr0, lr_min, lr_max = 0.1, 1e-5, 50.0
        en, ep = 0.5, 1.2
        p = _make_param([0.0])
        opt = paddle.optimizer.Rprop(
            lr0, learning_rate_range=(lr_min, lr_max), parameters=[p],
            etas=(en, ep))
        # step 1: prev=0 -> product==0 -> lr unchanged, update -lr*sign(g)
        t1 = _apply_grads(opt, p, [np.array([1.0])])[0]
        np.testing.assert_allclose(t1, [-lr0], rtol=1e-6)
        # step 2: same sign -> lr*eta+ and update
        t2 = _apply_grads(opt, p, [np.array([1.0])])[0]
        np.testing.assert_allclose(t2, [-lr0 - lr0 * ep], rtol=1e-6)
        # step 3: sign flip -> lr*eta-, NO update this step
        t3 = _apply_grads(opt, p, [np.array([-1.0])])[0]
        np.testing.assert_allclose(t3, t2, rtol=1e-6)
        # step 4: prev grad was zeroed -> product==0 -> update resumes
        # with the shrunk step
        t4 = _apply_grads(opt, p, [np.array([-1.0])])[0]
        np.testing.assert_allclose(t4, t2 + lr0 * ep * en, rtol=1e-6)

    def test_lr_clamped_to_range(self):
        p = _make_param([0.0])
        opt = paddle.optimizer.Rprop(1.0, learning_rate_range=(0.5, 1.5),
                                     parameters=[p], etas=(0.5, 1.2))
        for _ in range(10):
            _apply_grads(opt, p, [np.array([1.0])])
        lr = np.asarray(opt._accumulators[id(p)]["learning_rate"])
        assert lr[0] == pytest.approx(1.5)

    def test_full_batch_convergence(self):
        # Rprop is a full-batch method: fixed batch, quadratic objective
        paddle.seed(2)
        net = nn.Linear(4, 1)
        opt = paddle.optimizer.Rprop(0.01, parameters=net.parameters())
        rng = np.random.RandomState(0)
        xb = rng.randn(64, 4).astype(np.float32)
        target = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
        x, y = paddle.to_tensor(xb), paddle.to_tensor(xb @ target)
        for _ in range(150):
            loss = F.mse_loss(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss.numpy()) < 1e-3


class TestLBFGS:
    def test_linear_regression_exact(self):
        paddle.seed(3)
        net = nn.Linear(4, 1)
        opt = paddle.optimizer.LBFGS(parameters=net.parameters(),
                                     line_search_fn="strong_wolfe")
        rng = np.random.RandomState(0)
        xb = rng.randn(64, 4).astype(np.float32)
        target = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
        x, y = paddle.to_tensor(xb), paddle.to_tensor(xb @ target)

        def closure():
            opt.clear_grad()
            loss = F.mse_loss(net(x), y)
            loss.backward()
            return loss

        for _ in range(5):
            opt.step(closure)
        final = float(F.mse_loss(net(x), y).numpy())
        assert final < 1e-6, final
        np.testing.assert_allclose(net.weight.numpy().reshape(-1),
                                   target.reshape(-1), atol=1e-3)

    def test_rosenbrock_strong_wolfe(self):
        # the canonical curved-valley test: plain GD crawls, LBFGS nails
        # it in a handful of outer steps
        from paddle_tpu.core.tensor import Parameter
        import jax.numpy as jnp

        xy = Parameter(jnp.asarray(np.array([-1.2, 1.0], np.float32)))
        opt = paddle.optimizer.LBFGS(parameters=[xy], max_iter=40,
                                     line_search_fn="strong_wolfe")

        def closure():
            opt.clear_grad()
            a = xy[0]
            b = xy[1]
            loss = (1 - a) ** 2 + 100 * (b - a * a) ** 2
            loss.backward()
            return loss

        for _ in range(8):
            opt.step(closure)
        np.testing.assert_allclose(xy.numpy(), [1.0, 1.0], atol=1e-3)

    def test_no_line_search_path(self):
        paddle.seed(4)
        net = nn.Linear(2, 1)
        opt = paddle.optimizer.LBFGS(learning_rate=0.5, max_iter=10,
                                     parameters=net.parameters())
        rng = np.random.RandomState(0)
        xb = rng.randn(32, 2).astype(np.float32)
        target = np.array([[2.0], [-1.0]], np.float32)
        x, y = paddle.to_tensor(xb), paddle.to_tensor(xb @ target)

        def closure():
            opt.clear_grad()
            loss = F.mse_loss(net(x), y)
            loss.backward()
            return loss

        l0 = float(closure().numpy())
        for _ in range(10):
            opt.step(closure)
        assert float(closure().numpy()) < l0 * 1e-3

    def test_state_dict_roundtrip(self):
        from paddle_tpu.core.tensor import Parameter
        import jax.numpy as jnp

        def make():
            q = Parameter(jnp.asarray(np.array([0.5, -0.5], np.float32)))
            o = paddle.optimizer.LBFGS(parameters=[q], max_iter=4,
                                       line_search_fn="strong_wolfe")

            def closure():
                o.clear_grad()
                loss = ((q - paddle.to_tensor(
                    np.array([1.0, 2.0], np.float32))) ** 2).sum()
                loss.backward()
                return loss

            return q, o, closure

        q1, o1, c1 = make()
        o1.step(c1)
        sd = o1.state_dict()
        q2, o2, c2 = make()
        q2._rebind(q1._data)
        o2.set_state_dict(sd)
        o1.step(c1)
        o2.step(c2)
        np.testing.assert_allclose(q1.numpy(), q2.numpy(), rtol=1e-6)


class TestLocalSGD:
    def test_single_process_equals_inner(self):
        paddle.seed(6)
        net = nn.Linear(4, 1)
        import copy

        w0 = net.weight.numpy().copy()
        from paddle_tpu.incubate.optimizer import LocalSGD

        opt = LocalSGD(paddle.optimizer.SGD(0.1,
                                            parameters=net.parameters()),
                       k_steps=2)
        rng = np.random.RandomState(0)
        xb = rng.randn(8, 4).astype(np.float32)
        for _ in range(4):
            loss = F.mse_loss(net(paddle.to_tensor(xb)),
                              paddle.to_tensor(np.zeros((8, 1), np.float32)))
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert not np.allclose(net.weight.numpy(), w0)

    def test_sync_fires_every_k_steps(self, monkeypatch):
        from paddle_tpu.incubate.optimizer import LocalSGD

        p = _make_param([1.0, 2.0])
        opt = LocalSGD(paddle.optimizer.SGD(0.1, parameters=[p]),
                       k_steps=3)
        calls = []
        monkeypatch.setattr(opt, "_sync", lambda: calls.append(
            opt._step_count))
        for _ in range(7):
            _apply_grads(opt, p, [np.ones(2)])
        assert calls == [3, 6]


class TestLars:
    def test_trust_ratio_matches_rule(self):
        lr, mom, coeff, wd = 0.5, 0.0, 0.001, 0.0005
        p = _make_param([3.0, 4.0])          # ||p|| = 5
        g = np.array([0.6, 0.8], np.float64)  # ||g|| = 1
        opt = paddle.optimizer.Lars(lr, momentum=mom, lars_coeff=coeff,
                                    lars_weight_decay=wd, parameters=[p])
        t1 = _apply_grads(opt, p, [g])[0]
        local_lr = lr * coeff * 5.0 / (1.0 + wd * 5.0)
        expect = np.array([3.0, 4.0]) - local_lr * (g + wd * np.array([3.0, 4.0]))
        np.testing.assert_allclose(t1, expect, rtol=1e-5)

    def test_exclude_falls_back_to_momentum_sgd(self):
        p = _make_param([3.0, 4.0])
        p.name = "bn_scale"
        g = np.array([0.6, 0.8], np.float64)
        opt = paddle.optimizer.Lars(0.5, momentum=0.0, parameters=[p],
                                    exclude_from_weight_decay=["bn_"])
        t1 = _apply_grads(opt, p, [g])[0]
        np.testing.assert_allclose(t1, np.array([3.0, 4.0]) - 0.5 * g,
                                   rtol=1e-5)

    def test_converges(self):
        paddle.seed(5)
        net = nn.Linear(4, 1)
        opt = paddle.optimizer.Lars(0.5, momentum=0.9, lars_coeff=0.01,
                                    parameters=net.parameters())
        target = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
        rng = np.random.RandomState(0)
        for _ in range(400):
            xb = rng.randn(32, 4).astype(np.float32)
            loss = F.mse_loss(net(paddle.to_tensor(xb)),
                              paddle.to_tensor(xb @ target))
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss.numpy()) < 0.1
