"""Low-memory optimizer-state knobs: bf16 moments + stochastic rounding.

Reference anchor: the multi_precision fused adam kernel
(/root/reference/paddle/phi/kernels/gpu/adam_kernel.cu) keeps fp32
master weights for fp16/bf16 params; these knobs are the TPU-memory
equivalents that let GPT-3 1.3B + AdamW fit a single 16GB chip
(bf16 moments halve moment memory; stochastic rounding removes the
fp32 master entirely while keeping the update unbiased).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _train(opt_kwargs, amp_master=True, steps=20, seed=0):
    paddle.seed(seed)
    model = nn.Sequential(
        nn.Linear(16, 64), nn.GELU(), nn.Linear(64, 4))
    opt = paddle.optimizer.AdamW(
        1e-2, parameters=model.parameters(), weight_decay=0.01,
        **opt_kwargs)
    model, opt = paddle.amp.decorate(
        model, opt, level="O2", dtype="bfloat16",
        master_weight=amp_master)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(32, 16).astype("float32"))
    y = paddle.to_tensor(rng.randn(32, 4).astype("float32"))

    losses = []
    for _ in range(steps):
        out = model(x.astype("bfloat16"))
        loss = ((out.astype("float32") - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses, model, opt


class TestBf16Moments:
    def test_loss_matches_fp32_moments(self):
        ref, _, _ = _train({})
        low, _, opt = _train({"moment_dtype": "bfloat16"})
        # both must train; trajectories track closely at these scales
        assert low[-1] < low[0] * 0.5
        assert abs(low[-1] - ref[-1]) < 0.25 * abs(ref[0])

    def test_moment_storage_dtype(self):
        import jax.numpy as jnp

        _, _, opt = _train({"moment_dtype": "bfloat16"}, steps=2)
        sts = list(opt._accumulators.values())
        assert sts, "no accumulators created"
        for st in sts:
            assert st["moment1"].dtype == jnp.bfloat16
            assert st["moment2"].dtype == jnp.bfloat16
            # master stays fp32 — compute precision is preserved
            assert st["_master"].dtype == jnp.float32

    def test_rejects_unknown_dtype(self):
        with pytest.raises(ValueError):
            paddle.optimizer.Adam(
                parameters=[nn.Linear(2, 2).weight],
                moment_dtype="int8")


class TestStochasticRounding:
    def test_trains_without_master(self):
        import jax.numpy as jnp

        low, model, opt = _train(
            {"stochastic_rounding": True, "moment_dtype": "bfloat16"},
            amp_master=False)
        assert low[-1] < low[0] * 0.5, f"did not train: {low}"
        # no fp32 master anywhere in the state
        for st in opt._accumulators.values():
            assert "_master" not in st
        for p in model.parameters():
            if p._data.dtype == jnp.bfloat16:
                break
        else:
            pytest.fail("expected bf16 params under O2")

    def test_round_is_unbiased(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.optimizer.optimizer import _stochastic_round_bf16

        x = jnp.full((20000,), 1.0 + 1.0 / 512.0, jnp.float32)  # between
        # two bf16 grid points (1.0 and 1.0078125): mean of SR must land
        # near the true value, while deterministic rounding would not
        out = _stochastic_round_bf16(x, jax.random.PRNGKey(0))
        assert out.dtype == jnp.bfloat16
        mean = float(out.astype(jnp.float32).mean())
        assert abs(mean - (1.0 + 1.0 / 512.0)) < 1e-3
        # negative values round correctly too
        xn = -x
        outn = _stochastic_round_bf16(xn, jax.random.PRNGKey(1))
        assert abs(float(outn.astype(jnp.float32).mean()) + 1.0 + 1.0 / 512.0) < 1e-3
