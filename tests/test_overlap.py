"""Comm/compute overlap (ISSUE 19): hide the collectives.

Tier-1 acceptance pins:

- **ring reduction** (``overlap="ring"`` / ``FLAGS_tp_overlap``): the
  mp2 decode path produces BITWISE-identical outputs to the blocking
  ``psum`` reference, and the traced census changes from exactly
  ``[psum, psum]`` per layer body to the exact ``mp*(mp-1)``-ppermute
  ladder (``ring_census``); an axis of extent 1 traces NO collective
  under either mode;
- **EP double buffering** (``FLAGS_ep_overlap``): ep2 greedy tokens
  stay identical through the engine while the per-layer census flips
  from the serialized dispatch/combine/gather triple to 4 all_to_alls
  + 1 all_gather;
- **async migration** (``FLAGS_migrate_async``): a fleet drain streams
  KV pages while the source keeps decoding — zero admitted requests
  lost, byte-identical continuation, decode progress DURING the
  stream, exact page accounting, and the ``fleet.migrate.stream``
  profiler span demonstrably overlapping ``fleet.replica.step`` spans
  in a captured trace;
- **S-OVERLAP** (``analysis/overlap.py``): the repo's overlap sites
  are census-clean, an injected blocking psum inside a ring site is
  caught, census drift is caught, and inline waivers silence;
- **tooling**: bench_gate directions, ``serve_bench --drain-async``,
  ``bench.py --all`` and the overlap rungs are wired.
"""
import importlib.util
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.analysis import trace_census
from paddle_tpu.analysis.overlap import (OVERLAP_SITES, OverlapSite,
                                         check_overlap_program,
                                         run_overlap_pass)
from paddle_tpu.analysis.spmd import (_build_moe_ep_decode,
                                      _tp_serving_setup)
from paddle_tpu.distributed.tp import (reduce_over_axis, resolve_overlap,
                                       ring_census, serving_mesh,
                                       shard_map_fn)
from paddle_tpu.incubate.nn.fused_transformer import PagedKV
from paddle_tpu.inference import FusedCausalLM, GenerationEngine
from paddle_tpu.profiler import (start_span_capture, stats,
                                 stop_span_capture)
from paddle_tpu.serving import FleetRouter, ServingEngine, SLOConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _flags:
    """Scoped flag override (flags are process-global)."""

    def __init__(self, **kw):
        self._new = {f"FLAGS_{k}": v for k, v in kw.items()}

    def __enter__(self):
        self._old = paddle.get_flags(list(self._new))
        paddle.set_flags(self._new)
        return self

    def __exit__(self, *exc):
        paddle.set_flags(self._old)


def _smap(body, mesh, in_specs, out_specs):
    kwargs = {}
    if getattr(jax.lax, "pcast", None) is None:
        kwargs["check_rep"] = False
    return shard_map_fn()(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)


def _mp_mesh(n):
    return serving_mesh(n, devices=jax.devices("cpu")[:n])


# =====================================================================
# ring reduction: the collective seam itself
# =====================================================================

class TestRingReduce:
    def _mk(self, mode, n=2):
        mesh = _mp_mesh(n)

        def body(v):
            return reduce_over_axis(v, "mp", mode)

        return _smap(body, mesh, (P("mp", None),), P("mp", None))

    def test_ring_matches_psum_bitwise(self, virtual_devices):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 16).astype(np.float32))
        ref = np.asarray(self._mk("psum")(x))
        out = np.asarray(self._mk("ring")(x))
        # BITWISE, not allclose: the ring re-orders the collected
        # partials into global rank order before summing, so every
        # shard adds in the same order the psum does
        assert np.array_equal(ref, out)

    def test_census_psum_vs_ring(self, virtual_devices):
        x = jnp.ones((2, 16), jnp.float32)
        assert trace_census(self._mk("psum"), x) \
            == [("psum", "('mp',)")]
        assert trace_census(self._mk("ring"), x) \
            == ring_census("mp", 2)

    def test_axis_extent_one_traces_no_collective(self, virtual_devices):
        # the single-shard TP view: the reduction is the identity and
        # the census must stay EMPTY — no no-op psum in the program
        x = jnp.ones((2, 16), jnp.float32)
        for mode in ("psum", "ring"):
            assert trace_census(self._mk(mode, n=1), x) == [], mode

    def test_bad_mode_raises(self, virtual_devices):
        x = jnp.ones((2, 16), jnp.float32)
        with pytest.raises(ValueError, match="overlap"):
            self._mk("bogus")(x)

    def test_ring_census_helper_shape(self):
        seq = ring_census("mp", 4, reductions=2)
        assert len(seq) == 4 * 3 * 2
        assert set(seq) == {("ppermute", "('mp',)")}

    def test_resolve_overlap_knob_beats_flag(self):
        assert resolve_overlap("ring") == "ring"
        with _flags(tp_overlap="ring"):
            assert resolve_overlap(None) == "ring"
            assert resolve_overlap("psum") == "psum"
        assert resolve_overlap(None) == "psum"


# =====================================================================
# ring reduction through the mp2 decode path
# =====================================================================

class TestDecodeRing:
    def _decode_fns(self):
        st, tp, w_tp, cache, tables, cos, sin, lens = \
            _tp_serving_setup()
        x = jnp.ones((2, st.embed_dim), jnp.float32)

        def mk(mode):
            def fn(w, xb, ck, cv):
                h, c2 = st.decode_raw(w, xb, PagedKV(ck, cv), tables,
                                      lens, cos, sin, tp=tp,
                                      overlap=mode)
                return h, c2.k, c2.v

            return fn

        return mk, (w_tp, x, cache.k, cache.v)

    def test_bitwise_parity_and_exact_census_flip(self, virtual_devices):
        """THE tentpole pin: same bits out, and the program's census
        changes from exactly [psum, psum] (the once-traced layer
        body's O-proj + FFN2 pair) to the exact ppermute ladder."""
        mk, args = self._decode_fns()
        ref = mk("psum")(*args)
        out = mk("ring")(*args)
        for r, o in zip(ref, out):
            assert np.array_equal(np.asarray(r), np.asarray(o))
        assert trace_census(mk("psum"), *args) \
            == [("psum", "('mp',)")] * 2
        assert trace_census(mk("ring"), *args) \
            == ring_census("mp", 2, reductions=2)

    def test_engine_token_parity_under_ring_flag(self, virtual_devices):
        def model():
            paddle.seed(7)
            return FusedCausalLM(vocab_size=64, embed_dim=32,
                                 num_heads=4, dim_feedforward=64,
                                 num_layers=2, max_position=128)

        rng = np.random.RandomState(3)
        ids = rng.randint(0, 64, (2, 6))
        ref = GenerationEngine(model(), page_size=4,
                               max_length=64).generate(
                                   ids, max_new_tokens=8)
        stats.reset()
        with _flags(tp_overlap="ring"):
            out = GenerationEngine(model(), page_size=4, max_length=64,
                                   mp_degree=2).generate(
                                       ids, max_new_tokens=8)
        assert np.array_equal(ref, out)
        # the ring schedule accounted for itself
        assert stats.counter("dist.overlap_ring_reduces").value > 0
        assert stats.gauge("dist.overlap_ring_phases").value == 2.0


# =====================================================================
# EP double buffering
# =====================================================================

def _moe_model(seed=11):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=96, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=128, moe_num_experts=4,
                         moe_top_k=2)


class TestEPDoubleBuffer:
    def test_greedy_parity_through_engine(self, virtual_devices):
        rng = np.random.RandomState(5)
        ids = rng.randint(0, 96, (2, 10))
        ref = GenerationEngine(_moe_model(), page_size=4,
                               max_length=64).generate(
                                   ids, max_new_tokens=12)
        with _flags(ep_overlap=True):
            out = GenerationEngine(_moe_model(), page_size=4,
                                   max_length=64,
                                   ep_degree=2).generate(
                                       ids, max_new_tokens=12)
        assert np.array_equal(ref, out)

    def test_census_flips_to_double_buffer(self, virtual_devices):
        fn, args = _build_moe_ep_decode()
        base = trace_census(fn, *args)
        assert [p for p, _ in base] \
            == ["all_to_all", "all_to_all", "all_gather"], base
        with _flags(ep_overlap=True):
            # the flag resolves at trace time and jax caches traces
            # per closure instance, so the flipped census needs a
            # freshly built site
            fn2, args2 = _build_moe_ep_decode()
            seq = trace_census(fn2, *args2)
        # both half-buffer dispatches, combine0/combine1, then the
        # replicated-hidden gather — all_to_all carries a bare axis
        # name, all_gather the normalized tuple
        assert seq == [("all_to_all", "ep")] * 4 \
            + [("all_gather", str(("ep",)))], seq


# =====================================================================
# async migration: decode-concurrent fleet drain
# =====================================================================

def _serve_engine(seed=7):
    paddle.seed(seed)
    model = FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                          dim_feedforward=64, num_layers=2,
                          max_position=256)
    return ServingEngine(model, max_batch=2, page_size=4,
                         max_length=96, decode_chunk=2,
                         slo=SLOConfig(prefill_chunk=8))


_PROMPT = np.random.RandomState(0).randint(0, 64, (10,))


def _ref_tokens(max_new=8):
    eng = _serve_engine()
    rid = eng.submit(_PROMPT, max_new_tokens=max_new)
    done = {r.id: r for r in eng.run()}
    assert done[rid].state == "ok"
    return list(done[rid].generated)


def _mid_decode_router(n_generated=2, max_new=8):
    """A 2-replica sync-driven fleet with one request mid-decode."""
    router = FleetRouter(engine_factory=lambda i: _serve_engine(),
                         n_replicas=2)
    rid = router.submit(_PROMPT, max_new_tokens=max_new)
    steps = 0
    while True:
        router.step()
        steps += 1
        assert steps < 500
        req = router.results()[rid]
        if len(req.generated) >= n_generated and not req.done:
            break
    src = next(r.idx for r in router.replicas if r.eng.num_active)
    return router, rid, src


class TestAsyncMigration:
    def test_zero_loss_parity_progress_and_accounting(self):
        """THE async-drain pin: pages stream while the source keeps
        decoding (token progress DURING the stream), the re-homed
        request finishes byte-identically, nothing recomputes, and
        page accounting closes exactly on both pools."""
        stats.reset()
        # enough remaining tokens that the source can't finish the
        # request mid-stream (which would legitimately skip the join)
        ref = _ref_tokens(max_new=24)
        with _flags(migrate_async=True):
            router, rid, src = _mid_decode_router(max_new=24)
            src_eng = router.replicas[src].eng
            dst_eng = router.replicas[1 - src].eng
            n_before = len(router.results()[rid].generated)
            router.drain(src)
            assert router.replicas[src].state == "drained"
            n_after = len(router.results()[rid].generated)
            # decode-concurrent: the drain drove source decode steps
            # BETWEEN page batches, so the stream saw tokens land
            assert n_after > n_before
            assert stats.counter("fleet.async_migrations").value == 1
            assert stats.counter("fleet.migrations").value == 1
            assert stats.counter("serving.preemptions").value == 0
            # source pool drained to empty (scratch page reserved)...
            assert src_eng._mgr.free_pages \
                == src_eng._mgr.num_pages - 1
            assert src_eng._mgr._owned == {}
            # ...and the destination owns the slot at refcount 1
            j = next(i for i in range(dst_eng.max_batch)
                     if dst_eng._slots[i] is not None)
            for p in dst_eng._mgr._owned[("slot", j)]:
                assert dst_eng._mgr.refcount(p) == 1
            # destination journal: an async-marked migrate event and
            # NO admitted event — the request never re-prefilled
            evs = dst_eng.journal.events(rid)
            mig = [e for e in evs if e["ev"] == "migrate"]
            assert mig and mig[0].get("async") is True
            assert not any(e["ev"] == "admitted" for e in evs)
            done = {r.id: r for r in router.run()}
        assert done[rid].state == "ok"
        assert list(done[rid].generated) == ref

    def test_flag_off_stays_on_blocking_path(self):
        stats.reset()
        ref = _ref_tokens()
        router, rid, src = _mid_decode_router()
        router.drain(src)
        assert router.replicas[src].state == "drained"
        assert stats.counter("fleet.async_migrations").value == 0
        assert stats.counter("fleet.migrations").value == 1
        done = {r.id: r for r in router.run()}
        assert done[rid].state == "ok"
        assert list(done[rid].generated) == ref

    def test_stream_span_overlaps_decode_spans(self):
        """The profiler sees the overlap: decode-step spans land
        INSIDE the fleet.migrate.stream span's wall window (the
        cross-thread span sink captures both)."""
        stats.reset()
        with _flags(migrate_async=True):
            router, rid, src = _mid_decode_router()
            sink = start_span_capture()
            try:
                router.drain(src)
            finally:
                stop_span_capture(sink)
        streams = [e for e in sink
                   if e["name"] == "fleet.migrate.stream"]
        assert len(streams) == 1, [e["name"] for e in sink]
        lo = streams[0]["ts"]
        hi = lo + streams[0]["dur"]
        inside = [e for e in sink if e["name"] == "fleet.replica.step"
                  and e["ts"] >= lo and e["ts"] + e["dur"] <= hi]
        assert inside, [e["name"] for e in sink]


# =====================================================================
# S-OVERLAP: the census lint pass
# =====================================================================

def _mod_from(tmp_path, name, source):
    p = tmp_path / f"{name}.py"
    p.write_text(source)
    spec = importlib.util.spec_from_file_location(name, str(p))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestOverlapPass:
    def test_sites_registered(self):
        assert {s.name for s in OVERLAP_SITES} \
            == {"overlap.tp_decode_ring", "overlap.moe_ep_double"}
        assert all("psum" in s.forbidden for s in OVERLAP_SITES)

    def test_repo_sites_clean(self, virtual_devices):
        assert run_overlap_pass() == []

    def _ring_site_build(self, reductions):
        mesh = _mp_mesh(2)

        def body(v):
            out = v
            for _ in range(reductions):
                out = reduce_over_axis(out, "mp", "ring")
            return out

        fn = _smap(body, mesh, (P("mp", None),), P("mp", None))
        return fn, (jnp.ones((2, 8), jnp.float32),)

    def test_clean_site_no_findings(self, virtual_devices):
        site = OverlapSite("t.ring_ok",
                           lambda: self._ring_site_build(1),
                           expected=lambda: ring_census("mp", 2))
        assert check_overlap_program(site) == []

    def test_injected_blocking_psum_caught(self, virtual_devices):
        """Acceptance criterion: collapse the ring back into one
        blocking psum — bitwise-correct on CPU, so only the census
        knows — and S-OVERLAP fires twice (stray forbidden collective
        + exact-sequence mismatch)."""
        mesh = _mp_mesh(2)

        def build():
            fn = _smap(lambda v: jax.lax.psum(v, "mp"), mesh,
                       (P("mp", None),), P("mp", None))
            return fn, (jnp.ones((2, 8), jnp.float32),)

        site = OverlapSite("t.ring_collapsed", build,
                           expected=lambda: ring_census("mp", 2))
        findings = check_overlap_program(site)
        assert [f.rule for f in findings] == ["S-OVERLAP"] * 2
        assert "psum" in findings[0].message
        assert "blocking" in findings[0].message

    def test_census_drift_caught(self, virtual_devices):
        # right primitives, wrong phase count: one reduction traced
        # where the site declares two
        site = OverlapSite("t.ring_drift",
                           lambda: self._ring_site_build(1),
                           expected=lambda: ring_census(
                               "mp", 2, reductions=2))
        findings = check_overlap_program(site)
        assert len(findings) == 1
        assert "expected exactly" in findings[0].message

    def test_waiver_silences_s_overlap(self, tmp_path, virtual_devices):
        mod = _mod_from(tmp_path, "overlap_waived", (
            "def build():"
            "  # tpu-lint: ok(S-OVERLAP) -- census change intended\n"
            "    import jax, jax.numpy as jnp\n"
            "    from jax.sharding import PartitionSpec as P\n"
            "    from paddle_tpu.distributed.tp import serving_mesh,"
            " shard_map_fn\n"
            "    mesh = serving_mesh(2,"
            " devices=jax.devices('cpu')[:2])\n"
            "    kwargs = {}\n"
            "    if getattr(jax.lax, 'pcast', None) is None:\n"
            "        kwargs['check_rep'] = False\n"
            "    fn = shard_map_fn()(lambda v: jax.lax.psum(v, 'mp'),"
            " mesh=mesh, in_specs=(P('mp', None),),"
            " out_specs=P('mp', None), **kwargs)\n"
            "    return fn, (jnp.ones((2, 8), jnp.float32),)\n"))
        from paddle_tpu.distributed.tp import ring_census as rc
        site = OverlapSite("t.waived_overlap", mod.build,
                           expected=lambda: rc("mp", 2))
        findings = run_overlap_pass(sites=[site])
        assert findings and all(f.waived for f in findings)


# =====================================================================
# tooling wiring
# =====================================================================

class TestToolingWired:
    def test_bench_gate_directions(self):
        import sys
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import bench_gate
        finally:
            sys.path.pop(0)
        d = bench_gate.DEFAULT_METRICS
        assert d["decode_tp2_overlap_tokens_per_sec"] == "down"
        assert d["decode_tp2_overlap_pct_of_hbm_roofline"] == "down"
        assert d["moe_decode_ep2_overlap_tokens_per_sec"] == "down"
        assert d["fleet_async_migration_decode_tokens"] == "down"
        assert d["fleet_async_migration_stall_ms"] == "up"
        assert d["fleet_async_migration_lost"] == "up"
        # lost requests are strict: ONE regresses, no noise floor
        assert bench_gate._regressed("fleet_async_migration_lost",
                                     "up", 0.0, 1.0, 0.10)

    def test_serve_bench_drain_async_wired(self):
        with open(os.path.join(REPO, "tools", "serve_bench.py")) as f:
            src = f.read()
        for tok in ("--drain-async", "fleet_async_migrations",
                    "fleet_async_migration_decode_tokens",
                    "fleet_async_migration_lost"):
            assert tok in src, tok

    def test_bench_overlap_rungs_and_all_manifest(self):
        spec = importlib.util.spec_from_file_location(
            "bench_mod", os.path.join(REPO, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        for kind in ("--decode-tp-overlap", "--moe-decode-ep-overlap",
                     "--fleet"):
            assert kind in bench.SECONDARY_KINDS, kind
        # the CPU manifest subset only names real rungs, overlap
        # rungs included
        assert set(bench.CPU_KINDS) <= set(bench.SECONDARY_KINDS)
        assert "--decode-tp-overlap" in bench.CPU_KINDS
        assert "--moe-decode-ep-overlap" in bench.CPU_KINDS
        assert "--fleet" in bench.CPU_KINDS
        with open(os.path.join(REPO, "bench.py")) as f:
            src = f.read()
        assert '"--all"' in src and "def _run_all" in src
