"""Paged-attention backend dispatch + page-major layout invariants.

The fused Pallas kernel itself is TPU-only (numerically verified on the
chip against the XLA path across MHA/GQA/bench geometries — see the
decode_ablations_r4 record in bench_profile.json); these tests cover
what runs everywhere: the flag dispatch, layout contracts, and
write-path round-trips on the page-major pool.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn.functional.paged_attention import (
    _xla_paged, paged_attention, write_kv_pages)


def test_invalid_backend_flag_raises():
    paddle.set_flags({"paged_attention_backend": "palas"})
    try:
        with pytest.raises(ValueError, match="valid values"):
            paged_attention(jnp.zeros((1, 4, 8)),
                            jnp.zeros((4, 4, 4, 8)),
                            jnp.zeros((4, 4, 4, 8)),
                            jnp.ones((1,), jnp.int32),
                            jnp.zeros((1, 4), jnp.int32))
    finally:
        paddle.set_flags({"paged_attention_backend": "auto"})


def test_auto_backend_off_tpu_is_xla():
    # conftest pins CPU: auto must route to the XLA gather path and
    # compute correctly
    rng = np.random.RandomState(0)
    b, n, d, ps, pp = 2, 4, 8, 4, 3
    q = jnp.asarray(rng.randn(b, n, d).astype(np.float32))
    kc = jnp.asarray(rng.randn(b * pp, n, ps, d).astype(np.float32))
    vc = jnp.asarray(rng.randn(b * pp, n, ps, d).astype(np.float32))
    lens = jnp.asarray(np.array([5, 9], np.int32))
    tables = jnp.asarray(
        np.arange(b * pp, dtype=np.int32).reshape(b, pp))
    out = paged_attention(q, kc, vc, lens, tables)
    # independent dense reference (not _xla_paged — auto IS _xla_paged
    # off-TPU, which would compare the function to itself)
    max_len = pp * ps
    k_full = np.zeros((b, max_len, n, d), np.float32)
    v_full = np.zeros((b, max_len, n, d), np.float32)
    tb = np.asarray(tables)
    for i in range(b):
        for t in range(max_len):
            k_full[i, t] = np.asarray(kc)[tb[i, t // ps], :, t % ps]
            v_full[i, t] = np.asarray(vc)[tb[i, t // ps], :, t % ps]
    logits = np.einsum("bhd,blhd->bhl", np.asarray(q), k_full) \
        * (d ** -0.5)
    mask = np.arange(max_len)[None, :] < np.asarray(lens)[:, None]
    logits = np.where(mask[:, None, :], logits, -1e30)
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    ref = np.einsum("bhl,blhd->bhd", w, v_full)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                               atol=2e-5)


def test_page_major_scatter_roundtrip_dtype_cast():
    """bf16 pool accepts fp32 writes (serving KV dtype decoupled from
    compute dtype)."""
    ck = jnp.zeros((4, 3, 2, 8), jnp.bfloat16)
    cv = jnp.zeros_like(ck)
    k = jnp.ones((2, 3, 8), jnp.float32)
    v = jnp.full((2, 3, 8), 2.0, jnp.float32)
    pos = jnp.asarray(np.array([0, 3], np.int32))
    tables = jnp.asarray(np.array([[0, 1], [2, 3]], np.int32))
    ck2, cv2 = write_kv_pages(ck, cv, k, v, pos, tables)
    assert ck2.dtype == jnp.bfloat16
    # seq 0 wrote page 0 slot 0; seq 1 wrote page 3 slot 1
    np.testing.assert_allclose(np.asarray(ck2[0, :, 0], np.float32), 1.0)
    np.testing.assert_allclose(np.asarray(cv2[3, :, 1], np.float32), 2.0)
    np.testing.assert_allclose(np.asarray(ck2[1], np.float32), 0.0)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="fused kernel is TPU-only")
def test_fused_kernel_matches_xla_on_tpu():
    from paddle_tpu.nn.functional.paged_attention import _fused_paged

    rng = np.random.RandomState(0)
    b, n_q, n_kv, d, ps, pp = 4, 16, 8, 128, 16, 5
    P = b * pp + 1
    q = jnp.asarray(rng.randn(b, n_q, d).astype(np.float32)) \
        .astype(jnp.bfloat16)
    kc = jnp.asarray(rng.randn(P, n_kv, ps, d).astype(np.float32)) \
        .astype(jnp.bfloat16)
    vc = jnp.asarray(rng.randn(P, n_kv, ps, d).astype(np.float32)) \
        .astype(jnp.bfloat16)
    lens = jnp.asarray(rng.randint(1, pp * ps, (b,)).astype(np.int32))
    tables = jnp.asarray(
        (1 + np.arange(b * pp, dtype=np.int32)).reshape(b, pp))
    out_f = np.asarray(_fused_paged(q, kc, vc, lens, tables)
                       .astype(jnp.float32))
    out_x = np.asarray(_xla_paged(q, kc, vc, lens, tables)
                       .astype(jnp.float32))
    np.testing.assert_allclose(out_f, out_x, atol=0.03)


def _dense_paged_ref(q, kc, vc, lens, tables, ps):
    """NumPy dense reference over gathered pages."""
    b, n_q, d = q.shape
    n_kv = kc.shape[2]
    g = n_q // n_kv
    pp = tables.shape[1]
    max_len = pp * ps
    k_full = np.zeros((b, max_len, n_kv, d), np.float32)
    v_full = np.zeros((b, max_len, n_kv, d), np.float32)
    for i in range(b):
        for t in range(max_len):
            k_full[i, t] = np.asarray(kc)[tables[i, t // ps], :, t % ps]
            v_full[i, t] = np.asarray(vc)[tables[i, t // ps], :, t % ps]
    qh = np.asarray(q, np.float32).reshape(b, n_kv, g, d)
    logits = np.einsum("bngd,blnd->bngl", qh, k_full) * (d ** -0.5)
    mask = np.arange(max_len)[None, :] < np.asarray(lens)[:, None]
    logits = np.where(mask[:, None, None, :], logits, -1e30)
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    return np.einsum("bngl,blnd->bngd", w, v_full).reshape(b, n_q, d)


@pytest.mark.parametrize("g", [1, 2])
def test_stream_kernel_parity(g):
    """Pool-streaming kernel vs dense reference: MHA + GQA, ragged
    lens incl. a zero-length (idle slot) row, layer-folded base offset.
    Runs in Pallas interpret mode off-TPU, compiled on the chip."""
    from paddle_tpu.nn.functional.paged_attention import (
        _stream_paged, build_pool_ownership)

    rng = np.random.RandomState(1)
    b, n_kv, d, ps, pp = 4, 4, 128, 4, 6
    n_q = n_kv * g
    P, L = 24, 2
    q = jnp.asarray(rng.randn(b, n_q, d).astype(np.float32))
    kpool = jnp.asarray(rng.randn(L * P, n_kv, ps, d).astype(np.float32))
    vpool = jnp.asarray(rng.randn(L * P, n_kv, ps, d).astype(np.float32))
    lens_np = np.array([5, 17, 0, 24], np.int32)
    tables_np = np.zeros((b, pp), np.int32)
    perm = rng.permutation(np.arange(1, P))
    i = 0
    for r in range(b):
        n = -(-int(lens_np[r]) // ps)
        tables_np[r, :n] = perm[i:i + n]
        i += n
    lens, tables = jnp.asarray(lens_np), jnp.asarray(tables_np)
    own = build_pool_ownership(tables, lens, P, ps)
    for base in (0, P):
        out = np.asarray(_stream_paged(
            q, kpool, vpool, lens, tables, pool_base=base,
            pool_pages=P, ownership=own))
        ref = _dense_paged_ref(q, kpool[base:base + P],
                               vpool[base:base + P], lens_np, tables_np,
                               ps)
        # the zero-length row is defined as 0 output by the kernel
        ref[lens_np == 0] = 0.0
        np.testing.assert_allclose(out, ref, atol=3e-2)


@pytest.mark.parametrize("g", [1, 2])
def test_fused_inplace_kernel_parity(g):
    """paged_decode_attention_inplace (the default TPU serving path):
    append + attend in one kernel must equal scatter-write followed by
    the XLA gather attention with lens+1, AND must have patched exactly
    the written rows of the layer's pool region in place (other layers'
    regions untouched). Interpret mode off-TPU, compiled on the chip."""
    from paddle_tpu.nn.functional.paged_attention import (
        _xla_paged, paged_decode_attention_inplace, write_kv_pages)

    rng = np.random.RandomState(5)
    b, n_kv, d, ps = 4, 2, 128, 4
    n_q = n_kv * g
    pp, P, L = 6, 16, 2
    q = jnp.asarray(rng.randn(b, n_q, d).astype(np.float32))
    nk = jnp.asarray(rng.randn(b, n_kv, d).astype(np.float32))
    nv = jnp.asarray(rng.randn(b, n_kv, d).astype(np.float32))
    kpool = jnp.asarray(rng.randn(L * P, n_kv, ps, d).astype(np.float32))
    vpool = jnp.asarray(rng.randn(L * P, n_kv, ps, d).astype(np.float32))
    lens_np = np.array([5, 0, 13, 9], np.int32)  # incl. idle slot
    tables_np = np.zeros((b, pp), np.int32)
    perm = rng.permutation(np.arange(1, P))
    i = 0
    for r in range(b):
        n = -(-int(lens_np[r] + 1) // ps)
        tables_np[r, :n] = perm[i:i + n]
        i += n
    lens, tables = jnp.asarray(lens_np), jnp.asarray(tables_np)
    for base in (0, P):
        out, ck, cv = paged_decode_attention_inplace(
            q, nk, nv, kpool, vpool, lens, tables,
            pool_base=base, pool_pages=P)
        ck_ref, cv_ref = write_kv_pages(
            kpool[base:base + P], vpool[base:base + P], nk, nv, lens,
            tables)
        ref = _xla_paged(q, ck_ref, cv_ref, lens + 1, tables)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-2)
        # in-place page writes: layer region equals the scatter result,
        # the OTHER layer's region is bit-untouched
        np.testing.assert_array_equal(np.asarray(ck[base:base + P]),
                                      np.asarray(ck_ref))
        np.testing.assert_array_equal(np.asarray(cv[base:base + P]),
                                      np.asarray(cv_ref))
        other = slice(P, 2 * P) if base == 0 else slice(0, P)
        np.testing.assert_array_equal(np.asarray(ck[other]),
                                      np.asarray(kpool[other]))


def test_inplace_overfull_row_masked_noop_write():
    """seq_lens < pages_per_seq*page_size precondition guard (ADVICE
    r5): a row that is exactly full has no free slot — the in-place
    kernel must NOT overwrite slot lens%ps of its last allocated page
    (the clamped-index corruption), while other rows' appends still
    land. Covers both the bf16 and the int8 in-place kernels."""
    from paddle_tpu.nn.functional.paged_attention import (
        paged_decode_attention_inplace, paged_decode_attention_inplace_q,
        quantize_kv_rows)

    rng = np.random.RandomState(11)
    b, n_kv, d, ps, pp, P = 2, 2, 128, 4, 6, 32
    q = jnp.asarray(rng.randn(b, n_kv, d).astype(np.float32))
    nk = jnp.asarray(rng.randn(b, n_kv, d).astype(np.float32))
    nv = jnp.asarray(rng.randn(b, n_kv, d).astype(np.float32))
    kpool = jnp.asarray(rng.randn(P, n_kv, ps, d).astype(np.float32))
    vpool = jnp.asarray(rng.randn(P, n_kv, ps, d).astype(np.float32))
    lens_np = np.array([pp * ps, 5], np.int32)   # row 0 exactly full
    tables_np = np.zeros((b, pp), np.int32)
    tables_np[0] = np.arange(1, 1 + pp)
    tables_np[1, :2] = [7, 8]
    lens, tables = jnp.asarray(lens_np), jnp.asarray(tables_np)

    out, ck, cv = paged_decode_attention_inplace(
        q, nk, nv, kpool, vpool, lens, tables, pool_base=0,
        pool_pages=P)
    # expected: ONLY row 1's token written (page tables[1, 5//4]=8,
    # slot 1); row 0's pages — last one included — bit-identical
    exp_k = kpool.at[8, :, 1].set(nk[1])
    exp_v = vpool.at[8, :, 1].set(nv[1])
    np.testing.assert_array_equal(np.asarray(ck), np.asarray(exp_k))
    np.testing.assert_array_equal(np.asarray(cv), np.asarray(exp_v))
    assert np.isfinite(np.asarray(out)).all()

    # int8 variant: quantized pools + scale planes equally untouched for
    # the overfull row
    kq, s_k = quantize_kv_rows(kpool)
    vq, s_v = quantize_kv_rows(vpool)
    # build planes positionally: scale of (page p, slot s) at col p*ps+s
    ks_np = np.zeros((n_kv, P * ps), np.float32)
    vs_np = np.zeros((n_kv, P * ps), np.float32)
    for p in range(P):
        for s in range(ps):
            ks_np[:, p * ps + s] = np.asarray(s_k)[p, :, s]
            vs_np[:, p * ps + s] = np.asarray(s_v)[p, :, s]
    out_q, kq2, ks2, vq2, vs2 = paged_decode_attention_inplace_q(
        q, nk, nv, kq, jnp.asarray(ks_np), vq, jnp.asarray(vs_np),
        lens, tables, pool_base=0, pool_pages=P)
    nkq, nks = quantize_kv_rows(nk)
    nvq, nvs = quantize_kv_rows(nv)
    exp_kq = kq.at[8, :, 1].set(nkq[1])
    exp_ks = jnp.asarray(ks_np).at[:, 8 * ps + 1].set(nks[1])
    np.testing.assert_array_equal(np.asarray(kq2), np.asarray(exp_kq))
    np.testing.assert_allclose(np.asarray(ks2), np.asarray(exp_ks),
                               rtol=1e-6)
    exp_vq = vq.at[8, :, 1].set(nvq[1])
    np.testing.assert_array_equal(np.asarray(vq2), np.asarray(exp_vq))
    assert np.isfinite(np.asarray(out_q)).all()


def test_int8_kv_fused_kernel_parity():
    """Cache-KV int8 mode: the quantized fused kernel must match the
    dequantized-pool XLA reference within int8 tolerance, patch the
    written int8 rows + scale-plane columns in place, and leave other
    layers' regions untouched."""
    from paddle_tpu.nn.functional.paged_attention import (
        _xla_paged, paged_decode_attention_inplace_q, quantize_kv_rows,
        write_kv_pages)

    rng = np.random.RandomState(7)
    b, n_kv, d, ps = 4, 2, 128, 4
    pp, P, L = 6, 16, 2
    T = P * ps
    q = jnp.asarray(rng.randn(b, n_kv, d).astype(np.float32))
    nk = jnp.asarray(rng.randn(b, n_kv, d).astype(np.float32))
    nv = jnp.asarray(rng.randn(b, n_kv, d).astype(np.float32))
    kf = rng.randn(L * P, n_kv, ps, d).astype(np.float32)
    vf = rng.randn(L * P, n_kv, ps, d).astype(np.float32)
    s_k = np.maximum(np.abs(kf).max(-1) / 127.0, 1e-8)
    kq = np.clip(np.round(kf / s_k[..., None]), -127, 127) \
        .astype(np.int8)
    s_v = np.maximum(np.abs(vf).max(-1) / 127.0, 1e-8)
    vq = np.clip(np.round(vf / s_v[..., None]), -127, 127) \
        .astype(np.int8)
    ks_plane = np.zeros((n_kv, L * T), np.float32)
    vs_plane = np.zeros((n_kv, L * T), np.float32)
    for p in range(L * P):
        for s in range(ps):
            ks_plane[:, p * ps + s] = s_k[p, :, s]
            vs_plane[:, p * ps + s] = s_v[p, :, s]
    lens_np = np.array([5, 0, 13, 9], np.int32)
    tables_np = np.zeros((b, pp), np.int32)
    perm = rng.permutation(np.arange(1, P))
    i = 0
    for r in range(b):
        n = -(-int(lens_np[r] + 1) // ps)
        tables_np[r, :n] = perm[i:i + n]
        i += n
    lens, tables = jnp.asarray(lens_np), jnp.asarray(tables_np)
    for base in (0, P):
        out, kq2, ks2, vq2, vs2 = paged_decode_attention_inplace_q(
            q, nk, nv, jnp.asarray(kq), jnp.asarray(ks_plane),
            jnp.asarray(vq), jnp.asarray(vs_plane), lens, tables,
            pool_base=base, pool_pages=P)
        kd = kq[base:base + P].astype(np.float32) \
            * s_k[base:base + P][..., None]
        vd = vq[base:base + P].astype(np.float32) \
            * s_v[base:base + P][..., None]
        ck_ref, cv_ref = write_kv_pages(
            jnp.asarray(kd), jnp.asarray(vd), nk, nv, lens, tables)
        ref = _xla_paged(q, ck_ref, cv_ref, lens + 1, tables)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=0.08)
        kq2n, ks2n = np.asarray(kq2), np.asarray(ks2)
        for r in range(b):
            pos = int(lens_np[r])
            pg = tables_np[r, pos // ps] + base
            sl = pos % ps
            want_q, want_s = quantize_kv_rows(nk[r][None])
            np.testing.assert_array_equal(kq2n[pg, :, sl],
                                          np.asarray(want_q)[0])
            np.testing.assert_allclose(
                ks2n[:, pg * ps + sl], np.asarray(want_s)[0],
                rtol=1e-5)
        other = slice(P, 2 * P) if base == 0 else slice(0, P)
        np.testing.assert_array_equal(np.asarray(kq2)[other], kq[other])


class TestTruncateRollback:
    """BlockKVCacheManager.truncate (ISSUE 12): the speculative-
    decoding rejection path is a PAGE-TABLE rollback with exact
    free-pool/refcount accounting — shared prefix pages must never be
    freed by a rejection while another holder is live."""

    def _mgr(self, ps=4, pages=32):
        from paddle_tpu.inference.kv_cache import BlockKVCacheManager

        return BlockKVCacheManager(2, 2, 8, ps, num_pages=pages,
                                   reserve_scratch=True)

    def test_exact_free_pool_accounting(self):
        mgr = self._mgr()
        free0 = mgr.free_pages
        mgr.allocate("s", 20)                      # 5 pages
        assert mgr.free_pages == free0 - 5
        released = mgr.truncate("s", 9)            # keep ceil(9/4) = 3
        assert len(released) == 2
        assert mgr.free_pages == free0 - 3
        assert len(mgr._owned["s"]) == 3
        for p in released:
            assert mgr.refcount(p) == 0
        # released pages are immediately reusable
        mgr.grow("s", 2)
        assert mgr.free_pages == free0 - 5
        mgr.free("s")
        assert mgr.free_pages == free0 and mgr._refs == {}

    def test_noop_when_already_covered(self):
        mgr = self._mgr()
        mgr.allocate("s", 8)                       # 2 pages
        assert mgr.truncate("s", 8) == []
        assert mgr.truncate("s", 12) == []         # larger than held
        assert len(mgr._owned["s"]) == 2
        assert mgr.truncate("missing", 0) == []    # unknown seq: no-op

    def test_shared_prefix_pages_survive_truncate(self):
        """A truncated tail page also held by the prefix cache (or any
        sharer) drops to its other holder instead of the free list."""
        mgr = self._mgr()
        free0 = mgr.free_pages
        pages = mgr.allocate("a", 16)              # 4 pages
        mgr.retain(pages[:2])                      # prefix-cache refs
        released = mgr.truncate("a", 0)            # drop everything
        assert released == pages
        # tail pages freed; the retained prefix pages stay live at rc 1
        assert mgr.refcount(pages[0]) == 1
        assert mgr.refcount(pages[1]) == 1
        assert mgr.refcount(pages[2]) == 0
        assert mgr.free_pages == free0 - 2
        mgr.release_pages(pages[:2])               # cache eviction
        assert mgr.free_pages == free0

    def test_truncate_sharer_keeps_prefix_alive_for_owner(self):
        mgr = self._mgr()
        pa = mgr.allocate("a", 8)                  # 2 full pages
        mgr.share("b", pa)                         # b maps a's prefix
        mgr.grow("b", 2)                           # b's private tail
        # b speculates past its tail and rolls all the way back into
        # the SHARED region: a's pages must survive at refcount 1
        mgr.truncate("b", 4)                       # keep 1 shared page
        assert mgr.refcount(pa[0]) == 2
        assert mgr.refcount(pa[1]) == 1            # b's ref dropped
        assert pa[1] not in mgr._free              # a still owns it
        mgr.free("b")
        mgr.free("a")
        assert mgr._refs == {}

    def test_property_randomized_refcount_model(self):
        """Property test: a random op sequence (allocate/grow/share/
        truncate/free) against a pure-python refcount model — the
        manager's free list and refcounts must match the model after
        EVERY op."""
        rng = np.random.RandomState(0xC0FFEE)
        mgr = self._mgr(ps=4, pages=64)
        model_refs = {}                            # page -> rc
        model_owned = {}                           # seq -> [pages]
        next_seq = 0

        def check():
            assert mgr._refs == model_refs
            live = set(model_refs)
            expect_free = (mgr.num_pages - 1) - len(live)  # -scratch
            assert mgr.free_pages == expect_free
            for s, pgs in model_owned.items():
                assert mgr._owned.get(s, []) == pgs

        for _step in range(300):
            ops = ["alloc"]
            if model_owned:
                ops += ["grow", "truncate", "free", "share"]
            op = ops[rng.randint(len(ops))]
            seqs = list(model_owned)
            if op == "alloc" and mgr.free_pages >= 4:
                sid = f"s{next_seq}"
                next_seq += 1
                n_tok = int(rng.randint(1, 17))
                got = mgr.allocate(sid, n_tok)
                model_owned[sid] = list(got)
                for p in got:
                    model_refs[p] = 1
            elif op == "grow" and seqs and mgr.free_pages >= 2:
                sid = seqs[rng.randint(len(seqs))]
                got = mgr.grow(sid, int(rng.randint(1, 3)))
                model_owned[sid].extend(got)
                for p in got:
                    model_refs[p] = 1
            elif op == "share" and seqs:
                src = seqs[rng.randint(len(seqs))]
                if not model_owned[src]:
                    continue
                sid = f"s{next_seq}"
                next_seq += 1
                shared = model_owned[src][:rng.randint(
                    1, len(model_owned[src]) + 1)]
                mgr.share(sid, shared)
                model_owned[sid] = list(shared)
                for p in shared:
                    model_refs[p] += 1
            elif op == "truncate" and seqs:
                sid = seqs[rng.randint(len(seqs))]
                new_len = int(rng.randint(
                    0, 4 * len(model_owned[sid]) + 1))
                keep = -(-new_len // 4)
                expect_rel = model_owned[sid][keep:]
                got = mgr.truncate(sid, new_len)
                assert got == expect_rel
                del model_owned[sid][keep:]
                for p in expect_rel:
                    model_refs[p] -= 1
                    if model_refs[p] == 0:
                        del model_refs[p]
            elif op == "free" and seqs:
                sid = seqs[rng.randint(len(seqs))]
                mgr.free(sid)
                for p in model_owned.pop(sid):
                    model_refs[p] -= 1
                    if model_refs[p] == 0:
                        del model_refs[p]
            check()
        # drain everything: the pool must return to pristine
        for sid in list(model_owned):
            mgr.free(sid)
        assert mgr._refs == {}
        assert mgr.free_pages == mgr.num_pages - 1


def test_int8_kv_engine_tokens():
    """GenerationEngine kv_dtype='int8' end-to-end vs full-precision KV:
    greedy tokens must agree on a small model."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import FusedCausalLM, GenerationEngine

    paddle.seed(5)
    mk = dict(vocab_size=256, embed_dim=256, num_heads=2,
              dim_feedforward=512, num_layers=2, max_position=128)
    model = FusedCausalLM(**mk)
    ids = np.random.RandomState(2).randint(1, 256, (2, 12))
    out_a = GenerationEngine(model, page_size=4, max_length=48,
                             decode_chunk=4).generate(
                                 ids, max_new_tokens=8)
    out_b = GenerationEngine(model, page_size=4, max_length=48,
                             decode_chunk=4, kv_dtype="int8").generate(
                                 ids, max_new_tokens=8)
    agree = float((out_a[:, 12:] == out_b[:, 12:]).mean())
    assert agree >= 0.75, (out_a[:, 12:], out_b[:, 12:])
