"""Paged-attention backend dispatch + page-major layout invariants.

The fused Pallas kernel itself is TPU-only (numerically verified on the
chip against the XLA path across MHA/GQA/bench geometries — see the
decode_ablations_r4 record in bench_profile.json); these tests cover
what runs everywhere: the flag dispatch, layout contracts, and
write-path round-trips on the page-major pool.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn.functional.paged_attention import (
    _xla_paged, paged_attention, write_kv_pages)


def test_invalid_backend_flag_raises():
    paddle.set_flags({"paged_attention_backend": "palas"})
    try:
        with pytest.raises(ValueError, match="valid values"):
            paged_attention(jnp.zeros((1, 4, 8)),
                            jnp.zeros((4, 4, 4, 8)),
                            jnp.zeros((4, 4, 4, 8)),
                            jnp.ones((1,), jnp.int32),
                            jnp.zeros((1, 4), jnp.int32))
    finally:
        paddle.set_flags({"paged_attention_backend": "auto"})


def test_auto_backend_off_tpu_is_xla():
    # conftest pins CPU: auto must route to the XLA gather path and
    # compute correctly
    rng = np.random.RandomState(0)
    b, n, d, ps, pp = 2, 4, 8, 4, 3
    q = jnp.asarray(rng.randn(b, n, d).astype(np.float32))
    kc = jnp.asarray(rng.randn(b * pp, ps, n, d).astype(np.float32))
    vc = jnp.asarray(rng.randn(b * pp, ps, n, d).astype(np.float32))
    lens = jnp.asarray(np.array([5, 9], np.int32))
    tables = jnp.asarray(
        np.arange(b * pp, dtype=np.int32).reshape(b, pp))
    out = paged_attention(q, kc, vc, lens, tables)
    # independent dense reference (not _xla_paged — auto IS _xla_paged
    # off-TPU, which would compare the function to itself)
    max_len = pp * ps
    k_full = np.zeros((b, max_len, n, d), np.float32)
    v_full = np.zeros((b, max_len, n, d), np.float32)
    tb = np.asarray(tables)
    for i in range(b):
        for t in range(max_len):
            k_full[i, t] = np.asarray(kc)[tb[i, t // ps], t % ps]
            v_full[i, t] = np.asarray(vc)[tb[i, t // ps], t % ps]
    logits = np.einsum("bhd,blhd->bhl", np.asarray(q), k_full) \
        * (d ** -0.5)
    mask = np.arange(max_len)[None, :] < np.asarray(lens)[:, None]
    logits = np.where(mask[:, None, :], logits, -1e30)
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    ref = np.einsum("bhl,blhd->bhd", w, v_full)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                               atol=2e-5)


def test_page_major_scatter_roundtrip_dtype_cast():
    """bf16 pool accepts fp32 writes (serving KV dtype decoupled from
    compute dtype)."""
    ck = jnp.zeros((4, 2, 3, 8), jnp.bfloat16)
    cv = jnp.zeros_like(ck)
    k = jnp.ones((2, 3, 8), jnp.float32)
    v = jnp.full((2, 3, 8), 2.0, jnp.float32)
    pos = jnp.asarray(np.array([0, 3], np.int32))
    tables = jnp.asarray(np.array([[0, 1], [2, 3]], np.int32))
    ck2, cv2 = write_kv_pages(ck, cv, k, v, pos, tables)
    assert ck2.dtype == jnp.bfloat16
    # seq 0 wrote page 0 slot 0; seq 1 wrote page 3 slot 1
    np.testing.assert_allclose(np.asarray(ck2[0, 0], np.float32), 1.0)
    np.testing.assert_allclose(np.asarray(cv2[3, 1], np.float32), 2.0)
    np.testing.assert_allclose(np.asarray(ck2[1], np.float32), 0.0)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="fused kernel is TPU-only")
def test_fused_kernel_matches_xla_on_tpu():
    from paddle_tpu.nn.functional.paged_attention import _fused_paged

    rng = np.random.RandomState(0)
    b, n_q, n_kv, d, ps, pp = 4, 16, 8, 128, 16, 5
    P = b * pp + 1
    q = jnp.asarray(rng.randn(b, n_q, d).astype(np.float32)) \
        .astype(jnp.bfloat16)
    kc = jnp.asarray(rng.randn(P, ps, n_kv, d).astype(np.float32)) \
        .astype(jnp.bfloat16)
    vc = jnp.asarray(rng.randn(P, ps, n_kv, d).astype(np.float32)) \
        .astype(jnp.bfloat16)
    lens = jnp.asarray(rng.randint(1, pp * ps, (b,)).astype(np.int32))
    tables = jnp.asarray(
        (1 + np.arange(b * pp, dtype=np.int32)).reshape(b, pp))
    out_f = np.asarray(_fused_paged(q, kc, vc, lens, tables)
                       .astype(jnp.float32))
    out_x = np.asarray(_xla_paged(q, kc, vc, lens, tables)
                       .astype(jnp.float32))
    np.testing.assert_allclose(out_f, out_x, atol=0.03)
