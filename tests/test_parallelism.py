"""Hybrid parallelism tests: fleet topology, TP layers, SP, sharding,
PP schedule, MoE, recompute, ring attention — on the 8-device CPU mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet


@pytest.fixture(scope="module", autouse=True)
def _fleet_init():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        **strategy.hybrid_configs,
        "dp_degree": 2, "mp_degree": 4, "pp_degree": 1,
        "sharding_degree": 1, "sep_degree": 1,
    }
    fleet.init(is_collective=True, strategy=strategy)
    yield


class TestTopology:
    def test_comm_topology(self):
        topo = fleet.CommunicateTopology(
            ["pp", "mp", "sep", "sharding", "dp"], [2, 2, 1, 1, 2])
        assert topo.world_size() == 8
        assert topo.get_dim("mp") == 2
        mp_groups = topo.get_comm_list("mp")
        assert len(mp_groups) == 4
        for g in mp_groups:
            assert len(g) == 2
        assert topo.get_rank(pp=0, mp=0, sep=0, sharding=0, dp=0) == 0

    def test_hcg(self):
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == 4
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.mesh.get_dim_size("mp") == 4
        assert hcg.get_model_parallel_group() is not None


class TestTPLayers:
    def test_column_row_pair_matches_dense(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ColumnParallelLinear, RowParallelLinear,
        )

        paddle.seed(0)
        col = ColumnParallelLinear(16, 32, gather_output=False)
        row = RowParallelLinear(32, 16, input_is_parallel=True)
        x = paddle.randn([4, 16])
        y = row(col(x))
        ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
            @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(y.numpy(), ref, rtol=2e-4, atol=1e-5)

    def test_tp_weights_are_sharded(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ColumnParallelLinear,
        )

        col = ColumnParallelLinear(8, 16, gather_output=True)
        spec = col.weight._data.sharding.spec
        assert "mp" in str(spec)
        out = col(paddle.randn([2, 8]))
        assert out._data.sharding.is_fully_replicated

    def test_tp_backward(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ColumnParallelLinear, RowParallelLinear,
        )

        col = ColumnParallelLinear(8, 16, gather_output=False)
        row = RowParallelLinear(16, 8, input_is_parallel=True)
        x = paddle.randn([2, 8])
        row(col(x)).sum().backward()
        assert col.weight.grad is not None
        assert row.weight.grad is not None

    def test_vocab_parallel_embedding(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            VocabParallelEmbedding,
        )

        emb = VocabParallelEmbedding(64, 16)
        ids = paddle.to_tensor(np.array([[3, 7], [1, 2]]))
        out = emb(ids)
        np.testing.assert_allclose(out.numpy()[0, 0],
                                   emb.weight.numpy()[3], rtol=1e-5)
        out.sum().backward()
        assert emb.weight.grad is not None

    def test_parallel_cross_entropy(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ParallelCrossEntropy,
        )

        pce = ParallelCrossEntropy()
        logits = paddle.randn([4, 32])
        labels = paddle.to_tensor(np.array([0, 5, 10, 31]))
        loss = pce(logits, labels)
        ref = F.cross_entropy(logits, labels, reduction="none")
        np.testing.assert_allclose(loss.numpy(), ref.numpy(), rtol=1e-5)

    def test_rng_tracker(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            get_rng_state_tracker,
        )

        tracker = get_rng_state_tracker()
        if "local_seed" not in tracker.states_:
            tracker.add("local_seed", 123)
        with tracker.rng_state("local_seed"):
            a = paddle.rand([4])
        with tracker.rng_state():
            b = paddle.rand([4])
        assert not np.allclose(a.numpy(), b.numpy())


class TestSequenceParallel:
    def test_scatter_gather_roundtrip(self):
        from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils \
            import GatherOp, ScatterOp

        x = paddle.randn([8, 2, 16])
        s = ScatterOp.apply(x)
        assert "mp" in str(s._data.sharding.spec)
        g = GatherOp.apply(s)
        np.testing.assert_allclose(g.numpy(), x.numpy())

    def test_sp_linear_pair(self):
        from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils \
            import (ColumnSequenceParallelLinear, RowSequenceParallelLinear,
                    ScatterOp)

        paddle.seed(1)
        csl = ColumnSequenceParallelLinear(16, 32)
        rsl = RowSequenceParallelLinear(32, 16)
        x = paddle.randn([8, 2, 16])
        s = ScatterOp.apply(x)
        out = rsl(csl(s))
        ref = (x.numpy() @ csl.weight.numpy() + csl.bias.numpy()) \
            @ rsl.weight.numpy() + rsl.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=1e-5)


class TestSharding:
    def test_stage1_states_sharded(self):
        from paddle_tpu.distributed.fleet.meta_parallel.sharding \
            .sharding_optimizer import shard_optimizer_states

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            **strategy.hybrid_configs, "dp_degree": 1, "mp_degree": 1,
            "sharding_degree": 8,
        }
        f2 = fleet.Fleet()
        f2.init(strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        net = nn.Linear(16, 16)
        opt = paddle.optimizer.Adam(0.01, parameters=net.parameters())
        shard_optimizer_states(opt, hcg)
        net(paddle.randn([2, 16])).sum().backward()
        opt.step()
        st = opt._accumulators[id(net.weight)]
        assert not st["moment1"].sharding.is_fully_replicated

    def test_stage3_params_sharded(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            GroupShardedStage3,
        )

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            **strategy.hybrid_configs, "dp_degree": 1, "mp_degree": 1,
            "sharding_degree": 8,
        }
        fleet.Fleet().init(strategy=strategy)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 16))
        opt = paddle.optimizer.Adam(0.01, parameters=net.parameters())
        wrapped = GroupShardedStage3(net, opt)
        w = net[0].weight
        assert not w._data.sharding.is_fully_replicated
        out = wrapped(paddle.randn([2, 16]))
        out.sum().backward()
        opt.step()
        assert np.isfinite(out.numpy()).all()


class TestPipeline:
    def _strategy(self, acc=2):
        s = fleet.DistributedStrategy()
        s.hybrid_configs["pp_configs"].accumulate_steps = acc
        return s

    def test_pipeline_layer_segments(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            LayerDesc, PipelineLayer,
        )

        pl = PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 8) for _ in range(6)],
            num_stages=3, loss_fn=F.mse_loss)
        assert pl.segment_parts == [0, 2, 4, 6]
        assert pl.get_stage_from_index(3) == 1
        assert len(pl.stage_layers(2)) == 2

    def test_shared_layer_desc_ties_weights(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineLayer, SharedLayerDesc,
        )

        pl = PipelineLayer(
            layers=[
                SharedLayerDesc("embed", nn.Linear, None, "weight", 8, 8),
                SharedLayerDesc("embed", nn.Linear, None, "weight", 8, 8),
            ],
            num_stages=2, loss_fn=F.mse_loss)
        l0, l1 = pl.run_function[0], pl.run_function[1]
        assert l0.shared is l1.shared

    def test_train_batch_matches_plain_accumulation(self):
        """Numeric check at pp_degree=1 (this module's topology): the
        pipelined step must equal plain micro-batch accumulation. The
        real multi-stage schedule is covered in tests/test_pipeline.py."""
        from paddle_tpu.distributed.fleet.meta_parallel import (
            LayerDesc, PipelineLayer, PipelineParallel,
        )

        def build(seed):
            paddle.seed(seed)
            return PipelineLayer(
                layers=[LayerDesc(nn.Linear, 8, 8) for _ in range(4)],
                num_stages=1, loss_fn=F.mse_loss)

        hcg = fleet.get_hybrid_communicate_group()
        xb = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        yb = np.zeros((4, 8), np.float32)

        pl1 = build(5)
        pp = PipelineParallel(pl1, hcg, self._strategy(acc=2))
        opt1 = paddle.optimizer.SGD(0.1, parameters=pp.parameters())
        pp.train_batch([paddle.to_tensor(xb), paddle.to_tensor(yb)], opt1)

        pl2 = build(5)
        opt2 = paddle.optimizer.SGD(0.1, parameters=pl2.parameters())
        loss = F.mse_loss(pl2(paddle.to_tensor(xb)), paddle.to_tensor(yb))
        loss.backward()
        opt2.step()

        w1 = np.asarray(pp._stacked_params[0]._data[0])
        w2 = list(pl2.parameters())[0].numpy()
        np.testing.assert_allclose(w1, w2, rtol=1e-4, atol=1e-6)


class TestRecompute:
    def test_grad_parity_with_plain(self):
        from paddle_tpu.distributed.fleet import recompute

        paddle.seed(2)
        net = nn.Sequential(nn.Linear(8, 32), nn.GELU(), nn.Linear(32, 8))
        x = paddle.randn([4, 8])

        out = recompute(net, x)
        out.sum().backward()
        g_rc = net[0].weight.grad.numpy().copy()
        net[0].weight.clear_grad()

        net(x).sum().backward()
        np.testing.assert_allclose(g_rc, net[0].weight.grad.numpy(),
                                   rtol=1e-4)

    def test_recompute_sequential(self):
        from paddle_tpu.distributed.fleet import recompute_sequential

        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 8))
        x = paddle.randn([2, 8])
        out = recompute_sequential({"segments": 2}, net, x)
        out.sum().backward()
        assert net[0].weight.grad is not None

    def test_dropout_deterministic_replay(self):
        from paddle_tpu.distributed.fleet import recompute

        net = nn.Sequential(nn.Linear(16, 16), nn.Dropout(0.5))
        net.train()
        x = paddle.randn([4, 16])
        x.stop_gradient = False
        out = recompute(net, x)
        # backward recomputes forward — if the mask replay were wrong the
        # vjp would be inconsistent with the forward value
        out.sum().backward()
        assert x.grad is not None


class TestMoE:
    def test_stacked_moe(self):
        from paddle_tpu.incubate.moe import MoELayer

        paddle.seed(0)
        moe = MoELayer(d_model=16, num_experts=4, gate="gshard",
                       d_hidden=32)
        x = paddle.randn([2, 8, 16])
        out = moe(x)
        assert out.shape == [2, 8, 16]
        (out.sum() + moe.aux_loss).backward()
        assert moe.gate.weight.grad is not None
        assert moe.stacked.w1.grad is not None

    def test_switch_gate_top1(self):
        from paddle_tpu.incubate.moe import MoELayer

        moe = MoELayer(d_model=8, num_experts=2, gate="switch")
        assert moe.top_k == 1
        out = moe(paddle.randn([4, 8]))
        assert out.shape == [4, 8]

    def test_generic_experts(self):
        from paddle_tpu.incubate.moe import MoELayer

        experts = [nn.Linear(8, 8) for _ in range(2)]
        moe = MoELayer(d_model=8, experts=experts, gate="naive")
        out = moe(paddle.randn([4, 8]))
        out.sum().backward()
        assert experts[0].weight.grad is not None

    def test_capacity_drops_tokens_gracefully(self):
        from paddle_tpu.incubate.moe import MoELayer

        moe = MoELayer(d_model=8, num_experts=2, gate="gshard",
                       capacity_factor=0.25)
        out = moe(paddle.randn([4, 8]))
        assert np.isfinite(out.numpy()).all()


class TestRingAttention:
    def test_parity_dense(self):
        from paddle_tpu.nn.functional.ring_attention import ring_attention

        mesh = dist.ProcessMesh(list(range(8)), dim_names=["sep"])
        paddle.seed(0)
        q = paddle.randn([2, 32, 2, 8])
        k = paddle.randn([2, 32, 2, 8])
        v = paddle.randn([2, 32, 2, 8])
        ref = F.scaled_dot_product_attention(q, k, v).numpy()
        out = ring_attention(q, k, v, mesh=mesh, seq_axis="sep")
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)

    def test_parity_causal(self):
        from paddle_tpu.nn.functional.ring_attention import ring_attention

        mesh = dist.ProcessMesh(list(range(8)), dim_names=["sep"])
        q = paddle.randn([1, 16, 2, 8])
        ref = F.scaled_dot_product_attention(q, q, q, is_causal=True).numpy()
        out = ring_attention(
            paddle.to_tensor(q.numpy()), paddle.to_tensor(q.numpy()),
            paddle.to_tensor(q.numpy()), mesh=mesh, seq_axis="sep",
            causal=True)
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)

    def test_output_stays_seq_sharded(self):
        from paddle_tpu.nn.functional.ring_attention import ring_attention

        mesh = dist.ProcessMesh(list(range(8)), dim_names=["sep"])
        q = paddle.randn([1, 32, 2, 8])
        out = ring_attention(q, q, q, mesh=mesh, seq_axis="sep")
        assert "sep" in str(out._data.sharding.spec)


class TestHybridOptimizer:
    def test_distributed_optimizer_wraps(self):
        net = nn.Linear(4, 4)
        opt = paddle.optimizer.AdamW(
            0.01, parameters=net.parameters(),
            grad_clip=nn.ClipGradByGlobalNorm(1.0))
        dopt = fleet.distributed_optimizer(opt)
        net(paddle.randn([2, 4])).sum().backward()
        dopt.step()
        dopt.clear_grad()
        assert net.weight.grad is None
