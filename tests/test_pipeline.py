"""Real pipeline parallelism: stage-partitioned 1F1B / interleave over
the pp mesh axis (reference: fleet/meta_parallel/pipeline_parallel.py
:440 1F1B, :906 interleave; p2p_communication.py:313 — here ppermute /
collective-permute inside one compiled program)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet

PP = 4
VOCAB, D, HEADS = 32, 16, 2


@pytest.fixture(scope="module", autouse=True)
def _fleet_init():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        **strategy.hybrid_configs,
        "dp_degree": 2, "mp_degree": 1, "pp_degree": PP,
        "sharding_degree": 1, "sep_degree": 1,
    }
    fleet.init(is_collective=True, strategy=strategy)
    yield


class Block(nn.Layer):
    """Uniform pipeline body layer (no dropout for exact parity)."""

    def __init__(self):
        super().__init__()
        self.ln = nn.LayerNorm(D)
        self.fc1 = nn.Linear(D, 2 * D)
        self.fc2 = nn.Linear(2 * D, D)

    def forward(self, x):
        return x + self.fc2(F.gelu(self.fc1(self.ln(x))))


def _loss_fn(logits, labels):
    return F.cross_entropy(logits.reshape([-1, VOCAB]),
                           labels.reshape([-1]))


def _build(seed, n_blocks=4, num_virtual=None):
    from paddle_tpu.distributed.fleet.meta_parallel import (
        LayerDesc, PipelineLayer)

    paddle.seed(seed)
    descs = [LayerDesc(nn.Embedding, VOCAB, D)]
    descs += [LayerDesc(Block) for _ in range(n_blocks)]
    descs += [LayerDesc(nn.LayerNorm, D), LayerDesc(nn.Linear, D, VOCAB)]
    return PipelineLayer(layers=descs, num_stages=PP, loss_fn=_loss_fn,
                         num_virtual_pipeline_stages=num_virtual)


def _data(M=8, mb=2, seq=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(0, VOCAB, (M * mb, seq))
    y = rng.randint(0, VOCAB, (M * mb, seq))
    return paddle.to_tensor(x), paddle.to_tensor(y)


def _train_ref(seed, data, steps, lr=0.1, n_blocks=4):
    """Plain single-program training baseline on the same model."""
    pl = _build(seed, n_blocks)
    opt = paddle.optimizer.SGD(lr, parameters=pl.parameters())
    x, y = data
    losses = []
    for _ in range(steps):
        loss = _loss_fn(pl(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return pl, losses


class TestPipeline1F1B:
    def _wrap(self, seed, acc=8, n_blocks=4, schedule="1F1B"):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallel)

        s = fleet.DistributedStrategy()
        s.hybrid_configs["pp_configs"].accumulate_steps = acc
        s.hybrid_configs["pp_configs"].schedule_mode = schedule
        hcg = fleet.get_hybrid_communicate_group()
        return PipelineParallel(_build(seed, n_blocks), hcg, s)

    def test_stage_partitioning(self):
        pp = self._wrap(0)
        assert len(pp._pre_layers) == 1       # embedding
        assert len(pp._post_layers) == 2      # final norm + head
        assert pp._chunk_size == 1
        # stacked leaves [pp, ...] and pp-sharded
        for sp in pp._stacked_params:
            assert sp.shape[0] == PP
            spec = sp._data.sharding.spec
            assert spec[0] == "pp", spec

    def test_1f1b_matches_single_program(self):
        data = _data()
        ref, ref_losses = _train_ref(11, data, steps=3)
        pp = self._wrap(11)
        opt = paddle.optimizer.SGD(0.1, parameters=pp.parameters())
        losses = []
        for _ in range(3):
            loss = pp.train_batch(list(data), opt)
            losses.append(float(loss.numpy()))
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-4,
                                   atol=1e-5)
        # parameters after training match the unpipelined model
        ref_blocks = [l for l in ref.run_function
                      if isinstance(l, Block)]
        # first stacked param is block ln.weight across stages
        stacked0 = np.asarray(pp._stacked_params[0]._data)
        for s_idx in range(PP):
            ref_p = np.asarray(ref_blocks[s_idx].ln.weight._data)
            np.testing.assert_allclose(stacked0[s_idx], ref_p,
                                       rtol=2e-4, atol=1e-5)
        # embedding (pre) and head (post) also updated identically
        emb_ref = [l for l in ref.run_function
                   if isinstance(l, nn.Embedding)][0]
        np.testing.assert_allclose(
            np.asarray(pp._pre_params[0]._data),
            np.asarray(emb_ref.weight._data), rtol=2e-4, atol=1e-5)

    def test_collective_permute_in_hlo(self):
        pp = self._wrap(3)
        data = _data()
        pp.train_batch(list(data), paddle.optimizer.SGD(
            0.1, parameters=pp.parameters()))
        x_all = pp._split_micro_arrays(data[0])
        (labels_all,) = pp._split_micro_arrays(data[1])
        import jax.random as jr

        lowered = pp._step_fn.lower(
            [p._data for p in pp._pre_params],
            [p._data for p in pp._stacked_params],
            [p._data for p in pp._post_params],
            jr.key(0), x_all, labels_all)
        txt = lowered.compile().as_text()
        assert "collective-permute" in txt, \
            "stage handoff must lower to collective-permute"

    def test_fthenb_schedule_matches(self):
        data = _data()
        _, ref_losses = _train_ref(13, data, steps=2)
        pp = self._wrap(13, schedule="FThenB")
        opt = paddle.optimizer.SGD(0.1, parameters=pp.parameters())
        losses = [float(pp.train_batch(list(data), opt).numpy())
                  for _ in range(2)]
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-4,
                                   atol=1e-5)

    def test_eval_batch(self):
        data = _data()
        pp = self._wrap(7)
        ref, _ = _train_ref(7, data, steps=0)
        ev = pp.eval_batch([data[0], data[1]])
        ref_loss = _loss_fn(ref(data[0]), data[1])
        np.testing.assert_allclose(float(ev.numpy()),
                                   float(ref_loss.numpy()), rtol=1e-5)

    def test_1f1b_residual_live_set_bounded(self):
        """The 1F1B engine keeps residuals in a ring of depth 2*pp —
        the number of jaxpr values with a leading micro-batch dimension
        must stay O(1) (inputs/outputs), NOT O(num_layers*M) as a GPipe
        residual stash would be."""
        from paddle_tpu.distributed.fleet.meta_parallel.pp_utils import (
            spmd_pipeline)

        Pn, M, mb, Dd = 4, 16, 2, 6
        mesh = fleet.get_hybrid_communicate_group().mesh.jax_mesh()

        def stage_fn(sp, x):
            return jnp.tanh(x @ sp["w"])

        def head_loss(hp, y, lbl):
            return jnp.mean((y @ hp["wo"] - lbl) ** 2)

        stacked = {"w": jnp.ones((Pn, Dd, Dd)) * 0.1}
        head = {"wo": jnp.ones((Dd, 3))}
        h_all = jnp.ones((M, mb, Dd))
        lbl = jnp.ones((M, mb, 3))
        jaxpr = jax.make_jaxpr(
            lambda st, hp, ha, lb: spmd_pipeline.pipeline_1f1b_grads(
                stage_fn, head_loss, st, hp, ha, lb, mesh=mesh,
                num_stages=Pn))(stacked, head, h_all, lbl)
        text = str(jaxpr)
        ring_dim = 2 * Pn
        assert f"({ring_dim},{mb},{Dd})" in text.replace(" ", ""), \
            "residual ring buffers of depth 2*pp expected"
        # count distinct jaxpr arrays carrying a full [M, ...] stash
        import re

        m_stash = re.findall(rf"\({M},{mb},{Dd}\)", text.replace(" ", ""))
        assert len(m_stash) < 40, (
            f"too many [M,...] buffers ({len(m_stash)}) — residuals "
            f"should live in the 2*pp ring, not per-microbatch stashes")


class AttnToy(nn.Layer):
    def __init__(self):
        super().__init__()
        self.ln = nn.LayerNorm(D)
        self.qkv = nn.Linear(D, D)

    def forward(self, x):
        return x + self.qkv(self.ln(x))


class MlpToy(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(D, 2 * D)
        self.fc2 = nn.Linear(2 * D, D)

    def forward(self, x):
        return x + self.fc2(F.gelu(self.fc1(x)))


class TestAlternatingLayers:
    def test_period2_run_detection(self):
        """Alternating Attn/MLP LayerDescs (the reference's common
        decomposition) must stack as period-2 groups."""
        from paddle_tpu.distributed.fleet.meta_parallel import (
            LayerDesc, PipelineLayer, PipelineParallel)

        paddle.seed(3)
        descs = [LayerDesc(nn.Embedding, VOCAB, D)]
        for _ in range(PP):
            descs += [LayerDesc(AttnToy), LayerDesc(MlpToy)]
        descs += [LayerDesc(nn.Linear, D, VOCAB)]
        pl = PipelineLayer(layers=descs, num_stages=PP, loss_fn=_loss_fn)

        # unwrapped single-program baseline before wrapping mutates pl
        paddle.seed(3)
        pl_ref = PipelineLayer(layers=[LayerDesc(nn.Embedding, VOCAB, D)]
                               + sum([[LayerDesc(AttnToy),
                                       LayerDesc(MlpToy)]
                                      for _ in range(PP)], [])
                               + [LayerDesc(nn.Linear, D, VOCAB)],
                               num_stages=PP, loss_fn=_loss_fn)

        s = fleet.DistributedStrategy()
        s.hybrid_configs["pp_configs"].accumulate_steps = 4
        hcg = fleet.get_hybrid_communicate_group()
        pp = PipelineParallel(pl, hcg, s)
        assert pp._chunk_size == 2  # one Attn + one MLP per stage

        data = _data(M=4)
        opt = paddle.optimizer.SGD(0.1, parameters=pp.parameters())
        loss = float(pp.train_batch(list(data), opt).numpy())

        opt_ref = paddle.optimizer.SGD(0.1, parameters=pl_ref.parameters())
        l_ref = _loss_fn(pl_ref(data[0]), data[1])
        l_ref.backward()
        opt_ref.step()
        np.testing.assert_allclose(loss, float(l_ref.numpy()),
                                   rtol=2e-4, atol=1e-5)

    def test_config_difference_breaks_uniform_run(self):
        """Layers same class/shapes but different scalar config (eps)
        must NOT be stacked under one template."""
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel \
            import _layer_sig

        a, b = nn.LayerNorm(D, epsilon=1e-5), nn.LayerNorm(D, epsilon=1e-3)
        assert _layer_sig(a) != _layer_sig(b)


class BlockWide(nn.Layer):
    """Different config from Block (3x hidden) — NOT stackable with it."""

    def __init__(self):
        super().__init__()
        self.ln = nn.LayerNorm(D)
        self.fc1 = nn.Linear(D, 3 * D)
        self.fc2 = nn.Linear(3 * D, D)

    def forward(self, x):
        return x + self.fc2(F.gelu(self.fc1(self.ln(x))))


class TestMultiRunPipeline:
    """Models whose blocks change config mid-stack still pipeline:
    multi-run decomposition (reference seg-method flexibility,
    parallel_layers/pp_layers.py:237)."""

    def _descs(self, with_mid=False):
        from paddle_tpu.distributed.fleet.meta_parallel import LayerDesc

        descs = [LayerDesc(nn.Embedding, VOCAB, D)]
        descs += [LayerDesc(Block) for _ in range(PP)]
        if with_mid:
            descs += [LayerDesc(nn.LayerNorm, D, epsilon=1e-3)]
        descs += [LayerDesc(BlockWide) for _ in range(PP)]
        descs += [LayerDesc(nn.Linear, D, VOCAB)]
        return descs

    def _build_pl(self, seed, with_mid=False):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineLayer)

        paddle.seed(seed)
        return PipelineLayer(layers=self._descs(with_mid), num_stages=PP,
                             loss_fn=_loss_fn)

    @pytest.mark.parametrize("with_mid", [False, True])
    def test_two_configs_train_to_parity(self, with_mid):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallel)

        data = _data(M=4)
        pl_ref = self._build_pl(51, with_mid)
        opt_ref = paddle.optimizer.SGD(0.1, parameters=pl_ref.parameters())
        ref_losses = []
        for _ in range(2):
            loss = _loss_fn(pl_ref(data[0]), data[1])
            loss.backward()
            opt_ref.step()
            opt_ref.clear_grad()
            ref_losses.append(float(loss.numpy()))

        s = fleet.DistributedStrategy()
        s.hybrid_configs["pp_configs"].accumulate_steps = 4
        hcg = fleet.get_hybrid_communicate_group()
        pp = PipelineParallel(self._build_pl(51, with_mid), hcg, s)
        assert pp._multi_run
        n_stacks = sum(1 for sg in pp._segments if sg["kind"] == "stack")
        assert n_stacks == 2
        if with_mid:
            assert any(sg["kind"] == "repl" for sg in pp._segments)
        opt = paddle.optimizer.SGD(0.1, parameters=pp.parameters())
        losses = [float(pp.train_batch(list(data), opt).numpy())
                  for _ in range(2)]
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-4,
                                   atol=1e-5)

    def test_single_config_still_uses_1f1b(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallel)

        s = fleet.DistributedStrategy()
        s.hybrid_configs["pp_configs"].accumulate_steps = 4
        hcg = fleet.get_hybrid_communicate_group()
        pp = PipelineParallel(_build(9), hcg, s)
        assert not pp._multi_run


class TestPipelineInterleave:
    def test_interleave_matches_single_program(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallelWithInterleave)

        data = _data()
        ref, ref_losses = _train_ref(21, data, steps=2, n_blocks=8)
        s = fleet.DistributedStrategy()
        s.hybrid_configs["pp_configs"].accumulate_steps = 8
        hcg = fleet.get_hybrid_communicate_group()
        pp = PipelineParallelWithInterleave(
            _build(21, n_blocks=8, num_virtual=2), hcg, s)
        assert pp._num_virtual == 2
        for sp in pp._stacked_params:
            assert sp.shape[0] == PP * 2
        opt = paddle.optimizer.SGD(0.1, parameters=pp.parameters())
        losses = [float(pp.train_batch(list(data), opt).numpy())
                  for _ in range(2)]
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-4,
                                   atol=1e-5)

    def test_interleaved_1f1b_is_default_schedule(self):
        """PipelineParallelWithInterleave must run the TRUE interleaved
        1F1B engine (reference pipeline_parallel.py:906), not fall back
        to circular FThenB."""
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallelWithInterleave)

        s = fleet.DistributedStrategy()
        s.hybrid_configs["pp_configs"].accumulate_steps = 8
        hcg = fleet.get_hybrid_communicate_group()
        pp = PipelineParallelWithInterleave(
            _build(31, n_blocks=8, num_virtual=2), hcg, s)
        assert pp.schedule == "1F1B"

    def test_interleaved_residual_live_set_bounded(self):
        """The VPP engine keeps residuals in a ring of depth 2*v*pp —
        not per-microbatch stashes."""
        from paddle_tpu.distributed.fleet.meta_parallel.pp_utils import (
            spmd_pipeline)

        Pn, v, M, mb, Dd = 2, 2, 8, 2, 6
        mesh = fleet.get_hybrid_communicate_group().mesh.jax_mesh()
        # engine needs a pp axis of size 2 — reuse dp axis slot by
        # building a dedicated mesh
        import jax as _jax
        from jax.sharding import Mesh

        devs = np.array(_jax.devices()[:Pn])
        mesh = Mesh(devs, ("pp",))

        def stage_fn(sp, x):
            return jnp.tanh(x @ sp["w"])

        def head_loss(hp, y, lbl):
            return jnp.mean((y @ hp["wo"] - lbl) ** 2)

        stacked = {"w": jnp.ones((v * Pn, Dd, Dd)) * 0.1}
        head = {"wo": jnp.ones((Dd, 3))}
        h_all = jnp.ones((M, mb, Dd))
        lbl = jnp.ones((M, mb, 3))
        jaxpr = jax.make_jaxpr(
            lambda st, hp, ha, lb:
            spmd_pipeline.pipeline_interleaved_1f1b_grads(
                stage_fn, head_loss, st, hp, ha, lb, mesh=mesh,
                num_stages=Pn, num_virtual=v))(stacked, head, h_all, lbl)
        text = str(jaxpr).replace(" ", "")
        ring_dim = 2 * v * Pn
        assert f"({ring_dim},{mb},{Dd})" in text, \
            "residual ring buffers of depth 2*v*pp expected"
        import re

        m_stash = re.findall(rf"\({M},{mb},{Dd}\)", text)
        assert len(m_stash) < 40, (
            f"too many [M,...] buffers ({len(m_stash)}) — VPP residuals "
            f"should live in the 2*v*pp ring")

    def test_vpp_bubble_smaller_than_plain_1f1b(self):
        """The defining property of VPP (reference
        pipeline_parallel.py:906): the interleaved schedule's total
        compute-units must be strictly fewer than plain 1F1B over
        v-chunk stages, for every v > 1."""
        from paddle_tpu.distributed.fleet.meta_parallel.pp_utils.spmd_pipeline \
            import interleaved_tick_count

        for Pn in (2, 4, 8):
            for v in (2, 3, 4):
                for M in (8, 16, 64):
                    vpp_units = interleaved_tick_count(M, Pn, v)  # 1 chunk/tick
                    plain_units = (M + 2 * Pn - 1) * v  # v chunks/tick
                    assert vpp_units < plain_units, (
                        f"P={Pn} v={v} M={M}: VPP {vpp_units} !< "
                        f"plain {plain_units}")
        # bubble (extra units beyond the ideal M*v) shrinks toward
        # plain/vpp ≈ v(2P-1)/(vP+P-1) ≈ 2v/(v+1) at scale
        vpp_bubble = interleaved_tick_count(64, 8, 4) - 64 * 4
        plain_bubble = (64 + 2 * 8 - 1) * 4 - 64 * 4
        assert vpp_bubble <= plain_bubble / 1.5, (
            f"bubble {vpp_bubble} vs plain {plain_bubble}")

    def test_vpp_formulas_reduce_to_plain_at_v1(self):
        from paddle_tpu.distributed.fleet.meta_parallel.pp_utils.spmd_pipeline \
            import interleaved_tick_count

        for Pn in (2, 4):
            for M in (4, 8, 16):
                assert interleaved_tick_count(M, Pn, 1) == M + 2 * Pn - 1

    def test_distributed_model_picks_interleave(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallelWithInterleave)

        model = _build(5, n_blocks=8, num_virtual=2)
        wrapped = fleet.distributed_model(model)
        assert isinstance(wrapped, PipelineParallelWithInterleave)
