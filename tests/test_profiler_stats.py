"""profiler.stats registry + dispatch/engine telemetry wiring.

Covers the framework-wide runtime-telemetry subsystem: metric
semantics (counter/gauge/histogram), the auto ``op::`` spans emitted by
eager dispatch under a profiler window, VJP-cache outcome counters,
zero-emission when no window is open, and the chrome-trace export
round-trip carrying both "X" spans and "C" counter events."""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.ops import dispatch
from paddle_tpu.profiler import Profiler, load_profiler_result, stats
from paddle_tpu.profiler.profiler import _SPANS


@pytest.fixture(autouse=True)
def _fresh_stats():
    stats.enable()
    stats.reset()
    yield
    stats.enable()


class TestMetricSemantics:
    def test_counter(self):
        c = stats.counter("t.counter")
        assert c.value == 0
        c.inc()
        c.inc(5)
        assert c.value == 6
        assert stats.counter("t.counter") is c  # get-or-create

    def test_gauge(self):
        g = stats.gauge("t.gauge")
        g.set(3.5)
        assert g.value == 3.5
        g.inc(2)
        g.dec()
        assert g.value == 4.5
        g.set(7)  # last write wins
        assert g.value == 7.0

    def test_histogram(self):
        h = stats.histogram("t.hist")
        for v in (1.0, 3.0, 8.0):
            h.observe(v)
        s = h.summary()
        assert s["count"] == 3
        assert s["min"] == 1.0 and s["max"] == 8.0
        np.testing.assert_allclose(s["avg"], 4.0)

    def test_snapshot_json_roundtrip_and_reset(self):
        stats.inc("t.snap", 2)
        stats.set_gauge("t.snapg", 1.5)
        stats.observe("t.snaph", 10.0)
        snap = stats.snapshot()
        # JSON-able end to end (bench.py embeds this into BENCH_*.json)
        again = json.loads(json.dumps(snap))
        assert again["counters"]["t.snap"] == 2
        assert again["gauges"]["t.snapg"] == 1.5
        assert again["histograms"]["t.snaph"]["count"] == 1
        stats.reset()
        snap2 = stats.snapshot()
        assert "t.snap" not in snap2["counters"]  # zeroed drop out
        assert stats.counter("t.snap").value == 0

    def test_disable_makes_mutations_noops(self):
        c = stats.counter("t.disabled")
        stats.disable()
        try:
            c.inc(100)
            stats.inc("t.disabled", 100)
            stats.set_gauge("t.disabled_g", 9)
            stats.observe("t.disabled_h", 9)
            with stats.timed("t.disabled_h"):
                pass
        finally:
            stats.enable()
        assert c.value == 0
        assert stats.gauge("t.disabled_g").value == 0
        assert stats.histogram("t.disabled_h").count == 0

    def test_timed_observes_microseconds(self):
        with stats.timed("t.timed_us"):
            pass
        h = stats.histogram("t.timed_us")
        assert h.count == 1
        assert 0 <= h.total < 1e6  # sane µs range for a no-op body

    def test_histogram_exports_buckets(self):
        """The power-of-2 buckets the docstring promises actually leave
        the process: summary()/snapshot() carry [upper_edge, count]
        pairs, so a retrace storm (mass in the big-edge buckets) is
        distinguishable from steady cache hits (mass at the bottom)."""
        h = stats.histogram("t.buckets")
        for v in (0.5, 1.5, 3.0, 100.0):
            h.observe(v)
        s = h.summary()
        assert s["buckets"] == [[1.0, 1], [2.0, 1], [4.0, 1], [128.0, 1]]
        # snapshot carries the same buckets (JSON-able)
        snap = json.loads(json.dumps(stats.snapshot()))
        assert snap["histograms"]["t.buckets"]["buckets"] == \
            [[1.0, 1], [2.0, 1], [4.0, 1], [128.0, 1]]

    def test_histogram_percentiles(self):
        h = stats.histogram("t.pct")
        # steady-state: 90 fast observations, 10 slow outliers
        for _ in range(90):
            h.observe(3.0)
        for _ in range(10):
            h.observe(1000.0)
        s = h.summary()
        # p50 lives in the fast bucket, p99 in the slow tail
        assert s["p50"] <= 4.0
        assert s["p99"] >= 512.0
        assert s["min"] <= s["p50"] <= s["p90"] <= s["p99"] <= s["max"]
        # direct API agrees with the summary view
        assert h.percentile(0.5) == s["p50"]
        assert stats.histogram("t.empty").percentile(0.5) is None

    def test_percentiles_clamped_by_min_max(self):
        h = stats.histogram("t.clamp")
        h.observe(5.0)   # single sample: every percentile IS the sample
        s = h.summary()
        assert s["p50"] == s["p90"] == s["p99"] == 5.0

    def test_small_count_percentiles_exact(self):
        """ISSUE 9 satellite: the reservoir makes small-count
        percentiles EXACT observed values, not power-of-2 bucket
        midpoints (a 7-request serve bench's p99 TTFT used to land on
        a bucket edge, off by ~2x)."""
        h = stats.histogram("t.exact")
        for v in (5.0, 6.0, 7.0, 8.0, 9.0, 10.0, 200.0):
            h.observe(v)
        s = h.summary()
        assert s["p50"] == 8.0          # the 4th of 7 observations
        assert s["p90"] == 200.0        # ceil(0.9*7)=7th
        assert s["p99"] == 200.0        # an OBSERVED value, not ~181
        assert h.percentile(0.5) == 8.0

    def test_reservoir_bounded_and_deterministic(self):
        """Beyond RESERVOIR_SIZE observations the sample set stays
        bounded and the seeded eviction makes two identical
        observation sequences summarize identically."""
        cap = stats.Histogram.RESERVOIR_SIZE
        n = cap + 1000

        def feed(name):
            h = stats.histogram(name)
            for i in range(n):
                h.observe(float(i % 97))
            return h

        ha, hb = feed("t.resa"), feed("t.resb")
        assert len(ha._samples) == cap
        assert ha.count == n            # count/buckets stay exact
        sa, sb = ha.summary(), hb.summary()
        assert sa["p50"] == sb["p50"]
        assert sa["p90"] == sb["p90"]
        assert sa["p99"] == sb["p99"]
        # the uniform sample keeps percentiles near truth (exact
        # percentiles of i % 97 are 48/87/95 at p50/p90/p99)
        assert abs(sa["p50"] - 48.0) <= 5.0
        assert sa["buckets"] == sb["buckets"]   # bucket export intact
        ha._reset()
        assert ha._samples == [] and ha.count == 0

    def test_snapshot_meta_stamps_rank(self):
        snap = stats.snapshot()
        assert snap["meta"]["process_index"] == 0
        assert snap["meta"]["process_count"] >= 1
        assert snap["meta"]["pid"] > 0


class TestDispatchTelemetry:
    def test_per_op_call_counters(self):
        before = stats.counter("op.matmul").value
        a = paddle.to_tensor(np.ones((4, 4), np.float32))
        _ = a @ a
        _ = a @ a
        assert stats.counter("op.matmul").value == before + 2

    def test_auto_spans_only_inside_profiler_window(self):
        a = paddle.to_tensor(np.ones((4, 4), np.float32))
        assert not _SPANS.enabled
        _ = a @ a                       # no window open
        assert _SPANS.events == []      # zero span records emitted
        with Profiler(on_trace_ready=lambda p: None) as prof:
            _ = a @ a
        agg = prof.summary()
        assert "op::matmul" in agg
        assert agg["op::matmul"][1] == 1
        assert not _SPANS.enabled       # window closed again

    def test_vjp_cache_hit_counters(self):
        dispatch._VJP_CACHE.clear()
        dispatch._VJP_SEEN.clear()
        dispatch._VJP_BLOCK.clear()
        x_np = np.linspace(-1, 1, 8).astype(np.float32)

        def run():
            x = paddle.to_tensor(x_np, stop_gradient=False)
            y = paddle.tanh(x)
            y.sum().backward()

        run()   # sighting 1: miss
        run()   # sighting 2: miss + admit
        run()   # hit
        snap = stats.snapshot()["counters"]
        assert snap["vjp_cache.hit"] >= 1
        assert snap["vjp_cache.miss"] >= 2
        assert snap["vjp_cache.admit"] >= 1
        rate = stats.vjp_cache_hit_rate()
        assert rate is not None and 0 < rate < 1
        # the uncached traces observed wall time into the histogram
        assert stats.histogram("compile.vjp_trace_us").count >= 2

    def test_registry_op_call_counts(self):
        from paddle_tpu.ops.registry import op_call_counts

        a = paddle.to_tensor(np.ones((3, 3), np.float32))
        _ = a + a
        counts = op_call_counts()
        assert counts.get("add", 0) >= 1
        full = op_call_counts(include_unused=True)
        assert len(full) > len(counts)  # unused registered ops at 0
        assert all(v == 0 for k, v in full.items() if k not in counts)

    def test_backward_sweep_counters(self):
        before_sweeps = stats.counter("autograd.sweeps").value
        x = paddle.to_tensor(np.ones((3,), np.float32),
                             stop_gradient=False)
        ((x * x).sum()).backward()
        assert stats.counter("autograd.sweeps").value == before_sweeps + 1
        assert stats.counter("autograd.nodes").value >= 2  # mul + sum

    def test_backward_span_recorded_in_window(self):
        x = paddle.to_tensor(np.ones((3,), np.float32),
                             stop_gradient=False)
        with Profiler(on_trace_ready=lambda p: None) as prof:
            (x * x).sum().backward()
        agg = prof.summary()
        assert "autograd::backward" in agg


class TestChromeTraceExport:
    def test_counter_events_round_trip(self, tmp_path):
        a = paddle.to_tensor(np.ones((4, 4), np.float32))
        with Profiler(on_trace_ready=lambda p: None) as prof:
            for _ in range(3):
                _ = a @ a
            prof.step()
        path = prof.export(str(tmp_path / "trace.json"))
        tr = load_profiler_result(path)
        evs = tr["traceEvents"]
        x_names = {e["name"] for e in evs if e["ph"] == "X"}
        c_events = [e for e in evs if e["ph"] == "C"]
        assert "op::matmul" in x_names
        assert c_events, "no counter events exported"
        by_name = {e["name"] for e in c_events}
        assert any(n.startswith("op.") for n in by_name)
        for e in c_events:
            assert isinstance(e["args"]["value"], (int, float))
        # step() sampled mid-window: at least two samples per counter
        matmul_samples = [e for e in c_events if e["name"] == "op.matmul"]
        assert len(matmul_samples) >= 2

    def test_summary_has_max_column_and_cache_section(self, capsys):
        x = paddle.to_tensor(np.linspace(-1, 1, 8).astype(np.float32),
                             stop_gradient=False)
        with Profiler(on_trace_ready=lambda p: None) as prof:
            for _ in range(3):
                y = paddle.tanh(x)
                y.sum().backward()
        prof.summary()
        out = capsys.readouterr().out
        assert "Max(ms)" in out
        assert "vjp_cache hit rate" in out


class TestInferenceTelemetry:
    def test_round_pool_pages_caps_inflation(self):
        from paddle_tpu.inference.engine import _round_pool_pages
        from paddle_tpu.nn.functional.paged_attention import (
            stream_chunk_pages)

        # the ADVICE r5 case: 25 requested pages at page_size=4 must not
        # balloon to 256 (a full 1024-token chunk); the cap keeps it
        # within 2x of the request
        assert _round_pool_pages(25, 4) <= 64
        # the rounded pool still divides into stream chunks exactly
        for n, ps in ((25, 4), (7, 16), (100, 16), (1040, 16)):
            pool = _round_pool_pages(n, ps)
            assert pool >= n
            quantum = min(stream_chunk_pages(ps), pool)
            # some chunk size <= the full target divides the pool
            assert any(pool % cp == 0
                       for cp in range(1, quantum + 1))
        # large pools keep the old full-chunk rounding
        assert _round_pool_pages(1040, 16) == 1088

    def test_generate_sets_pool_gauges_and_decode_counters(self):
        from paddle_tpu.inference import FusedCausalLM, GenerationEngine

        paddle.seed(0)
        lm = FusedCausalLM(vocab_size=32, embed_dim=16, num_heads=2,
                           dim_feedforward=32, num_layers=1,
                           max_position=64)
        eng = GenerationEngine(lm, page_size=4, max_length=32,
                               decode_chunk=4)
        before = stats.counter("inference.decode_steps").value
        out = eng.generate(np.zeros((2, 4), np.int64), max_new_tokens=8)
        assert out.shape == (2, 12)
        snap = stats.snapshot()
        assert snap["gauges"]["inference.pool_pages"] >= \
            snap["gauges"]["inference.pool_pages_requested"]
        assert stats.counter("inference.decode_steps").value > before
        assert stats.counter("inference.prefills").value >= 1


class TestCollectiveTelemetry:
    def test_all_reduce_counts_calls_and_bytes(self):
        import paddle_tpu.distributed as dist

        t = paddle.to_tensor(np.ones((8,), np.float32))
        before = stats.counter("dist.all_reduce.calls").value
        dist.all_reduce(t)
        assert stats.counter("dist.all_reduce.calls").value == before + 1
        assert stats.counter("dist.all_reduce.bytes").value >= 32


class TestNamingConventions:
    def test_registered_names_match_conventions(self):
        """Lint the LIVE registry: every metric any layer registered in
        this process must use a documented namespace
        (stats.CONVENTION_PREFIXES / README conventions table) — fleet
        folding (tools/trace_merge.py) and the telemetry gate
        (tools/bench_gate.py) key on these prefixes."""
        # drive a cross-section of instrumented layers so the registry
        # is populated even when this test runs alone
        x = paddle.to_tensor(np.linspace(-1, 1, 8).astype(np.float32),
                             stop_gradient=False)
        (paddle.tanh(x).sum()).backward()
        from paddle_tpu.profiler import memory, roofline

        memory.sample()
        roofline.record_program("roofline.lint", flops=1.0,
                                bytes_accessed=1.0)
        # the A8W8 serving counters (engine dispatch layer +
        # QuantedLinear(a8w8=True)) live in their own namespace
        assert "quant." in stats.CONVENTION_PREFIXES
        stats.inc("quant.act_quant_calls")
        stats.inc("quant.a8w8_matmuls")

        names = (list(stats._COUNTERS) + list(stats._GAUGES)
                 + list(stats._HISTOGRAMS))
        assert names
        offenders = [n for n in names
                     if not n.startswith(stats.CONVENTION_PREFIXES)]
        assert not offenders, (
            f"metrics outside documented namespaces "
            f"{stats.CONVENTION_PREFIXES}: {offenders}")
