"""Quantization framework parity: QuantConfig resolution, factories,
quanter registry, PTQ/QAT of LeNet -> int8 inference predictor.

Reference parity targets: python/paddle/quantization/{config.py,
factory.py, ptq.py, qat.py, quanters/abs_max.py}.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (
    QAT, PTQ, AbsmaxObserver, FakeQuanterWithAbsMaxObserver,
    MovingAverageObserver, ObserverFactory, QuantConfig, QuantedLinear,
    QuanterFactory, SingleLayerConfig, quanter)


def _lenet():
    from paddle_tpu.vision.models import LeNet

    paddle.seed(3)
    return LeNet(num_classes=10)


def _calib_data(n=4):
    rng = np.random.RandomState(0)
    return paddle.to_tensor(rng.randn(n, 1, 28, 28).astype(np.float32))


class TestFactories:
    def test_quanter_factory_delays_construction(self):
        f = FakeQuanterWithAbsMaxObserver(moving_rate=0.8)
        assert isinstance(f, QuanterFactory)
        a, b = f._instance(), f._instance()
        assert a is not b
        assert a.momentum == 0.8

    def test_quanter_decorator_registers(self):
        from paddle_tpu.quantization.factory import QUANTER_REGISTRY

        assert "FakeQuanterWithAbsMaxObserver" in QUANTER_REGISTRY

        @quanter("MyTestQuanter")
        class MyTestQuanterLayer(AbsmaxObserver):
            pass

        assert "MyTestQuanter" in QUANTER_REGISTRY
        import paddle_tpu.quantization.factory  # registry module
        f = QUANTER_REGISTRY["MyTestQuanter"](quant_bits=4)
        assert f._instance().quant_bits == 4


class TestQuantConfigResolution:
    def test_type_config(self):
        cfg = QuantConfig()
        cfg.add_type_config(nn.Linear,
                            activation=lambda: MovingAverageObserver(),
                            weight=lambda: AbsmaxObserver())
        lin, conv = nn.Linear(4, 4), nn.Conv2D(1, 1, 3)
        assert cfg._get_config_by_layer("x", lin) is not None
        assert cfg._get_config_by_layer("y", conv) is None

    def test_name_config_beats_type(self):
        cfg = QuantConfig()
        marker = lambda: AbsmaxObserver(quant_bits=4)  # noqa: E731
        cfg.add_type_config(nn.Linear, weight=lambda: AbsmaxObserver())
        cfg.add_name_config("fc2", weight=marker)
        lin = nn.Linear(4, 4)
        got = cfg._get_config_by_layer("fc2", lin)
        from paddle_tpu.quantization.factory import instantiate

        assert instantiate(got.weight).quant_bits == 4

    def test_layer_config_beats_all(self):
        cfg = QuantConfig()
        lin = nn.Linear(4, 4)
        cfg.add_name_config("fc", weight=lambda: AbsmaxObserver(8))
        cfg.add_layer_config(lin, weight=lambda: AbsmaxObserver(4))
        from paddle_tpu.quantization.factory import instantiate

        got = cfg._get_config_by_layer("fc", lin)
        assert instantiate(got.weight).quant_bits == 4

    def test_qat_layer_mapping_registry(self):
        cfg = QuantConfig()
        assert nn.Linear in cfg.qat_layer_mappings

        class Custom(nn.Layer):
            pass

        class CustomQAT(nn.Layer):
            pass

        cfg.add_qat_layer_mapping(Custom, CustomQAT)
        assert cfg.qat_layer_mappings[Custom] is CustomQAT

    def test_customized_leaves_stop_descent(self):
        cfg = QuantConfig()

        class Blockish(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

        cfg.add_customized_leaves(Blockish)
        m = nn.Sequential(Blockish())
        PTQ(cfg).quantize(m)
        # the inner Linear must NOT have been wrapped
        from paddle_tpu.quantization import _ObservedLinear

        inner = list(m.named_sublayers())
        assert not any(isinstance(l, _ObservedLinear) for _, l in inner)


class TestLeNetPTQ:
    def test_ptq_lenet_to_predictor(self, tmp_path):
        """PTQ LeNet -> quantized predictor matches fp32 within
        tolerance (the reference's PTQ->save_inference_model flow)."""
        from paddle_tpu.inference import Config, create_predictor
        from paddle_tpu.static.input_spec import InputSpec

        net = _lenet()
        x = _calib_data()
        fp32_out = net(x).numpy()

        ptq = PTQ()
        net = ptq.quantize(net)
        for _ in range(3):   # calibration passes
            net(x)
        net = ptq.convert(net)
        assert any(isinstance(l, QuantedLinear)
                   for _, l in net.named_sublayers())
        q_out = net(x).numpy()
        # int8 weight-only: logits close to fp32
        assert np.mean(np.abs(q_out - fp32_out)) < 0.1 * (
            np.mean(np.abs(fp32_out)) + 1e-6)
        np.testing.assert_array_equal(q_out.argmax(-1),
                                      fp32_out.argmax(-1))

        path = str(tmp_path / "lenet_int8")
        paddle.jit.save(net, path,
                        input_spec=[InputSpec([4, 1, 28, 28], "float32")])
        pred = create_predictor(Config(path))
        name = pred.get_input_names()[0]
        pred.get_input_handle(name).copy_from_cpu(np.asarray(x.numpy()))
        assert pred.run()
        got = pred.get_output_handle(
            pred.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(got, q_out, rtol=1e-5, atol=1e-5)


class TestLeNetQAT:
    def test_qat_lenet_trains_and_converts(self, tmp_path):
        from paddle_tpu.inference import Config, create_predictor
        from paddle_tpu.static.input_spec import InputSpec
        import paddle_tpu.nn.functional as F

        net = _lenet()
        cfg = QuantConfig(
            activation=FakeQuanterWithAbsMaxObserver(moving_rate=0.9),
            weight=FakeQuanterWithAbsMaxObserver(moving_rate=0.9))
        qat = QAT(cfg)
        net = qat.quantize(net)

        opt = paddle.optimizer.Adam(1e-3, parameters=net.parameters())
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(8, 1, 28, 28).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 10, (8,)))
        losses = []
        for _ in range(5):
            loss = F.cross_entropy(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses  # STE lets QAT train

        net.eval()
        fake_out = net(x).numpy()
        net = qat.convert(net)
        assert any(isinstance(l, QuantedLinear)
                   for _, l in net.named_sublayers())
        q_out = net(x).numpy()
        # converted int8 model tracks the fake-quant model
        np.testing.assert_allclose(
            q_out.argmax(-1), fake_out.argmax(-1))

        path = str(tmp_path / "lenet_qat_int8")
        paddle.jit.save(net, path,
                        input_spec=[InputSpec([8, 1, 28, 28], "float32")])
        pred = create_predictor(Config(path))
        name = pred.get_input_names()[0]
        pred.get_input_handle(name).copy_from_cpu(np.asarray(x.numpy()))
        assert pred.run()
        got = pred.get_output_handle(
            pred.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(got, q_out, rtol=1e-4, atol=1e-4)
