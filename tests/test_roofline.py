"""Device-level observability: roofline math, HBM telemetry, fleet merge.

Covers PR-2's device observability layer: XLA cost-model extraction
(``compiled.cost_analysis()`` → flops/bytes), achieved-rate /
MFU / bandwidth-utilization arithmetic against the (env-overridable)
peak table, auto-recording from the jit layers, HBM memory sampling,
and the trace_merge fold (rank traces → one timeline; rank snapshots →
one fleet snapshot)."""
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.profiler import memory, roofline, stats

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_trace_merge():
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import trace_merge
    finally:
        sys.path.pop(0)
    return trace_merge


@pytest.fixture(autouse=True)
def _fresh():
    stats.enable()
    stats.reset()
    roofline.reset()
    yield
    roofline.reset()


class TestCostModel:
    def test_matmul_flops_matches_2mnk(self):
        """XLA's CPU cost model reports a matmul as exactly 2*M*N*K
        flops — the analytic anchor the whole roofline rests on."""
        M, K, N = 64, 128, 32
        f = jax.jit(lambda a, b: a @ b)
        compiled = f.lower(jnp.ones((M, K)), jnp.ones((K, N))).compile()
        cost = roofline.program_cost(compiled)
        assert cost is not None
        assert cost["flops"] == pytest.approx(2 * M * N * K, rel=1e-6)
        # bytes accessed covers at least the operands + the result
        min_bytes = 4 * (M * K + K * N + M * N)
        assert cost["bytes"] >= min_bytes

    def test_record_program_sets_compile_gauges(self):
        f = jax.jit(lambda a: a * 2.0)
        compiled = f.lower(jnp.ones((16, 16))).compile()
        cost = roofline.record_program("t.prog", compiled)
        assert cost["flops"] > 0
        assert stats.gauge("compile.flops").value == cost["flops"]
        assert stats.gauge("compile.bytes").value == cost["bytes"]
        assert "t.prog" in roofline.report()

    def test_analyze_computes_rates_from_cost(self, monkeypatch):
        """MFU and bandwidth utilization are DERIVED from the recorded
        cost + wall time + peak table — pin the peaks via env and check
        the arithmetic end to end."""
        monkeypatch.setenv(roofline.ENV_PEAK_FLOPS, "1e12")
        monkeypatch.setenv(roofline.ENV_PEAK_HBM_BW, "1e11")
        roofline.record_program("t.prog", flops=2e9, bytes_accessed=4e8)
        res = roofline.analyze("t.prog", wall_s=1e-3)
        assert res.achieved_flops_per_s == pytest.approx(2e12)
        assert res.achieved_bytes_per_s == pytest.approx(4e11)
        assert res.mfu == pytest.approx(2.0)       # 2e12 / 1e12
        assert res.bw_util == pytest.approx(4.0)   # 4e11 / 1e11
        # gauges published for the stats snapshot / chrome counters
        assert stats.gauge("roofline.mfu").value == pytest.approx(2.0)
        assert stats.gauge("roofline.bw_util").value == pytest.approx(4.0)
        # the formatted line carries the four figures
        line = res.format()
        assert "MFU" in line and "GB/s" in line

    def test_analyze_unknown_program_returns_none(self):
        assert roofline.analyze("t.nope", 1.0) is None
        assert roofline.analyze("t.nope", 0.0) is None

    def test_device_peaks_env_override(self, monkeypatch):
        monkeypatch.setenv(roofline.ENV_PEAK_FLOPS, "5e12")
        monkeypatch.setenv(roofline.ENV_PEAK_HBM_BW, "7e11")
        assert roofline.device_peaks() == (5e12, 7e11)

    def test_device_peaks_cpu_fallback(self):
        flops, bw = roofline.device_peaks(jax.devices()[0])
        assert flops == roofline.CPU_PEAK[0]
        assert bw == roofline.CPU_PEAK[1]


class TestJitLayerAutoRecording:
    def test_to_static_records_cost_and_roofline(self):
        M = 32

        @paddle.jit.to_static
        def f(x):
            return x @ x

        x = paddle.to_tensor(np.ones((M, M), np.float32))
        f(x)
        rep = roofline.report()
        assert "to_static[f]" in rep
        # the matmul dominates: flops ≈ 2*M^3 (XLA may fold a few
        # elementwise ops on top)
        assert rep["to_static[f]"]["flops"] >= 2 * M ** 3
        # the wrapped call observed wall time → rates present
        assert "mfu" in rep["to_static[f]"]
        assert stats.gauge("compile.flops").value > 0
        assert stats.histogram("roofline.wall_us").count >= 1

    def test_train_step_roofline(self):
        import paddle_tpu.nn as nn

        model = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        step = paddle.jit.TrainStep(
            model, lambda out, lbl: ((out - lbl) ** 2).mean(), opt)
        inp = paddle.to_tensor(np.ones((2, 8), np.float32))
        lbl = paddle.to_tensor(np.zeros((2, 4), np.float32))
        step([inp], [lbl])
        res = step.roofline(1e-3)
        assert res is not None
        assert res.flops > 0 and res.bytes > 0
        assert res.achieved_flops_per_s == pytest.approx(
            res.flops / 1e-3)

    def test_decode_engine_records_decode_cost(self):
        from paddle_tpu.inference import FusedCausalLM, GenerationEngine

        paddle.seed(0)
        lm = FusedCausalLM(vocab_size=32, embed_dim=16, num_heads=2,
                           dim_feedforward=32, num_layers=1,
                           max_position=64)
        eng = GenerationEngine(lm, page_size=4, max_length=32,
                               decode_chunk=4)
        out = eng.generate(np.zeros((2, 4), np.int64), max_new_tokens=8)
        assert out.shape == (2, 12)
        rep = roofline.report()
        assert "prefill" in rep
        # grouped weight-stream decode (the r6 default) reports under
        # decode.<dtype>_grouped[k=*]; ungrouped under decode[k=*]
        decode_names = [n for n in rep if n.startswith("decode")
                        and "[k=" in n]
        assert decode_names
        # the decode chunk was analyzed against an honestly synced wall
        # time, so achieved rates are present
        assert all("bw_util" in rep[n] for n in decode_names)


class TestMemoryTelemetry:
    def test_sample_smoke(self):
        x = paddle.to_tensor(np.ones((128, 128), np.float32))  # noqa: F841
        out = memory.sample()
        # CPU PJRT exposes no allocator counters — keys exist, zeros ok
        assert set(out) >= {"bytes_in_use", "peak_bytes_in_use",
                            "bytes_limit"}
        # ...but the live-array census always works
        assert out["live"]["count"] >= 1
        assert out["live"]["bytes"] >= 128 * 128 * 4
        assert stats.gauge("hbm.live_buffers").value >= 1
        assert stats.gauge("hbm.live_bytes").value >= 128 * 128 * 4
        assert "float32" in out["live"]["by_dtype"]
        assert out["live"]["top_shapes"]
        # JSON-able end to end (rides snapshots into BENCH files)
        json.dumps(out)

    def test_watermark_falls_back_to_census_on_cpu(self):
        x = paddle.to_tensor(np.ones((64,), np.float32))  # noqa: F841
        wm = memory.watermark()
        assert wm is not None
        assert wm["source"] in ("pjrt", "live_arrays")
        assert wm["bytes_in_use"] > 0

    def test_profiler_samples_hbm_gauges(self):
        from paddle_tpu.profiler import Profiler

        a = paddle.to_tensor(np.ones((32, 32), np.float32))
        with Profiler(on_trace_ready=lambda p: None) as prof:
            _ = a @ a
            prof.step()
        hbm_events = [e for e in prof._events
                      if e.get("ph") == "C"
                      and e["name"].startswith("hbm.")]
        assert hbm_events, "no hbm.* counter events sampled"


class TestTraceMerge:
    def _synthetic_rank(self, tmp_path, rank, pid):
        trace = {
            "traceEvents": [
                {"name": "op::matmul", "ph": "X", "pid": pid,
                 "tid": 1, "ts": 10.0 * rank, "dur": 5.0,
                 "cat": "host"},
                {"name": "op.matmul", "ph": "C", "pid": pid, "tid": 0,
                 "ts": 1.0, "cat": "counter",
                 "args": {"value": rank + 1}},
            ],
            "displayTimeUnit": "ms",
            "metadata": {"process_index": rank, "pid": pid},
        }
        snap = {
            "meta": {"process_index": rank, "process_count": 2,
                     "pid": pid},
            "counters": {"dist.all_reduce.calls": 3 + rank,
                         "op.matmul": 10 * (rank + 1)},
            "gauges": {"dist.process_index": rank,
                       "hbm.bytes_in_use": 100.0 * (rank + 1)},
            "histograms": {"compile.vjp_trace_us": {
                "count": 2, "total": 30.0 * (rank + 1),
                "avg": 15.0 * (rank + 1),
                "min": 10.0 * (rank + 1), "max": 20.0 * (rank + 1),
                "p50": 15.0, "p90": 20.0, "p99": 20.0,
                "buckets": [[16.0, 1], [32.0, 1]],
            }},
        }
        (tmp_path / f"trace_rank{rank}.json").write_text(
            json.dumps(trace))
        (tmp_path / f"stats_rank{rank}.json").write_text(
            json.dumps(snap))

    def test_round_trip_two_ranks(self, tmp_path):
        """Synthetic 2-rank run dir → one merged timeline + one folded
        fleet snapshot with sum/max/bucket-fold semantics."""
        trace_merge = _load_trace_merge()
        # both ranks landed the SAME host pid — the collision the
        # rank-stamping exists to disambiguate
        self._synthetic_rank(tmp_path, 0, pid=4242)
        self._synthetic_rank(tmp_path, 1, pid=4242)

        rc = trace_merge.main([str(tmp_path)])
        assert rc == 0

        merged = json.load(open(tmp_path / "merged_trace.json"))
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert pids == {0, 1}
        assert merged["metadata"]["ranks"] == [0, 1]
        spans = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert {e["pid"] for e in spans} == {0, 1}

        fleet = json.load(open(tmp_path / "fleet_stats.json"))
        assert fleet["counters"]["dist.all_reduce.calls"] == 7  # 3 + 4
        assert fleet["counters"]["op.matmul"] == 30
        assert fleet["gauges"]["dist.process_index"] == 1        # max
        assert fleet["gauges"]["hbm.bytes_in_use"] == 200.0      # max
        h = fleet["histograms"]["compile.vjp_trace_us"]
        assert h["count"] == 4
        assert h["total"] == pytest.approx(90.0)
        assert h["min"] == 10.0 and h["max"] == 40.0
        assert h["buckets"] == [[16.0, 2], [32.0, 2]]
        assert h["p50"] is not None and h["p99"] is not None
        assert h["min"] <= h["p50"] <= h["p90"] <= h["p99"] <= h["max"]

    def test_missing_dir_is_an_error(self, tmp_path):
        trace_merge = _load_trace_merge()
        assert trace_merge.main([str(tmp_path / "empty")]) == 2


class TestBenchGate:
    def _doc(self, hit_rate, jit_trace, mfu):
        return {"metric": "x", "telemetry": {
            "counters": {"jit.trace": jit_trace},
            "gauges": {"roofline.mfu": mfu},
            "histograms": {},
            "vjp_cache_hit_rate": hit_rate,
        }}

    def test_pass_and_fail_directions(self, tmp_path):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import bench_gate
        finally:
            sys.path.pop(0)
        prev = self._doc(hit_rate=0.95, jit_trace=10, mfu=0.50)
        same = self._doc(hit_rate=0.95, jit_trace=10, mfu=0.52)
        bad, n = bench_gate.gate(prev, same)
        assert n >= 3 and bad == []
        # retrace storm: jit.trace regresses UP
        storm = self._doc(hit_rate=0.95, jit_trace=40, mfu=0.50)
        bad, _ = bench_gate.gate(prev, storm)
        assert any("jit.trace" in b for b in bad)
        # utilization collapse: mfu regresses DOWN
        slow = self._doc(hit_rate=0.95, jit_trace=10, mfu=0.20)
        bad, _ = bench_gate.gate(prev, slow)
        assert any("roofline.mfu" in b for b in bad)
        # hit-rate collapse
        cold = self._doc(hit_rate=0.40, jit_trace=10, mfu=0.50)
        bad, _ = bench_gate.gate(prev, cold)
        assert any("vjp_cache_hit_rate" in b for b in bad)

    def test_root_scalar_serving_rungs_gate(self):
        """decode_*_tokens_per_sec / *_pct_of_hbm_roofline live at the
        bench JSON root (no telemetry block) — the gate must still
        catch a throughput collapse there, direction 'down'."""
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import bench_gate
        finally:
            sys.path.pop(0)
        prev = {"decode_a8w8_tokens_per_sec": 5000.0,
                "decode_a8w8_pct_of_hbm_roofline": 52.0}
        ok = {"decode_a8w8_tokens_per_sec": 5100.0,
              "decode_a8w8_pct_of_hbm_roofline": 53.0}
        bad_doc = {"decode_a8w8_tokens_per_sec": 3000.0,
                   "decode_a8w8_pct_of_hbm_roofline": 30.0}
        bad, n = bench_gate.gate(prev, ok)
        assert n >= 2 and bad == []
        bad, _ = bench_gate.gate(prev, bad_doc)
        assert any("decode_a8w8_tokens_per_sec" in b for b in bad)
        assert any("decode_a8w8_pct_of_hbm_roofline" in b for b in bad)
        # a FASTER run must not trip the 'down' gate
        bad, _ = bench_gate.gate(bad_doc, prev)
        assert bad == []

    def test_cli_round_trip(self, tmp_path):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import bench_gate
        finally:
            sys.path.pop(0)
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps(self._doc(0.9, 10, 0.5)))
        b.write_text(json.dumps(self._doc(0.9, 11, 0.5)))
        assert bench_gate.main([str(a), str(b)]) == 0
        b.write_text(json.dumps(self._doc(0.9, 100, 0.5)))
        assert bench_gate.main([str(a), str(b)]) == 1
