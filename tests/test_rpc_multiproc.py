"""paddle.distributed.rpc over the coordination KV (reference:
python/paddle/distributed/rpc/rpc.py; C++ paddle/fluid/distributed/rpc).
Two localhost processes: sync/async calls both directions, remote
exception propagation, worker-info registry, shutdown."""
import os
import socket
import subprocess
import sys
import textwrap

WORKER = textwrap.dedent("""
    import os
    for var in list(os.environ):
        if var.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            os.environ.pop(var)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import rpc

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    rpc.init_rpc(f"worker{rank}", rank=rank, world_size=2)

    infos = rpc.get_all_worker_infos()
    assert sorted(i.name for i in infos) == ["worker0", "worker1"], infos
    assert rpc.get_worker_info("worker1").rank == 1

    def add(a, b):
        return a + b

    def boom():
        raise ValueError("kaboom")

    peer = f"worker{1 - rank}"
    # sync both directions
    assert rpc.rpc_sync(peer, add, args=(2, 3)) == 5
    # async + numpy payload
    fut = rpc.rpc_async(peer, np.arange, args=(4,))
    np.testing.assert_array_equal(fut.wait(), np.arange(4))
    # remote exception propagates
    try:
        rpc.rpc_sync(peer, boom)
    except RuntimeError as e:
        assert "kaboom" in str(e)
    else:
        raise AssertionError("expected remote exception")
    # barrier before shutdown: a fast rank must not tear down its inbox
    # while the peer's last request is still in flight
    from jax._src import distributed as _dist
    _dist.global_state.client.wait_at_barrier("rpc_done_1", 60000)
    rpc.shutdown()
    print(f"RPC_RANK{rank}_OK")
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _run_cluster(script, port, repo):
    procs = []
    for rank in range(2):
        # strip stale distributed env from earlier tests in the session
        # (e.g. launch tests export PADDLE_TRAINER_ENDPOINTS)
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("PADDLE_", "MASTER_", "FLAGS_"))}
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
            "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = [p.communicate(timeout=600) for p in procs]
    return procs, results


def test_two_process_rpc(tmp_path):
    script = tmp_path / "rpc_worker.py"
    script.write_text(WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    last_err = ""
    for attempt in range(2):  # retry once: free-port races happen
        procs, results = _run_cluster(script, _free_port(), repo)
        if all(p.returncode == 0 for p in procs) and all(
                f"RPC_RANK{r}_OK" in out
                for r, (out, _) in enumerate(results)):
            return
        last_err = "\n".join(err[-1500:] for _, err in results)
    raise AssertionError(f"rpc cluster failed twice:\n{last_err}")


REINIT_WORKER = WORKER.replace(
    'print(f"RPC_RANK{rank}_OK")',
    '''# re-init after shutdown: the persisted inbox counter must not
# strand the fresh inbox thread (round-3 review fix)
rpc.init_rpc(f"worker{rank}", rank=rank, world_size=2)
assert rpc.rpc_sync(peer, add, args=(10, 20)) == 30
# rpc_async timeout is honored on the Future
fut = rpc.rpc_async(peer, add, args=(1, 1), timeout=30)
assert fut.wait() == 2
_dist.global_state.client.wait_at_barrier("rpc_done_2", 60000)
rpc.shutdown()
print(f"RPC_RANK{rank}_OK")''')


def test_rpc_reinit_after_shutdown(tmp_path):
    script = tmp_path / "rpc_reinit_worker.py"
    script.write_text(REINIT_WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    last_err = ""
    for attempt in range(2):
        procs, results = _run_cluster(script, _free_port(), repo)
        if all(p.returncode == 0 for p in procs) and all(
                f"RPC_RANK{r}_OK" in out
                for r, (out, _) in enumerate(results)):
            return
        last_err = "\n".join(err[-1500:] for _, err in results)
    raise AssertionError(f"rpc reinit cluster failed twice:\n{last_err}")
