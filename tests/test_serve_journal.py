"""Serving observability (ISSUE 9): request-lifecycle flight recorder,
SLO goodput monitor, crash-dump forensics, and live introspection.

Tier-1 acceptance pins:
- event-order invariant for a preempted request: its journal lane
  reads admitted → … → preempt → queued → admitted → … → finish, and
  ``tools/serve_top.py`` renders that full timeline from the journal;
- crash-dump-on-exception: an injected ``step()`` raise leaves a JSONL
  artifact carrying the event tail + ``stats.snapshot()`` + every
  still-unserved request (and bumps ``serving.unserved``);
- goodput arithmetic: ``slo.goodput``/burn-rate match hand-computed
  verdicts;
- disabled-journal overhead: with ``FLAGS_serve_journal`` off the
  scheduler holds no recorder and ``FlightRecorder.record`` is never
  called from ``step()``;
- chrome-trace export round-trips through ``tools/trace_merge.py``
  with rank-stamped request lanes.
"""
import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import set_flags
from paddle_tpu.inference import FusedCausalLM
from paddle_tpu.profiler import stats
from paddle_tpu.serving import (FlightRecorder, Request, ServingEngine,
                                SLOConfig, SLOMonitor)
from paddle_tpu.serving import journal as journal_mod
from paddle_tpu.serving.journal import chrome_trace, load_jsonl

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _journal_flags():
    """Every test starts from the default flag state and restores it."""
    set_flags({"serve_journal": True, "serve_journal_events": 4096,
               "serve_journal_dir": ""})
    yield
    set_flags({"serve_journal": True, "serve_journal_events": 4096,
               "serve_journal_dir": ""})


def _model(seed=7, max_position=256):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=max_position)


def _tools(name):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


@pytest.fixture(scope="module")
def pressure_serve():
    """The PR 8 pool-pressure repro (16-page pool, three concurrent
    24-token decoders) with the journal on: guarantees preemptions,
    so one run feeds the event-order, serve_top, and chrome-trace
    tests."""
    set_flags({"serve_journal": True})
    eng = ServingEngine(_model(), max_batch=3, page_size=4,
                        max_length=64, decode_chunk=2, num_pages=15,
                        slo=SLOConfig(prefill_chunk=8))
    rng = np.random.RandomState(29)
    prompts = [rng.randint(0, 64, (16,)) for _ in range(3)]
    rids = [eng.submit(p, max_new_tokens=24) for p in prompts]
    done = eng.run()
    assert sorted(r.id for r in done) == sorted(rids)
    return eng, rids, done


class TestFlightRecorder:
    def test_ring_bounds_and_drop_accounting(self):
        j = FlightRecorder(capacity=8)
        for i in range(20):
            j.record("submit", rid=i)
        evs = j.events()
        assert len(evs) == 8
        assert [e["rid"] for e in evs] == list(range(12, 20))
        assert [e["seq"] for e in evs] == list(range(12, 20))
        assert j.recorded == 20 and j.dropped == 12
        assert j.tail(3) == evs[-3:]

    def test_extra_fields_flatten_into_events(self):
        j = FlightRecorder()
        j.record("admitted", rid=3, slot=1, extra={"prefix_pages": 4})
        (e,) = j.events()
        assert e["ev"] == "admitted" and e["rid"] == 3
        assert e["slot"] == 1 and e["prefix_pages"] == 4
        assert j.events(rid=99) == []

    def test_clear_restarts_sequence(self):
        j = FlightRecorder(capacity=4)
        j.record("submit", rid=0)
        j.clear()
        assert j.events() == [] and j.recorded == 0
        j.record("submit", rid=1)
        assert j.events()[0]["seq"] == 0

    def test_dump_and_load_jsonl(self, tmp_path):
        j = FlightRecorder()
        j.record("submit", rid=0, extra={"prompt_len": 5})
        j.record("finish", rid=0, slot=2, extra={"n_tokens": 3})
        p = j.dump_jsonl(str(tmp_path / "j.jsonl"))
        events, extras = load_jsonl(p)
        assert [e["ev"] for e in events] == ["submit", "finish"]
        assert events[1]["n_tokens"] == 3 and extras == {}


class TestLifecycleEvents:
    def test_single_request_canonical_order(self):
        """A plain request's lane reads submit → queued → admitted →
        prefill_chunk+ → first_token → decode → finish, with the
        schema fields (prefix_pages, chunk c/pos, ttft, verdict)."""
        eng = ServingEngine(_model(), max_batch=2, page_size=4,
                            max_length=64, decode_chunk=2,
                            slo=SLOConfig(prefill_chunk=8))
        rng = np.random.RandomState(3)
        rid = eng.submit(rng.randint(0, 64, (12,)), max_new_tokens=4)
        eng.run()
        evs = eng.journal.events(rid)
        names = [e["ev"] for e in evs]
        assert names[:3] == ["submit", "queued", "admitted"]
        chunks = [e for e in evs if e["ev"] == "prefill_chunk"]
        assert len(chunks) == 2                 # 12 tokens / chunk 8
        assert chunks[0]["c"] == 8 and chunks[0]["pos"] == 8
        assert chunks[1]["pos"] == 12
        assert names[-1] == "finish"
        i_ft = names.index("first_token")
        assert names[i_ft + 1] == "decode"
        assert evs[2]["prefix_pages"] == 0
        assert evs[i_ft]["ttft_ms"] >= 0
        fin = evs[-1]
        assert fin["n_tokens"] == 4 and "slo_ok" in fin
        # monotonic timestamps down the lane
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)

    def test_preempt_resume_event_order(self, pressure_serve):
        """ISSUE 9 acceptance: the preempted request's lane carries
        its WHOLE life — admitted → … → preempt → queued →
        admitted(resume) → … → finish."""
        eng, rids, done = pressure_serve
        preempts = [e for e in eng.journal.events()
                    if e["ev"] == "preempt"]
        assert preempts, "pool-pressure repro produced no preemption"
        rid = preempts[0]["rid"]
        names = [e["ev"] for e in eng.journal.events(rid)]
        i_pre = names.index("preempt")
        assert "admitted" in names[:i_pre]
        assert names[i_pre + 1] == "queued"
        assert "admitted" in names[i_pre + 2:]
        assert names[-1] == "finish"
        # the re-admission is marked as a resume
        readmits = [e for e in eng.journal.events(rid)
                    if e["ev"] == "admitted"]
        assert readmits[-1]["resume"] is True
        assert readmits[0]["resume"] is False
        # and the request-level pressure counters agree
        req = {r.id: r for r in done}[rid]
        assert req.n_preempts >= 1
        # outputs stayed exact through it all (PR 8 guarantee)
        assert len(req.generated) == 24


class TestCrashDump:
    def test_injected_exception_dumps_artifact(self, tmp_path):
        """Any step() raise leaves a JSONL artifact: journal tail +
        stats snapshot + every in-flight request, and bumps the
        serving.unserved counter for the never-admitted ones."""
        set_flags({"serve_journal_dir": str(tmp_path)})
        eng = ServingEngine(_model(), max_batch=1, page_size=4,
                            max_length=64, decode_chunk=2,
                            slo=SLOConfig(prefill_chunk=8))
        rng = np.random.RandomState(5)
        eng.submit(rng.randint(0, 64, (8,)), max_new_tokens=4)
        eng.submit(rng.randint(0, 64, (8,)), max_new_tokens=4)
        eng.step()                       # admit the first request

        def boom(self):
            raise RuntimeError("injected step failure")

        eng._pick_action = types.MethodType(boom, eng)
        before = stats.counter("serving.unserved").value
        with pytest.raises(RuntimeError, match="injected"):
            eng.run()
        path = eng.last_crash_dump
        assert path is not None and os.path.dirname(path) == \
            str(tmp_path)
        events, extras = load_jsonl(path)
        names = [e["ev"] for e in events]
        assert "submit" in names and names[-1] == "error"
        snap = extras["stats"]["stats"]
        assert "counters" in snap and "meta" in snap
        crash = extras["crash"]
        assert "injected step failure" in crash["error"]
        states = {u["state"] for u in crash["unserved"]}
        # one request still waiting (unserved), one in flight on the
        # slot (prefilling or decoding, depending on chunk progress)
        assert "waiting" in states
        assert len(crash["unserved"]) == 2
        assert stats.counter("serving.unserved").value == before + 1

    def test_dump_without_journal_still_carries_state(self, tmp_path):
        """FLAGS_serve_journal=0: the crash artifact still records the
        snapshot + unserved requests (just no events)."""
        set_flags({"serve_journal": False,
                   "serve_journal_dir": str(tmp_path)})
        eng = ServingEngine(_model(), max_batch=1, page_size=4,
                            max_length=64, decode_chunk=2,
                            slo=SLOConfig(prefill_chunk=8))
        rng = np.random.RandomState(9)
        eng.submit(rng.randint(0, 64, (6,)), max_new_tokens=2)
        path = eng.crash_dump(error=ValueError("manual"))
        events, extras = load_jsonl(path)
        assert events == []
        assert extras["crash"]["unserved"][0]["state"] == "inbox"
        assert "stats" in extras


class TestDisabledJournal:
    def test_flag_off_means_no_recorder_and_zero_record_calls(
            self, monkeypatch):
        """ISSUE 9 satellite: with the flag off the engine holds NO
        recorder — step() performs zero journal allocations or calls
        (record is patched to explode if anything slips through) —
        while the SLO monitor keeps judging verdicts."""
        set_flags({"serve_journal": False})
        eng = ServingEngine(_model(), max_batch=2, page_size=4,
                            max_length=64, decode_chunk=2,
                            slo=SLOConfig(prefill_chunk=8))
        assert eng.journal is None and eng._journal is None
        assert eng.prefix_cache._journal is None

        def boom(self, *a, **k):  # pragma: no cover - must not fire
            raise AssertionError("journal recorded while disabled")

        monkeypatch.setattr(journal_mod.FlightRecorder, "record", boom)
        rng = np.random.RandomState(11)
        rid = eng.submit(rng.randint(0, 64, (12,)), max_new_tokens=4)
        done = eng.run()
        assert [r.id for r in done] == [rid]
        # verdict/goodput accounting is journal-independent
        assert done[0].slo_ok is not None
        assert eng.slo_monitor.goodput is not None


class TestSLOMonitor:
    @staticmethod
    def _req(ttft_ms=None, tpot_ms=None, n_tokens=8):
        """Request with synthetic lifecycle marks yielding exactly the
        given readings (arrival at t=0)."""
        r = Request([1, 2, 3], max_new_tokens=n_tokens,
                    arrival_time=0.0)
        if ttft_ms is not None:
            r.t_first_token = ttft_ms / 1e3
        r.generated = list(range(n_tokens))
        if tpot_ms is not None and ttft_ms is not None:
            r.t_done = r.t_first_token \
                + (n_tokens - 1) * tpot_ms / 1e3
        return r

    def test_goodput_arithmetic_vs_hand_computed(self):
        mon = SLOMonitor(ttft_target_ms=100.0, tpot_target_ms=10.0,
                         objective=0.9, window=16)
        # 3 ok, 1 ttft miss, 1 tpot miss -> goodput 3/5
        for ttft, tpot in ((50, 5), (99, 9.9), (100, 10)):
            v = mon.observe_finish(self._req(ttft, tpot))
            assert v["slo_ok"] is True
        v = mon.observe_finish(self._req(250, 5))
        assert v["ttft_ok"] is False and v["tpot_ok"] is True
        v = mon.observe_finish(self._req(50, 25))
        assert v["ttft_ok"] is True and v["tpot_ok"] is False
        assert mon.goodput == pytest.approx(0.6)
        # burn rate: miss rate 0.4 over a 0.1 error budget = 4x
        assert mon.burn_rate == pytest.approx(4.0)
        assert stats.gauge("slo.goodput").value == pytest.approx(0.6)
        assert stats.gauge("slo.burn_rate").value == pytest.approx(4.0)
        assert stats.counter("slo.ttft_miss").value >= 1
        assert stats.counter("slo.tpot_miss").value >= 1

    def test_rolling_window(self):
        mon = SLOMonitor(ttft_target_ms=100.0, tpot_target_ms=None,
                         window=2)
        mon.observe_finish(self._req(50, None))     # ok
        mon.observe_finish(self._req(500, None))    # miss
        mon.observe_finish(self._req(50, None))     # ok
        # window of 2: [miss, ok]
        assert mon.goodput == pytest.approx(0.5)

    def test_single_token_request_passes_tpot_vacuously(self):
        mon = SLOMonitor(ttft_target_ms=100.0, tpot_target_ms=0.001)
        r = self._req(ttft_ms=50, tpot_ms=None, n_tokens=1)
        v = mon.observe_finish(r)
        assert v["tpot_ms"] is None and v["tpot_ok"] is True
        assert v["slo_ok"] is True and r.slo_ok is True

    def test_objective_validation(self):
        with pytest.raises(ValueError):
            SLOMonitor(objective=1.0)
        with pytest.raises(ValueError):
            SLOConfig(goodput_objective=0.0)

    def test_config_carries_targets(self):
        slo = SLOConfig(ttft_target_ms=123.0, tpot_target_ms=None,
                        goodput_objective=0.95, slo_window=7)
        assert slo.ttft_target_ms == 123.0
        assert slo.tpot_target_ms is None
        assert slo.goodput_objective == 0.95 and slo.slo_window == 7


class TestChromeTraceExport:
    def test_one_lane_per_request_with_phases(self, pressure_serve):
        eng, rids, _ = pressure_serve
        tr = chrome_trace(eng.journal.events(), process_index=3)
        assert tr["metadata"]["process_index"] == 3
        evs = tr["traceEvents"]
        assert all(e["pid"] == 3 for e in evs)
        lanes = {e["args"]["name"] for e in evs
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        for rid in rids:
            assert f"req {rid}" in lanes
        spans = {e["name"] for e in evs if e["ph"] == "X"}
        assert {"queued", "prefill", "decode"} <= spans
        # the preemption is an instant mark on the request's own lane
        marks = [e for e in evs if e["ph"] == "i"
                 and e["name"] == "preempt"]
        assert marks and all(m["tid"] == m["args"]["rid"] + 1
                             for m in marks)

    def test_round_trips_through_trace_merge(self, pressure_serve,
                                             tmp_path):
        """ISSUE 9 acceptance: rank-stamped journal traces fold into
        one multi-rank timeline exactly like profiler traces."""
        eng, _, _ = pressure_serve
        events = eng.journal.events()
        paths = []
        for r in (0, 1):
            p = str(tmp_path / f"trace_rank{r}.json")
            with open(p, "w") as f:
                json.dump(chrome_trace(events, process_index=r), f)
            paths.append(p)
        trace_merge = _tools("trace_merge")
        merged = trace_merge.merge_traces(paths)
        assert merged["metadata"]["ranks"] == [0, 1]
        pids = {e["pid"] for e in merged["traceEvents"]
                if e.get("ph") == "X"}
        assert pids == {0, 1}


class TestServeTop:
    def test_offline_cli_smoke(self, pressure_serve, tmp_path):
        """ISSUE 9 acceptance: serve_top renders the preempted
        request's full timeline from a journal file (offline mode is
        stdlib-only, so the subprocess is fast)."""
        eng, _, _ = pressure_serve
        jpath = str(tmp_path / "journal.jsonl")
        eng.journal.dump_jsonl(jpath)
        rid = [e for e in eng.journal.events()
               if e["ev"] == "preempt"][0]["rid"]
        out_trace = str(tmp_path / "trace.json")
        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "serve_top.py"), jpath,
             "--top", "3", "--export-trace", out_trace],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "goodput" in proc.stdout
        assert "preempt" in proc.stdout
        assert os.path.exists(out_trace)
        # --req renders one full timeline
        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "serve_top.py"), jpath,
             "--req", str(rid)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr[-2000:]
        for ev in ("admitted", "preempt", "queued", "finish"):
            assert ev in proc.stdout, ev

    def test_summarize_counts_and_verdicts(self, pressure_serve):
        eng, rids, done = pressure_serve
        serve_top = _tools("serve_top")
        s = serve_top.summarize(eng.journal.events())
        assert s["finished"] == len(rids)
        assert s["preemptions"] >= 1
        assert s["goodput"] is not None
        # verdicts come from the journal's finish events (stamped by
        # the monitor), matching the requests' own verdicts
        expect = sum(1 for r in done if r.slo_ok) / len(done)
        assert s["goodput"] == pytest.approx(expect)

    def test_render_engine_live(self, pressure_serve):
        eng, rids, _ = pressure_serve
        serve_top = _tools("serve_top")
        out = serve_top.render_engine(eng, top=2)
        assert "serve_top" in out and "goodput" in out
        assert f"/{eng.max_batch}" in out    # live slot occupancy


class TestBenchGateGoodput:
    def test_goodput_gates_down(self):
        bench_gate = _tools("bench_gate")
        prev = {"serve_goodput": 0.99,
                "telemetry": {"gauges": {"slo.goodput": 0.99}}}
        worse = {"serve_goodput": 0.50,
                 "telemetry": {"gauges": {"slo.goodput": 0.50}}}
        bad, n = bench_gate.gate(prev, worse)
        assert n >= 2
        assert any("serve_goodput" in ln for ln in bad)
        assert any("slo.goodput" in ln for ln in bad)
        better = {"serve_goodput": 1.0,
                  "telemetry": {"gauges": {"slo.goodput": 1.0}}}
        bad, _ = bench_gate.gate(prev, better)
        assert not bad


class TestConventions:
    def test_journal_and_slo_prefixes_registered(self):
        """ISSUE 9 satellite: journal./slo. are documented namespaces
        so the PR 2 naming lint covers the new metrics."""
        assert "journal." in stats.CONVENTION_PREFIXES
        assert "slo." in stats.CONVENTION_PREFIXES

    def test_run_publishes_journal_gauges(self, pressure_serve):
        eng, _, _ = pressure_serve
        assert stats.gauge("journal.events").value > 0
        assert stats.gauge("slo.slot_occupancy").value >= 0
