"""Serving generality: per-sequence prompt lengths + continuous batching.

Reference parity target: the per-request seq_lens/block-table interface
of paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
plus the admit/evict loop of its serving frontends.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (
    ContinuousBatchingEngine, FusedCausalLM, GenerationEngine)


def _model(seed=7):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=128)


def _dense_greedy(model, prompt, n):
    """Reference: full re-forward each step, argmax of the last real
    position."""
    seq = np.asarray(prompt, np.int64).reshape(1, -1)
    for _ in range(n):
        logits = model(paddle.to_tensor(seq)).numpy()
        nxt = logits[:, -1].argmax(-1)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    return seq[0]


class TestRaggedPrompts:
    def test_unequal_prompt_lengths_per_seq_parity(self):
        """A batch with different prompt lengths must decode each row to
        the same tokens as that row generated alone (per-sequence greedy
        parity) — the reference's per-request seq_lens contract."""
        model = _model()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 64, (L,)) for L in (3, 6, 9)]
        n_new = 6

        engine = GenerationEngine(model, page_size=4, max_length=64,
                                  decode_chunk=2)
        out = engine.generate(prompts, max_new_tokens=n_new)
        assert out.shape == (3, 9 + n_new)

        for i, p in enumerate(prompts):
            ref = _dense_greedy(model, p, n_new)
            got = np.concatenate(
                [out[i, : len(p)], out[i, len(p): len(p) + n_new]])
            np.testing.assert_array_equal(
                got, ref, err_msg=f"row {i} (len {len(p)})")

    def test_rect_batch_with_seq_lens(self):
        model = _model()
        rng = np.random.RandomState(5)
        ids = rng.randint(0, 64, (2, 8))
        lens = np.array([5, 8])
        engine = GenerationEngine(model, page_size=4, max_length=64)
        out = engine.generate(ids, max_new_tokens=4, seq_lens=lens)
        for i in range(2):
            ref = _dense_greedy(model, ids[i, : lens[i]], 4)
            np.testing.assert_array_equal(
                out[i, lens[i]: lens[i] + 4], ref[lens[i]:])

    def test_on_demand_paging(self):
        """Pages must be allocated as sequences grow, not all upfront."""
        model = _model()
        engine = GenerationEngine(model, page_size=4, max_length=64,
                                  decode_chunk=2)
        ids = np.array([[1, 2, 3]])
        # instrument: capture free-page count right after prefill alloc
        from paddle_tpu.inference.kv_cache import BlockKVCacheManager

        orig_alloc = BlockKVCacheManager.allocate
        snapshots = []

        def spy(self, seq_id, max_length):
            r = orig_alloc(self, seq_id, max_length)
            snapshots.append(self.free_pages)
            return r

        BlockKVCacheManager.allocate = spy
        try:
            engine.generate(ids, max_new_tokens=12)
        finally:
            BlockKVCacheManager.allocate = orig_alloc
        # prompt len 3 -> 1 page allocated initially; 64-token coverage
        # would be 16 pages. Paging actually pages now.
        total = engine._mgr.num_pages
        assert snapshots[0] >= total - 2, (
            f"upfront allocation detected: {total - snapshots[0]} pages "
            "taken at prefill for a 3-token prompt")


class TestContinuousBatching:
    def test_batch_parity_with_solo_runs(self):
        model = _model()
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, 64, (L,)) for L in (4, 7, 5)]
        eng = ContinuousBatchingEngine(model, max_batch=3, page_size=4,
                                       max_length=64, decode_chunk=2)
        rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        done = eng.run()
        assert sorted(r.id for r in done) == sorted(rids)
        by_id = {r.id: r for r in done}
        for rid, p in zip(rids, prompts):
            ref = _dense_greedy(model, p, 6)
            np.testing.assert_array_equal(by_id[rid].output, ref,
                                          err_msg=f"req {rid}")

    def test_mid_stream_admit(self):
        """A request submitted while others are decoding must be admitted
        into a free slot mid-stream and still match its solo output."""
        model = _model()
        rng = np.random.RandomState(13)
        p1, p2 = rng.randint(0, 64, (5,)), rng.randint(0, 64, (8,))
        p3 = rng.randint(0, 64, (6,))

        eng = ContinuousBatchingEngine(model, max_batch=2, page_size=4,
                                       max_length=64, decode_chunk=2)
        r1 = eng.submit(p1, max_new_tokens=10)
        r2 = eng.submit(p2, max_new_tokens=10)
        eng.step()          # both decoding
        assert eng.num_active == 2
        r3 = eng.submit(p3, max_new_tokens=4)   # queued: no free slot
        eng.step()
        # r3 waits until a slot frees (max_batch=2)
        assert any(r.id == r3 for r in eng.waiting) or eng.num_active == 2
        done = eng.run()
        by_id = {r.id: r for r in done}
        assert set(by_id) == {r1, r2, r3}
        for rid, p, n in ((r1, p1, 10), (r2, p2, 10), (r3, p3, 4)):
            ref = _dense_greedy(model, p, n)
            np.testing.assert_array_equal(by_id[rid].output, ref,
                                          err_msg=f"req {rid}")

    def test_more_requests_than_slots(self):
        """6 requests through 2 slots: slot reuse + page recycling."""
        model = _model()
        rng = np.random.RandomState(17)
        prompts = [rng.randint(0, 64, (rng.randint(3, 10),))
                   for _ in range(6)]
        eng = ContinuousBatchingEngine(model, max_batch=2, page_size=4,
                                       max_length=64, decode_chunk=2)
        rids = [eng.submit(p, max_new_tokens=5) for p in prompts]
        done = eng.run()
        assert len(done) == 6
        by_id = {r.id: r for r in done}
        for rid, p in zip(rids, prompts):
            ref = _dense_greedy(model, p, 5)
            np.testing.assert_array_equal(by_id[rid].output, ref)
        # all pages returned to the pool
        assert eng._mgr.free_pages == eng._mgr.num_pages - 1  # scratch

    def test_eos_finishes_request(self):
        model = _model()
        ids = np.array([1, 2, 3])
        ref = _dense_greedy(model, ids, 1)
        eos = int(ref[-1])  # first generated token = EOS
        eng = ContinuousBatchingEngine(model, max_batch=2, page_size=4,
                                       max_length=32, decode_chunk=2)
        rid = eng.submit(ids, max_new_tokens=8, eos_token_id=eos)
        done = eng.run()
        assert done[0].id == rid and done[0].done
        assert done[0].generated[-1] == eos
        assert len(done[0].generated) <= 8


class TestA8W8Serving:
    """quant='a8w8' end-to-end: dynamic-activation int8 x int8 matmuls
    through both engines on CPU (the XLA int32-dot fallback runs the
    same quantized math the TPU kernel compiles)."""

    def _int8_model(self, seed=5):
        paddle.seed(seed)
        m = FusedCausalLM(vocab_size=256, embed_dim=256, num_heads=2,
                          dim_feedforward=512, num_layers=2,
                          max_position=128)
        return m

    def test_generation_engine_a8w8_tokens_sane(self):
        """A8W8 vs weight-only int8 on the SAME int8 stack: the only
        delta is activation quantization, so greedy tokens must largely
        agree — and all tokens must be in-vocab."""
        model = self._int8_model()
        ids = np.random.RandomState(2).randint(1, 256, (2, 12))
        out_w8 = GenerationEngine(model, page_size=4, max_length=48,
                                  decode_chunk=4, quant="int8") \
            .generate(ids, max_new_tokens=8)
        # stack already int8 now — a8w8 engine reuses it untouched
        out_a8 = GenerationEngine(model, page_size=4, max_length=48,
                                  decode_chunk=4, quant="a8w8") \
            .generate(ids, max_new_tokens=8)
        assert out_a8.shape == (2, 20)
        assert (out_a8 >= 0).all() and (out_a8 < 256).all()
        agree = float((out_w8[:, 12:] == out_a8[:, 12:]).mean())
        assert agree >= 0.75, (out_w8[:, 12:], out_a8[:, 12:])

    def test_continuous_batching_a8w8_parity_with_solo(self):
        """ContinuousBatchingEngine(quant='a8w8') must reproduce the
        solo a8w8 GenerationEngine greedy tokens (same quantized
        programs, deterministic greedy pick)."""
        model = self._int8_model(seed=9)
        rng = np.random.RandomState(31)
        prompts = [rng.randint(1, 256, (L,)) for L in (5, 9)]
        eng = ContinuousBatchingEngine(model, max_batch=2, page_size=4,
                                       max_length=64, decode_chunk=2,
                                       quant="a8w8")
        solo = GenerationEngine(model, page_size=4, max_length=64,
                                decode_chunk=2, quant="a8w8")
        rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        done = {r.id: r for r in eng.run()}
        assert sorted(done) == sorted(rids)
        for rid, p in zip(rids, prompts):
            ref = solo.generate([p], max_new_tokens=6)[0]
            np.testing.assert_array_equal(done[rid].output, ref,
                                          err_msg=f"req {rid}")

    def test_quant_counters_and_roofline_rung(self):
        """quant.* counters count executed a8w8 work, and the compiled
        programs report under the decode.a8w8/prefill.a8w8 roofline
        rungs (not the bf16 rows)."""
        from paddle_tpu.profiler import roofline, stats

        model = self._int8_model(seed=13)
        ids = np.random.RandomState(4).randint(1, 256, (1, 8))
        before_q = stats.counter("quant.act_quant_calls").value
        before_m = stats.counter("quant.a8w8_matmuls").value
        eng = GenerationEngine(model, page_size=4, max_length=32,
                               decode_chunk=2, quant="a8w8")
        eng.generate(ids, max_new_tokens=4)
        # prefill + 3 chunked decode steps, 4 matmuls x 2 layers each
        n_steps = 1 + 3
        assert stats.counter("quant.act_quant_calls").value \
            == before_q + 4 * 2 * n_steps
        assert stats.counter("quant.a8w8_matmuls").value \
            == before_m + 4 * 2 * n_steps
        rep = roofline.report()
        assert "prefill.a8w8" in rep
        assert any(k.startswith("decode.a8w8[k=") for k in rep)

    def test_invalid_quant_mode_raises(self):
        model = self._int8_model(seed=17)
        with pytest.raises(ValueError, match="a8w8"):
            GenerationEngine(model, quant="int4")


class TestDecodeChunkDefault:
    def test_auto_picked_128_with_override(self):
        """decode_chunk defaults to the measured-best 128 (chunk 64->128
        was +7% tok/s, bench_profile.json) in BOTH engines; an explicit
        kwarg still wins."""
        from paddle_tpu.inference import DEFAULT_DECODE_CHUNK

        assert DEFAULT_DECODE_CHUNK == 128
        model = _model()
        eng = GenerationEngine(model, page_size=4, max_length=64)
        assert eng.decode_chunk == 128
        assert GenerationEngine(model, page_size=4, max_length=64,
                                decode_chunk=16).decode_chunk == 16
        cb = ContinuousBatchingEngine(model, max_batch=2, page_size=4,
                                      max_length=64)
        assert cb.decode_chunk == 128
        cb2 = ContinuousBatchingEngine(model, max_batch=2, page_size=4,
                                       max_length=64, decode_chunk=2)
        assert cb2.decode_chunk == 2 and cb2._gen.decode_chunk == 2


class TestSampling:
    """Sampling decode (the reference's top_p_sampling serving surface):
    temperature / top-k / top-p with paddle.seed-governed keys."""

    def test_topk1_equals_greedy(self):
        model = _model()
        rng = np.random.RandomState(21)
        ids = rng.randint(0, 64, (2, 5))
        eng = GenerationEngine(model, page_size=4, max_length=32,
                               decode_chunk=2)
        greedy = eng.generate(ids, max_new_tokens=5)
        paddle.seed(0)
        topk1 = eng.generate(ids, max_new_tokens=5, do_sample=True,
                             top_k=1)
        np.testing.assert_array_equal(topk1, greedy)

    def test_tiny_temperature_equals_greedy(self):
        model = _model()
        rng = np.random.RandomState(22)
        ids = rng.randint(0, 64, (1, 6))
        eng = GenerationEngine(model, page_size=4, max_length=32,
                               decode_chunk=2)
        greedy = eng.generate(ids, max_new_tokens=4)
        paddle.seed(1)
        cold = eng.generate(ids, max_new_tokens=4, do_sample=True,
                            temperature=1e-5)
        np.testing.assert_array_equal(cold, greedy)

    def test_seed_reproducible_and_varies(self):
        model = _model()
        rng = np.random.RandomState(23)
        ids = rng.randint(0, 64, (1, 4))
        eng = GenerationEngine(model, page_size=4, max_length=64,
                               decode_chunk=4)
        kw = dict(max_new_tokens=12, do_sample=True, temperature=1.5,
                  top_p=0.95)
        paddle.seed(7)
        a = eng.generate(ids, **kw)
        paddle.seed(7)
        b = eng.generate(ids, **kw)
        np.testing.assert_array_equal(a, b)
        paddle.seed(8)
        c = eng.generate(ids, **kw)
        assert not np.array_equal(a, c), "different seeds gave same draw"

    def test_top_p_restricts_support(self):
        """Every sampled first token must lie in the minimal nucleus."""
        model = _model()
        rng = np.random.RandomState(24)
        ids = rng.randint(0, 64, (1, 5))
        logits = model(paddle.to_tensor(ids)).numpy()[0, -1]
        probs = np.exp(logits - logits.max())
        probs /= probs.sum()
        order = np.argsort(probs)[::-1]
        cum = np.cumsum(probs[order])
        nucleus = set(order[: int(np.searchsorted(cum, 0.5)) + 1].tolist())
        eng = GenerationEngine(model, page_size=4, max_length=32)
        for seed in range(8):
            paddle.seed(seed)
            out = eng.generate(ids, max_new_tokens=1, do_sample=True,
                               top_p=0.5)
            assert int(out[0, 5]) in nucleus, (int(out[0, 5]), nucleus)

    def test_greedy_does_not_consume_rng(self):
        """Greedy decode must leave the global RNG stream untouched."""
        model = _model()
        ids = np.random.RandomState(25).randint(0, 64, (1, 4))
        eng = GenerationEngine(model, page_size=4, max_length=32,
                               decode_chunk=2)
        paddle.seed(42)
        ref_draw = paddle.randn([4]).numpy()
        paddle.seed(42)
        eng.generate(ids, max_new_tokens=4)  # greedy
        post_draw = paddle.randn([4]).numpy()
        np.testing.assert_array_equal(ref_draw, post_draw)

    def test_temperature_change_reuses_compiled_program(self):
        """temperature/top_p are traced: different values must hit the
        same (k, top_k) program cache entry."""
        model = _model()
        ids = np.random.RandomState(26).randint(0, 64, (1, 4))
        eng = GenerationEngine(model, page_size=4, max_length=32,
                               decode_chunk=2)
        paddle.seed(0)
        eng.generate(ids, max_new_tokens=4, do_sample=True,
                     temperature=0.7, top_p=0.9)
        n_programs = len(eng._decode_k_jit)
        paddle.seed(0)
        eng.generate(ids, max_new_tokens=4, do_sample=True,
                     temperature=1.3, top_p=0.8)
        assert len(eng._decode_k_jit) == n_programs
