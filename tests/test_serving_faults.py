"""Chaos-hardened serving (ISSUE 11): deterministic fault injection,
per-request deadlines, overload shedding, crash-isolated engine steps.

Tier-1 acceptance pins:

- with a seeded fault schedule injecting >=5 distinct sites under
  concurrent load, the serve loop NEVER exits: faulted requests land
  in an ``error``/``deadline_exceeded`` terminal state, every
  SURVIVING request's greedy tokens are identical to a fault-free run,
  and goodput stays within a pinned bound
  (``TestAcceptance.test_five_site_schedule_survivor_parity``);
- every PR 8 pool-pressure recovery path (cold-prefix eviction,
  prefill stall/requeue, preemption-by-recompute) is drivable by
  injected pool-exhaustion (squeeze) faults with full token parity
  (``TestRecoveryPathsChaos``);
- deadline/backoff/watchdog tests run on the injectable ManualClock —
  no ``time.sleep`` flake anywhere in this file.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import stats
from paddle_tpu.inference import FusedCausalLM
from paddle_tpu.serving import (DeadlineExceeded, FaultInjector,
                                InjectedFault, ManualClock,
                                PoolSizingError, Request, SLOConfig,
                                ServerOverloaded, ServingEngine,
                                TokenCorruption, WatchdogTimeout,
                                use_clock)
from paddle_tpu.serving import faults as faults_mod

pytestmark = pytest.mark.chaos

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model(seed=7, max_position=256):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=max_position)


#: fault-free reference outputs, memoized per (workload, seed) — the
#: model rebuilds identically from its seed, so ONE fault-free
#: ServingEngine run serves every test over the same workload (the
#: acceptance criterion is literally "identical to a fault-free run";
#: chunked-serving == dense parity is already pinned by ISSUE 8 tests)
_REF_CACHE: dict = {}


def _ref_outputs(prompts, max_new, seed=7):
    key = (tuple(np.asarray(p, np.int32).tobytes() for p in prompts),
           int(max_new), int(seed))
    if key not in _REF_CACHE:
        eng = _engine(_model(seed))
        rids = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
        done = {r.id: r for r in eng.run()}
        assert all(done[rid].state == "ok" for rid in rids)
        _REF_CACHE[key] = [np.asarray(done[rid].output)
                           for rid in rids]
    return _REF_CACHE[key]


def _engine(model, faults=None, **kw):
    kw.setdefault("max_batch", 3)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_length", 128)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("slo", SLOConfig(prefill_chunk=16))
    return ServingEngine(model, faults=faults, **kw)


class _flags:
    """Scoped flag override (flags are process-global)."""

    def __init__(self, **kw):
        self._new = {f"FLAGS_{k}": v for k, v in kw.items()}

    def __enter__(self):
        self._old = paddle.get_flags(list(self._new))
        paddle.set_flags(self._new)
        return self

    def __exit__(self, *exc):
        paddle.set_flags(self._old)


# =====================================================================
# clock seam
# =====================================================================

class TestClockSeam:
    def test_manual_clock_advances_and_sleeps(self):
        clk = ManualClock(10.0)
        assert clk.now() == 10.0
        clk.sleep(2.5)                  # a backoff is a pure time-warp
        assert clk.now() == 12.5
        clk.advance(0.5)
        assert clk.now() == 13.0

    def test_use_clock_scopes_install(self):
        before = faults_mod.now()
        with use_clock(ManualClock(123.0)):
            assert faults_mod.now() == 123.0
        assert abs(faults_mod.now() - before) < 60.0  # real clock back

    def test_request_and_journal_timestamps_use_clock(self):
        """Every serving timestamp — request arrival, journal event
        ts — reads the injected clock, so lifecycle timelines are
        deterministic in tests."""
        from paddle_tpu.serving.journal import FlightRecorder

        with use_clock(ManualClock(50.0)) as clk:
            req = Request([1, 2, 3])
            assert req.arrival_time == 50.0
            jr = FlightRecorder(8)
            jr.record("submit", req.id, -1, None)
            clk.advance(1.0)
            jr.record("queued", req.id, -1, None)
            ts = [e["ts"] for e in jr.events()]
            assert ts == [50.0, 51.0]

    def test_slo_readings_deterministic_under_manual_clock(self):
        with use_clock(ManualClock(0.0)) as clk:
            req = Request([1, 2, 3], deadline_ms=None)
            clk.advance(0.25)
            req.t_admitted = faults_mod.now()
            assert req.queue_wait_s == pytest.approx(0.25)
            clk.advance(0.5)
            req.t_first_token = faults_mod.now()
            assert req.ttft_s == pytest.approx(0.75)


# =====================================================================
# injector scheduling
# =====================================================================

class TestInjectorSchedule:
    def test_at_every_times_deterministic(self):
        inj = (FaultInjector(seed=0)
               .add("s", kind="raise", at=2)
               .add("s", kind="raise", every=5, times=2))
        fired = []
        for hit in range(20):
            try:
                inj.fire("s")
            except InjectedFault as e:
                fired.append(hit)
                assert e.site == "s" and e.hit == hit
        # at=2 fires on hit 2; every=5 fires on hits 4 and 9 (capped
        # at times=2)
        assert fired == [2, 4, 9]
        assert inj.hits("s") == 20

    def test_probability_deterministic_given_seed(self):
        def run(seed):
            inj = FaultInjector(seed=seed).add(
                "s", kind="raise", p=0.3, times=-1)
            out = []
            for hit in range(50):
                try:
                    inj.fire("s")
                except InjectedFault:
                    out.append(hit)
            return out

        a, b = run(11), run(11)
        assert a == b and a  # same seed -> same schedule, nonempty
        assert run(12) != a  # different seed -> different schedule

    def test_corrupt_consumes_last_hit(self):
        inj = FaultInjector().add("s", kind="corrupt", at=1)
        inj.fire("s")                       # hit 0
        assert inj.corrupt("s", 7) == 7     # not scheduled
        inj.fire("s")                       # hit 1
        assert inj.corrupt("s", 7) == FaultInjector.CORRUPT_TOKEN

    def test_delay_sleeps_through_injected_clock(self):
        with use_clock(ManualClock(0.0)) as clk:
            inj = FaultInjector().add("s", kind="delay", at=0,
                                      delay_ms=40.0)
            inj.fire("s")
            assert clk.now() == pytest.approx(0.040)

    def test_fired_log_and_plan(self):
        inj = FaultInjector().add("s", kind="delay", at=0, delay_ms=0)
        inj.fire("s")
        assert inj.fired == [{"site": "s", "hit": 0, "kind": "delay"}]
        assert inj.plan()[0]["site"] == "s"

    def test_squeeze_and_release_work_real_free_list(self):
        model = _model()
        eng = _engine(model)
        free0 = eng._mgr.free_pages
        inj = (FaultInjector()
               .add("decode.step", kind="squeeze", pages=5, at=0)
               .add("decode.step", kind="release", at=1))
        eng.install_faults(inj)
        inj.fire("decode.step")
        assert eng._mgr.free_pages == free0 - 5
        assert inj.squeezed_pages == 5
        inj.fire("decode.step")
        assert eng._mgr.free_pages == free0
        assert inj.squeezed_pages == 0


# =====================================================================
# per-request deadlines
# =====================================================================

class TestDeadlines:
    def test_queued_request_past_deadline_aborts_only_itself(self):
        model = _model()
        with use_clock(ManualClock()) as clk:
            eng = _engine(model, max_batch=1)
            p_ok, p_dead = [np.arange(6) + 1, np.arange(9) + 2]
            r_ok = eng.submit(p_ok, max_new_tokens=4)
            r_dead = eng.submit(p_dead, max_new_tokens=4,
                                deadline_ms=50.0)
            clk.advance(0.2)   # 200ms > 50ms
            done = {r.id: r for r in eng.run()}
            assert done[r_dead].state == "deadline_exceeded"
            assert isinstance(done[r_dead].error, DeadlineExceeded)
            assert done[r_dead].slo_ok is False
            assert done[r_ok].state == "ok"
            np.testing.assert_array_equal(
                done[r_ok].output, _ref_outputs([p_ok], 4)[0])

    def test_decoding_request_deadline_frees_pages(self):
        """A deadline that lands mid-decode aborts the slot and frees
        every page it held (drain-to-exact-pool accounting)."""
        model = _model()
        with use_clock(ManualClock()) as clk:
            eng = _engine(model, max_batch=1,
                          slo=SLOConfig(prefill_chunk=16,
                                        prefix_cache=False))
            free0 = eng._mgr.free_pages
            rid = eng.submit(np.arange(8), max_new_tokens=64,
                             deadline_ms=100.0)
            # a few steps of progress, then jump past the deadline
            for _ in range(4):
                eng.step()
            assert eng.num_active + eng.num_prefilling == 1
            clk.advance(1.0)
            done = {r.id: r for r in eng.run()}
            assert done[rid].state == "deadline_exceeded"
            assert eng._mgr.free_pages == free0  # no page leaked
            # terminal event on the journal timeline
            evs = [e["ev"] for e in eng.journal.events(rid)]
            assert evs[-1] == "deadline_exceeded"

    def test_deadline_counter_and_no_deadline_unaffected(self):
        before = stats.counter("serving.deadline_exceeded").value
        model = _model()
        with use_clock(ManualClock()) as clk:
            eng = _engine(model)
            rid = eng.submit(np.arange(4), max_new_tokens=2,
                             deadline_ms=10.0)
            r2 = eng.submit(np.arange(4), max_new_tokens=2)
            clk.advance(5.0)
            done = {r.id: r for r in eng.run()}
        assert done[rid].state == "deadline_exceeded"
        assert done[r2].state == "ok"  # no deadline -> never expires
        assert stats.counter("serving.deadline_exceeded").value \
            == before + 1


# =====================================================================
# crash-isolated stepping
# =====================================================================

class TestCrashIsolation:
    def _run_with_faults(self, inj, n_req=3, max_new=6):
        model = _model()
        eng = _engine(model, faults=inj)
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 64, (L,)) for L in (37, 6, 9)][:n_req]
        rids = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
        done = {r.id: r for r in eng.run()}
        return model, eng, prompts, rids, done

    def test_transient_prefill_fault_retries_to_parity(self):
        before = stats.counter("serving.step_retries").value
        inj = FaultInjector().add("prefill.dispatch", kind="raise",
                                  at=1)
        model, eng, prompts, rids, done = self._run_with_faults(inj)
        for rid, ref in zip(rids, _ref_outputs(prompts, 6)):
            assert done[rid].state == "ok"
            np.testing.assert_array_equal(done[rid].output, ref)
        assert stats.counter("serving.step_retries").value > before
        assert any(e["ev"] == "retry" for e in eng.journal.events())

    def test_transient_decode_fault_retries_to_parity(self):
        inj = FaultInjector().add("decode.step", kind="raise", at=2)
        model, eng, prompts, rids, done = self._run_with_faults(inj)
        for rid, ref in zip(rids, _ref_outputs(prompts, 6)):
            assert done[rid].state == "ok"
            np.testing.assert_array_equal(done[rid].output, ref)

    def test_corrupt_token_detected_and_recomputed(self):
        """Corrupt-and-detect: the poisoned token never reaches any
        stream — validation raises BEFORE request state mutates, the
        chunk re-runs, and every token matches the dense reference."""
        inj = (FaultInjector()
               .add("decode.step", kind="corrupt", at=3)
               .add("prefill.dispatch", kind="corrupt", at=2))
        model, eng, prompts, rids, done = self._run_with_faults(inj)
        corrupt_fires = [f for f in inj.fired if f["kind"] == "corrupt"]
        assert corrupt_fires, "corruption never fired"
        for rid, ref in zip(rids, _ref_outputs(prompts, 6)):
            assert done[rid].state == "ok"
            np.testing.assert_array_equal(done[rid].output, ref)

    def test_persistent_prefill_fault_errors_only_offender(self):
        """A fault that hits EVERY dispatch of one request's chunks
        errors out that request after the retry budget — the loop
        keeps serving everyone else to full parity."""
        before = stats.counter("serving.request_errors").value
        # rid of the 37-token prompt is the first admitted: its chunk
        # dispatches are hits 0.. of the prefill site while shorter
        # prompts interleave; fail hits 0-30 → only requests whose
        # dispatches land there die. Use exc to pin the error type.
        inj = FaultInjector().add(
            "prefill.dispatch", kind="raise", every=1, times=-1)
        model, eng, prompts, rids, done = self._run_with_faults(inj)
        states = {rid: done[rid].state for rid in rids}
        # every prefill dispatch faults forever -> ALL requests error
        # out (bounded degradation), but the loop exits cleanly
        assert set(states.values()) == {"error"}
        for rid in rids:
            assert isinstance(done[rid].error, InjectedFault)
        assert stats.counter("serving.request_errors").value \
            >= before + 3

    def test_persistent_decode_fault_sacrifices_not_hangs(self):
        """Decode faults aren't attributable to one slot: after the
        chunk retry budget, the least-urgent active slot is
        sacrificed, and the loop always terminates."""
        inj = FaultInjector().add("decode.step", kind="raise",
                                  every=1, times=-1)
        model, eng, prompts, rids, done = self._run_with_faults(inj)
        assert sorted(done) == sorted(rids)      # loop exited
        errored = [r for r in done.values() if r.state == "error"]
        assert errored, "no request absorbed the persistent fault"
        # whoever still finished is exactly right
        for rid, ref in zip(rids, _ref_outputs(prompts, 6)):
            if done[rid].state == "ok":
                np.testing.assert_array_equal(done[rid].output, ref)

    def test_transient_kv_grow_fault_recovers(self):
        inj = FaultInjector().add("kv.grow", kind="raise", at=1)
        model, eng, prompts, rids, done = self._run_with_faults(inj)
        for rid, ref in zip(rids, _ref_outputs(prompts, 6)):
            assert done[rid].state == "ok"
            np.testing.assert_array_equal(done[rid].output, ref)

    def test_prefix_insert_fault_absorbed_never_fatal(self):
        """A prefix-cache registration failure costs future reuse,
        never the request: parity holds, the counter ticks, and no
        page leaks."""
        before = stats.counter("serving.prefix_insert_errors").value
        inj = FaultInjector().add("prefix.insert", kind="raise",
                                  every=1, times=-1)
        model, eng, prompts, rids, done = self._run_with_faults(inj)
        for rid, ref in zip(rids, _ref_outputs(prompts, 6)):
            assert done[rid].state == "ok"
            np.testing.assert_array_equal(done[rid].output, ref)
        assert stats.counter("serving.prefix_insert_errors").value \
            > before
        assert len(eng.prefix_cache) == 0   # nothing half-registered

    def test_backoff_is_capped_exponential_on_clock(self):
        """Retry k sleeps min(base * 2^(k-1), cap) through the
        injected clock — pinned exactly with a ManualClock."""
        model = _model()
        with _flags(serve_step_retries=3, serve_retry_backoff_ms=10.0,
                    serve_retry_backoff_cap_ms=25.0), \
                use_clock(ManualClock()) as clk:
            inj = FaultInjector().add("prefill.dispatch",
                                      kind="raise", every=1, times=-1)
            eng = _engine(model, faults=inj, max_batch=1)
            rid = eng.submit(np.arange(8), max_new_tokens=2)
            t0 = clk.now()
            done = {r.id: r for r in eng.run()}
            # 3 retries: 10 + 20 + 25(capped) = 55ms of backoff
            assert clk.now() - t0 == pytest.approx(0.055)
            assert done[rid].state == "error"

    def test_pool_sizing_error_still_propagates(self):
        """The informative never-fits sizing error is a CONFIG error,
        not a retryable fault — it must keep reaching run()'s caller
        (and its crash dump must not mask it)."""
        model = _model()
        eng = _engine(model, max_batch=2, max_length=64, num_pages=15,
                      slo=SLOConfig(prefill_chunk=8))
        rng = np.random.RandomState(37)
        eng.submit(rng.randint(0, 64, (56,)), max_new_tokens=8)
        with pytest.raises(PoolSizingError, match="num_pages"):
            eng.run()
        assert eng.last_crash_dump is not None  # dump still written
        os.remove(eng.last_crash_dump)


# =====================================================================
# progress watchdog
# =====================================================================

class TestWatchdog:
    def test_wedged_prefill_requeued_then_killed(self):
        """A prefilling request whose progress marker never moves:
        the watchdog requeues it after N ticks (first trip) and fails
        it with WatchdogTimeout on the second — the loop never hangs
        behind it, and everyone else keeps serving."""
        model = _model()
        p_before = stats.counter("serving.watchdog_preempts").value
        k_before = stats.counter("serving.watchdog_kills").value
        n = 3
        with _flags(serve_watchdog_steps=n):
            eng = _engine(model, max_batch=2)
            victim = eng.submit(np.arange(30), max_new_tokens=4)
            eng.step()                    # admit (+ first chunk)
            assert eng.num_prefilling == 1
            req = next(iter(eng._prefilling.values())).req
            assert req.id == victim
            # freeze the world: tick without running chunks
            for _ in range(n + 1):
                eng._watchdog_tick()
            assert req in eng.waiting     # first trip: requeued
            assert req.n_requeues == 1
            assert stats.counter("serving.watchdog_preempts").value \
                == p_before + 1
            free_before_kill = None
            eng._admit()                  # re-admit into a slot
            assert eng.num_prefilling == 1
            free_before_kill = eng._mgr.free_pages
            for _ in range(n + 1):
                eng._watchdog_tick()      # second trip: killed
            assert req.state == "error"
            assert isinstance(req.error, WatchdogTimeout)
            assert req in eng.finished
            assert eng.num_prefilling == 0
            # no page held by the killed slot leaks (re-admission maps
            # pages lazily, so the slot may legitimately hold none)
            assert eng._mgr.free_pages >= free_before_kill
            assert stats.counter("serving.watchdog_kills").value \
                == k_before + 1
            evs = [e["ev"] for e in eng.journal.events(victim)]
            assert evs.count("watchdog") == 2
            # the engine still serves other traffic to parity
            r2 = eng.submit(np.arange(5) + 1, max_new_tokens=3)
            done = {r.id: r for r in eng.run()}
            assert done[r2].state == "ok"
            np.testing.assert_array_equal(
                done[r2].output,
                _ref_outputs([np.arange(5) + 1], 3)[0])

    def test_wedged_decode_preempts_then_resumes_parity(self):
        """First watchdog trip on a decode slot preempts by
        recomputation — once the wedge clears, the stream resumes
        EXACTLY (the PR 8 preempt/resume machinery)."""
        model = _model()
        n = 2
        with _flags(serve_watchdog_steps=n):
            eng = _engine(model, max_batch=1)
            p = np.arange(8) + 3
            rid = eng.submit(p, max_new_tokens=8)
            while eng.num_active == 0:    # prefill through to decode
                eng.step()
            req = eng._slots[0]
            before = stats.counter("serving.preemptions").value
            for _ in range(n + 1):
                eng._watchdog_tick()      # trip 1: preempt + requeue
            assert stats.counter("serving.preemptions").value \
                == before + 1
            assert req.n_preempts == 1 and req._wd_trips == 1
            done = {r.id: r for r in eng.run()}
            assert done[rid].state == "ok"
            np.testing.assert_array_equal(done[rid].output,
                                          _ref_outputs([p], 8)[0])

    def test_watchdog_disabled_by_zero(self):
        """0 disables the watchdog: ticks never trip, whatever the
        (frozen) progress marker says."""
        model = _model()
        with _flags(serve_watchdog_steps=0):
            eng = _engine(model)
            rid = eng.submit(np.arange(30), max_new_tokens=2)
            eng.step()
            req = next(iter(eng._prefilling.values())).req
            for _ in range(50):
                eng._watchdog_tick()
            assert req._wd_trips == 0 and req.state is None
            done = {r.id: r for r in eng.run()}
            assert done[rid].state == "ok"


# =====================================================================
# overload shedding + graceful degradation
# =====================================================================

class TestOverloadShedding:
    def test_inbox_bound_backpressures_submitter(self):
        model = _model()
        shed_before = stats.counter("serving.shed").value
        with _flags(serve_inbox_limit=2):
            eng = _engine(model)
            eng.submit(np.arange(4), max_new_tokens=2)
            eng.submit(np.arange(4), max_new_tokens=2)
            with pytest.raises(ServerOverloaded, match="inbox"):
                eng.submit(np.arange(4), max_new_tokens=2)
        assert stats.counter("serving.shed").value == shed_before + 1

    def test_queue_depth_sheds_at_submit_and_drain(self):
        """Past the queue-depth threshold, submits reject AND the
        drain-side backstop sheds the sorted queue's overflow tail
        (lowest priority last) into the 'shed' terminal state."""
        model = _model()
        with _flags(serve_shed_queue_depth=3):
            eng = _engine(model, max_batch=1)
            # race-past-submit simulation: stuff the inbox directly
            reqs = [Request(np.arange(4), 2, priority=pr)
                    for pr in (5, 5, 0, 0, 0)]
            with eng._inbox_lock:
                eng._inbox.extend(reqs)
            eng._drain_inbox()
            shed = [r for r in reqs if r.state == "shed"]
            assert len(shed) == 2
            assert all(r.priority == 0 for r in shed)  # tail sheds
            assert all(isinstance(r.error, ServerOverloaded)
                       for r in shed)
            assert all(r in eng.finished for r in shed)
            # the survivors still serve to completion
            done = {r.id: r for r in eng.run()}
            for r in reqs:
                if r.state != "shed":
                    assert done[r.id].state == "ok"

    def test_burn_rate_shed(self):
        """A burn rate past FLAGS_serve_shed_burn_rate rejects new
        load while the service is missing its objective."""
        model = _model()
        with _flags(serve_shed_burn_rate=2.0):
            eng = _engine(model, slo=SLOConfig(
                prefill_chunk=16, ttft_target_ms=0.001,
                goodput_objective=0.9))
            # drive a few finishes that MISS the (absurd) TTFT target
            for _ in range(3):
                eng.submit(np.arange(4), max_new_tokens=2)
            eng.run()
            assert eng.slo_monitor.burn_rate > 2.0
            with pytest.raises(ServerOverloaded, match="burn"):
                eng.submit(np.arange(4), max_new_tokens=2)

    def test_chunk_shrink_before_stall(self):
        """Graceful degradation: a squeezed pool that can't fit the
        full chunk serves a SMALLER chunk instead of stalling — and
        the tokens still match the dense reference exactly."""
        model = _model()
        shrink_before = stats.counter("serving.chunk_shrinks").value
        stall_before = stats.counter("serving.prefill_stalls").value
        eng = _engine(model, max_batch=2, max_length=64, num_pages=15,
                      slo=SLOConfig(prefill_chunk=8,
                                    prefix_cache=False))
        inj = FaultInjector()
        eng.install_faults(inj)
        p = np.arange(20) % 64
        rid = eng.submit(p, max_new_tokens=3)
        eng.step()                        # admit + first chunk
        # leave exactly ONE page free: the next full 8-token chunk
        # needs 2 pages, a shrunk 4-token chunk needs 1
        inj._squeeze(eng._mgr.free_pages - 1)
        eng.step()
        assert stats.counter("serving.chunk_shrinks").value \
            > shrink_before
        inj.release_all()
        done = {r.id: r for r in eng.run()}
        assert done[rid].state == "ok"
        np.testing.assert_array_equal(done[rid].output,
                                      _ref_outputs([p], 3)[0])
        assert stats.counter("serving.prefill_stalls").value \
            == stall_before                # shrink PREVENTED the stall

    def test_shrink_disabled_falls_back_to_stall(self):
        model = _model()
        stall_before = stats.counter("serving.prefill_stalls").value
        with _flags(serve_chunk_shrink=False):
            eng = _engine(model, max_batch=2, max_length=64,
                          num_pages=15,
                          slo=SLOConfig(prefill_chunk=8,
                                        prefix_cache=False))
            inj = FaultInjector()
            eng.install_faults(inj)
            r_dec = eng.submit(np.arange(4), max_new_tokens=30)
            for _ in range(3):
                eng.step()
            rid = eng.submit(np.arange(20) + 1, max_new_tokens=3)
            eng.step()
            inj._squeeze(eng._mgr.free_pages - 1)
            for _ in range(6):
                eng.step()
            assert stats.counter("serving.prefill_stalls").value \
                > stall_before
            inj.release_all()
            done = {r.id: r for r in eng.run()}
            assert done[rid].state == "ok" and done[r_dec].state == "ok"


# =====================================================================
# journal / crash-dump hardening
# =====================================================================

class TestJournalHardening:
    def test_dump_jsonl_creates_directory(self, tmp_path):
        from paddle_tpu.serving.journal import FlightRecorder, load_jsonl

        jr = FlightRecorder(8)
        jr.record("submit", 1, -1, None)
        path = str(tmp_path / "deep" / "nested" / "j.jsonl")
        jr.dump_jsonl(path)
        events, _ = load_jsonl(path)
        assert len(events) == 1

    def test_crash_dump_creates_directory(self, tmp_path):
        model = _model()
        eng = _engine(model)
        path = str(tmp_path / "fresh" / "dir" / "crash.jsonl")
        out = eng.crash_dump(error=RuntimeError("x"), path=path)
        assert out == path and os.path.exists(path)

    def test_failed_dump_never_masks_original_exception(self, tmp_path):
        """An injected journal.dump fault (or any dump failure) must
        not replace the exception run() is re-raising."""
        model = _model()
        inj = (FaultInjector()
               .add("journal.dump", kind="raise", every=1, times=-1))
        eng = _engine(model, faults=inj, max_batch=2, max_length=64,
                      num_pages=15, slo=SLOConfig(prefill_chunk=8))
        rng = np.random.RandomState(37)
        eng.submit(rng.randint(0, 64, (56,)), max_new_tokens=8)
        with pytest.raises(PoolSizingError, match="num_pages"):
            eng.run()                    # NOT InjectedFault
        assert eng.last_crash_dump is None   # dump failed, silently

    def test_crash_dump_unwritable_path_returns_none(self):
        model = _model()
        eng = _engine(model)
        out = eng.crash_dump(error=RuntimeError("x"),
                             path="/proc/definitely/not/writable.jsonl")
        assert out is None


# =====================================================================
# PR 8 recovery paths driven by injected pool exhaustion
# =====================================================================

class TestRecoveryPathsChaos:
    def _pressure_engine(self, model, inj, **kw):
        kw.setdefault("max_batch", 2)
        kw.setdefault("page_size", 4)
        kw.setdefault("max_length", 64)
        kw.setdefault("decode_chunk", 2)
        kw.setdefault("num_pages", 15)
        kw.setdefault("slo", SLOConfig(prefill_chunk=8))
        return ServingEngine(model, faults=inj, **kw)

    def test_squeeze_drives_prefix_eviction_with_parity(self):
        """Injected pool exhaustion makes later grows dip into the
        prefix cache (PR 8 path 1) — tokens stay exact."""
        model = _model()
        evb = stats.counter("serving.prefix_insert_errors").value  # noqa
        inj = FaultInjector().add("decode.step", kind="squeeze",
                                  pages=3, at=1)
        eng = self._pressure_engine(model, inj)
        rng = np.random.RandomState(23)
        p1 = rng.randint(0, 64, (40,))
        eng.submit(p1, max_new_tokens=4)
        r = eng.run()[-1]
        np.testing.assert_array_equal(r.output,
                                      _ref_outputs([p1], 4)[0])
        cached = len(eng.prefix_cache)
        assert cached > 0
        p2 = rng.randint(0, 64, (8,))
        eng.submit(p2, max_new_tokens=12)
        r2 = eng.run()[-1]
        np.testing.assert_array_equal(r2.output,
                                      _ref_outputs([p2], 12)[0])
        assert len(eng.prefix_cache) < cached   # eviction engaged
        inj.release_all()

    def test_squeeze_drives_stall_and_requeue_with_parity(self):
        """With the pool squeezed, concurrent chunked prefills stall
        behind decoders / requeue each other (PR 8 path 2) and still
        produce exact streams once pages free."""
        model = _model()
        with _flags(serve_chunk_shrink=False):
            inj = (FaultInjector()
                   .add("decode.step", kind="squeeze", pages=4, at=0)
                   .add("decode.step", kind="release", at=10))
            eng = self._pressure_engine(model, inj)
            rng = np.random.RandomState(29)
            p_dec = rng.randint(0, 64, (8,))
            p_big = rng.randint(0, 64, (30,))
            r1 = eng.submit(p_dec, max_new_tokens=16)
            r2 = eng.submit(p_big, max_new_tokens=4)
            done = {r.id: r for r in eng.run()}
            assert done[r1].state == "ok" and done[r2].state == "ok"
            np.testing.assert_array_equal(
                done[r1].output, _ref_outputs([p_dec], 16)[0])
            np.testing.assert_array_equal(
                done[r2].output, _ref_outputs([p_big], 4)[0])
            inj.release_all()

    def test_preemption_by_recompute_with_parity(self):
        """Three concurrent decoders + a squeeze: least-urgent slots
        preempt by recomputation (PR 8 path 3) and every stream is
        exact and delivered once, in order."""
        model = _model()
        before = stats.counter("serving.preemptions").value
        inj = FaultInjector().add("decode.step", kind="squeeze",
                                  pages=2, at=2)
        eng = self._pressure_engine(model, inj, max_batch=3)
        rng = np.random.RandomState(31)
        prompts = [rng.randint(0, 64, (16,)) for _ in range(3)]
        streamed = {}
        rids = [eng.submit(
            p, max_new_tokens=16,
            on_token=lambda r, t: streamed.setdefault(r.id, [])
            .append(t)) for p in prompts]
        done = {r.id: r for r in eng.run()}
        for rid, p, ref in zip(rids, prompts,
                               _ref_outputs(prompts, 16)):
            assert done[rid].state == "ok"
            np.testing.assert_array_equal(done[rid].output, ref)
            assert streamed[rid] == list(done[rid].generated)
        assert stats.counter("serving.preemptions").value > before
        inj.release_all()


# =====================================================================
# acceptance: 5-site seeded schedule, survivor parity, bounded loss
# =====================================================================

class TestAcceptance:
    def test_five_site_schedule_survivor_parity(self):
        """ISSUE 11 acceptance: a seeded schedule spanning >=5 distinct
        fault sites under concurrent load — the loop never exits,
        every request reaches a terminal state, survivors' greedy
        tokens are IDENTICAL to a fault-free run, and goodput loss is
        bounded by the failed share."""
        model = _model()
        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, 64, (L,))
                   for L in (37, 6, 9, 22, 5, 14)]
        max_new = 6

        # fault-free reference
        eng0 = _engine(model)
        rids0 = [eng0.submit(p, max_new_tokens=max_new)
                 for p in prompts]
        base = {i: list(r.generated) for i, r in enumerate(
            eng0.run()[j] for j, _ in enumerate(rids0))}
        base_by_rid = {r.id: r for r in eng0.finished}
        base = {i: list(base_by_rid[rid].generated)
                for i, rid in enumerate(rids0)}

        inj = (FaultInjector(seed=0)
               .add("kv.grow", kind="raise", at=1)
               .add("prefill.dispatch", kind="raise", at=2)
               .add("prefill.dispatch", kind="delay", at=5,
                    delay_ms=1.0)
               .add("decode.step", kind="raise", at=2)
               .add("decode.step", kind="corrupt", at=5)
               .add("decode.step", kind="squeeze", pages=3, at=7)
               .add("prefix.insert", kind="raise", at=0)
               .add("journal.dump", kind="raise", at=0))
        eng = _engine(model, faults=inj)
        rids = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
        done = {r.id: r for r in eng.run()}     # never raises

        assert sorted(done) == sorted(rids)     # all terminal
        survivors = 0
        for i, rid in enumerate(rids):
            st = done[rid].state
            assert st in ("ok", "error", "deadline_exceeded"), st
            if st == "ok":
                survivors += 1
                assert list(done[rid].generated) == base[i], \
                    f"survivor {i} diverged from fault-free run"
            else:
                assert done[rid].error is not None
        assert survivors >= len(prompts) - 2    # bounded goodput loss
        # forensic dump swallows its injected fault
        assert eng.crash_dump(error=None) is None
        sites = {f["site"] for f in inj.fired}
        assert len(sites) >= 5, sites
        inj.release_all()

    def test_journal_carries_fault_timeline(self):
        """Every injected fire lands on the flight recorder as a
        ``fault`` event (the post-mortem's first question: what was
        injected, when)."""
        model = _model()
        inj = FaultInjector().add("decode.step", kind="raise", at=0)
        eng = _engine(model, faults=inj)
        rid = eng.submit(np.arange(8), max_new_tokens=4)
        done = {r.id: r for r in eng.run()}
        assert done[rid].state == "ok"
        evs = [e["ev"] for e in eng.journal.events()]
        assert "fault" in evs and "retry" in evs

    def test_serving_counters_registered_in_conventions(self):
        """The new failure-semantics counters live in documented
        namespaces (the naming lint covers the live registry)."""
        from paddle_tpu.profiler.stats import CONVENTION_PREFIXES

        for name in ("serving.step_retries", "serving.request_errors",
                     "serving.deadline_exceeded", "serving.shed",
                     "serving.watchdog_preempts", "serving.chunk_shrinks",
                     "serving.faults_injected", "slo.errors"):
            assert any(name.startswith(p) for p in CONVENTION_PREFIXES)


class TestChaosBenchCLI:
    def test_serve_bench_chaos_emits_and_passes(self):
        """CLI pin: --chaos emits the serve_chaos_* rungs, fires >=5
        distinct sites, and exits 0 (all robustness pins green)."""
        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "serve_bench.py"),
             "--streams", "2", "--requests", "5", "--max-new", "4",
             "--prompt-mix", "8,24", "--prefill-chunk", "16",
             "--decode-chunk", "4", "--rate", "500", "--no-lint",
             "--chaos"],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["serve_chaos_survivor_parity"] == 1.0
        assert out["serve_chaos_goodput_bound_ok"] == 1
        assert out["serve_chaos_dump_survived"] == 1
        assert len(out["serve_chaos_sites_fired"]) >= 5
        assert out["serve_chaos_faults_injected"] >= 5

    def test_bench_gate_gates_chaos_rungs(self):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import bench_gate
        finally:
            sys.path.pop(0)
        m = bench_gate.DEFAULT_METRICS
        assert m["serve_chaos_survivor_parity"] == "down"
        assert m["serve_chaos_goodput"] == "down"
        assert m["serve_chaos_tokens_per_sec"] == "down"
        assert m["serve_chaos_request_errors"] == "up"
