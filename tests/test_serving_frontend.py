"""SLO-aware serving frontend (paddle_tpu.serving): chunked prefill,
prefix/KV reuse, skip-ahead admission, lifecycle telemetry, serve bench.

Tier-1 acceptance pins (ISSUE 8):
- chunked prefill BOUNDS decode stall: a 1k-token prompt admitted
  mid-stream never opens an inter-token gap beyond one prefill chunk
  plus the decode chunk (``TestChunkedPrefill.test_stall_bound_*``);
- prefix reuse: two requests sharing a system prompt allocate strictly
  fewer pool pages than two cold requests, and freeing one never
  corrupts the other (refcounts — ``TestPrefixReuse``).
"""
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine, FusedCausalLM
from paddle_tpu.inference.kv_cache import BlockKVCacheManager
from paddle_tpu.profiler import stats
from paddle_tpu.serving import (PrefixCache, Request, ServingEngine,
                                SLOConfig)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model(seed=7, max_position=256):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=max_position)


def _dense_greedy(model, prompt, n):
    seq = np.asarray(prompt, np.int64).reshape(1, -1)
    for _ in range(n):
        logits = model(paddle.to_tensor(seq)).numpy()
        nxt = logits[:, -1].argmax(-1)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    return seq[0]


class TestChunkedPrefill:
    def test_long_prompt_chunked_parity(self):
        """A prompt spanning several prefill chunks (with a ragged
        tail) must decode exactly like the dense reference — the
        chunk program attends to cached pages + the in-chunk causal
        triangle."""
        model = _model()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 64, (L,)) for L in (37, 6, 9)]
        streamed = {}
        eng = ServingEngine(
            model, max_batch=3, page_size=4, max_length=128,
            decode_chunk=2, slo=SLOConfig(prefill_chunk=16))
        rids = [eng.submit(
            p, max_new_tokens=6,
            on_token=lambda r, t: streamed.setdefault(r.id, [])
            .append(t)) for p in prompts]
        done = {r.id: r for r in eng.run()}
        assert sorted(done) == sorted(rids)
        for rid, p in zip(rids, prompts):
            ref = _dense_greedy(model, p, 6)
            np.testing.assert_array_equal(done[rid].output, ref,
                                          err_msg=f"req {rid}")
            # streaming callback saw every token, in order
            assert streamed[rid] == list(done[rid].generated)
        # lifecycle telemetry stamped per request
        for r in done.values():
            assert r.ttft_s is not None and r.ttft_s >= 0
            assert r.queue_wait_s is not None
        assert stats.counter("serve.prefill_chunks").value > 0

    def test_stall_bound_1k_prompt_mid_stream(self):
        """ISSUE 8 acceptance: a 1k-token prompt admitted while a
        short request decodes must NOT stall it — with the default
        1:1 SLO weights at most ONE prefill chunk ever runs between
        that request's decode chunks, so its inter-token gap is
        bounded by (prefill_chunk + decode_chunk) of device work."""
        model = _model(max_position=1280)
        rng = np.random.RandomState(5)
        short = rng.randint(0, 64, (6,))
        long_p = rng.randint(0, 64, (1024,))
        eng = ServingEngine(
            model, max_batch=2, page_size=8, max_length=1152,
            decode_chunk=4, slo=SLOConfig(prefill_chunk=128))
        # A stays decode-active through B's entire 8-chunk prefill
        # (48 tokens / k=4 = 12 decode chunks > 8 prefill chunks), so
        # the bound must hold over the WHOLE action log
        ra = eng.submit(short, max_new_tokens=48)
        # get the short request decoding first
        while eng.num_active == 0:
            eng.step()
        eng.action_log.clear()
        rb = eng.submit(long_p, max_new_tokens=4)
        done = {r.id: r for r in eng.run()}
        assert set(done) == {ra, rb}
        # the bound: while A was decode-active, never two consecutive
        # prefill actions (1024/128 = 8 chunks all interleaved)
        log = eng.action_log
        assert log.count("prefill") >= 8, log
        for i in range(len(log) - 1):
            if log[i] == "prefill" and i + 1 < len(log):
                assert log[i + 1] == "decode", (
                    f"two consecutive prefill chunks at {i}: "
                    f"{log[max(0, i - 2): i + 3]}")
        # and both outputs still exact
        np.testing.assert_array_equal(
            done[ra].output, _dense_greedy(model, short, 48))
        np.testing.assert_array_equal(
            done[rb].output, _dense_greedy(model, long_p, 4))

    def test_ttft_weighted_interleave(self):
        """ttft_weight 2:1 allows two prefill chunks per decode chunk;
        the cycle is derived, not hardcoded."""
        assert SLOConfig(ttft_weight=2, tpot_weight=1) \
            .prefill_burst == 2
        assert SLOConfig(ttft_weight=1, tpot_weight=2) \
            .decode_burst == 2
        assert SLOConfig().prefill_burst == 1
        assert SLOConfig().decode_burst == 1
        with pytest.raises(ValueError):
            SLOConfig(ttft_weight=0)


class TestPrefixReuse:
    def _engine(self, model, **kw):
        kw.setdefault("max_batch", 2)
        kw.setdefault("page_size", 4)
        kw.setdefault("max_length", 128)
        kw.setdefault("decode_chunk", 2)
        kw.setdefault("slo", SLOConfig(prefill_chunk=8))
        return ServingEngine(model, **kw)

    def test_shared_prefix_allocates_strictly_fewer_pages(self):
        """ISSUE 8 acceptance: two requests sharing a 16-token system
        prompt allocate strictly fewer pool pages than two cold
        requests — exactly 4 pages (the full prefix pages) fewer."""
        model = _model()
        rng = np.random.RandomState(11)
        sysp = rng.randint(0, 64, (16,))
        tails = [rng.randint(0, 64, (5,)), rng.randint(0, 64, (7,))]
        prompts = [np.concatenate([sysp, t]) for t in tails]

        def run_pair(prefix_cache):
            eng = self._engine(_model(), slo=SLOConfig(
                prefill_chunk=8, prefix_cache=prefix_cache))
            allocated = []
            orig_alloc = BlockKVCacheManager.allocate
            orig_grow = BlockKVCacheManager.grow

            def spy_alloc(mgr, seq_id, n):
                r = orig_alloc(mgr, seq_id, n)
                allocated.extend(r)
                return r

            def spy_grow(mgr, seq_id, n):
                r = orig_grow(mgr, seq_id, n)
                allocated.extend(r)
                return r

            BlockKVCacheManager.allocate = spy_alloc
            BlockKVCacheManager.grow = spy_grow
            try:
                for p in prompts:   # sequential: 2nd hits the cache
                    eng.submit(p, max_new_tokens=4)
                    eng.run()
            finally:
                BlockKVCacheManager.allocate = orig_alloc
                BlockKVCacheManager.grow = orig_grow
            return len(allocated), eng

        before_saved = stats.counter("serving.prefix_pages_saved").value
        cold_pages, _ = run_pair(prefix_cache=False)
        warm_pages, eng = run_pair(prefix_cache=True)
        assert warm_pages < cold_pages
        # the 16-token prefix = 4 full pages at page_size 4
        assert cold_pages - warm_pages == 4
        saved = stats.counter("serving.prefix_pages_saved").value \
            - before_saved
        assert saved == 4
        assert stats.counter("serving.prefix_hit").value >= 1
        # outputs unaffected by reuse
        for r, p in zip(eng.finished, prompts):
            np.testing.assert_array_equal(
                r.output, _dense_greedy(model, p, 4))

    def test_refcount_free_does_not_corrupt_sharer(self):
        """ISSUE 8 acceptance: with two live sharers of one prefix,
        freeing the first must not free/corrupt the pages the second
        still maps (refcount), and its tokens stay exact."""
        model = _model()
        rng = np.random.RandomState(13)
        sysp = rng.randint(0, 64, (16,))
        pa = np.concatenate([sysp, rng.randint(0, 64, (5,))])
        pb = np.concatenate([sysp, rng.randint(0, 64, (6,))])
        pc = np.concatenate([sysp, rng.randint(0, 64, (7,))])
        eng = self._engine(model, max_batch=2)
        eng.submit(pa, max_new_tokens=2)
        eng.run()          # cold run registers pa's 5 full pages
        assert len(eng.prefix_cache) == 5   # 21 tokens // page 4
        assert all(eng._mgr.refcount(p) == 1
                   for p in eng.prefix_cache._entries.values())
        # the chain B/C share with A is the 4 system-prompt pages
        shared = eng.prefix_cache.match(pb)
        assert len(shared) == 4

        # B (short) and C (long) decode concurrently, both sharing
        rb = eng.submit(pb, max_new_tokens=2)
        rc = eng.submit(pc, max_new_tokens=12)
        while not any(r.id == rb for r in eng.finished):
            eng.step()
        # B freed its pages; C still maps the prefix: refcount must be
        # cache(1) + C(1) — B's free took only ITS reference
        assert any(r is not None and r.id == rc for r in eng._slots) \
            or rc in [s.req.id for s in eng._prefilling.values()]
        assert all(eng._mgr.refcount(p) == 2 for p in shared)
        done = {r.id: r for r in eng.run()}
        np.testing.assert_array_equal(
            done[rc].output, _dense_greedy(model, pc, 12))
        # drained: only the cache's references remain (pa's 5 pages +
        # B's and C's own full tail page each); pool accounting exact
        assert len(eng.prefix_cache) == 7
        cached = list(eng.prefix_cache._entries.values())
        assert all(eng._mgr.refcount(p) == 1 for p in cached)
        assert eng._mgr.free_pages == eng._mgr.num_pages - 1 \
            - len(cached)
        # eviction returns them and the pool closes the loop
        eng.prefix_cache.clear()
        assert eng._mgr.free_pages == eng._mgr.num_pages - 1

    def test_prefix_never_covers_whole_prompt(self):
        """A prompt that is ENTIRELY full cached pages must still
        prefill its last token (the first emitted token needs a fresh
        hidden state): match is capped at (len-1)//page_size pages."""
        mgr = BlockKVCacheManager(2, 4, 8, page_size=4, num_pages=16,
                                  reserve_scratch=True)
        cache = PrefixCache(mgr, page_size=4)
        prompt = np.arange(16, dtype=np.int32)
        pages = mgr.allocate("a", 16)
        cache.insert(prompt, pages)
        assert len(cache) == 4
        hit = cache.match(prompt)           # same 16 tokens
        assert len(hit) == 3                # NOT 4: last page prefills
        assert hit == pages[:3]


class TestKVRefcounting:
    def test_share_then_free_order_independent(self):
        mgr = BlockKVCacheManager(2, 4, 8, page_size=4, num_pages=16,
                                  reserve_scratch=True)
        a = mgr.allocate("a", 8)            # 2 pages, rc=1
        mgr.share("b", a)                   # rc=2
        mgr.allocate("b", 4)                # +1 private page
        free0 = mgr.free_pages
        mgr.free("a")                       # shared rc 2->1: not freed
        assert mgr.free_pages == free0
        assert all(mgr.refcount(p) == 1 for p in a)
        mgr.free("b")                       # last ref: all back
        assert mgr.free_pages == 15
        assert all(mgr.refcount(p) == 0 for p in a)

    def test_release_guards(self):
        mgr = BlockKVCacheManager(2, 4, 8, page_size=4, num_pages=16)
        with pytest.raises(KeyError):
            mgr.retain([3])                 # never allocated
        pages = mgr.allocate("a", 4)
        mgr.free("a")
        with pytest.raises(KeyError):
            mgr.release_pages(pages)        # double free


class TestAdmission:
    def _busy_engine(self):
        """max_batch=3 engine whose pool is mostly eaten by one active
        long request, so page-hungry admissions don't fit."""
        model = _model()
        eng = ContinuousBatchingEngine(
            model, max_batch=3, page_size=4, max_length=64,
            decode_chunk=2, num_pages=15)
        rng = np.random.RandomState(17)
        eng.submit(rng.randint(0, 64, (40,)), max_new_tokens=20)
        eng.step()
        assert eng.num_active == 1
        return eng, rng

    def test_skip_ahead_fixes_head_of_line(self):
        """When the head's pages don't fit, a later request that fits
        admits instead of blocking — with the skip counted."""
        eng, rng = self._busy_engine()
        before = stats.counter("serving.admission_skips").value
        big = eng.submit(rng.randint(0, 64, (24,)), max_new_tokens=4)
        small = eng.submit(rng.randint(0, 64, (4,)), max_new_tokens=4)
        eng.step()
        active_ids = [r.id for r in eng._slots if r is not None]
        assert small in active_ids, "small request head-of-line blocked"
        assert big in [r.id for r in eng.waiting]
        assert stats.counter("serving.admission_skips").value \
            == before + 1
        done = {r.id: r for r in eng.run()}     # big admits eventually
        assert big in done and done[big].done

    def test_starvation_bound_pins_queue(self):
        """After starvation_bound skips the window collapses to the
        head: later requests stop flowing past it even if they fit."""
        eng, rng = self._busy_engine()
        eng.starvation_bound = 1
        big = eng.submit(rng.randint(0, 64, (24,)), max_new_tokens=4)
        s1 = eng.submit(rng.randint(0, 64, (4,)), max_new_tokens=4)
        s2 = eng.submit(rng.randint(0, 64, (4,)), max_new_tokens=4)
        eng.step()     # s1 skips past big (big now at the bound)
        active_ids = [r.id for r in eng._slots if r is not None]
        assert s1 in active_ids
        # a slot is free and s2 fits, but big pins the queue now
        assert eng.num_active == 2
        assert [r.id for r in eng.waiting] == [big, s2]
        done = {r.id: r for r in eng.run()}
        assert len(done) == 4                  # drains completely

    def test_priority_admits_first(self):
        """Higher-priority requests admit ahead of earlier arrivals."""
        model = _model()
        eng = ServingEngine(model, max_batch=1, page_size=4,
                            max_length=64, decode_chunk=2,
                            slo=SLOConfig(prefill_chunk=8))
        rng = np.random.RandomState(19)
        lo = eng.submit(rng.randint(0, 64, (4,)), max_new_tokens=2)
        hi = eng.submit(rng.randint(0, 64, (4,)), max_new_tokens=2,
                        priority=5)
        eng.step()
        admitted = [s.req.id for s in eng._prefilling.values()] \
            + [r.id for r in eng._slots if r is not None]
        assert admitted == [hi]
        done = [r.id for r in eng.run()]
        assert set(done) == {lo, hi}


class TestPoolPressure:
    """Admission reserves only the FIRST prefill chunk's pages, so
    later chunk grows and decode-time grows must recover under pool
    pressure (evict cold cached prefixes; preempt-by-recompute as the
    last resort) instead of crashing ``run()``."""

    def _engine(self, **kw):
        kw.setdefault("max_batch", 2)
        kw.setdefault("page_size", 4)
        kw.setdefault("max_length", 64)
        kw.setdefault("decode_chunk", 2)
        kw.setdefault("num_pages", 15)   # true 16-page pool (1 scratch)
        kw.setdefault("slo", SLOConfig(prefill_chunk=8))
        return ServingEngine(_model(), **kw)

    def test_prefill_grow_evicts_cached_prefixes(self):
        """REVIEW repro: 16-page pool, sequential 40-token prompts.
        The unbounded prefix cache holds the first prompt's 10 pages;
        the second request's LATER chunks must evict them instead of
        dying on 'KV pool exhausted'."""
        model = _model()
        eng = self._engine()
        assert eng._mgr.num_pages == 16
        rng = np.random.RandomState(23)
        for p in [rng.randint(0, 64, (40,)) for _ in range(3)]:
            eng.submit(p, max_new_tokens=4)
            r = eng.run()[-1]
            np.testing.assert_array_equal(
                r.output, _dense_greedy(model, p, 4))

    def test_decode_grow_evicts_cached_prefixes(self):
        """Decode-time grows (engine step) under pool pressure must
        also dip into the prefix cache."""
        model = _model()
        eng = self._engine()
        rng = np.random.RandomState(27)
        eng.submit(rng.randint(0, 64, (40,)), max_new_tokens=2)
        eng.run()                       # cache now holds 10 pages
        cached = len(eng.prefix_cache)
        assert cached == 10
        p = rng.randint(0, 64, (8,))    # tiny prefill, long decode
        eng.submit(p, max_new_tokens=28)
        r = eng.run()[-1]
        np.testing.assert_array_equal(
            r.output, _dense_greedy(model, p, 28))
        assert len(eng.prefix_cache) < cached   # eviction happened

    def test_decode_pressure_preempts_and_resumes_exact(self):
        """Three concurrent decoders whose combined growth exceeds the
        pool: least-urgent slots are preempted by recomputation and
        resumed, with every stream exact and every token delivered
        once, in order. Three slots also pin the grow loop's skip of a
        slot preempted by an EARLIER slot's grow in the same step."""
        model = _model()
        before = stats.counter("serving.preemptions").value
        eng = self._engine(max_batch=3)
        rng = np.random.RandomState(29)
        prompts = [rng.randint(0, 64, (16,)) for _ in range(3)]
        streamed = {}
        rids = [eng.submit(
            p, max_new_tokens=24,
            on_token=lambda r, t: streamed.setdefault(r.id, [])
            .append(t)) for p in prompts]
        done = {r.id: r for r in eng.run()}
        assert sorted(done) == sorted(rids)
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(
                done[rid].output, _dense_greedy(model, p, 24))
            assert streamed[rid] == list(done[rid].generated)
        assert stats.counter("serving.preemptions").value > before

    def test_admit_eviction_recomputes_first_chunk_need(self):
        """REVIEW: _can_admit's eviction loop can evict the very chain
        its page count treated as covered; the admit decision must
        reflect the post-eviction cache, or the first chunk's grow
        exceeds the free list."""
        eng = self._engine(prompt_bucket=4)
        rng = np.random.RandomState(31)
        prompt = rng.randint(0, 64, (13,))
        # LRU-coldest entry: a page a live sequence still maps, so
        # evicting it frees nothing and the loop digs into the chain
        pinned = eng._mgr.allocate("live", 4)
        eng.prefix_cache.insert(np.arange(4), pinned)
        own = eng._mgr.allocate("tmp", 12)
        eng.prefix_cache.insert(prompt[:12], own)
        eng._mgr.free("tmp")            # the chain survives, cache-held
        eng._mgr.allocate("ballast", 4 * eng._mgr.free_pages)
        req = Request(prompt, max_new_tokens=4)
        admitted = eng._can_admit(req)
        if admitted:   # the admit promise must be honest post-eviction
            assert eng._first_chunk_pages(req) <= eng._mgr.free_pages

    def test_oversized_request_raises_informative(self):
        """A request whose pages can NEVER fit the pool (even with the
        cache drained and every peer gone) raises a sizing error
        rather than spinning or crashing obscurely."""
        eng = self._engine()
        rng = np.random.RandomState(37)
        eng.submit(rng.randint(0, 64, (56,)), max_new_tokens=8)
        with pytest.raises(RuntimeError, match="num_pages"):
            eng.run()


class TestSatellites:
    def test_genrequest_ids_thread_safe(self):
        """ISSUE 8 satellite: concurrent construction never duplicates
        ids (itertools.count, atomic under CPython)."""
        from paddle_tpu.inference import GenRequest

        ids = []
        lock = threading.Lock()

        def worker():
            local = [GenRequest([1], 1).id for _ in range(250)]
            with lock:
                ids.extend(local)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(ids) == len(set(ids)) == 2000

    def test_wasted_decode_tokens_counted(self):
        """Tokens decoded past req.done inside a chunk are counted —
        the decode_chunk tuning signal."""
        model = _model()
        eng = ContinuousBatchingEngine(model, max_batch=1, page_size=4,
                                       max_length=64, decode_chunk=4)
        before = stats.counter("serving.wasted_decode_tokens").value
        eng.submit(np.array([1, 2, 3]), max_new_tokens=2)
        eng.run()
        # admission emits token 1; the k=4 chunk consumes 1 more and
        # discards 3
        assert stats.counter("serving.wasted_decode_tokens").value \
            == before + 3

    def test_tpot_observed_per_token(self):
        """REVIEW: serve.tpot_ms weights per TOKEN and a slot that
        finishes mid-chunk still contributes — every decoded token is
        exactly one histogram observation."""
        model = _model()
        eng = ServingEngine(model, max_batch=2, page_size=4,
                            max_length=64, decode_chunk=4,
                            slo=SLOConfig(prefill_chunk=8))
        h = stats.histogram("serve.tpot_ms")
        before = h.count
        rng = np.random.RandomState(41)
        # max_new 6 with k=4: chunks emit 4 then 2 mid-chunk tokens
        eng.submit(rng.randint(0, 64, (6,)), max_new_tokens=6)
        # max_new 2: a single mid-chunk token, previously unobserved
        eng.submit(rng.randint(0, 64, (6,)), max_new_tokens=2)
        eng.run()
        # each request's first token comes from prefill; every decoded
        # token after it is one observation: (6-1) + (2-1)
        assert h.count - before == 6

    def test_serve_prefix_registered_in_conventions(self):
        """ISSUE 8 satellite: serve./serving. are documented metric
        namespaces (the naming lint in test_profiler_stats covers the
        live registry)."""
        assert "serve." in stats.CONVENTION_PREFIXES
        assert "serving." in stats.CONVENTION_PREFIXES

    def test_request_slo_properties(self):
        r = Request([1, 2], max_new_tokens=4, priority=2,
                    arrival_time=100.0)
        assert r.priority == 2 and r.arrival_time == 100.0
        assert r.ttft_s is None and r.tpot_s is None
        r.t_admitted = 100.5
        r.t_first_token = 101.0
        assert r.queue_wait_s == pytest.approx(0.5)
        assert r.ttft_s == pytest.approx(1.0)
        r.generated = [1, 2, 3]
        r.t_done = 102.0
        assert r.tpot_s == pytest.approx(0.5)


class TestServeBench:
    def test_cli_smoke_emits_slo_rungs(self):
        """ISSUE 8 acceptance: serve_bench runs on CPU and emits the
        serve_{p50,p99}_ttft_ms + serve_tokens_per_sec rungs with a
        telemetry block."""
        import json

        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "serve_bench.py"),
             "--streams", "2", "--requests", "4", "--seed", "0",
             "--prompt-mix", "6,14", "--system-prompt", "8",
             "--max-new", "4", "--prefill-chunk", "8",
             "--decode-chunk", "2", "--d-model", "32", "--layers", "1",
             "--heads", "2", "--vocab", "64", "--rate", "500",
             "--no-lint"],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr[-2000:]
        doc = json.loads(
            [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")][-1])
        for key in ("serve_p50_ttft_ms", "serve_p99_ttft_ms",
                    "serve_tokens_per_sec"):
            assert isinstance(doc[key], (int, float)), key
        assert doc["serve_p50_ttft_ms"] <= doc["serve_p99_ttft_ms"]
        assert doc["serve_requests"] == 4
        tele = doc["telemetry"]
        assert "serve.ttft_ms" in tele["histograms"]
        assert tele["histograms"]["serve.ttft_ms"]["count"] == 4

    def test_bench_gate_gates_serve_rungs(self):
        """TTFT regresses UP, tokens/sec DOWN; improvements pass."""
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import bench_gate
        finally:
            sys.path.pop(0)
        prev = {"serve_p50_ttft_ms": 10.0, "serve_p99_ttft_ms": 40.0,
                "serve_tokens_per_sec": 1000.0}
        worse_ttft = dict(prev, serve_p99_ttft_ms=80.0)
        bad, n = bench_gate.gate(prev, worse_ttft)
        assert n and any("serve_p99_ttft_ms" in ln for ln in bad)
        worse_tps = dict(prev, serve_tokens_per_sec=500.0)
        bad, _ = bench_gate.gate(prev, worse_tps)
        assert any("serve_tokens_per_sec" in ln for ln in bad)
        better = {"serve_p50_ttft_ms": 5.0, "serve_p99_ttft_ms": 20.0,
                  "serve_tokens_per_sec": 2000.0}
        bad, _ = bench_gate.gate(prev, better)
        assert not bad
