"""paddle.signal + incubate optimizers tests (reference:
test/legacy_test/test_stft_op.py, test_lookahead.py,
test_modelaverage.py)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.audio.functional import get_window


class TestSignal:
    def test_frame_overlap_add_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 1000).astype(np.float32)
        fr = paddle.signal.frame(paddle.to_tensor(x), 100, 100)
        assert fr.shape == [2, 10, 100]
        back = paddle.signal.overlap_add(fr, 100)
        np.testing.assert_allclose(back.numpy(), x, atol=1e-6)

    def test_overlapping_frames(self):
        x = paddle.to_tensor(np.arange(10, dtype=np.float32))
        fr = paddle.signal.frame(x, 4, 2)
        assert fr.shape == [4, 4]
        np.testing.assert_allclose(fr.numpy()[1], [2, 3, 4, 5])

    def test_stft_istft_roundtrip(self):
        rng = np.random.RandomState(1)
        x = rng.randn(2, 1024).astype(np.float32)
        win = get_window("hann", 256)
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=256,
                                  window=win)
        assert spec.shape == [2, 129, 17]  # onesided bins, frames
        rec = paddle.signal.istft(spec, n_fft=256, window=win,
                                  length=1024)
        np.testing.assert_allclose(rec.numpy(), x, atol=1e-4)

    def test_stft_tone_peak(self):
        sr, f, n_fft = 8000, 1000.0, 256
        t = np.arange(2048) / sr
        x = np.sin(2 * np.pi * f * t).astype(np.float32)
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=n_fft,
                                  window=get_window("hann", n_fft))
        mag = np.abs(np.asarray(spec.numpy())).mean(axis=-1)
        assert abs(int(mag.argmax()) - round(f / (sr / n_fft))) <= 1


class TestIncubateOptimizers:
    def _problem(self):
        import paddle_tpu.nn as nn

        paddle.seed(0)
        rng = np.random.RandomState(0)
        xs = rng.randn(64, 4).astype(np.float32)
        w = rng.randn(4, 1).astype(np.float32)
        ys = xs @ w
        model = nn.Linear(4, 1)
        return model, paddle.to_tensor(xs), paddle.to_tensor(ys)

    def test_lookahead_converges(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.incubate.optimizer import LookAhead

        model, x, y = self._problem()
        inner = paddle.optimizer.SGD(0.05, parameters=model.parameters())
        opt = LookAhead(inner, alpha=0.5, k=5)
        losses = []
        for _ in range(60):
            loss = F.mse_loss(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.05

    def test_model_average_apply_restore(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.incubate.optimizer import ModelAverage

        model, x, y = self._problem()
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        avg = ModelAverage(parameters=model.parameters())
        snapshots = []
        for _ in range(10):
            loss = F.mse_loss(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            avg.step()
            snapshots.append(model.weight.numpy().copy())
        live = model.weight.numpy().copy()
        avg.apply()
        np.testing.assert_allclose(model.weight.numpy(),
                                   np.mean(snapshots, axis=0), rtol=1e-5)
        avg.restore()
        np.testing.assert_allclose(model.weight.numpy(), live)

    def test_model_average_window_rollover(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate.optimizer import ModelAverage

        paddle.seed(1)
        p = nn.Linear(2, 1, bias_attr=False)
        avg = ModelAverage(parameters=p.parameters(),
                           min_average_window=3, max_average_window=3)
        vals = []
        for i in range(9):
            p.weight.set_value(paddle.to_tensor(
                np.full((2, 1), float(i), np.float32)))
            avg.step()
            vals.append(float(i))
        avg.apply()
        # windows of 3: average spans at most the last two windows
        # (values 3..8), NOT the stale 0..2
        got = float(p.weight.numpy()[0, 0])
        np.testing.assert_allclose(got, np.mean(vals[3:]), rtol=1e-5)
        avg.restore()

    def test_model_average_need_restore_false(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate.optimizer import ModelAverage

        paddle.seed(2)
        p = nn.Linear(2, 1, bias_attr=False)
        avg = ModelAverage(parameters=p.parameters())
        avg.step()
        applied = None
        avg.apply(need_restore=False)
        applied = p.weight.numpy().copy()
        avg.restore()  # must be a no-op
        np.testing.assert_allclose(p.weight.numpy(), applied)

    def test_lookahead_first_sync_moves_toward_init(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate.optimizer import LookAhead

        paddle.seed(3)
        p = nn.Linear(2, 1, bias_attr=False)
        init = p.weight.numpy().copy()
        inner = paddle.optimizer.SGD(0.0, parameters=p.parameters())
        la = LookAhead(inner, alpha=0.5, k=1)
        # manually move fast weights, then one sync
        p.weight.set_value(paddle.to_tensor(init + 2.0))
        la.step()
        # slow = init + 0.5*(fast - init) = init + 1
        np.testing.assert_allclose(p.weight.numpy(), init + 1.0,
                                   rtol=1e-5)


class TestDGCMomentum:
    def test_sparse_residual_semantics(self):
        """DGC: only top-k entries update the param; the rest accumulate
        locally and flush once they grow — total update over enough
        steps approaches plain momentum SGD on a constant gradient."""
        import paddle_tpu as paddle
        from paddle_tpu.incubate.optimizer import DGCMomentum

        paddle.seed(0)
        p = paddle.to_tensor(np.zeros((8,), np.float32))
        p.stop_gradient = False
        opt = DGCMomentum([p], learning_rate=0.1, momentum=0.0,
                          sparsity=0.75)  # k = 2 of 8
        g = np.array([8, 7, 6, 5, 4, 3, 2, 1], np.float32)
        # one step: only the top-2 |v| entries (g[0], g[1]) applied
        (p * paddle.to_tensor(g)).sum().backward()
        opt.step()
        opt.clear_grad()
        out = np.asarray(p.numpy())
        assert (out[:2] != 0).all() and np.allclose(out[2:], 0)
        np.testing.assert_allclose(out[:2], -0.1 * g[:2], rtol=1e-6)
        # keep stepping with the same grad: residuals flush in
        # magnitude order, so the set of updated coordinates grows
        # MONOTONICALLY. With k=2 the smallest coordinate (g=1)
        # accumulates 1/step against regrown large coordinates and only
        # wins a top-2 slot around step 15 — 8 steps cannot cover all 8
        # coordinates, 16 can.
        moved = {0, 1}
        for _ in range(15):
            (p * paddle.to_tensor(g)).sum().backward()
            opt.step()
            opt.clear_grad()
            now = set(np.nonzero(np.asarray(p.numpy()))[0].tolist())
            assert moved <= now  # never un-moves
            moved = now
        out = np.asarray(p.numpy())
        assert (out != 0).all()
        # conservation: total applied equals total gradient mass minus
        # what still sits in the UNSENT accumulator v. At momentum=0 the
        # velocity u is rebuilt from the fresh grad every step (its
        # leftover never feeds a later v-add), so adding u here would
        # double-count the non-selected coordinates.
        applied = -out / 0.1
        residual = np.asarray(opt._v[0])
        np.testing.assert_allclose(applied + residual, 16 * g, rtol=1e-5)

    def test_trains_small_model(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu.incubate.optimizer import DGCMomentum

        paddle.seed(1)
        w = paddle.randn([16, 4]) * 0.1
        w.stop_gradient = False
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(32, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (32,)))
        opt = DGCMomentum([w], learning_rate=0.5, momentum=0.9,
                          sparsity=0.9)
        losses = []
        for _ in range(30):
            loss = F.cross_entropy(paddle.matmul(x, w), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.8
