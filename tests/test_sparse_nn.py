"""sparse.nn: Conv3D/SubmConv3D/MaxPool3D/BatchNorm/activations/attention.

Reference parity targets: python/paddle/sparse/nn (layer/conv.py:239
Conv3D, :509 SubmConv3D; functional/transformer.py:22 attention;
kernels paddle/phi/kernels/sparse/). Numeric reference: dense conv on
the densified input.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.sparse as sparse
from paddle_tpu.sparse import nn as snn


def _rand_coo(rng, shape, density=0.2):
    """Random sparse [N, D, H, W, C] with unique active sites."""
    N, D, H, W, C = shape
    total = N * D * H * W
    n_active = max(int(total * density), 1)
    flat = rng.choice(total, n_active, replace=False)
    coords = np.stack(np.unravel_index(flat, (N, D, H, W)), axis=0)
    vals = rng.randn(n_active, C).astype(np.float32)
    return sparse.sparse_coo_tensor(coords, vals, shape=list(shape))


def _dense_conv3d(x_dense, w, b, stride, padding):
    """Dense NDHWC conv reference via numpy (small sizes)."""
    N, D, H, W, C = x_dense.shape
    kd, kh, kw, cin, cout = w.shape
    sd = sh = sw = stride
    pd = ph = pw = padding
    od = (D + 2 * pd - kd) // sd + 1
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1
    xp = np.zeros((N, D + 2 * pd, H + 2 * ph, W + 2 * pw, C),
                  x_dense.dtype)
    xp[:, pd:pd + D, ph:ph + H, pw:pw + W] = x_dense
    out = np.zeros((N, od, oh, ow, cout), np.float32)
    for n in range(N):
        for i in range(od):
            for j in range(oh):
                for k in range(ow):
                    patch = xp[n, i * sd:i * sd + kd, j * sh:j * sh + kh,
                               k * sw:k * sw + kw]
                    out[n, i, j, k] = np.tensordot(
                        patch, w, axes=([0, 1, 2, 3], [0, 1, 2, 3]))
    if b is not None:
        out += b
    return out


class TestSparseConv:
    def test_conv3d_matches_dense(self):
        rng = np.random.RandomState(0)
        shape = (2, 4, 4, 4, 3)
        x = _rand_coo(rng, shape, density=0.3)
        w = rng.randn(3, 3, 3, 3, 5).astype(np.float32) * 0.3
        b = rng.randn(5).astype(np.float32)
        out = snn.conv3d(x, paddle.to_tensor(w), paddle.to_tensor(b),
                         stride=1, padding=1)
        got = out.to_dense().numpy()
        ref = _dense_conv3d(x.to_dense().numpy(), w, None, 1, 1)
        # sparse conv adds bias only at ACTIVE output sites; compare there
        active = np.abs(got).sum(-1) > 0
        np.testing.assert_allclose(got[active], (ref + b)[active],
                                   rtol=1e-4, atol=1e-4)

    def test_subm_conv3d_preserves_pattern(self):
        rng = np.random.RandomState(1)
        shape = (1, 5, 5, 5, 2)
        x = _rand_coo(rng, shape, density=0.15)
        w = rng.randn(3, 3, 3, 2, 4).astype(np.float32)
        out = snn.subm_conv3d(x, paddle.to_tensor(w), None, stride=1,
                              padding=1)
        assert out.shape == [1, 5, 5, 5, 4]
        in_coords = set(map(tuple, np.asarray(
            x.indices().numpy()).T.tolist()))
        out_coords = set(map(tuple, np.asarray(
            out.indices().numpy()).T.tolist()))
        assert out_coords == in_coords  # submanifold contract

    def test_conv3d_layer_and_stride(self):
        rng = np.random.RandomState(2)
        paddle.seed(0)
        conv = snn.Conv3D(2, 6, kernel_size=2, stride=2, padding=0)
        x = _rand_coo(rng, (1, 4, 4, 4, 2), density=0.4)
        out = conv(x)
        assert out.shape == [1, 2, 2, 2, 6]
        w = conv.weight.numpy()
        b = conv.bias.numpy()
        ref = _dense_conv3d(x.to_dense().numpy(), w, None, 2, 0)
        got = out.to_dense().numpy()
        active = np.abs(got).sum(-1) > 0
        np.testing.assert_allclose(got[active], (ref + b)[active],
                                   rtol=1e-4, atol=1e-4)

    def test_max_pool3d(self):
        rng = np.random.RandomState(3)
        x = _rand_coo(rng, (1, 4, 4, 4, 2), density=0.4)
        out = snn.max_pool3d(x, kernel_size=2, stride=2)
        assert out.shape == [1, 2, 2, 2, 2]
        dense = x.to_dense().numpy()
        got = out.to_dense().numpy()
        # at active output sites: max over the 2x2x2 window's ACTIVE
        # inputs (empty sites don't contribute zeros)
        for (n, i, j, k) in np.argwhere(np.abs(got).sum(-1) > 0):
            win = dense[n, 2 * i:2 * i + 2, 2 * j:2 * j + 2,
                        2 * k:2 * k + 2].reshape(-1, 2)
            active_rows = win[np.abs(win).sum(-1) > 0]
            np.testing.assert_allclose(got[n, i, j, k],
                                       active_rows.max(0), rtol=1e-5)


class TestSparseActivationsNorm:
    def test_activations(self):
        rng = np.random.RandomState(4)
        x = _rand_coo(rng, (1, 3, 3, 3, 4), density=0.3)
        vals = x.values().numpy()
        np.testing.assert_allclose(
            snn.ReLU()(x).values().numpy(), np.maximum(vals, 0))
        np.testing.assert_allclose(
            snn.ReLU6()(x).values().numpy(),
            np.clip(vals * 1.0, 0, 6), rtol=1e-6)
        np.testing.assert_allclose(
            snn.LeakyReLU(0.1)(x).values().numpy(),
            np.where(vals >= 0, vals, 0.1 * vals), rtol=1e-6)

    def test_csr_softmax(self):
        crows = np.array([0, 2, 3])
        cols = np.array([0, 2, 1])
        vals = np.array([1.0, 2.0, 5.0], np.float32)
        csr = sparse.sparse_csr_tensor(crows, cols, vals, [2, 3])
        out = snn.Softmax()(csr)
        v = out.values().numpy()
        e = np.exp([1.0, 2.0])
        np.testing.assert_allclose(v[:2], e / e.sum(), rtol=1e-5)
        np.testing.assert_allclose(v[2], 1.0, rtol=1e-6)

    def test_batchnorm(self):
        rng = np.random.RandomState(5)
        paddle.seed(0)
        x = _rand_coo(rng, (2, 3, 3, 3, 4), density=0.5)
        bn = snn.BatchNorm(4)
        out = bn(x)
        v = out.values().numpy()
        assert v.shape == x.values().numpy().shape
        np.testing.assert_allclose(v.mean(0), 0.0, atol=1e-4)
        np.testing.assert_allclose(v.std(0), 1.0, atol=1e-2)


class TestSparseAttention:
    def test_matches_dense_masked(self):
        rng = np.random.RandomState(6)
        b, h, s, d = 1, 2, 4, 8
        q = rng.randn(b, h, s, d).astype(np.float32)
        k = rng.randn(b, h, s, d).astype(np.float32)
        v = rng.randn(b, h, s, d).astype(np.float32)
        # causal pattern as batched CSR [b*h, s, s]
        crows, cols = [], []
        for _ in range(b * h):
            cr = [0]
            for r in range(s):
                cols.extend(range(r + 1))
                cr.append(cr[-1] + r + 1)
            crows.extend(cr)
        nnz = sum(r + 1 for r in range(s)) * b * h
        mask = sparse.sparse_csr_tensor(
            np.array(crows), np.array(cols),
            np.ones(nnz, np.float32), [b * h, s, s])
        out = snn.attention(paddle.to_tensor(q), paddle.to_tensor(k),
                            paddle.to_tensor(v), mask).numpy()
        # dense causal reference
        logits = np.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(d)
        causal = np.tril(np.ones((s, s), bool))
        logits = np.where(causal, logits, -1e30)
        w = np.exp(logits - logits.max(-1, keepdims=True))
        w /= w.sum(-1, keepdims=True)
        ref = np.einsum("bhst,bhtd->bhsd", w, v)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
