"""Speculative decoding (ISSUE 12): greedy-token parity for every
drafter, fused-verify numerics, rollback accounting, scheduler
composition (chunked prefill / preemption / deadlines / faults), the
trace-pinned amortization bound, and TP mp2.

The load-bearing invariant: ACCEPTANCE NEVER CHANGES OUTPUT. The
verify pass computes the target's own greedy picks at every window
position and accepts a draft token only when it equals them — so the
emitted stream is byte-identical to non-speculative greedy decode for
ANY drafter, including adversarially wrong ones.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.incubate.nn.fused_transformer import (
    FusedMultiTransformer, PagedKV, rope_table)
from paddle_tpu.inference import (ContinuousBatchingEngine,
                                  DraftModelDrafter, FusedCausalLM,
                                  ScheduledDrafter)
from paddle_tpu.inference.kv_cache import BlockKVCacheManager
from paddle_tpu.profiler import stats


def _model(seed=7):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=128)


def _draft_model(seed=99):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=64, embed_dim=16, num_heads=2,
                         dim_feedforward=32, num_layers=1,
                         max_position=128)


def _prompts(n=3, seed=3):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 64, (L,)) for L in (3, 6, 9)[:n]]


def _run(speculative=None, prompts=None, n_new=8, seed=7, eos=None,
         **kw):
    """Engine run -> per-submission generated streams (id-ordered)."""
    prompts = _prompts() if prompts is None else prompts
    eng = ContinuousBatchingEngine(
        _model(seed), max_batch=4, page_size=4, max_length=64,
        decode_chunk=2, speculative=speculative, **kw)
    rids = [eng.submit(p, max_new_tokens=n_new, eos_token_id=eos)
            for p in prompts]
    eng.run()
    by = {r.id: list(r.generated) for r in eng.finished}
    return [by[r] for r in rids]


# =====================================================================
# the verify program's numerics: chunked verify == sequential decode
# =====================================================================

class TestVerifyProgramNumerics:
    def test_chunk_verify_matches_sequential_decode(self):
        """The verify pass scores a window with prefill_chunk_raw; the
        non-speculative engine scores it token-by-token with
        decode_raw. Over a RANDOM cache state and a random window the
        two paths must agree on every hidden state — the numeric
        foundation under every parity test below (discriminating even
        where tiny random models emit convergent streams)."""
        paddle.seed(13)
        st = FusedMultiTransformer(32, 4, 64, 2, max_position=64)
        cos, sin = rope_table(64, st.head_dim)
        w = st._stack()
        b, L, win_len, ps, pp = 2, 6, 4, 4, 8
        mgr = BlockKVCacheManager(st.num_layers, st.num_kv_heads,
                                  st.head_dim, ps, num_pages=32,
                                  reserve_scratch=True)
        for i in range(b):
            mgr.allocate(i, L + win_len)
        tables = mgr.block_tables(range(b), pp)
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(b, L, 32).astype(np.float32))
        _h, cache = st.prefill_raw(w, x, mgr.fresh_cache(), tables,
                                   cos, sin)
        win = jnp.asarray(rng.randn(b, win_len, 32).astype(np.float32))

        h_chunk, _c = st.prefill_chunk_raw(
            w, win, cache, tables, jnp.full((b,), L, jnp.int32),
            jnp.full((b,), win_len, jnp.int32), cos, sin)

        ck, cv = cache.k, cache.v
        seq = []
        for j in range(win_len):
            hj, c2 = st.decode_raw(
                w, win[:, j], PagedKV(ck, cv), tables,
                jnp.full((b,), L + j, jnp.int32), cos, sin)
            ck, cv = c2.k, c2.v
            seq.append(np.asarray(hj))
        np.testing.assert_allclose(
            np.asarray(h_chunk), np.stack(seq, axis=1),
            atol=2e-4, rtol=2e-4)


# =====================================================================
# greedy-token parity: every drafter, forced schedules
# =====================================================================

class TestGreedyParity:
    def test_self_draft_heads_parity(self):
        assert _run() == _run("self", spec_k=3)

    def test_draft_model_parity(self):
        assert _run() == _run(DraftModelDrafter(_draft_model()),
                              spec_k=3)

    def test_draft_model_instance_shorthand(self):
        # a bare FusedCausalLM wraps into a DraftModelDrafter
        assert _run() == _run(_draft_model(), spec_k=2)

    @pytest.mark.parametrize("k", [1, 3, 5])
    def test_forced_full_accept_schedule(self, k):
        """Oracle drafts (the true greedy stream) — every draft
        accepts, and the output still matches exactly."""
        base = _run()
        prompts = _prompts()
        exp = {np.asarray(p, np.int32).tobytes(): g
               for p, g in zip(prompts, base)}
        stats.reset()
        got = _run(ScheduledDrafter(
            lambda r: exp[np.asarray(r.prompt).tobytes()]),
            prompts=prompts, spec_k=k)
        assert got == base
        drafted = stats.counter("serving.spec_drafted_tokens").value
        accepted = stats.counter("serving.spec_accepted_tokens").value
        assert drafted > 0
        # full-accept schedule: only window clamping (request tails)
        # may reject
        assert accepted >= drafted - len(prompts) * k

    def test_forced_full_reject_schedule(self):
        """Adversarial drafts (true next token + 1, guaranteed wrong)
        — every round rejects everything and emits only the bonus
        token, degenerating to per-token decode THROUGH THE VERIFY
        PATH; output still byte-identical."""
        base = _run()
        prompts = _prompts()
        wrong = {np.asarray(p, np.int32).tobytes(): [(t + 1) % 64 for t in g]
                 for p, g in zip(prompts, base)}
        stats.reset()
        got = _run(ScheduledDrafter(
            lambda r: wrong[np.asarray(r.prompt).tobytes()]),
            prompts=prompts, spec_k=3)
        assert got == base
        assert stats.counter("serving.spec_accepted_tokens").value == 0
        assert stats.counter("serving.spec_rejected_tokens").value > 0

    def test_eos_mid_window_parity(self):
        base = _run(n_new=8)
        eos = base[0][0]  # a token the stream actually emits
        assert _run(n_new=8, eos=eos) == \
            _run("self", n_new=8, eos=eos, spec_k=3)

    def test_single_token_requests(self):
        # max_new_tokens=1 finishes at admission; spec must not break
        assert _run(n_new=1) == _run("self", n_new=1, spec_k=3)


# =====================================================================
# rollback + telemetry accounting
# =====================================================================

class TestAccountingAndRollback:
    def test_counters_and_accept_len_histogram(self):
        stats.reset()
        _run("self", prompts=_prompts(1), spec_k=3, n_new=8)
        rounds = stats.counter("serving.spec_rounds").value
        drafted = stats.counter("serving.spec_drafted_tokens").value
        accepted = stats.counter("serving.spec_accepted_tokens").value
        rejected = stats.counter("serving.spec_rejected_tokens").value
        assert rounds > 0 and drafted == rounds * 3
        assert accepted + rejected == drafted
        h = stats.histogram("serve.accept_len")
        assert h.count == rounds  # one observation per slot per round
        assert stats.gauge("spec.k").value == 3

    def test_no_page_leak_and_exact_pool_drain(self):
        """Every speculative run must drain back to the exact starting
        free-pool count — grows for rejected windows are handed back
        by BlockKVCacheManager.truncate."""
        eng = ContinuousBatchingEngine(
            _model(), max_batch=2, page_size=4, max_length=64,
            speculative="self", spec_k=4)
        free0 = eng._mgr.free_pages
        for p in _prompts(2):
            eng.submit(p, max_new_tokens=10)
        eng.run()
        assert eng._mgr.free_pages == free0
        assert eng._mgr._refs == {}

    def test_amortization_bound_trace_pinned(self):
        """ONE streamed verify pass per accepted window: with oracle
        drafts (accept rate 1.0) the round count is exactly
        ceil((n_new - 1) / (k + 1)) — vs n_new - 1 streamed chunks for
        non-speculative decode at chunk 1 — and never exceeds the
        non-speculative streamed-call count / mean(accept_len)."""
        n_new, k = 16, 3
        prompts = _prompts(1)
        base = _run(prompts=prompts, n_new=n_new)
        exp = {np.asarray(p, np.int32).tobytes(): g
               for p, g in zip(prompts, base)}
        stats.reset()
        got = _run(ScheduledDrafter(
            lambda r: exp[np.asarray(r.prompt).tobytes()]),
            prompts=prompts, spec_k=k, n_new=n_new)
        assert got == base
        rounds = stats.counter("serving.spec_rounds").value
        drafted = stats.counter("serving.spec_drafted_tokens").value
        accepted = stats.counter("serving.spec_accepted_tokens").value
        assert rounds == -(-(n_new - 1) // (k + 1))  # ceil: 4, not 15
        mean_accept = accepted / rounds
        assert mean_accept > 0
        assert rounds <= (n_new - 1) / mean_accept

    def test_bad_spec_k_raises(self):
        with pytest.raises(ValueError, match="spec_k"):
            ContinuousBatchingEngine(_model(), max_batch=2,
                                     page_size=4, max_length=64,
                                     speculative="self", spec_k=0)

    def test_draft_flag_without_model_raises(self):
        with pytest.raises(ValueError, match="draft model"):
            ContinuousBatchingEngine(_model(), max_batch=2,
                                     page_size=4, max_length=64,
                                     speculative="draft")


# =====================================================================
# serving-scheduler composition
# =====================================================================

def _serve(speculative=None, prompts=None, n_new=8, seed=7,
           max_batch=4, **kw):
    from paddle_tpu.serving import ServingEngine, SLOConfig

    prompts = _prompts() if prompts is None else prompts
    eng = ServingEngine(
        _model(seed), max_batch=max_batch, page_size=4, max_length=64,
        decode_chunk=2, slo=SLOConfig(prefill_chunk=4),
        speculative=speculative, spec_k=3, **kw)
    rids = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
    eng.run()
    by = {r.id: r for r in eng.finished}
    return eng, [by[r] for r in rids]


class TestServingComposition:
    def test_serving_engine_spec_parity_with_chunked_prefill(self):
        _e1, base = _serve()
        _e2, spec = _serve("self")
        assert [r.generated for r in base] == \
            [r.generated for r in spec]
        assert all(r.state == "ok" for r in spec)

    def test_spec_verify_journal_events_and_chrome_span(self):
        from paddle_tpu.serving.journal import (LIFECYCLE_EVENTS,
                                                chrome_trace)

        assert "spec_verify" in LIFECYCLE_EVENTS
        eng, done = _serve("self", prompts=_prompts(1))
        evs = eng.journal.events(done[0].id)
        sv = [e for e in evs if e["ev"] == "spec_verify"]
        assert sv and all("k" in e and "accepted" in e for e in sv)
        trace = chrome_trace(eng.journal.events())
        spans = [t for t in trace["traceEvents"]
                 if t.get("name") == "spec_verify"]
        assert spans and all(t["ph"] == "X" for t in spans)

    def test_preemption_resume_redrafts_parity(self):
        """Preempt a speculating slot by recompute mid-stream: the
        request re-admits, its drafter state resets ('resume
        re-drafts'), and the user-visible stream continues exactly —
        token parity with the untouched run."""
        _e0, base = _serve("self", prompts=_prompts(2), n_new=10)
        from paddle_tpu.serving import ServingEngine, SLOConfig

        eng = ServingEngine(
            _model(), max_batch=2, page_size=4, max_length=64,
            decode_chunk=2, slo=SLOConfig(prefill_chunk=4),
            speculative="self", spec_k=3)
        rids = [eng.submit(p, max_new_tokens=10)
                for p in _prompts(2)]
        # run until both are decoding with a few tokens out, then
        # preempt slot 0 (vLLM-style recompute), then drain
        for _ in range(30):
            eng.step()
            if all(r is not None for r in eng._slots) and \
                    len(eng._slots[0].generated) >= 3:
                break
        assert eng._slots[0] is not None
        eng._preempt_slot(0)
        eng.run()
        by = {r.id: r for r in eng.finished}
        assert [by[r].generated for r in rids] == \
            [r.generated for r in base]
        assert stats is not None

    def test_mid_verify_fault_retries_cleanly(self):
        """An injected decode.step raise lands INSIDE a speculative
        round; the crash-isolated retry re-runs the round (drafter
        propose is idempotent) and the stream stays byte-identical."""
        from paddle_tpu.serving import FaultInjector

        _e0, base = _serve("self", prompts=_prompts(2))
        inj = FaultInjector(seed=0).add("decode.step", kind="raise",
                                        at=2)
        _e1, got = _serve("self", prompts=_prompts(2), faults=inj)
        assert [r.generated for r in got] == \
            [r.generated for r in base]
        assert stats.counter("serving.step_retries").value > 0

    def test_deadline_expiry_mid_speculation(self):
        """A deadline landing while a request speculates aborts only
        that request (pages freed, drafter slot reset); the survivor's
        stream keeps parity. Accepted tokens count as watchdog/SLO
        progress via len(req.generated)."""
        from paddle_tpu.serving import (ManualClock, ServingEngine,
                                        SLOConfig, use_clock)

        _e0, base = _serve("self", prompts=_prompts(2), n_new=10)
        with use_clock(ManualClock()) as clk:
            eng = ServingEngine(
                _model(), max_batch=2, page_size=4, max_length=64,
                decode_chunk=2, slo=SLOConfig(prefill_chunk=4),
                speculative="self", spec_k=3)
            free0 = eng._mgr.free_pages
            r_ok = eng.submit(_prompts(2)[0], max_new_tokens=10)
            r_dead = eng.submit(_prompts(2)[1], max_new_tokens=10,
                                deadline_ms=50.0)
            for _ in range(6):
                eng.step()
            clk.advance(1.0)
            eng.run()
            by = {r.id: r for r in eng.finished}
            assert by[r_dead].state == "deadline_exceeded"
            assert by[r_ok].state == "ok"
            assert by[r_ok].generated == base[0].generated
            # exact-pool drain once the prefix cache's legitimate
            # references (full prompt pages) are dropped
            if eng.prefix_cache is not None:
                eng.prefix_cache.clear()
            assert eng._mgr.free_pages == free0

    def test_serve_top_accept_rate_row(self):
        from tools import serve_top

        eng, _done = _serve("self", prompts=_prompts(1))
        s = serve_top.summarize(eng.journal.events())
        assert s["spec_rounds"] > 0
        assert s["spec_accept_rate"] is not None
        assert "accept_rate" in serve_top.render(s)


# =====================================================================
# tensor parallelism: verify shard_mapped, draft weights replicated
# =====================================================================

class TestSpeculativeTP:
    def test_mp2_spec_parity(self, virtual_devices):
        """mp2 speculative serving must emit the mp1 non-speculative
        engine's exact tokens — the verify pass runs shard_mapped like
        prefill_chunk_raw while the self-draft heads stay replicated."""
        _e0, base = _serve(None, prompts=_prompts(2))
        _e1, spec = _serve("self", prompts=_prompts(2), mp_degree=2)
        assert [r.generated for r in spec] == \
            [r.generated for r in base]
        assert _e1._gen._tp is not None and _e1._gen._tp.mp == 2

    def test_mp2_draft_model_parity(self, virtual_devices):
        """Draft-model speculation under TP: draft weights replicated
        (plain jit), target verify sharded."""
        _e0, base = _serve(None, prompts=_prompts(2))
        _e1, spec = _serve(DraftModelDrafter(_draft_model()),
                           prompts=_prompts(2), mp_degree=2)
        assert [r.generated for r in spec] == \
            [r.generated for r in base]

    def test_verify_rung_carries_mp_suffix(self, virtual_devices):
        eng = ContinuousBatchingEngine(
            _model(), max_batch=2, page_size=4, max_length=64,
            speculative="self", spec_k=3, mp_degree=2)
        assert eng._spec._rung() == "serve.verify[k=3,mp=2]"


# =====================================================================
# program-site registration (tpu_lint preflight coverage)
# =====================================================================

class TestVerifyProgramSite:
    def test_serve_verify_site_traces(self):
        from paddle_tpu.analysis.program_sites import (PROGRAM_SITES,
                                                       trace_program)

        site = {s.name: s for s in PROGRAM_SITES}["serve.verify"]
        traced = trace_program(site)
        assert traced.donated_invars  # the pool operands may die
        assert site.compute_dtype == "bfloat16"
