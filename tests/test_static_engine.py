"""static.Program/Executor + auto-parallel Engine tests.

Mirrors the reference's static-graph and engine tests (reference:
test/legacy_test executor tests; test/auto_parallel engine API tests).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static


class TestStaticProgram:
    def test_program_records_and_runs(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 3], "float32")
            y = paddle.matmul(x, paddle.to_tensor(
                np.ones((3, 2), np.float32)))
            z = y + 1.0
        assert prog.num_ops >= 2
        exe = static.Executor()
        xv = np.arange(12).reshape(4, 3).astype(np.float32)
        (out,) = exe.run(prog, feed={"x": xv}, fetch_list=[z])
        np.testing.assert_allclose(out, xv @ np.ones((3, 2)) + 1.0)

    def test_layers_under_program_guard(self):
        paddle.seed(0)
        net = nn.Linear(5, 2)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [3, 5], "float32")
            out = net(x)
        exe = static.Executor()
        xv = np.random.RandomState(0).randn(3, 5).astype(np.float32)
        (got,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
        want = net(paddle.to_tensor(xv)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_captured_params_are_live(self):
        """Parameters are captured by reference: mutating them between
        runs changes the program's result (reference scope semantics)."""
        net = nn.Linear(2, 2, bias_attr=False)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [1, 2], "float32")
            out = net(x)
        exe = static.Executor()
        xv = np.ones((1, 2), np.float32)
        (a,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
        net.weight.set_value(paddle.to_tensor(
            np.zeros((2, 2), np.float32)))
        (b,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
        np.testing.assert_allclose(b, np.zeros((1, 2)))
        assert not np.allclose(a, b)

    def test_fetch_by_name_and_bad_feed(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2], "float32")
            y = x * 2.0
        exe = static.Executor()
        with pytest.raises(KeyError):
            exe.run(prog, feed={"bogus": np.ones(2, np.float32)},
                    fetch_list=[y])

    def test_data_outside_guard_raises(self):
        with pytest.raises(RuntimeError):
            static.data("oops", [2], "float32")

    def test_dynamic_batch_export(self, tmp_path):
        """A None batch dim survives export: the saved artifact accepts
        any batch size (reference save_inference_model dynamic batch)."""
        paddle.seed(2)
        net = nn.Linear(4, 2)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            out = net(x)
        path = str(tmp_path / "dyn")
        static.save_inference_model(path, [x], [out])
        layer, _, _ = static.load_inference_model(path)
        for bs in (1, 5, 9):
            xv = np.random.RandomState(bs).randn(bs, 4).astype(np.float32)
            got = layer(paddle.to_tensor(xv)).numpy()
            want = net(paddle.to_tensor(xv)).numpy()
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_default_main_program(self):
        before = static.default_main_program()
        prog = static.Program()
        with static.program_guard(prog):
            assert static.default_main_program() is prog
        assert static.default_main_program() is before

    def test_save_load_inference_model(self, tmp_path):
        paddle.seed(1)
        net = nn.Linear(4, 3)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            out = net(x)
        path = str(tmp_path / "static_model")
        static.save_inference_model(path, [x], [out])

        # loadable both via static.load_inference_model and the Predictor
        layer, feeds, fetches = static.load_inference_model(path)
        xv = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        got = layer(paddle.to_tensor(xv))
        want = net(paddle.to_tensor(xv)).numpy()
        np.testing.assert_allclose(got.numpy(), want, rtol=1e-5, atol=1e-6)

        from paddle_tpu.inference import Config, create_predictor

        pred = create_predictor(Config(path))
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.copy_from_cpu(xv)
        pred.run()
        got2 = pred.get_output_handle(
            pred.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(got2, want, rtol=1e-5, atol=1e-6)


class TestAutoParallelEngine:
    def _data(self, n=64):
        from paddle_tpu.io import Dataset

        rng = np.random.RandomState(0)
        xs = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8, 1).astype(np.float32)
        ys = (xs @ w).astype(np.float32)

        class DS(Dataset):
            def __len__(self):
                return n

            def __getitem__(self, i):
                return xs[i], ys[i]

        return DS()

    def test_engine_fit_converges(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.distributed.auto_parallel import Engine

        paddle.seed(0)
        model = nn.Linear(8, 1)
        opt = paddle.optimizer.Adam(0.05, parameters=model.parameters())
        engine = Engine(model=model, loss=F.mse_loss, optimizer=opt)
        hist = engine.fit(self._data(), epochs=20, batch_size=16)
        assert hist["loss"][-1] < hist["loss"][0] * 0.01

    def test_engine_evaluate_predict(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.distributed.auto_parallel import Engine
        from paddle_tpu.metric import Accuracy

        paddle.seed(0)
        model = nn.Linear(8, 1)
        opt = paddle.optimizer.Adam(0.01, parameters=model.parameters())
        engine = Engine(model=model, loss=F.mse_loss, optimizer=opt)
        engine.fit(self._data(), epochs=1, batch_size=16)
        res = engine.evaluate(self._data(), batch_size=16)
        assert res["loss"] is not None and np.isfinite(res["loss"])
        preds = engine.predict(self._data(), batch_size=16)
        assert len(preds) == 4 and preds[0].shape == (16, 1)

    def test_engine_save_load(self, tmp_path):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.distributed.auto_parallel import Engine

        paddle.seed(0)
        model = nn.Linear(8, 1)
        opt = paddle.optimizer.Adam(0.01, parameters=model.parameters())
        engine = Engine(model=model, loss=F.mse_loss, optimizer=opt)
        engine.fit(self._data(), epochs=1, batch_size=16)
        w_before = model.weight.numpy().copy()
        engine.save(str(tmp_path / "ckpt"))
        model.weight.set_value(paddle.to_tensor(
            np.zeros_like(w_before)))
        engine.load(str(tmp_path / "ckpt"))
        np.testing.assert_allclose(model.weight.numpy(), w_before)
