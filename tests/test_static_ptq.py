"""Static-artifact PTQ: jit.save artifact -> int8 artifact -> Predictor.

Reference workflow:
python/paddle/static/quantization/post_training_quantization.py (load a
saved inference program, calibrate, emit a quantized program). Here the
emitted artifact is weight-only int8 (TPU serving is HBM-bound — see
static/quantization.py docstring) and must round-trip through jit.load
AND inference.Predictor.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static.input_spec import InputSpec


def _make_artifact(tmp_path, d=64):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(d, 2 * d), nn.GELU(),
                        nn.Linear(2 * d, d), nn.LayerNorm(d),
                        nn.Linear(d, 32))
    net.eval()
    prefix = str(tmp_path / "model")
    paddle.jit.save(net, prefix,
                    input_spec=[InputSpec([4, d], "float32")])
    return net, prefix


class TestStaticPTQ:
    def test_roundtrip_from_saved_artifact(self, tmp_path):
        net, prefix = _make_artifact(tmp_path)
        rng = np.random.RandomState(0)
        calib = [paddle.to_tensor(rng.randn(4, 64).astype(np.float32))
                 for _ in range(3)]

        from paddle_tpu.quantization import post_training_quantize

        res = post_training_quantize(prefix, calib_reader=calib)
        # the three Linear weights quantize; LN/bias params skip
        assert len(res.quantized) == 3, res
        assert res.calib_stats["batches"] == 3
        # weight-only int8 of a well-scaled model stays close
        assert res.calib_stats["max_abs_err"] < \
            0.05 * max(res.calib_stats["out_scale"], 1.0), res.calib_stats

        loaded = paddle.jit.load(res.output_prefix)
        x = calib[0]
        ref = net(x).numpy()
        out = loaded(x).numpy()
        np.testing.assert_allclose(out, ref, atol=0.05 * np.abs(ref).max())
        # int8 weights really are int8 in the artifact
        sd = loaded.state_dict()
        w_names = [n for n in sd if n in res.quantized]
        assert w_names and all(
            str(sd[n]._data.dtype) == "int8" for n in w_names)
        assert any(n.endswith("@scale") for n in sd)

    def test_predictor_loads_int8_artifact(self, tmp_path):
        net, prefix = _make_artifact(tmp_path)
        from paddle_tpu.quantization import post_training_quantize

        res = post_training_quantize(prefix)
        from paddle_tpu.inference import Config, create_predictor

        pred = create_predictor(Config(res.output_prefix))
        x = np.random.RandomState(1).randn(4, 64).astype(np.float32)
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.copy_from_cpu(x)
        assert pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]) \
            .copy_to_cpu()
        ref = net(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, ref,
                                   atol=0.05 * np.abs(ref).max())

    def test_accepts_config_and_skip_params(self, tmp_path):
        net, prefix = _make_artifact(tmp_path)
        from paddle_tpu.inference import Config
        from paddle_tpu.static.quantization import post_training_quantize

        first_w = [n for n, p in net.named_parameters()
                   if p._data.ndim == 2][0]
        res = post_training_quantize(Config(prefix),
                                     skip_params=(first_w,),
                                     output_prefix=str(tmp_path / "q2"))
        assert first_w in res.skipped
        assert len(res.quantized) == 2

    def test_artifact_without_program_raises(self, tmp_path):
        net = nn.Linear(4, 4)
        prefix = str(tmp_path / "noprog")
        paddle.jit.save(net, prefix)  # no input_spec -> state only
        from paddle_tpu.static.quantization import post_training_quantize

        with pytest.raises(ValueError, match="input_spec"):
            post_training_quantize(prefix)
