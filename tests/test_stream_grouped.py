"""Grouped bf16 weight-stream decode: interpret-mode parity + call
structure (the r6 tentpole, nn/functional/stream_linear.py).

Three contracts pinned on CPU:

1. KERNEL PARITY — ``stream_layer_tail``'s fused Pallas kernel
   (interpret mode) reproduces an independent per-projection numpy
   reference within fp tolerance, for stacked and unstacked weights,
   f32/bf16/int8(weight-only == the a8w8 stack's grouped math), ragged
   N (the XLA fallback), and a TRACED layer index under jit.
2. CALL STRUCTURE — one decode step issues at most TWO streamed weight
   matmul calls per transformer layer (ONE in steady state with
   cross-layer prefetch): counted at trace level, since the fori_loop
   body traces once.
3. ENGINE PARITY — GenerationEngine greedy tokens with
   ``FLAGS_decode_grouped`` on vs off are identical for the fp32
   stack, and decode_raw hidden states agree within quant tolerance
   for int8 stacks.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn.functional.stream_linear import (stream_layer_tail,
                                                    stream_linear)

EPS = 1e-5


def _flags(**kw):
    paddle.set_flags(kw)


@pytest.fixture(autouse=True)
def _restore_flags():
    yield
    paddle.set_flags({"decode_grouped": "auto",
                      "decode_prefetch": True,
                      "decode_linear": "auto"})


def _mk(rng, L, Ka, d, dff, Nq, dtype=np.float32, int8=False):
    """Random stacked tail weights (+ optional int8 quantization)."""
    def w(*s):
        return (rng.randn(*s) * 0.05).astype(np.float32)

    p = dict(wo=w(L, Ka, d), w1=w(L, d, dff), w2=w(L, dff, d),
             wq=w(L, d, Nq), bo=w(L, d), b1=w(L, dff), b2=w(L, d),
             bq=w(L, Nq),
             l2s=(1 + 0.1 * rng.randn(L, d)).astype(np.float32),
             l2b=(0.1 * rng.randn(L, d)).astype(np.float32),
             l1s=(1 + 0.1 * rng.randn(L, d)).astype(np.float32),
             l1b=(0.1 * rng.randn(L, d)).astype(np.float32))
    scales = {}
    if int8:
        for n in ("wo", "w1", "w2", "wq"):
            full = p[n]
            s = np.maximum(np.abs(full).max(axis=-2) / 127.0, 1e-8)
            p[n] = np.clip(np.round(full / s[:, None, :]), -127,
                           127).astype(np.int8)
            scales["s" + n[1:]] = s.astype(np.float32)
    return p, scales


def _ref_tail(att, h, p, scales, layer, activation="gelu",
              with_q=True, lq=None):
    """Independent numpy reference of the grouped tail's math: the
    ungrouped per-projection decode path (fp32)."""
    def deq(n):
        w = p[n].astype(np.float32)
        s = scales.get("s" + n[1:])
        return w * s[:, None, :] if s is not None else w

    def ln(x, s, b):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + EPS) * s + b

    def act(x):
        if activation == "gelu":
            return np.asarray(jax.nn.gelu(jnp.asarray(x)))
        return np.maximum(x, 0)

    att = np.asarray(att, np.float32)
    h = np.asarray(h, np.float32)
    h2 = h + att @ deq("wo")[layer] + p["bo"][layer]
    hn = ln(h2, p["l2s"][layer], p["l2b"][layer])
    ff = act(hn @ deq("w1")[layer] + p["b1"][layer])
    h_out = h2 + ff @ deq("w2")[layer] + p["b2"][layer]
    if not with_q:
        return h_out
    lq = layer + 1 if lq is None else lq
    hn1 = ln(h_out, p["l1s"][lq], p["l1b"][lq])
    return h_out, hn1 @ deq("wq")[lq] + p["bq"][lq]


def _call_tail(att, h, p, scales, layer, *, stacked=True, with_q=True,
               lq=None, interpret=True, out_dtype=jnp.float32,
               activation="gelu"):
    j = jnp.asarray

    def pick(a, l):
        return j(a) if stacked else j(a[l])

    L = p["wo"].shape[0]
    lq = (layer + 1 if lq is None else lq)
    lq = min(lq, L - 1)
    nq = None
    if with_q:
        nq = dict(w=pick(p["wq"], lq), b=pick(p["bq"], lq),
                  ln_s=pick(p["l1s"], lq), ln_b=pick(p["l1b"], lq))
        if scales:
            nq["s"] = pick(scales["sq"], lq)
        if stacked:
            nq["layer"] = lq
    return stream_layer_tail(
        j(att), j(h), pick(p["wo"], layer), pick(p["w1"], layer),
        pick(p["w2"], layer), layer=layer if stacked else None,
        bo=pick(p["bo"], layer), b1=pick(p["b1"], layer),
        b2=pick(p["b2"], layer), ln2_scale=pick(p["l2s"], layer),
        ln2_bias=pick(p["l2b"], layer), epsilon=EPS,
        activation=activation,
        so=pick(scales["so"], layer) if scales else None,
        s1=pick(scales["s1"], layer) if scales else None,
        s2=pick(scales["s2"], layer) if scales else None,
        next_qkv=nq, out_dtype=out_dtype, interpret=interpret)


class TestGroupedKernelParity:
    """Interpret-mode fused-tail kernel vs the per-projection numpy
    reference (contract 1)."""

    @pytest.mark.parametrize("stacked", [True, False])
    def test_f32_matches_reference_every_layer(self, stacked):
        rng = np.random.RandomState(0)
        L, Ka, d, dff, Nq = 3, 128, 256, 512, 384
        p, _ = _mk(rng, L, Ka, d, dff, Nq)
        att = rng.randn(8, Ka).astype(np.float32)
        h = rng.randn(8, d).astype(np.float32)
        for l in range(L - 1):
            hk, qk = _call_tail(att, h, p, {}, l, stacked=stacked)
            hr, qr = _ref_tail(att, h, p, {}, l)
            np.testing.assert_allclose(np.asarray(hk), hr, rtol=2e-5,
                                       atol=2e-5)
            np.testing.assert_allclose(np.asarray(qk), qr, rtol=2e-5,
                                       atol=2e-5)

    def test_bf16_within_bf16_tolerance(self):
        rng = np.random.RandomState(1)
        L, Ka, d, dff, Nq = 2, 128, 256, 512, 384
        p, _ = _mk(rng, L, Ka, d, dff, Nq)
        pb = {n: (jnp.asarray(a).astype(jnp.bfloat16)
                  if a.ndim == 3 else a) for n, a in p.items()}
        att = jnp.asarray(rng.randn(16, Ka).astype(np.float32)) \
            .astype(jnp.bfloat16)
        h = jnp.asarray(rng.randn(16, d).astype(np.float32)) \
            .astype(jnp.bfloat16)
        hk, qk = _call_tail(np.asarray(att, np.float32),
                            np.asarray(h, np.float32),
                            {n: np.asarray(a, np.float32)
                             for n, a in pb.items()}, {}, 0)
        # run the real bf16 operands through the kernel too
        hkb, qkb = stream_layer_tail(
            att, h, pb["wo"], pb["w1"], pb["w2"], layer=0,
            bo=jnp.asarray(p["bo"]), b1=jnp.asarray(p["b1"]),
            b2=jnp.asarray(p["b2"]), ln2_scale=jnp.asarray(p["l2s"]),
            ln2_bias=jnp.asarray(p["l2b"]), epsilon=EPS,
            activation="gelu",
            next_qkv=dict(w=pb["wq"], b=jnp.asarray(p["bq"]),
                          ln_s=jnp.asarray(p["l1s"]),
                          ln_b=jnp.asarray(p["l1b"]), layer=1),
            out_dtype=jnp.float32, interpret=True)
        # bf16 weights: parity vs the f32 run within bf16 resolution
        np.testing.assert_allclose(np.asarray(hkb), np.asarray(hk),
                                   rtol=0.1, atol=0.2)
        np.testing.assert_allclose(np.asarray(qkb), np.asarray(qk),
                                   rtol=0.1, atol=0.2)

    @pytest.mark.parametrize("stacked", [True, False])
    def test_int8_weight_only_matches_dequant_reference(self, stacked):
        """int8 (and thus the a8w8 stack's grouped form — same
        weights+scales; grouped runs weight-only math by design)."""
        rng = np.random.RandomState(2)
        L, Ka, d, dff, Nq = 2, 128, 256, 256, 384
        p, scales = _mk(rng, L, Ka, d, dff, Nq, int8=True)
        att = rng.randn(8, Ka).astype(np.float32)
        h = rng.randn(8, d).astype(np.float32)
        hk, qk = _call_tail(att, h, p, scales, 0, stacked=stacked)
        hr, qr = _ref_tail(att, h, p, scales, 0)
        np.testing.assert_allclose(np.asarray(hk), hr, rtol=2e-4,
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(qk), qr, rtol=2e-4,
                                   atol=2e-4)

    def test_ragged_n_takes_fallback_and_matches_reference(self):
        """dff/d not 128-multiples -> XLA fallback, same math."""
        rng = np.random.RandomState(3)
        L, Ka, d, dff, Nq = 2, 96, 80, 72, 48
        p, _ = _mk(rng, L, Ka, d, dff, Nq)
        att = rng.randn(5, Ka).astype(np.float32)
        h = rng.randn(5, d).astype(np.float32)
        hk, qk = _call_tail(att, h, p, {}, 0, interpret=None)
        hr, qr = _ref_tail(att, h, p, {}, 0)
        np.testing.assert_allclose(np.asarray(hk), hr, rtol=2e-5,
                                   atol=2e-5)
        np.testing.assert_allclose(np.asarray(qk), qr, rtol=2e-5,
                                   atol=2e-5)

    def test_traced_layer_index_under_jit(self):
        rng = np.random.RandomState(4)
        L, Ka, d, dff, Nq = 3, 128, 128, 256, 128
        p, _ = _mk(rng, L, Ka, d, dff, Nq)
        att = rng.randn(8, Ka).astype(np.float32)
        h = rng.randn(8, d).astype(np.float32)
        j = jnp.asarray

        @jax.jit
        def f(l):
            nq = dict(w=j(p["wq"]), b=j(p["bq"]), ln_s=j(p["l1s"]),
                      ln_b=j(p["l1b"]),
                      layer=jnp.minimum(l + 1, L - 1))
            return stream_layer_tail(
                j(att), j(h), j(p["wo"]), j(p["w1"]), j(p["w2"]),
                layer=l, bo=j(p["bo"]), b1=j(p["b1"]), b2=j(p["b2"]),
                ln2_scale=j(p["l2s"]), ln2_bias=j(p["l2b"]),
                epsilon=EPS, activation="gelu", next_qkv=nq,
                out_dtype=jnp.float32, interpret=True)

        for l in range(L - 1):
            hk, qk = f(jnp.asarray(l, jnp.int32))
            hr, qr = _ref_tail(att, h, p, {}, l)
            np.testing.assert_allclose(np.asarray(hk), hr, rtol=2e-5,
                                       atol=2e-5)
            np.testing.assert_allclose(np.asarray(qk), qr, rtol=2e-5,
                                       atol=2e-5)

    def test_odd_batch_pads_to_sublane(self):
        rng = np.random.RandomState(5)
        p, _ = _mk(rng, 1, 128, 128, 256, 128)
        att = rng.randn(3, 128).astype(np.float32)
        h = rng.randn(3, 128).astype(np.float32)
        hk = _call_tail(att, h, p, {}, 0, with_q=False)
        hr = _ref_tail(att, h, p, {}, 0, with_q=False)
        assert hk.shape == (3, 128)
        np.testing.assert_allclose(np.asarray(hk), hr, rtol=2e-5,
                                   atol=2e-5)

    def test_guards(self):
        rng = np.random.RandomState(6)
        p, scales = _mk(rng, 2, 128, 128, 256, 128, int8=True)
        att = jnp.ones((4, 128))
        h = jnp.ones((4, 128))
        with pytest.raises(ValueError, match="all of so/s1/s2"):
            stream_layer_tail(
                att, h, jnp.asarray(p["wo"]), jnp.asarray(p["w1"]),
                jnp.asarray(p["w2"]), layer=0,
                bo=jnp.asarray(p["bo"]), b1=jnp.asarray(p["b1"]),
                b2=jnp.asarray(p["b2"]),
                ln2_scale=jnp.asarray(p["l2s"]),
                ln2_bias=jnp.asarray(p["l2b"]), epsilon=EPS,
                so=jnp.asarray(scales["so"]))
        with pytest.raises(ValueError, match="stacked"):
            stream_layer_tail(
                att, h, jnp.asarray(p["wo"]), jnp.asarray(p["w1"][0]),
                jnp.asarray(p["w2"]), layer=0,
                bo=jnp.asarray(p["bo"]), b1=jnp.asarray(p["b1"]),
                b2=jnp.asarray(p["b2"]),
                ln2_scale=jnp.asarray(p["l2s"]),
                ln2_bias=jnp.asarray(p["l2b"]), epsilon=EPS)


def _tiny_stack(L=3, d=32, heads=4, dff=64):
    from paddle_tpu.incubate.nn.fused_transformer import (
        FusedMultiTransformer, PagedKV, rope_table)

    paddle.seed(11)
    st = FusedMultiTransformer(d, heads, dff, L, max_position=64)
    cos, sin = rope_table(64, st.head_dim)
    npages = 4
    cache = PagedKV(
        jnp.zeros((L * npages, heads, 4, st.head_dim)),
        jnp.zeros((L * npages, heads, 4, st.head_dim)))
    tables = jnp.asarray(
        np.arange(2 * 2, dtype=np.int32).reshape(2, 2))
    lens = jnp.asarray(np.array([3, 5], np.int32))
    return st, cache, tables, lens, cos, sin


class TestCallStructure:
    """Contract 2: the decode loop's TRACE issues <=2 streamed weight
    matmul calls per transformer layer (1 fused tail in steady state
    with prefetch; +1 per-layer QKV stream with prefetch off). The
    fori_loop body traces once, so python-level call counts ARE the
    per-layer counts (plus the one loop-prologue QKV call)."""

    def _count(self, prefetch, weights=None):
        import paddle_tpu.nn.functional.stream_linear as sl

        _flags(decode_grouped="on", decode_prefetch=prefetch)
        st, cache, tables, lens, cos, sin = _tiny_stack()
        calls = {"linear": 0, "tail": 0}
        orig_lin, orig_tail = sl.stream_linear, sl.stream_layer_tail

        def lin(*a, **k):
            calls["linear"] += 1
            return orig_lin(*a, **k)

        def tail(*a, **k):
            calls["tail"] += 1
            return orig_tail(*a, **k)

        sl.stream_linear, sl.stream_layer_tail = lin, tail
        try:
            w = weights(st) if weights else st._stack()
            h, _ = st.decode_raw(w, jnp.ones((2, 32)), cache, tables,
                                 lens, cos, sin)
        finally:
            sl.stream_linear, sl.stream_layer_tail = orig_lin, orig_tail
        assert np.isfinite(np.asarray(h)).all()
        return calls

    def test_prefetch_on_one_streamed_call_per_layer(self):
        calls = self._count(True)
        # fori_loop body: 1 fused tail, 0 standalone QKV (carried);
        # prologue: 1 QKV stream outside the loop
        assert calls["tail"] == 1
        assert calls["linear"] == 1

    def test_prefetch_off_two_streamed_calls_per_layer(self):
        calls = self._count(False)
        assert calls["tail"] == 1
        assert calls["linear"] == 2  # prologue + per-layer QKV

    def test_unstacked_prefetch_on(self):
        calls = self._count(
            True, weights=lambda st: st.unstack_weights())
        L = 3
        # python-unrolled: 1 tail per layer + 1 prologue QKV
        assert calls["tail"] == L
        assert calls["linear"] == 1


class TestDecodeParity:
    """Contract 3: grouped vs ungrouped decode agree."""

    def _decode(self, grouped, weights=None, prefetch=True):
        _flags(decode_grouped=grouped, decode_prefetch=prefetch)
        st, cache, tables, lens, cos, sin = _tiny_stack()
        w = weights(st) if weights else st._stack()
        h, cache2 = st.decode_raw(w, jnp.ones((2, 32)) * 0.1, cache,
                                  tables, lens, cos, sin)
        return np.asarray(h), np.asarray(cache2.k)

    @pytest.mark.parametrize("prefetch", [True, False])
    def test_stacked_grouped_matches_ungrouped_f32(self, prefetch):
        h0, k0 = self._decode("off")
        h1, k1 = self._decode("on", prefetch=prefetch)
        np.testing.assert_allclose(h1, h0, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(k1, k0, rtol=1e-5, atol=1e-6)

    def test_unstacked_grouped_matches_ungrouped(self):
        h0, _ = self._decode("off")
        h1, _ = self._decode("on",
                             weights=lambda st: st.unstack_weights())
        np.testing.assert_allclose(h1, h0, rtol=1e-5, atol=1e-6)

    def test_int8_grouped_matches_ungrouped_stream(self):
        def quant(st):
            st.quantize_weight_only_int8()
            return st._stack()

        h0, _ = self._decode("off", weights=quant)
        h1, _ = self._decode("on", weights=quant)
        np.testing.assert_allclose(h1, h0, rtol=2e-3, atol=2e-3)

    def test_a8w8_auto_stays_ungrouped_but_on_forces_grouped(self):
        import paddle_tpu.nn.functional.stream_linear as sl

        st, cache, tables, lens, cos, sin = _tiny_stack()
        st.quantize_weight_only_int8()
        w = st._stack()
        calls = {"tail": 0}
        orig = sl.stream_layer_tail

        def tail(*a, **k):
            calls["tail"] += 1
            return orig(*a, **k)

        sl.stream_layer_tail = tail
        try:
            _flags(decode_grouped="auto")
            st.decode_raw(w, jnp.ones((2, 32)), cache, tables, lens,
                          cos, sin, a8w8=True)
            assert calls["tail"] == 0  # auto: a8w8 keeps act-quant path
            _flags(decode_grouped="on")
            h, _ = st.decode_raw(w, jnp.ones((2, 32)), cache, tables,
                                 lens, cos, sin, a8w8=True)
            assert calls["tail"] == 1  # forced grouped accepts a8w8
            assert np.isfinite(np.asarray(h)).all()
        finally:
            sl.stream_layer_tail = orig


class TestEngineParity:
    """Engine-level greedy-token parity grouped vs ungrouped (fp32 on
    CPU — the grouped fallback mirrors the ungrouped math op-for-op,
    so the token sequences must be identical)."""

    def _gen(self):
        from paddle_tpu.inference import FusedCausalLM

        paddle.seed(7)
        return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                             dim_feedforward=64, num_layers=2,
                             max_position=128)

    def test_generate_tokens_identical(self):
        from paddle_tpu.inference import GenerationEngine

        rng = np.random.RandomState(3)
        ids = rng.randint(0, 64, (2, 6))
        outs = {}
        for mode in ("off", "on"):
            _flags(decode_grouped=mode)
            model = self._gen()
            eng = GenerationEngine(model, page_size=4, max_length=64)
            outs[mode] = eng.generate(ids, max_new_tokens=8)
        np.testing.assert_array_equal(outs["on"], outs["off"])

    def test_grouped_engine_reports_grouped_rung(self):
        from paddle_tpu.inference import GenerationEngine

        _flags(decode_grouped="on")
        eng = GenerationEngine(self._gen(), page_size=4, max_length=64)
        assert eng._decode_tag == "decode.f32_grouped"
        _flags(decode_grouped="off")
        eng = GenerationEngine(self._gen(), page_size=4, max_length=64)
        assert eng._decode_tag == "decode"


class TestBenchGateRungs:
    def test_grouped_rung_metrics_gated_down(self):
        import tools.bench_gate as bg

        assert bg.DEFAULT_METRICS[
            "decode_bf16_grouped_tokens_per_sec"] == "down"
        assert bg.DEFAULT_METRICS[
            "decode_bf16_grouped_pct_of_hbm_roofline"] == "down"
        prev = {"decode_bf16_grouped_tokens_per_sec": 5000.0,
                "decode_bf16_grouped_pct_of_hbm_roofline": 52.0}
        cur = {"decode_bf16_grouped_tokens_per_sec": 3400.0,
               "decode_bf16_grouped_pct_of_hbm_roofline": 35.0}
        bad, compared = bg.gate(prev, cur)
        assert compared >= 2 and len(bad) == 2
        bad, _ = bg.gate(prev, dict(prev))
        assert not bad

    def test_decode_profile_has_grouped_ablation_rows(self):
        import tools.decode_profile as dp

        for row in ("weights_only_grouped", "prefetch_on",
                    "prefetch_off", "engine_grouped_b32",
                    "engine_ungrouped_b32"):
            assert row in dp.MODES
