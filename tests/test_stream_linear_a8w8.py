"""A8W8 stream_linear parity: the int8-activation streaming kernel (and
its XLA fallback) vs an fp32 reference, on CPU.

Like tests/test_paged_backends.py's stream-kernel tests, the Pallas
kernel runs in interpret mode off-TPU so CPU CI pins the exact numerics
the chip compiles. The fp32 reference is ``x @ dequant(w) + bias`` —
the only error the A8W8 path may add over it is the per-token dynamic
activation quantization, bounded elementwise by

    |out - ref| <= 0.5 * act_scale(row) * sum_k |w_dequant[k, n]|

(round-to-nearest symmetric int8; see quantization/dynamic.py), which
is the tolerance every assertion below derives — not a magic atol.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn.functional.stream_linear import (_stream_linear_a8w8,
                                                    stream_linear)
from paddle_tpu.quantization.dynamic import (dynamic_act_quant,
                                             int8_dot_dequant)


def _quantize_weights(rng, L, K, N):
    """Weight-only int8 per-output-channel quantization (the engine's
    quantize_weight_only_int8 layout): wq [L, K, N] int8, s [L, N]."""
    w = rng.randn(L, K, N).astype(np.float32)
    s = np.maximum(np.abs(w).max(axis=1) / 127.0, 1e-8)
    wq = np.clip(np.round(w / s[:, None, :]), -127, 127).astype(np.int8)
    return wq, s


def _dynamic_quant_bound(x, w_deq):
    """Documented elementwise error bound of the dynamic act quant:
    0.5 * act_scale per element through the K-long dot columns."""
    x_s = np.maximum(np.abs(np.asarray(x, np.float32)).max(-1) / 127.0,
                     1e-8)                               # [M]
    col_abs = np.abs(w_deq).sum(axis=0)                  # [N]
    return 0.5 * x_s[:, None] * col_abs[None, :] + 1e-2


class TestDynamicActQuant:
    def test_roundtrip_error_bound_and_range(self):
        rng = np.random.RandomState(0)
        x = rng.randn(16, 64).astype(np.float32) * 10.0
        q, s = dynamic_act_quant(jnp.asarray(x))
        qn, sn = np.asarray(q), np.asarray(s)
        assert qn.dtype == np.int8
        assert qn.min() >= -127 and qn.max() <= 127
        np.testing.assert_allclose(
            sn, np.abs(x).max(-1) / 127.0, rtol=1e-6)
        err = np.abs(qn.astype(np.float32) * sn[:, None] - x)
        assert (err <= 0.5 * sn[:, None] + 1e-6).all()

    def test_zero_row_is_finite(self):
        """absmax-0 row: the eps floor must give q=0 with a finite
        scale, and the matmul output must be exactly 0 for that row."""
        x = np.zeros((4, 32), np.float32)
        x[1] = 1.0
        q, s = dynamic_act_quant(jnp.asarray(x))
        assert np.isfinite(np.asarray(s)).all()
        assert (np.asarray(q)[0] == 0).all()
        w = jnp.ones((32, 8), jnp.int8)
        out = int8_dot_dequant(q, s, w, jnp.full((8,), 0.01))
        assert (np.asarray(out)[0] == 0).all()
        assert np.isfinite(np.asarray(out)).all()

    def test_saturation_worst_case(self):
        """A row holding one huge outlier + tiny values: the small
        values collapse toward 0 (the documented accuracy caveat of
        per-token quant) but the bound still holds and nothing clips
        past +-127."""
        rng = np.random.RandomState(3)
        x = rng.randn(8, 128).astype(np.float32) * 0.01
        x[:, 0] = 1000.0  # absmax -> scale ~7.87, small values -> 0
        q, s = dynamic_act_quant(jnp.asarray(x))
        qn = np.asarray(q)
        assert qn[:, 0].max() <= 127 and (np.abs(qn) <= 127).all()
        wq, ws = _quantize_weights(rng, 1, 128, 256)
        w_deq = wq[0].astype(np.float32) * ws[0]
        out = int8_dot_dequant(q, s, jnp.asarray(wq[0]),
                               jnp.asarray(ws[0]))
        ref = x @ w_deq
        bound = _dynamic_quant_bound(x, w_deq)
        assert (np.abs(np.asarray(out) - ref) <= bound).all()


class TestKernelParity:
    """Interpret-mode Pallas kernel vs fp32 reference + vs the XLA
    int8 fallback (identical quantized math -> near-bitwise)."""

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("with_bias", [True, False])
    def test_kernel_matches_fp32_reference(self, dtype, with_bias):
        rng = np.random.RandomState(1)
        M, K, N, L = 8, 256, 384, 2
        wq, ws = _quantize_weights(rng, L, K, N)
        bias = rng.randn(L, N).astype(np.float32) if with_bias else None
        x = jnp.asarray(rng.randn(M, K).astype(np.float32)) \
            .astype(dtype)
        xq, xs = dynamic_act_quant(x)
        for layer in range(L):
            out = _stream_linear_a8w8(
                xq, xs, jnp.asarray(wq),
                jnp.asarray(ws).reshape(L, 1, N),
                None if bias is None
                else jnp.asarray(bias).reshape(L, 1, N),
                jnp.asarray(layer, jnp.int32), None, jnp.float32,
                interpret=True)
            w_deq = wq[layer].astype(np.float32) * ws[layer]
            ref = np.asarray(x, np.float32) @ w_deq
            if bias is not None:
                ref = ref + bias[layer]
            bound = _dynamic_quant_bound(np.asarray(x, np.float32),
                                         w_deq)
            assert (np.abs(np.asarray(out) - ref) <= bound).all(), \
                f"layer {layer} exceeded the dynamic-quant bound"

    def test_kernel_matches_xla_fallback_bitwise_scale(self):
        """Kernel and XLA int8 fallback share the quantized operands:
        outputs must agree to float32 rounding, for every M the engine
        emits (incl. the sublane-padded M=8 and unpadded M=32)."""
        rng = np.random.RandomState(2)
        K, N, L = 128, 256, 1
        wq, ws = _quantize_weights(rng, L, K, N)
        bias = rng.randn(L, N).astype(np.float32)
        for M in (8, 32):
            x = jnp.asarray(rng.randn(M, K).astype(np.float32))
            xq, xs = dynamic_act_quant(x)
            out_k = _stream_linear_a8w8(
                xq, xs, jnp.asarray(wq),
                jnp.asarray(ws).reshape(L, 1, N),
                jnp.asarray(bias).reshape(L, 1, N), None, None,
                jnp.float32, interpret=True)
            out_x = int8_dot_dequant(xq, xs, jnp.asarray(wq[0]),
                                     jnp.asarray(ws[0]),
                                     bias=jnp.asarray(bias[0]))
            np.testing.assert_allclose(np.asarray(out_k),
                                       np.asarray(out_x),
                                       rtol=1e-5, atol=1e-4)

    def test_activation_fusion(self):
        rng = np.random.RandomState(4)
        K, N = 128, 128
        wq, ws = _quantize_weights(rng, 1, K, N)
        x = jnp.asarray(rng.randn(8, K).astype(np.float32))
        xq, xs = dynamic_act_quant(x)
        for act, f in (("relu", lambda a: np.maximum(a, 0)),
                       ("gelu", lambda a: np.asarray(
                           jax.nn.gelu(jnp.asarray(a))))):
            out = _stream_linear_a8w8(
                xq, xs, jnp.asarray(wq),
                jnp.asarray(ws).reshape(1, 1, N), None, None, act,
                jnp.float32, interpret=True)
            base = int8_dot_dequant(xq, xs, jnp.asarray(wq[0]),
                                    jnp.asarray(ws[0]))
            np.testing.assert_allclose(np.asarray(out),
                                       f(np.asarray(base)),
                                       rtol=1e-5, atol=1e-4)


class TestPublicPathA8W8:
    """The public stream_linear(act_quant=True) — the exact call the
    decode loop emits — across stacked/unstacked and ragged K/N (the
    shapes that must take the XLA int8 fallback)."""

    @pytest.mark.parametrize("K,N", [(96, 80), (130, 257), (128, 128)])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_ragged_and_aligned_shapes(self, K, N, dtype):
        rng = np.random.RandomState(5)
        wq, ws = _quantize_weights(rng, 1, K, N)
        bias = rng.randn(N).astype(np.float32)
        x = jnp.asarray(rng.randn(6, K).astype(np.float32)) \
            .astype(dtype)
        out = stream_linear(x, jnp.asarray(wq[0]),
                            bias=jnp.asarray(bias),
                            scale=jnp.asarray(ws[0]), act_quant=True,
                            out_dtype=jnp.float32)
        w_deq = wq[0].astype(np.float32) * ws[0]
        ref = np.asarray(x, np.float32) @ w_deq + bias
        bound = _dynamic_quant_bound(np.asarray(x, np.float32), w_deq)
        assert out.dtype == jnp.float32
        assert (np.abs(np.asarray(out) - ref) <= bound).all()

    def test_stacked_traced_layer_index(self):
        """Layer-stacked weights with a TRACED index under jit — the
        decode loop's form."""
        rng = np.random.RandomState(6)
        L, K, N = 3, 128, 128
        wq, ws = _quantize_weights(rng, L, K, N)
        bias = rng.randn(L, N).astype(np.float32)
        x = jnp.asarray(rng.randn(8, K).astype(np.float32))

        @jax.jit
        def f(l, x):
            return stream_linear(x, jnp.asarray(wq), layer=l,
                                 bias=jnp.asarray(bias),
                                 scale=jnp.asarray(ws), act_quant=True,
                                 out_dtype=jnp.float32)

        for l in range(L):
            out = f(jnp.asarray(l, jnp.int32), x)
            w_deq = wq[l].astype(np.float32) * ws[l]
            ref = np.asarray(x) @ w_deq + bias[l]
            bound = _dynamic_quant_bound(np.asarray(x), w_deq)
            assert (np.abs(np.asarray(out) - ref) <= bound).all()

    def test_act_quant_requires_int8_weights_and_scales(self):
        x = jnp.ones((4, 16))
        w_f = jnp.ones((16, 8))
        w_q = jnp.ones((16, 8), jnp.int8)
        with pytest.raises(ValueError, match="int8 weights"):
            stream_linear(x, w_f, act_quant=True)
        with pytest.raises(ValueError, match="scales"):
            stream_linear(x, w_q, act_quant=True)

    def test_decode_raw_rejects_float_stack(self):
        from paddle_tpu.incubate.nn.fused_transformer import (
            FusedMultiTransformer, PagedKV, rope_table)

        paddle.seed(0)
        st = FusedMultiTransformer(32, 4, 64, 1, max_position=64)
        cos, sin = rope_table(64, st.head_dim)
        cache = PagedKV(jnp.zeros((4, 4, 4, 8)), jnp.zeros((4, 4, 4, 8)))
        with pytest.raises(ValueError, match="int8 weight stack"):
            st.decode_raw(st._stack(), jnp.ones((2, 32)), cache,
                          jnp.zeros((2, 2), jnp.int32),
                          jnp.zeros((2,), jnp.int32), cos, sin,
                          a8w8=True)


class TestQuantedLinearA8W8:
    def test_forward_matches_bound_and_counts(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.profiler import stats
        from paddle_tpu.quantization import QuantedLinear

        paddle.seed(1)
        lin = nn.Linear(32, 16)
        w = lin.weight._data
        wt_scale = float(jnp.abs(w).max() / 127.0)
        q = QuantedLinear(lin, wt_scale, a8w8=True)
        x = np.random.RandomState(7).randn(4, 32).astype(np.float32)
        before = stats.counter("quant.a8w8_matmuls").value
        out = q(paddle.to_tensor(x)).numpy()
        assert stats.counter("quant.a8w8_matmuls").value == before + 1
        w_deq = np.asarray(q.w_int, np.float32) * wt_scale
        ref = x @ w_deq + np.asarray(lin.bias._data)
        bound = _dynamic_quant_bound(x, w_deq)
        assert (np.abs(out - ref) <= bound).all()

    def test_ptq_convert_a8w8(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import PTQ, QuantedLinear

        paddle.seed(2)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 4)

            def forward(self, x):
                return self.fc(x)

        net = PTQ().quantize(Net())
        x = paddle.to_tensor(
            np.random.RandomState(8).randn(2, 8).astype(np.float32))
        net(x)  # calibrate
        net = PTQ().convert(net, a8w8=True)
        assert isinstance(net.fc, QuantedLinear) and net.fc.a8w8
        out = net(x).numpy()
        assert np.isfinite(out).all()
