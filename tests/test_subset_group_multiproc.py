"""Subset-group collectives across 3 processes (member + non-member).

Exercises the store-brokered members-only paths: rank 2 is NOT in the
group and must no-op without corrupting the barrier (reference
semantics: non-members return untouched). Mirrors
test_collective_api_base.py with a sub-world group.
"""
import os
import socket
import subprocess
import sys
import textwrap

WORKER = textwrap.dedent("""
    import os
    for var in list(os.environ):
        if var.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            os.environ.pop(var)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.communication.collectives import (
        all_reduce, all_gather, broadcast, reduce_scatter)
    from paddle_tpu.distributed.communication.group import new_group

    dist.init_parallel_env()
    rank = jax.process_index()
    g = new_group([0, 1])  # rank 2 is NOT a member

    # all_reduce on the subset: members see the member sum; the
    # non-member's tensor is untouched
    t = paddle.to_tensor(np.full(3, rank + 1.0, np.float32))
    all_reduce(t, group=g)
    if rank in (0, 1):
        np.testing.assert_allclose(t.numpy(), np.full(3, 3.0))
    else:
        np.testing.assert_allclose(t.numpy(), np.full(3, rank + 1.0))

    # all_gather: members collect exactly the 2 member rows
    outs = []
    all_gather(outs, paddle.to_tensor(np.full(2, float(rank),
                                              np.float32)), group=g)
    if rank in (0, 1):
        got = np.stack([o.numpy() for o in outs])
        np.testing.assert_allclose(got, [[0, 0], [1, 1]])
    else:
        assert outs == []

    # broadcast with GLOBAL src rank 1 (permuted/subset convention)
    t = paddle.to_tensor(np.full(2, float(rank * 5), np.float32))
    broadcast(t, src=1, group=g)
    if rank in (0, 1):
        np.testing.assert_allclose(t.numpy(), [5.0, 5.0])
    else:
        np.testing.assert_allclose(t.numpy(), [10.0, 10.0])  # untouched

    # reduce_scatter on the subset: member r keeps member-sum of chunk r
    if rank in (0, 1):
        chunks = [paddle.to_tensor(np.full(2, rank * 10 + i, np.float32))
                  for i in range(2)]
        out = paddle.to_tensor(np.zeros(2, np.float32))
        reduce_scatter(out, chunks, group=g)
        gr = g.get_group_rank(rank)
        want = np.full(2, (0 * 10 + gr) + (1 * 10 + gr), np.float32)
        np.testing.assert_allclose(out.numpy(), want)
    print(f"RANK{rank}_OK")
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_three_process_subset_group(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for rank in range(3):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "3",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
            "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    for rank, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"rank {rank} failed:\n{err[-3000:]}"
        assert f"RANK{rank}_OK" in out
