"""Per-step serving-time attribution + fleet telemetry (ISSUE 16).

Tier-1 acceptance pins:

- on a real ServingEngine run the ``serve.step.*_ms`` phase
  histograms PARTITION the step wall time exactly: admit + work
  phase + host-overhead residual == total, step for step
  (``TestAttribution``);
- the spec-verify and migration phases appear EXACTLY when
  speculation / a drain migration is active
  (``TestPhasePresence``);
- ``FleetRouter`` telemetry: per-replica samplers fold into one
  fleet series whose counters sum the replicas' exactly, served on
  one Prometheus port (``TestFleetTelemetry``).
"""
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import FusedCausalLM
from paddle_tpu.profiler import stats
from paddle_tpu.serving import (FleetRouter, ServingEngine,
                                SLOConfig)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_registry():
    stats.enable()
    stats.reset()
    yield
    stats.reset()


def _model(seed=7):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=256)


def _engine(seed=7, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_length", 96)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("slo", SLOConfig(prefill_chunk=8))
    return ServingEngine(_model(seed), **kw)


def _prompts(n=3):
    rng = np.random.RandomState(0)
    return [rng.randint(0, 64, (L,)) for L in (6, 10, 14)[:n]]


def _phase_hists():
    snap = stats.snapshot(prefix="serve.step.")
    return snap["histograms"]


class TestAttribution:
    def test_phases_partition_step_wall_time(self):
        eng = _engine()
        for p in _prompts():
            eng.submit(p, max_new_tokens=6)
        eng.run()
        h = _phase_hists()
        total = h["serve.step.total_ms"]
        admit = h["serve.step.admit_ms"]
        host = h["serve.step.host_overhead_ms"]
        # every completed step observed all three framing stamps
        assert total["count"] == admit["count"] == host["count"] > 0
        work = sum(h[n]["total"] for n in
                   ("serve.step.prefill_chunk_ms",
                    "serve.step.decode_chunk_ms") if n in h)
        # the partition: admit + work + host == total (exact stamps,
        # float-summation tolerance only)
        assert admit["total"] + work + host["total"] \
            == pytest.approx(total["total"], rel=1e-6, abs=1e-6)
        # both work phases ran on this mixed load
        assert h["serve.step.prefill_chunk_ms"]["count"] > 0
        assert h["serve.step.decode_chunk_ms"]["count"] > 0
        # work-phase steps never exceed total steps
        assert (h["serve.step.prefill_chunk_ms"]["count"]
                + h["serve.step.decode_chunk_ms"]["count"]) \
            <= total["count"]

    def test_disabled_stats_records_nothing(self):
        stats.disable()
        try:
            eng = _engine()
            eng.submit(_prompts(1)[0], max_new_tokens=4)
            eng.run()
            assert _phase_hists() == {}
        finally:
            stats.enable()

    def test_recovery_steps_skip_attribution(self):
        """A step that dies in its work phase early-returns through
        ``_recover_*`` WITHOUT observing — so the partition invariant
        holds over the completed steps even under faults."""
        from paddle_tpu.serving import FaultInjector

        inj = (FaultInjector(seed=1)
               .add("decode.step", kind="raise", at=1)
               .add("prefill.dispatch", kind="raise", at=1))
        eng = _engine(faults=inj)
        for p in _prompts():
            eng.submit(p, max_new_tokens=6)
        eng.run()
        h = _phase_hists()
        total = h["serve.step.total_ms"]
        work = sum(h[n]["total"] for n in
                   ("serve.step.prefill_chunk_ms",
                    "serve.step.decode_chunk_ms") if n in h)
        assert h["serve.step.admit_ms"]["total"] + work \
            + h["serve.step.host_overhead_ms"]["total"] \
            == pytest.approx(total["total"], rel=1e-6, abs=1e-6)


class TestPhasePresence:
    def test_spec_verify_phase_exactly_when_speculative(self):
        eng = _engine()
        for p in _prompts():
            eng.submit(p, max_new_tokens=6)
        eng.run()
        h = _phase_hists()
        assert "serve.step.spec_verify_ms" not in h
        assert h["serve.step.decode_chunk_ms"]["count"] > 0

        stats.reset()
        spec = ServingEngine(
            _model(), max_batch=2, page_size=4, max_length=96,
            slo=SLOConfig(prefill_chunk=8), speculative="self",
            spec_k=3)
        for p in _prompts():
            spec.submit(p, max_new_tokens=6)
        spec.run()
        h = _phase_hists()
        # speculation owns the decode slot: its verify rounds land in
        # the spec_verify phase, never decode_chunk
        assert h["serve.step.spec_verify_ms"]["count"] > 0
        assert "serve.step.decode_chunk_ms" not in h
        total = h["serve.step.total_ms"]
        work = sum(h[n]["total"] for n in
                   ("serve.step.prefill_chunk_ms",
                    "serve.step.spec_verify_ms") if n in h)
        assert h["serve.step.admit_ms"]["total"] + work \
            + h["serve.step.host_overhead_ms"]["total"] \
            == pytest.approx(total["total"], rel=1e-6, abs=1e-6)

    def test_migration_phase_exactly_when_draining(self):
        router = FleetRouter(
            engine_factory=lambda i: _engine(), n_replicas=2)
        rid = router.submit(_prompts(2)[1], max_new_tokens=8)
        steps = 0
        while True:
            router.step()
            steps += 1
            assert steps < 500
            req = router.results()[rid]
            if len(req.generated) >= 2 and not req.done:
                break
        assert "serve.step.migration_ms" not in _phase_hists()
        src = next(r.idx for r in router.replicas
                   if r.eng.num_active)
        router.drain(src)
        h = _phase_hists()
        assert h["serve.step.migration_ms"]["count"] \
            == stats.counter("fleet.migrations").value == 1
        router.run()


class TestFleetTelemetry:
    def _loaded_router(self, n_reqs=4):
        router = FleetRouter(
            engine_factory=lambda i: _engine(), n_replicas=2,
            policy="rr")
        for p in _prompts(2) * (n_reqs // 2):
            router.submit(p, max_new_tokens=4)
        router.run()
        return router

    def test_fleet_series_sums_replica_counters_exactly(self):
        router = self._loaded_router()
        router.telemetry_tick()
        samplers = router.telemetry_samplers()
        assert len(samplers) == 2
        per_replica = [s.cum("serve.finished") for s in samplers]
        assert per_replica == [len(r.eng.finished)
                               for r in router.replicas]
        assert all(v > 0 for v in per_replica)  # rr spread the load
        fleet = router.fleet_series()
        assert fleet[-1]["counters"]["serve.finished"][0] \
            == sum(per_replica)
        # gauges fold by MAX
        assert fleet[-1]["gauges"]["slo.slot_occupancy"] \
            == max(s.value("slo.slot_occupancy") for s in samplers)

    def test_fleet_prometheus_endpoint_one_port(self):
        router = self._loaded_router()
        router.telemetry_tick()
        srv = router.start_telemetry(port=0)
        try:
            assert srv is not None
            url = f"http://127.0.0.1:{srv.port}/metrics"
            body = urllib.request.urlopen(url, timeout=10) \
                .read().decode()
            total = sum(len(r.eng.finished) for r in router.replicas)
            assert f"serve_finished_total {total}" in body
        finally:
            router.stop_telemetry()
        assert router._telemetry_srv is None

    def test_engine_source_reads_live_state(self):
        from paddle_tpu.profiler.timeseries import engine_source

        eng = _engine()
        counters, gauges, hists = engine_source(eng)()
        assert counters["serve.finished"] == 0
        assert gauges["slo.queue_depth"] == 0
        assert hists == {}
        eng.submit(_prompts(1)[0], max_new_tokens=4)
        eng.run()
        counters, gauges, _ = engine_source(eng)()
        assert counters["serve.finished"] == 1
        assert counters["journal.events"] > 0
