"""paddle.text (datasets + viterbi_decode) and incubate fills
(autotune, DistributedFusedLamb, multiprocessing).

Reference parity targets: python/paddle/text/, incubate/autotune.py,
incubate/optimizer/distributed_fused_lamb.py:115,
incubate/multiprocessing/.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _np_viterbi(emission, trans, length, include_bos_eos):
    """Brute-force reference over all tag paths (small cases)."""
    import itertools

    T, n = emission.shape
    best, best_path = -np.inf, None
    for path in itertools.product(range(n), repeat=length):
        s = emission[0, path[0]]
        if include_bos_eos:
            s += trans[n - 1, path[0]]
        for t in range(1, length):
            s += trans[path[t - 1], path[t]] + emission[t, path[t]]
        if include_bos_eos:
            s += trans[path[length - 1], n - 2]
        if s > best:
            best, best_path = s, path
    return best, list(best_path)


class TestViterbi:
    @pytest.mark.parametrize("include", [False, True])
    def test_matches_bruteforce(self, include):
        rng = np.random.RandomState(0)
        b, T, n = 3, 5, 4
        emission = rng.rand(b, T, n).astype(np.float32)
        trans = rng.rand(n, n).astype(np.float32)
        lengths = np.array([5, 3, 4], np.int64)
        scores, paths = paddle.text.viterbi_decode(
            paddle.to_tensor(emission), paddle.to_tensor(trans),
            paddle.to_tensor(lengths), include_bos_eos_tag=include)
        scores, paths = scores.numpy(), paths.numpy()
        assert paths.shape == (b, 5)
        for i in range(b):
            ref_s, ref_p = _np_viterbi(emission[i], trans,
                                       int(lengths[i]), include)
            np.testing.assert_allclose(scores[i], ref_s, rtol=1e-5,
                                       err_msg=f"row {i}")
            np.testing.assert_array_equal(
                paths[i, : lengths[i]], ref_p, err_msg=f"row {i}")
            assert (paths[i, lengths[i]:] == 0).all()

    def test_decoder_layer(self):
        rng = np.random.RandomState(1)
        trans = paddle.to_tensor(rng.rand(4, 4).astype(np.float32))
        dec = paddle.text.ViterbiDecoder(trans, include_bos_eos_tag=False)
        em = paddle.to_tensor(rng.rand(2, 4, 4).astype(np.float32))
        lens = paddle.to_tensor(np.array([4, 2], np.int64))
        scores, paths = dec(em, lens)
        assert tuple(scores.shape) == (2,) and tuple(paths.shape) == (2, 4)


class TestTextDatasets:
    def test_all_datasets_build_and_index(self):
        from paddle_tpu.text import (Conll05st, Imdb, Imikolov, Movielens,
                                     UCIHousing, WMT14, WMT16)

        ds = Imdb(mode="train", synthetic_size=32)
        doc, label = ds[0]
        assert doc.dtype == np.int64 and label.shape == (1,)
        assert len(ds) == 32

        ng = Imikolov(mode="train", window_size=5, synthetic_size=16)
        assert len(ng[0]) == 5

        ml = Movielens(mode="test", synthetic_size=8)
        rec = ml[3]
        assert len(rec) == 8 and rec[-1].dtype == np.float32

        uci = UCIHousing(mode="train", synthetic_size=16)
        f, t = uci[0]
        assert f.shape == (13,) and t.shape == (1,)

        for cls in (WMT14, WMT16):
            wmt = cls(mode="train", synthetic_size=8)
            src, trg, nxt = wmt[0]
            assert src[0] == 0 and src[-1] == 1  # BOS/EOS framing
            assert len(trg) == len(nxt)

        srl = Conll05st(synthetic_size=4)
        sample = srl[0]
        assert len(sample) == 9
        assert all(len(s) == len(sample[0]) for s in sample)

    def test_uci_trains_linear_regression(self):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.text import UCIHousing

        ds = UCIHousing(mode="train", synthetic_size=64)
        loader = DataLoader(ds, batch_size=16, shuffle=True)
        paddle.seed(0)
        model = nn.Linear(13, 1)
        opt = paddle.optimizer.SGD(0.05, parameters=model.parameters())
        losses = []
        for _ in range(5):
            for x, y in loader:
                loss = ((model(x) - y) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.5


class TestIncubateAutotune:
    def test_set_config_dict_and_reset(self):
        from paddle_tpu.incubate import autotune
        from paddle_tpu.io import dataloader as dl

        autotune.set_config({"dataloader": {"enable": True,
                                            "tuning_steps": 100}})
        assert dl.AUTOTUNE_NUM_WORKERS is True
        assert dl.AUTOTUNE_STEPS == 100
        cfg = autotune.get_config()
        assert cfg["dataloader"]["enable"] is True
        autotune.set_config({"dataloader": {"enable": False}})
        assert dl.AUTOTUNE_NUM_WORKERS is False

    def test_set_config_json_file(self, tmp_path):
        import json

        from paddle_tpu.incubate import autotune

        p = tmp_path / "tune.json"
        p.write_text(json.dumps({"kernel": {"enable": True}}))
        autotune.set_config(str(p))
        assert autotune.get_config()["kernel"]["enable"] is True


class TestDistributedFusedLamb:
    def test_trains_and_matches_lamb_at_acc1(self):
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb

        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randn(16, 2).astype(np.float32))

        def run(opt_cls, **kw):
            paddle.seed(5)
            m = nn.Linear(8, 2)
            opt = opt_cls(learning_rate=1e-2,
                          parameters=m.parameters(), **kw)
            for _ in range(5):
                loss = ((m(x) - y) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
            return float(loss.numpy()), m

        l_ref, _ = run(paddle.optimizer.Lamb)
        l_dfl, _ = run(DistributedFusedLamb)
        np.testing.assert_allclose(l_dfl, l_ref, rtol=1e-5)

    def test_gradient_accumulation(self):
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb

        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
        y = paddle.to_tensor(rng.randn(8, 2).astype(np.float32))
        paddle.seed(5)
        m = nn.Linear(4, 2)
        w0 = m.weight.numpy().copy()
        opt = DistributedFusedLamb(1e-2, parameters=m.parameters(),
                                   gradient_accumulation_steps=2)
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        opt.step()   # accumulates, no update
        np.testing.assert_array_equal(m.weight.numpy(), w0)
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        opt.step()   # applies
        assert not np.allclose(m.weight.numpy(), w0)


class TestIncubateMultiprocessing:
    def test_tensor_through_queue(self):
        from paddle_tpu.incubate import multiprocessing as mp

        q = mp.get_context("spawn").Queue() if False else mp.Queue()
        t = paddle.to_tensor(np.arange(6, dtype=np.float32))
        q.put(t)
        got = q.get(timeout=30)
        np.testing.assert_allclose(got.numpy(), t.numpy())
        assert isinstance(got, type(t))

    def test_pickle_roundtrip(self):
        import pickle

        t = paddle.to_tensor(np.ones((3, 2), np.float32))
        t.stop_gradient = False
        r = pickle.loads(pickle.dumps(t))
        np.testing.assert_allclose(r.numpy(), t.numpy())
        assert r.stop_gradient is False
