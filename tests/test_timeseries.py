"""Continuous telemetry (ISSUE 16): TimeSeriesSampler determinism,
disabled-path zero cost, registry snapshot/reset concurrency, and the
Prometheus / JSONL / fleet-fold exporters.

Tier-1 acceptance pins:

- deterministic ManualClock sampling: exact counter-delta rates and
  window aggregates (``TestDeterministicSampling``);
- disabled path allocates NO rings and records nothing, with a
  measured per-tick overhead bound on the enabled path
  (``TestDisabledAndOverhead``);
- ``stats.snapshot()``/``reset()`` stay consistent against a
  concurrent sampler thread — no torn histogram reads, definitions
  intact after reset (``TestSnapshotResetConcurrency``);
- the Prometheus endpoint serves a parseable text scrape with
  monotone counters and cumulative buckets, and ``aggregate_ticks``
  sums replica counters exactly (``TestExporters``).
"""
import json
import os
import threading
import urllib.request

import pytest

from paddle_tpu.profiler import TimeSeriesSampler, stats, timeseries
from paddle_tpu.serving import ManualClock


@pytest.fixture(autouse=True)
def _clean_registry():
    stats.enable()
    stats.reset()
    yield
    stats.reset()


def _sampler(clk=None, window=64, interval_ms=100.0, **kw):
    return TimeSeriesSampler(interval_ms=interval_ms, window=window,
                             clock=clk or ManualClock(), **kw)


# =====================================================================
# deterministic sampling on a ManualClock
# =====================================================================

class TestDeterministicSampling:
    def test_counter_delta_rates_exact(self):
        clk = ManualClock()
        s = _sampler(clk)
        stats.inc("t.events", 10)
        s.tick()
        assert s.rate("t.events") is None  # no previous tick
        clk.advance(2.0)
        stats.inc("t.events", 100)
        s.tick()
        assert s.rate("t.events") == pytest.approx(50.0)
        assert s.cum("t.events") == 110
        clk.advance(0.5)
        stats.inc("t.events", 5)
        s.tick()
        assert s.rate("t.events") == pytest.approx(10.0)
        pts = s.series("t.events")
        assert [p[1] for p in pts] == [10, 110, 115]
        assert [p[0] for p in pts] == [0.0, 2.0, 2.5]

    def test_gauge_levels_and_window_aggregates(self):
        clk = ManualClock()
        s = _sampler(clk)
        for v in (0.5, 0.9, 0.7, 0.1):
            stats.set_gauge("t.level", v)
            s.tick()
            clk.advance(1.0)
        agg = s.aggregate("t.level")
        assert agg["min"] == pytest.approx(0.1)
        assert agg["max"] == pytest.approx(0.9)
        assert agg["mean"] == pytest.approx(0.55)
        assert agg["p99"] == pytest.approx(0.9)
        assert agg["last"] == pytest.approx(0.1)
        assert agg["n"] == 4

    def test_counter_aggregate_is_over_rates(self):
        clk = ManualClock()
        s = _sampler(clk)
        for d in (10, 20, 40):
            stats.inc("t.c", d)
            s.tick()
            clk.advance(1.0)
        agg = s.aggregate("t.c")
        # rates: first tick has none; then +20/1s, +40/1s
        assert agg["n"] == 2
        assert agg["min"] == pytest.approx(20.0)
        assert agg["max"] == pytest.approx(40.0)

    def test_histogram_count_total_pairs(self):
        clk = ManualClock()
        s = _sampler(clk)
        stats.observe("t.h_ms", 2.0)
        stats.observe("t.h_ms", 4.0)
        s.tick()
        ts, count, total = s.series("t.h_ms")[-1]
        assert (count, total) == (2, 6.0)

    def test_window_is_bounded(self):
        clk = ManualClock()
        s = _sampler(clk, window=8)
        for _ in range(50):
            stats.inc("t.c")
            s.tick()
            clk.advance(1.0)
        assert len(s.series("t.c")) == 8
        assert len(s.ticks()) == 8

    def test_sampler_accounts_itself(self):
        s = _sampler()
        stats.inc("t.c")
        s.tick()
        s.tick()
        assert stats.counter("telemetry.ticks").value == 2
        assert stats.histogram("telemetry.tick_us").count == 2

    def test_sample_values_prefix_filter(self):
        stats.inc("t.a")
        stats.set_gauge("serving.x", 3)
        stats.observe("t.h", 1.0)
        counters, gauges, hists = stats.sample_values(prefix="t.")
        assert "t.a" in counters and "t.h" in hists
        assert "serving.x" not in gauges


# =====================================================================
# disabled path + overhead bound
# =====================================================================

class TestDisabledAndOverhead:
    def test_disabled_records_nothing(self):
        s = TimeSeriesSampler(interval_ms=0.0, clock=ManualClock())
        assert not s.enabled
        # PR 9 discipline: nothing allocated on the disabled path
        assert s._counters is None and s._gauges is None
        assert s._hists is None and s._ticks is None
        stats.inc("t.c")
        assert s.tick() is None
        assert s.ticks() == [] and s.series("t.c") == []
        assert s.value("t.c") is None and s.aggregate("t.c") is None
        assert s.metrics() == []
        assert stats.counter("telemetry.ticks").value == 0

    def test_flag_default_disables(self):
        # FLAGS_telemetry_interval_ms defaults to 0 -> disabled
        s = TimeSeriesSampler(clock=ManualClock())
        assert not s.enabled

    def test_per_tick_overhead_bounded(self):
        import time as _time

        # a realistically-populated registry
        for i in range(50):
            stats.inc(f"t.c{i}", i)
            stats.set_gauge(f"t.g{i}", i * 0.5)
            stats.observe(f"t.h{i}", float(i))
        s = _sampler(window=256)
        t0 = _time.perf_counter()
        for _ in range(100):
            s.tick()
        per_tick_ms = (_time.perf_counter() - t0) * 1e3 / 100
        # generous CI bound: one pass over 150 metrics must stay
        # far below any sane sampling interval
        assert per_tick_ms < 5.0, per_tick_ms
        h = stats.histogram("telemetry.tick_us")
        assert h.count == 100
        assert h.total / h.count < 5000.0  # mean < 5ms in us


# =====================================================================
# snapshot/reset vs a concurrent sampler thread (satellite 1)
# =====================================================================

class TestSnapshotResetConcurrency:
    def test_reset_keeps_definitions(self):
        c = stats.counter("t.c")
        g = stats.gauge("t.g")
        h = stats.histogram("t.h")
        c.inc(5), g.set(2.0), h.observe(1.0)
        stats.reset()
        # the REGISTERED OBJECTS survive reset (series definitions
        # intact — a sampler holding references keeps publishing)
        assert stats.counter("t.c") is c and c.value == 0
        assert stats.gauge("t.g") is g and g.value == 0
        assert stats.histogram("t.h") is h and h.count == 0

    def test_snapshot_hammer_no_torn_histograms(self):
        """Writers + a reset thread hammer the registry while the
        main thread snapshots: every histogram summary must be
        internally consistent (bucket counts sum to count, avg =
        total/count) — a torn read breaks that invariant."""
        stop = threading.Event()
        errors = []

        def writer(k):
            while not stop.is_set():
                stats.observe("t.hot%d" % k, 1.0)
                stats.inc("t.cnt%d" % k)

        def resetter():
            while not stop.is_set():
                stats.reset()

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(3)]
        threads.append(threading.Thread(target=resetter))
        for t in threads:
            t.start()
        try:
            for _ in range(200):
                snap = stats.snapshot(prefix="t.")
                for name, h in snap["histograms"].items():
                    n_buckets = sum(n for _, n in h["buckets"])
                    if n_buckets != h["count"]:
                        errors.append(
                            f"{name}: buckets {n_buckets} != "
                            f"count {h['count']}")
                    if h["count"] and abs(
                            h["avg"] - h["total"] / h["count"]) > 1e-6:
                        errors.append(f"{name}: torn avg")
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors, errors[:5]

    def test_sampler_thread_vs_snapshot(self):
        """A live background sampler plus foreground snapshot/reset
        — the ISSUE's exact concurrency scenario — runs clean."""
        s = TimeSeriesSampler(interval_ms=1.0, window=32,
                              enabled=True)
        s.start()
        try:
            for i in range(50):
                stats.inc("t.c", 2)
                stats.observe("t.h", float(i))
                snap = stats.snapshot(prefix="t.")
                for h in snap["histograms"].values():
                    assert sum(n for _, n in h["buckets"]) \
                        == h["count"]
                if i % 10 == 9:
                    stats.reset()
        finally:
            s.stop()
        assert s.n_ticks > 0


# =====================================================================
# exporters: JSONL round-trip, fleet fold, Prometheus
# =====================================================================

class TestExporters:
    def test_dump_load_round_trip_appends(self, tmp_path):
        clk = ManualClock()
        s = _sampler(clk)
        stats.inc("t.c", 3)
        s.tick()
        p = str(tmp_path / "series.jsonl")
        s.dump_jsonl(p)
        clk.advance(1.0)
        stats.inc("t.c", 7)
        s.tick()
        s.dump_jsonl(p)  # append-only: only the new tick lands
        ticks = timeseries.load_jsonl(p)
        assert len(ticks) == 2
        assert ticks[0]["counters"]["t.c"] == [3, None]
        assert ticks[1]["counters"]["t.c"] == [10, 7.0]

    def test_aggregate_ticks_sums_counters_exactly(self):
        def tick(ts, cum, rate, g, hc, ht):
            return {"ts": ts, "counters": {"c": [cum, rate]},
                    "gauges": {"g": g}, "histograms": {"h": [hc, ht]}}

        r0 = [tick(0.0, 10, None, 1.0, 2, 4.0),
              tick(1.0, 30, 20.0, 3.0, 4, 8.0)]
        r1 = [tick(0.1, 5, None, 2.0, 1, 1.0),
              tick(1.1, 25, 20.0, 1.0, 2, 2.0)]
        fleet = timeseries.aggregate_ticks([r0, r1])
        assert len(fleet) == 2
        assert fleet[0]["counters"]["c"] == [15, None]
        assert fleet[1]["counters"]["c"] == [55, 40.0]  # exact sums
        assert fleet[0]["gauges"]["g"] == 2.0           # max
        assert fleet[1]["gauges"]["g"] == 3.0
        assert fleet[1]["histograms"]["h"] == [6, 10.0]
        assert fleet[1]["ts"] == 1.1                    # max ts

    def test_aggregate_ticks_ragged_and_alerts(self):
        r0 = [{"ts": 0.0, "counters": {}, "gauges": {},
               "histograms": {}, "alerts": ["a"]}]
        r1 = [{"ts": 0.2, "counters": {}, "gauges": {},
               "histograms": {}, "alerts": ["b"]},
              {"ts": 1.2, "counters": {}, "gauges": {"g": 1},
               "histograms": {}}]
        fleet = timeseries.aggregate_ticks([r0, r1])
        assert len(fleet) == 2
        assert fleet[0]["alerts"] == ["a", "b"]  # union
        assert "alerts" not in fleet[1]

    def test_prometheus_text_shapes(self):
        stats.inc("t.reqs", 7)
        stats.set_gauge("t.depth", 3.5)
        for v in (0.5, 1.5, 300.0):
            stats.observe("t.lat_ms", v)
        txt = timeseries.prometheus_text(stats.snapshot(prefix="t."))
        assert "# TYPE t_reqs_total counter" in txt
        assert "t_reqs_total 7" in txt
        assert "t_depth 3.5" in txt
        # cumulative buckets, closed by +Inf == count
        bucket_vals = [int(ln.rsplit(" ", 1)[1])
                       for ln in txt.splitlines()
                       if ln.startswith("t_lat_ms_bucket")]
        assert bucket_vals == sorted(bucket_vals)
        assert bucket_vals[-1] == 3
        assert "t_lat_ms_count 3" in txt

    def test_http_endpoint_monotone_counters(self):
        stats.inc("t.reqs", 1)
        srv = timeseries.TelemetryServer(0)
        try:
            def scrape():
                url = f"http://127.0.0.1:{srv.port}/metrics"
                body = urllib.request.urlopen(url, timeout=10)
                return body.read().decode()

            t1 = scrape()
            assert "t_reqs_total 1" in t1
            stats.inc("t.reqs", 4)
            t2 = scrape()
            assert "t_reqs_total 5" in t2  # monotone across scrapes
            # parseable: every sample line is "name[{labels}] value"
            for ln in t2.splitlines():
                if ln.startswith("#") or not ln:
                    continue
                name, val = ln.rsplit(" ", 1)
                float(val)
                assert name
        finally:
            srv.stop()

    def test_start_http_server_disabled_by_default(self):
        # FLAGS_telemetry_port defaults 0 -> no exporter
        assert timeseries.start_http_server() is None

    def test_trace_merge_series_fold_round_trip(self, tmp_path):
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        clk = ManualClock()
        for rank in range(2):
            s = _sampler(clk, source=lambda: (
                {"serve.finished": 4}, {"slo.goodput": 0.5}, {}))
            s.tick()
            s.dump_jsonl(str(tmp_path / f"telemetry_rank{rank}.jsonl"))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(repo, "tools", "trace_merge.py"),
             str(tmp_path)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["ticks"] == 1 and doc["ranks"] == 2
        merged = [json.loads(ln) for ln in
                  open(doc["out_series"]) if ln.strip()]
        assert merged[0]["counters"]["serve.finished"][0] == 8  # sum
        assert merged[0]["gauges"]["slo.goodput"] == 0.5        # max


# =====================================================================
# conventions (satellite 4)
# =====================================================================

class TestConventions:
    def test_new_prefixes_registered(self):
        assert "telemetry." in stats.CONVENTION_PREFIXES
        assert "alert." in stats.CONVENTION_PREFIXES

    def test_alert_event_in_journal_vocabulary(self):
        from paddle_tpu.serving.journal import LIFECYCLE_EVENTS

        assert "alert" in LIFECYCLE_EVENTS
