"""Tensor-parallel serving (ISSUE 10): mp-sharded FusedMultiTransformer,
kv-head-sharded paged pool, per-shard weight streaming, engine plumbing.

Everything runs on the conftest virtual 8-device CPU mesh. Parity
targets: the TP path must reproduce the single-chip engine's hidden
states/logits (fp32, allclose) and its greedy token streams (exact —
both runs are deterministic, so equality is stable). Collective
discipline: the traced decode/prefill programs carry exactly ONE psum
per column→row projection pair (two per layer — the reference's
fused_multi_transformer_op.cu:220,529 ring_id allreduce points; the
sequential pre-LN math admits no fewer) and no other collective.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed.tp import (TPContext, serving_mesh,
                                       split_kv_heads)
from paddle_tpu.incubate.nn.fused_transformer import (
    FusedMultiTransformer, PagedKV, rope_table)
from paddle_tpu.inference import FusedCausalLM, GenerationEngine
from paddle_tpu.inference.kv_cache import BlockKVCacheManager
from paddle_tpu.profiler import stats


def _mesh2():
    return serving_mesh(2, devices=jax.devices("cpu")[:2])


def _stack(num_kv_heads=2, d=32, H=4, dff=64, L=2):
    paddle.seed(21)
    return FusedMultiTransformer(d, H, dff, L,
                                 num_kv_heads=num_kv_heads,
                                 max_position=64)


def _pool(st, tp=None, ps=4, npages=16, n_seq=2, tokens=8):
    mgr = BlockKVCacheManager(
        st.num_layers, st.num_kv_heads, st.head_dim, ps,
        num_pages=npages, reserve_scratch=True,
        mp_degree=tp.mp if tp else 1, mesh=tp.mesh if tp else None)
    for i in range(n_seq):
        mgr.allocate(i, tokens)
    tables = mgr.block_tables(range(n_seq), tokens // ps)
    return mgr, mgr.fresh_cache(), tables


class TestSplitKVHeads:
    def test_sharded_branch(self):
        assert split_kv_heads(8, 4) == (2, 1)
        assert split_kv_heads(2, 2) == (1, 1)

    def test_replication_branch(self):
        # GQA small-kv: each kv head replicated over mp//n_kv shards
        assert split_kv_heads(2, 4) == (1, 2)
        assert split_kv_heads(1, 8) == (1, 8)

    def test_mp1_identity(self):
        assert split_kv_heads(5, 1) == (5, 1)

    def test_indivisible_raises_informative(self):
        with pytest.raises(ValueError) as e:
            split_kv_heads(3, 2)
        msg = str(e.value)
        assert "num_kv_heads=3" in msg and "mp_degree=2" in msg
        assert "replication" in msg  # names the GQA fallback

    def test_heads_divisibility_checked(self):
        with pytest.raises(ValueError, match="num_heads"):
            TPContext.create(3, 3, 8, mesh=_mesh2(), mp_degree=None)


class TestKVCacheManagerTP:
    def test_sharded_pool_shape_and_placement(self, virtual_devices):
        tp = TPContext.create(4, 2, 8, mesh=_mesh2())
        mgr = BlockKVCacheManager(2, 2, 8, 4, num_pages=8,
                                  mp_degree=2, mesh=tp.mesh)
        cache = mgr.fresh_cache()
        assert cache.k.shape == (2 * 8, 2, 4, 8)  # heads stay global
        # axis 1 sharded over mp: each device holds one kv head
        assert not cache.k.sharding.is_fully_replicated

    def test_replication_pool_grows_heads(self, virtual_devices):
        # n_kv=1, mp=2 → one replicated head per shard, pool axis1 = 2
        tp = TPContext.create(4, 1, 8, mesh=_mesh2())
        mgr = BlockKVCacheManager(2, 1, 8, 4, num_pages=8,
                                  mp_degree=2, mesh=tp.mesh)
        assert mgr.kv_heads_per_shard == 1 and mgr.kv_replication == 2
        assert mgr.fresh_cache().k.shape[1] == 2

    def test_indivisible_raises_before_any_pool(self):
        with pytest.raises(ValueError, match="num_kv_heads=3"):
            BlockKVCacheManager(2, 3, 8, 4, num_pages=8, mp_degree=2)

    def test_int8_kv_plus_mesh_rejected(self, virtual_devices):
        with pytest.raises(NotImplementedError, match="int8 cache-KV"):
            BlockKVCacheManager(2, 2, 8, 4, num_pages=8, dtype="int8",
                                mp_degree=2, mesh=_mesh2())


class TestShardMapLayerParity:
    """Column/row shard math vs the dense single-chip reference."""

    def _parity(self, num_kv_heads):
        st = _stack(num_kv_heads=num_kv_heads)
        cos, sin = rope_table(64, st.head_dim)
        w = st._stack()
        tp = TPContext.create(st.num_heads, st.num_kv_heads,
                              st.head_dim, mesh=_mesh2())
        w_tp = tp.shard_stack(w)
        rng = np.random.RandomState(3)
        x3 = jnp.asarray(rng.randn(2, 6, st.embed_dim)
                         .astype(np.float32))
        _m1, c1, t1 = _pool(st)
        _m2, c2, t2 = _pool(st, tp)
        h1, c1 = st.prefill_raw(w, x3, c1, t1, cos, sin)
        h2, c2 = st.prefill_raw(w_tp, x3, c2, t2, cos, sin, tp=tp)
        np.testing.assert_allclose(np.asarray(h1), np.asarray(h2),
                                   atol=1e-5)
        if tp.kv_replication == 1:
            # sharded pool: same global head order as the mp1 pool
            np.testing.assert_allclose(np.asarray(c1.k),
                                       np.asarray(c2.k), atol=1e-5)
        x1 = jnp.asarray(rng.randn(2, st.embed_dim).astype(np.float32))
        lens = jnp.array([6, 6], jnp.int32)
        hd1, _ = st.decode_raw(w, x1, c1, t1, lens, cos, sin)
        hd2, _ = st.decode_raw(w_tp, x1, c2, t2, lens, cos, sin, tp=tp)
        np.testing.assert_allclose(np.asarray(hd1), np.asarray(hd2),
                                   atol=1e-5)

    def test_kv_sharded_parity(self, virtual_devices):
        self._parity(num_kv_heads=2)

    def test_gqa_replication_parity(self, virtual_devices):
        # n_kv=1 < mp=2 → the kv-head-replication fallback branch
        self._parity(num_kv_heads=1)

    def test_chunked_prefill_parity(self, virtual_devices):
        st = _stack()
        cos, sin = rope_table(64, st.head_dim)
        w = st._stack()
        tp = TPContext.create(st.num_heads, st.num_kv_heads,
                              st.head_dim, mesh=_mesh2())
        w_tp = tp.shard_stack(w)
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(2, 4, st.embed_dim)
                        .astype(np.float32))
        _m1, c1, t1 = _pool(st)
        _m2, c2, t2 = _pool(st, tp)
        start = jnp.zeros((2,), jnp.int32)
        clens = jnp.array([4, 3], jnp.int32)  # ragged tail row
        h1, _ = st.prefill_chunk_raw(w, x, c1, t1, start, clens,
                                     cos, sin)
        h2, _ = st.prefill_chunk_raw(w_tp, x, c2, t2, start, clens,
                                     cos, sin, tp=tp)
        # only the VALID rows are defined (pad rows are garbage)
        np.testing.assert_allclose(np.asarray(h1)[0], np.asarray(h2)[0],
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(h1)[1, :3],
                                   np.asarray(h2)[1, :3], atol=1e-5)

    def test_weight_stacks_are_sharded_slices(self, virtual_devices):
        st = _stack()
        tp = TPContext.create(st.num_heads, st.num_kv_heads,
                              st.head_dim, mesh=_mesh2())
        w_tp = tp.shard_stack(st._stack())
        # column/row stacks are NOT replicated — each chip holds 1/mp
        for name in ("qkv_weight", "out_weight", "ffn1_weight",
                     "ffn2_weight"):
            assert not w_tp[name].sharding.is_fully_replicated, name
        # LN params and row-parallel biases are replicated
        for name in ("ln1_scale", "out_bias", "ffn2_bias"):
            assert w_tp[name].sharding.is_fully_replicated, name


class TestCollectiveCount:
    """PR-5-style trace pin: the decode program's once-traced layer
    body carries exactly one psum per column→row projection pair (2
    total: O-proj + FFN2) and no other collective primitive."""

    def _seq(self, fn, *args):
        from paddle_tpu.analysis import trace_census

        return trace_census(fn, *args)

    def test_decode_psums_per_layer(self, virtual_devices):
        st = _stack()
        cos, sin = rope_table(64, st.head_dim)
        tp = TPContext.create(st.num_heads, st.num_kv_heads,
                              st.head_dim, mesh=_mesh2())
        w_tp = tp.shard_stack(st._stack())
        _m, cache, tables = _pool(st, tp)
        lens = jnp.array([6, 6], jnp.int32)
        x = jnp.ones((2, st.embed_dim), jnp.float32)

        def fn(w, xb, ck, cv):
            h, c2 = st.decode_raw(w, xb, PagedKV(ck, cv), tables,
                                  lens, cos, sin, tp=tp)
            return h, c2.k, c2.v

        seq = self._seq(fn, w_tp, x, cache.k, cache.v)
        assert seq == [("psum", "('mp',)")] * 2, seq

    def test_chunk_prefill_psums_per_layer(self, virtual_devices):
        st = _stack()
        cos, sin = rope_table(64, st.head_dim)
        tp = TPContext.create(st.num_heads, st.num_kv_heads,
                              st.head_dim, mesh=_mesh2())
        w_tp = tp.shard_stack(st._stack())
        _m, cache, tables = _pool(st, tp)
        x = jnp.ones((2, 4, st.embed_dim), jnp.float32)
        start = jnp.zeros((2,), jnp.int32)
        clens = jnp.full((2,), 4, jnp.int32)

        def fn(w, xb, ck, cv):
            h, c2 = st.prefill_chunk_raw(
                w, xb, PagedKV(ck, cv), tables, start, clens, cos,
                sin, tp=tp)
            return h, c2.k, c2.v

        seq = self._seq(fn, w_tp, x, cache.k, cache.v)
        assert seq == [("psum", "('mp',)")] * 2, seq


class TestEngineTP:
    def _model(self):
        paddle.seed(7)
        return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                             dim_feedforward=64, num_layers=2,
                             max_position=128)

    def test_generate_token_parity_mp2(self, virtual_devices):
        rng = np.random.RandomState(3)
        ids = rng.randint(0, 64, (2, 6))
        out1 = GenerationEngine(self._model(), page_size=4,
                                max_length=64).generate(
            ids, max_new_tokens=8)
        out2 = GenerationEngine(self._model(), page_size=4,
                                max_length=64, mp_degree=2).generate(
            ids, max_new_tokens=8)
        np.testing.assert_array_equal(out1, out2)

    def test_rung_names_and_gauge(self, virtual_devices):
        eng = GenerationEngine(self._model(), page_size=4,
                               max_length=64, mp_degree=2)
        assert eng._decode_rung(8).endswith("[k=8,mp=2]")
        assert eng._mp_suffix() == "[mp=2]"
        assert stats.snapshot()["gauges"]["dist.mp_degree"] == 2.0
        eng1 = GenerationEngine(self._model(), page_size=4,
                                max_length=64)
        assert eng1._decode_rung(8).endswith("[k=8]")

    def test_mesh_kwarg_accepts_process_mesh(self, mesh2x4):
        # the conftest dp2 x mp4 ProcessMesh: engine resolves the mp
        # axis (extent 4) via .jax_mesh(); weights replicate over dp
        eng = GenerationEngine(self._model(), page_size=4,
                               max_length=64, mesh=mesh2x4)
        assert eng._tp is not None and eng._tp.mp == 4
        assert eng._tp.heads_per_shard == 1

    @pytest.mark.slow  # composition smoke, not a tier-1 invariant
    def test_a8w8_tp_runs_finite(self, virtual_devices):
        rng = np.random.RandomState(5)
        ids = rng.randint(0, 64, (2, 6))
        eng = GenerationEngine(self._model(), page_size=4,
                               max_length=64, mp_degree=2,
                               quant="a8w8")
        out = eng.generate(ids, max_new_tokens=4)
        assert out.shape == (2, 10)

    def test_indivisible_heads_raise_at_engine_init(self,
                                                    virtual_devices):
        paddle.seed(7)
        model = FusedCausalLM(vocab_size=64, embed_dim=30, num_heads=3,
                              dim_feedforward=64, num_layers=2,
                              max_position=128)
        with pytest.raises(ValueError, match="num_heads"):
            GenerationEngine(model, page_size=4, max_length=64,
                             mp_degree=2)


class TestServingEngineTP:
    def _model(self):
        paddle.seed(9)
        return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                             dim_feedforward=64, num_layers=2,
                             max_position=128)

    def _serve(self, mp, prompts, **kw):
        from paddle_tpu.serving import ServingEngine, SLOConfig

        eng = ServingEngine(
            self._model(), max_batch=2, page_size=4, max_length=64,
            decode_chunk=4, slo=SLOConfig(prefill_chunk=4),
            mp_degree=mp if mp > 1 else None, **kw)
        for p in prompts:
            eng.submit(p, max_new_tokens=6)
        eng.run()
        return eng

    @pytest.mark.slow  # tier-1 parity is pinned by the mesh2x4 e2e
    def test_end_to_end_parity_on_mesh(self, virtual_devices):
        rng = np.random.RandomState(11)
        sysp = list(rng.randint(0, 64, (8,)))
        prompts = [sysp + [1, 2, 3], sysp + [4, 5]]
        g1 = sorted(tuple(r.generated)
                    for r in self._serve(1, prompts).finished)
        g2 = sorted(tuple(r.generated)
                    for r in self._serve(2, prompts).finished)
        assert g1 == g2

    def test_serving_engine_on_mesh2x4_fixture(self, mesh2x4):
        # multi-axis mesh: the serving stack shards over its mp axis
        # (extent 4) and replicates over dp — end-to-end on the shared
        # conftest fixture, with token parity vs the mp1 engine
        from paddle_tpu.serving import ServingEngine, SLOConfig

        eng = ServingEngine(
            self._model(), max_batch=2, page_size=4, max_length=64,
            decode_chunk=4, slo=SLOConfig(prefill_chunk=4),
            mesh=mesh2x4)
        assert eng._gen._tp is not None and eng._gen._tp.mp == 4
        eng.submit([1, 2, 3, 4, 5], max_new_tokens=5)
        done = eng.run()
        assert len(done) == 1 and len(done[0].generated) == 5
        ref = self._serve(1, [[1, 2, 3, 4, 5]])  # emits 6 tokens
        assert done[0].generated == ref.finished[0].generated[:5]

    def test_prefix_pages_saved_invariant_under_mp2(self,
                                                    virtual_devices):
        # PR 8's pages-saved pin, now under mp2: a 16-token shared
        # prefix at page_size 4 saves exactly 4 pages for the second
        # request (page TABLES are replicated host ints — sharding
        # the pool must not change page accounting)
        from paddle_tpu.serving import ServingEngine, SLOConfig

        rng = np.random.RandomState(13)
        sysp = list(rng.randint(0, 64, (16,)))
        base = int(stats.counter("serving.prefix_pages_saved").value)
        eng = ServingEngine(
            self._model(), max_batch=2, page_size=4, max_length=64,
            decode_chunk=4, slo=SLOConfig(prefill_chunk=4),
            mp_degree=2)
        for p in (sysp + [1, 2], sysp + [3, 4]):
            # sequential submit→run: the 2nd request hits the prefix
            # the 1st registered at prefill completion (the PR 8 pin)
            eng.submit(p, max_new_tokens=4)
            eng.run()
        saved = int(stats.counter("serving.prefix_pages_saved").value) \
            - base
        assert saved == 4
        assert len(eng.finished) == 2

    def test_chunk_rung_carries_mp_suffix(self, virtual_devices):
        from paddle_tpu.serving import ServingEngine, SLOConfig

        eng = ServingEngine(
            self._model(), max_batch=2, page_size=4, max_length=64,
            slo=SLOConfig(prefill_chunk=4), mp_degree=2)
        assert eng._chunk_rung(4) == "serve.prefill[c=4,mp=2]"


class TestToolsTP:
    def test_bench_gate_tp_directions(self):
        import tools.bench_gate as bg

        assert bg.DEFAULT_METRICS["decode_tp2_tokens_per_sec"] == "down"
        assert bg.DEFAULT_METRICS[
            "decode_tp2_pct_of_hbm_roofline"] == "down"
        assert bg.DEFAULT_METRICS["serve_tp2_tokens_per_sec"] == "down"
        assert bg.DEFAULT_METRICS["serve_tp2_p99_ttft_ms"] == "up"
        prev = {"decode_tp2_tokens_per_sec": 6000.0}
        bad, n = bg.gate(prev, {"decode_tp2_tokens_per_sec": 4000.0})
        assert n >= 1 and bad

    def test_decode_profile_has_mp2_row(self):
        import tools.decode_profile as dp

        assert "engine_grouped_mp2_b32" in dp.MODES

    def test_serve_bench_has_mp_flag(self):
        import os

        src = open(os.path.join(os.path.dirname(__file__), "..",
                                "tools", "serve_bench.py")).read()
        assert '"--mp"' in src
        assert 'f"serve_tp{args.mp}_"' in src  # rung-key renaming

    def test_bench_has_decode_tp_rung(self):
        import os

        src = open(os.path.join(os.path.dirname(__file__), "..",
                                "bench.py")).read()
        assert "--decode-tp" in src
        assert 'f"decode_tp{mp}_tokens_per_sec"' in src


class TestSpmdSitesTP:
    def test_sites_registered(self):
        from paddle_tpu.analysis.spmd import SPMD_SITES

        names = {s.name for s in SPMD_SITES}
        assert {"tp.decode", "tp.prefill_chunk"} <= names
        for s in SPMD_SITES:
            if s.name.startswith("tp."):
                assert s.allowed == frozenset({"all-reduce"})
                assert s.expects_constraint

    def test_tp_decode_site_clean(self, virtual_devices):
        from paddle_tpu.analysis.spmd import (SPMD_SITES,
                                              check_spmd_site)

        site = next(s for s in SPMD_SITES if s.name == "tp.decode")
        assert check_spmd_site(site) == []
