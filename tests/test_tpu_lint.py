"""tpu_lint static-analysis suite (ISSUE 6 tentpole).

Tier-1 coverage of paddle_tpu/analysis:

- the repo itself is CLEAN under all four passes (geometry, donation,
  purity, flags) with zero unwaivered findings — the gate that keeps
  kernel geometry, donation contracts, and traced-code purity honest
  without chip time;
- per-site VMEM regression: the analyzer's predicted footprint for each
  of the 8 ``pallas_call`` sites equals an independently hand-written
  block list (analysis/sites.py), so analyzer drift OR a silent kernel
  geometry change fails here first;
- each geometry rule fires on a synthetic bad launch spec;
- the ``FLAGS_check_donation`` poison mode catches a deliberately
  injected use-after-donate (refcount guard bypassed) and stays silent
  when the guard does its job;
- the purity lint flags each hazard class and honors inline waivers;
- flags/env parity: every flag readable via ``PADDLE_TPU_*`` with
  ``FLAGS_*`` taking precedence.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import analysis
from paddle_tpu.analysis.audit import BlockSpecInfo, PallasCallRecord
from paddle_tpu.analysis.geometry import (analyze_record,
                                          tile_padded_bytes,
                                          vmem_footprint)
from paddle_tpu.analysis.purity import run_purity_file
from paddle_tpu.analysis.sites import KERNEL_SITES
from paddle_tpu.analysis.sites import trace_site as _trace_site_raw
from paddle_tpu.device import vmem as dvmem
from paddle_tpu.ops import dispatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SITE_RECORDS: dict = {}


def trace_site(site):
    """Each dry-trace is deterministic over a fixed inventory; four
    tests walking all sites re-traced everything — memoize per site
    (records are read-only)."""
    if site.name not in _SITE_RECORDS:
        _SITE_RECORDS[site.name] = _trace_site_raw(site)
    return _SITE_RECORDS[site.name]


# ---------------------------------------------------------------------
# the repo is clean (the acceptance gate)
# ---------------------------------------------------------------------

class TestRepoIsClean:
    def test_all_passes_zero_unwaivered_under_60s(self):
        t0 = time.time()
        results = analysis.run_all_passes()
        elapsed = time.time() - t0
        # 3 kernel-level (PR 6) + flags + 5 program-level (PR 7 +
        # the ISSUE 19 overlap-census pass)
        assert set(results) == set(analysis.PASS_NAMES) == {
            "geometry", "donation", "purity", "flags",
            "dtype", "sync", "memory", "spmd", "overlap"}
        for name, findings in results.items():
            live = analysis.unwaivered(findings)
            assert not live, (
                f"pass {name!r} has unwaivered findings:\n  "
                + "\n  ".join(f.render() for f in live))
        # acceptance criterion: the full run fits in the CI budget
        assert elapsed < 60, f"tpu_lint took {elapsed:.1f}s (>60s)"

    def test_cli_json_report_and_baseline_ratchet(self, tmp_path):
        """One CLI run: schema-v2 JSON report (waived findings carry
        their reasons) + --write-baseline, then the ratchet compare
        against the fresh baseline passes by construction."""
        base = tmp_path / "lint_base.json"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "tpu_lint.py"),
             "--json", "--write-baseline", str(base)],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["schema_version"] == 2
        assert report["ok"] is True
        assert report["unwaivered"] == 0
        assert set(report["passes"]) == set(analysis.PASS_NAMES)
        # audit trail: waived findings listed with reasons
        for f in report["waived_findings"]:
            assert f["waived"] and f["waive_reason"]
        assert report["waived"] == len(report["waived_findings"])
        # the baseline stub holds per-rule unwaivered counts (clean
        # tree -> {}) and ratchets in-process
        doc = json.loads(base.read_text())
        assert doc["rule_counts"] == report["rule_counts"] == {}
        assert analysis.ratchet(report["rule_counts"],
                                doc["rule_counts"]) == []


# ---------------------------------------------------------------------
# geometry: site coverage + footprint regression
# ---------------------------------------------------------------------

class TestKernelSites:
    def test_all_sites_dry_trace(self):
        assert len(KERNEL_SITES) == 14
        for site in KERNEL_SITES:
            records = trace_site(site)
            assert len(records) == site.n_calls
            rec = records[0]
            assert rec.grid, f"{site.name}: empty grid"
            assert rec.operands, f"{site.name}: no operand avals"

    def test_footprint_matches_hand_block_list(self):
        """Analyzer prediction == independent hand-written block list,
        per site — guards analyzer drift when kernels change."""
        for site in KERNEL_SITES:
            records = trace_site(site)
            got = sum(vmem_footprint(r).total_bytes for r in records)
            if site.expected_vmem is None:  # stock jax flash kernel
                assert 0 < got <= dvmem.vmem_budget_bytes(), site.name
                continue
            assert got == site.expected_vmem(), (
                f"{site.name}: analyzer footprint {got:,} != hand "
                f"block list {site.expected_vmem():,} — kernel "
                "geometry or the footprint model changed; reconcile "
                "analysis/sites.py")

    def test_repo_kernels_within_declared_limits(self):
        for site in KERNEL_SITES:
            for rec in trace_site(site):
                fp = vmem_footprint(rec).total_bytes
                limit = (rec.vmem_limit_bytes
                         or dvmem.MOSAIC_DEFAULT_VMEM_LIMIT_BYTES)
                assert fp <= limit, (site.name, fp, limit)

    def test_repo_kernel_limits_derive_from_budget_table(self):
        # the satellite: the 100 MiB caps are the named constant now
        assert dvmem.KERNEL_VMEM_LIMIT_BYTES == (
            dvmem.VMEM_BUDGET_BYTES[dvmem.DEFAULT_GENERATION]
            - dvmem.VMEM_RESERVE_BYTES) == 100 * 2 ** 20
        declared = [rec.vmem_limit_bytes
                    for site in KERNEL_SITES
                    if "flash" not in site.name
                    for rec in trace_site(site)]
        assert declared and all(
            v == dvmem.KERNEL_VMEM_LIMIT_BYTES for v in declared)


def _rec(in_specs, operands, out_specs=(), out_shapes=(), grid=(4,),
         scratch=(), vmem=None):
    return PallasCallRecord(
        kernel_name="k", path="synthetic.py", line=1, grid=grid,
        num_scalar_prefetch=0, in_specs=list(in_specs),
        out_specs=list(out_specs), scratch=list(scratch),
        out_shapes=list(out_shapes), vmem_limit_bytes=vmem,
        input_output_aliases={}, interpret=False,
        operands=list(operands))


class TestGeometryRules:
    def test_tile_padding_model(self):
        assert tile_padded_bytes((8, 128), "float32") == 8 * 128 * 4
        # sublane pad: bf16 needs 16 sublanes, int8 needs 32
        assert tile_padded_bytes((8, 128), "bfloat16") == 16 * 128 * 2
        assert tile_padded_bytes((8, 128), "int8") == 32 * 128
        # lane pad: last dim 1 -> 128
        assert tile_padded_bytes((8, 1), "float32") == 8 * 128 * 4
        # leading dims multiply unpadded
        assert tile_padded_bytes((3, 8, 128), "float32") == 3 * 8 * 128 * 4

    def test_tile_misalignment_flagged(self):
        rec = _rec(
            [BlockSpecInfo((8, 130), lambda i: (0, i), None)],
            [((8, 520), "float32")])
        assert any(f.rule == "G-TILE" for f in analyze_record(rec))

    def test_divisibility_flagged(self):
        rec = _rec(
            [BlockSpecInfo((8, 128), lambda i: (0, 0), None)],
            [((8, 500), "float32")])
        assert any(f.rule == "G-DIV" for f in analyze_record(rec))

    def test_index_map_out_of_bounds_at_grid_edge(self):
        rec = _rec(
            [BlockSpecInfo((8, 128), lambda i: (0, i), None)],
            [((8, 256), "float32")])  # grid (4,) -> block 2 maps past N
        assert any(f.rule == "G-BOUNDS" for f in analyze_record(rec))

    def test_vmem_overflow_flagged_against_mosaic_default(self):
        big = BlockSpecInfo((8, 4 * 2 ** 20), lambda i: (0, i), None)
        rec = _rec([big], [((8, 16 * 2 ** 20), "float32")])
        assert any(f.rule == "G-VMEM" for f in analyze_record(rec))

    def test_budget_overflow_flagged(self):
        rec = _rec(
            [BlockSpecInfo((8, 128), lambda i: (0, 0), None)],
            [((8, 128), "float32")], vmem=200 * 2 ** 20)
        assert any(f.rule == "G-BUDGET"
                   for f in analyze_record(rec, generation="v5e"))
        # and a 100 MiB declared limit cannot fit a v3
        rec2 = _rec(
            [BlockSpecInfo((8, 128), lambda i: (0, 0), None)],
            [((8, 128), "float32")],
            vmem=dvmem.KERNEL_VMEM_LIMIT_BYTES)
        assert any(f.rule == "G-BUDGET"
                   for f in analyze_record(rec2, generation="v3"))

    def test_streamed_blocks_double_buffered(self):
        streamed = _rec(
            [BlockSpecInfo((8, 128), lambda i: (0, i), None)],
            [((8, 512), "float32")])
        resident = _rec(
            [BlockSpecInfo((8, 128), lambda i: (0, 0), None)],
            [((8, 512), "float32")])
        assert (vmem_footprint(streamed).total_bytes
                == 2 * vmem_footprint(resident).total_bytes)

    def test_magic_literal_scan_clean_and_fires(self, tmp_path):
        assert analysis.scan_magic_vmem_literals(
            os.path.join(REPO, "paddle_tpu")) == []
        bad = tmp_path / "pkg" / "bad.py"
        bad.parent.mkdir()
        bad.write_text("f(vmem_limit_bytes=100 * 1024 * 1024)\n")
        found = analysis.scan_magic_vmem_literals(str(bad.parent))
        assert [f.rule for f in found] == ["G-MAGIC"]


# ---------------------------------------------------------------------
# donation: poison mode + static audit
# ---------------------------------------------------------------------

class TestUseAfterDonate:
    def _fresh(self):
        dispatch._FWD_SEEN.clear()
        dispatch._FWD_CACHE.clear()
        analysis.clear_poisoned()

    def test_poison_mode_catches_injected_use_after_donate(self):
        """Bypass the refcount guard (the injected bug) and hold an
        alias across a donating call: the poisoned read must raise."""
        self._fresh()
        orig_guard = dispatch._donation_safe
        paddle.set_flags({"FLAGS_check_donation": True})
        dispatch._donation_safe = lambda arrays, i: True
        try:
            x = paddle.to_tensor(
                np.random.randn(8, 8).astype(np.float32))
            F.relu_(x)   # sighting
            F.relu_(x)   # admitted: compiled with donation
            alias = x.detach()          # aliases x's current buffer
            F.relu_(x)   # cache hit donates the aliased buffer
            assert analysis.poisoned_count() >= 1
            with pytest.raises(analysis.UseAfterDonateError):
                alias.numpy()
            with pytest.raises(analysis.UseAfterDonateError):
                F.relu(alias)           # dispatch-entry check too
        finally:
            dispatch._donation_safe = orig_guard
            paddle.set_flags({"FLAGS_check_donation": False})
            self._fresh()

    def test_refcount_guard_prevents_false_positive(self):
        """With the real guard, a held alias suppresses donation — the
        poison mode must stay silent and values must be correct."""
        self._fresh()
        paddle.set_flags({"FLAGS_check_donation": True})
        try:
            src = np.random.randn(8, 8).astype(np.float32)
            for _ in range(3):
                x = paddle.to_tensor(src)
                alias = x.detach()
                F.relu_(x)
                np.testing.assert_array_equal(alias.numpy(), src)
        finally:
            paddle.set_flags({"FLAGS_check_donation": False})
            self._fresh()

    def test_poison_registry_purges_on_death(self):
        self._fresh()
        import jax.numpy as jnp

        a = jnp.ones((4,))
        analysis.poison(a, "t")
        assert analysis.is_poisoned(a) == "t"
        assert analysis.poisoned_count() == 1
        del a
        import gc

        gc.collect()
        assert analysis.poisoned_count() == 0

    def test_registry_audit_clean_and_detects_bad_contract(self):
        from paddle_tpu.ops import registry

        assert analysis.run_donation_pass() == []
        registry._REGISTRY["__lint_bad_op__"] = registry.OpDef(
            "__lint_bad_op__", lambda x: x, donates=(0, 1))
        try:
            rules = {f.rule for f in analysis.run_donation_pass()}
            assert {"D-SLOT", "D-ORPHAN", "D-TAG"} <= rules
        finally:
            registry._REGISTRY.pop("__lint_bad_op__")
        assert analysis.run_donation_pass() == []

    def test_inplace_family_contracts_complete(self):
        from paddle_tpu.ops.registry import all_ops

        ops = all_ops()
        for name in ("relu_", "tanh_", "elu_", "softmax_", "reshape_",
                     "increment_"):
            d = ops[name]
            assert d.donates == (0,), name
            assert d.inplace_of in ops, (name, d.inplace_of)


# ---------------------------------------------------------------------
# purity lint
# ---------------------------------------------------------------------

_BAD_TRACED = '''\
import random
import time

import jax
import numpy as np


def outer(n, x0):
    acc = []

    def body(i, carry):
        if carry > 0:
            carry = carry + 1
        t = time.time()
        r = random.random()
        v = float(carry)
        a = np.abs(carry)
        acc.append(i)
        return carry + t + r + v + a

    return jax.lax.fori_loop(0, n, body, x0)


def waived(n, x0):
    def body(i, carry):
        r = random.random()  # tpu-lint: ok(P-HOST-RNG) -- test fixture
        return carry + r

    return jax.lax.fori_loop(0, n, body, x0)


def fine(n, x0):
    def body(i, carry):
        if i is None:
            return carry
        k = len(carry)
        return carry * k

    return jax.lax.fori_loop(0, n, body, x0)
'''


class TestPurityLint:
    def test_each_hazard_class_fires(self, tmp_path):
        p = tmp_path / "bad_traced.py"
        p.write_text(_BAD_TRACED)
        findings = run_purity_file(str(p), "bad_traced.py")
        rules = {f.rule for f in findings if not f.waived}
        assert {"P-TRACER-IF", "P-HOST-TIME", "P-HOST-RNG",
                "P-CONCRETIZE", "P-NP-TRACER", "P-STATE-MUT"} <= rules

    def test_waiver_honored_with_reason(self, tmp_path):
        p = tmp_path / "bad_traced.py"
        p.write_text(_BAD_TRACED)
        findings = run_purity_file(str(p), "bad_traced.py")
        waived = [f for f in findings if f.waived]
        assert len(waived) == 1
        assert waived[0].rule == "P-HOST-RNG"
        assert "test fixture" in waived[0].waive_reason

    def test_bare_waiver_flagged(self, tmp_path):
        p = tmp_path / "w.py"
        p.write_text("x = 1  # tpu-lint: ok(P-HOST-RNG)\n")
        findings = run_purity_file(str(p), "w.py")
        assert [f.rule for f in findings] == ["P-WAIVER"]

    def test_static_accessors_not_flagged(self, tmp_path):
        p = tmp_path / "bad_traced.py"
        p.write_text(_BAD_TRACED)
        findings = run_purity_file(str(p), "bad_traced.py")
        # `fine()` uses is-None identity + len(): both python-static
        fine_lines = [i for i, l in enumerate(_BAD_TRACED.splitlines(),
                                              1) if "def fine" in l]
        assert not [f for f in findings if f.line >= fine_lines[0]]


# ---------------------------------------------------------------------
# flags/env parity
# ---------------------------------------------------------------------

class TestFlagsParity:
    def test_paddle_tpu_env_override(self, monkeypatch):
        from paddle_tpu.core import flags as fl

        name = "t_lint_env_demo"
        monkeypatch.setenv(fl.env_var_for(name), "5")
        try:
            fl.define_flag(name, 0, "test-only")
            assert fl.flag(name) == 5
        finally:
            fl._FLAGS.pop(name, None)

    def test_flags_env_wins_over_paddle_tpu(self, monkeypatch):
        from paddle_tpu.core import flags as fl

        name = "t_lint_env_prec"
        monkeypatch.setenv(f"FLAGS_{name}", "1")
        monkeypatch.setenv(fl.env_var_for(name), "2")
        try:
            fl.define_flag(name, 0, "test-only")
            assert fl.flag(name) == 1
        finally:
            fl._FLAGS.pop(name, None)

    def test_every_flag_has_readme_row(self):
        assert analysis.run_flags_pass(REPO) == []

    def test_missing_row_detected(self, tmp_path):
        from paddle_tpu.core import flags as fl

        name = "t_lint_readme_hole"
        try:
            fl.define_flag(name, 0, "test-only")
            findings = analysis.run_flags_pass(REPO)
            assert any(f.rule == "F-README"
                       and name in (f.site or "") for f in findings)
        finally:
            fl._FLAGS.pop(name, None)
